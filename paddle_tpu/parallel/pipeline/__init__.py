"""Pipeline parallelism: stage-split programs + micro-batch schedules.

The reference's dist transpiler splits ONE ProgramDesc into per-role
sub-programs (trainer/pserver); this package is the TPU-first analogue
for INTER-LAYER pipelining (GPipe, Huang et al. 2019; PipeDream's 1F1B):

  * partition.py — cut a trained Program (fwd+bwd+optimize) at
    user-annotated or auto-balanced boundaries into per-stage
    sub-programs with explicit activation/grad boundary vars; optimizer
    ops stay local to the stage owning the param.  The N-segment
    generalization of Executor.run_accumulated's prefix/suffix split.
  * schedule.py — per-tick GPipe / 1F1B event tables shared by the host
    scheduler and the mesh runner; dependency-validated.
  * trainer.py — PipelineProgram: drives the per-stage compiled entries
    through the executor (exe.run delegation, like ShardedProgram) with
    activation stashing and loss/grad accumulation IDENTICAL to
    run_accumulated (bit-parity asserted in tests/test_pipeline.py).
  * mesh.py — PipelineMeshProgram: the same schedule as ONE compiled
    collective program over a `pipe` mesh axis (shard_map + ppermute
    boundary transfers), composing with the dp/tp sharding rules of
    parallel/sharding.py.
"""

from .partition import (  # noqa: F401
    PipelineStage,
    PipelineStages,
    split_program,
)
from .schedule import (  # noqa: F401
    schedule_table,
    validate_schedule,
    bubble_fraction,
    SCHEDULES,
)
from .trainer import PipelineProgram  # noqa: F401
from .mesh import PipelineMeshProgram  # noqa: F401

__all__ = [
    "PipelineStage",
    "PipelineStages",
    "split_program",
    "schedule_table",
    "validate_schedule",
    "bubble_fraction",
    "SCHEDULES",
    "PipelineProgram",
    "PipelineMeshProgram",
]
