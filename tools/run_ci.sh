#!/usr/bin/env bash
# CI entry (reference role: paddle/scripts/paddle_build.sh — cmake_gen:58,
# run_test:408).  Runs the full validation ladder on a plain CPU host:
#   1. lint/format gate (ruff or pyflakes when available, else a
#      compile-all syntax sweep — the gate must exist on a bare image)
#      + repo-specific AST rules (tools/lint_rules.py: every FLAGS_* read
#      declared in flags.py, no host clock reads inside kernels/)
#   2. graph-lint gate: the static-analysis tier (tools/graph_lint.py)
#      over the FULL model matrix incl. the serving bucket-ladder/AOT
#      programs + the Pallas kernel plan linter; fails on ANY finding and
#      archives ci_artifacts/graph_lint.json
#   3. full test suite on the virtual 8-device CPU mesh
#   4. bench smoke (real chip if present, else CPU) with telemetry,
#      flight recorder, and metrics-snapshot artifacts
#   5. bench regression sentry: tools/bench_diff.py diffs every archived
#      smoke artifact against the committed baselines under
#      ci_artifacts/baselines/ (noise-aware: runs[] envelopes + rel-tol;
#      regression only when envelopes separate), asserts every record
#      carries a provenance block, and proves the gate can go RED by
#      chaos-injecting per-token latency into a decode re-run
#   6. chaos kill-and-resume fault-tolerance gate
#   7. numerics observability gate: a chaos-poisoned op output (a REAL
#      NaN in the compiled graph) must trip the watchdog and the
#      FLAGS_check_numerics=locate capture/replay must NAME the injected
#      op in the flight dump — tools/numerics_smoke.py, artifacts under
#      ci_artifacts/numerics/
#   8. serving smoke gate: export a model, boot the inference server,
#      drive tools/loadgen.py — p99/batch-fill histograms on /metrics,
#      zero recompiles across a shape-varying stream, the dynamic-
#      batching A/B (batched >= 2x batch-size-1 QPS), the OVERLOAD gate
#      (open-loop flood at ~4x measured capacity vs a chaos-armed
#      server: 429 shedding + Retry-After, expired-deadline drops before
#      dispatch, zero crash-5xx, bounded accepted p99, flat compile
#      counter, and a mid-load SIGTERM graceful drain exiting 0 with a
#      drain-trigger flight dump — overload_smoke.json), and the
#      generation continuous-batching gate (late joins without
#      retrace/stall, concurrent streams >= 2x batch-1 decode tokens/sec)
#   9. router smoke gate: a 3-replica supervised fleet behind the
#      scale-out router survives a chaos SIGKILL mid-flood with zero
#      non-429 client errors (failover + evict/readmit + crash restart)
#      and < 5ms p50 router tax — tools/router_smoke.py,
#      ci_artifacts/serving/router_smoke.json
#  10. compile-check + multichip dryrun (the driver's graft contract)
# Usage: tools/run_ci.sh [fast]   — "fast" skips the bench smoke.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== [1/10] lint gate"
if command -v ruff >/dev/null 2>&1; then
  ruff check paddle_tpu tools tests bench.py __graft_entry__.py
elif python -c 'import pyflakes' >/dev/null 2>&1; then
  python -m pyflakes paddle_tpu tools tests bench.py __graft_entry__.py
else
  echo "-- no ruff/pyflakes in image; falling back to compileall"
  python -m compileall -q paddle_tpu tools tests bench.py __graft_entry__.py
fi
python tools/lint_rules.py

echo "== [2/10] graph-lint gate (static analysis over the model matrix)"
mkdir -p ci_artifacts
JAX_PLATFORMS=cpu python tools/graph_lint.py \
  --out ci_artifacts/graph_lint.json
echo "-- graph-lint findings artifact: ci_artifacts/graph_lint.json"

echo "== [3/10] test suite (virtual 8-device CPU mesh)"
python -m pytest tests/ -q

if [[ "${1:-}" != "fast" ]]; then
  echo "== [4/10] bench smoke (telemetry on; snapshot + flight artifacts)"
  mkdir -p ci_artifacts
  rm -f ci_artifacts/bench_steps.jsonl  # StepMonitor appends; keep one run
  rm -rf ci_artifacts/flight && mkdir -p ci_artifacts/flight
  # Warnings gate: any Python UserWarning raised during the smoke (e.g.
  # jnp's int64-truncation warning that once fired per trace) FAILS the
  # step.  Allowlist a known-benign warning by appending another filter
  # AFTER the error one (later -W filters take precedence):
  #   -W "ignore:exact message prefix:UserWarning"
  # The JSON metric lines land in ci_artifacts/bench_smoke.json — the
  # per-workload record (runs[]/spread fields) used for A/B comparisons.
  FLAGS_monitor=1 FLAGS_monitor_jsonl=ci_artifacts/bench_steps.jsonl \
    FLAGS_flight_dir=ci_artifacts/flight \
    python -W error::UserWarning bench.py --smoke \
      --monitor-snapshot ci_artifacts/metrics.prom \
    | tee ci_artifacts/bench_smoke.json
  echo "-- A/B bench record artifact: ci_artifacts/bench_smoke.json ($(grep -c '' ci_artifacts/bench_smoke.json) records, streamed above)"
  # conv+BN microbench leg (PERF.md r07 per-lever A/B): tiny shapes under
  # the same warnings gate; the JSON record sits next to bench_smoke.json
  python -W error::UserWarning bench.py --model convbn --smoke \
    | tee ci_artifacts/bench_convbn_smoke.json
  echo "-- convbn A/B record artifact: ci_artifacts/bench_convbn_smoke.json"
  # DeepFM sparse-tier leg (PERF.md r08 A/B): the fused multi-table
  # embedding record next to its FLAGS_fused_embedding=0 per-slot
  # baseline, both under the warnings gate; the paired records (config
  # carries the flag + runs[]/spread) are the launch-collapse A/B artifact
  python -W error::UserWarning bench.py --model deepfm --smoke \
    | tee ci_artifacts/bench_deepfm_smoke.json
  FLAGS_fused_embedding=0 python -W error::UserWarning bench.py \
    --model deepfm --smoke | tee -a ci_artifacts/bench_deepfm_smoke.json
  python - <<'PY'
import json
recs = [json.loads(l) for l in open("ci_artifacts/bench_deepfm_smoke.json")
        if l.strip().startswith("{")]
recs = [r for r in recs if r.get("metric", "").startswith("deepfm")]
flags = {r["config"]["fused_embedding"] for r in recs}
assert flags == {True, False}, f"need a fused AND an unfused record: {flags}"
print("deepfm A/B records OK:", [(r["config"]["fused_embedding"],
                                  r["value"]) for r in recs])
PY
  echo "-- deepfm A/B record artifact: ci_artifacts/bench_deepfm_smoke.json"
  # Transformer fused-qkv-projection leg (PERF.md r09 A/B): the fused-
  # projection record next to its FLAGS_fused_qkv_attention=0 unfused-
  # composition baseline, both under the warnings gate (paired records,
  # config carries the flag + runs[]/spread) — the projection-boundary
  # A/B artifact for the driver's chip run
  python -W error::UserWarning bench.py --model transformer --smoke \
    | tee ci_artifacts/bench_transformer_smoke.json
  FLAGS_fused_qkv_attention=0 python -W error::UserWarning bench.py \
    --model transformer --smoke \
    | tee -a ci_artifacts/bench_transformer_smoke.json
  python - <<'PY'
import json
recs = [json.loads(l) for l in open(
    "ci_artifacts/bench_transformer_smoke.json")
    if l.strip().startswith("{")]
recs = [r for r in recs if r.get("metric", "").startswith("transformer")]
flags = {r["config"]["fused_qkv_attention"] for r in recs}
assert flags == {True, False}, f"need a fused AND an unfused record: {flags}"
print("transformer A/B records OK:", [(r["config"]["fused_qkv_attention"],
                                       r["value"]) for r in recs])
PY
  echo "-- transformer A/B record artifact: ci_artifacts/bench_transformer_smoke.json"
  # Recompute A/B leg (PERF.md r12 / ISSUE 15): the activation-recompute
  # rewrite paired against the plain record — the rewritten record must
  # carry a LOWER planner activation peak and the est FLOPs factor, and
  # every dense record now carries activation_peak_bytes (planner) +
  # memory_analysis_peak_bytes (XLA ground truth), both under the
  # warnings gate
  python -W error::UserWarning bench.py --model transformer --smoke \
    --recompute | tee ci_artifacts/bench_recompute_smoke.json
  python -W error::UserWarning bench.py --model transformer --smoke \
    | tee -a ci_artifacts/bench_recompute_smoke.json
  python - <<'PY'
import json
recs = [json.loads(l) for l in open("ci_artifacts/bench_recompute_smoke.json")
        if l.strip().startswith("{")]
recs = [r for r in recs if r.get("metric", "").startswith("transformer")]
flags = {r["config"]["recompute"] for r in recs}
assert flags == {True, False}, f"need a recompute AND a plain record: {flags}"
for r in recs:
    assert "activation_peak_bytes" in r["config"], r["config"]
    assert "memory_analysis_peak_bytes" in r["config"], r["config"]
rc = next(r for r in recs if r["config"]["recompute"])
plain = next(r for r in recs if not r["config"]["recompute"])
assert rc["config"]["activation_peak_bytes"] \
    < plain["config"]["activation_peak_bytes"], (rc, plain)
# the <= 1.35 FLOPs bar is a transformer-BASE property (gated in
# graph_lint's memory builder + tests/test_memory.py); the tiny smoke
# model is less matmul-dominant, so this leg only sanity-bounds it
assert rc["config"]["recompute_flops_ratio"] <= 1.5, rc["config"]
print("recompute A/B records OK:",
      [(r["config"]["recompute"], r["config"]["activation_peak_bytes"],
        r["value"]) for r in recs])
PY
  echo "-- recompute A/B record artifact: ci_artifacts/bench_recompute_smoke.json"
  # Memory report (ISSUE 15 satellite): planner table + memory_analysis
  # ground-truth columns + the donated-param entry-copy row, archived
  # like the copy census
  python tools/hlo_diag.py transformer_smoke \
    ci_artifacts/hlo_memory_probe.txt --memory | tail -25
  rm -f ci_artifacts/hlo_memory_probe.txt  # keep the memory JSON
  echo "-- memory report artifact:"
  ls ci_artifacts/*.memory.json
  # Decode generation leg (PERF.md r10): tokens/sec at two batch sizes
  # through the KV-cache + flash-decode path, paired with the
  # FLAGS_kv_cache=0 full-prefix-recompute baseline record; every record
  # must carry compile_flat=true — the executor compile cache may NOT
  # grow across generated tokens (the length-independent-key contract)
  python -W error::UserWarning bench.py --model decode --smoke --runs 3 \
    | tee ci_artifacts/bench_decode_smoke.json
  FLAGS_kv_cache=0 python -W error::UserWarning bench.py \
    --model decode --smoke | tee -a ci_artifacts/bench_decode_smoke.json
  python - <<'PY'
import json
recs = [json.loads(l) for l in open("ci_artifacts/bench_decode_smoke.json")
        if l.strip().startswith("{")]
recs = [r for r in recs if r.get("metric", "").startswith("decode")]
flags = {r["config"]["kv_cache"] for r in recs}
assert flags == {True, False}, f"need a cached AND a recompute record: {flags}"
bad = [r for r in recs if not r["config"]["compile_flat"]]
assert not bad, f"executor compile cache grew across generated tokens: {bad}"
# megastep gate (PERF.md r15): the cached run emits fused/unfused PAIRS;
# at batch 1 the fused decode program may not lose to the unfused one.
# Noise-aware like bench_diff: red only when the run envelopes SEPARATE
# (best fused repeat below the worst unfused repeat) — CPU-box b1
# tokens/sec jitters +-15% run to run
cached = [r for r in recs if r["config"]["kv_cache"]]
pairs = {r["metric"]: r for r in cached}
fused = pairs.get("decode_tokens_per_sec_b1")
unfused = pairs.get("decode_tokens_per_sec_b1_unfused")
assert fused is not None and unfused is not None, \
    f"need the fused/unfused b1 pair, have {sorted(pairs)}"
assert fused["config"]["fused_decode_step"] is True
assert unfused["config"]["fused_decode_step"] is False
assert max(fused["config"]["runs"]) >= min(unfused["config"]["runs"]), (
    f"fused decode LOST to unfused at b1 beyond noise: fused runs "
    f"{fused['config']['runs']} vs unfused {unfused['config']['runs']}")
print(f"decode megastep gate OK: fused b1 {fused['value']:.1f} vs "
      f"unfused {unfused['value']:.1f} tokens/sec "
      f"(runs {fused['config']['runs']} / {unfused['config']['runs']})")
# paged KV-cache capacity gate (ISSUE 20): at the fixed smoke HBM
# budget the paged layout must admit >= 2x the sequences the ring
# layout does (it charges blocks actually touched, not full rings),
# and the bench's resident-bytes claim must match the memory planner's
# kv_cache row (the hlo_diag --memory number) within 1%
paged = pairs.get("decode_tokens_per_sec_b1_paged")
assert paged is not None, f"need the paged b1 record, have {sorted(pairs)}"
assert paged["config"]["paged"] is True and paged["config"]["compile_flat"]
r_slots = fused["config"]["concurrent_slots_at_budget"]
p_slots = paged["config"]["concurrent_slots_at_budget"]
ratio = p_slots / max(r_slots, 1)
assert ratio >= 2.0, (
    f"paged capacity gate RED: {p_slots} paged vs {r_slots} ring slots "
    f"at {paged['config']['kv_budget_bytes']} bytes (ratio {ratio:.2f} "
    f"< 2.0)")
for rec in (fused, paged):
    resident = rec["config"]["kv_resident_gb"] * 1e9
    row = rec["config"]["planner_kv_cache_bytes"]
    assert abs(row - resident) <= 0.01 * resident, (
        f"planner kv_cache row {row} disagrees with bench resident "
        f"bytes {resident:.0f} ({rec['metric']})")
with open("ci_artifacts/kv_capacity_gate.json", "w") as f:
    json.dump({"ring_slots_at_budget": r_slots,
               "paged_slots_at_budget": p_slots,
               "capacity_ratio": round(ratio, 2),
               "budget_bytes": paged["config"]["kv_budget_bytes"],
               "ring_bytes_per_seq": fused["config"]["kv_bytes_per_seq"],
               "paged_bytes_per_seq": paged["config"]["kv_bytes_per_seq"],
               "paged_tokens_per_sec_per_hbm_gb":
                   paged["config"]["tokens_per_sec_per_hbm_gb"]}, f,
              indent=1)
print(f"paged capacity gate OK: {p_slots} paged vs {r_slots} ring "
      f"slots at budget (ratio {ratio:.2f} >= 2.0)")
print("decode A/B records OK:", [(r["config"]["kv_cache"], r["metric"],
                                  r["value"]) for r in recs])
PY
  echo "-- paged capacity gate artifact: ci_artifacts/kv_capacity_gate.json"
  echo "-- decode A/B record artifact: ci_artifacts/bench_decode_smoke.json"
  # Pipeline-parallel leg (PERF.md r11): pp=2 GPipe vs 1F1B vs single-
  # program run_accumulated on the CPU mesh — every pipeline record must
  # carry state_bit_parity=true (training state may not drift a BIT from
  # the unsplit program) and a fetched-loss trajectory within 1 ulp;
  # bench.py itself raises if they do not, this check keeps the archived
  # artifact honest
  python -W error::UserWarning bench.py --model transformer --pp 2 \
    --smoke | tee ci_artifacts/bench_pipeline_smoke.json
  # transformer-BASE widths (d_model 512, 6 layers; short seq), pp=2 AND
  # pp=4, dropout ON — the base-width pipeline parity gates
  python -W error::UserWarning bench.py --model transformer --pp 2 \
    | tee -a ci_artifacts/bench_pipeline_smoke.json
  python -W error::UserWarning bench.py --model transformer --pp 4 \
    | tee -a ci_artifacts/bench_pipeline_smoke.json
  python - <<'PY'
import json
recs = [json.loads(l) for l in open("ci_artifacts/bench_pipeline_smoke.json")
        if l.strip().startswith("{")]
recs = [r for r in recs if r.get("metric", "").startswith("transformer_pp")]
groups = {}
for r in recs:
    groups.setdefault((r["config"]["pp"], r["config"]["tiny"]),
                      set()).add(r["config"]["schedule"])
assert (2, True) in groups and (2, False) in groups \
    and (4, False) in groups, f"missing pipeline legs: {sorted(groups)}"
for g, scheds in groups.items():
    assert scheds == {"single", "gpipe", "1f1b"}, (g, scheds)
bad = [r["metric"] for r in recs
       if r["config"]["schedule"] != "single"
       and (r["config"]["state_bit_parity"] is not True
            or r["config"]["loss_max_rel_diff"] > 3e-7)]
assert not bad, f"pipeline schedules lost parity: {bad}"
print("pipeline records OK:",
      [(r["config"]["pp"], r["config"]["tiny"], r["config"]["schedule"],
        r["value"]) for r in recs])
PY
  echo "-- pipeline A/B record artifact: ci_artifacts/bench_pipeline_smoke.json"
  # Numerics-observability overhead leg (PERF.md r13): transformer smoke
  # with FLAGS_check_numerics=summary (fused per-param-group stats
  # reductions + one packed [N,4] fetch per step) paired against the
  # plain record, both under the warnings gate.  The <3% bar is gated in
  # PERF.md from a quiet-box measurement; CI only requires the summary
  # record within 15% of the plain one (CPU boxes are noisy) and prints
  # the measured delta for the archived pair.
  python -W error::UserWarning bench.py --model transformer --smoke \
    | tee ci_artifacts/bench_numerics_smoke.json
  FLAGS_check_numerics=summary FLAGS_monitor=1 \
    python -W error::UserWarning bench.py --model transformer --smoke \
    | tee -a ci_artifacts/bench_numerics_smoke.json
  python - <<'PY'
import json
recs = [json.loads(l) for l in open("ci_artifacts/bench_numerics_smoke.json")
        if l.strip().startswith("{")]
recs = [r for r in recs if r.get("metric", "").startswith("transformer")]
by = {r["provenance"]["flags"].get("check_numerics", "off"): r
      for r in recs}
assert set(by) == {"off", "summary"}, \
    f"need an off AND a summary record: {sorted(by)}"
overhead = 1.0 - by["summary"]["value"] / by["off"]["value"]
assert overhead < 0.15, \
    f"check_numerics=summary cost {overhead:.1%} tokens/sec (>15%)"
print(f"numerics A/B records OK: off={by['off']['value']} "
      f"summary={by['summary']['value']} (overhead {overhead:+.2%})")
PY
  echo "-- numerics A/B record artifact: ci_artifacts/bench_numerics_smoke.json"
  # Dispatch microbench (ISSUE 16): per-launch overhead of a cache-hit
  # exe.run — the measured launch constant the static cost model's
  # roofline attribution charges per op (analysis/costmodel.py)
  python -W error::UserWarning bench.py --model dispatch --smoke \
    | tee ci_artifacts/bench_dispatch_smoke.json
  echo "-- dispatch overhead artifact: ci_artifacts/bench_dispatch_smoke.json"
  # Copy census (PERF.md r09 attribution artifact): the automated
  # while-body copy-byte attribution on the smoke transformer, fused vs
  # unfused — tests assert the projection-site collapse; CI archives the
  # paired JSON for the record
  python tools/hlo_diag.py transformer_smoke \
    ci_artifacts/hlo_transformer_smoke_fused.txt --copy-census \
    | tail -20
  FLAGS_fused_qkv_attention=0 python tools/hlo_diag.py transformer_smoke \
    ci_artifacts/hlo_transformer_smoke_unfused.txt --copy-census \
    | tail -20
  rm -f ci_artifacts/hlo_transformer_smoke_*.txt  # keep the census JSONs
  echo "-- copy-census artifacts:"
  ls ci_artifacts/*.census.json
  # Donated-param entry-copy repro ladder (PERF.md r09): archives the
  # per-variant aliasing/entry-copy report — a CPU box documents the
  # negative result; the driver's chip run pinpoints the culprit rung
  JAX_PLATFORMS=cpu python tools/donation_repro.py \
    ci_artifacts/donation_repro.json
  echo "-- donation repro artifact: ci_artifacts/donation_repro.json"
  echo "-- metrics snapshot:"
  head -40 ci_artifacts/metrics.prom || true
  echo "-- flight record (black box of the smoke run):"
  ls ci_artifacts/flight/
  head -3 ci_artifacts/flight/flight-*-atexit.jsonl || true
fi

if [[ "${1:-}" != "fast" ]]; then
  echo "== [5/10] bench regression sentry (diff vs committed baselines)"
  # Provenance contract (ISSUE 16 satellite): every archived record must
  # say which commit/flags/jax produced it, or the baseline ledger is
  # unreviewable.
  python - <<'PY'
import glob, json
for path in sorted(glob.glob("ci_artifacts/bench_*_smoke.json")) \
        + ["ci_artifacts/bench_smoke.json"]:
    for line in open(path):
        if not line.strip().startswith("{"):
            continue
        rec = json.loads(line)
        p = rec.get("provenance")
        assert p and "git_commit" in p and "flags" in p and "jax" in p, \
            f"{path}: record {rec.get('metric')} lacks a provenance block"
print("provenance blocks OK across all archived smoke artifacts")
PY
  # Noise-aware diff of every archived smoke artifact against the
  # committed baseline ledger.  rel-tol 0.50: CI boxes differ from the
  # baseline box; the runs[]-envelope + 50% padding only separates on
  # real cliffs (the chaos demo below injects -95% and is caught), so a
  # red here is a finding, not weather.  Refresh protocol: rerun the
  # smoke legs on a quiet box and copy the artifacts over
  # ci_artifacts/baselines/ in the SAME commit as an intended perf
  # change.
  for a in bench_smoke bench_convbn_smoke bench_deepfm_smoke \
           bench_transformer_smoke bench_recompute_smoke \
           bench_decode_smoke bench_pipeline_smoke bench_dispatch_smoke \
           bench_numerics_smoke
  do
    python tools/bench_diff.py ci_artifacts/baselines/$a.json \
      ci_artifacts/$a.json --rel-tol 0.50
  done
  # RED-gate demo: chaos-inject 20ms per decoded token and require the
  # sentry to fail NAMING the regressed (workload, metric) pair — proof
  # the gate can actually fire, not just pass.
  FLAGS_chaos=1 FLAGS_chaos_serve_latency_s=0.02 \
    python bench.py --model decode --smoke \
    > ci_artifacts/bench_decode_chaos.json
  set +e
  python tools/bench_diff.py ci_artifacts/baselines/bench_decode_smoke.json \
    ci_artifacts/bench_decode_chaos.json --rel-tol 0.50 \
    | tee ci_artifacts/bench_diff_red.txt
  rc=${PIPESTATUS[0]}
  set -e
  if [[ $rc -ne 1 ]]; then
    echo "bench_diff red-gate demo: expected exit 1, got rc=$rc"
    exit 1
  fi
  grep -q "REGRESSION (decode, decode_tokens_per_sec_b1)" \
    ci_artifacts/bench_diff_red.txt
  echo "-- sentry red-gate demo OK (chaos-injected decode regression caught by name)"
fi

if [[ "${1:-}" != "fast" ]]; then
  echo "== [6/10] chaos smoke: kill-and-resume fault-tolerance gate"
  # A training subprocess is SIGKILLed mid-run by the chaos harness, then
  # resumed from the latest verifiable checkpoint; the gate passes when the
  # resumed run reports a non-zero start step and finishes.  Artifacts: the
  # recovered run's checkpoint MANIFEST.json + flight record.
  rm -rf ci_artifacts/chaos && mkdir -p ci_artifacts/chaos/flight
  set +e
  JAX_PLATFORMS=cpu FLAGS_chaos=1 FLAGS_chaos_kill_at_step=6 \
    FLAGS_flight_dir=ci_artifacts/chaos/flight \
    python tools/chaos_train.py --ckpt-dir ci_artifacts/chaos/ckpt \
      --steps 10 --interval 3 > ci_artifacts/chaos/killed_run.json
  rc=$?
  set -e
  if [[ $rc -ne 137 ]]; then
    echo "chaos gate: expected the run to be SIGKILLed (rc 137), got rc=$rc"
    exit 1
  fi
  JAX_PLATFORMS=cpu FLAGS_flight_dir=ci_artifacts/chaos/flight \
    python tools/chaos_train.py --ckpt-dir ci_artifacts/chaos/ckpt \
      --steps 10 --interval 3 | tee ci_artifacts/chaos/resumed_run.json
  python - <<'PY'
import glob, json
rec = json.loads(open("ci_artifacts/chaos/resumed_run.json").read().strip().splitlines()[-1])
assert rec["start"] > 0, f"resume did not pick up a checkpoint: {rec}"
man = max(glob.glob("ci_artifacts/chaos/ckpt/ckpt-*/MANIFEST.json"),
          key=lambda p: int(p.split("ckpt-")[-1].split("/")[0]))
m = json.load(open(man))
print(f"chaos gate OK: resumed at step {rec['start']}, "
      f"latest manifest step {m['step']} trigger {m['trigger']!r}")
PY
  echo "-- recovered manifest artifact:"
  ls ci_artifacts/chaos/ckpt
fi

echo "== [7/10] numerics observability gate (NaN-origin locate red-gate)"
# A REAL NaN is chaos-injected at one known op output in the compiled
# graph; the gate passes only when the watchdog-tripped locate replay
# NAMES that op in the flight dump — under the same warnings gate as the
# bench legs.  Runs in fast mode too: it is seconds of CPU work and it
# is THE proof the tier's flagship path works end to end.
rm -rf ci_artifacts/numerics
JAX_PLATFORMS=cpu python -W error::UserWarning tools/numerics_smoke.py \
  --out-dir ci_artifacts/numerics
echo "-- numerics gate artifacts:"
ls ci_artifacts/numerics/ ci_artifacts/numerics/flight/

if [[ "${1:-}" != "fast" ]]; then
  echo "== [8/10] serving smoke: dynamic-batching inference gate"
  # Exports a demo model, boots two inference servers (batched + forced
  # --max-batch 1), and drives tools/loadgen.py through both:
  #   * a shape-varying stream must finish with the executor compile
  #     counter FLAT (warm bucket ladder, zero recompiles) and the
  #     request-latency p99 / batch-fill histograms on /metrics;
  #   * the A/B: dynamic batching must serve >= 2x the QPS of
  #     batch-size-1 mode on the same single-row stream — BOTH servers
  #     chaos-latency-pinned (FLAGS_chaos_serve_latency_s) so capacity
  #     is set by the injected per-batch cost, not the CI box
  #     (box-independent gate; interleaved trial pairs still absorb
  #     noisy-neighbour variance);
  #   * the overload gate: ~4x-capacity open-loop flood vs a
  #     chaos-latency-armed bounded-queue server — shedding engaged
  #     (429 + Retry-After), expired_dropped_total > 0 (deadline drops
  #     before dispatch, asserted via /metrics delta), zero crash-5xx,
  #     accepted p99 under the stated bound, compile counter FLAT; then
  #     SIGTERM mid-load drains in-flight work and exits 0 with a
  #     drain-trigger flight dump;
  #   * the tracing gate: a FLAGS_trace_requests server echoes the
  #     client traceparent, serves /v1/traces span trees for predict +
  #     generation, exposes SLO burn-rate gauges, and closes the
  #     loadgen --trace correlation loop (trace_sample.json).
  # Artifacts: ci_artifacts/serving/loadgen_*.json + ab_summary.json
  #            + overload_smoke.json + trace_sample.json (+ flight/).
  rm -rf ci_artifacts/serving && mkdir -p ci_artifacts/serving
  JAX_PLATFORMS=cpu python tools/serving_smoke.py \
    --out-dir ci_artifacts/serving
  # Trace-sample contract: every span kind present across the archived
  # predict+generate traces, and each decomposition must SUM to the
  # measured end-to-end latency within tolerance (5% + 0.5ms jitter
  # floor) — the "why was this request slow" story stays trustworthy.
  python - <<'PY'
import json
d = json.load(open("ci_artifacts/serving/trace_sample.json"))
kinds = set()
for key in ("predict", "generate"):
    tr = d[key]
    dec = tr["decomposition"]
    total = dec["total_ms"]
    s = sum(dec["components_ms"].values())
    tol = 0.05 * total + 0.5
    assert abs(s + dec["unattributed_ms"] - total) <= tol, (key, dec)
    assert dec["unattributed_ms"] <= tol, (key, dec)
    kinds |= {sp["name"] for sp in tr["spans"]}
need = {"parse", "admission", "queue.wait", "batch.form", "batch.pad",
        "batch.exec", "debatch", "respond", "prefill", "decode.step",
        "deliver", "executor.run"}
missing = need - kinds
assert not missing, f"span kinds missing from trace sample: {missing}"
print(f"trace sample OK: decompositions sum within tolerance; "
      f"{len(kinds)} span kinds present")
PY
  echo "-- serving artifacts:"
  ls ci_artifacts/serving/
fi

if [[ "${1:-}" != "fast" ]]; then
  echo "== [9/10] router smoke: scale-out fleet fault-tolerance gate"
  # A 3-replica supervised fleet behind the router survives a chaos
  # SIGKILL mid-flood (FLAGS_chaos_kill_replica_after arms one replica):
  # zero non-429 client-visible errors, failover_total > 0, the victim
  # is evicted AND re-admitted (flight events), the supervisor's crash
  # restart brings it back, and the router's proxy tax stays < 5 ms p50
  # over direct-to-replica at --max-batch 1.
  mkdir -p ci_artifacts/serving
  JAX_PLATFORMS=cpu python tools/router_smoke.py \
    --out-dir ci_artifacts/serving
  echo "-- router fleet artifact: ci_artifacts/serving/router_smoke.json"
fi

echo "== [10/10] entry compile-check + multichip dryrun"
python __graft_entry__.py

echo "CI OK"
