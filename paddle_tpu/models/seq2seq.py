"""GRU encoder-decoder for machine translation (reference:
tests/book/test_machine_translation.py — encoder + decoder with a GRU
cell, trained with teacher forcing; decode via layers.beam_search in a
While loop, the same in-program pattern as models/transformer.py
build_decoder).

Shared parameter names let a scope trained with build_train_net decode
directly through build_decoder."""

from __future__ import annotations

from .. import layers
from ..param_attr import ParamAttr


def _encoder(src_word, src_vocab, emb_dim, hidden_dim, seq_len):
    emb = layers.embedding(
        src_word, size=[src_vocab, emb_dim],
        param_attr=ParamAttr(name="src_emb"))
    emb = layers.reshape(emb, [-1, seq_len, emb_dim])
    proj = layers.fc(emb, size=hidden_dim * 3, num_flatten_dims=2,
                     param_attr=ParamAttr(name="enc_proj_w"),
                     bias_attr=ParamAttr(name="enc_proj_b"))
    hidden = layers.dynamic_gru(
        proj, size=hidden_dim,
        param_attr=ParamAttr(name="enc_gru_w"),
        bias_attr=ParamAttr(name="enc_gru_b"))
    return layers.sequence_pool(hidden, "last")          # [B, H]


def _decoder_step_params():
    return dict(
        emb=ParamAttr(name="trg_emb"),
        proj_w=ParamAttr(name="dec_proj_w"),
        proj_b=ParamAttr(name="dec_proj_b"),
        gru_w=ParamAttr(name="dec_gru_w"),
        gru_b=ParamAttr(name="dec_gru_b"),
        out_w=ParamAttr(name="dec_out_w"),
        out_b=ParamAttr(name="dec_out_b"),
    )


def build_train_net(src_vocab=1000, trg_vocab=1000, emb_dim=32,
                    hidden_dim=64, src_seq_len=18, trg_seq_len=18):
    """Teacher-forced training net.  Feeds: src_word [B, Ts, 1],
    trg_word [B, Tt, 1], trg_next [B, Tt, 1] int64.  Returns avg_cost."""
    p = _decoder_step_params()
    src = layers.data(name="src_word", shape=[src_seq_len, 1], dtype="int64")
    trg = layers.data(name="trg_word", shape=[trg_seq_len, 1], dtype="int64")
    nxt = layers.data(name="trg_next", shape=[trg_seq_len, 1], dtype="int64")

    enc_last = _encoder(src, src_vocab, emb_dim, hidden_dim, src_seq_len)

    temb = layers.embedding(trg, size=[trg_vocab, emb_dim], param_attr=p["emb"])
    temb = layers.reshape(temb, [-1, trg_seq_len, emb_dim])
    proj = layers.fc(temb, size=hidden_dim * 3, num_flatten_dims=2,
                     param_attr=p["proj_w"], bias_attr=p["proj_b"])
    hidden = layers.dynamic_gru(
        proj, size=hidden_dim, h_0=enc_last,
        param_attr=p["gru_w"], bias_attr=p["gru_b"])     # [B, Tt, H]
    logits = layers.fc(hidden, size=trg_vocab, num_flatten_dims=2,
                       param_attr=p["out_w"], bias_attr=p["out_b"])
    cost = layers.softmax_with_cross_entropy(
        layers.reshape(logits, [-1, trg_vocab]),
        layers.reshape(nxt, [-1, 1]))
    return layers.mean(cost)


def build_decoder(src_vocab=1000, trg_vocab=1000, emb_dim=32, hidden_dim=64,
                  src_seq_len=18, batch_size=4, beam_size=3, max_out_len=16,
                  bos_id=0, eos_id=1):
    """Beam-search decoder sharing the train net's parameters; the While
    loop carries (pre_ids, pre_scores, hidden) per beam lane.  Returns
    (sentence_ids [b, beam, T], sentence_scores [b, beam], feed_names)."""
    p = _decoder_step_params()
    b, k = batch_size, beam_size
    bk = b * k
    neg_inf = -1e9

    src = layers.data(name="src_word", shape=[src_seq_len, 1], dtype="int64")
    enc_last = _encoder(src, src_vocab, emb_dim, hidden_dim, src_seq_len)
    # tile per beam: [b, H] -> [b, k, H]
    hidden = layers.expand(layers.reshape(enc_last, [b, 1, hidden_dim]),
                           [1, k, 1])

    t = layers.fill_constant([1], "int64", 0)
    limit = layers.fill_constant([1], "int64", max_out_len)
    cond = layers.less_than(t, limit)

    pre_ids = layers.fill_constant([b, k], "int64", bos_id)
    beam0 = layers.one_hot(layers.fill_constant([1], "int64", 0), k)
    pre_scores = layers.expand(
        layers.reshape(layers.scale(beam0, scale=1e9, bias=neg_inf), [1, k]),
        [b, 1])
    hidden_state = layers.assign(hidden)

    ids_arr = layers.create_array("int64", element_shape=[b, k],
                                  capacity=max_out_len)
    parents_arr = layers.create_array("int64", element_shape=[b, k],
                                      capacity=max_out_len)

    w = layers.While(cond)
    with w.block():
        emb = layers.embedding(
            layers.reshape(pre_ids, [bk, 1]),
            size=[trg_vocab, emb_dim], param_attr=p["emb"])
        emb = layers.reshape(emb, [bk, emb_dim])
        proj = layers.fc(emb, size=hidden_dim * 3,
                         param_attr=p["proj_w"], bias_attr=p["proj_b"])
        h_flat = layers.reshape(hidden_state, [bk, hidden_dim])
        new_h, _, _ = layers.gru_unit(
            proj, h_flat, size=hidden_dim * 3,
            param_attr=p["gru_w"], bias_attr=p["gru_b"])
        logits = layers.fc(new_h, size=trg_vocab,
                           param_attr=p["out_w"], bias_attr=p["out_b"])
        probs = layers.softmax(logits)
        log_probs = layers.reshape(
            layers.log(layers.scale(probs, bias=1e-9)), [b, k, trg_vocab])

        sel_ids, sel_scores, parent_idx = layers.beam_search(
            pre_ids, pre_scores, None, log_probs, beam_size=k,
            end_id=eos_id)

        # reorder hidden by the parent beam each token came from
        par3 = layers.expand(layers.reshape(parent_idx, [b, k, 1]),
                             [1, 1, hidden_dim])
        new_h3 = layers.reshape(new_h, [b, k, hidden_dim])
        h_re = layers.take_along_axis(new_h3, par3, axis=1)

        layers.array_write(sel_ids, t, array=ids_arr)
        layers.array_write(parent_idx, t, array=parents_arr)
        layers.assign(h_re, output=hidden_state)
        layers.assign(sel_ids, output=pre_ids)
        layers.assign(sel_scores, output=pre_scores)
        layers.increment(t, value=1.0, in_place=True)
        layers.less_than(t, limit, cond=cond)

    sent_ids, sent_scores = layers.beam_search_decode(
        ids_arr, pre_scores, beam_size=k, end_id=eos_id,
        parents=parents_arr)
    return sent_ids, sent_scores, ["src_word"]
