"""Dygraph nn Layers + eager optimizers (VERDICT r3 item 7): Conv2D /
Pool2D / FC / Embedding / BatchNorm Layer classes train a LeNet eagerly to
accuracy parity with the graph path on the same synthetic digits.
Reference: python/paddle/fluid/imperative/nn.py:33 (Conv2D), :146
(Pool2D), :208 (FC)."""

import numpy as np

import paddle_tpu as pt
from paddle_tpu import imperative, layers
from paddle_tpu.imperative import nn as enn


def _synthetic_digits(rs, n):
    """Linearly-separable 'digits': class = brightest quadrant pattern."""
    imgs = np.zeros((n, 1, 16, 16), "float32")
    lbls = rs.randint(0, 4, (n, 1)).astype("int64")
    for i in range(n):
        q = int(lbls[i, 0])
        r0, c0 = (q // 2) * 8, (q % 2) * 8
        imgs[i, 0, r0:r0 + 8, c0:c0 + 8] = 1.0
    imgs += 0.15 * rs.randn(*imgs.shape).astype("float32")
    return imgs, lbls


class LeNet(imperative.Layer):
    def __init__(self):
        super().__init__("lenet")
        self.conv1 = enn.Conv2D(1, 6, 5, padding=2, act="relu")
        self.pool1 = enn.Pool2D(2, "max", 2)
        self.conv2 = enn.Conv2D(6, 16, 5, act="relu")
        self.pool2 = enn.Pool2D(2, "max", 2)
        self.bn = enn.BatchNorm(16)
        self.fc1 = enn.FC(32, act="relu")
        self.fc2 = enn.FC(4)

    def forward(self, x):
        h = self.pool1(self.conv1(x))
        h = self.pool2(self.conv2(h))
        h = self.bn(h)
        h = layers.reshape(h, [-1, 16 * 2 * 2])
        return self.fc2(self.fc1(h))


def _train_eager(steps=40, lr=1e-3, seed=5):
    rs = np.random.RandomState(seed)
    with imperative.guard(seed=0):
        model = LeNet()
        opt = pt.optimizer.AdamOptimizer(learning_rate=lr)
        accs, losses = [], []
        for _ in range(steps):
            xb, yb = _synthetic_digits(rs, 32)
            x = imperative.to_variable(xb, stop_gradient=True)
            y = imperative.to_variable(yb, stop_gradient=True)
            logits = model(x)
            loss = layers.mean(
                layers.softmax_with_cross_entropy(logits, y))
            opt.minimize(loss)
            losses.append(float(loss.numpy()))
            pred = np.asarray(logits.numpy()).argmax(1)
            accs.append((pred == yb[:, 0]).mean())
            model.clear_gradients()
        n_params = len(model.parameters())
    return losses, accs, n_params


def test_eager_lenet_trains_and_reuses_params():
    losses, accs, n_params = _train_eager()
    # conv1 w+b, conv2 w+b, bn scale+bias, fc1 w+b, fc2 w+b
    assert n_params == 10, n_params
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])
    assert np.mean(accs[-5:]) > 0.9, np.mean(accs[-5:])


def test_eager_matches_graph_path_accuracy():
    """Same data distribution, same architecture: eager training reaches
    the accuracy of the graph path within a few points."""
    _, eager_accs, _ = _train_eager(steps=50)

    rs = np.random.RandomState(5)
    img = layers.data(name="img", shape=[1, 16, 16], dtype="float32")
    lbl = layers.data(name="lbl", shape=[1], dtype="int64")
    c1 = layers.conv2d(img, num_filters=6, filter_size=5, padding=2,
                       act="relu")
    p1 = layers.pool2d(c1, pool_size=2, pool_type="max", pool_stride=2)
    c2 = layers.conv2d(p1, num_filters=16, filter_size=5, act="relu")
    p2 = layers.pool2d(c2, pool_size=2, pool_type="max", pool_stride=2)
    bn = layers.batch_norm(p2)
    flat = layers.reshape(bn, [-1, 16 * 2 * 2])
    f1 = layers.fc(flat, size=32, act="relu")
    logits = layers.fc(f1, size=4)
    loss = layers.mean(layers.softmax_with_cross_entropy(logits, lbl))
    acc = layers.accuracy(layers.softmax(logits), lbl)
    pt.optimizer.AdamOptimizer(learning_rate=1e-3).minimize(loss)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    graph_accs = []
    for _ in range(50):
        xb, yb = _synthetic_digits(rs, 32)
        _, av = exe.run(feed={"img": xb, "lbl": yb}, fetch_list=[loss, acc])
        graph_accs.append(float(np.asarray(av)))
    assert np.mean(graph_accs[-5:]) > 0.9
    assert abs(np.mean(eager_accs[-5:]) - np.mean(graph_accs[-5:])) < 0.08


def test_eager_embedding_layer():
    rs = np.random.RandomState(2)
    with imperative.guard():
        emb = enn.Embedding(size=[50, 8])
        ids = imperative.to_variable(
            rs.randint(0, 50, (4, 3)).astype("int64"), stop_gradient=True)
        out = emb(ids)
        v = out.numpy()
        assert v.shape == (4, 3, 8)
        # same table on second call (no re-init)
        v2 = emb(ids).numpy()
        np.testing.assert_allclose(v, v2)
        loss = layers.mean(emb(ids))
        loss.backward()
        g = emb._table.gradient()
        assert g is not None and g.shape == (50, 8)


def test_eager_batchnorm_running_stats_update():
    rs = np.random.RandomState(3)
    with imperative.guard():
        bn = enn.BatchNorm(4, momentum=0.5)
        x = imperative.to_variable(
            (rs.randn(8, 4, 3, 3) * 2 + 5).astype("float32"),
            stop_gradient=True)
        m0 = imperative.value_of(bn._mean).copy()
        bn(x)
        m1 = imperative.value_of(bn._mean)
        assert not np.allclose(m0, m1), "running mean must move"
        assert (m1 > 1.0).all()  # toward the data mean of ~5
