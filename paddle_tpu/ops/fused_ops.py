"""Fused ops backed by Pallas kernels (the TPU analogue of the reference's
operators/fused/ CPU+cuDNN fusions and operators/jit/ codegen kernels —
SURVEY.md §2.3)."""

from __future__ import annotations

from ..core.registry import register


@register("fused_attention")
def lower_fused_attention(ctx, ins):
    """Flash attention over [B,H,T,D] q/k/v with optional additive bias.

    No dropout inside the op: attention-weight dropout is not expressible in
    the streaming kernel, and in-op randomness would break the generic vjp
    re-trace.  The contrib layer applies a separate dropout op on the output
    (correct masked gradients via the dropout op's saved Mask)."""
    from ..kernels.attention import flash_attention

    q, k, v = ins["Q"][0], ins["K"][0], ins["V"][0]
    bias = ins.get("Bias", [None])[0]
    out = flash_attention(
        q, k, v, bias,
        scale=ctx.attr("scale", 1.0),
        causal=ctx.attr("causal", False),
        block_q=ctx.attr("block_q", 512),
        block_k=ctx.attr("block_k", 512),
    )
    return {"Out": [out]}


@register("fused_layer_norm_gelu")
def lower_fused_ln_gelu(ctx, ins):
    """layer_norm + gelu epilogue; XLA fuses these — kept as one op so graph
    passes can target it (parity with fuse_elewise_add_act ideas)."""
    import jax

    from .nn_ops import layer_norm_core

    x = ins["X"][0]
    y, _, _ = layer_norm_core(
        x,
        ins.get("Scale", [None])[0],
        ins.get("Bias", [None])[0],
        ctx.attr("begin_norm_axis", x.ndim - 1),
        ctx.attr("epsilon", 1e-5),
    )
    return {"Out": [jax.nn.gelu(y)]}
