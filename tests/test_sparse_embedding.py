"""Sparse embedding path: SelectedRows grads, sparse optimizer updates,
mesh-sharded tables, host-offloaded tables.

Mirrors the reference's sparse lookup_table contract
(lookup_table_op.h:41,132 SelectedRows grads; adagrad_op.h:24
SparseAdagradFunctor; operators/distributed/parameter_prefetch.cc
distributed tables)."""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.core.framework import grad_var_name


def _build_shared_table_net(is_sparse, opt_factory, vocab=50, dim=8):
    prog, startup = pt.Program(), pt.Program()
    with pt.program_guard(prog, startup):
        with pt.core.framework.guard_unique_name():
            ids = layers.data(name="ids", shape=[1], dtype="int64")
            ids2 = layers.data(name="ids2", shape=[1], dtype="int64")
            y = layers.data(name="y", shape=[1], dtype="int64")
            # shared table used twice: grads accumulate through the sum op
            # (all-SelectedRows sum = concat, math/selected_rows_functor.h)
            emb1 = layers.embedding(ids, size=[vocab, dim],
                                    is_sparse=is_sparse,
                                    param_attr=pt.ParamAttr(name="tbl"))
            emb2 = layers.embedding(ids2, size=[vocab, dim],
                                    is_sparse=is_sparse,
                                    param_attr=pt.ParamAttr(name="tbl"))
            h = layers.concat([emb1, emb2], axis=1)
            logits = layers.fc(h, size=2)
            loss = layers.mean(layers.softmax_with_cross_entropy(logits, y))
            opt_factory().minimize(loss)
    prog.random_seed = 7
    return prog, startup, loss


_OPTIMIZERS = {
    "sgd": lambda: pt.optimizer.SGD(learning_rate=0.1),
    # lazy_mode exercises the row-sparse adam branch; with an identical
    # batch each step the touched-row set is constant, so lazy == dense
    "adam": lambda: pt.optimizer.Adam(learning_rate=0.05, lazy_mode=True),
    "adam_nonlazy": lambda: pt.optimizer.Adam(learning_rate=0.05),
    "adagrad": lambda: pt.optimizer.Adagrad(learning_rate=0.1),
    "momentum": lambda: pt.optimizer.Momentum(learning_rate=0.1,
                                              momentum=0.9),
}


@pytest.mark.parametrize("opt_name", sorted(_OPTIMIZERS))
def test_sparse_matches_dense(opt_name):
    """Row-sparse updates must match the dense path bit-for-bit-ish, with
    duplicate ids inside the batch and across the two shared lookups."""
    rng = np.random.RandomState(0)
    feed = {
        "ids": rng.randint(0, 50, (32, 1)).astype("int64"),
        "ids2": rng.randint(0, 50, (32, 1)).astype("int64"),
        "y": rng.randint(0, 2, (32, 1)).astype("int64"),
    }
    losses = {}
    for sparse in (False, True):
        prog, startup, loss = _build_shared_table_net(
            sparse, _OPTIMIZERS[opt_name]
        )
        scope = pt.Scope()
        exe = pt.Executor(pt.CPUPlace())
        exe.run(startup, scope=scope)
        losses[sparse] = [
            float(np.asarray(
                exe.run(prog, feed=feed, fetch_list=[loss], scope=scope)[0]
            ))
            for _ in range(8)
        ]
    np.testing.assert_allclose(losses[False], losses[True],
                               rtol=2e-4, atol=2e-5)
    assert losses[True][-1] < losses[True][0]


def test_sparse_with_global_norm_clip():
    """Gradient clipping must work with row-sparse grads (reference clip.py
    merges SelectedRows before clipping)."""
    rng = np.random.RandomState(0)
    feed = {
        "ids": rng.randint(0, 50, (32, 1)).astype("int64"),
        "ids2": rng.randint(0, 50, (32, 1)).astype("int64"),
        "y": rng.randint(0, 2, (32, 1)).astype("int64"),
    }
    losses = {}
    for sparse in (False, True):
        prog, startup = pt.Program(), pt.Program()
        with pt.program_guard(prog, startup):
            with pt.core.framework.guard_unique_name():
                ids = layers.data(name="ids", shape=[1], dtype="int64")
                ids2 = layers.data(name="ids2", shape=[1], dtype="int64")
                y = layers.data(name="y", shape=[1], dtype="int64")
                emb1 = layers.embedding(ids, size=[50, 8], is_sparse=sparse,
                                        param_attr=pt.ParamAttr(name="tbl"))
                emb2 = layers.embedding(ids2, size=[50, 8], is_sparse=sparse,
                                        param_attr=pt.ParamAttr(name="tbl"))
                h = layers.concat([emb1, emb2], axis=1)
                loss = layers.mean(layers.softmax_with_cross_entropy(
                    layers.fc(h, size=2), y))
                pt.clip.set_gradient_clip(
                    pt.clip.GradientClipByGlobalNorm(0.01))
                pt.optimizer.SGD(learning_rate=0.5).minimize(loss)
        prog.random_seed = 7
        scope = pt.Scope()
        exe = pt.Executor(pt.CPUPlace())
        exe.run(startup, scope=scope)
        losses[sparse] = [
            float(np.asarray(
                exe.run(prog, feed=feed, fetch_list=[loss], scope=scope)[0]))
            for _ in range(6)
        ]
    np.testing.assert_allclose(losses[False], losses[True],
                               rtol=2e-4, atol=2e-5)


def test_selected_rows_merge():
    """merged() combines duplicate ids exactly (MergeAdd parity)."""
    import jax.numpy as jnp

    from paddle_tpu.core.selected_rows import SelectedRows

    ids = jnp.array([3, 1, 3, 7, 1, 3], "int32")
    rows = jnp.arange(12, dtype="float32").reshape(6, 2)
    sr = SelectedRows(ids, rows, height=10)
    uids, mrows = sr.merged()
    dense = np.zeros((10, 2), "float32")
    np.add.at(dense, np.asarray(ids), np.asarray(rows))
    got = np.zeros((10, 2), "float32")
    for u, r in zip(np.asarray(uids), np.asarray(mrows)):
        if u < 10:
            got[u] += np.asarray(r)
    np.testing.assert_allclose(got, dense)
    # dense scatter round-trip
    np.testing.assert_allclose(np.asarray(sr.to_dense()), dense)


@pytest.mark.slow
def test_deepfm_full_hash_dim_trains():
    """The dist_ctr.py north-star config: 26 slots x hash_dim=1,000,001.
    Viable only because grads are row-sparse — the dense path would
    materialize 26 zeros_like([1e6, D]) tensors per step."""
    from paddle_tpu.models import deepfm

    prog, startup = pt.Program(), pt.Program()
    with pt.program_guard(prog, startup):
        with pt.core.framework.guard_unique_name():
            avg_cost, auc_var, _, _ = deepfm.build_train_net(
                embedding_size=4, hash_dim=1000001, is_sparse=True, lr=1e-2,
                optimizer="sgd",
            )
    scope = pt.Scope()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(0)
    batch = deepfm.make_batch(64, hash_dim=1000001, rng=rng)
    losses = []
    for _ in range(5):
        l, _ = exe.run(prog, feed=batch, fetch_list=[avg_cost, auc_var],
                       scope=scope)
        losses.append(float(np.asarray(l)))
    assert losses[-1] < losses[0], losses
    assert np.isfinite(losses).all()


def test_vocab_sharded_embedding_parity():
    """Vocab-sharded table over the virtual 8-device mesh: same losses as
    the unsharded single-device run (GSPMD gathers replace RPC prefetch)."""
    import jax

    from paddle_tpu.parallel.embedding import vocab_sharded_rules
    from paddle_tpu.parallel.sharding import ShardingPlan, ShardedProgram

    if len(jax.devices()) < 8:
        pytest.skip("needs the virtual 8-device mesh")

    def build():
        prog, startup = pt.Program(), pt.Program()
        with pt.program_guard(prog, startup):
            with pt.core.framework.guard_unique_name():
                ids = layers.data(name="ids", shape=[1], dtype="int64")
                y = layers.data(name="y", shape=[1], dtype="int64")
                emb = layers.embedding(
                    ids, size=[64, 16], is_sparse=False,
                    param_attr=pt.ParamAttr(name="big_table"))
                loss = layers.mean(layers.softmax_with_cross_entropy(
                    layers.fc(emb, size=2), y))
                pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
        prog.random_seed = 3
        return prog, startup, loss

    rng = np.random.RandomState(1)
    feed = {
        "ids": rng.randint(0, 64, (16, 1)).astype("int64"),
        "y": rng.randint(0, 2, (16, 1)).astype("int64"),
    }

    # single-device reference
    prog, startup, loss = build()
    scope = pt.Scope()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup, scope=scope)
    ref = [float(np.asarray(
        exe.run(prog, feed=feed, fetch_list=[loss], scope=scope)[0]))
        for _ in range(4)]

    # vocab-sharded over model axis
    prog, startup, loss = build()
    scope = pt.Scope()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup, scope=scope)
    plan = ShardingPlan(
        mesh_axes={"data": 2, "model": 4},
        param_rules=vocab_sharded_rules(["big_table"]),
    )
    sharded = ShardedProgram(prog, plan, loss_name=loss.name)
    got = [float(np.asarray(
        exe.run(sharded, feed=feed, fetch_list=[loss], scope=scope)[0]))
        for _ in range(4)]
    np.testing.assert_allclose(ref, got, rtol=1e-4, atol=1e-5)


def test_host_embedding_table_parity():
    """Host-offloaded table (pserver-capability parity): lookup on host,
    feed rows, fetch row grads, apply on host — must track the all-device
    run."""
    from paddle_tpu.parallel.embedding import HostEmbeddingTable

    dim, vocab, bs = 8, 40, 16
    rng = np.random.RandomState(0)
    ids_np = rng.randint(0, vocab, (bs, 1)).astype("int64")
    y_np = rng.randint(0, 2, (bs, 1)).astype("int64")

    # --- host-offloaded run ---
    prog, startup = pt.Program(), pt.Program()
    with pt.program_guard(prog, startup):
        with pt.core.framework.guard_unique_name():
            rows = layers.data(name="rows", shape=[dim], dtype="float32")
            rows.stop_gradient = False
            y = layers.data(name="y", shape=[1], dtype="int64")
            logits = layers.fc(rows, size=2,
                               param_attr=pt.ParamAttr(name="w"),
                               bias_attr=pt.ParamAttr(name="b"))
            loss = layers.mean(layers.softmax_with_cross_entropy(logits, y))
            opt = pt.optimizer.SGD(learning_rate=0.1)
            opt.minimize(loss)
    table = HostEmbeddingTable(vocab, dim, optimizer="sgd", lr=0.1, seed=5)
    scope = pt.Scope()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup, scope=scope)
    w0 = np.asarray(scope.find_var("w")).copy()
    b0 = np.asarray(scope.find_var("b")).copy()
    host_losses = []
    for _ in range(6):
        rows_np = table.lookup(ids_np[:, 0])
        l, g = exe.run(
            prog, feed={"rows": rows_np, "y": y_np},
            fetch_list=[loss, grad_var_name("rows")], scope=scope,
        )
        table.apply_grad(ids_np[:, 0], np.asarray(g))
        host_losses.append(float(np.asarray(l)))
    assert host_losses[-1] < host_losses[0]

    # --- all-device reference with identical init ---
    prog, startup = pt.Program(), pt.Program()
    with pt.program_guard(prog, startup):
        with pt.core.framework.guard_unique_name():
            ids = layers.data(name="ids", shape=[1], dtype="int64")
            y = layers.data(name="y", shape=[1], dtype="int64")
            emb = layers.embedding(ids, size=[vocab, dim], is_sparse=True,
                                   param_attr=pt.ParamAttr(name="tbl"))
            logits = layers.fc(emb, size=2,
                               param_attr=pt.ParamAttr(name="w"),
                               bias_attr=pt.ParamAttr(name="b"))
            loss = layers.mean(layers.softmax_with_cross_entropy(logits, y))
            pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
    scope2 = pt.Scope()
    exe2 = pt.Executor(pt.CPUPlace())
    exe2.run(startup, scope=scope2)
    # identical table + fc init
    ref_table = HostEmbeddingTable(vocab, dim, optimizer="sgd", lr=0.1,
                                   seed=5)
    scope2.set_var("tbl", np.asarray(ref_table.table))
    scope2.set_var("w", w0)
    scope2.set_var("b", b0)
    dev_losses = []
    for _ in range(6):
        (l,) = exe2.run(prog, feed={"ids": ids_np, "y": y_np},
                        fetch_list=[loss], scope=scope2)
        dev_losses.append(float(np.asarray(l)))
    np.testing.assert_allclose(host_losses, dev_losses, rtol=1e-4, atol=1e-5)
