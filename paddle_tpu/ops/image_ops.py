"""Image-space ops: affine_grid, grid_sampler, random_crop, hash.

Capability parity with the reference's affine_grid_op.cc, grid_sampler_op.cc
(cuDNN spatial-transformer path), random_crop_op.cc and hash_op.cc (xxhash),
rebuilt TPU-first: everything is a static-shape gather/interpolation XLA
lowering; random_crop draws its offsets from the executor's threefry key
(no host RNG round-trip); hash is a splitmix-style integer mix instead of a
dlopen'd xxhash (deterministic across hosts, vectorizes on VPU).
"""

from __future__ import annotations

from ..core.registry import register


def _jnp():
    import jax.numpy as jnp

    return jnp


def _affine_infer(ctx):
    ts = ctx.input_shape("Theta")
    shape = ctx.attr("output_shape")
    if ts is not None and shape:
        n = ts[0]
        ctx.set_output("Output", [n, shape[-2], shape[-1], 2],
                       ctx.input_dtype("Theta"))


@register("affine_grid", infer_shape=_affine_infer)
def lower_affine_grid(ctx, ins):
    """Theta [N,2,3] + output_shape attr [N,C,H,W] -> sampling grid
    [N,H,W,2] of (x,y) in [-1,1] (reference affine_grid_op.cc / layer
    nn.py:7239; align_corners=True semantics of fluid 1.2)."""
    jnp = _jnp()
    theta = ins["Theta"][0]
    shape = ins.get("OutputShape", [None])[0]
    if shape is not None:
        # dynamic shape input unsupported on TPU (static shapes); require attr
        raise ValueError("affine_grid: pass output_shape as a static attr")
    out_shape = ctx.attr("output_shape")
    h, w = int(out_shape[-2]), int(out_shape[-1])
    xs = jnp.linspace(-1.0, 1.0, w, dtype=theta.dtype)
    ys = jnp.linspace(-1.0, 1.0, h, dtype=theta.dtype)
    gx, gy = jnp.meshgrid(xs, ys)                      # [H, W]
    ones = jnp.ones_like(gx)
    base = jnp.stack([gx, gy, ones], axis=-1)          # [H, W, 3]
    # grid[n,h,w,k] = sum_j theta[n,k,j] * base[h,w,j]
    grid = jnp.einsum("nkj,hwj->nhwk", theta, base)
    return {"Output": [grid]}


@register("grid_sampler")
def lower_grid_sampler(ctx, ins):
    """Bilinear sampling of X [N,C,H,W] at Grid [N,Hg,Wg,2] ((x,y) in
    [-1,1]); out-of-bounds reads contribute zero (reference
    grid_sampler_op.cc zeros-padding mode, align_corners=True)."""
    jnp = _jnp()
    x = ins["X"][0]
    grid = ins["Grid"][0]
    n, c, h, w = x.shape
    gx = (grid[..., 0] + 1.0) / 2.0 * (w - 1)          # [N,Hg,Wg]
    gy = (grid[..., 1] + 1.0) / 2.0 * (h - 1)
    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)
    batch = jnp.arange(n)[:, None, None]

    def tap(yi, xi):
        wgt = (1.0 - jnp.abs(gx - xi)) * (1.0 - jnp.abs(gy - yi))
        inb = (xi >= 0) & (xi <= w - 1) & (yi >= 0) & (yi <= h - 1)
        xc = jnp.clip(xi, 0, w - 1).astype(jnp.int32)
        yc = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
        v = x[batch, :, yc, xc]                        # [N,Hg,Wg,C]
        wgt = jnp.where(inb, wgt, 0.0).astype(x.dtype)
        return v * wgt[..., None]

    out = (tap(y0, x0) + tap(y0, x0 + 1) + tap(y0 + 1, x0)
           + tap(y0 + 1, x0 + 1))                      # [N,Hg,Wg,C]
    return {"Output": [jnp.transpose(out, (0, 3, 1, 2))]}


def _crop_infer(ctx):
    xs = ctx.input_shape("X")
    shape = ctx.attr("shape")
    if xs is not None and shape:
        k = len(shape)
        ctx.set_output("Out", list(xs[: len(xs) - k]) + list(shape),
                       ctx.input_dtype("X"))


@register("random_crop", no_grad=True, infer_shape=_crop_infer,
          derives_rng=True)
def lower_random_crop(ctx, ins):
    """Crop a random window of attr `shape` from each instance's trailing
    dims (reference random_crop_op.cc/.h RandomCropFunctor; the Seed
    input/attr is replaced by the executor's per-op threefry key — listed
    in the executor's _RANDOM_OPS set)."""
    import jax
    jnp = _jnp()

    x = ins["X"][0]
    crop = [int(s) for s in ctx.attr("shape")]
    k = len(crop)
    lead = x.shape[: x.ndim - k]
    tail = x.shape[x.ndim - k:]
    key = ctx.next_rng_key()
    batch = 1
    for d in lead:
        batch *= d
    xf = x.reshape((batch,) + tuple(tail))
    # draw per-instance, per-dim offsets in one batched call
    maxs = jnp.asarray([tail[j] - crop[j] + 1 for j in range(k)])
    u = jax.random.uniform(key, (batch, k))
    starts = jnp.floor(u * maxs[None, :]).astype(jnp.int32)
    starts = jnp.minimum(starts, maxs[None, :] - 1)

    def slice_one(xi, si):
        return jax.lax.dynamic_slice(xi, tuple(si[j] for j in range(k)),
                                     crop)

    out = jax.vmap(slice_one)(xf, starts)
    return {"Out": [out.reshape(tuple(lead) + tuple(crop))]}


@register("hash", no_grad=True)
def lower_hash(ctx, ins):
    """Hash each input row num_hash times into [0, mod_by) (reference
    hash_op.cc uses xxhash over the row bytes; here a splitmix32-style
    avalanche mix seeded per hash index — deterministic, vectorized)."""
    jnp = _jnp()
    x = ins["X"][0]
    num_hash = ctx.attr("num_hash", 1)
    mod_by = ctx.attr("mod_by", 1)
    ids = x.reshape(x.shape[0], -1).astype(jnp.uint32)

    def mix(v):
        v = (v ^ (v >> 16)) * jnp.uint32(0x7FEB352D)
        v = (v ^ (v >> 15)) * jnp.uint32(0x846CA68B)
        return v ^ (v >> 16)

    outs = []
    for i in range(num_hash):
        seed = (0x9E3779B9 * (i + 1)) & 0xFFFFFFFF
        acc = jnp.full((ids.shape[0],), jnp.uint32(seed))
        for j in range(ids.shape[1]):
            acc = mix(acc ^ ids[:, j])
        outs.append((acc % jnp.uint32(mod_by)).astype(jnp.int32))
    out = jnp.stack(outs, axis=1)[..., None]           # [N, num_hash, 1]
    return {"Out": [out]}
