"""Memory-optimization tier (paddle_tpu/memory): the static HBM liveness
planner (hand-computed red-gates, class split, sub-blocks, accumulated /
pipeline-stage variants, XLA memory_analysis agreement), the
activation-recompute pass (loss/grad parity, bit-identical dropout
masks, rng-without-id stash rule, flag-off zero cost, verifier-clean
output, checkpoint interop), and the host-offload pass (value parity,
exact watermark subtraction)."""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers, memory
from paddle_tpu.analysis import verify_program
from paddle_tpu.core import framework as fw
from paddle_tpu.flags import FLAGS


# ---------------------------------------------------------------------------
# builders
# ---------------------------------------------------------------------------


def _mlp(dropout=0.3, sizes=(32, 32), feature=8, optimizer="adam"):
    prog, start = pt.Program(), pt.Program()
    with pt.program_guard(prog, start):
        x = layers.data(name="x", shape=[feature], dtype="float32")
        y = layers.data(name="y", shape=[1], dtype="float32")
        h = x
        for i, sz in enumerate(sizes):
            h = layers.fc(h, size=sz, act="tanh",
                          param_attr=pt.ParamAttr(name=f"w{i}"),
                          bias_attr=pt.ParamAttr(name=f"b{i}"))
            if dropout:
                h = layers.dropout(
                    h, dropout_prob=dropout,
                    dropout_implementation="upscale_in_train")
        pred = layers.fc(h, size=1, param_attr=pt.ParamAttr(name="w_out"),
                         bias_attr=pt.ParamAttr(name="b_out"))
        loss = layers.mean(layers.square(pred - y))
        if optimizer == "adam":
            pt.optimizer.AdamOptimizer(learning_rate=0.01).minimize(loss)
        else:
            pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return prog, start, loss


def _tiny_transformer(dropout=0.1, seq=16, n_layer=2):
    from paddle_tpu.models import transformer as T

    prog, start = pt.Program(), pt.Program()
    with pt.program_guard(prog, start), fw.guard_unique_name():
        avg_cost, _, feeds = T.transformer(
            src_vocab_size=128, trg_vocab_size=128, max_length=32,
            n_layer=n_layer, n_head=4, d_key=16, d_value=16, d_model=64,
            d_inner_hid=128, dropout_rate=dropout, src_seq_len=seq,
            trg_seq_len=seq, use_flash=False)
        pt.optimizer.Adam(learning_rate=1e-3).minimize(avg_cost)
    return prog, start, avg_cost.name, list(feeds)


def _transformer_feed(k, mbs, seq=16):
    from paddle_tpu.models import transformer as T

    batches = [T.make_batch(mbs, seq, seq, 4, 128, 128,
                            rng=np.random.RandomState(s))
               for s in range(k)]
    return {n: np.stack([b[n] for b in batches]) for n in batches[0]}


def _run_pair(prog_a, prog_b, start, loss_name, feed, steps=3,
              runner=None):
    """Run both programs from IDENTICAL param init; returns (losses_a,
    losses_b, params_a, params_b)."""
    pnames = [p.name for p in prog_a.all_parameters()]

    def one(prog):
        scope, exe = pt.Scope(), pt.Executor()
        exe.run(start, scope=scope)
        if one.init is None:
            one.init = {n: np.asarray(scope.find_var(n)).copy()
                        for n in pnames}
        else:
            for n, v in one.init.items():
                scope.set_var(n, v)
        losses = []
        for _ in range(steps):
            if runner is None:
                out = exe.run(prog, feed=feed, fetch_list=[loss_name],
                              scope=scope)
            else:
                out = runner(exe, prog, scope)
            losses.append(np.asarray(out[0]))
        return losses, {n: np.asarray(scope.find_var(n)) for n in pnames}

    one.init = None
    la, pa = one(prog_a)
    lb, pb = one(prog_b)
    return la, lb, pa, pb


# ---------------------------------------------------------------------------
# planner red-gates
# ---------------------------------------------------------------------------


def _fabricate_chain():
    """square-op chain with fully known shapes: a[4,8] -> b -> c -> d,
    every var 4*8*4 = 128 bytes.  Liveness by hand: feed a dies after
    op0, b after op1, c after op2; d is the fetch.  The sweep's live set
    is 256 bytes at every op — the hand-computed peak."""
    prog = pt.Program()
    blk = prog.global_block()
    blk.create_var(name="a", shape=[4, 8], dtype="float32", is_data=True)
    for n in ("b", "c", "d"):
        blk.create_var(name=n, shape=[4, 8], dtype="float32")
    blk.append_op("square", {"X": ["a"]}, {"Out": ["b"]})
    blk.append_op("square", {"X": ["b"]}, {"Out": ["c"]})
    blk.append_op("square", {"X": ["c"]}, {"Out": ["d"]})
    return prog


class TestPlanner:
    def test_hand_computed_peak(self):
        plan = memory.plan_program(_fabricate_chain(), ["a"], ["d"])
        assert plan.peak_bytes == 256
        assert plan.warnings == []
        # lifetimes table is exact
        assert plan.lifetimes["a"].last_use == 0
        assert plan.lifetimes["b"].last_use == 1
        assert plan.lifetimes["d"].last_use == 2
        # b, c, d are forward products = activations; a is the feed
        assert plan.lifetimes["b"].klass == "activations"
        assert plan.lifetimes["a"].klass == "feeds"

    def test_unknown_shape_degrades_to_named_warning(self):
        prog = pt.Program()
        blk = prog.global_block()
        blk.create_var(name="a", shape=[4, 8], dtype="float32",
                       is_data=True)
        blk.create_var(name="b")
        blk.create_var(name="c", shape=[4, 8], dtype="float32")
        blk.append_op("square", {"X": ["a"]}, {"Out": ["b"]})
        blk.append_op("square", {"X": ["b"]}, {"Out": ["c"]})
        blk.vars["b"].shape = None  # stale/undeclared IR shape
        plan = memory.plan_program(prog, ["a"], ["c"])
        assert any(w["var"] == "b" and w["check"] == "unknown-shape"
                   for w in plan.warnings)
        # degraded to 0 bytes, never a fabricated number
        assert plan.lifetimes["b"].bytes == 0
        # a (128 B) dies after op0 and b contributes 0: both op live
        # sets hold exactly one known 128 B var
        assert plan.peak_bytes == 128

    def test_batch_substitution(self):
        prog = pt.Program()
        blk = prog.global_block()
        blk.create_var(name="a", shape=[-1, 8], dtype="float32",
                       is_data=True)
        blk.create_var(name="b", shape=[-1, 8], dtype="float32")
        blk.append_op("square", {"X": ["a"]}, {"Out": ["b"]})
        plan = memory.plan_program(prog, ["a"], ["b"], batch_size=16)
        assert plan.lifetimes["b"].bytes == 16 * 8 * 4
        assert plan.warnings == []
        plan0 = memory.plan_program(prog, ["a"], ["b"])
        assert plan0.lifetimes["b"].bytes == 0
        assert any(w["check"] == "dynamic-dim" for w in plan0.warnings)

    def test_class_split_on_trained_mlp(self):
        prog, _, loss = _mlp(dropout=0.0)
        plan = memory.plan_program(prog, ["x", "y"], [loss.name],
                                   batch_size=16)
        assert plan.class_peaks["params"] > 0
        assert plan.class_peaks["opt_state"] > 0       # adam moments
        assert plan.class_peaks["activations"] > 0
        assert plan.class_peaks["workspace"] > 0       # grads
        assert plan.peak_bytes >= plan.class_peaks["params"]
        # the fwd->bwd gap signal exists for a stashed activation
        gaps = [lf.fwd_bwd_gap for lf in plan.lifetimes.values()
                if lf.klass == "activations"]
        assert max(gaps) > 0

    def test_sub_block_peak_charged_at_parent(self):
        # fabricated op types (no registered infer) keep the declared
        # shapes authoritative — the planner is registry-independent
        prog = pt.Program()
        blk = prog.global_block()
        blk.create_var(name="a", shape=[4, 8], dtype="float32",
                       is_data=True)
        blk.create_var(name="out", shape=[4, 8], dtype="float32")
        sub = prog._create_block()
        sub.create_var(name="i1", shape=[16, 16], dtype="float32")
        sub.create_var(name="i2", shape=[16, 16], dtype="float32")
        sub.append_op("fab_body_op", {"X": ["a"]}, {"Out": ["i1"]})
        sub.append_op("fab_body_op", {"X": ["i1"]}, {"Out": ["i2"]})
        prog.current_block_idx = 0
        blk.append_op("while", {"X": ["a"]}, {"Out": ["out"]},
                      attrs={"sub_block": sub})
        plan = memory.plan_program(prog, ["a"], ["out"])
        # 128 (a) + 128 (out) + 2048 (interior body transient: i1 + i2
        # both live at the body's second op)
        assert plan.peak_bytes == 128 + 128 + 2 * 16 * 16 * 4

    def test_plan_accumulated_scales_feed_stack(self):
        prog, _, loss = _mlp(dropout=0.0)
        r1 = memory.plan_accumulated(prog, ["x", "y"], [loss.name],
                                     accumulate_steps=1, batch_size=8)
        r4 = memory.plan_accumulated(prog, ["x", "y"], [loss.name],
                                     accumulate_steps=4, batch_size=8)
        assert r4["grad_sum_bytes"] == r1["grad_sum_bytes"] > 0
        assert r4["feed_stack_bytes"] == 4 * r1["feed_stack_bytes"]
        assert r4["peak_bytes"] > r1["peak_bytes"]

    def test_plan_stages_stash_and_inflight(self):
        from paddle_tpu.parallel.pipeline import split_program

        prog, _, loss = _mlp(dropout=0.0, sizes=(16, 16))
        stages = split_program(prog, ["x", "y"], n_stages=2)
        rows = memory.plan_stages(stages, schedule="1f1b",
                                  micro_batches=8, batch_size=8)
        assert len(rows) == 2
        assert all(r["in_flight"] == 2 for r in rows)  # min(K, S)
        grows = memory.plan_stages(stages, schedule="gpipe",
                                   micro_batches=8, batch_size=8)
        assert all(r["in_flight"] == 8 for r in grows)
        # some stage stashes fwd state for its own bwd
        assert any(r["stash_bytes"] > 0 for r in rows)
        assert all(r["peak_bytes"] > 0 for r in rows)

    def test_activation_cost_split_balances(self):
        from paddle_tpu.parallel.pipeline import split_program

        prog, _, loss = _mlp(dropout=0.0, sizes=(16, 16, 16))
        stages = split_program(prog.clone(), ["x", "y"], n_stages=2,
                               cost="activations")
        assert stages.n_stages == 2
        assert all(st.fwd_idx for st in stages)

    def test_agreement_mnist(self):
        """Estimator vs compiled.memory_analysis() ground truth on the
        mnist train step (CPU): within the STATED tolerance factor."""
        from paddle_tpu.models import mnist as M

        prog, start = pt.Program(), pt.Program()
        with pt.program_guard(prog, start):
            img, label, avg_cost, acc, _ = M.build_train_net()
            pt.optimizer.SGD(learning_rate=0.01).minimize(avg_cost)
        bs = 32
        plan = memory.plan_program(prog, ["pixel", "label"],
                                   [avg_cost.name], batch_size=bs)
        scope, exe = pt.Scope(), pt.Executor()
        exe.run(start, scope=scope)
        rng = np.random.RandomState(0)
        feed = {"pixel": rng.rand(bs, 1, 28, 28).astype("float32"),
                "label": rng.randint(0, 10, (bs, 1)).astype("int64")}
        stats = memory.xla_cross_check(plan, exe, prog, feed,
                                       [avg_cost.name], scope)
        ratio = plan.peak_bytes / stats["peak_bytes"]
        assert 1.0 / memory.PLANNER_XLA_TOLERANCE <= ratio \
            <= memory.PLANNER_XLA_TOLERANCE, (plan.peak_bytes, stats)
        # the delta rides the plan artifact
        assert plan.to_dict()["xla_ratio"] == round(ratio, 3)

    @pytest.mark.slow
    @pytest.mark.parametrize("model", ["transformer", "bert"])
    def test_agreement_base_widths(self, model):
        """The CI agreement gate at transformer-base / bert-base widths
        (short seq + small batch keep the CPU compile tractable — the
        run_ci pipeline-leg convention)."""
        prog, start = pt.Program(), pt.Program()
        bs = 2
        if model == "transformer":
            from paddle_tpu.models import transformer as T

            with pt.program_guard(prog, start), fw.guard_unique_name():
                avg, _, feeds = T.transformer(
                    src_vocab_size=2048, trg_vocab_size=2048,
                    max_length=32, n_layer=6, n_head=8, d_key=64,
                    d_value=64, d_model=512, d_inner_hid=2048,
                    dropout_rate=0.1, src_seq_len=32, trg_seq_len=32,
                    use_flash=False)
                pt.optimizer.Adam(learning_rate=1e-4).minimize(avg)
            feed = T.make_batch(bs, 32, 32, 8, 2048, 2048,
                                rng=np.random.RandomState(0))
            loss_name = avg.name
        else:
            from paddle_tpu.models import bert as B

            with pt.program_guard(prog, start), fw.guard_unique_name():
                avg, _ = B.build_pretrain_net(
                    vocab_size=4096, seq_len=32, n_layer=12, n_head=12,
                    d_model=768, d_ff=3072, dropout_rate=0.1,
                    use_flash=False)
            batch = B.make_batch(bs, 32, 4096,
                                 rng=np.random.RandomState(0))
            feed = batch
            feeds = sorted(batch)
            loss_name = avg.name
        plan = memory.plan_program(prog, sorted(feed), [loss_name],
                                   batch_size=bs)
        assert plan.warnings == []
        scope, exe = pt.Scope(), pt.Executor()
        exe.run(start, scope=scope)
        stats = memory.xla_cross_check(plan, exe, prog, feed,
                                       [loss_name], scope)
        ratio = plan.peak_bytes / stats["peak_bytes"]
        assert 1.0 / memory.PLANNER_XLA_TOLERANCE <= ratio \
            <= memory.PLANNER_XLA_TOLERANCE, (model, plan.peak_bytes,
                                              stats)


# ---------------------------------------------------------------------------
# recompute pass
# ---------------------------------------------------------------------------


class TestRecompute:
    def test_flag_off_zero_cost(self):
        prog, _, loss = _mlp()
        fp = prog.fingerprint()
        assert FLAGS.recompute == ""
        assert memory.maybe_optimize_memory(
            prog, ["x", "y"], [loss.name]) is None
        assert prog.fingerprint() == fp  # byte-identical

    def test_mlp_parity_and_peak(self):
        prog, start, loss = _mlp(dropout=0.3)
        prog2 = prog.clone()
        rep = memory.apply_recompute(prog2, ["x", "y"],
                                     fetch_names=[loss.name],
                                     batch_size=16)
        assert rep["cloned_ops"] > 0
        assert rep["activation_peak_after"] < rep["activation_peak_before"]
        rng = np.random.RandomState(0)
        feed = {"x": rng.randn(16, 8).astype("float32"),
                "y": rng.randn(16, 1).astype("float32")}
        la, lb, pa, pb = _run_pair(prog, prog2, start, loss.name, feed)
        # forward MATH is untouched, but the rewritten program is a
        # separately compiled XLA module: a reduce feeding only the
        # fetched loss scalar may re-round its last bit (the PR-12
        # class) — losses agree to 1 ulp, params to a TIGHT tolerance
        for a, b in zip(la, lb):
            np.testing.assert_allclose(a, b, rtol=1e-6)
        for n in pa:
            np.testing.assert_allclose(pa[n], pb[n], rtol=1e-6,
                                       atol=1e-7)

    def test_dropout_mask_bit_identical(self):
        """A recomputed segment containing dropout regenerates the SAME
        mask: the renamed recomputed value equals the stashed original
        bitwise in one run (the static rng_id replays the step key)."""
        prog, start, loss = _mlp(dropout=0.4)
        prog2 = prog.clone()
        memory.apply_recompute(prog2, ["x", "y"], fetch_names=[loss.name],
                               batch_size=16)
        blk = prog2.global_block()
        rc = sorted(n for n in blk.vars
                    if n.startswith("dropout_") and "@RC" in n
                    and not n.endswith(".tmp_1"))
        assert rc, "no recomputed dropout output — segment missed dropout"
        orig = rc[0].split("@RC")[0]
        scope, exe = pt.Scope(), pt.Executor()
        exe.run(start, scope=scope)
        rng = np.random.RandomState(0)
        feed = {"x": rng.randn(16, 8).astype("float32"),
                "y": rng.randn(16, 1).astype("float32")}
        a, b = exe.run(prog2, feed=feed, fetch_list=[orig, rc[0]],
                       scope=scope)
        assert np.array_equal(a, b)
        assert np.any(a == 0.0)  # dropout actually dropped something

    def test_rng_without_static_id_stays_stashed(self):
        """An RNG op with no rng_id/seed cannot replay deterministically:
        the pass must stash its output, not clone a DIFFERENT mask."""
        prog, start = pt.Program(), pt.Program()
        with pt.program_guard(prog, start):
            x = layers.data(name="x", shape=[8], dtype="float32")
            h = layers.fc(x, size=8, act="tanh",
                          param_attr=pt.ParamAttr(name="w0"),
                          bias_attr=pt.ParamAttr(name="b0"))
            u = layers.ops.uniform_random([16, 8])
            h2 = h * u
            h3 = layers.fc(h2, size=8, act="tanh",
                           param_attr=pt.ParamAttr(name="w1"),
                           bias_attr=pt.ParamAttr(name="b1"))
            loss = layers.mean(layers.square(h3))
            pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
        u_name = u.name
        ck = [op.output("Out")[0] for op in prog.global_block().ops
              if op.type == "tanh"]
        memory.apply_recompute(prog, ["x"], checkpoints=ck[:1],
                               fetch_names=[loss.name], batch_size=16)
        blk = prog.global_block()
        # no clone of the uniform_random, no rename of its output
        assert not any(op.type == "uniform_random"
                       and op.attr("recompute_segment") is not None
                       for op in blk.ops)
        assert u_name + "@RC1" not in blk.vars
        # its backward reader still reads the stashed original
        readers = [op for op in blk.ops
                   if u_name in op.input_arg_names()
                   and op.type.endswith("_grad")]
        assert readers
        # and the rewritten program still runs
        scope, exe = pt.Scope(), pt.Executor()
        exe.run(start, scope=scope)
        exe.run(prog, feed={"x": np.ones((16, 8), np.float32)},
                fetch_list=[loss.name], scope=scope)

    def test_verifier_clean_and_checkpoint_interop(self):
        prog, start, loss = _mlp(dropout=0.3)
        names_before = sorted(p.name for p in prog.all_parameters())
        prog2 = prog.clone()
        memory.apply_recompute(prog2, ["x", "y"], fetch_names=[loss.name],
                               batch_size=16)
        findings = verify_program(prog2, feed_names=["x", "y"],
                                  fetch_names=[loss.name],
                                  check_dead=True)
        assert findings == [], [str(f) for f in findings]
        # checkpoint-v2 interop: param names unchanged across the flag,
        # so a scope saved under either program loads into the other
        assert sorted(p.name for p in prog2.all_parameters()) \
            == names_before

    def test_checkpoint_v2_roundtrip_across_flag(self, tmp_path):
        prog, start, loss = _mlp(dropout=0.0, sizes=(16,))
        prog2 = prog.clone()
        memory.apply_recompute(prog2, ["x", "y"], fetch_names=[loss.name],
                               batch_size=8)
        feed = {"x": np.ones((8, 8), np.float32),
                "y": np.ones((8, 1), np.float32)}
        scope, exe = pt.Scope(), pt.Executor()
        exe.run(start, scope=scope)
        exe.run(prog2, feed=feed, fetch_list=[loss.name], scope=scope)
        pt.io.save_persistables(exe, str(tmp_path), main_program=prog2,
                                scope=scope)
        # load the rewritten program's checkpoint under the PLAIN program
        scope2, exe2 = pt.Scope(), pt.Executor()
        exe2.run(start, scope=scope2)
        pt.io.load_persistables(exe2, str(tmp_path), main_program=prog,
                                scope=scope2)
        for p in prog.all_parameters():
            np.testing.assert_array_equal(
                np.asarray(scope.find_var(p.name)),
                np.asarray(scope2.find_var(p.name)))

    @pytest.mark.slow
    def test_tiny_transformer_reduction_and_parity(self):
        prog, start, loss_name, feeds = _tiny_transformer()
        pt.amp.enable(prog)
        prog2 = prog.clone()
        prog2._amp_bf16 = True
        rep = memory.apply_recompute(prog2, feeds,
                                     fetch_names=[loss_name],
                                     batch_size=4)
        before, after = (rep["activation_peak_before"],
                         rep["activation_peak_after"])
        assert 1.0 - after / before >= 0.40, (before, after)
        assert rep["flops_ratio"] <= 1.35
        findings = verify_program(prog2, feed_names=feeds,
                                  fetch_names=[loss_name],
                                  check_dead=True)
        assert findings == [], [str(f) for f in findings]
        # run_accumulated compose: K=2 micro-batches, dropout + amp on —
        # training state parity at tight tolerance
        feed = _transformer_feed(2, 2)

        def runner(exe, prog_, scope):
            return exe.run_accumulated(prog_, feed=feed,
                                       fetch_list=[loss_name],
                                       scope=scope)

        la, lb, pa, pb = _run_pair(prog, prog2, start, loss_name, feed,
                                   steps=2, runner=runner)
        for n in pa:
            np.testing.assert_allclose(
                pa[n].astype(np.float32), pb[n].astype(np.float32),
                rtol=2e-6, atol=1e-7)

    def test_composes_with_pipeline_stage(self):
        """Recompute within a stage: the pass applied to a split_program
        stage program emits verifier-clean IR."""
        from paddle_tpu.parallel.pipeline import split_program

        prog, start, loss = _mlp(dropout=0.0, sizes=(16, 16, 16))
        stages = split_program(prog, ["x", "y"], n_stages=2)
        st = stages.stages[0]
        feedish = (st.feeds + [n for n, _, _ in st.fwd_inputs]
                   + [n for n, _, _ in st.bwd_inputs] + st.bwd_feeds)
        fetch = ([n for n, _, _ in st.fwd_outputs]
                 + [n for n, _, _ in st.bwd_outputs])
        rep = memory.apply_recompute(st.program, feedish,
                                     fetch_names=fetch, batch_size=8)
        findings = verify_program(st.program, feed_names=feedish,
                                  fetch_names=fetch)
        assert [f for f in findings if f.severity == "error"] == []

    @pytest.mark.slow
    def test_transformer_base_reduction_bar(self):
        """ISSUE 15 acceptance: >= 40% estimated activation-peak
        reduction at <= 1.35x estimated FLOPs on transformer-base widths
        (IR-only — no compile)."""
        from paddle_tpu.models import transformer as T

        prog, start = pt.Program(), pt.Program()
        with pt.program_guard(prog, start), fw.guard_unique_name():
            avg, _, feeds = T.transformer(
                src_vocab_size=2048, trg_vocab_size=2048, max_length=64,
                n_layer=6, n_head=8, d_key=64, d_value=64, d_model=512,
                d_inner_hid=2048, dropout_rate=0.1, src_seq_len=64,
                trg_seq_len=64, use_flash=False)
            pt.optimizer.Adam(learning_rate=1e-4).minimize(avg)
        rep = memory.apply_recompute(prog, feeds, fetch_names=[avg.name],
                                     batch_size=8)
        reduction = 1.0 - (rep["activation_peak_after"]
                           / rep["activation_peak_before"])
        assert reduction >= 0.40, reduction
        assert rep["flops_ratio"] <= 1.35, rep["flops_ratio"]

    def test_rejects_control_flow_and_forward_only(self):
        prog = pt.Program()
        blk = prog.global_block()
        blk.create_var(name="a", shape=[4], dtype="float32", is_data=True)
        blk.create_var(name="b", shape=[4], dtype="float32")
        blk.append_op("square", {"X": ["a"]}, {"Out": ["b"]})
        with pytest.raises(memory.RecomputeError, match="no Backward"):
            memory.apply_recompute(prog, ["a"], fetch_names=["b"])
        sub = prog._create_block()
        prog.current_block_idx = 0
        blk.append_op("while", {"X": ["b"]}, {"Out": ["b"]},
                      attrs={"sub_block": sub})
        with pytest.raises(memory.RecomputeError, match="sub-block"):
            memory.apply_recompute(prog, ["a"], fetch_names=["b"])

    def test_unknown_checkpoint_raises(self):
        prog, _, loss = _mlp()
        with pytest.raises(memory.RecomputeError, match="nope"):
            memory.apply_recompute(prog, ["x", "y"], checkpoints=["nope"],
                                   fetch_names=[loss.name])


# ---------------------------------------------------------------------------
# offload pass
# ---------------------------------------------------------------------------


def _fabricate_gap_program():
    """A = square(feed) [4096 B, read only by the trailing Backward-role
    op] rides across a gap whose middle op is the watermark (B and C are
    16 KB each, so the gap dominates both before AND after the rewrite);
    offloading A must subtract its 4096 bytes from the peak exactly."""
    prog = pt.Program()
    blk = prog.global_block()
    blk.create_var(name="f", shape=[8, 8], dtype="float32", is_data=True)
    blk.create_var(name="A", shape=[32, 32], dtype="float32")   # 4096 B
    blk.create_var(name="B", shape=[64, 64], dtype="float32")   # 16384 B
    blk.create_var(name="C", shape=[64, 64], dtype="float32")
    blk.create_var(name="D", shape=[8, 8], dtype="float32")
    # fabricated op types: no registered infer, so the declared shapes
    # above stay authoritative (the planner is registry-independent)
    blk.append_op("fab_stash_op", {"X": ["f"]}, {"Out": ["A"]})
    blk.append_op("fab_gap_op", {"X": ["f"]}, {"Out": ["B"]})
    blk.append_op("fab_gap_op", {"X": ["B"]}, {"Out": ["C"]})
    blk.append_op("fab_gap_op", {"X": ["A"]}, {"Out": ["D"]},
                  attrs={fw.OpRole.ROLE_ATTR_NAME: fw.OpRole.Backward})
    return prog


class TestOffload:
    def test_exact_watermark_subtraction(self):
        prog = _fabricate_gap_program()
        before = memory.plan_program(prog, ["f"], ["C", "D"])
        # watermark: op2 holds A(4096) + B(16384) + C(16384); the feed
        # died after op1
        assert before.peak_bytes == 4096 + 16384 + 16384
        rep = memory.apply_offload(prog, ["f"], offload_vars=["A"],
                                   fetch_names=["C", "D"])
        assert rep["offloaded"] == ["A"]
        assert rep["offloaded_bytes"] == 4096
        # A is parked in host memory across the gap: the device
        # watermark subtracts exactly its bytes
        assert rep["peak_after"] == before.peak_bytes - 4096
        after = rep["plan_after"]
        assert after.lifetimes["A@HOST"].klass == "host"
        assert after.offloaded_bytes == 4096

    def test_value_parity_and_planner_peak(self):
        prog, start, loss = _mlp(dropout=0.0, sizes=(32, 32))
        prog2 = prog.clone()
        plan = memory.plan_program(prog2, ["x", "y"], [loss.name],
                                   batch_size=32)
        cands = memory.select_offload_vars(plan, min_bytes=1,
                                           min_gap_frac=0.1)
        assert cands
        rep = memory.apply_offload(prog2, ["x", "y"], offload_vars=cands,
                                   fetch_names=[loss.name], batch_size=32)
        assert rep["offloaded_bytes"] > 0
        assert rep["peak_after"] < rep["peak_before"]
        findings = verify_program(prog2, feed_names=["x", "y"],
                                  fetch_names=[loss.name],
                                  check_dead=True)
        assert findings == [], [str(f) for f in findings]
        rng = np.random.RandomState(0)
        feed = {"x": rng.randn(32, 8).astype("float32"),
                "y": rng.randn(32, 1).astype("float32")}
        la, lb, pa, pb = _run_pair(prog, prog2, start, loss.name, feed)
        for a, b in zip(la, lb):
            assert np.array_equal(a, b)  # identity memcpys: exact
        for n in pa:
            np.testing.assert_array_equal(pa[n], pb[n])

    def test_flag_gated_entry_point(self):
        prog, start, loss = _mlp(dropout=0.3, sizes=(32,))
        FLAGS.offload_activations = True
        FLAGS.recompute = "auto"
        try:
            rep = memory.maybe_optimize_memory(prog, ["x", "y"],
                                               [loss.name])
        finally:
            FLAGS.reset("offload_activations")
            FLAGS.reset("recompute")
        assert rep is not None
        assert rep["recompute"]["cloned_ops"] >= 0
        assert "offload" in rep
        # the combined rewrite still runs
        scope, exe = pt.Scope(), pt.Executor()
        exe.run(start, scope=scope)
        out = exe.run(prog, feed={"x": np.ones((8, 8), np.float32),
                                  "y": np.ones((8, 1), np.float32)},
                      fetch_list=[loss.name], scope=scope)
        assert np.isfinite(np.asarray(out[0])).all()


def test_trace_report_renders_memory_section():
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "trace_report", os.path.join(os.path.dirname(__file__), "..",
                                     "tools", "trace_report.py"))
    tr = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(tr)
    doc = {"traceEvents": [], "flight": {"header": {}, "events": [
        {"kind": "memory.plan", "name": "bench", "peak_bytes": 12e6,
         "peak_op_index": 42, "peak_op_type": "mul_grad",
         "activation_peak_bytes": 6e6, "offloaded_bytes": 1e6,
         "peak_by_class": {"params": 2e6, "opt_state": 3e6,
                           "activations": 6e6, "workspace": 1e6,
                           "feeds": 0},
         "warnings": 0},
    ]}}
    text = tr.report(doc)
    assert "Memory (planner table" in text
    assert "mul_grad" in text
    assert "activations 6.00 MB" in text


# ---------------------------------------------------------------------------
# telemetry
# ---------------------------------------------------------------------------


class TestTelemetry:
    def test_publish_plan_zero_cost_off(self):
        import paddle_tpu.monitor as monitor
        from paddle_tpu.monitor import flight

        prog, _, loss = _mlp(dropout=0.0, sizes=(16,))
        plan = memory.plan_program(prog, ["x", "y"], [loss.name],
                                   batch_size=8)
        # force the flag OFF for the zero-cost probe (another test in
        # the session may have flipped the process-global default)
        prev = FLAGS.monitor
        FLAGS.monitor = False
        try:
            before = monitor.default_registry().get(
                "memory.activation_peak_bytes")
            val_before = before.value if before is not None else None
            n_ev = len([e for e in flight.default_recorder().events()
                        if e.get("kind") == "memory.plan"])
            memory.publish_plan(plan)  # one enabled() read, no writes
            after = monitor.default_registry().get(
                "memory.activation_peak_bytes")
            assert (after.value if after is not None else None) \
                == val_before
            assert len([e for e in flight.default_recorder().events()
                        if e.get("kind") == "memory.plan"]) == n_ev
        finally:
            FLAGS.monitor = prev

    def test_publish_plan_gauges_and_flight(self):
        import paddle_tpu.monitor as monitor
        from paddle_tpu.monitor import flight

        prog, _, loss = _mlp(dropout=0.0, sizes=(16,))
        plan = memory.plan_program(prog, ["x", "y"], [loss.name],
                                   batch_size=8)
        prev = FLAGS.monitor
        FLAGS.monitor = True
        try:
            memory.publish_plan(plan, name="test")
            g = monitor.gauge("memory.activation_peak_bytes")
            assert g.value == plan.activation_peak_bytes
            evs = [e for e in flight.default_recorder().events()
                   if e.get("kind") == "memory.plan"
                   and e.get("name") == "test"]
            assert evs
            assert evs[-1]["peak_bytes"] == plan.peak_bytes
        finally:
            FLAGS.monitor = prev
