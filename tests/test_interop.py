"""DLPack interop (VERDICT missing #4): zero-copy exchange with torch.

The contract under test is not "values survive a round trip" (numpy does
that) — it is that NO copy happens: producer and consumer see the same
buffer, asserted by pointer equality on the CPU mesh."""

import numpy as np
import pytest

import paddle_tpu as pt


def test_torch_roundtrip_zero_copy():
    torch = pytest.importorskip("torch")
    import jax.numpy as jnp

    # torch -> paddle_tpu: same buffer
    t = torch.arange(12, dtype=torch.float32).reshape(3, 4)
    x = pt.from_dlpack(t)
    np.testing.assert_array_equal(np.asarray(x), t.numpy())
    assert x.unsafe_buffer_pointer() == t.data_ptr()

    # paddle_tpu -> torch: same buffer
    y = jnp.asarray(np.random.RandomState(0).randn(4, 5).astype("float32"))
    t2 = torch.from_dlpack(pt.to_dlpack(y))
    np.testing.assert_array_equal(t2.numpy(), np.asarray(y))
    assert t2.data_ptr() == y.unsafe_buffer_pointer()

    # full round trip preserves values and dtype
    t3 = torch.from_dlpack(pt.to_dlpack(pt.from_dlpack(t)))
    assert t3.dtype == t.dtype
    np.testing.assert_array_equal(t3.numpy(), t.numpy())


def test_scope_var_exports_to_torch():
    """The practical path: a trained parameter leaves the scope for a
    torch-side eval harness without a host round-trip."""
    torch = pytest.importorskip("torch")
    from paddle_tpu import layers

    prog, startup = pt.Program(), pt.Program()
    with pt.program_guard(prog, startup):
        x = layers.data(name="x", shape=[4], dtype="float32")
        y = layers.fc(x, size=3)  # creates a persistable weight
    exe = pt.Executor(pt.CPUPlace())
    scope = pt.Scope()
    with pt.scope_guard(scope):
        exe.run(startup, scope=scope)
        exe.run(prog, feed={"x": np.ones((2, 4), "float32")},
                fetch_list=[y], scope=scope)
    w_name = [n for n in scope.local_var_names() if "w" in n][0]
    w = scope.find_var(w_name)
    tw = torch.from_dlpack(pt.to_dlpack(w))
    assert tw.shape == tuple(np.asarray(w).shape)
    np.testing.assert_array_equal(tw.numpy(), np.asarray(w))


def test_from_dlpack_accepts_numpy():
    """numpy arrays speak __dlpack__ too; importing one must work (the
    cheapest producer in every test harness)."""
    a = np.arange(6, dtype="float32").reshape(2, 3)
    x = pt.from_dlpack(a)
    np.testing.assert_array_equal(np.asarray(x), a)
