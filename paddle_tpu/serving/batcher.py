"""DynamicBatcher: per-model request queue drained by a scheduler thread
that coalesces concurrent requests into pad-to-bucket batch shapes.

The serving tier's core loop (continuous/dynamic batching — Orca OSDI'22,
Clipper NSDI'17 adaptive batching — mapped onto the executor's
per-feed-signature compile cache):

  * callers (HTTP handler threads) `submit()` a feed and block on an
    event; the scheduler thread takes the oldest request and keeps
    collecting compatible ones (same item signature + precision) until
    the batch is full or the first request's max-wait deadline passes;
  * the coalesced rows are padded UP to the model's bucket ladder, so
    every executed batch hits a warm compiled signature (pad rows repeat
    the last row and are sliced off the outputs);
  * incompatible requests spill to the front of the queue for the next
    round — one ragged stream never head-of-line-blocks another shape.

Policy knobs (per model, flag defaults): bucket ladder, max_batch rows,
max_wait deadline.  Observability: queue-latency + batch-fill histograms,
per-model in-flight gauge and request/row counters, all in the PR-1
registry.

Overload hardening (the robustness tier):

  * admission control — the queue is BOUNDED (FLAGS_serving_max_queue_depth);
    at the bound `submit()` fails fast with `Overloaded` (HTTP 429) carrying
    a Retry-After derived from the observed queue-latency EWMA, instead of
    letting queue latency grow without bound until every request times out;
  * deadline propagation — each request carries `deadline` (its client
    timeout_s); the scheduler drops already-expired requests BEFORE forming
    a batch (`expired_dropped_total`, never dispatched to the executor), so
    an overloaded device never burns time computing answers nobody waits for;
  * circuit breaker — FLAGS_serving_breaker_threshold consecutive batch
    failures open the per-model breaker: submits fail fast with
    `Unavailable` (HTTP 503) until a half-open probe succeeds;
  * graceful drain — `drain()` stops admission and waits for queued-admitted
    work; `stop()` fails whatever is still queued with a NAMED 503
    (`Unavailable`) even when the scheduler thread already died;
  * scheduler hardening — an exception escaping the batch-forming path
    fails that group and keeps the loop alive (counted
    `scheduler_restarts`, fatal flight event); `scheduler_alive` feeds the
    /health `scheduler_dead` probe for the truly unrecoverable case.
"""

from __future__ import annotations

import collections
import math
import queue
import threading
import time
from typing import Dict, Optional

import numpy as np

from ..flags import FLAGS
from ..monitor import tracing
from .model import ServingModel, item_signature

# batch-fill is a fraction of the executed bucket: fixed 0..1 ladder
FILL_BUCKETS = (0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0)

_STOP = object()


class _ServingRejection(RuntimeError):
    """Base of the fail-fast rejections: carries the machine-readable
    `reason` and the Retry-After contract (`retry_after_s` float +
    the integer-delta-seconds HTTP header form)."""

    def __init__(self, message: str,
                 retry_after_s: Optional[float] = None,
                 reason: str = "rejected"):
        super().__init__(message)
        self.retry_after_s = retry_after_s
        self.reason = reason

    @property
    def retry_after_header(self) -> Optional[str]:
        """HTTP Retry-After is integer delta-seconds; the JSON body
        carries the sub-second `retry_after_s` for latency-sensitive
        clients (tools/loadgen.py honors the body value).  None when no
        hint applies."""
        if not self.retry_after_s:
            return None
        return str(max(1, int(math.ceil(self.retry_after_s))))


class Overloaded(_ServingRejection):
    """Admission control rejected the request — HTTP 429 with a
    Retry-After.  `retry_after_s` is derived from the shedding batcher's
    observed queue-latency EWMA (how long a retry would realistically
    wait right now); `reason` names the saturated resource
    (queue_depth / inflight_cap / gen_queue_depth)."""

    def __init__(self, message: str, retry_after_s: float = 1.0,
                 reason: str = "overloaded"):
        super().__init__(message, retry_after_s=float(retry_after_s),
                         reason=reason)


class Unavailable(_ServingRejection):
    """Named fail-fast rejection — HTTP 503: the server is draining, the
    batcher stopped, or the model's circuit breaker is open.  Unlike a
    crash-500, a 503 tells load balancers/clients the condition is
    intentional and retryable elsewhere/later."""

    def __init__(self, message: str, retry_after_s: Optional[float] = None,
                 reason: str = "unavailable"):
        super().__init__(message, retry_after_s=retry_after_s,
                         reason=reason)


def _record_shed(counter_name: str, reason: str, retry_after_s: float,
                 **flight_fields) -> None:
    """Shared shed telemetry (dynamic batcher / generation wait-queue /
    server in-flight cap): the named counter + the aggregate
    serving.shed_total + one serving.shed flight event, all no-ops with
    FLAGS.monitor off."""
    from .. import monitor
    from ..monitor import flight

    if monitor.enabled():
        monitor.counter(counter_name).inc()
        monitor.counter("serving.shed_total").inc()
    flight.record("serving.shed", reason=reason,
                  retry_after_s=round(retry_after_s, 4), **flight_fields)


def _slo_bad(model_name: str) -> None:
    """One bad SLO event for a model (shed / timeout / expiry / error) —
    shared by both batcher kinds; no-op unless FLAGS_serving_slo_ms names
    the model.  Counted exactly ONCE per request, always on the path
    that delivers the failure to the caller (the submit waiter, or the
    admission check that raises) — scheduler-side failure paths set
    `req.error` and let the waiter count, so a request that both times
    out client-side and later expires scheduler-side is one bad event,
    not two."""
    from .. import monitor

    if monitor.enabled():
        tracing.slo_observe(model_name, 0.0, ok=False)


def _fail_waiters(q: "queue.Queue", pending, message: str) -> None:
    """Fail every request still in `pending` (a deque) or `q` with the
    NAMED 503 and set their events — the shared stop()/scheduler-death
    drain of both batcher kinds (no waiter ever rides out its full
    client timeout against a stopped scheduler)."""
    leftovers = list(pending)
    pending.clear()
    while True:
        try:
            r = q.get_nowait()
        except queue.Empty:
            break
        if r is not _STOP:
            leftovers.append(r)
    for r in leftovers:
        r.error = Unavailable(message, reason="stopped")
        tracing.reject(getattr(r, "trace", None), "stopped")
        r.event.set()


class CircuitBreaker:
    """Per-model executor-failure breaker: CLOSED until
    FLAGS_serving_breaker_threshold CONSECUTIVE batch executions fail,
    then OPEN (allow() is False — submits fail fast with 503 instead of
    queueing against a broken executor) for
    FLAGS_serving_breaker_cooldown_s, then HALF-OPEN: exactly ONE probe
    request is admitted; its success closes the breaker, its failure
    re-opens it.  Threshold 0 disables — allow() is always True and the
    only cost is one flag read.  The `serving.<name>.breaker_state`
    gauge (0 closed / 1 open / 2 half-open) tracks transitions while
    FLAGS.monitor is on."""

    CLOSED, OPEN, HALF_OPEN = 0, 1, 2

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._probing = False
        self._probe_started = 0.0

    @property
    def state(self) -> int:
        return self._state

    def _transition(self, state: int) -> None:
        from .. import monitor

        self._state = state
        if monitor.enabled():
            monitor.gauge(f"serving.{self.name}.breaker_state").set(state)

    def allow(self) -> bool:
        if FLAGS.serving_breaker_threshold <= 0:
            return True
        with self._lock:
            if self._state == self.CLOSED:
                return True
            now = time.monotonic()
            if self._state == self.OPEN:
                if (now - self._opened_at
                        < FLAGS.serving_breaker_cooldown_s):
                    return False
                self._transition(self.HALF_OPEN)
                self._probing = False
            # HALF_OPEN: admit one in-flight probe at a time.  The slot
            # RECLAIMS after a cooldown: a probe that never reached the
            # executor (shed by admission, dropped expired, killed by a
            # batch-forming crash) must not wedge the breaker half-open
            # forever — the next caller becomes the probe instead.
            if (self._probing
                    and now - self._probe_started
                    < FLAGS.serving_breaker_cooldown_s):
                return False
            self._probing = True
            self._probe_started = now
            return True

    def record_success(self) -> None:
        if FLAGS.serving_breaker_threshold <= 0:
            return
        with self._lock:
            self._failures = 0
            self._probing = False
            if self._state != self.CLOSED:
                self._transition(self.CLOSED)

    def record_failure(self) -> None:
        threshold = FLAGS.serving_breaker_threshold
        if threshold <= 0:
            return
        with self._lock:
            self._failures += 1
            probe_failed = self._probing and self._state == self.HALF_OPEN
            self._probing = False
            if probe_failed or self._failures >= threshold:
                self._opened_at = time.monotonic()
                if self._state != self.OPEN:
                    from ..monitor import flight

                    flight.record("serving.breaker_open", model=self.name,
                                  consecutive_failures=self._failures)
                    self._transition(self.OPEN)


class _Request:
    __slots__ = ("feed", "rows", "sig", "precision", "t_enqueue",
                 "deadline", "event", "outputs", "meta", "error",
                 "trace", "t_exec_end")

    def __init__(self, feed, rows, sig, precision, timeout=None,
                 trace=None):
        self.feed = feed
        self.rows = rows
        self.sig = sig
        self.precision = precision
        self.t_enqueue = time.perf_counter()
        # the client abandons the wait at t_enqueue + timeout; past that
        # point executing the request only burns device time under the
        # very overload that made it late — the scheduler drops it
        self.deadline = (None if timeout is None
                         else self.t_enqueue + float(timeout))
        self.event = threading.Event()
        self.outputs = None
        self.meta = None
        self.error = None
        # request-scoped trace (monitor/tracing.py): None unless
        # FLAGS_trace_requests — the trace id rides the queued request
        # through the scheduler so queue/form/exec/debatch spans attach
        self.trace = trace
        self.t_exec_end = None  # scheduler exec-done stamp (trace only)


class DynamicBatcher:
    """One scheduler thread + queue per served model."""

    def __init__(self, model: ServingModel,
                 max_batch: Optional[int] = None,
                 max_wait_ms: Optional[float] = None):
        self.model = model
        mb = max_batch if max_batch is not None else model.config.max_batch
        # never coalesce past the ladder: a batch bigger than the largest
        # bucket cannot pad DOWN and would compile a fresh signature
        self.max_batch = max(1, min(int(mb), model.buckets[-1]))
        wait = (max_wait_ms if max_wait_ms is not None
                else model.config.max_wait_ms)
        self.max_wait_s = max(0.0, float(wait) / 1000.0)
        self._queue: "queue.Queue" = queue.Queue()
        self._spill: "collections.deque" = collections.deque()
        self._thread: Optional[threading.Thread] = None
        self._running = False
        self._draining = False
        # scheduler-thread-written, submit-side-read (GIL-atomic floats):
        # the queue-latency EWMA behind Retry-After, and the busy flag
        # drain() polls alongside the queue
        self._queue_ewma_s = 0.0
        self._busy = False
        self.breaker = CircuitBreaker(model.name)

    # -- lifecycle -------------------------------------------------------
    def start(self) -> None:
        if self._running:
            return
        self._running = True
        self._draining = False
        self._thread = threading.Thread(
            target=self._loop, name=f"serving-batcher-{self.model.name}",
            daemon=True)
        self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        if self._running:
            self._running = False
            self._queue.put(_STOP)
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None
        # belt and braces: the scheduler's own finally-drain covers the
        # normal path, but a dead scheduler (or one that never started)
        # leaves queued waiters riding out their full client timeout —
        # fail them NOW with the named 503
        self._fail_pending()

    def begin_drain(self) -> None:
        """Stop admitting: every subsequent submit gets Unavailable
        (HTTP 503).  Queued-admitted and in-flight work still runs."""
        self._draining = True

    def drain(self, timeout: float) -> bool:
        """begin_drain(), then wait (bounded by `timeout` seconds) until
        the queue, spill and in-flight batch are all empty; returns True
        when fully drained inside the budget."""
        self.begin_drain()
        t_end = time.monotonic() + max(0.0, timeout)
        while True:
            idle = self._idle()
            if idle:
                time.sleep(0.02)  # re-confirm across the pop hand-off
                idle = self._idle()
            if idle or time.monotonic() >= t_end:
                return idle
            time.sleep(0.02)

    def _idle(self) -> bool:
        """Nothing queued, spilled, or popped-but-unexecuted.  drain()
        samples this TWICE (the pop->_busy hand-off in _take is two
        instructions wide) before trusting it."""
        return (self._queue.qsize() == 0 and not self._spill
                and not self._busy)

    @property
    def scheduler_alive(self) -> bool:
        """False only when the batcher SHOULD be running but its
        scheduler thread died (a BaseException escaped the hardened
        loop) — the /health `scheduler_dead` probe."""
        if not self._running:
            return True
        return self._thread is not None and self._thread.is_alive()

    def _fail_pending(self) -> None:
        """Fail everything still queued/spilled with the named 503
        (satellite: stop-with-queued-requests)."""
        _fail_waiters(self._queue, self._spill,
                      f"serving batcher for {self.model.name!r} stopped")

    # -- client side -----------------------------------------------------
    def submit(self, feed: Dict[str, np.ndarray],
               precision: str = "fp32", timeout: float = 30.0,
               trace=None):
        """Block until the batch containing this request executes; returns
        (outputs list parallel to fetch_names, batch meta dict).  `trace`
        is the request's RequestTrace (or None, the no-tracing fast
        path): the batcher attaches the queue/form/exec/debatch spans and
        closes the trace on rejection."""
        from .. import monitor

        if trace is not None:
            t_submit0 = time.perf_counter()
        self.model.predictor(precision)  # validate precision early
        missing = [n for n in self.model.feed_names if n not in feed]
        if missing:
            raise KeyError(
                f"model {self.model.name!r}: missing feeds {missing}")
        feed = {n: np.asarray(feed[n]) for n in self.model.feed_names}
        scalars = [n for n, a in feed.items() if not np.asarray(a).ndim]
        if scalars:
            # 0-d arrays carry no batch dim: item_signature (shape[1:])
            # would coalesce them with 1-d requests and the concatenate/
            # pad path would crash the whole batch
            raise ValueError(
                f"model {self.model.name!r}: feeds {scalars} are 0-d — "
                "serving feeds need a leading batch dim (send [[v]], "
                "not v)")
        rows = {int(a.shape[0]) for a in feed.values()}
        if len(rows) != 1:
            raise ValueError(
                f"model {self.model.name!r}: feed arrays disagree on the "
                f"leading batch dim ({sorted(rows)})")
        (n_rows,) = rows
        if n_rows == 0:
            raise ValueError("empty batch (0 rows)")
        # -- admission control (after validation: a malformed request is
        # a 4xx, not a shed) ---------------------------------------------
        if self._draining:
            _slo_bad(self.model.name)
            tracing.reject(trace, "draining")
            raise Unavailable(
                f"model {self.model.name!r} is draining", reason="draining")
        # queue depth BEFORE the breaker: a shed must not consume the
        # breaker's half-open probe slot (the probe should only be
        # admitted when it can actually reach the executor)
        depth = FLAGS.serving_max_queue_depth
        if depth > 0 and self._queue.qsize() + len(self._spill) >= depth:
            self._shed("queue_depth",
                       f"model {self.model.name!r}: request queue full "
                       f"({depth} queued)", trace=trace)
        if not self.breaker.allow():
            if monitor.enabled():
                monitor.counter(
                    f"serving.{self.model.name}.breaker_rejected_total"
                ).inc()
            _slo_bad(self.model.name)
            tracing.reject(trace, "breaker_open")
            raise Unavailable(
                f"model {self.model.name!r}: circuit breaker open "
                f"({FLAGS.serving_breaker_threshold} consecutive executor "
                "failures; half-open probe pending)",
                retry_after_s=FLAGS.serving_breaker_cooldown_s,
                reason="breaker_open")
        req = _Request(feed, n_rows, item_signature(feed), precision,
                       timeout=timeout, trace=trace)
        if trace is not None:
            # the admitted decision as a span: validation + admission
            # checks, ending where the queue wait begins
            trace.add_span("admission", tracing.pc_to_epoch(t_submit0),
                           tracing.pc_to_epoch(req.t_enqueue),
                           outcome="admitted", rows=n_rows)

        mon = monitor.enabled()
        inflight = (monitor.gauge(f"serving.{self.model.name}.inflight")
                    if mon else None)
        t0 = time.perf_counter()
        if inflight is not None:
            inflight.inc()
        try:
            self._queue.put(req)
            if not req.event.wait(timeout):
                req.error = TimeoutError(
                    f"request not served within {timeout}s "
                    f"(model {self.model.name!r})")
                if mon:
                    monitor.counter(
                        f"serving.{self.model.name}.timeouts").inc()
                    _slo_bad(self.model.name)
                if trace is not None:
                    trace.finish(status="timeout")
                raise req.error
        finally:
            if inflight is not None:
                inflight.dec()
        if req.error is not None:
            if mon:
                monitor.counter(
                    f"serving.{self.model.name}.request_errors").inc()
                _slo_bad(self.model.name)
            raise req.error
        if trace is not None and req.t_exec_end is not None:
            # de-batch + hand-off back to this thread: exec done (the
            # scheduler's stamp) -> the waiter waking here.  Measured on
            # the WAITER side so the thread-wakeup gap is attributed, not
            # unaccounted.
            trace.add_span("debatch", tracing.pc_to_epoch(req.t_exec_end),
                           tracing.pc_to_epoch(time.perf_counter()),
                           rows=req.rows)
        if mon:
            dt = time.perf_counter() - t0
            monitor.counter(f"serving.{self.model.name}.requests").inc()
            monitor.counter("serving.requests").inc()
            monitor.counter(f"serving.{self.model.name}.rows").inc(n_rows)
            monitor.histogram(
                f"serving.{self.model.name}.request_seconds").observe(dt)
            monitor.histogram("serving.request_seconds").observe(dt)
            tracing.slo_observe(self.model.name, dt, ok=True)
        return req.outputs, req.meta

    def retry_after(self) -> float:
        """Suggested client back-off for a shed: ~2x the observed
        queue-latency EWMA (what a retry would realistically wait right
        now), floored at the batch max-wait, capped at 30s."""
        return min(30.0, max(self.max_wait_s, 2.0 * self._queue_ewma_s,
                             0.05))

    def _shed(self, reason: str, message: str, trace=None) -> None:
        """Count + flight-tag one shed admission, then raise Overloaded
        (HTTP 429 + Retry-After)."""
        ra = self.retry_after()
        _record_shed(f"serving.{self.model.name}.shed_total", reason, ra,
                     model=self.model.name)
        _slo_bad(self.model.name)
        tracing.reject(trace, reason)
        raise Overloaded(message, retry_after_s=ra, reason=reason)

    # -- scheduler side --------------------------------------------------
    def _take(self, timeout: float):
        """Next pending request: spilled (incompatible last round) first,
        then the shared queue.  timeout <= 0 means poll (non-blocking).
        A popped request flips `_busy` IMMEDIATELY — it is out of the
        queue but not yet executed, and drain()'s idle check must not
        mistake that hand-off window for 'fully drained'."""
        if self._spill:
            self._busy = True
            return self._spill.popleft()
        try:
            if timeout <= 0:
                r = self._queue.get_nowait()
            else:
                r = self._queue.get(timeout=timeout)
        except queue.Empty:
            return None
        if r is not _STOP:
            self._busy = True
        return r

    def _take_live(self, timeout: float):
        """_take, dropping requests whose deadline already passed — they
        are counted (`expired_dropped_total`) and NEVER dispatched: under
        the overload that made them late, executing them would spend
        device time on answers nobody is waiting for."""
        t_end = (time.perf_counter() + timeout) if timeout > 0 else None
        while True:
            r = self._take(timeout)
            if (r is None or r is _STOP
                    or r.deadline is None
                    or time.perf_counter() < r.deadline):
                return r
            self._drop_expired(r)
            if t_end is not None:
                # the block budget is a deadline, not per-attempt: after
                # draining an expired request, only the remainder blocks
                timeout = max(0.0, t_end - time.perf_counter())

    def _drop_expired(self, r) -> None:
        from .. import monitor
        from ..monitor import flight

        r.error = TimeoutError(
            f"request expired before dispatch (deadline passed while "
            f"queued; model {self.model.name!r})")
        if r.trace is not None:
            r.trace.add_span("queue.wait",
                             tracing.pc_to_epoch(r.t_enqueue),
                             tracing.pc_to_epoch(time.perf_counter()))
            r.trace.finish(status="expired")
        r.event.set()
        if monitor.enabled():
            # no SLO count here: the waiter sees req.error and counts
            # the bad event once (or already counted its own timeout)
            monitor.counter(
                f"serving.{self.model.name}.expired_dropped_total").inc()
            monitor.counter("serving.expired_dropped_total").inc()
        flight.record("serving.expired_dropped", model=self.model.name,
                      queued_s=round(time.perf_counter() - r.t_enqueue, 4))

    def _collect(self, first, group) -> int:
        """Coalesce compatible pending requests behind `first` up to
        max_batch / the first request's max-wait deadline; returns total
        rows.  Incompatible requests spill to the next round."""
        rows = first.rows
        # the max-wait deadline bounds a request's QUEUE time; under
        # saturation it is often already past when the scheduler gets
        # here (the request aged while the previous batch executed) —
        # so pending requests always drain for free (poll), and the
        # scheduler only BLOCKS for stragglers while under deadline
        # with an unfilled batch
        deadline = first.t_enqueue + self.max_wait_s
        defer = []
        while rows < self.max_batch:
            nxt = self._take_live(0.0)
            if nxt is None:
                rem = deadline - time.perf_counter()
                if rem <= 0:
                    break
                nxt = self._take_live(rem)
                if nxt is None:
                    break
            if nxt is _STOP:
                self._running = False
                break
            if (nxt.precision == first.precision
                    and nxt.sig == first.sig
                    and rows + nxt.rows <= self.max_batch):
                group.append(nxt)
                rows += nxt.rows
            else:
                defer.append(nxt)
        # deferred requests lead the next round, in arrival order
        self._spill.extendleft(reversed(defer))
        return rows

    def _loop(self) -> None:
        try:
            while self._running:
                first = self._take_live(0.1)
                if first is None or first is _STOP:
                    # an expired-drop round may have flipped _busy: the
                    # dropped request completed (error set), nothing is
                    # pending execution
                    self._busy = False
                    if first is _STOP:
                        break
                    continue
                group = [first]
                t_pickup = time.perf_counter()
                try:
                    rows = self._collect(first, group)
                    self._execute(group, rows, t_pickup)
                except Exception as e:  # noqa: BLE001 — a scheduler
                    # crash would strand every current AND future
                    # caller behind a healthy-looking server: fail this
                    # round's requests, record the fatal event, keep
                    # the loop alive
                    for r in group:
                        r.error = e
                        r.event.set()
                    self._note_scheduler_error(e)
                finally:
                    self._busy = False
        finally:
            # fail whatever is still queued so no caller hangs — in a
            # finally so even a BaseException escape drains its callers
            self._fail_pending()

    def _note_scheduler_error(self, exc: Exception) -> None:
        from .. import monitor
        from ..monitor import flight

        flight.record("serving.scheduler_error", model=self.model.name,
                      fatal=True,
                      error=f"{type(exc).__name__}: {exc}")
        if monitor.enabled():
            monitor.counter(
                f"serving.{self.model.name}.scheduler_restarts").inc()

    def _execute(self, group, rows: int,
                 t_pickup: Optional[float] = None) -> None:
        from .. import monitor

        model = self.model
        mon = monitor.enabled()
        t_start = time.perf_counter()
        if t_pickup is None:
            t_pickup = t_start
        # queue-latency EWMA (scheduler-thread-only write): the basis of
        # the Retry-After a shed response suggests
        self._queue_ewma_s += 0.2 * (
            max(t_start - r.t_enqueue for r in group) - self._queue_ewma_s)
        if mon:
            qh = monitor.histogram(
                f"serving.{model.name}.queue_seconds")
            for r in group:
                qh.observe(t_start - r.t_enqueue)
        bucket = model.bucket_for(rows)
        if bucket is None:
            # oversize: runs at its exact shape (fresh signature) — named
            # counter + the run_batch flight tag make the ladder gap loud
            bucket = rows
            if mon:
                monitor.counter(
                    f"serving.{model.name}.oversize_batches").inc()
        traces = [r.trace for r in group if r.trace is not None]
        if traces:
            # queue.wait per request: enqueue -> the scheduler picking up
            # this batch (late joiners clamp to zero — the batch formed
            # around them while they arrived)
            e_pickup = tracing.pc_to_epoch(t_pickup)
            for r in group:
                if r.trace is not None:
                    e_enq = tracing.pc_to_epoch(r.t_enqueue)
                    r.trace.add_span("queue.wait", e_enq,
                                     max(e_enq, e_pickup))
            t_pad0 = time.perf_counter()
        feed = {
            n: (np.concatenate([r.feed[n] for r in group], axis=0)
                if len(group) > 1 else group[0].feed[n])
            for n in model.feed_names
        }
        feed = model.pad_feed(feed, rows, bucket)
        t_exec0 = time.perf_counter()
        if traces:
            # batch.form: pickup -> dispatch (coalescing + concat + pad),
            # the fan-in span parented by every member request; batch.pad
            # attributes the wasted-compute rows the batch-fill histogram
            # cannot pin on a request.  Each member's copy is FLOORED at
            # its own enqueue stamp: a late joiner (arrived mid-collect)
            # must not be handed span time from before it existed, or
            # its components would sum past its own wall clock
            form_sid = tracing.add_shared_span(
                traces, "batch.form", tracing.pc_to_epoch(t_pickup),
                tracing.pc_to_epoch(t_exec0),
                floors=[tracing.pc_to_epoch(r.t_enqueue)
                        for r in group if r.trace is not None],
                rows=rows, bucket=bucket, coalesced=len(group))
            tracing.add_shared_span(
                traces, "batch.pad", tracing.pc_to_epoch(t_pad0),
                tracing.pc_to_epoch(t_exec0), parent_id=form_sid,
                fan_in_attrs=False, rows_real=rows,
                rows_padded=bucket - rows, bucket=bucket,
                fill=round(rows / bucket, 4))
        try:
            if traces:
                with tracing.executor_context(traces):
                    outs = model.run_batch(group[0].precision, feed, rows,
                                           bucket, group[0].sig)
            else:
                outs = model.run_batch(group[0].precision, feed, rows,
                                       bucket, group[0].sig)
        except Exception as e:  # noqa: BLE001 — fail the batch, not the loop
            self.breaker.record_failure()
            for r in group:
                r.error = e
                if r.trace is not None:
                    r.trace.finish(status="error:batch")
                r.event.set()
            if mon:
                # SLO bad events land waiter-side (each member's submit
                # sees req.error) — counting here too would double them
                monitor.counter(f"serving.{model.name}.batch_errors").inc()
            return
        self.breaker.record_success()
        if traces:
            t_exec1 = time.perf_counter()
            # the executor-run fan-in span: ONE batch execution parented
            # by N request spans (executor.compile/run sub-spans landed
            # via the executor_context hook)
            tracing.add_shared_span(
                traces, "batch.exec", tracing.pc_to_epoch(t_exec0),
                tracing.pc_to_epoch(t_exec1), rows=rows, bucket=bucket,
                precision=group[0].precision)
            for r in group:
                if r.trace is not None:
                    r.t_exec_end = t_exec1
        if mon:
            monitor.counter(f"serving.{model.name}.batches").inc()
            monitor.counter(f"serving.{model.name}.padded_rows").inc(
                bucket - rows)
            monitor.histogram(f"serving.{model.name}.batch_fill",
                              buckets=FILL_BUCKETS).observe(rows / bucket)
            monitor.histogram("serving.batch_fill",
                              buckets=FILL_BUCKETS).observe(rows / bucket)
        exec_ms = round((time.perf_counter() - t_start) * 1e3, 3)
        batched_flags = model.fetch_batched
        offset = 0
        for r in group:
            sliced = []
            for j, o in enumerate(outs):
                arr = np.asarray(o)
                is_batched = (batched_flags[j]
                              if j < len(batched_flags) else None)
                if is_batched is None:
                    # unknown declared shape: fall back to the shape
                    # heuristic (can't distinguish a fixed leading dim
                    # that happens to equal the bucket)
                    is_batched = bool(arr.ndim) and arr.shape[0] == bucket
                if is_batched and arr.ndim and arr.shape[0] == bucket:
                    sliced.append(arr[offset:offset + r.rows])
                else:
                    # non-batched fetch (reduced scalar / fixed-dim
                    # output): every request gets the whole value
                    sliced.append(arr)
            r.outputs = sliced
            r.meta = {
                "bucket": bucket,
                "batch_rows": rows,
                "request_rows": r.rows,
                "coalesced": len(group),
                "precision": r.precision,
                "queue_ms": round((t_start - r.t_enqueue) * 1e3, 3),
                "exec_ms": exec_ms,
            }
            offset += r.rows
            r.event.set()
