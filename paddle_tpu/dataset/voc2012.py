"""PASCAL VOC2012 segmentation dataset (reference:
python/paddle/dataset/voc2012.py — train/test/val readers yielding
(CHW float image, HW int segmentation label) from the VOCtrainval tar).

Offline fallback: synthetic images with a colored rectangle whose mask is
the label — enough to exercise a segmentation head end to end."""

from __future__ import annotations

import io
import tarfile

import numpy as np

from . import common, image

URL = ("http://host.robots.ox.ac.uk/pascal/VOC/voc2012/"
       "VOCtrainval_11-May-2012.tar")
_SET_DIR = "VOCdevkit/VOC2012/ImageSets/Segmentation/{}.txt"
_IMG = "VOCdevkit/VOC2012/JPEGImages/{}.jpg"
_LBL = "VOCdevkit/VOC2012/SegmentationClass/{}.png"


def _synthetic_reader(seed, n=64, size=64):
    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            cls = int(rng.randint(1, 21))
            im = rng.rand(3, size, size).astype("float32") * 0.2
            lbl = np.zeros((size, size), "int32")
            y0, x0 = rng.randint(4, size // 2, 2)
            h, w = rng.randint(8, size // 2, 2)
            im[cls % 3, y0:y0 + h, x0:x0 + w] += 0.8
            lbl[y0:y0 + h, x0:x0 + w] = cls
            yield im, lbl
    return reader


def _real_reader(sub_name):
    def reader():
        path = common.download(URL, "voc2012", None)
        with tarfile.open(path, "r") as f:
            names = (f.extractfile(_SET_DIR.format(sub_name))
                     .read().decode().split())
            for name in names:
                img = image.load_image_bytes(
                    f.extractfile(_IMG.format(name)).read())
                lbl = image.load_image_bytes(
                    f.extractfile(_LBL.format(name)).read(), is_color=False)
                yield (image.to_chw(img).astype("float32") / 255.0,
                       lbl[:, :, 0].astype("int32"))
    return reader


def train(synthetic=False):
    if common.use_synthetic(synthetic):
        return _synthetic_reader(51)
    return _real_reader("train")


def val(synthetic=False):
    if common.use_synthetic(synthetic):
        return _synthetic_reader(52)
    return _real_reader("val")


def test(synthetic=False):
    if common.use_synthetic(synthetic):
        return _synthetic_reader(53)
    return _real_reader("trainval")
