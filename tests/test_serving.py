"""Serving tier (paddle_tpu/serving): multi-model inference server with
dynamic batching on the AOT-bundle path.

Covers the PR-6 tentpole + satellites: pad-to-bucket dynamic batching
(every executed batch on a warm compiled signature), Predictor/executor
thread-safety under concurrent callers (N threads x M signatures ->
exactly M compiles), serving-tier recompile tagging, int8 replicas via
contrib.quantize.freeze_int8, /health readiness-vs-liveness, the HTTP
endpoint surface, export_aot_bundle -> fresh-process zero-trace serving
(subprocess), corrupted-bundle JIT degradation, and the loadgen harness.
"""

import glob
import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.flags import FLAGS
from paddle_tpu.inference import Predictor, export_aot_bundle
from paddle_tpu.monitor import default_registry, flight
from paddle_tpu.monitor import serve as mserve
from paddle_tpu.serving import (
    DynamicBatcher,
    InferenceServer,
    ModelConfig,
    ServingModel,
    enable_compilation_cache,
    parse_buckets,
)
from paddle_tpu.serving.model import item_signature

rng = np.random.RandomState(7)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    """Default flags + empty registry around every test; never leak the
    serving readiness provider (it would flip other suites' /health)."""
    FLAGS.reset()
    default_registry().reset()
    yield
    mserve.set_readiness_provider(None)
    FLAGS.reset()
    default_registry().reset()


# ---------------------------------------------------------------------------
# model export helpers (explicit programs/scopes: independent of the
# per-test default-program reset, so module-scoped dirs stay valid)
# ---------------------------------------------------------------------------


def _export_fc_model(dirname, in_dim=6, out_dim=3, seed=3):
    """Plain fc inference artifact with randomized (startup-initialized)
    weights; feed "x" declares (-1, in_dim)."""
    prog, startup = pt.Program(), pt.Program()
    prog.random_seed = startup.random_seed = seed
    with pt.program_guard(prog, startup):
        x = layers.data(name="x", shape=[in_dim], dtype="float32")
        h = layers.fc(x, size=8, act="relu")
        out = layers.fc(h, size=out_dim)
    scope = pt.Scope()
    exe = pt.Executor(pt.CPUPlace())
    with pt.scope_guard(scope):
        exe.run(startup, scope=scope)
        pt.io.save_inference_model(dirname, ["x"], [out], exe,
                                   main_program=prog, scope=scope)
    return dirname


def _export_dynamic_model(dirname):
    """Artifact whose feed "x" declares (-1, -1): requests with different
    trailing lengths are DIFFERENT item signatures (spill + ladder-gap
    coverage); warmup cannot synthesize the unknown dim."""
    prog, startup = pt.Program(), pt.Program()
    with pt.program_guard(prog, startup):
        x = layers.data(name="x", shape=[-1], dtype="float32")
        out = layers.reduce_mean(x, dim=-1, keep_dim=True)
    scope = pt.Scope()
    exe = pt.Executor(pt.CPUPlace())
    with pt.scope_guard(scope):
        exe.run(startup, scope=scope)
        pt.io.save_inference_model(dirname, ["x"], [out], exe,
                                   main_program=prog, scope=scope)
    return dirname


def _export_fixed_fetch_model(dirname):
    """Artifact whose only fetch has a FIXED leading dim (reduce over the
    batch axis of (-1, 4) -> shape (4,)): regression bait for the
    de-batching heuristic, since the fixed dim equals a ladder bucket."""
    prog, startup = pt.Program(), pt.Program()
    with pt.program_guard(prog, startup):
        x = layers.data(name="x", shape=[4], dtype="float32")
        out = layers.reduce_mean(x, dim=0)
    scope = pt.Scope()
    exe = pt.Executor(pt.CPUPlace())
    with pt.scope_guard(scope):
        exe.run(startup, scope=scope)
        pt.io.save_inference_model(dirname, ["x"], [out], exe,
                                   main_program=prog, scope=scope)
    return dirname


def _export_qat_model(dirname, seed=11):
    """QAT-transpiled fc artifact with warmed activation scales — the
    int8-replica path (freeze_int8) needs its fake_quantize ops."""
    from paddle_tpu.contrib.quantize import QuantizeTranspiler

    lrng = np.random.RandomState(seed)
    prog, startup = pt.Program(), pt.Program()
    with pt.program_guard(prog, startup):
        x = layers.data(name="x", shape=[16], dtype="float32")
        h = layers.fc(x, size=32, act="relu")
        out = layers.fc(h, size=10)
    with pt.program_guard(prog, startup):
        QuantizeTranspiler().training_transpile(prog, startup)
    scope = pt.Scope()
    exe = pt.Executor(pt.CPUPlace())
    with pt.scope_guard(scope):
        exe.run(startup, scope=scope)
        feed = {"x": lrng.rand(8, 16).astype("float32")}
        for _ in range(10):  # warm the moving-average activation scales
            exe.run(prog, feed=feed, fetch_list=[out], scope=scope)
        test_prog = prog.clone(for_test=True)
        pt.io.save_inference_model(dirname, ["x"], [out], exe,
                                   main_program=test_prog, scope=scope)
    return dirname


@pytest.fixture(scope="module")
def fc_dir(tmp_path_factory):
    return _export_fc_model(str(tmp_path_factory.mktemp("serving") / "fc"))


@pytest.fixture(scope="module")
def dyn_dir(tmp_path_factory):
    return _export_dynamic_model(
        str(tmp_path_factory.mktemp("serving") / "dyn"))


@pytest.fixture(scope="module")
def qat_dir(tmp_path_factory):
    return _export_qat_model(
        str(tmp_path_factory.mktemp("serving") / "qat"))


def _serving_model(dirname, **kw):
    kw.setdefault("buckets", "1,2,4,8")
    kw.setdefault("max_wait_ms", 20.0)
    return ServingModel(ModelConfig("m", dirname, **kw))


# ---------------------------------------------------------------------------
# bucket ladder + padding units
# ---------------------------------------------------------------------------


class TestBucketLadder:
    def test_parse_buckets(self):
        assert parse_buckets("1,2,4,8") == (1, 2, 4, 8)
        assert parse_buckets("8, 2,2, 1") == (1, 2, 8)  # sorted, deduped
        assert parse_buckets([4, 2]) == (2, 4)
        with pytest.raises(ValueError):
            parse_buckets("")
        with pytest.raises(ValueError):
            parse_buckets("1,0,4")

    def test_bucket_for(self, fc_dir):
        m = _serving_model(fc_dir, buckets="2,4,8")
        assert m.bucket_for(1) == 2
        assert m.bucket_for(2) == 2
        assert m.bucket_for(5) == 8
        assert m.bucket_for(9) is None  # past the ladder

    def test_pad_feed_repeats_last_row(self):
        feed = {"x": np.arange(6, dtype="float32").reshape(2, 3)}
        out = ServingModel.pad_feed(feed, 2, 5)
        assert out["x"].shape == (5, 3)
        np.testing.assert_array_equal(out["x"][:2], feed["x"])
        for i in range(2, 5):
            np.testing.assert_array_equal(out["x"][i], feed["x"][-1])
        # no-op pad returns the feed unchanged
        assert ServingModel.pad_feed(feed, 2, 2) is feed

    def test_item_signature_excludes_batch_dim(self):
        a = {"x": np.zeros((2, 3), "float32")}
        b = {"x": np.zeros((7, 3), "float32")}
        c = {"x": np.zeros((2, 4), "float32")}
        assert item_signature(a) == item_signature(b)
        assert item_signature(a) != item_signature(c)

    def test_model_name_must_be_path_safe(self, fc_dir):
        with pytest.raises(ValueError):
            ModelConfig("a/b", fc_dir)
        with pytest.raises(ValueError):
            ModelConfig("", fc_dir)


# ---------------------------------------------------------------------------
# Predictor thread-safety (satellite: required before the batcher drains
# the compile cache from scheduler threads)
# ---------------------------------------------------------------------------


class TestConcurrentPredictor:
    def test_n_threads_m_signatures_exactly_m_compiles(self, fc_dir):
        """8 threads hammering 3 feed signatures -> exactly 3 compiles,
        and every result matches the single-threaded reference (no torn
        outputs from interleaved cache fills)."""
        sizes = (1, 2, 4)
        feeds = {b: {"x": rng.randn(b, 6).astype("float32")}
                 for b in sizes}
        ref_pred = Predictor(fc_dir, optimize=False)
        refs = {b: np.asarray(ref_pred.run(feeds[b])[0]) for b in sizes}

        pred = Predictor(fc_dir, optimize=False)
        n_threads, iters = 8, 25
        errors, mismatches = [], []

        def work(tid):
            lrng = np.random.RandomState(tid)
            try:
                for _ in range(iters):
                    b = sizes[lrng.randint(len(sizes))]
                    (out,) = pred.run(feeds[b])
                    if not np.allclose(np.asarray(out), refs[b],
                                       rtol=1e-5, atol=1e-6):
                        mismatches.append(b)
            except Exception as e:  # noqa: BLE001 — surface in main thread
                errors.append(e)

        threads = [threading.Thread(target=work, args=(t,))
                   for t in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors
        assert not mismatches, mismatches
        assert pred.compile_count == len(sizes), pred.compile_count

    def test_run_entries_carry_the_stateful_lock(self):
        """Entries compiled via plain Executor.run — the path serving's
        batcher and Predictor hit — must carry the executor's stateful
        run lock when the program writes state (donated rw buffers +
        scope write-back must be atomic across threads), and must NOT
        serialize stateless programs."""
        prog, startup = pt.Program(), pt.Program()
        with pt.program_guard(prog, startup):
            x = layers.data(name="x", shape=[4], dtype="float32")
            h = layers.batch_norm(x)  # training mode: running-stat writes
        prog2, startup2 = pt.Program(), pt.Program()
        with pt.program_guard(prog2, startup2):
            x2 = layers.data(name="x", shape=[4], dtype="float32")
            stateless = layers.fc(x2, size=2)
        scope, exe = pt.Scope(), pt.Executor(pt.CPUPlace())
        feed = {"x": rng.randn(2, 4).astype("float32")}
        with pt.scope_guard(scope):
            exe.run(startup, scope=scope)
            exe.run(prog, feed=feed, fetch_list=[h], scope=scope)
            stateful_entry = list(exe._cache.values())[-1]
            exe.run(startup2, scope=scope)
            exe.run(prog2, feed=feed, fetch_list=[stateless], scope=scope)
            stateless_entry = list(exe._cache.values())[-1]
        assert stateful_entry.state_writes, "premise: batch_norm writes"
        assert stateful_entry.run_lock is exe._stateful_lock
        assert not stateless_entry.state_writes
        assert stateless_entry.run_lock is None


# ---------------------------------------------------------------------------
# dynamic batcher
# ---------------------------------------------------------------------------


def _start_batcher(model, **kw):
    b = DynamicBatcher(model, **kw)
    b.start()
    return b


class TestDynamicBatcher:
    def test_coalesces_concurrent_requests_and_slices_rows(self, fc_dir):
        """Concurrent 1-row submits coalesce into one padded batch; each
        caller gets exactly its own rows back (correct slicing)."""
        m = _serving_model(fc_dir, max_wait_ms=100.0)
        m.warmup()
        ref = Predictor(fc_dir, optimize=False)
        b = _start_batcher(m)
        try:
            n = 6
            feeds = [{"x": rng.randn(1, 6).astype("float32")}
                     for _ in range(n)]
            results = [None] * n

            def fire(i):
                results[i] = b.submit(feeds[i])

            threads = [threading.Thread(target=fire, args=(i,))
                       for i in range(n)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            coalesced = [r[1]["coalesced"] for r in results]
            assert max(coalesced) > 1, coalesced  # batching happened
            for i, (outs, meta) in enumerate(results):
                (want,) = ref.run(feeds[i])
                np.testing.assert_allclose(
                    np.asarray(outs[0]), np.asarray(want),
                    rtol=1e-5, atol=1e-6)
                assert meta["request_rows"] == 1
                assert meta["bucket"] in m.buckets
        finally:
            b.stop()

    def test_multi_row_requests_slice_at_offsets(self, fc_dir):
        m = _serving_model(fc_dir, max_wait_ms=100.0)
        m.warmup()
        ref = Predictor(fc_dir, optimize=False)
        b = _start_batcher(m)
        try:
            sizes = [1, 2, 3]
            feeds = [{"x": rng.randn(s, 6).astype("float32")}
                     for s in sizes]
            results = [None] * len(sizes)

            def fire(i):
                results[i] = b.submit(feeds[i])

            threads = [threading.Thread(target=fire, args=(i,))
                       for i in range(len(sizes))]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            for i, (outs, meta) in enumerate(results):
                assert np.asarray(outs[0]).shape[0] == sizes[i]
                (want,) = ref.run(feeds[i])
                np.testing.assert_allclose(
                    np.asarray(outs[0]), np.asarray(want),
                    rtol=1e-5, atol=1e-6)
        finally:
            b.stop()

    def test_fixed_leading_dim_fetch_is_not_sliced(self, tmp_path):
        """A fetch whose fixed leading dim coincidentally equals the
        executed bucket (reduce over the batch axis -> shape (4,) on a
        1,2,4,8 ladder) must reach every request WHOLE — the de-batch
        decision comes from the declared fetch shape, not from comparing
        the output's leading dim against the bucket.  (Such outputs are
        computed over the padded/coalesced batch; the serving contract
        for them is "whole value", and slicing them is silent
        corruption.)"""
        d = _export_fixed_fetch_model(str(tmp_path / "fixed"))
        m = _serving_model(d, max_wait_ms=10.0)
        assert m.fetch_batched == [False], m.fetch_batched
        m.warmup()
        b = _start_batcher(m)
        try:
            # 3 rows pad to bucket 4 == the fetch's fixed dim: the old
            # shape heuristic sliced the (4,) vector to its first 3
            # elements
            outs, meta = b.submit(
                {"x": rng.randn(3, 4).astype("float32")})
            assert meta["bucket"] == 4, meta
            assert np.asarray(outs[0]).shape == (4,), \
                np.asarray(outs[0]).shape
        finally:
            b.stop()

    def test_max_batch_caps_coalescing(self, fc_dir):
        m = _serving_model(fc_dir, max_batch=2, max_wait_ms=50.0)
        m.warmup()
        b = _start_batcher(m)
        try:
            n = 6
            results = [None] * n
            feeds = [{"x": rng.randn(1, 6).astype("float32")}
                     for _ in range(n)]

            def fire(i):
                results[i] = b.submit(feeds[i])

            threads = [threading.Thread(target=fire, args=(i,))
                       for i in range(n)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert all(r[1]["batch_rows"] <= 2 for r in results), \
                [r[1] for r in results]
        finally:
            b.stop()

    def test_oversize_request_runs_and_is_counted(self, fc_dir):
        FLAGS.monitor = True
        m = _serving_model(fc_dir, buckets="1,2")
        m.warmup()
        b = _start_batcher(m)
        try:
            outs, meta = b.submit({"x": rng.randn(5, 6).astype("float32")})
            assert np.asarray(outs[0]).shape[0] == 5
            assert meta["bucket"] == 5  # exact-size execution
            c = default_registry().get("serving.m.oversize_batches")
            assert c is not None and c.value == 1
        finally:
            b.stop()

    def test_mixed_item_signatures_spill_not_mix(self, dyn_dir):
        """Requests with different trailing lengths never coalesce into
        one batch, and all of them are answered correctly."""
        m = _serving_model(dyn_dir, max_wait_ms=100.0)
        m.warmup()  # nothing warmable: the trailing dim is unknown
        b = _start_batcher(m)
        try:
            lens = [5, 7, 5, 7, 5, 7]
            feeds = [{"x": rng.randn(1, L).astype("float32")}
                     for L in lens]
            results = [None] * len(lens)

            def fire(i):
                results[i] = b.submit(feeds[i])

            threads = [threading.Thread(target=fire, args=(i,))
                       for i in range(len(lens))]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            for i, (outs, meta) in enumerate(results):
                want = feeds[i]["x"].mean(axis=-1, keepdims=True)
                np.testing.assert_allclose(np.asarray(outs[0]), want,
                                           rtol=1e-5, atol=1e-6)
        finally:
            b.stop()

    def test_validation_errors(self, fc_dir):
        m = _serving_model(fc_dir)
        m.warmup()
        b = _start_batcher(m)
        try:
            with pytest.raises(KeyError):  # missing feed
                b.submit({})
            with pytest.raises(ValueError):  # zero rows
                b.submit({"x": np.zeros((0, 6), "float32")})
            with pytest.raises(KeyError):  # unknown precision
                b.submit({"x": np.zeros((1, 6), "float32")},
                         precision="int8")
        finally:
            b.stop()


# ---------------------------------------------------------------------------
# warmup + compile-cache behavior (the tentpole property)
# ---------------------------------------------------------------------------


class TestWarmupAndCompileCache:
    def test_shape_varying_stream_zero_compiles_after_warmup(self, fc_dir):
        """The acceptance property at unit scale: after warming the
        ladder, an unbounded stream of request sizes causes ZERO further
        compiles (every batch padded onto a warm signature)."""
        FLAGS.monitor = True
        m = _serving_model(fc_dir, buckets="1,2,4,8")
        warmed = m.warmup()
        assert warmed == 4 and m.ready
        pred = m.predictor()
        frozen = pred.compile_count
        assert frozen == 4
        b = _start_batcher(m)
        try:
            for i in range(30):
                s = 1 + (i % 8)
                outs, meta = b.submit(
                    {"x": rng.randn(s, 6).astype("float32")})
                assert np.asarray(outs[0]).shape[0] == s
                assert meta["bucket"] >= s
        finally:
            b.stop()
        assert pred.compile_count == frozen  # flat: no retrace, ever
        c = default_registry().get("serving.unplanned_compiles")
        assert c is None or c.value == 0

    def test_serving_recompile_is_flight_tagged(self, dyn_dir):
        """Satellite: a compile taken while serving (ladder gap) lands in
        /flight with the requested vs bucketed signature + a named
        counter — diagnosable, not a silent retrace stall."""
        FLAGS.monitor = True
        m = _serving_model(dyn_dir, buckets="1,2")
        m.warmup()  # warms nothing; flips ready
        assert m.ready
        b = _start_batcher(m)
        try:
            b.submit({"x": rng.randn(1, 9).astype("float32")})
        finally:
            b.stop()
        evs = flight.default_recorder().events(kind="serving.compile")
        assert evs, "serving-tier compile not flight-recorded"
        ev = evs[-1]
        assert ev["model"] == "m" and ev["after_warmup"]
        assert ev["requested_rows"] == 1 and ev["bucketed_rows"] == 1
        assert ev["requested_signature"] == [["x", [9], "float32"]]
        assert ev["ctx"] == "serving/m"
        c = default_registry().get("serving.unplanned_compiles")
        assert c is not None and c.value >= 1

    def test_persistent_compilation_cache_populates(self, fc_dir,
                                                    tmp_path):
        import jax

        cache_dir = str(tmp_path / "xla_cache")
        FLAGS.serving_cache_dir = cache_dir
        try:
            assert enable_compilation_cache()
            m = _serving_model(fc_dir, buckets="1,2")
            m.warmup()
            assert os.listdir(cache_dir), \
                "warmup compiles not persisted to the cache dir"
        finally:
            jax.config.update("jax_compilation_cache_dir", None)
        FLAGS.serving_cache_dir = ""
        assert enable_compilation_cache() is False  # empty flag: off


# ---------------------------------------------------------------------------
# int8 replicas (contrib.quantize.freeze_int8 path)
# ---------------------------------------------------------------------------


class TestInt8Replica:
    def test_int8_replica_serves_and_matches_fp32(self, qat_dir):
        m = ServingModel(ModelConfig("q", qat_dir, int8=True,
                                     buckets="1,2,4", max_wait_ms=20.0))
        assert m.precisions == ["fp32", "int8"]
        # the replica's program really is frozen: int8 consumers, no fakes
        i8_ops = [op.type for op in
                  m.predictor("int8")._program.global_block().ops]
        assert "int8_mul" in i8_ops
        assert not any(t.startswith("fake_") for t in i8_ops)
        m.warmup()
        b = _start_batcher(m)
        try:
            feed = {"x": rng.rand(2, 16).astype("float32")}
            fp, _ = b.submit(feed)
            i8, meta = b.submit(feed, precision="int8")
            assert meta["precision"] == "int8"
            fp, i8 = np.asarray(fp[0]), np.asarray(i8[0])
            err = np.abs(fp - i8).max() / (np.abs(fp).max() + 1e-6)
            assert err < 0.1, err  # int8 quantization error bound
        finally:
            b.stop()

    def test_int8_requires_qat_artifact(self, fc_dir):
        with pytest.raises(ValueError, match="fake_quantize"):
            ServingModel(ModelConfig("f", fc_dir, int8=True))


# ---------------------------------------------------------------------------
# /health: trainer liveness vs serving readiness (satellite)
# ---------------------------------------------------------------------------


@pytest.fixture()
def _saved_step_state():
    rec = flight.default_recorder()
    saved = (rec.last_step, rec.last_loss, rec.last_step_ts)
    yield rec
    rec.last_step, rec.last_loss, rec.last_step_ts = saved


class TestHealth:
    def test_zero_steps_is_not_stalled(self, _saved_step_state):
        rec = _saved_step_state
        rec.last_step = rec.last_loss = rec.last_step_ts = None
        body, code = mserve.health_body()
        assert code == 200 and body["status"] == "ok"
        assert body["trainer"] is None  # no step monitor -> no liveness

    def test_stall_threshold_is_the_flag(self, _saved_step_state):
        rec = _saved_step_state
        rec.last_step, rec.last_step_ts = 42, time.time() - 5.0
        FLAGS.health_stall_s = 2.0
        body, code = mserve.health_body()
        assert code == 503 and body["status"] == "stalled"
        assert body["trainer"]["alive"] is False
        assert body["trainer"]["stall_after_s"] == 2.0
        FLAGS.health_stall_s = 60.0  # same staleness, wider threshold
        body, code = mserve.health_body()
        assert code == 200 and body["status"] == "ok"
        assert body["trainer"]["alive"] is True

    def test_readiness_distinct_from_liveness(self, _saved_step_state):
        rec = _saved_step_state
        rec.last_step = rec.last_loss = rec.last_step_ts = None
        mserve.set_readiness_provider(
            lambda: {"ready": False, "models": {}})
        body, code = mserve.health_body()
        assert code == 503 and body["status"] == "not_ready"
        mserve.set_readiness_provider(lambda: {"ready": True})
        body, code = mserve.health_body()
        assert code == 200 and body["status"] == "ok"
        # a broken probe answers 503, never raises
        def boom():
            raise RuntimeError("probe exploded")
        mserve.set_readiness_provider(boom)
        body, code = mserve.health_body()
        assert code == 503 and "probe exploded" in body["serving"]["error"]


# ---------------------------------------------------------------------------
# HTTP server surface
# ---------------------------------------------------------------------------


def _http(url, data=None, headers=None, timeout=30):
    """-> (status, body bytes); HTTP errors return their status+body."""
    req = urllib.request.Request(url, data=data, headers=headers or {})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


@pytest.fixture()
def fc_server(fc_dir):
    srv = InferenceServer(
        [ModelConfig("fc", fc_dir, buckets="1,2,4", max_wait_ms=5.0)],
        port=0)
    srv.start()
    yield srv, f"http://127.0.0.1:{srv.port}"
    srv.stop()


class TestHTTPServer:
    def test_predict_json_matches_direct_predictor(self, fc_server,
                                                   fc_dir):
        srv, url = fc_server
        x = rng.randn(3, 6).astype("float32")
        status, raw = _http(
            f"{url}/v1/models/fc:predict",
            data=json.dumps({"inputs": {"x": x.tolist()}}).encode(),
            headers={"Content-Type": "application/json"})
        assert status == 200
        body = json.loads(raw)
        (want,) = Predictor(fc_dir, optimize=False).run({"x": x})
        got = np.asarray(body["outputs"][srv.model("fc").fetch_names[0]])
        np.testing.assert_allclose(got, np.asarray(want),
                                   rtol=1e-4, atol=1e-5)
        assert body["batch"]["bucket"] == 4  # 3 rows pad to bucket 4
        assert body["batch"]["request_rows"] == 3

    def test_predict_b64_and_npz_roundtrip(self, fc_server):
        import base64
        import io as _io

        srv, url = fc_server
        x = rng.randn(2, 6).astype("float32")
        # b64 raw-buffer JSON form
        status, raw = _http(
            f"{url}/v1/models/fc:predict",
            data=json.dumps({"inputs": {"x": {
                "b64": base64.b64encode(x.tobytes()).decode(),
                "dtype": "float32", "shape": [2, 6]}}}).encode(),
            headers={"Content-Type": "application/json"})
        assert status == 200
        want = np.asarray(json.loads(raw)["outputs"][
            srv.model("fc").fetch_names[0]])
        # npz request + npz response
        buf = _io.BytesIO()
        np.savez(buf, x=x)
        status, raw = _http(
            f"{url}/v1/models/fc:predict?format=npz",
            data=buf.getvalue(),
            headers={"Content-Type": "application/x-npz"})
        assert status == 200
        with np.load(_io.BytesIO(raw)) as z:
            got = z[srv.model("fc").fetch_names[0]]
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    def test_introspection_health_metrics(self, fc_server):
        srv, url = fc_server
        status, raw = _http(f"{url}/v1/models")
        assert status == 200
        (info,) = json.loads(raw)["models"]
        assert info["name"] == "fc" and info["ready"]
        assert info["buckets"] == [1, 2, 4]
        assert info["feeds"]["x"]["shape"] == [-1, 6]
        status, raw = _http(f"{url}/v1/models/fc")
        assert status == 200 and json.loads(raw)["name"] == "fc"
        # a zero-step serving process is healthy (readiness, not stall)
        status, raw = _http(f"{url}/health")
        assert status == 200
        health = json.loads(raw)
        assert health["serving"]["ready"] is True
        assert health["serving"]["models"]["fc"]["ready"] is True
        # serve one request, then the metrics surface must carry the
        # serving histograms/counters
        x = rng.randn(1, 6).astype("float32")
        _http(f"{url}/v1/models/fc:predict",
              data=json.dumps({"inputs": {"x": x.tolist()}}).encode(),
              headers={"Content-Type": "application/json"})
        status, raw = _http(f"{url}/metrics")
        text = raw.decode()
        for needle in ("serving_fc_request_seconds", "serving_fc_batches",
                       "serving_fc_batch_fill_bucket", "serving_requests",
                       "executor_compiles"):
            assert needle in text, needle

    def test_error_surface(self, fc_server):
        srv, url = fc_server
        post = {"Content-Type": "application/json"}
        cases = [
            # unknown model
            (f"{url}/v1/models/nope:predict",
             json.dumps({"inputs": {"x": [[0.0] * 6]}}).encode(), post,
             404),
            # malformed JSON
            (f"{url}/v1/models/fc:predict", b"{not json", post, 400),
            # missing "inputs" key
            (f"{url}/v1/models/fc:predict", b'{"x": 1}', post, 400),
            # missing feed
            (f"{url}/v1/models/fc:predict",
             json.dumps({"inputs": {}}).encode(), post, 400),
            # unknown precision replica
            (f"{url}/v1/models/fc:predict",
             json.dumps({"inputs": {"x": [[0.0] * 6]},
                         "precision": "int8"}).encode(), post, 400),
            # unsupported content type
            (f"{url}/v1/models/fc:predict", b"x,1,2",
             {"Content-Type": "text/csv-not-a-thing/x"}, 415),
        ]
        for target, data, headers, want in cases:
            status, raw = _http(target, data=data, headers=headers)
            assert status == want, (target, status, raw[:200])
            assert "error" in json.loads(raw)
        # GET on an unknown path still 404s through the monitor fallback
        status, _ = _http(f"{url}/definitely/not/a/route")
        assert status == 404

    def test_duplicate_model_name_rejected(self, fc_server, fc_dir):
        srv, _ = fc_server
        with pytest.raises(ValueError, match="already served"):
            srv.add_model(ModelConfig("fc", fc_dir))


# ---------------------------------------------------------------------------
# export_aot_bundle -> fresh-process serving (subprocess; satellite)
# ---------------------------------------------------------------------------


def _spawn_server(args, extra_env=None):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO_ROOT + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    env.pop("FLAGS_monitor", None)
    env.update(extra_env or {})
    proc = subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu.serving"] + args,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        cwd=REPO_ROOT, env=env, text=True)
    deadline = time.time() + 120
    line = ""
    while time.time() < deadline:
        line = proc.stdout.readline()
        if line.strip():
            break
        if proc.poll() is not None:
            break
    if not line.strip() or proc.poll() is not None:
        err = proc.stderr.read() if proc.stderr else ""
        proc.kill()
        raise AssertionError(f"server did not come up: {err[-2000:]}")
    ready = json.loads(line)
    assert ready["event"] == "serving_ready"
    return proc, f"http://127.0.0.1:{ready['port']}"


def _stop_server(proc):
    proc.send_signal(signal.SIGTERM)
    try:
        proc.wait(timeout=15)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait(timeout=5)


def _scrape_scalar(url, name):
    text = _http(f"{url}/metrics")[1].decode()
    for line in text.splitlines():
        if line.startswith(name + " "):
            return float(line.split()[-1])
    return 0.0


class TestAOTServingSubprocess:
    @pytest.fixture(scope="class")
    def aot_dir(self, tmp_path_factory):
        d = _export_fc_model(
            str(tmp_path_factory.mktemp("serving") / "aot_fc"))
        n = export_aot_bundle(
            d, [{"x": np.zeros((b, 6), "float32")} for b in (1, 2, 4)])
        assert n == 3
        return d

    def test_fresh_process_serves_with_zero_traces(self, aot_dir):
        """The reference's out-of-Python property, end to end: a FRESH
        process loads the exported dir with use_aot and serves 100
        shape-varying requests with the executor compile counter FLAT at
        zero — no trace, no compile, bundles only."""
        proc, url = _spawn_server(
            ["--model", f"demo={aot_dir}", "--port", "0", "--use-aot",
             "--buckets", "1,2,4", "--max-wait-ms", "1"])
        try:
            info = json.loads(_http(f"{url}/v1/models/demo")[1])
            assert info["use_aot"] and info["aot_signatures"] == 3
            compiles_after_warmup = _scrape_scalar(url, "executor_compiles")
            assert compiles_after_warmup == 0, \
                "AOT warmup must serve from bundles, not compile"
            lrng = np.random.RandomState(0)
            for i in range(100):
                s = 1 + (i % 4)
                x = lrng.randn(s, 6).astype("float32")
                status, raw = _http(
                    f"{url}/v1/models/demo:predict",
                    data=json.dumps({"inputs": {"x": x.tolist()}}).encode(),
                    headers={"Content-Type": "application/json"})
                assert status == 200, raw[:200]
            assert _scrape_scalar(url, "executor_compiles") \
                == compiles_after_warmup, "a request triggered a trace"
            assert _scrape_scalar(url, "serving_demo_requests") == 100
        finally:
            _stop_server(proc)

    def test_corrupt_bundle_degrades_to_jit_with_named_counter(
            self, aot_dir, tmp_path):
        """A corrupted sig_*.xla must not take the model down: the load
        degrades that signature to the JIT path, counts it
        (inference_aot_bundle_errors), and serves correct results."""
        import shutil

        d = str(tmp_path / "corrupt")
        shutil.copytree(aot_dir, d)
        victim = sorted(glob.glob(os.path.join(d, "__aot__",
                                               "sig_*.xla")))[0]
        with open(victim, "wb") as f:
            f.write(b"\x00garbage, definitely not an XLA payload")
        proc, url = _spawn_server(
            ["--model", f"demo={d}", "--port", "0", "--use-aot",
             "--buckets", "1,2,4", "--max-wait-ms", "1"])
        try:
            info = json.loads(_http(f"{url}/v1/models/demo")[1])
            assert info["ready"] and info["aot_signatures"] == 2
            assert _scrape_scalar(url, "inference_aot_bundle_errors") >= 1
            # the degraded signature compiled (JIT fallback), served fine
            assert _scrape_scalar(url, "executor_compiles") >= 1
            x = rng.randn(1, 6).astype("float32")
            status, raw = _http(
                f"{url}/v1/models/demo:predict",
                data=json.dumps({"inputs": {"x": x.tolist()}}).encode(),
                headers={"Content-Type": "application/json"})
            assert status == 200, raw[:200]
        finally:
            _stop_server(proc)


# ---------------------------------------------------------------------------
# AOT bundle donation safety (v2 bundles)
# ---------------------------------------------------------------------------


class TestAOTDonationSafety:
    """Regression: v1 bundles baked the executor's donate_argnums
    aliasing into the serialized executable, and jax's deserialized
    Compiled path has no donation bookkeeping — running a STATEFUL
    bundle (QAT quant-state write-backs) returned state arrays aliasing
    freed buffers, corrupting the scope nondeterministically under
    serving load.  v2 bundles serialize donation-free; loaders reject
    v1 to the JIT path."""

    @pytest.fixture()
    def qat_aot_dir(self, qat_dir, tmp_path):
        import shutil

        d = str(tmp_path / "qat_aot")
        shutil.copytree(qat_dir, d)
        assert export_aot_bundle(
            d, [{"x": np.zeros((b, 16), "float32")} for b in (2, 4)]) == 2
        return d

    def test_stateful_bundle_state_and_values_stable(self, qat_aot_dir):
        """Warmup-style zeros runs + real runs through a stateful bundle
        leave the quant state EXACTLY unchanged (test-mode passthrough)
        and serve the JIT predictor's values.  Under the v1 donation bug
        this corrupted within a couple of iterations whenever another
        predictor churned the heap."""
        with open(glob.glob(os.path.join(
                qat_aot_dir, "__aot__", "sig_*.json"))[0]) as f:
            manifest = json.load(f)
        assert manifest["aot_version"] >= 2
        state_names = manifest["state_writes"]
        assert state_names, "QAT artifact must carry quant-state writes"

        pred = Predictor(qat_aot_dir, optimize=False, use_aot=True)
        assert len(pred.aot_signatures) == 2
        # a second predictor in the same process: heap churn was part of
        # the original corruption trigger
        ref = Predictor(qat_aot_dir, optimize=False, use_aot=False)
        x = np.random.RandomState(5).rand(4, 16).astype("float32")
        want = np.asarray(ref.run({"x": x})[0])

        state0 = {n: np.asarray(pred._scope.find_var(n)).copy()
                  for n in state_names}
        for i in range(12):
            pred.run({"x": np.zeros((2 if i % 2 else 4, 16), "float32")})
            out = np.asarray(pred.run({"x": x})[0])
            np.testing.assert_allclose(out, want, rtol=0, atol=1e-6)
            for n, v0 in state0.items():
                np.testing.assert_array_equal(
                    np.asarray(pred._scope.find_var(n)), v0,
                    err_msg=f"quant state {n} drifted at iteration {i}")

    def test_v1_donating_bundle_rejected_to_jit(self, qat_aot_dir):
        for p in glob.glob(os.path.join(qat_aot_dir, "__aot__",
                                        "sig_*.json")):
            with open(p) as f:
                m = json.load(f)
            del m["aot_version"]  # pre-versioning == v1 == donating
            with open(p, "w") as f:
                json.dump(m, f)
        FLAGS.monitor = True
        pred = Predictor(qat_aot_dir, optimize=False, use_aot=True)
        assert pred.aot_signatures == []
        errs = default_registry().get("inference.aot_bundle_errors")
        assert errs is not None and errs.value >= 2
        # JIT fallback still serves correct values
        ref = Predictor(qat_aot_dir, optimize=False, use_aot=False)
        x = np.random.RandomState(5).rand(2, 16).astype("float32")
        np.testing.assert_allclose(
            np.asarray(pred.run({"x": x})[0]),
            np.asarray(ref.run({"x": x})[0]), rtol=0, atol=1e-6)
        assert pred.compile_count == 1


# ---------------------------------------------------------------------------
# loadgen harness (tools/loadgen.py)
# ---------------------------------------------------------------------------


class TestLoadgen:
    def test_loadgen_artifact_against_live_server(self, fc_dir, tmp_path):
        srv = InferenceServer(
            [ModelConfig("fc", fc_dir, buckets="1,2,4,8",
                         max_wait_ms=3.0)], port=0)
        srv.start()
        out = str(tmp_path / "loadgen.json")
        try:
            rc = subprocess.run(
                [sys.executable, os.path.join(REPO_ROOT, "tools",
                                              "loadgen.py"),
                 "--url", f"http://127.0.0.1:{srv.port}", "--model", "fc",
                 "--requests", "40", "--concurrency", "4",
                 "--batch-sizes", "1,2,3", "--out", out],
                capture_output=True, text=True, timeout=120)
            assert rc.returncode == 0, rc.stderr[-2000:]
        finally:
            srv.stop()
        art = json.loads(open(out).read())
        assert art["completed"] == 40 and art["errors"] == 0
        assert art["qps"] > 0
        assert art["latency_ms"]["p99"] >= art["latency_ms"]["p50"] > 0
        assert art["policy"]["buckets"] == [1, 2, 4, 8]
        sm = art["server_metrics"]
        assert sm["batches"] >= 1
        assert sm["unplanned_compiles"] == 0  # warm ladder held
        assert sm["batch_fill_mean"] is not None
        assert 0 < sm["batch_fill_mean"] <= 1
