"""Request-scoped distributed tracing + SLO burn-rate accounting
(ISSUE 14: monitor/tracing.py threaded through serving -> batcher ->
executor -> decode).

Covers the acceptance criteria: a dynamically-batched predict request
and a multi-token generation both yield traces whose component sum
matches wall clock within 5%; FLAGS_trace_requests off is zero-cost (no
trace objects, no flight events, no registry entries); burn-rate gauges
and /v1/traces ride the /metrics server; the chrome-trace export renders
request spans on the shared flight/xplane clock.  Plus the satellites:
W3C traceparent round-trip, fan-in span sharing across coalesced
requests, pad-waste attribution, bounded trace-store memory under
concurrent scrape load, crash dumps carrying in-flight request state,
and the trace_report "Requests" section.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers, profiler
from paddle_tpu.flags import FLAGS
from paddle_tpu.monitor import default_registry, flight, tracing
from paddle_tpu.monitor import serve as mserve
from paddle_tpu.serving import InferenceServer, ModelConfig, Unavailable
from paddle_tpu.serving.generation import build_demo_generation_model


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    """Default flags + empty registry/trace store around every test."""
    FLAGS.reset()
    default_registry().reset()
    tracing.reset()
    flight.default_recorder().clear()
    yield
    mserve.set_readiness_provider(None)
    FLAGS.reset()
    default_registry().reset()
    tracing.reset()
    flight.default_recorder().clear()


def _export_fc_model(dirname, in_dim=6, out_dim=3, seed=3):
    prog, startup = pt.Program(), pt.Program()
    prog.random_seed = startup.random_seed = seed
    with pt.program_guard(prog, startup):
        x = layers.data(name="x", shape=[in_dim], dtype="float32")
        h = layers.fc(x, size=8, act="relu")
        out = layers.fc(h, size=out_dim)
    scope = pt.Scope()
    exe = pt.Executor(pt.CPUPlace())
    with pt.scope_guard(scope):
        exe.run(startup, scope=scope)
        pt.io.save_inference_model(dirname, ["x"], [out], exe,
                                   main_program=prog, scope=scope)
    return dirname


@pytest.fixture(scope="module")
def fc_dir(tmp_path_factory):
    return _export_fc_model(str(tmp_path_factory.mktemp("tracing") / "fc"))


def _server(fc_dir, buckets="1,2,4", trace=True, warmup=True, **flag_kw):
    if trace:
        FLAGS.trace_requests = True
    for k, v in flag_kw.items():
        FLAGS.set(k, v)
    srv = InferenceServer(
        [ModelConfig("demo", fc_dir, buckets=buckets)], port=0)
    srv.start(warmup=warmup)
    return srv


def _predict(srv, rows=3, traceparent=None, timeout=30):
    body = json.dumps(
        {"inputs": {"x": [[0.1] * 6] * rows}}).encode()
    headers = {"Content-Type": "application/json"}
    if traceparent:
        headers["traceparent"] = traceparent
    req = urllib.request.Request(
        f"http://127.0.0.1:{srv.port}/v1/models/demo:predict",
        data=body, headers=headers)
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, dict(r.getheaders()), json.loads(r.read())


def _get_json(srv, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}{path}", timeout=10) as r:
        return json.loads(r.read())


def _components_ok(dec, label="", tol_frac=0.05, tol_abs_ms=0.5):
    """The acceptance sum contract: components + unattributed == total,
    and the unattributed remainder stays under 5% (+ jitter floor)."""
    total = dec["total_ms"]
    s = sum(dec["components_ms"].values())
    tol = tol_frac * total + tol_abs_ms
    assert abs(s + dec["unattributed_ms"] - total) <= tol, (label, dec)
    assert dec["unattributed_ms"] <= tol, (label, dec)


def _retry_timing(fn, attempts=3):
    """Run one request-and-assert attempt up to `attempts` times.  The
    5% sum contract is a TIMING gate: thread-handoff gaps between spans
    inflate under CI CPU contention (a noisy neighbour can add ms-scale
    scheduler delay to a ~15ms request), the same reason the serving A/B
    gates run interleaved trials.  Structural assertions inside `fn`
    stay strict — they pass or fail identically on every attempt."""
    for i in range(attempts):
        try:
            return fn(i)
        except AssertionError:
            if i == attempts - 1:
                raise
            time.sleep(0.2)


# ---------------------------------------------------------------------------
# traceparent
# ---------------------------------------------------------------------------


def test_traceparent_parse_and_format():
    tid, sid = "ab" * 16, "cd" * 8
    hdr = tracing.format_traceparent(tid, sid)
    assert hdr == f"00-{tid}-{sid}-01"
    assert tracing.parse_traceparent(hdr) == (tid, sid)
    assert tracing.parse_traceparent(hdr.upper()) == (tid, sid)
    # malformed headers start a fresh trace instead of failing
    for bad in (None, "", "garbage", "00-short-cdcdcdcdcdcdcdcd-01",
                f"00-{tid}-{sid}",            # 3 segments
                f"ff-{tid}-{sid}-01",         # reserved version
                f"00-{'0' * 32}-{sid}-01",    # zero trace id
                f"00-{tid}-{'0' * 16}-01",    # zero span id
                f"00-{'zz' * 16}-{sid}-01"):  # non-hex
        assert tracing.parse_traceparent(bad) is None, bad
    # generated ids are valid by construction
    t2 = tracing.new_trace_id()
    s2 = tracing.new_span_id()
    assert tracing.parse_traceparent(
        tracing.format_traceparent(t2, s2)) == (t2, s2)


def test_slo_config_parsing():
    assert tracing.parse_slo_config("") == {}
    assert tracing.parse_slo_config("50") == {"*": 50.0}
    assert tracing.parse_slo_config("a=50, b=2.5") == {"a": 50.0,
                                                      "b": 2.5}
    assert tracing.parse_slo_config("25,a=50") == {"*": 25.0, "a": 50.0}
    # malformed entries are dropped, not fatal
    assert tracing.parse_slo_config("a=oops,b=3") == {"b": 3.0}
    FLAGS.serving_slo_ms = "a=50,10"
    assert tracing.slo_objective("a") == 50.0
    assert tracing.slo_objective("other") == 10.0
    FLAGS.serving_slo_ms = ""
    assert tracing.slo_objective("a") is None


# ---------------------------------------------------------------------------
# zero-cost-off contract
# ---------------------------------------------------------------------------


def test_zero_cost_with_tracing_off(fc_dir):
    """FLAGS_trace_requests off: no trace objects on the request path,
    no trace store entries, no trace.* flight events, no SLO registry
    entries — monitor itself stays on (the serving default)."""
    srv = _server(fc_dir, trace=False)
    try:
        assert tracing.start("predict", "demo") is None
        status, headers, payload = _predict(srv, rows=2)
        assert status == 200
        assert "traceparent" not in {k.lower() for k in headers}
        assert "trace" not in payload["batch"]
        outs, meta = srv.submit("demo", {"x": np.ones((1, 6), "f4")})
        assert "trace" not in meta
    finally:
        srv.stop()
    assert len(tracing.default_store()) == 0
    assert tracing._open_traces == {}
    evs = flight.default_recorder().events(kind="trace")
    assert evs == []
    assert not [n for n in default_registry().names() if "slo" in n]


# ---------------------------------------------------------------------------
# predict-path traces
# ---------------------------------------------------------------------------


def test_predict_trace_decomposition_and_header_echo(fc_dir):
    srv = _server(fc_dir)

    def attempt(i):
        tid = f"{0xabababababababababababababababab + i:032x}"
        t0 = time.perf_counter()
        status, headers, payload = _predict(
            srv, rows=3, traceparent=f"00-{tid}-{'cd' * 8}-01")
        client_ms = (time.perf_counter() - t0) * 1e3
        assert status == 200
        hdr = {k.lower(): v for k, v in headers.items()}
        # the client's trace id is echoed with OUR root span as parent
        parsed = tracing.parse_traceparent(hdr["traceparent"])
        assert parsed is not None and parsed[0] == tid
        meta_trace = payload["batch"]["trace"]
        assert meta_trace["trace_id"] == tid
        assert "batch.exec" in meta_trace["components_ms"]

        tr = _get_json(srv, f"/v1/traces/{tid}")
        assert tr["status"] == "ok" and tr["kind"] == "predict"
        assert tr["client_parent"] == "cd" * 8
        kinds = {s["name"] for s in tr["spans"]}
        assert {"parse", "admission", "queue.wait", "batch.form",
                "batch.pad", "batch.exec", "debatch",
                "respond"} <= kinds
        # executor sub-span (warm ladder -> run, not compile)
        assert "executor.run" in kinds
        dec = tr["decomposition"]
        _components_ok(dec, "predict")
        # server window nests inside the client-measured wall clock
        assert dec["total_ms"] <= client_ms + 1.0
        # pad-to-bucket waste attributed per request: 3 rows -> bucket 4
        pad = dec["padding"]
        assert (pad["rows_real"], pad["rows_padded"],
                pad["bucket"]) == (3, 1, 4)
        assert pad["fill"] == pytest.approx(0.75)

    try:
        _retry_timing(attempt)
    finally:
        srv.stop()


def test_fan_in_one_exec_span_parented_by_n_requests(fc_dir):
    """Two coalesced requests share ONE batch.exec span id whose parents
    list BOTH request root spans — the dynamic-batching fan-in."""
    srv = _server(fc_dir)
    try:
        # widen the coalescing window so both submits land in one batch
        batcher = srv._batchers["demo"]
        batcher.max_wait_s = 0.25
        results = {}

        def go(name):
            results[name] = srv.submit(
                "demo", {"x": np.full((1, 6), 0.5, "f4")})

        ts = [threading.Thread(target=go, args=(i,)) for i in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        metas = [results[i][1] for i in range(2)]
        tids = [m["trace"]["trace_id"] for m in metas]
        traces = [tracing.default_store().get(t).to_json() for t in tids]
        execs = [next(s for s in tr["spans"]
                      if s["name"] == "batch.exec") for tr in traces]
        assert execs[0]["span_id"] == execs[1]["span_id"]
        assert execs[0]["attrs"]["fan_in"] == 2
        roots = {tr["spans"][0]["span_id"] for tr in traces}
        assert set(execs[0]["attrs"]["parents"]) == roots
        # each copy hangs off its OWN trace's root
        for tr, ex in zip(traces, execs):
            assert ex["parent_id"] == tr["spans"][0]["span_id"]
        assert metas[0]["coalesced"] == 2
    finally:
        srv.stop()


def test_inprocess_submit_gets_full_decomposition(fc_dir):
    srv = _server(fc_dir)

    def attempt(i):
        outs, meta = srv.submit("demo", {"x": np.ones((2, 6), "f4")})
        block = meta["trace"]
        assert block["total_ms"] > 0
        _components_ok(block, "in-process predict")
        assert tracing.default_store().get(block["trace_id"]) is not None

    try:
        _retry_timing(attempt)
    finally:
        srv.stop()


def test_rejected_request_trace_names_the_shed(fc_dir):
    srv = _server(fc_dir)
    try:
        srv._batchers["demo"].begin_drain()
        with pytest.raises(Unavailable):
            srv.submit("demo", {"x": np.ones((1, 6), "f4")})
    finally:
        srv.stop()
    rejected = [t for t in tracing.default_store().last(10)
                if t.status.startswith("rejected:")]
    assert rejected, [t.status for t in tracing.default_store().last(10)]
    tr = rejected[0].to_json()
    assert tr["status"] == "rejected:draining"
    adm = [s for s in tr["spans"] if s["name"] == "admission"]
    assert adm and adm[0]["attrs"]["outcome"] == "draining"


def test_executor_compile_span_on_cold_signature(fc_dir):
    """A cold-signature request traces the COMPILE wall time; the next
    request on the warm signature traces a run span."""
    srv = _server(fc_dir, warmup=False)
    try:
        _, meta1 = srv.submit("demo", {"x": np.ones((1, 6), "f4")})
        tr1 = tracing.default_store().get(
            meta1["trace"]["trace_id"]).to_json()
        kinds1 = {s["name"] for s in tr1["spans"]}
        assert "executor.compile" in kinds1
        assert meta1["trace"]["executor_ms"]["compile"] > 0
        _, meta2 = srv.submit("demo", {"x": np.ones((1, 6), "f4")})
        tr2 = tracing.default_store().get(
            meta2["trace"]["trace_id"]).to_json()
        kinds2 = {s["name"] for s in tr2["spans"]}
        assert "executor.run" in kinds2
        assert "executor.compile" not in kinds2
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# generation traces
# ---------------------------------------------------------------------------


def _gen_server(**flag_kw):
    FLAGS.trace_requests = True
    for k, v in flag_kw.items():
        FLAGS.set(k, v)
    srv = InferenceServer([], port=0)
    srv.add_generation_model(
        build_demo_generation_model("gendemo", slots=4))
    srv.start()
    return srv


def test_generation_trace_decode_iterations(fc_dir):
    srv = _gen_server()

    def attempt(i):
        tid = f"{0x12121212121212121212121212121212 + i:032x}"
        body = json.dumps({"prompt": [3, 5, 7],
                           "max_tokens": 10}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/v1/models/gendemo:generate",
            data=body,
            headers={"Content-Type": "application/json",
                     "traceparent": f"00-{tid}-{'ef' * 8}-01"})
        t0 = time.perf_counter()
        with urllib.request.urlopen(req, timeout=60) as r:
            headers = dict(r.getheaders())
            payload = json.loads(r.read())
        client_ms = (time.perf_counter() - t0) * 1e3
        assert tid in headers.get("traceparent", "")
        tr = _get_json(srv, f"/v1/traces/{tid}")
        assert tr["kind"] == "generate" and tr["status"] == "ok"
        kinds = {s["name"] for s in tr["spans"]}
        assert {"parse", "admission", "queue.wait", "prefill",
                "decode.step", "deliver", "respond"} <= kinds
        dec = tr["decomposition"]
        # iteration accounting: one decode.step span per generated token
        assert dec["decode_steps"] == len(payload["tokens"])
        steps = [s for s in tr["spans"] if s["name"] == "decode.step"]
        assert [s["attrs"]["token_index"] for s in steps] == \
            list(range(len(steps)))
        assert all(s["attrs"]["occupancy"] >= 1 for s in steps)
        # TTFT linkage on the root span
        root = tr["spans"][0]
        assert root["attrs"]["ttft_ms"] == payload["meta"]["ttft_ms"]
        assert root["attrs"]["tokens"] == len(payload["tokens"])
        _components_ok(dec, "generation")
        assert dec["total_ms"] <= client_ms + 1.0

    try:
        _retry_timing(attempt)
    finally:
        srv.stop()


def test_generation_late_join_spans_do_not_overlap_prefill():
    """A request joining mid-flight: its first decode.step span starts
    AFTER its own prefill ends, while the in-flight sequence's iteration
    span keeps the prefill stall it sat through."""
    srv = _gen_server()

    def attempt(i):
        done = {}

        def long_req():
            done["long"] = srv.submit_generate(
                "gendemo", [3, 5, 7], max_tokens=48)

        t = threading.Thread(target=long_req)
        t.start()
        time.sleep(0.03)  # let the long request start decoding
        _, meta_short = srv.submit_generate("gendemo", [9, 2],
                                            max_tokens=2)
        t.join(timeout=60)
        short = tracing.default_store().get(
            meta_short["trace"]["trace_id"]).to_json()
        prefill = next(s for s in short["spans"]
                       if s["name"] == "prefill")
        steps = [s for s in short["spans"] if s["name"] == "decode.step"]
        pre_end = prefill["t0"] + prefill["dur_ms"] / 1e3
        assert steps and all(s["t0"] >= pre_end - 1e-4 for s in steps)
        _components_ok(short["decomposition"], "late joiner")
        long_tr = tracing.default_store().get(
            done["long"][1]["trace"]["trace_id"]).to_json()
        _components_ok(long_tr["decomposition"], "long generation")

    try:
        _retry_timing(attempt)
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# /v1/traces endpoints + bounded store
# ---------------------------------------------------------------------------


def test_traces_endpoints_last_n_and_404(fc_dir):
    srv = _server(fc_dir)
    try:
        ids = []
        for i in range(3):
            _, meta = srv.submit("demo", {"x": np.ones((1, 6), "f4")})
            ids.append(meta["trace"]["trace_id"])
        body = _get_json(srv, "/v1/traces?last=2")
        assert body["enabled"] is True and body["stored"] == 3
        got = [t["trace_id"] for t in body["traces"]]
        assert got == [ids[2], ids[1]]  # most recent first
        one = _get_json(srv, f"/v1/traces/{ids[0]}")
        assert one["trace_id"] == ids[0]
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get_json(srv, "/v1/traces/" + "0" * 32)
        assert ei.value.code == 404
    finally:
        srv.stop()


def test_trace_store_bounded_eviction(fc_dir):
    FLAGS.trace_store = 4
    srv = _server(fc_dir)
    try:
        ids = []
        for _ in range(7):
            _, meta = srv.submit("demo", {"x": np.ones((1, 6), "f4")})
            ids.append(meta["trace"]["trace_id"])
        store = tracing.default_store()
        assert len(store) == 4
        assert store.get(ids[0]) is None  # oldest evicted
        assert store.get(ids[-1]) is not None
    finally:
        srv.stop()


def test_concurrent_metrics_and_traces_scrapes_under_load(fc_dir):
    """Satellite: the MonitorHandler shares the stdlib server with
    predict traffic — concurrent /metrics + /v1/traces scrapes during
    active load must return parseable payloads (no interleaving
    corruption) and the trace store must stay bounded."""
    FLAGS.trace_store = 16
    srv = _server(fc_dir, serving_slo_ms="demo=250")
    try:
        stop = threading.Event()
        errors = []

        def submitter():
            i = 0
            while not stop.is_set():
                try:
                    srv.submit("demo",
                               {"x": np.full((1 + i % 3, 6), 0.1, "f4")})
                except Exception as e:  # noqa: BLE001 — recorded
                    errors.append(("submit", repr(e)))
                i += 1

        def scraper(path, check):
            while not stop.is_set():
                try:
                    with urllib.request.urlopen(
                            f"http://127.0.0.1:{srv.port}{path}",
                            timeout=10) as r:
                        assert r.status == 200
                        check(r.read())
                except Exception as e:  # noqa: BLE001 — recorded
                    errors.append((path, repr(e)))

        def check_metrics(raw):
            text = raw.decode()
            assert "serving_demo_request_seconds_bucket" in text or \
                "executor_" in text

        def check_traces(raw):
            body = json.loads(raw)
            assert isinstance(body["traces"], list)
            assert body["stored"] <= 16

        threads = ([threading.Thread(target=submitter)
                    for _ in range(3)]
                   + [threading.Thread(target=scraper,
                                       args=("/metrics", check_metrics))
                      for _ in range(2)]
                   + [threading.Thread(
                       target=scraper,
                       args=("/v1/traces?last=10", check_traces))
                      for _ in range(2)])
        for t in threads:
            t.start()
        time.sleep(1.5)
        stop.set()
        for t in threads:
            t.join(timeout=10)
        assert not errors, errors[:5]
        assert len(tracing.default_store()) <= 16
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# SLO engine
# ---------------------------------------------------------------------------


def test_slo_engine_burn_rates_on_metrics(fc_dir):
    # an objective every request MISSES: all events bad, burn > 0
    srv = _server(fc_dir, serving_slo_ms="demo=0.0001")
    try:
        for _ in range(4):
            srv.submit("demo", {"x": np.ones((1, 6), "f4")})
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics", timeout=10) as r:
            text = r.read().decode()
        assert "serving_demo_slo_bad_total 4" in text
        burn = [ln for ln in text.splitlines()
                if ln.startswith("serving_demo_slo_burn_rate_5m ")]
        assert burn and float(burn[0].split()[1]) > 1.0
        assert "serving_demo_slo_objective_ms 0.0001" in text
        # /v1/models surfaces the SLO block (finite p99 via the
        # quantile clamp rides the same info payload)
        info = _get_json(srv, "/v1/models/demo")
        assert info["slo"]["bad_total"] == 4
        assert info["slo"]["burn_rate"]["5m"] > 1.0
        # a generous objective counts good and burns nothing
        FLAGS.serving_slo_ms = "demo=60000"
        tracing.reset()
        srv.submit("demo", {"x": np.ones((1, 6), "f4")})
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics", timeout=10) as r:
            text = r.read().decode()
        assert "serving_demo_slo_good_total 5" in text or \
            "serving_demo_slo_good_total 1" in text
        burn = [ln for ln in text.splitlines()
                if ln.startswith("serving_demo_slo_burn_rate_5m ")]
        assert burn and float(burn[0].split()[1]) == 0.0
    finally:
        srv.stop()


def test_slo_shed_counts_bad(fc_dir):
    srv = _server(fc_dir, serving_slo_ms="demo=1000")
    try:
        srv._batchers["demo"].begin_drain()
        with pytest.raises(Unavailable):
            srv.submit("demo", {"x": np.ones((1, 6), "f4")})
    finally:
        srv.stop()
    tr = tracing.slo_tracker("demo")
    assert tr is not None and tr.bad_total == 1 and tr.good_total == 0


# ---------------------------------------------------------------------------
# flight ring, crash dumps, unified timeline, trace_report
# ---------------------------------------------------------------------------


def test_flight_events_and_unified_timeline(fc_dir, tmp_path):
    srv = _server(fc_dir)
    try:
        _, meta = srv.submit("demo", {"x": np.ones((3, 6), "f4")})
    finally:
        srv.stop()
    evs = flight.default_recorder().events(kind="trace")
    kinds = {e["kind"] for e in evs}
    assert kinds == {"trace.span", "trace.request"}
    req_ev = [e for e in evs if e["kind"] == "trace.request"][-1]
    assert req_ev["trace"] == meta["trace"]["trace_id"]
    assert req_ev["trace_kind"] == "predict"
    assert req_ev["decomposition"]["components_ms"]
    assert req_ev["padded_rows"] == 1  # 3 rows -> bucket 4

    # the unified chrome export puts request spans on their own host
    # track, on the SAME bridged clock as the executor spans
    out = str(tmp_path / "merged.json")
    profiler.export_unified_chrome_trace(out, trace_dir="")
    doc = json.load(open(out))
    spans = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    trace_spans = [e for e in spans if e["name"].startswith("trace:")]
    request_spans = [e for e in spans
                     if e["name"].startswith("request:")]
    exec_spans = [e for e in spans
                  if e["name"].startswith("executor.")]
    assert trace_spans and request_spans and exec_spans
    assert {e["name"] for e in trace_spans} >= {
        "trace:queue.wait", "trace:batch.exec", "trace:debatch"}
    # one clock: every span inside a narrow shared window
    all_ts = [e["ts"] for e in trace_spans + exec_spans]
    assert max(all_ts) - min(all_ts) < 60e6
    # the trace track is its own tid, separate from the executor's
    assert {e["tid"] for e in trace_spans} == {4}
    assert {e["tid"] for e in exec_spans} == {0}


def test_crash_dump_carries_inflight_requests(tmp_path):
    FLAGS.trace_requests = True
    FLAGS.monitor = True
    tr = tracing.start("predict", "demo")
    tr.add_span("queue.wait", time.time(), time.time() + 0.01)
    path = str(tmp_path / "dump.jsonl")
    flight.default_recorder().dump(path=path, trigger="manual")
    header = json.loads(open(path).readline())
    assert header["open_trace_count"] == 1
    (entry,) = header["open_traces"]
    assert entry["trace"] == tr.trace_id
    assert entry["model"] == "demo" and entry["spans"] == 2
    # finishing clears the in-flight state
    tr.finish()
    flight.default_recorder().dump(path=path, trigger="manual")
    header = json.loads(open(path).readline())
    assert "open_trace_count" not in header


def test_trace_report_requests_section(fc_dir, tmp_path):
    import sys

    sys.path.insert(0, "tools")
    try:
        import trace_report
    finally:
        sys.path.pop(0)

    srv = _server(fc_dir)
    try:
        _, meta = srv.submit("demo", {"x": np.ones((3, 6), "f4")})
    finally:
        srv.stop()
    out = str(tmp_path / "merged.json")
    profiler.export_unified_chrome_trace(out, trace_dir="")
    text = trace_report.report(json.load(open(out)))
    assert "Requests (request-scoped traces" in text
    assert meta["trace"]["trace_id"][:16] in text
    assert "Padding waste" in text
    assert "demo:predict: 1" in text


def test_span_cap_bounds_trace_memory():
    FLAGS.trace_requests = True
    tr = tracing.start("predict", "demo")
    for i in range(tracing.MAX_SPANS + 40):
        tr.add_span("queue.wait", time.time(), dur=0.001)
    assert len(tr.spans) == tracing.MAX_SPANS
    assert tr.dropped_spans == 41  # +1: the root occupies a slot
    tr.finish()
    assert tr.to_json()["dropped_spans"] == 41
