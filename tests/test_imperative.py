"""Imperative (dygraph) mode (reference: paddle/fluid/imperative/,
python/paddle/fluid/tests/unittests/test_imperative.py — to_variable,
Layer.forward, backward, gradients)."""

import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu import imperative
from paddle_tpu.core import framework as fw

rng = np.random.RandomState(3)


def test_eager_ops_execute_immediately():
    with imperative.guard():
        x = imperative.to_variable(np.array([[1.0, 2.0], [3.0, 4.0]],
                                            "float32"))
        y = layers.scale(x, scale=2.0, bias=1.0)
        np.testing.assert_allclose(y.numpy(), [[3.0, 5.0], [7.0, 9.0]])
        z = layers.reduce_sum(y)
        np.testing.assert_allclose(z.numpy(), [24.0])


def test_eager_backward_matches_manual():
    with imperative.guard():
        xv = rng.randn(3, 4).astype("float32")
        x = imperative.to_variable(xv)
        y = layers.tanh(x)
        loss = layers.reduce_sum(layers.square(y))
        loss.backward()
        g = x.gradient()
        expected = 2 * np.tanh(xv) * (1 - np.tanh(xv) ** 2)
        np.testing.assert_allclose(g, expected, rtol=1e-5)


def test_eager_layer_with_parameters_and_grads():
    class MLP(imperative.Layer):
        def forward(self, x):
            h = layers.fc(x, size=8, act="relu")
            return layers.fc(h, size=1)

    with imperative.guard(seed=0):
        x = imperative.to_variable(rng.randn(4, 6).astype("float32"))
        mlp = MLP()
        out = mlp(x)
        assert out.numpy().shape == (4, 1)
        loss = layers.mean(layers.square(out))
        loss.backward()
        params = mlp.parameters()
        assert len(params) == 4  # 2x (w, b)
        grads = [p.gradient() for p in params]
        assert all(g is not None for g in grads)
        assert any(np.abs(g).sum() > 0 for g in grads)


def test_eager_grads_match_compiled_path():
    """Same net, same params: eager backward == append_backward grads."""
    xv = rng.randn(5, 3).astype("float32")

    # eager path first — capture its initialized weight + grad
    with imperative.guard():
        xe = imperative.to_variable(xv)
        he = layers.fc(xe, size=2, bias_attr=False)
        w = imperative.parameters()[0]
        wv = np.asarray(imperative.value_of(w))
        le = layers.mean(layers.square(layers.tanh(he)))
        le.backward()
        gw = w.gradient()

    # compiled/program path with the same weight value
    x = layers.data(name="x", shape=[3], dtype="float32")
    h = layers.fc(x, size=2, bias_attr=False)
    loss = layers.mean(layers.square(layers.tanh(h)))
    from paddle_tpu.core.backward import append_backward

    append_backward(loss)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    w_name = pt.default_main_program().all_parameters()[0].name
    pt.global_scope().set_var(w_name, wv)
    (gw_ref,) = exe.run(
        feed={"x": xv}, fetch_list=[fw.grad_var_name(w_name)])

    np.testing.assert_allclose(gw, np.asarray(gw_ref), rtol=1e-5, atol=1e-6)


def test_eager_training_loop_reduces_loss():
    with imperative.guard(seed=1):
        w_true = rng.randn(4, 1).astype("float32")
        losses = []
        for step in range(30):
            xv = rng.randn(16, 4).astype("float32")
            yv = xv @ w_true
            x = imperative.to_variable(xv)
            y = imperative.to_variable(yv, stop_gradient=True)
            pred = layers.fc(x, size=1,
                             param_attr=pt.param_attr.ParamAttr(
                                 name="lin_w"),
                             bias_attr=pt.param_attr.ParamAttr(
                                 name="lin_b"))
            loss = layers.mean(layers.square(pred - y))
            loss.backward()
            losses.append(float(loss.numpy().reshape(-1)[0]))
            imperative.apply_sgd(lr=0.05)
            imperative.clear_gradients()
        assert losses[-1] < losses[0] * 0.1, (losses[0], losses[-1])


def test_eager_rejects_sub_block_ops():
    import pytest

    with imperative.guard():
        i = layers.fill_constant([1], "float32", 0.0)
        limit = layers.fill_constant([1], "float32", 3.0)
        cond = layers.less_than(i, limit)
        w = layers.While(cond)
        with pytest.raises(NotImplementedError):
            with w.block():
                layers.increment(i, in_place=True)
                layers.less_than(i, limit, cond=cond)
