"""DLPack interop: zero-copy tensor exchange with torch/numpy/cupy/...

Reference gap (VERDICT round-5 missing #4): the reference exchanged
tensors with other frameworks by round-tripping through numpy on the
host; DLPack is the modern zero-copy contract, and JAX arrays already
speak it (jax.dlpack).  These two wrappers exist so `paddle_tpu`
user code has a framework-level spelling — scope vars, fetch results
(when return_numpy=False) and feed values are all jax.Arrays here.

    import torch
    t = torch.arange(6).reshape(2, 3)
    x = paddle_tpu.from_dlpack(t)          # zero-copy on shared devices
    t2 = torch.from_dlpack(paddle_tpu.to_dlpack(x))

Copy semantics are DLPack's: producer and consumer must share a device
(CPU<->CPU, or framework CUDA<->CUDA); TPU-resident arrays export only
after an explicit device_get by the caller — DLPack has no TPU device
type, and hiding a device->host copy behind a "zero-copy" API would be a
lie.
"""

from __future__ import annotations


def to_dlpack(array):
    """Export a framework tensor (jax.Array, or anything numpy-coercible
    that already lives on a DLPack-capable device) for another framework.

    Returns the array itself when it implements `__dlpack__` (the modern
    protocol consumers like `torch.from_dlpack` prefer — keeps lifetime
    management in the producer), else a legacy DLPack capsule."""
    import jax

    if not isinstance(array, jax.Array):
        import jax.numpy as jnp

        array = jnp.asarray(array)
    if hasattr(array, "__dlpack__"):
        return array
    return jax.dlpack.to_dlpack(array)  # older jax: capsule form


def from_dlpack(external):
    """Import a tensor from any DLPack producer (torch.Tensor, numpy
    array, cupy array, a raw capsule...) as a jax.Array, zero-copy when
    devices are shared."""
    import jax

    return jax.dlpack.from_dlpack(external)
