"""Numerics instrumentation pass: rewrite a Program so every step also
computes tensor-health statistics, fetched as ONE packed [N, 4] tensor.

The reference framework's FLAGS_check_nan_inf (operator.cc:943) walks
every operator's outputs on the host after each op executes — free in an
interpreter, impossible in a whole-block XLA world where ops never
individually return to the host.  This pass is that capability rebuilt
as a graph rewrite (same family as memory/recompute.py): behind
FLAGS_check_numerics, each instrumented tensor gets one fused
`numerics_stat` reduction ([nonfinite_count, abs_max, abs_mean, l2] —
ops/numerics_ops.py) and all rows pack into a single stats tensor the
executor fetches alongside the user's fetches — one device->host
transfer per step, not N.

Two levels:

  * `summary` — training-dynamics telemetry: per-parameter grad rows,
    post-update weight rows, and update rows (delta stats over
    `ParamOut - Param`, via a pre-optimizer snapshot `assign`), feeding
    the per-param-group gauges monitor/numerics.py publishes (grad-norm,
    weight-norm, update-to-weight ratio, overflow counts).
  * `locate` — full per-op-output instrumentation: every op output in
    the global block and in depth-1 `while` sub-blocks gets a row, so
    the first op in topological order with a non-finite output can be
    named.  Used by the watchdog's failing-step replay
    (monitor/numerics.py locate_in_program), not for steady-state runs.

Packing splits by op role so `Executor.run_accumulated`'s prefix/suffix
partition stays clean: rows produced by non-Optimize ops pack into
`__numerics_stats__` (prefix — returned stacked [K, N, 4] per
micro-batch), rows produced by Optimize-role ops pack into
`__numerics_stats_opt__` (suffix — single post-update [M, 4]).  Each
stat op carries its producer's role attr.

While sub-blocks ride loop-carried accumulators: the [4] row var is
seeded by `numerics_zeros` in the outer block right before the `while`
op, and the in-loop `numerics_stat` combines with the carry
([add, max, max, max]) — `lower_while` picks the var up as a carry
(written + present in the outer env) and pushes the final value back to
the outer env, so inner tensors are observed with zero per-iteration
host traffic.  `conditional_block` branches and nested (depth>1) while
loops return only their declared outputs, so their interiors are NOT
instrumented — a NaN born there localizes to the control-flow op itself.

Zero-cost contract (the recompute-pass idiom): `maybe_instrument` reads
FLAGS.check_numerics ONCE and returns None without touching the program
when it is 'off' — graphs stay byte-identical (same fingerprint), no
registry or flight writes, asserted in tests/test_numerics.py.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ..core import framework as fw
from ..ops.numerics_ops import STAT_WIDTH

# the packed stats tensors the executor auto-fetches; order matters
# (non-Optimize rows first — program order)
STATS_VAR = "__numerics_stats__"
STATS_OPT_VAR = "__numerics_stats_opt__"

# op types whose outputs are never instrumented (our own machinery)
_SELF_TYPES = frozenset({"numerics_stat", "numerics_pack", "numerics_zeros"})

_GRAD_SUFFIX = "@GRAD"  # fw.grad_var_name's suffix


def is_instrumented(program) -> bool:
    return getattr(program, "_numerics_meta", None) is not None


def param_group(name: str) -> str:
    """Param-group key for gauge aggregation: the var-name prefix up to
    the first '.' (layer_helper names params '<layer>.w_0' / '<layer>.b_0',
    so this groups by layer)."""
    return name.split(".", 1)[0] if "." in name else name


def _role(op) -> int:
    try:
        return int(op.attrs.get(fw.OpRole.ROLE_ATTR_NAME, 0))
    except (TypeError, ValueError):
        return 0


def _is_opt(op) -> bool:
    return bool(_role(op) & fw.OpRole.Optimize)


class _Builder:
    """Accumulates stat rows for one instrumentation run over a program."""

    def __init__(self, program, level: str):
        self.program = program
        self.block = program.global_block()
        self.level = level
        self.k = 0          # unique-name counter
        self.pos = 0        # global topological row position
        self.rows: List[str] = []       # non-Optimize row var names
        self.rows_opt: List[str] = []
        self.meta: List[dict] = []      # rows for STATS_VAR, in order
        self.meta_opt: List[dict] = []
        self.while_blocks = 0

    def _row_var(self) -> str:
        name = f"__numerics_s{self.k}"
        self.k += 1
        self.block.create_var(name=name, shape=(STAT_WIDTH,),
                              dtype="float32", stop_gradient=True)
        return name

    def stat_op(self, block, x_name: str, *, ref: Optional[str] = None,
                acc: Optional[str] = None, out: Optional[str] = None,
                role: int = 0, meta: Optional[dict] = None) -> fw.Operator:
        """Build (don't splice) a numerics_stat op + its row var/meta."""
        out = out or self._row_var()
        inputs = {"X": [x_name]}
        if ref:
            inputs["Ref"] = [ref]
        if acc:
            inputs["Acc"] = [acc]
        attrs = {}
        if role:
            attrs[fw.OpRole.ROLE_ATTR_NAME] = role
        op = fw.Operator(block, "numerics_stat", inputs, {"Out": [out]},
                         attrs)
        m = dict(meta or {})
        m.setdefault("kind", "op")
        m["pos"] = self.pos
        self.pos += 1
        m["row_var"] = out
        if role & fw.OpRole.Optimize:
            self.rows_opt.append(out)
            self.meta_opt.append(m)
        else:
            self.rows.append(out)
            self.meta.append(m)
        return op

    def finish(self) -> dict:
        """Append the pack op(s), stamp program attrs, return the report."""
        block = self.block
        stats_vars = []
        if self.rows:
            block.create_var(name=STATS_VAR,
                             shape=(len(self.rows), STAT_WIDTH),
                             dtype="float32", stop_gradient=True)
            pack = fw.Operator(block, "numerics_pack",
                               {"X": list(self.rows)},
                               {"Out": [STATS_VAR]},
                               {"n": len(self.rows)})
            block.ops.append(pack)
            stats_vars.append(STATS_VAR)
        if self.rows_opt:
            block.create_var(name=STATS_OPT_VAR,
                             shape=(len(self.rows_opt), STAT_WIDTH),
                             dtype="float32", stop_gradient=True)
            pack = fw.Operator(block, "numerics_pack",
                               {"X": list(self.rows_opt)},
                               {"Out": [STATS_OPT_VAR]},
                               {"n": len(self.rows_opt),
                                fw.OpRole.ROLE_ATTR_NAME:
                                    fw.OpRole.Optimize})
            block.ops.append(pack)
            stats_vars.append(STATS_OPT_VAR)
        meta = {
            "level": self.level,
            "tensors": {STATS_VAR: self.meta,
                        STATS_OPT_VAR: self.meta_opt},
            "while_blocks": self.while_blocks,
        }
        self.program._numerics_meta = meta
        self.program._numerics_stats_vars = stats_vars
        block._bump()
        return {
            "level": self.level,
            "rows": len(self.rows) + len(self.rows_opt),
            "tensors": {n: len(meta["tensors"][n]) for n in stats_vars},
            "while_blocks": self.while_blocks,
        }


def _instrument_locate(b: _Builder) -> None:
    """Every op output in the global block + depth-1 while sub-blocks."""
    block = b.block
    new_ops: List[fw.Operator] = []
    for op_idx, op in enumerate(list(block.ops)):
        if op.type in _SELF_TYPES:
            new_ops.append(op)
            continue
        role = _role(op)
        if op.type == "while":
            sub = op.attrs.get("sub_block")
            if sub is not None:
                new_ops.extend(
                    _instrument_while(b, op_idx, op, sub, role))
        new_ops.append(op)
        seen = set()
        for slot in op.outputs:
            for name in op.outputs[slot]:
                if not name or name in seen:
                    continue
                seen.add(name)
                sop = b.stat_op(
                    block, name, role=role,
                    meta={"block": block.idx, "op_index": op_idx,
                          "op_type": op.type, "var": name})
                new_ops.append(sop)
    block.ops[:] = new_ops


def _instrument_while(b: _Builder, op_idx: int, while_op, sub,
                      role: int) -> List[fw.Operator]:
    """Instrument a depth-1 while sub-block via loop-carried accumulator
    rows.  Returns the `numerics_zeros` seed ops that must precede the
    while op in the outer block."""
    b.while_blocks += 1
    seeds: List[fw.Operator] = []
    new_sub_ops: List[fw.Operator] = []
    for in_idx, iop in enumerate(list(sub.ops)):
        new_sub_ops.append(iop)
        if iop.type in _SELF_TYPES:
            continue
        seen = set()
        for slot in iop.outputs:
            for name in iop.outputs[slot]:
                if not name or name in seen:
                    continue
                seen.add(name)
                acc = b._row_var()  # lives in the OUTER block
                seeds.append(fw.Operator(b.block, "numerics_zeros", {},
                                         {"Out": [acc]}))
                sop = b.stat_op(
                    sub, name, acc=acc, out=acc, role=role,
                    meta={"block": sub.idx, "op_index": in_idx,
                          "op_type": iop.type, "var": name,
                          "in_loop": True,
                          "while_op_index": op_idx})
                new_sub_ops.append(sop)
    sub.ops[:] = new_sub_ops
    return seeds


def _instrument_summary(b: _Builder) -> None:
    """Grad / weight / update rows for every Parameter the program's
    Optimize suffix updates (plus grad rows for params with a grad but no
    optimizer op — e.g. a forward+backward-only program)."""
    block = b.block
    params = {p.name for p in block.all_parameters()}

    # last writer of each param grad (grad-accumulation sums rewrite the
    # same name; the LAST write is the grad the optimizer consumes)
    last_grad_writer: Dict[str, int] = {}
    opt_op_for_param: Dict[str, int] = {}
    for i, op in enumerate(block.ops):
        if op.type in _SELF_TYPES:
            continue
        if not _is_opt(op):
            for name in op.output_arg_names():
                if name.endswith(_GRAD_SUFFIX) and \
                        name[: -len(_GRAD_SUFFIX)] in params:
                    last_grad_writer[name] = i
        else:
            for pname in op.inputs.get("Param", []):
                if pname in params and pname not in opt_op_for_param and \
                        pname in op.outputs.get("ParamOut", []):
                    opt_op_for_param[pname] = i

    before: Dict[int, List[fw.Operator]] = {}
    after: Dict[int, List[fw.Operator]] = {}

    def _emit(idx, op, where):
        where.setdefault(idx, []).append(op)

    for gname, idx in sorted(last_grad_writer.items(),
                             key=lambda kv: (kv[1], kv[0])):
        pname = gname[: -len(_GRAD_SUFFIX)]
        sop = b.stat_op(block, gname, role=_role(block.ops[idx]),
                        meta={"kind": "grad", "param": pname,
                              "group": param_group(pname), "var": gname,
                              "block": block.idx, "op_index": idx,
                              "op_type": block.ops[idx].type})
        _emit(idx, sop, after)

    for pname, idx in sorted(opt_op_for_param.items(),
                             key=lambda kv: (kv[1], kv[0])):
        opt_op = block.ops[idx]
        role = _role(opt_op)
        # optimizer updates are in-place (ParamOut name == Param name),
        # so the pre-update value must be snapshotted for the delta row
        snap = f"__numerics_prev{b.k}"
        b.k += 1
        pvar = block._find_var_recursive(pname)
        b.block.create_var(name=snap,
                           shape=getattr(pvar, "shape", None),
                           dtype=getattr(pvar, "dtype", "float32"),
                           stop_gradient=True)
        asn = fw.Operator(block, "assign", {"X": [pname]},
                          {"Out": [snap]},
                          {fw.OpRole.ROLE_ATTR_NAME: role})
        _emit(idx, asn, before)
        upd = b.stat_op(block, pname, ref=snap, role=role,
                        meta={"kind": "update", "param": pname,
                              "group": param_group(pname), "var": pname,
                              "block": block.idx, "op_index": idx,
                              "op_type": opt_op.type})
        _emit(idx, upd, after)
        wgt = b.stat_op(block, pname, role=role,
                        meta={"kind": "weight", "param": pname,
                              "group": param_group(pname), "var": pname,
                              "block": block.idx, "op_index": idx,
                              "op_type": opt_op.type})
        _emit(idx, wgt, after)

    new_ops: List[fw.Operator] = []
    for i, op in enumerate(block.ops):
        new_ops.extend(before.get(i, ()))
        new_ops.append(op)
        new_ops.extend(after.get(i, ()))
    block.ops[:] = new_ops


def instrument_program(program, level: str) -> dict:
    """Mutate `program` IN PLACE with `level` instrumentation
    ('summary' | 'locate'); returns a report dict.  Idempotent guard:
    an already-instrumented program raises (re-instrumenting would
    double-count rows)."""
    if level not in ("summary", "locate"):
        raise ValueError(
            f"check_numerics level must be 'off', 'summary' or 'locate', "
            f"got {level!r}")
    if is_instrumented(program):
        raise ValueError("program is already numerics-instrumented")
    b = _Builder(program, level)
    if level == "locate":
        _instrument_locate(b)
    else:
        _instrument_summary(b)
    return b.finish()


def maybe_instrument(program, level: Optional[str] = None):
    """Flag-gated entry point (FLAGS_check_numerics).  Off (the default)
    costs ONE flag read and leaves the program byte-identical — the
    zero-cost contract, same shape as memory.maybe_optimize_memory.

    'locate' arms the executor's failing-step capture+replay but does
    NOT rewrite the steady-state program (full per-op instrumentation
    is replay-only); 'summary' rewrites in place.  Returns the report
    dict, or None when off."""
    if level is None:
        from ..flags import FLAGS

        level = FLAGS.check_numerics
    if not level or level == "off":
        return None
    if level == "locate":
        # steady-state graph unchanged: the watchdog-trip replay
        # (monitor/numerics.py) instruments a CLONE of the failing
        # program; arming is flag-driven inside the executor
        return {"level": "locate", "rows": 0, "deferred": True}
    return instrument_program(program, level)


__all__ = [
    "STATS_VAR",
    "STATS_OPT_VAR",
    "instrument_program",
    "maybe_instrument",
    "is_instrumented",
    "param_group",
]
