#!/usr/bin/env python
"""Benchmark driver entry: trains the flagship models on the available chip
and prints ONE JSON line {"metric", "value", "unit", "vs_baseline"}.

vs_baseline compares against the reference's best committed ResNet-50
training throughput (84.08 img/s, 2-socket Xeon 6148 + MKL-DNN,
benchmark/IntelOptimizedPaddle.md:40-46 — see BASELINE.md; the reference
repo has no committed GPU ResNet-50 number)."""

import argparse
import json
import sys
import time

import numpy as np

REFERENCE_RESNET50_IMGS_PER_SEC = 84.08


def bench_resnet50(batch_size=64, steps=20, warmup=3, image_size=224,
                   depth=50):
    import paddle_tpu as pt
    from paddle_tpu.models import resnet as R

    prog, startup = pt.Program(), pt.Program()
    with pt.program_guard(prog, startup):
        img, label, avg_cost, acc, _ = R.build_train_net(
            class_dim=1000, image_shape=(3, image_size, image_size),
            depth=depth, lr=0.1,
        )
    scope = pt.Scope()
    exe = pt.Executor()
    exe.run(startup, scope=scope)

    import jax.numpy as jnp

    rng = np.random.RandomState(0)
    x = rng.rand(batch_size, 3, image_size, image_size).astype("float32")
    y = rng.randint(0, 1000, (batch_size, 1)).astype("int64")
    # device-resident feeds: input upload overlaps compute in real pipelines
    feed = {"image": jnp.asarray(x), "label": jnp.asarray(y)}

    for _ in range(warmup):
        exe.run(prog, feed=feed, fetch_list=[avg_cost], scope=scope)
    t0 = time.perf_counter()
    for _ in range(steps):
        (loss,) = exe.run(prog, feed=feed, fetch_list=[avg_cost], scope=scope)
    # fetch forces sync (loss returned as numpy)
    dt = time.perf_counter() - t0
    ips = batch_size * steps / dt
    return ips, float(loss)


def bench_transformer(batch_size=16, seq_len=256, steps=10, warmup=3):
    import paddle_tpu as pt
    from paddle_tpu.models import transformer as T

    prog, startup = pt.Program(), pt.Program()
    with pt.program_guard(prog, startup):
        avg_cost, _, feeds = T.transformer(
            src_vocab_size=32000, trg_vocab_size=32000, max_length=seq_len,
            n_layer=6, n_head=8, d_key=64, d_value=64, d_model=512,
            d_inner_hid=2048, dropout_rate=0.1, src_seq_len=seq_len,
            trg_seq_len=seq_len,
        )
        pt.optimizer.Adam(learning_rate=1e-4).minimize(avg_cost)
    scope = pt.Scope()
    exe = pt.Executor()
    exe.run(startup, scope=scope)
    batch = T.make_batch(batch_size, seq_len, seq_len, 8, 32000, 32000)
    for _ in range(warmup):
        exe.run(prog, feed=batch, fetch_list=[avg_cost], scope=scope)
    t0 = time.perf_counter()
    for _ in range(steps):
        (loss,) = exe.run(prog, feed=batch, fetch_list=[avg_cost], scope=scope)
    dt = time.perf_counter() - t0
    tokens_per_sec = batch_size * seq_len * 2 * steps / dt  # src+trg tokens
    return tokens_per_sec, float(loss)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="resnet50",
                   choices=["resnet50", "transformer"])
    p.add_argument("--smoke", action="store_true",
                   help="tiny shapes for a fast correctness pass")
    p.add_argument("--batch-size", type=int, default=None)
    p.add_argument("--steps", type=int, default=None)
    args = p.parse_args()

    if args.model == "resnet50":
        if args.smoke:
            ips, loss = bench_resnet50(batch_size=8, steps=3, warmup=1,
                                       image_size=64, depth=18)
        else:
            ips, loss = bench_resnet50(
                batch_size=args.batch_size or 64, steps=args.steps or 20
            )
        print(json.dumps({
            "metric": "resnet50_train_images_per_sec_per_chip",
            "value": round(ips, 2),
            "unit": "images/sec",
            "vs_baseline": round(ips / REFERENCE_RESNET50_IMGS_PER_SEC, 3),
        }))
    else:
        tps, loss = bench_transformer(
            batch_size=args.batch_size or (2 if args.smoke else 16),
            seq_len=64 if args.smoke else 256,
            steps=args.steps or (2 if args.smoke else 10),
        )
        print(json.dumps({
            "metric": "transformer_base_train_tokens_per_sec_per_chip",
            "value": round(tps, 2),
            "unit": "tokens/sec",
            "vs_baseline": 0.0,
        }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
