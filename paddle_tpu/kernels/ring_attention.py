"""Ring attention: exact attention over sequences sharded across devices.

New TPU capability beyond the reference (SURVEY.md §5.7: the reference's max
context is bounded by single-device memory; nothing shards the sequence
axis).  Design: the sequence axis is sharded over a mesh axis; each device
holds a Q shard and streams K/V shards around the ring with
`jax.lax.ppermute` over ICI, combining per-shard partial softmax results with
the same online-softmax algebra as flash attention (kernels/attention.py).
Communication overlaps compute: while device d processes K/V shard s, shard
s+1 is in flight.

Entry point `ring_attention(q, k, v, mesh, axis_name, causal)` is meant to be
called under `shard_map` (or via ring_attention_sharded which wraps it).
"""

from __future__ import annotations

import functools


def _local_attention_chunk(q, k, v, scale, mask=None):
    """Partial attention of local q against one k/v chunk.
    Returns (numerator, denominator, rowmax) in fp32."""
    import jax.numpy as jnp

    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask, s, -1e30)
    m = s.max(axis=-1)  # [b,h,q]
    p = jnp.exp(s - m[..., None])
    num = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    den = p.sum(axis=-1)
    return num, den, m


def ring_attention(q, k, v, axis_name, scale=1.0, causal=False):
    """Runs INSIDE shard_map: q,k,v are the per-device sequence shards
    [b, h, t_local, d].  Exact softmax attention over the full (sharded)
    sequence via ring passes of K/V."""
    import jax
    import jax.numpy as jnp

    n = jax.lax.axis_size(axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    t_local = q.shape[2]

    fwd_perm = [(i, (i + 1) % n) for i in range(n)]

    def mask_for(kv_idx):
        if not causal:
            return None
        # global positions: q_pos = my_idx*t_local + iq ; k_pos = kv_idx*t_local + ik
        iq = jnp.arange(t_local)[:, None] + my_idx * t_local
        ik = jnp.arange(t_local)[None, :] + kv_idx * t_local
        return (iq >= ik)[None, None]  # [1,1,tq,tk]

    def body(i, carry):
        k_cur, v_cur, num, den, m = carry
        kv_idx = (my_idx - i) % n
        c_num, c_den, c_m = _local_attention_chunk(
            q, k_cur, v_cur, scale, mask_for(kv_idx)
        )
        m_new = jnp.maximum(m, c_m)
        alpha = jnp.exp(m - m_new)
        beta = jnp.exp(c_m - m_new)
        num = num * alpha[..., None] + c_num * beta[..., None]
        den = den * alpha + c_den * beta
        # rotate K/V around the ring (device i sends to i+1)
        k_next = jax.lax.ppermute(k_cur, axis_name, fwd_perm)
        v_next = jax.lax.ppermute(v_cur, axis_name, fwd_perm)
        return k_next, v_next, num, den, m_new

    b, h, t, d = q.shape
    num0 = jnp.zeros((b, h, t, d), jnp.float32)
    den0 = jnp.zeros((b, h, t), jnp.float32)
    m0 = jnp.full((b, h, t), -jnp.inf, jnp.float32)
    carry = (k, v, num0, den0, m0)
    # static unroll (n is a python int) lets XLA overlap ppermute with compute
    for i in range(n):
        carry = body(i, carry)
    _, _, num, den, _ = carry
    return (num / den[..., None]).astype(q.dtype)


def ring_attention_sharded(q, k, v, mesh, axis_name="sp", scale=1.0,
                           causal=False):
    """Whole-array entry: q,k,v are global [b, h, T, d] arrays; the sequence
    dim is sharded over `axis_name` of `mesh`; returns global output with the
    same sharding."""
    import jax
    from jax.sharding import PartitionSpec as P

    spec = P(None, None, axis_name, None)
    fn = jax.shard_map(
        functools.partial(ring_attention, axis_name=axis_name, scale=scale,
                          causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)
