#!/usr/bin/env python
"""perf_report: render the static cost model's attribution for the
bundled programs, and falsify it against measured bench records.

Three products on stdout:

  1. Per-program roofline tables (paddle_tpu/analysis/costmodel): per-op
     FLOPs + HBM bytes, compute/memory/launch classification against the
     resolved device model, and the predicted step time
     `max(flops/peak, bytes/bw) + n_launches * overhead`.
  2. The decode program's LAUNCH-BOUND FRACTION — ROADMAP item 1's
     go/no-go number for the decode megakernel, CPU-estimable today.
  3. With --bench <record.json> (bench.py / run_ci smoke artifacts):
     predicted-vs-measured step-time ratios for every record whose
     config carries the cost probe's fields — the model is falsifiable,
     not just quotable.

Usage:
  python tools/perf_report.py                          # all programs
  python tools/perf_report.py --programs decode
  python tools/perf_report.py --bench ci_artifacts/bench_smoke.json \
      --bench ci_artifacts/bench_decode_smoke.json
  python tools/perf_report.py --device "TPU v5e"       # what-if retarget
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

PROGRAMS = ("mnist", "transformer_smoke", "decode")


def _build_mnist(batch_size):
    """The bench_mnist one-step train program (smoke shapes)."""
    import paddle_tpu as pt
    from paddle_tpu.models import mnist as M

    prog, startup = pt.Program(), pt.Program()
    with pt.program_guard(prog, startup):
        _, _, avg_cost, _, _ = M.build_train_net()
        pt.optimizer.Adam(learning_rate=1e-3).minimize(avg_cost)
    return [("mnist", prog, batch_size)]


def _build_transformer_smoke(batch_size):
    """The bench_transformer --smoke train program (tiny config,
    seq 64)."""
    import paddle_tpu as pt
    from paddle_tpu.models import transformer as T

    prog, startup = pt.Program(), pt.Program()
    with pt.program_guard(prog, startup):
        avg_cost, _, _ = T.transformer(
            src_vocab_size=256, trg_vocab_size=256, max_length=64,
            n_layer=2, n_head=4, d_key=16, d_value=16, d_model=64,
            d_inner_hid=128, dropout_rate=0.1, src_seq_len=64,
            trg_seq_len=64)
        pt.optimizer.Adam(learning_rate=1e-4).minimize(avg_cost)
    return [("transformer_smoke", prog, batch_size)]


def _build_decode(batch_size):
    """The bench_decode --smoke program pair (tiny config): the
    per-token decode program is the megakernel candidate; prefill rides
    along for contrast."""
    from paddle_tpu.models import transformer as T

    progs = T.build_generation_programs(
        src_vocab_size=1000, trg_vocab_size=1000, max_length=50,
        n_layer=2, n_head=4, d_key=32, d_value=32, d_model=128,
        d_inner_hid=256, batch_size=batch_size, src_seq_len=32,
        max_out_len=16, bos_id=0, eos_id=-1, strategy="greedy")
    return [("decode", progs.decode, batch_size),
            ("decode.prefill", progs.prefill, batch_size)]


_BUILDERS = {
    "mnist": _build_mnist,
    "transformer_smoke": _build_transformer_smoke,
    "decode": _build_decode,
}

_DEFAULT_BATCH = {"mnist": 64, "transformer_smoke": 2, "decode": 1}


def roofline_section(names, device_name, batch_size, top):
    from paddle_tpu.analysis.costmodel import (
        cost_program,
        resolve_device_model,
    )

    device = resolve_device_model(device_name)
    out, decode_cost = [], None
    for prog_name in names:
        if prog_name not in _BUILDERS:
            raise SystemExit(f"unknown program {prog_name!r} "
                             f"(choices: {', '.join(PROGRAMS)})")
        bs = batch_size or _DEFAULT_BATCH[prog_name]
        for tag, prog, b in _BUILDERS[prog_name](bs):
            cost = cost_program(prog, name=tag, batch_size=b,
                                device=device)
            out.append(f"== Roofline: {tag} (batch {b}) ==")
            out.append(cost.table(top=top))
            out.append("")
            if tag == "decode":
                decode_cost = cost
    if decode_cost is not None:
        out.append("== Decode launch-bound fraction (ROADMAP item 1) ==")
        out.append(
            f"  {decode_cost.launch_bound_fraction:.1%} of the predicted "
            f"per-token step is dispatch overhead "
            f"({decode_cost.n_launches} launches x "
            f"{decode_cost.device.launch_overhead_s * 1e6:.1f} us on "
            f"{decode_cost.device.name}, {decode_cost.device.source}); "
            f"fusion-corrected {decode_cost.launch_bound_fraction_fused:.1%} "
            f"({decode_cost.n_launches_fused} launches after charging "
            f"compiler-fused epilogue ops zero) — the corrected number is "
            f"the one to hold against the executor's measured dispatch_s "
            f"split, and FLAGS_fused_decode_step's megastep path is what "
            f"drives it down")
        out.append("")
    return "\n".join(out)


def _measured_step_seconds(rec):
    """Seconds one execution of the record's one-step program took,
    derived from the record's throughput number and its config —
    None when the record shape is not derivable."""
    cfg = rec.get("config") or {}
    value = rec.get("value")
    unit = rec.get("unit", "")
    batch = cfg.get("batch")
    if not value or not batch:
        return None
    if unit in ("images/sec", "examples/sec"):
        return batch / value
    if unit == "tokens/sec":
        if str(rec.get("metric", "")).startswith("decode_tokens_per_sec"):
            # one decode-program call emits `batch` tokens (one per lane)
            return batch / value
        seq = cfg.get("seq_len")
        return (batch * seq / value) if seq else None
    return None


def load_records(paths):
    recs = []
    for p in paths:
        with open(p) as f:
            for line in f:
                line = line.strip()
                if not line.startswith("{"):
                    continue
                try:
                    recs.append(json.loads(line))
                except ValueError:
                    continue
    return recs


def predicted_vs_measured(recs):
    """One line per record carrying cost-probe fields: predicted (static
    model) vs measured (the bench number) step time and their ratio.
    Ratio >> 1 = the model overcharges (fusion merged launches, shapes
    overstated); << 1 = hidden costs the model misses.  pred_f/ratio_f
    repeat the prediction with the fusion-corrected launch count
    (cost_predicted_step_us_fused) — the r13 decode bias fix: epilogue
    ops XLA fuses into their producers no longer charge a dispatch."""
    rows = []
    for rec in recs:
        cfg = rec.get("config") or {}
        pred_us = cfg.get("cost_predicted_step_us")
        meas_s = _measured_step_seconds(rec)
        if pred_us is None or meas_s is None or meas_s <= 0:
            continue
        pred_f = cfg.get("cost_predicted_step_us_fused")
        rows.append((rec["metric"], pred_us, meas_s * 1e6,
                     pred_us / (meas_s * 1e6),
                     pred_f,
                     (pred_f / (meas_s * 1e6)) if pred_f else None,
                     cfg.get("cost_launch_bound_fraction"),
                     cfg.get("cost_device", "?")))
    if not rows:
        return ("== Predicted vs measured ==\n  (no records with cost "
                "fields — run bench.py from this tree; the cost probe "
                "stamps config.cost_predicted_step_us)\n")
    out = ["== Predicted vs measured (per one-step program call) =="]
    out.append(f"  {'metric':44s} {'pred us':>10s} {'meas us':>10s} "
               f"{'ratio':>7s} {'pred_f':>10s} {'ratio_f':>7s} "
               f"{'launch%':>8s}  device")
    for m, p, s, r, pf, rf, lf, dev in rows:
        lf_s = f"{lf:.1%}" if lf is not None else "?"
        pf_s = f"{pf:10.1f}" if pf is not None else f"{'?':>10s}"
        rf_s = f"{rf:7.3f}" if rf is not None else f"{'?':>7s}"
        out.append(f"  {m:44s} {p:10.1f} {s:10.1f} {r:7.3f} {pf_s} {rf_s} "
                   f"{lf_s:>8s}  {dev}")
    out.append("")
    return "\n".join(out)


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--programs", default=",".join(PROGRAMS),
                    help=f"comma list of {', '.join(PROGRAMS)}; "
                         f"'none' skips the static tables")
    ap.add_argument("--bench", action="append", default=[],
                    metavar="RECORD_JSON",
                    help="bench/smoke JSON-lines artifact(s) for the "
                         "predicted-vs-measured section (repeatable)")
    ap.add_argument("--device", default=None,
                    help="device model name (default: FLAGS_device_model "
                         "or auto-detect; 'cpu-host' off-chip)")
    ap.add_argument("--batch-size", type=int, default=None)
    ap.add_argument("--top", type=int, default=8,
                    help="heaviest-ops rows per table")
    args = ap.parse_args()

    names = [] if args.programs == "none" else [
        n for n in args.programs.split(",") if n]
    if names:
        print(roofline_section(names, args.device, args.batch_size,
                               args.top))
    if args.bench:
        print(predicted_vs_measured(load_records(args.bench)))
    elif not names:
        print("nothing to do: --programs none and no --bench",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
