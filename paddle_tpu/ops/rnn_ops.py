"""RNN ops via lax.scan (reference: dynamic_lstm (lstm_op.cc),
dynamic_gru (gru_op.cc), gru_unit_op.cc, lstm_unit_op.cc,
cudnn_lstm_op.cu.cc; the graph-level RecurrentOp/StepScopes loop of
recurrent_op.cc:39 is subsumed by while/scan).

TPU-first: time-major lax.scan compiles to one fused loop; variable lengths
are handled by masking state updates past each row's length (the reference
sorts by length via lod_rank_table — unnecessary here)."""

from __future__ import annotations

from ..core.registry import register


def _jnp():
    import jax.numpy as jnp

    return jnp


def _act(name):
    import jax
    import jax.numpy as jnp

    return {
        "sigmoid": jax.nn.sigmoid,
        "tanh": jnp.tanh,
        "relu": jax.nn.relu,
        "identity": lambda x: x,
    }[name]


def _length_mask(ins, x):
    jnp = _jnp()
    lens = ins.get("Length", [None])
    if lens and lens[0] is not None:
        return lens[0].reshape(-1).astype("int32")
    return jnp.full((x.shape[0],), x.shape[1], "int32")


def _reverse_valid(x, length):
    """Reverse each row's valid prefix only (padding stays in place) — keeps
    length-masking correct under is_reverse."""
    jnp = _jnp()
    t = x.shape[1]
    ar = jnp.arange(t)[None, :]
    idx = jnp.where(ar < length[:, None], length[:, None] - 1 - ar, ar)
    idx = idx.reshape((x.shape[0], t) + (1,) * (x.ndim - 2)).astype("int32")
    return jnp.take_along_axis(x, idx, axis=1)


def _lstm_scan(ctx, ins, proj=None):
    """Shared LSTM machinery (bias/peephole slicing, activations, length
    masking, is_reverse, H0/C0, the c,i,f,o gate step, one lax.scan).
    `proj`: optional (w_proj, proj_act) — the LSTMP recurrent projection
    applied to h before it becomes the carried state (lstmp_op.cc).
    Returns (states [B, T, state_dim], cells [B, T, D])."""
    import jax

    jnp = _jnp()
    x = ins["Input"][0]
    w = ins["Weight"][0]
    bias = ins.get("Bias", [None])[0]
    b, t, d4 = x.shape
    d = d4 // 4
    length = _length_mask(ins, x)
    use_peep = ctx.attr("use_peepholes", False)
    gate_act = _act(ctx.attr("gate_activation", "sigmoid"))
    cell_act = _act(ctx.attr("cell_activation", "tanh"))
    cand_act = _act(ctx.attr("candidate_activation", "tanh"))
    is_reverse = ctx.attr("is_reverse", False)

    if bias is not None:
        x = x + bias.reshape(1, 1, -1)[:, :, : 4 * d]
        if use_peep:
            peep = bias.reshape(-1)[4 * d:]
            w_ic, w_fc, w_oc = peep[:d], peep[d: 2 * d], peep[2 * d: 3 * d]
        else:
            w_ic = w_fc = w_oc = None
    else:
        w_ic = w_fc = w_oc = None

    xs = _reverse_valid(x, length) if is_reverse else x
    xs = jnp.swapaxes(xs, 0, 1)  # [T, B, 4D]
    step_ids = jnp.arange(t)

    state_dim = proj[0].shape[1] if proj is not None else d
    h0 = ins.get("H0", [None])[0]
    c0 = ins.get("C0", [None])[0]
    h_init = h0 if h0 is not None else jnp.zeros((b, state_dim), x.dtype)
    c_init = c0 if c0 is not None else jnp.zeros((b, d), x.dtype)

    def step(carry, inp):
        h_prev, c_prev = carry
        xt, tid = inp
        gates = xt + h_prev @ w  # [B, 4D], columns c,i,f,o
        gc, gi, gf, go = jnp.split(gates, 4, axis=1)
        if use_peep and w_ic is not None:
            gi = gi + c_prev * w_ic
            gf = gf + c_prev * w_fc
        i = gate_act(gi)
        f = gate_act(gf)
        c = f * c_prev + i * cand_act(gc)
        if use_peep and w_oc is not None:
            go = go + c * w_oc
        o = gate_act(go)
        h = o * cell_act(c)
        if proj is not None:
            w_proj, proj_act = proj
            h = proj_act(h @ w_proj)  # [B, P]
        valid = (tid < length)[:, None]
        h = jnp.where(valid, h, h_prev)
        c = jnp.where(valid, c, c_prev)
        return (h, c), (h, c)

    _, (hs, cs) = jax.lax.scan(step, (h_init, c_init), (xs, step_ids))
    hs = jnp.swapaxes(hs, 0, 1)
    cs = jnp.swapaxes(cs, 0, 1)
    if is_reverse:
        hs = _reverse_valid(hs, length)
        cs = _reverse_valid(cs, length)
    return hs, cs


@register("dynamic_lstm")
def lower_dynamic_lstm(ctx, ins):
    """Input: [B, T, 4D] pre-projected gates input (reference lstm_op.cc
    expects x already times W_x); Weight [D, 4D] recurrent; Bias [1, 4D]
    (+ peephole terms if use_peepholes).  Gate column order c,i,f,o —
    candidate first, matching the reference weight layout
    (math/detail/lstm_kernel.h; nn.py:397 documents {W_ch, W_ih, W_fh,
    W_oh}) so reference-trained weights port unchanged."""
    hs, cs = _lstm_scan(ctx, ins)
    return {"Hidden": [hs], "Cell": [cs]}


@register("dynamic_gru")
def lower_dynamic_gru(ctx, ins):
    """Input [B, T, 3D] pre-projected; Weight [D, 3D] laid out as
    [update|reset (2D), candidate (D)] (reference gru_op.cc)."""
    import jax

    jnp = _jnp()
    x = ins["Input"][0]
    w = ins["Weight"][0]
    bias = ins.get("Bias", [None])[0]
    b, t, d3 = x.shape
    d = d3 // 3
    length = _length_mask(ins, x)
    gate_act = _act(ctx.attr("gate_activation", "sigmoid"))
    cand_act = _act(ctx.attr("activation", "tanh"))
    is_reverse = ctx.attr("is_reverse", False)
    origin_mode = ctx.attr("origin_mode", False)

    if bias is not None:
        x = x + bias.reshape(1, 1, -1)

    w_g = w[:, : 2 * d]  # update+reset recurrent weights
    w_c = w[:, 2 * d:]  # candidate recurrent weights

    xs = jnp.flip(x, axis=1) if is_reverse else x
    xs = jnp.swapaxes(xs, 0, 1)
    step_ids = jnp.arange(t)
    h0 = ins.get("H0", [None])[0]
    h_init = h0 if h0 is not None else jnp.zeros((b, d), x.dtype)

    def step(h_prev, inp):
        xt, tid = inp
        xu, xr, xc = jnp.split(xt, 3, axis=1)
        gr = h_prev @ w_g
        u = gate_act(xu + gr[:, :d])
        r = gate_act(xr + gr[:, d:])
        c = cand_act(xc + (r * h_prev) @ w_c)
        if origin_mode:
            h = u * h_prev + (1 - u) * c
        else:
            h = (1 - u) * h_prev + u * c
        valid = (tid < length)[:, None]
        h = jnp.where(valid, h, h_prev)
        return h, h

    h_last, hs = jax.lax.scan(step, h_init, (xs, step_ids))
    hs = jnp.swapaxes(hs, 0, 1)
    if is_reverse:
        hs = jnp.flip(hs, axis=1)
    return {"Hidden": [hs]}


@register("gru_unit")
def lower_gru_unit(ctx, ins):
    import jax

    jnp = _jnp()
    x = ins["Input"][0]  # [B, 3D]
    h_prev = ins["HiddenPrev"][0]
    w = ins["Weight"][0]
    bias = ins.get("Bias", [None])[0]
    d = h_prev.shape[1]
    if bias is not None:
        x = x + bias.reshape(1, -1)
    gate_act = _act({1: "sigmoid", 2: "tanh", 0: "identity", 3: "relu"}.get(
        ctx.attr("gate_activation", 1), "sigmoid") if isinstance(
        ctx.attr("gate_activation", 1), int) else ctx.attr("gate_activation"))
    cand_act = _act({1: "sigmoid", 2: "tanh", 0: "identity", 3: "relu"}.get(
        ctx.attr("activation", 2), "tanh") if isinstance(
        ctx.attr("activation", 2), int) else ctx.attr("activation"))
    xu, xr, xc = jnp.split(x, 3, axis=1)
    gr = h_prev @ w[:, : 2 * d]
    u = gate_act(xu + gr[:, :d])
    r = gate_act(xr + gr[:, d:])
    c = cand_act(xc + (r * h_prev) @ w[:, 2 * d:])
    h = u * c + (1 - u) * h_prev
    gate = jnp.concatenate([u, r, c], axis=1)
    return {"Hidden": [h], "Gate": [gate], "ResetHiddenPrev": [r * h_prev]}


@register("lstm_unit")
def lower_lstm_unit(ctx, ins):
    import jax

    jnp = _jnp()
    x = ins["X"][0]  # [B, 4D]
    c_prev = ins["C_prev"][0]
    forget_bias = ctx.attr("forget_bias", 0.0)
    gi, gf, gc, go = jnp.split(x, 4, axis=1)
    i = jax.nn.sigmoid(gi)
    f = jax.nn.sigmoid(gf + forget_bias)
    c = f * c_prev + i * jnp.tanh(gc)
    h = jax.nn.sigmoid(go) * jnp.tanh(c)
    return {"C": [c], "H": [h]}


@register("lstmp")
def lower_lstmp(ctx, ins):
    """LSTM with a recurrent projection layer (reference lstmp_op.cc:
    r_t = proj_act(h_t @ P); the recurrence runs over the PROJECTED state,
    so Weight is [P, 4D]).  Shares the gate/peephole/masking/is_reverse
    core with dynamic_lstm (_lstm_scan)."""
    w_proj = ins["ProjWeight"][0]
    proj_act = _act(ctx.attr("proj_activation", "tanh"))
    rs, cs = _lstm_scan(ctx, ins, proj=(w_proj, proj_act))
    return {"Projection": [rs], "Cell": [cs]}
