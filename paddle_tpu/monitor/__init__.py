"""Runtime telemetry subsystem (reference role: the glog VLOG counters +
platform/profiler.h host ranges + the benchmark/fluid metric prints; none of
which exposed a scrapeable registry — this is the production-serving gap
named in ROADMAP.md).

Five pieces:

  * `registry.py` — a thread-safe metrics registry (counters, gauges,
    histograms with bounded buckets) with Prometheus-text and JSONL
    exposition.  A process-wide default registry backs the module-level
    `counter()/gauge()/histogram()` helpers.
  * `step.py` — `StepMonitor`, per-step training telemetry (loss,
    examples/sec, tokens/sec, rolling MFU via `profiler.cost_analysis` or
    analytic FLOPs) written as BENCH-format-compatible JSONL.
  * `flight.py` — the flight recorder: a bounded ring of structured
    runtime events (steps, compile/run spans, recompile causes, feed
    stalls, collective traces) dumped as JSONL on crash / SIGTERM /
    watchdog trip, so a dead run leaves a black box.
  * `watchdog.py` — anomaly detection fed by StepMonitor: NaN/Inf loss,
    loss-spike z-score, throughput collapse, and a hang monitor on a
    daemon thread; actions log / dump / raise.
  * `serve.py` — stdlib-http exposition: /metrics (Prometheus), /health,
    /flight (last-N events), behind FLAGS.monitor_port.
  * `numerics.py` — the monitor half of the FLAGS_check_numerics tier:
    per-param-group training-dynamics gauges from the in-graph stats
    fetch (analysis/numerics.py), amp overflow accounting, and the
    failing-step capture/replay that names the first op with a
    non-finite output on a watchdog nan_loss trip.
  * instrumentation call-sites live in the runtime itself
    (`core/executor.py` compile/run/recompile, `data_feed.py` queue
    gauges, `inference.py` request histograms, `parallel/distributed.py`
    collective counters), every one gated on `FLAGS.monitor` so the hot
    paths pay nothing when telemetry is off.

Usage:

    from paddle_tpu.flags import FLAGS
    FLAGS.monitor = True                      # or env FLAGS_monitor=1
    ... run training ...
    import paddle_tpu.monitor as monitor
    print(monitor.default_registry().prometheus_text())
"""

from .registry import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    DEFAULT_BUCKETS,
    counter,
    gauge,
    histogram,
    default_registry,
    enabled,
)
from .registry import SloTracker  # noqa: F401
from .step import StepMonitor  # noqa: F401
from . import flight  # noqa: F401
from .flight import FlightRecorder  # noqa: F401
from .watchdog import Watchdog, WatchdogError  # noqa: F401
from . import serve  # noqa: F401
from . import numerics  # noqa: F401
from . import tracing  # noqa: F401
from .tracing import RequestTrace, TraceStore  # noqa: F401
