"""Inference serving: Predictor with an AOT executable cache + the
BN-fold inference optimization pass.

Reference parity:
  * PaddlePredictor / NativeConfig — inference/api/paddle_api.h:153,200,
    api/api_impl.h:34 (NativePaddlePredictor): load a saved model once,
    then serve many Run() calls with no per-call graph work.
  * AnalysisPredictor pass pipeline — api/analysis_predictor.h:45,
    analysis/analyzer.cc: IR optimization before serving; the first pass
    delivered here is conv/fc + batch_norm folding, the reference's
    inference_transpiler.py:1 / conv_bn_fuse_pass.cc.

TPU-first: the "executable cache" is the Executor's fingerprint-keyed XLA
compile cache — Run() re-traces nothing after the first call per feed
signature; parameters stay resident in the Predictor's private Scope (HBM)
across calls, mirroring ir_params_sync_among_devices_pass.cc's
params-frozen-to-device behavior.
"""

from __future__ import annotations

import contextlib
import os
from typing import Dict, List, Optional

import numpy as np

from . import io
from .core import framework as fw
from .core.executor import CPUPlace, Executor, Scope


def _consumers(block: fw.Block, name: str) -> List[fw.Operator]:
    return [op for op in block.ops if name in op.input_arg_names()]


def _fold_bn_into(block, scope, idx, bn_op, prod_op) -> bool:
    """Fold `bn_op` (at op index `idx`) into its producer conv2d/mul.
    Returns True on success; mutates program + scope."""
    if prod_op.type == "conv2d":
        # the BN must normalize the conv's channel axis: its data_layout
        # has to agree with the conv's data_format
        if (bn_op.attr("data_layout", "NCHW")
                != prod_op.attr("data_format", "NCHW")):
            return False
        w_name = prod_op.input("Filter")[0]
        out_axis = 0  # filter is OIHW for either data_format
    elif prod_op.type == "mul":
        w_name = prod_op.input("Y")[0]
        out_axis = 1  # [in, out]
    else:
        return False

    w_var = scope.find_var(w_name)
    if w_var is None:
        return False
    gamma = np.asarray(scope.find_var(bn_op.input("Scale")[0]))
    beta = np.asarray(scope.find_var(bn_op.input("Bias")[0]))
    mean = np.asarray(scope.find_var(bn_op.input("Mean")[0]))
    var = np.asarray(scope.find_var(bn_op.input("Variance")[0]))
    eps = bn_op.attr("epsilon", 1e-5)

    w = np.asarray(w_var)
    orig_dtype = w.dtype
    factor = (gamma / np.sqrt(var.astype("float64") + eps)).astype("float64")
    bshape = [1] * w.ndim
    bshape[out_axis] = -1
    scope.set_var(
        w_name,
        (w.astype("float64") * factor.reshape(bshape)).astype(orig_dtype),
    )
    fold_bias = (
        beta.astype("float64") - mean.astype("float64") * factor
    ).astype(orig_dtype)

    bias_name = fw.unique_name(f"{w_name}.bn_fold_bias")
    block.create_var(
        name=bias_name, shape=list(fold_bias.shape),
        dtype=str(fold_bias.dtype), persistable=True,
    )
    scope.set_var(bias_name, fold_bias)

    y_name = bn_op.output("Y")[0]
    x_name = bn_op.input("X")[0]
    block.remove_op(idx)
    # channel axis of the producer's output: conv2d NCHW -> 1, NHWC -> -1;
    # mul output [.., C] -> -1
    if prod_op.type == "conv2d":
        axis = -1 if prod_op.attr("data_format", "NCHW") == "NHWC" else 1
    else:
        axis = -1
    block.insert_op(
        idx,
        "elementwise_add",
        inputs={"X": [x_name], "Y": [bias_name]},
        outputs={"Out": [y_name]},
        attrs={"axis": axis},
    )
    return True


def inference_transpile(program: fw.Program, scope: Scope) -> int:
    """Fold batch_norm (inference mode) into the preceding conv2d/mul
    weights: W' = W * gamma/sqrt(var+eps); +bias' = beta - mean*that
    (reference: transpiler/inference_transpiler.py:1, ir/conv_bn_fuse_pass.cc).

    Mutates `program` and the parameter values in `scope`; returns the
    number of batch_norm ops folded.  Only valid for inference programs
    (clone(for_test=True) / load_inference_model output)."""
    block = program.global_block()
    folded = 0
    changed = True
    while changed:
        changed = False
        producers: Dict[str, tuple] = {}
        for i, op in enumerate(block.ops):
            for n in op.output_arg_names():
                producers[n] = (i, op)
        for i, op in enumerate(block.ops):
            if op.type != "batch_norm":
                continue
            x_name = op.input("X")[0]
            prod = producers.get(x_name)
            if prod is None:
                continue
            _, prod_op = prod
            # the conv output must feed only this BN (otherwise other
            # consumers would see the refolded weights)
            if len(_consumers(block, x_name)) != 1:
                continue
            if _fold_bn_into(block, scope, i, op, prod_op):
                folded += 1
                changed = True
                break
    return folded


AOT_DIRNAME = "__aot__"
# v2: executables are serialized WITHOUT buffer donation.  v1 bundles
# baked the executor's donate_argnums aliasing into the payload, and
# jax's deserialized-Compiled path lacks the donation bookkeeping that
# marks consumed arrays deleted — running one returns state arrays
# aliasing freed buffers (use-after-free; nondeterministic corruption
# under serving load).  Loaders REJECT v1 bundles (JIT fallback).
AOT_VERSION = 2


def _feed_signature(feed_names, feed):
    return tuple(
        (n, tuple(np.asarray(feed[n]).shape), str(np.asarray(feed[n]).dtype))
        for n in feed_names
    )


def _aot_trees(n_feed, n_rw, n_ro, needs_key, n_fetch, n_state):
    """Reconstruct the executable's in/out pytree structures from the
    executor calling convention — fn((feed_list, rw_list, ro_list[, key]),
    {}) -> (fetch_list, state_list).  Rebuilding them from counts keeps the
    MANIFEST pickle-free (JSON + raw XLA payload).  SECURITY: the payload
    itself is NOT safe — jax's deserialize_and_load runs an unrestricted
    unpickler over it, so loading a bundle from an untrusted model
    directory can execute arbitrary code.  That is why Predictor defaults
    use_aot=False (explicit opt-in for trusted artifacts)."""
    import jax

    args = ([0] * n_feed, [0] * n_rw, [0] * n_ro)
    if needs_key:
        args = args + (0,)
    in_tree = jax.tree_util.tree_structure((args, {}))
    out_tree = jax.tree_util.tree_structure(([0] * n_fetch, [0] * n_state))
    return in_tree, out_tree


def export_aot_bundle(dirname, feed_examples, place=None) -> int:
    """Serialize AOT-compiled executables for the saved model at `dirname`
    (reference gap: the C++ predictor serves without the framework in the
    loop, api/paddle_api.h:153 — the TPU-native analogue is an XLA
    executable serialized NEXT TO the save_inference_model artifact, so a
    serving process loads and runs it with NO program re-trace).

    feed_examples: list of feed dicts (one per signature to pre-compile).
    Writes `<dirname>/__aot__/sig_<i>.json` manifests + `sig_<i>.xla`
    payloads; returns how many were exported.  Loading falls back to the
    normal retrace path when a bundle does not match the runtime
    (jax/platform change) — see Predictor.

    SECURITY: the sig_<i>.xla payload is deserialized via jax's
    serialize_executable, which uses pickle under the hood — a bundle is a
    TRUSTED artifact (like a pickle checkpoint), and Predictor only loads
    one when constructed with use_aot=True."""
    import json

    import jax
    from jax.experimental import serialize_executable as se

    with _persistent_cache_disabled():
        return _export_aot_bundle(dirname, feed_examples, place, jax, se,
                                  json)


def reset_compilation_cache_singleton():
    """Reset jax's persistent-compilation-cache singleton: jax memoizes
    cache-enablement at first compile, so flipping
    jax_compilation_cache_dir without this leaves the old cache live.
    Best-effort private-API workaround, shared by export (cache OFF
    around bundle serialization) and the serving server (cache ON at
    startup) — keep the jax-upgrade fix in this one place."""
    try:
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()
    except Exception:  # noqa: BLE001 — private API: best-effort
        pass


@contextlib.contextmanager
def _persistent_cache_disabled():
    """Disable jax's persistent compilation cache for the duration.

    An executable LOADED from the persistent cache re-serializes as a
    thin reference to in-process jit symbols (XLA:CPU deserialize then
    fails with "Symbols not found" in any other process), so
    export_aot_bundle must compile its payloads fresh — a bundle's whole
    point is surviving the process that wrote it."""
    import jax

    try:
        prev = jax.config.jax_compilation_cache_dir
    except AttributeError:
        prev = None

    if prev is None:
        # a live singleton can outlast config=None
        reset_compilation_cache_singleton()
        yield
        return
    jax.config.update("jax_compilation_cache_dir", None)
    reset_compilation_cache_singleton()
    try:
        yield
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)
        reset_compilation_cache_singleton()


def _export_aot_bundle(dirname, feed_examples, place, jax, se, json) -> int:
    pred = Predictor(dirname, place=place, optimize=False, use_aot=False)
    exe, scope, program = pred._exe, pred._scope, pred._program
    out_dir = os.path.join(dirname, AOT_DIRNAME)
    os.makedirs(out_dir, exist_ok=True)
    n_ok = 0
    for i, feed in enumerate(feed_examples):
        # prime the executor cache (compiles exactly this signature); the
        # cache is cleared first so the single surviving entry IS this
        # signature's (a repeat signature would otherwise hit an older
        # entry and [-1] would grab the wrong executable)
        exe._cache.clear()
        exe.run(program, feed=feed, fetch_list=pred._fetch_names,
                scope=scope)
        entry = list(exe._cache.values())[-1]
        feed_names = sorted(feed)
        feed_vals = [exe._to_device_array(program, n, feed[n])
                     for n in feed_names]
        rw_vals = [scope.find_var(n) for n in entry.rw_state]
        ro_vals = [scope.find_var(n) for n in entry.ro_state]
        args = (feed_vals, rw_vals, ro_vals)
        if entry.needs_key:
            from .core.executor import prng_key

            args = args + (jax.random.fold_in(
                prng_key(program.random_seed or 0), 0),)
        # The executor's entry is jitted with donate_argnums=(1,) (rw
        # buffers update in place), and that input/output aliasing gets
        # baked into the serialized executable.  jax's deserialized
        # Compiled call path has none of the donation bookkeeping that
        # marks consumed arrays deleted, so a donating bundle returns
        # state arrays aliasing freed buffers — serving reads then race
        # the allocator (nondeterministic corruption under load).
        # Bundles therefore serialize a donation-FREE recompile; rw
        # state on inference programs is tiny (quant scalars, BN stats),
        # so the per-call copy is noise.
        entry_src = getattr(entry.fn, "__wrapped__", None)
        if entry_src is None:
            raise RuntimeError(
                "export_aot_bundle: executor entry is not a jitted "
                "function; cannot build a donation-free executable")
        payload, in_tree, out_tree = se.serialize(
            jax.jit(entry_src).lower(*args).compile())
        # the bundle stores only counts; verify the rebuilt trees match
        # the real ones so a convention drift fails at EXPORT, not serve
        want_in, want_out = _aot_trees(
            len(feed_vals), len(entry.rw_state), len(entry.ro_state),
            entry.needs_key, len(pred._fetch_names),
            len(entry.state_writes))
        if want_in != in_tree or want_out != out_tree:
            raise RuntimeError(
                "export_aot_bundle: executable pytree structure diverged "
                "from the executor calling convention — update _aot_trees")
        manifest = {
            "aot_version": AOT_VERSION,
            "signature": _feed_signature(feed_names, feed),
            "feed_names": feed_names,
            "rw_state": entry.rw_state,
            "ro_state": entry.ro_state,
            "state_writes": entry.state_writes,
            "needs_key": bool(entry.needs_key),
            "fetch_names": pred._fetch_names,
            "platform": jax.default_backend(),
            "n_devices": 1,  # Predictor executables are single-device
            "jax_version": jax.__version__,
        }
        with open(os.path.join(out_dir, f"sig_{i}.json"), "w") as f:
            json.dump(manifest, f)
        with open(os.path.join(out_dir, f"sig_{i}.xla"), "wb") as f:
            f.write(payload)
        n_ok += 1
    return n_ok


class Predictor:
    """Load-once, serve-many inference API (reference: PaddlePredictor
    api/paddle_api.h:153 + NativePaddlePredictor api_impl.h:34).

        pred = Predictor(dirname)            # load + optimize once
        outs = pred.run({"x": batch})        # AOT-cached; no retracing

    Each distinct feed signature (shapes/dtypes) compiles exactly once;
    `pred.compile_count` exposes the executable-cache size for tests.

    If the artifact carries an AOT bundle (save_inference_model
    aot_feed_examples / export_aot_bundle) AND the Predictor is built with
    `use_aot=True`, matching-signature calls serve straight from the
    DESERIALIZED XLA EXECUTABLE — the program is never re-traced, the
    reference's no-framework-in-the-loop serving property.  A bundle that
    fails to load (different platform / incompatible jax) falls back to
    the retrace path; `pred.aot_signatures` lists live bundles.

    use_aot defaults to FALSE: bundle deserialization runs jax's
    serialize_executable unpickler over the payload, so a bundle must be
    treated like a pickle file — opt in only for model directories you
    trust (ones your own pipeline exported).

    run() is THREAD-SAFE: the per-signature compile cache is guarded by
    per-key locks in the Executor (N concurrent callers x M signatures
    compile exactly M executables), stateless executables run fully
    concurrently, and stateful ones (scope write-backs, e.g. unfolded BN
    pass-through) serialize on the executor's ONE stateful-run lock —
    every feed signature (and every AOT bundle) donates the same scope
    arrays, so per-entry locking would race a use-after-donate.
    Required by the serving tier's dynamic batcher, whose scheduler
    threads drain into this cache."""

    def __init__(
        self,
        dirname: str,
        place=None,
        optimize: bool = True,
        model_filename: Optional[str] = None,
        params_filename: Optional[str] = None,
        use_aot: bool = False,
    ):
        self._scope = Scope()
        self._exe = Executor(place or CPUPlace())
        self._program, self._feed_names, self._fetch_vars = (
            io.load_inference_model(
                dirname, self._exe, scope=self._scope,
                model_filename=model_filename,
                params_filename=params_filename,
            )
        )
        self._fetch_names = [v.name for v in self._fetch_vars]
        self._aot: Dict[tuple, dict] = {}
        if use_aot:
            self._load_aot_bundles(dirname)
        self.folded_ops = 0
        # BN-folding mutates the SAME scope params the AOT executables were
        # compiled against (they bake the unfolded program in) — folding
        # under live bundles would silently corrupt AOT results.  XLA fuses
        # inference BN anyway, so the fold is skipped when bundles loaded.
        if optimize and not self._aot:
            self.folded_ops = inference_transpile(self._program, self._scope)

    def _load_aot_bundles(self, dirname):
        """Load serialized executables (use_aot=True opt-in ONLY).  The
        manifest is plain JSON, but deserialize_and_load runs an
        unrestricted unpickler over the sig_*.xla payload — loading a
        bundle from an untrusted model directory can execute arbitrary
        code, which is exactly why this path is off by default."""
        import glob
        import json

        import jax

        for path in sorted(
                glob.glob(os.path.join(dirname, AOT_DIRNAME,
                                       "sig_*.json"))):
            try:
                with open(path) as f:
                    bundle = json.load(f)
                if bundle["platform"] != jax.default_backend():
                    raise RuntimeError(
                        f"bundle platform {bundle['platform']} != runtime "
                        f"{jax.default_backend()}")
                if bundle.get("aot_version", 1) != AOT_VERSION:
                    raise RuntimeError(
                        f"bundle version {bundle.get('aot_version', 1)} != "
                        f"{AOT_VERSION} (v1 bundles donate buffers, which "
                        "corrupts state through jax's deserialized call "
                        "path — re-export with export_aot_bundle)")
                with open(path[:-5] + ".xla", "rb") as f:
                    payload = f.read()
                in_tree, out_tree = _aot_trees(
                    len(bundle["feed_names"]), len(bundle["rw_state"]),
                    len(bundle["ro_state"]), bundle["needs_key"],
                    len(bundle["fetch_names"]),
                    len(bundle["state_writes"]))
                from .kernels.jax_compat import deserialize_and_load

                loaded = deserialize_and_load(
                    payload, in_tree, out_tree,
                    n_devices=bundle.get("n_devices", 1))
                bundle["loaded"] = loaded
                # stateful bundles (scope write-backs) serialize on the
                # EXECUTOR's one stateful-run lock — the same scope
                # state backs every bundle signature AND the JIT
                # entries, so a per-bundle lock would let two
                # signatures interleave their write-backs; stateless
                # bundles run concurrently from serving threads
                bundle["run_lock"] = (self._exe._stateful_lock
                                      if bundle["state_writes"] else None)
                sig = tuple((n, tuple(shape), dt)
                            for n, shape, dt in bundle["signature"])
                self._aot[sig] = bundle
            except Exception as e:  # noqa: BLE001 — any mismatch: retrace
                from . import monitor
                from .log import vlog

                # degrade, never fail the model load: the JIT path serves
                # every signature the bundle would have; the NAMED counter
                # + flight event make the silent-retrace cause visible on
                # /metrics and /flight (serving satellite: a corrupted
                # sig_*.xla must not take the model down)
                if monitor.enabled():
                    monitor.counter("inference.aot_bundle_errors").inc()
                    from .monitor import flight as _mflight

                    _mflight.record(
                        "inference.aot_bundle_error", path=path,
                        error=f"{type(e).__name__}: {str(e)[:200]}")
                vlog(1, f"Predictor: AOT bundle {path} unusable "
                        f"({type(e).__name__}: {e}); falling back to "
                        "retrace")

    @property
    def feed_names(self) -> List[str]:
        return list(self._feed_names)

    @property
    def fetch_names(self) -> List[str]:
        return list(self._fetch_names)

    def feed_var_specs(self) -> Dict[str, tuple]:
        """{feed name: (declared shape tuple, dtype str)} from the loaded
        program — the leading batch dim is -1 for data-layer feeds.  The
        serving tier derives warmup shapes for its bucket ladder from
        this (serving/model.py)."""
        block = self._program.global_block()
        specs = {}
        for n in self._feed_names:
            v = block._find_var_recursive(n)
            specs[n] = (tuple(v.shape) if v is not None else None,
                        str(v.dtype) if v is not None else "float32")
        return specs

    def fetch_var_specs(self) -> List[tuple]:
        """[(fetch name, declared shape tuple or None, dtype str)] in
        fetch order — a leading -1 marks a batch-dependent output.  The
        serving batcher uses this to decide which outputs to slice back
        per coalesced request (serving/batcher.py)."""
        specs = []
        for v in self._fetch_vars:
            try:
                shape = tuple(v.shape)
            except (AttributeError, TypeError):
                shape = None
            specs.append((v.name, shape, str(getattr(v, "dtype", "float32"))))
        return specs

    @property
    def program(self) -> fw.Program:
        return self._program

    @property
    def compile_count(self) -> int:
        return len(self._exe._cache)

    @property
    def aot_signatures(self):
        return list(self._aot)

    def _run_aot(self, bundle, feed, return_numpy):
        import contextlib

        import jax

        feed_names = bundle["feed_names"]
        feed_vals = [self._exe._to_device_array(self._program, n, feed[n])
                     for n in feed_names]
        lock = bundle.get("run_lock")
        with lock if lock is not None else contextlib.nullcontext():
            rw_vals = [self._scope.find_var(n) for n in bundle["rw_state"]]
            ro_vals = [self._scope.find_var(n)
                       for n in bundle["ro_state"]]
            args = (feed_vals, rw_vals, ro_vals)
            if bundle["needs_key"]:
                from .core.executor import prng_key

                args = args + (jax.random.fold_in(
                    prng_key(self._program.random_seed or 0),
                    self._exe._next_run_id()),)
            fetches, new_state = bundle["loaded"](*args)
            for n, v in zip(bundle["state_writes"], new_state):
                self._scope.set_var(n, v)
        if return_numpy:
            return [np.asarray(v) for v in fetches]
        return list(fetches)

    def run(self, feed: Dict[str, np.ndarray], return_numpy: bool = True):
        """Serve one batch; a matching AOT bundle serves without any trace,
        otherwise compiles on first call per feed signature.

        With FLAGS.monitor on, each call lands in the
        `inference.request_seconds` latency histogram and the
        `inference.requests` counter (QPS = rate over scrapes)."""
        from . import monitor

        if not monitor.enabled():
            return self._run_impl(feed, return_numpy)

        import time as _time

        t0 = _time.perf_counter()
        try:
            outs = self._run_impl(feed, return_numpy)
        except Exception:
            monitor.counter("inference.request_errors").inc()
            raise
        dt = _time.perf_counter() - t0
        monitor.counter("inference.requests").inc()
        monitor.histogram("inference.request_seconds").observe(dt)
        # batch size comes from the FEED (fetches may be scalars/reduced)
        shape = getattr(feed.get(self._feed_names[0])
                        if self._feed_names else None, "shape", None)
        monitor.counter("inference.examples").inc(
            int(shape[0]) if shape else 1)
        return outs

    def _run_impl(self, feed, return_numpy):
        missing = [n for n in self._feed_names if n not in feed]
        if missing:
            raise KeyError(f"Predictor.run: missing feeds {missing}")
        if self._aot:
            feed = {n: feed[n] for n in self._feed_names}
            sig = _feed_signature(sorted(feed), feed)
            bundle = self._aot.get(sig)
            if bundle is not None:
                return self._run_aot(bundle, feed, return_numpy)
        return self._exe.run(
            self._program,
            feed={n: feed[n] for n in self._feed_names},
            fetch_list=self._fetch_names,
            scope=self._scope,
            return_numpy=return_numpy,
        )
