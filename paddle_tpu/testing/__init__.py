"""Test-support subpackage: deterministic fault injection (chaos.py).

Production modules import `paddle_tpu.testing.chaos` and call its hooks at
their fault points; every hook is a no-op unless FLAGS_chaos is on, so the
subpackage is safe (and free) to import from the runtime itself.
"""

from . import chaos  # noqa: F401
