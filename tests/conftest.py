import os

# Force a virtual 8-device CPU mesh for all tests (SURVEY.md §4 test plan:
# multi-host behavior simulated via xla_force_host_platform_device_count).
# PT_TEST_PLATFORM=axon runs the suite against the real (tunneled) TPU
# backend — exercises the actual compiled Mosaic kernel paths (the flash
# attention + in-kernel dropout tests pass there; multi-device tests need
# the CPU mesh).  Default is deterministic CPU.
_platform = os.environ.get("PT_TEST_PLATFORM", "cpu")
os.environ["JAX_PLATFORMS"] = _platform

# Executed-op recording for the op-contract gate (test_zz_op_gate.py):
# every op type the executor trace / imperative dispatcher lowers during
# the session lands in monitor.flight.lowered_op_types(), and the gate
# asserts registry.all_ops() ⊆ recorded ∪ CONTRACT_EXEMPT — enforcement
# by execution, not by grepping test files for op-name substrings.
os.environ.setdefault("FLAGS_record_lowered_ops", "1")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import numpy as np
import pytest

import jax

# A sitecustomize hook may force jax_platforms past the env var (axon image);
# the config update is authoritative as long as it runs before device init.
jax.config.update("jax_platforms", _platform)

# Numeric tests compare against float64 numpy references; use full-precision
# matmuls (the framework default is device-native fast precision).
jax.config.update("jax_default_matmul_precision", "highest")

# NOTE: do NOT enable jax's persistent compilation cache here.  It cuts
# suite wall time ~40% warm, but on this jaxlib the CPU backend's Pallas
# kernels lower to custom_calls whose callback pointers are baked into
# the serialized executable — a cache hit across processes returns a
# stale/wrong kernel (observed: fused-qkv checkpoint-interop loss
# mismatch, then a segfault on re-execution).  Re-evaluate on a jaxlib
# whose CPU thunk serialization is stable.


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: heavy tests excluded from the tier-1 quick gate "
        "(-m 'not slow'); tools/run_ci.sh runs the suite unfiltered")


def pytest_sessionfinish(session, exitstatus):
    """PT_DUMP_LOWERED_OPS=<path>: write the executed-op set observed this
    session (one op type per line) — the maintenance tool for the
    op-contract gate's CONTRACT_EXEMPT list."""
    path = os.environ.get("PT_DUMP_LOWERED_OPS")
    if path:
        from paddle_tpu.monitor import flight

        with open(path, "w") as f:
            f.write("\n".join(sorted(flight.lowered_op_types())) + "\n")


@pytest.fixture(autouse=True)
def _fresh_programs():
    """Give every test fresh default programs + scope."""
    import paddle_tpu as pt
    from paddle_tpu.core import framework as fw
    from paddle_tpu.core import executor as ex

    old_main = fw.switch_main_program(fw.Program())
    old_startup = fw.switch_startup_program(fw.Program())
    old_scope = ex._global_scope
    ex._global_scope = ex.Scope()
    with fw.guard_unique_name():
        yield
    fw.switch_main_program(old_main)
    fw.switch_startup_program(old_startup)
    ex._global_scope = old_scope
    # serving warmup legitimately flips the verify gate off for its
    # process ("off in hot serving paths after warmup"); don't let that —
    # or its process-global did-we-drop-it bookkeeping — leak across tests
    import sys as _sys

    from paddle_tpu.flags import FLAGS

    FLAGS.reset("verify_program")
    _sv = _sys.modules.get("paddle_tpu.serving.server")
    if _sv is not None:
        _sv._VERIFY_DROPPED[0] = False
