"""CLI: `python -m paddle_tpu.serving --model name=/path/to/export ...`

Boots an InferenceServer, warms every model's bucket ladder, prints ONE
machine-readable ready line to stdout —

    {"event": "serving_ready", "port": N, "models": [...]}

— then serves until SIGTERM/SIGINT (the CI gate and subprocess tests
parse the ready line for the ephemeral port).

SIGTERM triggers a GRACEFUL DRAIN (the load-balancer contract): /health
flips to "draining" (503 — LBs stop sending), new requests get 503,
in-flight and queued-admitted work completes up to
FLAGS_serving_drain_timeout_s, the flight recorder dumps with trigger
"drain", and the process exits 0.  SIGINT stops immediately (interactive
use).
"""

from __future__ import annotations

import argparse
import json
import signal
import sys
import threading


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.serving",
        description="multi-model inference server with dynamic batching")
    p.add_argument("--model", action="append", default=[],
                   metavar="NAME=DIR",
                   help="serve the exported model at DIR as NAME "
                        "(repeatable)")
    p.add_argument("--demo-generation", action="append", default=[],
                   metavar="NAME",
                   help="also serve the seeded tiny transformer "
                        "generation model as NAME (continuous "
                        "token-level batching at "
                        "POST /v1/models/NAME:generate; the CI smoke "
                        "and loadgen --generate target)")
    p.add_argument("--gen-slots", type=int, default=None,
                   help="cache-slot count (decode batch) for "
                        "--demo-generation models "
                        "(default FLAGS_serving_decode_slots)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8000,
                   help="0 picks an ephemeral port (printed in the ready "
                        "line)")
    p.add_argument("--buckets", default=None,
                   help="pad-to-bucket ladder, e.g. 1,2,4,8,16 "
                        "(default FLAGS_serving_buckets)")
    p.add_argument("--max-batch", type=int, default=None)
    p.add_argument("--max-wait-ms", type=float, default=None)
    p.add_argument("--use-aot", action="store_true",
                   help="load serialized AOT executable bundles — TRUSTED "
                        "artifacts only (pickle-based deserialization)")
    p.add_argument("--int8", action="append", default=[], metavar="NAME",
                   help="also serve an int8 replica of NAME (QAT-exported "
                        "models; selectable per request via precision)")
    p.add_argument("--no-optimize", action="store_true",
                   help="skip the BN-fold inference pass")
    p.add_argument("--no-warmup", action="store_true",
                   help="skip bucket-ladder pre-compilation (first "
                        "requests then pay the compiles)")
    p.add_argument("--cache-dir", default=None,
                   help="persistent XLA compilation cache dir "
                        "(default FLAGS_serving_cache_dir)")
    p.add_argument("--replicas", type=int, default=0,
                   help="fleet mode: supervise N replica subprocesses "
                        "(each serving the same models on an ephemeral "
                        "port) behind a health-driven router; this "
                        "process becomes supervisor + router and prints "
                        'a {"event": "router_ready", ...} line instead')
    p.add_argument("--router-port", type=int, default=None,
                   help="fleet mode: router listen port "
                        "(default FLAGS_router_port; 0 = ephemeral)")
    args = p.parse_args(argv)

    from paddle_tpu.flags import FLAGS
    from paddle_tpu.serving import InferenceServer, ModelConfig

    if args.cache_dir is not None:
        FLAGS.serving_cache_dir = args.cache_dir

    int8_names = set(args.int8)
    configs = []
    for spec in args.model:
        name, sep, dirname = spec.partition("=")
        if not sep or not name or not dirname:
            p.error(f"--model expects NAME=DIR, got {spec!r}")
        configs.append(ModelConfig(
            name=name, dirname=dirname, use_aot=args.use_aot,
            optimize=not args.no_optimize, int8=name in int8_names,
            buckets=args.buckets, max_batch=args.max_batch,
            max_wait_ms=args.max_wait_ms))
    unknown = int8_names - {c.name for c in configs}
    if unknown:
        p.error(f"--int8 names not among --model entries: {sorted(unknown)}")
    if not configs and not args.demo_generation:
        p.error("nothing to serve: pass --model and/or --demo-generation")

    if args.replicas > 0:
        return _run_fleet(args)

    server = InferenceServer(configs, host=args.host, port=args.port)
    if args.demo_generation:
        from paddle_tpu.serving.generation import \
            build_demo_generation_model

        for name in args.demo_generation:
            server.add_generation_model(
                build_demo_generation_model(name, slots=args.gen_slots))
    server.start(warmup=not args.no_warmup)
    print(json.dumps({
        "event": "serving_ready",
        "port": server.port,
        "host": args.host,
        "models": server.model_names,
    }), flush=True)

    done = threading.Event()
    sigs = []

    def _shutdown(signum, frame):
        sigs.append(signum)
        done.set()

    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(sig, _shutdown)
        except (ValueError, OSError):
            pass
    try:
        done.wait()
    finally:
        if sigs and sigs[0] == signal.SIGTERM:
            # graceful drain: readiness -> draining, new requests 503,
            # admitted work completes (bounded), flight dump, exit 0
            from paddle_tpu.monitor import flight

            drained = server.drain(reason="sigterm")
            flight.record("serving.drain_complete", drained=drained)
            flight.dump(trigger="drain",
                        extra={"drained": drained, "signal": "SIGTERM"})
        else:
            server.stop()
    return 0


def _replica_args(args) -> list:
    """Rebuild the per-replica CLI from the parsed fleet CLI (everything
    except the fleet-only and port arguments — the supervisor owns
    ports)."""
    out = []
    for spec in args.model:
        out += ["--model", spec]
    for name in args.demo_generation:
        out += ["--demo-generation", name]
    if args.gen_slots is not None:
        out += ["--gen-slots", str(args.gen_slots)]
    if args.buckets is not None:
        out += ["--buckets", args.buckets]
    if args.max_batch is not None:
        out += ["--max-batch", str(args.max_batch)]
    if args.max_wait_ms is not None:
        out += ["--max-wait-ms", str(args.max_wait_ms)]
    if args.use_aot:
        out += ["--use-aot"]
    for name in args.int8:
        out += ["--int8", name]
    if args.no_optimize:
        out += ["--no-optimize"]
    if args.no_warmup:
        out += ["--no-warmup"]
    if args.cache_dir is not None:
        out += ["--cache-dir", args.cache_dir]
    return out


def _run_fleet(args) -> int:
    """Fleet mode: this process is supervisor + router; the replicas are
    subprocesses of the SAME CLI without --replicas."""
    from paddle_tpu.monitor import flight
    from paddle_tpu.serving.fleet import ReplicaSupervisor
    from paddle_tpu.serving.router import Router

    from paddle_tpu.flags import FLAGS

    FLAGS.monitor = True  # a blind router is undebuggable (same stance
    #                       as the replica server)
    router = Router(host=args.host, port=args.router_port)
    sup = ReplicaSupervisor(_replica_args(args), n=args.replicas,
                            router=router, host=args.host)
    sup.start()
    print(json.dumps({
        "event": "router_ready",
        "port": router.port,
        "host": args.host,
        "replicas": args.replicas,
        "replica_ports": [sup.replica_port(f"r{i}")
                          for i in range(args.replicas)],
    }), flush=True)

    done = threading.Event()

    def _shutdown(signum, frame):
        done.set()

    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(sig, _shutdown)
        except (ValueError, OSError):
            pass
    try:
        done.wait()
    finally:
        flight.record("router.fleet_stop", replicas=args.replicas)
        sup.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
