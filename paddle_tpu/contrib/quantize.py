"""QAT program rewrite (reference:
python/paddle/fluid/contrib/quantize/quantize_transpiler.py:81
QuantizeTranspiler).

Inserts fake_quantize/fake_dequantize pairs around the quantizable ops'
inputs: weights use per-step abs_max, activations a moving-average abs-max
with persistable scale state initialized in the startup program.

Contract difference from the reference: call `training_transpile` BEFORE
optimizer.minimize() — the straight-through estimator lives inside the
fake-quant lowerings (ops/quant_ops.py), so append_backward differentiates
the rewritten program directly instead of the reference's separate grad-op
rewiring pass.
"""

from __future__ import annotations

from typing import Dict, Optional

from ..core import framework as fw

QUANTIZABLE_OPS = {
    "conv2d": ("Input", "Filter"),
    "depthwise_conv2d": ("Input", "Filter"),
    "mul": ("X", "Y"),
}


class QuantizeTranspiler:
    def __init__(
        self,
        weight_bits: int = 8,
        activation_bits: int = 8,
        activation_quantize_type: str = "moving_average_abs_max",
        weight_quantize_type: str = "abs_max",
        moving_rate: float = 0.9,
    ):
        if activation_quantize_type not in (
            "moving_average_abs_max", "abs_max"
        ):
            raise ValueError(
                f"unsupported activation_quantize_type "
                f"{activation_quantize_type!r}")
        if weight_quantize_type != "abs_max":
            raise ValueError("weight_quantize_type must be 'abs_max'")
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.activation_quantize_type = activation_quantize_type
        self.moving_rate = moving_rate

    # -- helpers ---------------------------------------------------------

    def _quant_abs_max(self, block, idx, name, bits):
        q = block.create_var(
            name=fw.unique_name(f"{name}.quantized"), dtype="float32")
        scale = block.create_var(
            name=fw.unique_name(f"{name}.scale"), dtype="float32")
        block.insert_op(
            idx,
            "fake_quantize_abs_max",
            inputs={"X": [name]},
            outputs={"Out": [q], "OutScale": [scale]},
            attrs={"bit_length": bits},
        )
        return q.name, scale.name

    def _quant_moving_average(self, block, startup, idx, name, bits):
        def state(suffix, init):
            v = block.create_var(
                name=fw.unique_name(f"{name}.{suffix}"),
                shape=[1], dtype="float32", persistable=True)
            v.stop_gradient = True  # scale state gets no cotangent
            sv = startup.global_block().create_var(
                name=v.name, shape=[1], dtype="float32", persistable=True)
            startup.global_block().append_op(
                "fill_constant",
                outputs={"Out": [sv]},
                attrs={"shape": [1], "value": init, "dtype": "float32"},
            )
            return v

        scale_in = state("quant_scale", 0.001)
        accum = state("quant_accum", 0.0)
        st = state("quant_state", 0.0)
        q = block.create_var(
            name=fw.unique_name(f"{name}.quantized"), dtype="float32")
        block.insert_op(
            idx,
            "fake_quantize_moving_average_abs_max",
            inputs={"X": [name], "InScale": [scale_in],
                    "InAccum": [accum], "InState": [st]},
            outputs={"Out": [q], "OutScale": [scale_in],
                     "OutAccum": [accum], "OutState": [st]},
            attrs={"bit_length": bits, "moving_rate": self.moving_rate},
        )
        return q.name, scale_in.name

    def _dequant(self, block, idx, name, scale_name, bits):
        out = block.create_var(
            name=fw.unique_name(f"{name}.dequantized"), dtype="float32")
        block.insert_op(
            idx,
            "fake_dequantize_max_abs",
            inputs={"X": [name], "Scale": [scale_name]},
            outputs={"Out": [out]},
            attrs={"max_range": float((1 << (bits - 1)) - 1),
                   "bit_length": bits},
        )
        return out.name

    # -- public ----------------------------------------------------------

    def training_transpile(
        self,
        program: Optional[fw.Program] = None,
        startup_program: Optional[fw.Program] = None,
    ) -> int:
        """Rewrite `program` in place; returns the number of quantized
        input slots.  Call before minimize()."""
        program = program or fw.default_main_program()
        startup = startup_program or fw.default_startup_program()
        block = program.global_block()
        params = {p.name for p in block.all_parameters()}

        dequantized: Dict[str, str] = {}
        count = 0
        i = 0
        while i < len(block.ops):
            op = block.ops[i]
            slots = QUANTIZABLE_OPS.get(op.type)
            if slots is None:
                i += 1
                continue
            for slot in slots:
                names = op.input(slot)
                if not names:
                    continue
                name = names[0]
                if name not in dequantized:
                    is_weight = name in params
                    bits = (self.weight_bits if is_weight
                            else self.activation_bits)
                    if is_weight or (
                        self.activation_quantize_type == "abs_max"
                    ):
                        qname, sname = self._quant_abs_max(
                            block, i, name, bits)
                    else:
                        qname, sname = self._quant_moving_average(
                            block, startup, i, name, bits)
                    i += 1
                    dq = self._dequant(block, i, qname, sname, bits)
                    i += 1
                    dequantized[name] = dq
                op.inputs[slot] = [dequantized[name]]
                count += 1
            block._bump()
            i += 1
        return count
