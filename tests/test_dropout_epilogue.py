"""Fused dropout-add epilogue (kernels/dropout_epilogue.py) + in-kernel
PRNG dropout paths.

The contract under test (ISSUE 4 acceptance):
  * statistical: keep-rate within a chi-square bound per implementation;
  * mask parity: forward and backward regenerate BIT-IDENTICAL keep-masks
    in each of the three implementations (Pallas kernel [interpret mode
    on CPU, compiled on TPU] and the pure-XLA fallback), and the
    interpret kernel matches the XLA fallback bit-for-bit (both hash the
    same (seed, flat index));
  * zero-cost-off: rate 0 compiles to the identical HLO as a plain add,
    and the models' graphs are unchanged by FLAGS.fused_dropout_add when
    dropout is off;
  * seed determinism across executor recompiles: the mask is a pure
    function of (program seed, run counter, rng_id) — a recompile (new
    fetch list -> new cache entry) with a checkpoint-restored RNG counter
    replays the mask bit-exactly (PR-3 fixture pattern).

The TPU hardware-PRNG variants (pltpu.prng_seed has no CPU/interpret
lowering in jax 0.4.37) are covered by the skipif-tpu class at the
bottom — they run on the driver's chip.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.flags import FLAGS
from paddle_tpu.kernels import dropout_epilogue, hash_rng

SEED = 12345

# implementation -> interpret argument for dropout_add on a CPU host:
# "kernel" runs the Pallas kernel in interpret mode, "xla" forces the
# pure-XLA fallback (interpret=False off-TPU fails _plan's backend check)
CPU_IMPLS = {"kernel": True, "xla": False}


def _seed():
    return jnp.asarray([SEED], jnp.uint32)


def _mask_of(out, residual):
    """Recover the keep-mask from dropout_add output (x strictly nonzero)."""
    return np.abs(np.asarray(out) - np.asarray(residual)) > 1e-7


class TestKeepRateChiSquare:
    @pytest.mark.parametrize("impl", sorted(CPU_IMPLS))
    @pytest.mark.parametrize("rate", [0.1, 0.5])
    def test_keep_rate_within_chi_square_bound(self, impl, rate):
        # 64 buckets of 2048 Bernoulli(1-rate) draws: chi2 ~ X^2_64,
        # 3-sigma bound 64 + 3*sqrt(128) ~ 98
        n_bucket, m = 64, 2048
        x = jnp.ones((n_bucket * m // 128, 128), jnp.float32)
        r = jnp.zeros_like(x)
        out = dropout_epilogue.dropout_add(
            x, r, rate, _seed(), interpret=CPU_IMPLS[impl])
        kept = _mask_of(out, r).reshape(n_bucket, m)
        obs = kept.sum(axis=1)
        exp = m * (1.0 - rate)
        var = m * (1.0 - rate) * rate
        chi2 = ((obs - exp) ** 2 / var).sum()
        assert chi2 < 110, (impl, rate, chi2)
        assert abs(kept.mean() - (1.0 - rate)) < 0.01

    def test_sites_decorrelated(self):
        # two stream seeds (two rng_ids): ~50% mask agreement
        key = jax.random.key(0, impl="rbg")
        x = jnp.ones((128, 128), jnp.float32)
        r = jnp.zeros_like(x)
        masks = []
        for rng_id in (1, 2):
            s = jnp.reshape(hash_rng.seed_from_key(key, rng_id), (1,))
            out = dropout_epilogue.dropout_add(x, r, 0.5, s, interpret=True)
            masks.append(_mask_of(out, r))
        agree = (masks[0] == masks[1]).mean()
        assert 0.45 < agree < 0.55, agree


class TestMaskParity:
    def test_interpret_kernel_matches_xla_bitwise(self):
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(4, 64, 128).astype("float32"))
        r = jnp.asarray(rng.randn(4, 64, 128).astype("float32"))
        outs = {
            impl: np.asarray(dropout_epilogue.dropout_add(
                x, r, 0.3, _seed(), interpret=interp))
            for impl, interp in CPU_IMPLS.items()
        }
        assert np.array_equal(outs["kernel"], outs["xla"])

    @pytest.mark.parametrize("impl", sorted(CPU_IMPLS))
    def test_fwd_bwd_regenerate_identical_mask(self, impl):
        """The gradient wrt x must be exactly scale on kept entries and
        exactly 0 on dropped ones — i.e. the backward regenerated the
        forward's mask bit-exactly; dres is the untouched cotangent."""
        rate = 0.4
        rng = np.random.RandomState(1)
        x = jnp.asarray(rng.randn(8, 32, 128).astype("float32"))
        r = jnp.asarray(rng.randn(8, 32, 128).astype("float32"))
        interp = CPU_IMPLS[impl]

        out = dropout_epilogue.dropout_add(x, r, rate, _seed(),
                                           interpret=interp)
        fwd_mask = _mask_of(out, r)

        gx, gr = jax.grad(
            lambda x, r: jnp.sum(dropout_epilogue.dropout_add(
                x, r, rate, _seed(), interpret=interp)),
            (0, 1))(x, r)
        gx = np.asarray(gx)
        scale = 1.0 / (1.0 - rate)
        assert np.allclose(gx[fwd_mask], scale, atol=1e-5), impl
        assert np.allclose(gx[~fwd_mask], 0.0), impl
        assert np.allclose(np.asarray(gr), 1.0), impl

    def test_mixed_dtype_residual(self):
        # amp shape: bf16 activations, f32 residual — out/dx bf16, dres f32
        rng = np.random.RandomState(2)
        x = jnp.asarray(rng.randn(8, 128).astype("float32")
                        ).astype(jnp.bfloat16)
        r = jnp.asarray(rng.randn(8, 128).astype("float32"))
        out = dropout_epilogue.dropout_add(x, r, 0.3, _seed(),
                                           interpret=True)
        assert out.dtype == jnp.bfloat16
        gx, gr = jax.grad(
            lambda x, r: jnp.sum(dropout_epilogue.dropout_add(
                x, r, 0.3, _seed(), interpret=True).astype(jnp.float32)),
            (0, 1))(x, r)
        assert gx.dtype == jnp.bfloat16 and gr.dtype == jnp.float32


class TestZeroCostOff:
    def test_rate0_hlo_identical_to_plain_add(self):
        x = jnp.zeros((64, 128), jnp.float32)
        r = jnp.ones((64, 128), jnp.float32)
        h_fused = jax.jit(
            lambda x, r: dropout_epilogue.dropout_add(x, r, 0.0, None)
        ).lower(x, r).as_text()
        h_add = jax.jit(lambda x, r: x + r).lower(x, r).as_text()
        assert h_fused == h_add

    def test_models_rate0_graph_unchanged_by_flag(self):
        """With dropout off the transformer/BERT builders must emit the
        SAME op sequence whether FLAGS.fused_dropout_add is on or off —
        the fused path costs exactly nothing when dropout is off."""
        from paddle_tpu.models import bert as B
        from paddle_tpu.models import transformer as T

        def ops(flag):
            FLAGS.fused_dropout_add = flag
            try:
                prog, startup = pt.Program(), pt.Program()
                with pt.program_guard(prog, startup):
                    T.transformer(
                        src_vocab_size=64, trg_vocab_size=64, max_length=16,
                        n_layer=1, n_head=2, d_key=8, d_value=8, d_model=16,
                        d_inner_hid=32, dropout_rate=0.0, src_seq_len=16,
                        trg_seq_len=16)
                    B.build_pretrain_net(vocab_size=64, seq_len=16,
                                         n_layer=1, n_head=2, d_model=16,
                                         d_ff=32, dropout_rate=0.0,
                                         with_optimizer=False)
                return [op.type for op in prog.global_block().ops]
            finally:
                FLAGS.reset("fused_dropout_add")

        on, off = ops(True), ops(False)
        assert on == off
        assert "dropout_add" not in on and "dropout" not in on

    def test_models_with_dropout_use_fused_op_under_flag(self):
        from paddle_tpu.models import transformer as T

        def ops(flag):
            FLAGS.fused_dropout_add = flag
            try:
                prog, startup = pt.Program(), pt.Program()
                with pt.program_guard(prog, startup):
                    T.transformer(
                        src_vocab_size=64, trg_vocab_size=64, max_length=16,
                        n_layer=1, n_head=2, d_key=8, d_value=8, d_model=16,
                        d_inner_hid=32, dropout_rate=0.1, src_seq_len=16,
                        trg_seq_len=16)
                return [op.type for op in prog.global_block().ops]
            finally:
                FLAGS.reset("fused_dropout_add")

        on, off = ops(True), ops(False)
        assert "dropout_add" in on
        assert "dropout_add" not in off
        # every residual dropout site fused: 3 sub-layers/enc + 4/dec... at
        # n_layer=1: enc 2 + dec 3 = 5 "dan" sites
        assert on.count("dropout_add") == 5


class TestOpInProgram:
    def test_fwd_bwd_and_is_test(self):
        prog, startup = pt.Program(), pt.Program()
        with pt.program_guard(prog, startup):
            x = layers.data(name="x", shape=[64, 128], dtype="float32")
            r = layers.data(name="r", shape=[64, 128], dtype="float32")
            x.stop_gradient = False
            r.stop_gradient = False
            out = layers.dropout_add(x, r, 0.4)
            loss = layers.reduce_sum(out)
            pt.append_backward(loss)
        exe = pt.Executor()
        scope = pt.Scope()
        exe.run(startup, scope=scope)
        rng = np.random.RandomState(0)
        xv = rng.randn(1, 64, 128).astype("float32")
        rv = rng.randn(1, 64, 128).astype("float32")
        o, gx, gr = (np.asarray(v) for v in exe.run(
            prog, feed={"x": xv, "r": rv},
            fetch_list=[out.name, "x@GRAD", "r@GRAD"], scope=scope))
        kept = np.abs(o - rv) > 1e-7
        scale = 1.0 / 0.6
        assert abs(kept.mean() - 0.6) < 0.05
        np.testing.assert_allclose(o[kept], xv[kept] * scale + rv[kept],
                                   atol=1e-5)
        np.testing.assert_allclose(o[~kept], rv[~kept], atol=1e-6)
        assert np.allclose(gx[kept], scale, atol=1e-5)
        assert np.allclose(gx[~kept], 0.0)
        assert np.allclose(gr, 1.0)
        # inference clone: plain add
        infer = prog.clone(for_test=True)
        (oi,) = exe.run(infer, feed={"x": xv, "r": rv},
                        fetch_list=[out.name], scope=scope)
        np.testing.assert_allclose(np.asarray(oi), xv + rv, atol=1e-6)

    def test_seed_determinism_across_recompiles(self, tmp_path):
        """PR-3 RNG fixture pattern: the mask is a pure function of
        (program seed, executor run counter, rng_id).  Save the RNG state,
        let the counter drift, resume, then rerun with a WIDER fetch list
        — a new compile-cache entry, i.e. a genuine recompile — and the
        dropout-add output must replay bit-exactly."""
        prog, startup = pt.Program(), pt.Program()
        with pt.program_guard(prog, startup):
            x = layers.data(name="x", shape=[16, 128], dtype="float32")
            r = layers.data(name="r", shape=[16, 128], dtype="float32")
            out = layers.dropout_add(x, r, 0.4)
            total = layers.reduce_sum(out)
        exe = pt.Executor()
        scope = pt.Scope()
        exe.run(startup, scope=scope)
        rng = np.random.RandomState(3)
        feed = {"x": rng.randn(1, 16, 128).astype("float32"),
                "r": rng.randn(1, 16, 128).astype("float32")}

        mgr = pt.io.CheckpointManager(str(tmp_path), exe, interval_steps=1,
                                      main_program=prog, scope=scope)
        exe.run(prog, feed=feed, fetch_list=[out], scope=scope)
        mgr.on_step(0)  # snapshots the executor RNG fold-in counter
        (o_next,) = exe.run(prog, feed=feed, fetch_list=[out], scope=scope)

        # drift the counter further; masks keep changing per step
        (o_drift,) = exe.run(prog, feed=feed, fetch_list=[out], scope=scope)
        assert not np.array_equal(np.asarray(o_next), np.asarray(o_drift))

        assert mgr.resume() is not None
        # wider fetch list -> new cache key -> the program RECOMPILES;
        # the restored counter must regenerate o_next's mask bit-exactly
        o_replay, _ = exe.run(prog, feed=feed, fetch_list=[out, total],
                              scope=scope)
        assert np.array_equal(np.asarray(o_replay), np.asarray(o_next))


@pytest.mark.skipif(
    jax.default_backend() != "tpu",
    reason="hardware-PRNG dropout needs a compiled TPU kernel "
           "(pltpu.prng_seed has no CPU/interpret lowering)")
class TestHardwarePrngTPU:
    """Compiled-TPU coverage of the pltpu.prng_seed/prng_random_bits
    paths — the bits differ from the hash fallback by design, so the
    contract here is per-implementation: fwd/bwd bit-parity, keep-rate,
    and call-to-call determinism."""

    def test_epilogue_fwd_bwd_mask_parity_and_rate(self):
        rate = 0.3
        rng = np.random.RandomState(5)
        x = jnp.asarray(rng.randn(64, 256).astype("float32"))
        r = jnp.asarray(rng.randn(64, 256).astype("float32"))
        out = dropout_epilogue.dropout_add(x, r, rate, _seed())
        out2 = dropout_epilogue.dropout_add(x, r, rate, _seed())
        assert np.array_equal(np.asarray(out), np.asarray(out2))
        fwd_mask = _mask_of(out, r)
        assert abs(fwd_mask.mean() - (1.0 - rate)) < 0.02
        gx = np.asarray(jax.grad(
            lambda x: jnp.sum(dropout_epilogue.dropout_add(
                x, r, rate, _seed())))(x))
        scale = 1.0 / (1.0 - rate)
        assert np.allclose(gx[fwd_mask], scale, atol=1e-5)
        assert np.allclose(gx[~fwd_mask], 0.0)

    def test_flash_attention_hw_dropout_deterministic_and_finite(self):
        from paddle_tpu.kernels.attention import flash_attention

        d, t, rate = 64, 256, 0.2
        rng = np.random.RandomState(6)
        shape = (2, t, 2, d)
        q, k, v = (jnp.asarray(rng.randn(*shape).astype("float32"))
                   for _ in range(3))
        seed = _seed()

        def f(q, k, v):
            return flash_attention(q, k, v, None, scale=d ** -0.5,
                                   fmt="bthd", dropout_rate=rate,
                                   dropout_seed=seed)

        o1, o2 = f(q, k, v), f(q, k, v)
        assert np.array_equal(np.asarray(o1), np.asarray(o2))
        nodrop = flash_attention(q, k, v, None, scale=d ** -0.5, fmt="bthd")
        assert not np.allclose(np.asarray(o1), np.asarray(nodrop))
        g = jax.grad(lambda q, k, v: jnp.sum(f(q, k, v)), (0, 1, 2))(q, k, v)
        for a in g:
            assert np.all(np.isfinite(np.asarray(a)))

        # stop-gradient bias (the bundled models' shape): hw PRNG stays
        # enabled via trainable_bias=False — determinism + finite grads
        bias = jnp.zeros((2, 1, 1, t), jnp.float32)

        def fb(q, k, v):
            return flash_attention(q, k, v, bias, scale=d ** -0.5,
                                   fmt="bthd", dropout_rate=rate,
                                   dropout_seed=seed, trainable_bias=False)

        b1, b2 = fb(q, k, v), fb(q, k, v)
        assert np.array_equal(np.asarray(b1), np.asarray(b2))
        gb = jax.grad(lambda q, k, v: jnp.sum(fb(q, k, v)),
                      (0, 1, 2))(q, k, v)
        for a in gb:
            assert np.all(np.isfinite(np.asarray(a)))
