"""Dataset cache/download helpers (reference: python/paddle/dataset/common.py
— DATA_HOME, download with md5 check, cached unpacking)."""

from __future__ import annotations

import hashlib
import os
import shutil

DATA_HOME = os.path.expanduser(
    os.environ.get("PADDLE_TPU_DATA_HOME", "~/.cache/paddle_tpu/dataset")
)


def must_mkdirs(path):
    os.makedirs(path, exist_ok=True)
    return path


def md5file(fname):
    hash_md5 = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(4096), b""):
            hash_md5.update(chunk)
    return hash_md5.hexdigest()


def download(url, module_name, md5sum, save_name=None):
    """Download-with-cache (reference common.py:download).  In zero-egress
    environments, place the file at the cache path manually; a missing file
    raises with that path in the message."""
    dirname = must_mkdirs(os.path.join(DATA_HOME, module_name))
    filename = os.path.join(dirname, save_name or url.split("/")[-1])
    if os.path.exists(filename) and (not md5sum or md5file(filename) == md5sum):
        return filename
    try:
        import urllib.request

        tmp = filename + ".part"
        urllib.request.urlretrieve(url, tmp)
        shutil.move(tmp, filename)
    except Exception as e:
        raise RuntimeError(
            f"cannot download {url} (offline?): {e}. "
            f"Place the file manually at {filename}."
        ) from e
    if md5sum and md5file(filename) != md5sum:
        raise RuntimeError(f"md5 mismatch for {filename}")
    return filename


def use_synthetic(explicit=False):
    """Whether readers should yield synthetic offline data (explicit arg,
    FLAGS_synthetic_data, or PADDLE_TPU_SYNTH_DATA=1)."""
    from ..flags import FLAGS

    return bool(
        explicit
        or FLAGS.synthetic_data
        or os.environ.get("PADDLE_TPU_SYNTH_DATA") == "1"
    )
