"""Version-tolerant wrappers for jax APIs that moved between releases.

The framework targets current jax (top-level `jax.shard_map`, the
varying-type system's `jax.lax.pvary`), but CI hosts may carry an older
jaxlib where shard_map still lives in jax.experimental (param `check_rep`
instead of `check_vma`) and pvary does not exist (no varying-type checks,
so identity is the correct degenerate form).
"""

from __future__ import annotations


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = False,
              auto=None):
    """auto: optional frozenset of mesh axis names left to GSPMD while
    the remaining axes are manual (the pipeline tier shard_maps over its
    `pipe` axis only, composing with dp/tp GSPMD sharding inside).  Old
    jaxlib builds without partial-manual support raise a NAMED error
    rather than silently running fully manual."""
    import inspect

    import jax

    if hasattr(jax, "shard_map"):
        fn = jax.shard_map
    else:
        from jax.experimental.shard_map import shard_map as fn
    params = inspect.signature(fn).parameters
    # the check param was renamed check_rep -> check_vma independently of
    # the experimental->top-level promotion; probe the actual signature
    if "check_vma" in params:
        kw = {"check_vma": check_vma}
    else:
        kw = {"check_rep": check_vma}
    if auto:
        if "auto" not in params:
            raise NotImplementedError(
                "this jax's shard_map has no `auto` parameter (partial "
                "manual mode); the pipeline mesh path needs it")
        kw["auto"] = frozenset(auto)
    return fn(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)


def pvary(x, axis_name):
    import jax

    if hasattr(jax.lax, "pvary"):
        return jax.lax.pvary(x, axis_name)
    return x


def axis_size(axis_name):
    import jax

    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    # old jax: psum of a Python-int constant folds to a static int
    return jax.lax.psum(1, axis_name)


def deserialize_and_load(payload, in_tree, out_tree, n_devices: int = 1):
    """serialize_executable.deserialize_and_load grew an
    execution_devices kwarg; older jax derives placement from the
    payload.  (The payload is pickle-deserialized either way — callers
    must treat it as a trusted artifact.)"""
    import inspect

    import jax
    from jax.experimental import serialize_executable as se

    params = inspect.signature(se.deserialize_and_load).parameters
    if "execution_devices" in params:
        return se.deserialize_and_load(
            payload, in_tree, out_tree,
            execution_devices=jax.devices()[:n_devices])
    return se.deserialize_and_load(payload, in_tree, out_tree)
