"""Shared retry-with-backoff (reference role: the Go pserver/master clients
retry RPCs with backoff on lost connections, go/master/client.go RetryBuffer
idiom; the reference Python had no shared utility, so every call site —
dataset downloads, checkpoint writes — either raised on the first transient
error or hand-rolled a loop).

One policy, three production call sites: checkpoint writes (io.py
CheckpointManager), AsyncExecutor shard workers (data_feed.py), and dataset
downloads (dataset/common.py).  Jittered exponential backoff with a delay
cap and a typed give-up exception; deterministic when seeded (the chaos
tests pin `seed` so injected-fault schedules replay exactly).
"""

from __future__ import annotations

import random
import time
from typing import Callable, Iterator, Optional, Tuple, Type


class RetryError(RuntimeError):
    """Give-up: every attempt failed.  Carries the last exception
    (`.last`, also the __cause__) and the attempt count (`.attempts`)."""

    def __init__(self, msg: str, last: BaseException, attempts: int):
        super().__init__(msg)
        self.last = last
        self.attempts = attempts


def backoff_delays(
    retries: int,
    base_delay: float = 0.05,
    factor: float = 2.0,
    max_delay: float = 2.0,
    jitter: float = 0.25,
    seed: Optional[int] = None,
    deadline_s: Optional[float] = None,
) -> Iterator[float]:
    """Yield `retries` sleep durations: capped exponential with
    multiplicative jitter in [1-jitter, 1+jitter].  `seed` pins the jitter
    sequence (tests / deterministic chaos replay).

    `deadline_s` is a sleep budget (the caller's REMAINING deadline, not a
    wall-clock instant): the generator stops yielding once the cumulative
    sleep it has handed out would exceed it, so a retry loop driven by
    these delays can never sleep a request past its own timeout.  The
    final yielded delay is clipped to the remaining budget rather than
    dropped — a 100 ms budget gets at most 100 ms of total sleep, never
    the full next exponential step.  None = unbudgeted (legacy behavior);
    a non-positive budget yields nothing (no sleeps, thus no retries for
    retry_call callers)."""
    rng = random.Random(seed) if seed is not None else random
    remaining = deadline_s
    for i in range(retries):
        if remaining is not None and remaining <= 0:
            return
        d = min(max_delay, base_delay * (factor ** i))
        if jitter:
            d *= 1.0 + jitter * (2.0 * rng.random() - 1.0)
        d = max(0.0, d)
        if remaining is not None:
            d = min(d, remaining)
            remaining -= d
        yield d


def retry_call(
    fn: Callable,
    *args,
    retries: int = 3,
    base_delay: float = 0.05,
    factor: float = 2.0,
    max_delay: float = 2.0,
    jitter: float = 0.25,
    retry_on: Tuple[Type[BaseException], ...] = (OSError,),
    seed: Optional[int] = None,
    name: str = "",
    on_retry: Optional[Callable[[BaseException, int, float], None]] = None,
    sleep: Optional[Callable[[float], None]] = None,
    deadline_s: Optional[float] = None,
    **kwargs,
):
    """Call `fn(*args, **kwargs)`; on a `retry_on` exception, back off and
    retry up to `retries` more times, then raise RetryError (cause = the
    last exception).  Exceptions NOT in `retry_on` propagate immediately —
    a programming error must not be retried into silence.

    `on_retry(exc, attempt, delay)` observes each scheduled retry (the
    call sites log / bump monitor counters there); `name` labels the
    default telemetry.  Total attempts = retries + 1.  `deadline_s`
    bounds the cumulative backoff sleep (see backoff_delays): once the
    budget is spent, the next failure gives up instead of retrying."""
    if sleep is None:
        sleep = time.sleep  # resolved per call: tests patch time.sleep
    delays = backoff_delays(retries, base_delay, factor, max_delay,
                            jitter, seed, deadline_s=deadline_s)
    attempt = 0
    while True:
        attempt += 1
        try:
            return fn(*args, **kwargs)
        except retry_on as e:
            try:
                delay = next(delays)
            except StopIteration:
                raise RetryError(
                    f"{name or getattr(fn, '__name__', 'call')}: giving up "
                    f"after {attempt} attempts: {type(e).__name__}: {e}",
                    e, attempt) from e
            if on_retry is not None:
                on_retry(e, attempt, delay)
            else:
                _note_retry(name, e, attempt)
            if delay > 0:
                sleep(delay)


def _note_retry(name: str, exc: BaseException, attempt: int) -> None:
    """Default retry telemetry: a monitor counter + flight event per
    scheduled retry (both no-ops while FLAGS.monitor is off)."""
    try:
        from ..monitor import counter, enabled
        from ..monitor import flight

        if enabled():
            counter(f"retry.{name or 'anonymous'}").inc()
            flight.record("retry", site=name or "anonymous",
                          attempt=attempt,
                          error=f"{type(exc).__name__}: {exc}")
    except Exception:
        pass  # telemetry must never break the retried operation
