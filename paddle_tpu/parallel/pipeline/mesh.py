"""PipelineMeshProgram: the pipeline schedule as ONE compiled collective
program over a `pipe` mesh axis.

Where trainer.py time-multiplexes per-stage executables on the host,
this runner lowers the SAME tick table (schedule.py) into a single
jitted step over a dp x tp x pp jax.sharding.Mesh:

  * shard_map over the `pipe` axis only — the data/model axes stay AUTO,
    so the existing GSPMD dp/tp sharding rules (parallel/sharding.py
    ShardingPlan param/feed specs) compose unchanged inside each stage;
  * per-tick boundary transfers are neighbor hops of a fixed-width
    packed f32 wire (crossing-set layouts from partition.py; pass-through
    vars ride hop-by-hop, so a stage-0 activation consumed at stage 3
    crosses every cut between) — realized as a psum of a one-hot [S, W]
    scatter because this jaxlib's partial-auto partitioner hard-rejects
    lax.ppermute (and typed PRNG keys, and lax.axis_index) inside a
    manual-pipe subgroup;
  * the backward recomputes each stage's forward from the stashed wire
    input under jax.vjp (rematerialization — the standard pipeline
    memory trade; rng_id-keyed dropout regenerates bit-identical masks),
    seeding the TRUE loss var's cotangent with 1.0 on its owning rank
    (mirroring the IR's Backward|Loss fill_constant) and pulling the
    cotangent wire back rank-by-rank; grads psum over `pipe` and the
    UNSPLIT optimizer suffix runs once in plain GSPMD land, so parameter
    updates land identically on every rank.

Every rank's compiled program carries all stage branches (lax.switch on
the pipe rank) and both phase switches execute per tick with invalid
slots masked — demonstration-grade SPMD for the dryrun matrix, honest
about the ~2x trace-size cost; production-scale pipelining over separate
processes rides trainer.py's per-stage entries.

Backend status: green at dp2 x tp2 x pp2 on dense towers (CPU mesh,
tier-1 + dryrun).  jaxlib 0.4.37's CPU partial-auto SPMD partitioner
does NOT terminate compiling transformer-class stage traces (scanned or
unrolled) — retry on the driver's TPU runtime before trusting that
negative (PERF.md round 11, risk a); the sharded host scheduler
(PipelineProgram plan=) covers transformer dp x tp x pp meanwhile.

Contract (named errors at compile): forward stages free of rw scope
state (BatchNorm running stats), boundary vars float32, fetches scalar,
and the optimizer consumes RAW `<param>@GRAD` grads — gradient-clip /
regularization ops are Backward-role program ops the vjp recompute does
not replay.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ...core import executor as exec_mod
from ...core import framework as fw
from ...core.executor import prng_key as _prng_key
from . import schedule as sched_mod
from .partition import PipelineStages, split_program
from .trainer import _phase_state


def _tables(ticks, n_stages):
    """Tick table -> (fwd_tbl, bwd_tbl) int32 [T, S]; -1 = idle slot."""
    T = len(ticks)
    fwd = -np.ones((T, n_stages), np.int32)
    bwd = -np.ones((T, n_stages), np.int32)
    for t, tick in enumerate(ticks):
        for s, phase, m in tick:
            (fwd if phase == "fwd" else bwd)[t, s] = m
    return fwd, bwd


def _find_loss_name(program: fw.Program) -> str:
    """The var whose gradient the IR backward seeds with 1.0 (the
    Backward|Loss fill_constant append_backward emits)."""
    mask = fw.OpRole.Backward | fw.OpRole.Loss
    for op in program.global_block().ops:
        role = int(op.attrs.get(fw.OpRole.ROLE_ATTR_NAME, 0))
        if op.type == "fill_constant" and (role & mask) == mask:
            for n in op.output_arg_names():
                if n.endswith("@GRAD"):
                    return n[:-len("@GRAD")]
    raise ValueError(
        "PipelineMeshProgram: program has no Backward|Loss grad seed "
        "(call optimizer.minimize / append_backward first)")


class _ScopeView:
    """Minimal scope shim over a name->value dict (shape-inference time)."""

    def __init__(self, env):
        self._env = env

    def find_var(self, name):
        return self._env.get(name)

    def has_var(self, name):
        return name in self._env


class PipelineMeshProgram:
    def __init__(
        self,
        program: fw.Program,
        feed_names: Sequence[str],
        plan,
        cut_vars: Optional[Sequence[str]] = None,
        schedule: str = "gpipe",
        pipe_axis: str = "pipe",
        stages: Optional[PipelineStages] = None,
        unroll_ticks: bool = True,
    ):
        if pipe_axis not in plan.mesh_axes:
            raise ValueError(
                f"ShardingPlan has no {pipe_axis!r} mesh axis "
                f"(axes: {list(plan.mesh_axes)})")
        self.plan = plan
        self.pipe_axis = pipe_axis
        self.schedule = schedule
        n_stages = int(plan.mesh_axes[pipe_axis])
        self.stages = stages if stages is not None else split_program(
            program, feed_names, n_stages=n_stages, cut_vars=cut_vars)
        self.program = program
        self.feed_names = list(feed_names)
        self.loss_name = _find_loss_name(program)
        # unroll the tick loop instead of lax.scan: scanning the tick
        # body (switch over stage branches inside a manual-pipe subgroup
        # with auto dp/tp axes) sends this jaxlib's SPMD partitioner into
        # a non-terminating compile on non-trivial models; the unrolled
        # module is T times larger but partitions in seconds
        self.unroll_ticks = unroll_ticks
        self._mesh = None
        self._cache: Dict[Any, Any] = {}

    @property
    def mesh(self):
        if self._mesh is None:
            self._mesh = self.plan.build_mesh()
        return self._mesh

    # -- static contract checks -------------------------------------------
    def _check_contract(self, scope, fetch_names):
        if getattr(self.program, "_amp_bf16", False):
            # declared IR dtypes stay float32 under amp but the traced
            # boundary activations are bf16 — the f32 wire check below
            # cannot see that, so name the rejection here
            raise NotImplementedError(
                "pipeline mesh path: amp (_amp_bf16) programs trace bf16 "
                "boundary activations; the packed wire is float32-only — "
                "use the host scheduler (PipelineProgram)")
        for c, layout in enumerate(self.stages.crossing):
            for name, _, dtype in layout:
                if dtype != "float32":
                    raise NotImplementedError(
                        f"pipeline mesh path: boundary var {name!r} at cut "
                        f"{c} has dtype {dtype}; the packed ppermute wire "
                        f"is float32-only")
        producible = set()
        for st in self.stages:
            producible |= st.fetch_candidates
            _, writes = _phase_state(
                st.fwd_ops(), scope,
                st.feeds + [n for n, _, _ in st.fwd_inputs])
            if writes:
                raise NotImplementedError(
                    f"pipeline mesh path: stage {st.index} forward writes "
                    f"scope state {writes[:4]} (e.g. BatchNorm running "
                    f"stats) — use the host scheduler (PipelineProgram)")
            for op in st.opt_ops():
                pnames = op.inputs.get("Param", [])
                for p, g in zip(pnames, op.inputs.get("Grad", [])):
                    if p and g and g != fw.grad_var_name(p):
                        raise NotImplementedError(
                            f"pipeline mesh path: optimizer op {op.type!r} "
                            f"reads transformed grad {g!r} for {p!r} "
                            f"(gradient clip/regularization ops are not "
                            f"replayed by the vjp recompute)")
        missing = [n for n in fetch_names if n not in producible]
        if missing:
            raise KeyError(
                f"PipelineMeshProgram: fetch target(s) {missing} produced "
                f"by no stage forward (mesh fetches are scalar forward "
                f"values — loss terms)")

    # -- compile ----------------------------------------------------------
    def _infer_shapes(self, feed_stack, state_env):
        """Concrete shapes for every boundary var via a chained
        jax.eval_shape of the stage forwards on one micro-batch — the
        declared IR shapes carry -1 batch dims, so wire widths must come
        from the live feed signature."""
        import jax

        shapes: Dict[str, Any] = {}
        for n, v in feed_stack.items():
            shapes[n] = jax.ShapeDtypeStruct(tuple(v.shape[1:]), v.dtype)
        for n, v in state_env.items():
            shapes[n] = jax.ShapeDtypeStruct(
                tuple(v.shape), np.asarray(v).dtype)
        key_aval = jax.eval_shape(lambda: _prng_key(0))
        for st in self.stages:
            names_in = [n for n, _, _ in st.fwd_inputs]
            names_out = [n for n, _, _ in st.fwd_outputs]
            reads = _phase_state(st.fwd_ops(), _ScopeView(state_env),
                                 st.feeds + names_in)[0]

            def one(feeds, ins, states, key, st=st, names_in=names_in,
                    names_out=names_out, reads=reads):
                tctx = exec_mod.TraceContext(
                    st.program, key,
                    is_test=getattr(st.program, "_is_test", False))
                env = dict(zip(st.feeds, feeds))
                env.update(zip(names_in, ins))
                env.update(zip(reads, states))
                exec_mod.trace_block(st.program.global_block(), env, tctx,
                                     ops=st.fwd_ops())
                return [env[n] for n in names_out]

            outs = jax.eval_shape(
                one, [shapes[n] for n in st.feeds],
                [shapes[n] for n in names_in],
                [shapes[n] for n in reads], key_aval)
            for n, o in zip(names_out, outs):
                shapes[n] = o
        layouts = []
        for layout in self.stages.crossing:
            layouts.append([
                (n, tuple(shapes[n].shape), str(shapes[n].dtype))
                for n, _, _ in layout
            ])
        return layouts

    def _compile(self, feed_stack, fetch_names, scope, k: int):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ...kernels.jax_compat import shard_map as _shard_map

        self._check_contract(scope, fetch_names)
        mesh = self.mesh
        S = self.stages.n_stages
        pipe = self.pipe_axis
        auto_axes = frozenset(a for a in self.plan.mesh_axes if a != pipe)

        # ---- state (params + anything scope-resident the stages read) --
        state_names: List[str] = []
        seen = set()
        for st in self.stages:
            reads, _ = _phase_state(
                st.fwd_ops(), scope,
                st.feeds + [n for n, _, _ in st.fwd_inputs])
            for n in reads:
                if n not in seen:
                    seen.add(n)
                    state_names.append(n)
        suffix_ops = [op for st in self.stages for op in st.opt_ops()]
        grad_names = sorted({
            n for op in suffix_ops for n in op.inputs.get("Grad", []) if n})
        opt_reads, opt_writes = _phase_state(suffix_ops, scope, grad_names)
        opt_rw = [n for n in opt_reads if n in set(opt_writes)]
        opt_writes = opt_rw + [n for n in opt_writes
                               if n not in set(opt_rw)]
        for n in opt_reads:
            if n not in seen:
                seen.add(n)
                state_names.append(n)
        params = {p.name for p in self.program.all_parameters()}

        # ---- wire layouts ----------------------------------------------
        state_env = {n: scope.find_var(n) for n in state_names}
        layouts = self._infer_shapes(feed_stack, state_env)
        W = max([sum(int(np.prod(s)) if s else 1 for _, s, _ in lo)
                 for lo in layouts] + [1])
        in_layouts = [[]] + layouts          # stage s consumes layouts[s-1]
        out_layouts = layouts + [[]]         # stage s produces layouts[s]

        ticks = sched_mod.schedule_table(S, k, self.schedule)
        fwd_tbl, bwd_tbl = _tables(ticks, S)

        feed_names_sorted = sorted(feed_stack)
        loss_name = self.loss_name
        is_test = getattr(self.program, "_is_test", False)
        n_fetch = len(fetch_names)

        def _unpack(vec, layout):
            env, off = {}, 0
            for n, shape, _ in layout:
                size = int(np.prod(shape)) if shape else 1
                env[n] = vec[off:off + size].reshape(shape)
                off += size
            return env

        def _pack(env, layout):
            parts = [jnp.ravel(env[n]) for n, _, _ in layout]
            vec = (jnp.concatenate(parts) if parts
                   else jnp.zeros((0,), jnp.float32))
            return jnp.pad(vec, (0, W - vec.shape[0]))

        def _fwd_core(s, wire_in, feeds_m, state_vals, key):
            """-> (wire_out [W], loss scalar, fetch_vec [n_fetch])."""
            st = self.stages.stages[s]
            tctx = exec_mod.TraceContext(st.program, key, is_test=is_test)
            # the mesh stage trace runs under jax.vjp, and
            # optimization_barrier has no differentiation rule; this path
            # asserts allclose (not bit) parity, so barriers are moot
            tctx.boundary_barriers = False
            env = dict(_unpack(wire_in, in_layouts[s]))
            env.update(zip(feed_names_sorted, feeds_m))
            env.update(zip(state_names, state_vals))
            exec_mod.trace_block(st.program.global_block(), env, tctx,
                                 ops=st.fwd_ops())
            wire_out = _pack(env, out_layouts[s])
            loss = (env[loss_name].astype(jnp.float32).reshape(())
                    if loss_name in st.fetch_candidates
                    else jnp.asarray(0.0, jnp.float32))
            fetch_vec = (jnp.stack([
                (env[n].astype(jnp.float32).reshape(())
                 if n in st.fetch_candidates
                 else jnp.asarray(0.0, jnp.float32))
                for n in fetch_names])
                if n_fetch else jnp.zeros((0,), jnp.float32))
            return wire_out, loss, fetch_vec

        def _make_fwd_branch(s):
            def branch(wire_in, feeds_m, state_vals, key, cot_wire, dloss):
                wire_out, _, fetch_vec = _fwd_core(
                    s, wire_in, feeds_m, state_vals, key)
                zeros = [jnp.zeros_like(v) for v in state_vals]
                return (wire_out, fetch_vec,
                        jnp.zeros((W,), jnp.float32), zeros)
            return branch

        def _make_bwd_branch(s):
            def branch(wire_in, feeds_m, state_vals, key, cot_wire, dloss):
                def f(w, sv):
                    wire_out, loss, _ = _fwd_core(s, w, feeds_m, sv, key)
                    return wire_out, loss

                _, vjp_fn = jax.vjp(f, wire_in, list(state_vals))
                dwire, dstates = vjp_fn((cot_wire, dloss))
                return (jnp.zeros((W,), jnp.float32),
                        jnp.zeros((n_fetch,), jnp.float32),
                        dwire, list(dstates))
            return branch

        fwd_branches = [_make_fwd_branch(s) for s in range(S)]
        bwd_branches = [_make_bwd_branch(s) for s in range(S)]

        def body(feed_vals, state_vals, key_data, rank_arr):
            # the pipe rank rides in as a P('pipe')-sharded iota slice:
            # lax.axis_index lowers to PartitionId, which GSPMD rejects
            # inside partial-auto shard_map; the PRNG key rides as raw
            # uint32 key data for the same reason (typed key arrays fail
            # partial-auto sharding validation at the shard_map boundary)
            rank = rank_arr[0]
            base_key = jax.random.wrap_key_data(key_data, impl="rbg")

            def _shift(vec, dst, ok):
                """Deliver each rank's [W] vec to rank `dst` (one hop of
                the boundary wire).  lax.ppermute is rejected by the
                partial-auto SPMD partitioner (manual-subgroup check), so
                the hop is a psum of a one-hot [S, W] scatter — S times
                the wire bytes, fine at pipeline depths."""
                scatter = jnp.zeros((S, W), jnp.float32)
                scatter = jax.lax.dynamic_update_index_in_dim(
                    scatter, vec, jnp.clip(dst, 0, S - 1), 0)
                scatter = jnp.where(ok, scatter, 0.0)
                total = jax.lax.psum(scatter, pipe)
                return jax.lax.dynamic_index_in_dim(
                    total, rank, 0, keepdims=False)
            zero_wire = jnp.zeros((k, W), jnp.float32)
            grads0 = [jnp.zeros_like(v) for v in state_vals]
            fetch0 = jnp.zeros((n_fetch, k), jnp.float32)

            def tick(carry, xs):
                inbox_f, inbox_b, fetch_buf, grads = carry
                # per-tick micro-batch indices arrive PRE-GATHERED per
                # rank (xs streams, hoisted below): a take(tbl, rank)
                # inside the scan body trips a fatal manual-subgroup
                # check in the partial-auto SPMD partitioner
                m_f, m_b, m_in, m_gin = xs

                # ---- forward slot ------------------------------------
                do_f = m_f >= 0
                mf = jnp.clip(m_f, 0, k - 1)
                feeds_f = [jax.lax.dynamic_index_in_dim(
                    v, mf, 0, keepdims=False) for v in feed_vals]
                w_out, fvec, _, _ = jax.lax.switch(
                    rank, fwd_branches, inbox_f[mf], feeds_f, state_vals,
                    jax.random.fold_in(base_key, mf),
                    jnp.zeros((W,), jnp.float32),
                    jnp.asarray(0.0, jnp.float32))
                w_out = jnp.where(do_f, w_out, 0.0)
                fetch_buf = jnp.where(
                    do_f,
                    jax.lax.dynamic_update_index_in_dim(
                        fetch_buf, fvec, mf, 1),
                    fetch_buf)

                # ---- backward slot (recompute + vjp) -----------------
                do_b = m_b >= 0
                mb = jnp.clip(m_b, 0, k - 1)
                feeds_b = [jax.lax.dynamic_index_in_dim(
                    v, mb, 0, keepdims=False) for v in feed_vals]
                # the IR backward's loss-grad seed is 1.0; only the
                # owning stage's trace touches the loss, so a global 1.0
                # is exact there and inert elsewhere
                dloss = jnp.where(do_b, 1.0, 0.0).astype(jnp.float32)
                _, _, dwire, dstates = jax.lax.switch(
                    rank, bwd_branches, inbox_f[mb], feeds_b, state_vals,
                    jax.random.fold_in(base_key, mb), inbox_b[mb], dloss)
                dwire = jnp.where(do_b, dwire, 0.0)
                grads = [g + jnp.where(do_b, d, jnp.zeros_like(d))
                         for g, d in zip(grads, dstates)]

                # ---- boundary transfers ------------------------------
                recv_f = _shift(w_out, rank + 1, rank + 1 <= S - 1)
                recv_b = _shift(dwire, rank - 1, rank - 1 >= 0)
                ok_in = (rank > 0) & (m_in >= 0)
                inbox_f = jnp.where(
                    ok_in,
                    jax.lax.dynamic_update_index_in_dim(
                        inbox_f, recv_f, jnp.clip(m_in, 0, k - 1), 0),
                    inbox_f)
                ok_gin = (rank < S - 1) & (m_gin >= 0)
                inbox_b = jnp.where(
                    ok_gin,
                    jax.lax.dynamic_update_index_in_dim(
                        inbox_b, recv_b, jnp.clip(m_gin, 0, k - 1), 0),
                    inbox_b)
                return (inbox_f, inbox_b, fetch_buf, grads), None

            ftj = jnp.asarray(fwd_tbl)  # [T, S]
            btj = jnp.asarray(bwd_tbl)

            def _col(tbl, i):
                return jax.lax.dynamic_index_in_dim(
                    tbl.T, jnp.clip(i, 0, S - 1), 0, keepdims=False)

            xs = (_col(ftj, rank), _col(btj, rank),
                  _col(ftj, rank - 1), _col(btj, rank + 1))
            carry = (zero_wire, zero_wire, fetch0, grads0)
            if self.unroll_ticks:
                for t in range(fwd_tbl.shape[0]):
                    carry, _ = tick(carry, tuple(x[t] for x in xs))
            else:
                carry, _ = jax.lax.scan(tick, carry, xs)
            (_, _, fetch_buf, grads) = carry
            # each value lives on exactly one rank; psum replicates
            fetch_buf = jax.lax.psum(fetch_buf, pipe)
            grads = [jax.lax.psum(g, pipe) for g in grads]
            return fetch_buf, grads

        smapped = _shard_map(
            body, mesh,
            in_specs=([P()] * len(feed_names_sorted),
                      [P()] * len(state_names), P(), P(pipe)),
            out_specs=(P(), [P()] * len(state_names)),
            auto=auto_axes)

        def step(feed_vals, state_vals, base_key):
            import jax.numpy as jnp

            rank_arr = jnp.arange(S, dtype=jnp.int32)
            fetch_buf, grads = smapped(feed_vals, state_vals,
                                       jax.random.key_data(base_key),
                                       rank_arr)
            # optimizer suffix ONCE in plain GSPMD land on the averaged
            # grads — the run_accumulated suffix contract (key fold K,
            # sums / float(K))
            env: Dict[str, Any] = dict(zip(state_names, state_vals))
            by_name = dict(zip(state_names, grads))
            for g in grad_names:
                env[g] = by_name[g[:-len("@GRAD")]] / float(k)
            tctx = exec_mod.TraceContext(
                self.program, jax.random.fold_in(base_key, k),
                is_test=is_test)
            exec_mod.trace_block(self.program.global_block(), env, tctx,
                                 ops=suffix_ops)
            new_state = [env.get(n) for n in opt_writes]
            return fetch_buf, new_state

        def sharding_for(name):
            v = scope.find_var(name)
            spec = self.plan.spec_for_param(
                name, getattr(v, "shape", None),
                is_moment=name not in params)
            return NamedSharding(mesh, spec)

        def feed_sharding(name):
            spec = self.plan.spec_for_feed(name)
            return NamedSharding(mesh, P(*((None,) + tuple(spec))))

        # NOTE: state is deliberately NOT donated — read-only members
        # (position tables, lr) are not returned as outputs, and donating
        # an unreturned buffer would delete the live scope array
        jitted = jax.jit(
            step,
            in_shardings=([feed_sharding(n) for n in feed_names_sorted],
                          [sharding_for(n) for n in state_names], None),
            out_shardings=(None, [sharding_for(n) for n in opt_writes]))
        return (jitted, state_names, opt_writes, feed_names_sorted)

    # -- execution (exe.run delegates here) -------------------------------
    def _run(self, executor, feed, fetch_list, scope, return_numpy):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        feed = feed or {}
        scope = scope or exec_mod.global_scope()
        fetch_names = [
            v.name if isinstance(v, fw.Variable) else v
            for v in (fetch_list or [])
        ]
        feed_stack = {
            n: executor._to_device_array(self.program, n, feed[n])
            for n in sorted(feed)
        }
        if not feed_stack:
            raise ValueError("PipelineMeshProgram needs a "
                             "[K, micro_bs, ...] feed")
        k = int(next(iter(feed_stack.values())).shape[0])

        key = (k,
               tuple((n, tuple(v.shape), str(v.dtype))
                     for n, v in sorted(feed_stack.items())),
               tuple(fetch_names))
        entry = self._cache.get(key)
        if entry is None:
            entry = self._compile(feed_stack, fetch_names, scope, k)
            self._cache[key] = entry
        jitted, state_names, opt_writes, feed_names_sorted = entry

        mesh = self.mesh
        feed_vals = []
        for n in feed_names_sorted:
            spec = self.plan.spec_for_feed(n)
            feed_vals.append(jax.device_put(
                feed_stack[n],
                NamedSharding(mesh, P(*((None,) + tuple(spec))))))
        state_vals = [scope.find_var(n) for n in state_names]

        # step key from the delegating executor's run counter (the
        # run_accumulated key schedule, same as trainer.py)
        base_key = jax.random.fold_in(
            _prng_key(self.program.random_seed or 0),
            executor._next_run_id())
        fetch_buf, new_state = jitted(feed_vals, state_vals, base_key)
        for n, v in zip(opt_writes, new_state):
            if v is not None:
                scope.set_var(n, v)
        outs = [fetch_buf[i] for i in range(len(fetch_names))]
        if return_numpy:
            return [np.asarray(v) for v in outs]
        return outs
