"""Static-analysis tier (paddle_tpu/analysis): red-gate + zero-false-positive
coverage.

Red gate: one seeded defect per analysis class — shape mismatch, use
before def, donated+fetched var, unthreaded RNG op, misaligned Pallas
block — and the verifier/linter must NAME each one.  Green gate: zero
findings across the bundled models and the built-in kernel plan matrix.
Wiring: the Executor pre-compile hook verifies once per signature, raises
on errors, and is skipped entirely (zero calls) with
FLAGS_verify_program off.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.analysis import (
    Finding,
    ProgramVerifyError,
    lint_kernel_plans,
    verify_or_raise,
    verify_program,
)
from paddle_tpu.analysis import kernel_lint
from paddle_tpu.core import registry
from paddle_tpu.flags import FLAGS

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _checks(findings):
    return {f.check for f in findings}


def _small_train_net():
    x = layers.data(name="x", shape=[4], dtype="float32")
    y = layers.data(name="y", shape=[1], dtype="float32")
    pred = layers.fc(x, size=1)
    loss = layers.mean(layers.square(pred - y))
    return x, y, loss


# ---------------------------------------------------------------------------
# red gate: the five seeded defect classes
# ---------------------------------------------------------------------------


class TestRedGate:
    def test_shape_mismatch_named(self):
        prog, startup = pt.Program(), pt.Program()
        with pt.program_guard(prog, startup):
            _small_train_net()
        # corrupt the IR: a mul output's declared shape no longer matches
        # what its contract infers (deserialized/hand-edited program class)
        blk = prog.global_block()
        mul_op = next(op for op in blk.ops if op.type == "mul")
        out_name = mul_op.output("Out")[0]
        v = blk.var(out_name)
        v.shape = (7, 7)
        findings = verify_program(prog, feed_names=["x", "y"])
        hits = [f for f in findings if f.check == "shape-mismatch"]
        assert hits, findings
        assert hits[0].op_type == "mul" and hits[0].var == out_name
        assert "(7, 7)" in hits[0].message

    def test_shape_contract_failure_named(self):
        # a mul whose K dims disagree: infer_shape itself still produces a
        # shape, but corrupting the INPUT var makes a concat contract blow
        prog, startup = pt.Program(), pt.Program()
        with pt.program_guard(prog, startup):
            a = layers.data(name="a", shape=[2, 3], dtype="float32")
            b = layers.data(name="b", shape=[2, 3], dtype="float32")
            layers.concat([a, b], axis=1)
        blk = prog.global_block()
        blk.var("a").shape = (-1, 2, 999)  # rank-consistent, dim mismatch
        findings = verify_program(prog, feed_names=["a", "b"])
        assert any(f.check in ("shape-contract", "shape-mismatch")
                   for f in findings), findings

    def test_use_before_def_named(self):
        prog, startup = pt.Program(), pt.Program()
        with pt.program_guard(prog, startup):
            x = layers.data(name="x", shape=[4], dtype="float32")
            out = layers.relu(x)
        blk = prog.global_block()
        # seed: an op reading a name nothing defines
        blk.append_op("relu", inputs={"X": ["ghost_var"]},
                      outputs={"Out": [out.name]})
        findings = verify_program(prog, feed_names=["x"])
        hits = [f for f in findings if f.check == "use-before-def"]
        assert hits and hits[0].var == "ghost_var", findings

    def test_donated_fetched_var_named(self):
        prog, startup = pt.Program(), pt.Program()
        with pt.program_guard(prog, startup):
            _, _, loss = _small_train_net()
            pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
        param = prog.all_parameters()[0].name
        findings = verify_program(prog, feed_names=["x", "y"],
                                  fetch_names=[param])
        hits = [f for f in findings if f.check == "donated-fetch"]
        assert hits and hits[0].var == param, findings
        # without the conflicting fetch the program is clean of it
        clean = verify_program(prog, feed_names=["x", "y"],
                               fetch_names=[loss.name])
        assert "donated-fetch" not in _checks(clean)

    def test_unthreaded_rng_op_named(self):
        # the PR-4 bug class: an op whose lowering draws PRNG bits but is
        # invisible to executor.op_threads_rng
        @registry.register("test_rogue_rng_op", derives_rng=True,
                           no_grad=True)
        def _lower(ctx, ins):  # pragma: no cover - never traced here
            return {"Out": [ins["X"][0]]}

        try:
            prog, startup = pt.Program(), pt.Program()
            with pt.program_guard(prog, startup):
                x = layers.data(name="x", shape=[4], dtype="float32")
                out = prog.global_block().create_var(shape=x.shape,
                                                     dtype="float32")
                prog.global_block().append_op(
                    "test_rogue_rng_op", inputs={"X": [x.name]},
                    outputs={"Out": [out.name]})
            findings = verify_program(prog, feed_names=["x"])
            hits = [f for f in findings if f.check == "rng-unthreaded"]
            assert hits and hits[0].op_type == "test_rogue_rng_op", findings
            assert "register_random_op" in hits[0].message
            # the downstream remediation: declaring the op to the
            # executor's threading clears the finding
            from paddle_tpu.core import executor as ex

            ex.register_random_op("test_rogue_rng_op")
            try:
                clean = verify_program(prog, feed_names=["x"])
                assert "rng-unthreaded" not in _checks(clean)
                assert ex.program_uses_random(prog.global_block())
            finally:
                ex._EXTRA_RANDOM_OPS.discard("test_rogue_rng_op")
        finally:
            registry._registry.pop("test_rogue_rng_op", None)

    def test_threaded_but_undeclared_rng_named(self):
        """The reverse direction of the RNG cross-check: an op the
        executor threads a key for must carry derives_rng metadata."""
        from paddle_tpu.core import executor as ex

        @registry.register("test_undeclared_rng_op", no_grad=True)
        def _lower(ctx, ins):  # pragma: no cover - never traced here
            return {"Out": [ins["X"][0]]}

        ex.register_random_op("test_undeclared_rng_op")
        try:
            prog, startup = pt.Program(), pt.Program()
            with pt.program_guard(prog, startup):
                x = layers.data(name="x", shape=[4], dtype="float32")
                out = prog.global_block().create_var(shape=x.shape,
                                                     dtype="float32")
                prog.global_block().append_op(
                    "test_undeclared_rng_op", inputs={"X": [x.name]},
                    outputs={"Out": [out.name]})
            findings = verify_program(prog, feed_names=["x"])
            hits = [f for f in findings if f.check == "rng-undeclared"]
            assert hits and hits[0].op_type == "test_undeclared_rng_op", \
                findings
        finally:
            ex._EXTRA_RANDOM_OPS.discard("test_undeclared_rng_op")
            registry._registry.pop("test_undeclared_rng_op", None)

    def test_misaligned_pallas_block_named(self):
        # the kernel linter must reject a fabricated compiled-mode plan
        # whose blocks break the 128-lane Mosaic alignment
        cfg = dict(label="seeded-misaligned", b=2, h=4, t=192, d=64,
                   dtype="float32", fmt="bhtd")
        findings = []
        kernel_lint.check_attention_plan(cfg, True, 96, 96, False,
                                         findings)
        assert any(f.check == "kernel-misaligned-block" for f in findings), \
            findings
        assert any("128-lane" in f.message for f in findings)

    def test_kernel_vmem_budget_named(self):
        # a qkv plan whose dkv-walk resident set exceeds the gate's bound
        cfg = dict(label="seeded-vmem", b=1, t=2048, dm=2048, h=16, dh=128,
                   dtype="float32")
        findings = []
        kernel_lint.check_qkv_plan(cfg, True, 128, 128, False, findings)
        assert any(f.check == "kernel-vmem-budget" for f in findings), \
            findings

    def test_kernel_alias_mismatch_named(self):
        cfg = dict(label="seeded-alias",
                   tables=[((100, 8), "float32"), ((100, 8), "bfloat16")],
                   batch=32, tiers=1)
        findings = []
        kernel_lint.check_embedding_group(cfg, 32, findings)
        assert any(f.check == "kernel-alias-mismatch" for f in findings), \
            findings

    def test_unregistered_op_named(self):
        prog, startup = pt.Program(), pt.Program()
        with pt.program_guard(prog, startup):
            x = layers.data(name="x", shape=[4], dtype="float32")
        prog.global_block().append_op("no_such_op_type",
                                      inputs={"X": [x.name]},
                                      outputs={"Out": ["o"]})
        findings = verify_program(prog, feed_names=["x"])
        assert "unregistered-op" in _checks(findings), findings

    def test_fetch_unreachable_named(self):
        prog, startup = pt.Program(), pt.Program()
        with pt.program_guard(prog, startup):
            x = layers.data(name="x", shape=[4], dtype="float32")
            layers.relu(x)
        findings = verify_program(prog, feed_names=["x"],
                                  fetch_names=["never_made"])
        hits = [f for f in findings if f.check == "fetch-unreachable"]
        assert hits and hits[0].var == "never_made"


# ---------------------------------------------------------------------------
# green gate: zero findings on the bundled models + kernel matrix
# ---------------------------------------------------------------------------


class TestNoFalsePositives:
    def _verify_clean(self, prog, feeds, fetch, startup=None):
        findings = verify_program(prog, feed_names=feeds,
                                  fetch_names=fetch, check_dead=True)
        assert findings == [], [str(f) for f in findings]
        if startup is not None:
            sfind = verify_program(startup, check_dead=True)
            assert sfind == [], [str(f) for f in sfind]

    def test_mnist_clean(self):
        from paddle_tpu.models import mnist as M

        prog, startup = pt.Program(), pt.Program()
        with pt.program_guard(prog, startup):
            _, _, avg_cost, acc, _ = M.build_train_net()
            pt.optimizer.SGD(learning_rate=0.01).minimize(avg_cost)
        self._verify_clean(prog, ["pixel", "label"],
                           [avg_cost.name, acc.name], startup)

    def test_deepfm_clean(self):
        from paddle_tpu.models import deepfm as D

        prog, startup = pt.Program(), pt.Program()
        with pt.program_guard(prog, startup):
            avg_cost, auc_var, _, feeds = D.build_train_net()
        self._verify_clean(prog, feeds, [avg_cost.name, auc_var.name],
                           startup)

    def test_seq2seq_clean(self):
        from paddle_tpu.models import seq2seq as S

        prog, startup = pt.Program(), pt.Program()
        with pt.program_guard(prog, startup):
            avg_cost = S.build_train_net()
            pt.optimizer.Adam(learning_rate=1e-3).minimize(avg_cost)
        self._verify_clean(prog, ["src_word", "trg_word", "trg_next"],
                           [avg_cost.name], startup)

    def test_weighted_loss_has_no_dead_grad_branch(self):
        """The transformer/BERT pattern that used to leave dead grad ops:
        a stop-gradient weights feed reshaped once and consumed twice
        (numerator mul + denominator reduce_sum).  append_backward must
        prune the branch (backward.py no-grad-branch pruning)."""
        prog, startup = pt.Program(), pt.Program()
        with pt.program_guard(prog, startup):
            x = layers.data(name="x", shape=[4], dtype="float32")
            w = layers.data(name="w", shape=[1], dtype="float32")
            cost = layers.square(layers.fc(x, size=1))
            w2 = layers.reshape(w, [-1, 1])
            weighted = layers.elementwise_mul(cost, w2)
            avg = layers.elementwise_div(
                layers.reduce_sum(weighted), layers.reduce_sum(w2))
            pt.optimizer.SGD(learning_rate=0.1).minimize(avg)
        w2_grad = pt.core.framework.grad_var_name(w2.name)
        writers = [op.type for op in prog.global_block().ops
                   if w2_grad in op.output_arg_names()]
        assert writers == [], writers
        self._verify_clean(prog, ["x", "w"], [avg.name], startup)

    @pytest.mark.slow
    def test_transformer_and_bert_clean(self):
        from paddle_tpu.models import bert as B
        from paddle_tpu.models import transformer as T

        prog, startup = pt.Program(), pt.Program()
        with pt.program_guard(prog, startup):
            avg_cost, _, feeds = T.transformer(
                src_vocab_size=512, trg_vocab_size=512, max_length=64,
                n_layer=2, n_head=4, d_key=32, d_value=32, d_model=128,
                d_inner_hid=256, dropout_rate=0.1, src_seq_len=64,
                trg_seq_len=64, use_flash=True)
            pt.optimizer.Adam(learning_rate=1e-4).minimize(avg_cost)
        self._verify_clean(prog, list(feeds), [avg_cost.name], startup)

        prog, startup = pt.Program(), pt.Program()
        with pt.program_guard(prog, startup):
            avg_loss, _ = B.build_pretrain_net(
                vocab_size=512, seq_len=64, n_layer=2, n_head=4,
                d_model=128, d_ff=256, dropout_rate=0.1, use_flash=True)
        self._verify_clean(
            prog,
            ["src_ids", "pos_ids", "sent_ids", "input_mask",
             "mask_labels", "mask_weights"],
            [avg_loss.name], startup)

    def test_kernel_plan_matrix_clean(self):
        findings, report = lint_kernel_plans()
        assert findings == [], [str(f) for f in findings]
        # every Pallas plan family in kernels/ is covered
        assert set(report) == {
            "attention", "qkv_attention", "conv_bn", "dropout_epilogue",
            "embedding", "ring_attention", "decode_attention",
            "decode_step", "paged_decode_attention", "paged_decode_step",
        }
        for fam, rows in report.items():
            assert rows, fam
        # paged matrix contract: the capacity pair accepts, the
        # misaligned-pool and oversized-table rows reject (block_t is
        # pool geometry — never snapped)
        paged = {r["label"]: r["accepted"]
                 for r in report["paged_decode_attention"]}
        assert paged["paged-base-b1"] and paged["paged-base-b64"]
        assert not paged["paged-bt12-reject"]
        assert not paged["paged-table-overflow-reject"]
        pstep = {r["label"]: r for r in report["paged_decode_step"]}
        assert pstep["paged-megastep-base"]["accepted"]
        assert pstep["paged-megastep-fused-ffn"]["fuse_ffn"]
        assert not pstep["paged-megastep-bt12-reject"]["accepted"]
        assert not pstep[
            "paged-megastep-table-overflow-reject"]["accepted"]
        # the perf-critical plans ACCEPT (no silent fallback regression)
        acc = {r["label"]: r.get("accepted") for r in report["attention"]}
        assert acc["transformer-base-f32"] and acc["bert-base-bf16"]
        assert acc["transformer-base-bthd"]
        qkv = {r["label"]: r["accepted"] for r in report["qkv_attention"]}
        assert qkv["transformer-base-f32"] and qkv["bert-base-bf16"]
        assert not qkv["transformer-smoke"]  # t=64: designed fallback

    def test_attention_bthd_f32_cap_is_dtype_aware(self):
        """Regression for the linter's first real catch: the bthd kv-tile
        cap must scale with dtype (f32 tiles at the bf16 cap reached
        512 KB)."""
        import jax

        from paddle_tpu.kernels import attention as att

        q32 = jax.ShapeDtypeStruct((2, 256, 8, 64), np.float32)
        q16 = jax.ShapeDtypeStruct((2, 256, 8, 64), np.dtype("float16"))
        with kernel_lint._pretend_tpu():
            _, bq32, bk32, _ = att._plan(q32, q32, 512, 512, False, "bthd")
            _, bq16, bk16, _ = att._plan(q16, q16, 512, 512, False, "bthd")
        assert bk32 * 8 * 64 * 4 <= 256 * 1024
        assert bk16 * 8 * 64 * 2 <= 256 * 1024
        assert bk16 >= bk32  # wider dtype -> tighter cap


# ---------------------------------------------------------------------------
# executor wiring: FLAGS_verify_program
# ---------------------------------------------------------------------------


class TestExecutorHook:
    def _count_verifies(self, monkeypatch):
        import paddle_tpu.analysis as an

        calls = []
        real = an.verify_or_raise

        def counting(*a, **k):
            calls.append(1)
            return real(*a, **k)

        monkeypatch.setattr(an, "verify_or_raise", counting)
        return calls

    def test_verify_runs_once_per_signature(self, monkeypatch):
        calls = self._count_verifies(monkeypatch)
        FLAGS.verify_program = True
        prog, startup = pt.Program(), pt.Program()
        with pt.program_guard(prog, startup):
            _, _, loss = _small_train_net()
            pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
        scope, exe = pt.Scope(), pt.Executor(pt.CPUPlace())
        exe.run(startup, scope=scope)
        n0 = len(calls)
        feed = {"x": np.zeros((4, 4), "float32"),
                "y": np.zeros((4, 1), "float32")}
        exe.run(prog, feed=feed, fetch_list=[loss], scope=scope)
        assert len(calls) == n0 + 1
        # warm path: cache hit AND verify memo both skip
        exe.run(prog, feed=feed, fetch_list=[loss], scope=scope)
        assert len(calls) == n0 + 1

    def test_error_finding_blocks_compile(self):
        FLAGS.verify_program = True
        prog, startup = pt.Program(), pt.Program()
        with pt.program_guard(prog, startup):
            x = layers.data(name="x", shape=[4], dtype="float32")
            out = layers.relu(x)
        prog.global_block().append_op("relu", inputs={"X": ["ghost"]},
                                      outputs={"Out": [out.name]})
        exe = pt.Executor(pt.CPUPlace())
        with pytest.raises(ProgramVerifyError) as ei:
            exe.run(prog, feed={"x": np.zeros((2, 4), "float32")},
                    fetch_list=[out], scope=pt.Scope())
        assert "ghost" in str(ei.value)

    def test_flag_off_skips_entirely(self, monkeypatch):
        """The perf guard: with FLAGS_verify_program off the hook makes
        ZERO verifier calls — compile path and hot path both."""
        calls = self._count_verifies(monkeypatch)
        FLAGS.verify_program = False
        prog, startup = pt.Program(), pt.Program()
        with pt.program_guard(prog, startup):
            _, _, loss = _small_train_net()
            pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
        scope, exe = pt.Scope(), pt.Executor(pt.CPUPlace())
        exe.run(startup, scope=scope)
        feed = {"x": np.zeros((4, 4), "float32"),
                "y": np.zeros((4, 1), "float32")}
        for _ in range(3):
            exe.run(prog, feed=feed, fetch_list=[loss], scope=scope)
        assert calls == []

    def test_concurrent_compiles_verify_safely(self):
        """Serving-style concurrency: N threads compile the same program
        at different feed shapes while the verifier (which temporarily
        mutates then restores Variable shapes) runs — the verify lock
        must prevent spurious mismatches and IR corruption."""
        import threading

        FLAGS.verify_program = True
        prog, startup = pt.Program(), pt.Program()
        with pt.program_guard(prog, startup):
            x = layers.data(name="x", shape=[4], dtype="float32")
            out = layers.fc(layers.fc(x, size=8, act="relu"), size=2)
        scope, exe = pt.Scope(), pt.Executor(pt.CPUPlace())
        exe.run(startup, scope=scope)
        shapes_before = {
            n: v.shape for n, v in prog.global_block().vars.items()
        }
        errors = []

        def worker(bs):
            try:
                for _ in range(3):
                    exe.run(prog, feed={"x": np.zeros((bs, 4), "float32")},
                            fetch_list=[out], scope=scope)
            except Exception as e:  # pragma: no cover - the regression
                errors.append(e)

        threads = [threading.Thread(target=worker, args=(bs,))
                   for bs in (1, 2, 3, 4, 5, 6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert errors == [], errors
        shapes_after = {
            n: v.shape for n, v in prog.global_block().vars.items()
        }
        assert shapes_after == shapes_before  # no transient-shape leak

    def test_verify_cost_is_compile_time_only(self):
        """Benchmark note for the perf guard: one verify of a transformer
        block-scale program stays far below XLA-compile scale, and the
        hook pays it once per signature (memoized)."""
        import time

        from paddle_tpu.models import bert as B

        prog, startup = pt.Program(), pt.Program()
        with pt.program_guard(prog, startup):
            avg_loss, _ = B.build_pretrain_net(
                vocab_size=512, seq_len=64, n_layer=2, n_head=4,
                d_model=128, d_ff=256, dropout_rate=0.1, use_flash=True)
        t0 = time.perf_counter()
        findings = verify_program(prog, feed_names=[
            "src_ids", "pos_ids", "sent_ids", "input_mask",
            "mask_labels", "mask_weights"], fetch_names=[avg_loss.name])
        dt = time.perf_counter() - t0
        assert findings == []
        # generous bound: the walk is O(ops); XLA compiles of this program
        # are seconds-scale, the verify is centi-seconds-scale
        assert dt < 5.0, f"verify took {dt:.2f}s"

    def test_serving_warmup_disables_verify(self, tmp_path):
        """'off in hot serving paths after warmup': the SERVER drops the
        flag only once ALL models' ladders are warm (a per-model flip
        would leave later models' warmup compiles unverified)."""
        from paddle_tpu.serving.model import ModelConfig
        from paddle_tpu.serving.server import InferenceServer

        for name in ("m1", "m2"):
            prog, startup = pt.Program(), pt.Program()
            with pt.program_guard(prog, startup):
                x = layers.data(name="x", shape=[6], dtype="float32")
                out = layers.fc(x, size=2)
            scope, exe = pt.Scope(), pt.Executor(pt.CPUPlace())
            with pt.scope_guard(scope):
                exe.run(startup, scope=scope)
                pt.io.save_inference_model(
                    str(tmp_path / name), ["x"], [out], exe,
                    main_program=prog, scope=scope)
        FLAGS.verify_program = True
        srv = InferenceServer([
            ModelConfig("m1", str(tmp_path / "m1"), buckets=(1, 2)),
            ModelConfig("m2", str(tmp_path / "m2"), buckets=(1, 2)),
        ])
        # per-model warmup must NOT flip the gate mid-ladder...
        assert srv.model("m1").warmup() > 0
        assert FLAGS.verify_program is True
        # ...the server-level warmup (all models) does
        assert srv.warmup() > 0
        assert FLAGS.verify_program is False
        from paddle_tpu.serving import server as sv

        assert sv._VERIFY_DROPPED[0] is True
        # a SECOND server in the same process restores the gate for its
        # own planned compiles, then re-drops it (process-global policy)
        srv2 = InferenceServer([
            ModelConfig("m2b", str(tmp_path / "m2"), buckets=(1,))])
        assert srv2.warmup() > 0
        assert FLAGS.verify_program is False


# ---------------------------------------------------------------------------
# CLI + repo lint rules
# ---------------------------------------------------------------------------


class TestTools:
    def test_graph_lint_cli_clean_subset(self, tmp_path):
        out = tmp_path / "graph_lint.json"
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "graph_lint.py"),
             "--models", "mnist,serving", "--skip-kernels",
             "--out", str(out)],
            capture_output=True, text=True, timeout=300,
            env=dict(os.environ, JAX_PLATFORMS="cpu"))
        assert r.returncode == 0, r.stdout + r.stderr
        import json

        rep = json.loads(out.read_text())
        assert rep["total_findings"] == 0
        names = {p["name"] for p in rep["programs"]}
        assert "mnist" in names
        assert any(n.startswith("serving/aot-inference[b") for n in names)

    def test_lint_rules_clean_and_red(self, tmp_path):
        sys.path.insert(0, os.path.join(REPO, "tools"))
        try:
            import lint_rules
        finally:
            sys.path.pop(0)
        flags = lint_rules.declared_flags()
        assert "verify_program" in flags and "monitor" in flags
        bad = tmp_path / "bad.py"
        bad.write_text("from paddle_tpu.flags import FLAGS\n"
                       "v = FLAGS.undeclared_thing\n")
        v = lint_rules.check_file(str(bad), flags)
        assert v and "flags-declared" in v[0][2]
        kdir = tmp_path / "paddle_tpu" / "kernels"
        kdir.mkdir(parents=True)
        kbad = kdir / "k.py"
        kbad.write_text("import time\n\n"
                        "def body(ref):\n    return time.time()\n")
        v = lint_rules.check_file(str(kbad), flags)
        assert v and "no-kernel-time" in v[0][2]
        # the repo itself is clean
        viol = []
        for f in lint_rules.iter_py_files(["paddle_tpu", "tools",
                                           "bench.py"]):
            viol.extend(lint_rules.check_file(f, flags))
        assert viol == [], viol

    def test_finding_repr_roundtrip(self):
        f = Finding("dead-op", "warning", "msg", block_idx=0, op_index=3,
                    op_type="relu", var="v")
        d = f.to_dict()
        assert d["check"] == "dead-op" and d["op_type"] == "relu"
        assert "dead-op" in str(f) and "warning" in str(f)

    def test_verify_or_raise_passes_warnings(self):
        prog, startup = pt.Program(), pt.Program()
        with pt.program_guard(prog, startup):
            _, _, loss = _small_train_net()
            pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
        param = prog.all_parameters()[0].name
        # donated-fetch is warning severity: reported, not raised
        fs = verify_or_raise(prog, feed_names=["x", "y"],
                             fetch_names=[param])
        assert any(f.check == "donated-fetch" for f in fs)
