"""Model/param save-load + inference model serialization
(reference: python/paddle/fluid/io.py:89-843 — save/load_vars/params/
persistables, save/load_inference_model; operators/save_op.cc tensor format).

TPU-first: tensors serialize via numpy `.npz`-style files (one file per var or
combined), programs via the JSON IR (framework.py).  The reference's
per-tensor version header + LoD payload maps to numpy's self-describing
format; checkpoint/resume of optimizer accumulators works because they are
persistable Scope vars, exactly like the reference (SURVEY.md §5.4)."""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Sequence

import numpy as np

from .core import framework as fw
from .core.executor import Scope, global_scope

SAVE_FORMAT_VERSION = 1


# ---------------------------------------------------------------------------
# var save/load
# ---------------------------------------------------------------------------


def _is_persistable(var: fw.Variable) -> bool:
    return var.persistable and not var.is_data


def _is_parameter(var: fw.Variable) -> bool:
    return isinstance(var, fw.Parameter)


def save_vars(
    executor,
    dirname,
    main_program: Optional[fw.Program] = None,
    vars: Optional[Sequence[fw.Variable]] = None,
    predicate=None,
    filename: Optional[str] = None,
    scope: Optional[Scope] = None,
):
    main_program = main_program or fw.default_main_program()
    scope = scope or global_scope()
    if vars is None:
        vars = [
            v
            for v in main_program.list_vars()
            if predicate is None or predicate(v)
        ]
    os.makedirs(dirname, exist_ok=True)
    arrays = {}
    for v in vars:
        val = scope.find_var(v.name)
        if val is None:
            continue
        arr = np.asarray(val)
        if str(arr.dtype) == "bfloat16":
            arrays[v.name] = {"data": arr.astype(np.float32), "dtype": "bfloat16"}
        else:
            arrays[v.name] = {"data": arr, "dtype": str(arr.dtype)}
    if filename is not None:
        np.savez(
            os.path.join(dirname, filename),
            **{k: d["data"] for k, d in arrays.items()},
        )
        meta = {k: d["dtype"] for k, d in arrays.items()}
        with open(os.path.join(dirname, filename + ".meta"), "w") as f:
            json.dump({"version": SAVE_FORMAT_VERSION, "dtypes": meta}, f)
    else:
        for k, d in arrays.items():
            np.save(os.path.join(dirname, k.replace("/", "__")), d["data"])
            with open(os.path.join(dirname, k.replace("/", "__") + ".meta"), "w") as f:
                json.dump({"version": SAVE_FORMAT_VERSION, "dtype": d["dtype"]}, f)


def load_vars(
    executor,
    dirname,
    main_program: Optional[fw.Program] = None,
    vars: Optional[Sequence[fw.Variable]] = None,
    predicate=None,
    filename: Optional[str] = None,
    scope: Optional[Scope] = None,
):
    import jax.numpy as jnp

    main_program = main_program or fw.default_main_program()
    scope = scope or global_scope()
    if vars is None:
        vars = [
            v
            for v in main_program.list_vars()
            if predicate is None or predicate(v)
        ]
    if filename is not None:
        path = os.path.join(dirname, filename)
        if not path.endswith(".npz"):
            path = path + ".npz"
        data = np.load(path)
        meta = {}
        mp = os.path.join(dirname, filename + ".meta")
        if os.path.exists(mp):
            with open(mp) as f:
                meta = json.load(f).get("dtypes", {})
        for v in vars:
            if v.name in data:
                arr = data[v.name]
                val = jnp.asarray(arr)
                if meta.get(v.name) == "bfloat16":
                    val = val.astype(jnp.bfloat16)
                scope.set_var(v.name, val)
    else:
        for v in vars:
            p = os.path.join(dirname, v.name.replace("/", "__") + ".npy")
            if os.path.exists(p):
                arr = np.load(p)
                val = jnp.asarray(arr)
                mp = os.path.join(dirname, v.name.replace("/", "__") + ".meta")
                if os.path.exists(mp):
                    with open(mp) as f:
                        if json.load(f).get("dtype") == "bfloat16":
                            val = val.astype(jnp.bfloat16)
                scope.set_var(v.name, val)


def save_params(executor, dirname, main_program=None, filename=None, scope=None):
    return save_vars(
        executor, dirname, main_program, predicate=_is_parameter,
        filename=filename, scope=scope,
    )


def load_params(executor, dirname, main_program=None, filename=None, scope=None):
    return load_vars(
        executor, dirname, main_program, predicate=_is_parameter,
        filename=filename, scope=scope,
    )


def save_persistables(executor, dirname, main_program=None, filename=None, scope=None):
    """Parameters AND optimizer accumulators / BN stats (reference io.py:270)."""
    return save_vars(
        executor, dirname, main_program, predicate=_is_persistable,
        filename=filename, scope=scope,
    )


def load_persistables(executor, dirname, main_program=None, filename=None, scope=None):
    return load_vars(
        executor, dirname, main_program, predicate=_is_persistable,
        filename=filename, scope=scope,
    )


# ---------------------------------------------------------------------------
# inference model (reference io.py:570 save_inference_model, :704 load)
# ---------------------------------------------------------------------------


def save_inference_model(
    dirname,
    feeded_var_names: List[str],
    target_vars: List[fw.Variable],
    executor,
    main_program: Optional[fw.Program] = None,
    model_filename: Optional[str] = None,
    params_filename: Optional[str] = None,
    scope: Optional[Scope] = None,
    aot_feed_examples: Optional[List[Dict]] = None,
):
    """Save a pruned test-mode program + params (reference io.py:570).

    aot_feed_examples: optional list of feed dicts; for each, an
    AOT-COMPILED XLA EXECUTABLE is serialized next to the artifact
    (`<dirname>/__aot__/`) so a serving process (Predictor built with
    use_aot=True — bundles deserialize via jax's pickle-based executable
    loader, so they are trusted artifacts) can run that feed signature
    with NO re-trace — the TPU-native analogue of the reference's
    out-of-Python C++ serving (api/paddle_api.h:153)."""
    main_program = main_program or fw.default_main_program()
    scope = scope or global_scope()
    os.makedirs(dirname, exist_ok=True)

    pruned = main_program.clone(for_test=True)
    target_names = [v.name for v in target_vars]
    pruned = pruned.prune(target_names)
    pruned.feed_var_names = list(feeded_var_names)
    pruned.fetch_var_names = target_names

    model_path = os.path.join(dirname, model_filename or "__model__")
    with open(model_path, "wb") as f:
        f.write(pruned.serialize_to_string())

    persist = [v for v in pruned.list_vars() if _is_persistable(v)]
    save_vars(
        executor, dirname, pruned, vars=persist,
        filename=params_filename or "__params__", scope=scope,
    )
    if aot_feed_examples:
        from .inference import export_aot_bundle

        export_aot_bundle(dirname, aot_feed_examples,
                          place=getattr(executor, "place", None))
    return target_names


def load_inference_model(
    dirname,
    executor,
    model_filename: Optional[str] = None,
    params_filename: Optional[str] = None,
    scope: Optional[Scope] = None,
):
    scope = scope or global_scope()
    model_path = os.path.join(dirname, model_filename or "__model__")
    with open(model_path, "rb") as f:
        program = fw.Program.parse_from_string(f.read())
    program._is_test = True
    persist = [v for v in program.list_vars() if _is_persistable(v)]
    load_vars(
        executor, dirname, program, vars=persist,
        filename=params_filename or "__params__", scope=scope,
    )
    fetch_vars = [
        program.global_block()._find_var_recursive(n)
        for n in program.fetch_var_names
    ]
    return program, list(program.feed_var_names), fetch_vars


class CheckpointManager:
    """Interval auto-checkpointing with resume-latest (reference: the Go
    pserver's fault-tolerance design — checkpoint to disk on an interval
    with integrity checks + load-on-restart, go/pserver/service.go:119-156,
    174-205; SURVEY §5.3 maps elasticity on TPU to
    restart-from-checkpoint).

        mgr = io.CheckpointManager(dirname, exe, interval_steps=100)
        start = mgr.resume()              # 0 if no checkpoint yet
        for step in range(start, n):
            ... train ...
            mgr.on_step(step)             # saves every interval
    """

    def __init__(self, dirname, executor, interval_steps=100,
                 main_program=None, scope=None, keep_last=2):
        import json

        self.dirname = dirname
        self.executor = executor
        self.interval = max(1, int(interval_steps))
        self.program = main_program or fw.default_main_program()
        self.scope = scope
        self.keep_last = keep_last
        self._json = json
        os.makedirs(dirname, exist_ok=True)

    def _ckpt_dir(self, step):
        return os.path.join(self.dirname, f"ckpt-{step}")

    def _latest_path(self):
        return os.path.join(self.dirname, "LATEST")

    def save(self, step):
        """Write a checkpoint for `step` (persistables incl. optimizer
        accumulators) and atomically advance the LATEST pointer."""
        d = self._ckpt_dir(step)
        tmp = d + ".tmp"
        os.makedirs(tmp, exist_ok=True)
        save_persistables(self.executor, tmp, self.program,
                          scope=self.scope)
        with open(os.path.join(tmp, "meta.json"), "w") as f:
            self._json.dump({"step": int(step)}, f)
        if os.path.exists(d):
            import shutil

            shutil.rmtree(d)
        os.replace(tmp, d)
        # atomic pointer: readers never see a half-written checkpoint
        with open(self._latest_path() + ".tmp", "w") as f:
            f.write(str(int(step)))
        os.replace(self._latest_path() + ".tmp", self._latest_path())
        self._gc()

    def _gc(self):
        import re
        import shutil

        steps = sorted(
            int(m.group(1))
            for m in (re.fullmatch(r"ckpt-(\d+)", n)
                      for n in os.listdir(self.dirname))
            if m
        )
        for s in steps[:-self.keep_last]:
            shutil.rmtree(self._ckpt_dir(s), ignore_errors=True)

    def on_step(self, step):
        if (step + 1) % self.interval == 0:
            self.save(step)

    def latest_step(self):
        try:
            with open(self._latest_path()) as f:
                return int(f.read().strip())
        except (FileNotFoundError, ValueError):
            return None

    def resume(self):
        """Load the latest checkpoint into the scope; returns the next
        step index to run (0 when starting fresh)."""
        step = self.latest_step()
        if step is None:
            return 0
        load_persistables(self.executor, self._ckpt_dir(step),
                          self.program, scope=self.scope)
        return step + 1
