"""Layers DSL (reference: python/paddle/fluid/layers/__init__.py)."""

from .nn import *  # noqa: F401,F403
from .tensor import *  # noqa: F401,F403
from .ops import *  # noqa: F401,F403
from . import nn, tensor, ops, contrib  # noqa: F401

from .tensor import data  # noqa: F401
