"""Optimizers (reference: python/paddle/fluid/optimizer.py:44 Optimizer base;
SGD:407, Momentum:454, LarsMomentum:539, Adagrad:625, Adam:701, Adamax:860,
DecayedAdagrad:993, Adadelta:1078, RMSProp:1175, Ftrl:1325, ModelAverage:1467).

Parity design: `minimize` = append_backward + regularization + clipping +
per-param optimizer ops appended to the program; accumulators are persistable
Scope vars created via the startup program.  On TPU the whole train step —
forward, backward, and these update ops — compiles to one XLA program, so
parameters and moments update in-place in HBM (donated buffers)."""

from __future__ import annotations

from typing import List, Optional, Tuple

from .core import framework as fw
from .core.backward import append_backward
from .initializer import ConstantInitializer
from .layer_helper import LayerHelper
from . import clip as clip_mod
from . import regularizer as reg_mod


class Optimizer:
    def __init__(self, learning_rate, regularization=None, name=None):
        self._learning_rate = learning_rate
        self.regularization = regularization
        self._name = name
        self._accumulators = {}  # name -> {param_name: Variable}
        self._learning_rate_var = None
        self.helper = None
        self.type = "optimizer"

    # -- learning rate ---------------------------------------------------
    def _create_global_learning_rate(self):
        if isinstance(self._learning_rate, fw.Variable):
            self._learning_rate_var = self._learning_rate
            return
        if self._learning_rate_var is not None:
            return
        helper = LayerHelper("learning_rate")
        lr = helper.create_global_variable(
            persistable=True,
            name=fw.unique_name("learning_rate"),
            shape=[1],
            dtype="float32",
        )
        helper.set_variable_initializer(
            lr, ConstantInitializer(float(self._learning_rate))
        )
        self._learning_rate_var = lr

    def _global_learning_rate(self):
        return self._learning_rate_var

    def _create_param_lr(self, param_and_grad):
        param = param_and_grad[0]
        param_lr = getattr(param, "optimize_attr", {}).get("learning_rate", 1.0)
        base = self._global_learning_rate()
        if param_lr == 1.0:
            return base
        helper = LayerHelper("param_lr")
        out = helper.create_variable_for_type_inference("float32")
        helper.append_op(
            "scale",
            inputs={"X": [base]},
            outputs={"Out": [out]},
            attrs={"scale": float(param_lr)},
        )
        return out

    # -- accumulators ------------------------------------------------------
    def _add_accumulator(self, name, param, dtype=None, fill_value=0.0, shape=None):
        if name in self._accumulators and param.name in self._accumulators[name]:
            return self._accumulators[name][param.name]
        helper = LayerHelper(name)
        var = helper.create_global_variable(
            persistable=True,
            name=fw.unique_name(f"{param.name}_{name}"),
            shape=shape or list(param.shape),
            dtype=dtype or param.dtype,
        )
        helper.set_variable_initializer(var, ConstantInitializer(float(fill_value)))
        self._accumulators.setdefault(name, {})[param.name] = var
        return var

    def _get_accumulator(self, name, param):
        return self._accumulators[name][param.name]

    # -- hooks -------------------------------------------------------------
    def _create_accumulators(self, block, parameters):
        pass

    def _append_optimize_op(self, block, param_and_grad):
        raise NotImplementedError

    def _finish_update(self, block, parameters_and_grads):
        pass

    # -- main entry --------------------------------------------------------
    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        return append_backward(loss, parameter_list, no_grad_set, callbacks)

    def apply_gradients(self, params_grads) -> List[fw.Operator]:
        prog = fw.default_main_program()
        block = prog.global_block()
        self._create_global_learning_rate()

        params_grads = clip_mod.append_gradient_clip_ops(params_grads)
        params_grads = reg_mod.append_regularization_ops(
            params_grads, self.regularization
        )

        self._create_accumulators(block, [p for p, g in params_grads])
        ops = []
        for pg in params_grads:
            if pg[1] is None:
                continue
            ops.append(self._append_optimize_op(block, pg))
        self._finish_update(block, params_grads)
        return ops

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        from . import imperative as imp

        if imp.enabled():
            return self._minimize_eager(loss, parameter_list, no_grad_set)
        params_grads = self.backward(
            loss, startup_program, parameter_list, no_grad_set
        )
        optimize_ops = self.apply_gradients(params_grads)
        return optimize_ops, params_grads

    def _minimize_eager(self, loss, parameter_list=None, no_grad_set=None):
        """Dygraph minimize: tape-vjp backward, then the SAME registered
        optimizer ops — appended under the eager hook they execute
        immediately, updating parameter values in the session (the
        reference's dygraph optimizer path reuses its graph ops the same
        way).  Call imperative.clear_gradients() (or layer
        .clear_gradients()) after each step."""
        from . import imperative as imp

        imp.backward(loss)
        session = imp._require_session()
        block = fw.default_main_program().global_block()
        params = parameter_list or fw.default_main_program().all_parameters()
        frozen = {getattr(v, "name", v) for v in (no_grad_set or ())}
        params_grads = []
        for p in params:
            g = session.grads.get(p.name)
            if (g is None or getattr(p, "stop_gradient", False)
                    or p.name in frozen):
                continue
            gv = block.create_var(
                name=fw.unique_name(p.name + "@EGRAD"),
                shape=list(p.shape), dtype=p.dtype)
            gv.stop_gradient = True
            session.values[gv.name] = g
            params_grads.append((p, gv))
        ops = self.apply_gradients(params_grads)
        return ops, params_grads


class SGDOptimizer(Optimizer):
    def __init__(self, learning_rate, regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self.type = "sgd"

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        return block.append_op(
            "sgd",
            inputs={
                "Param": [p],
                "Grad": [g],
                "LearningRate": [self._create_param_lr(param_and_grad)],
            },
            outputs={"ParamOut": [p.name]},
            attrs={fw.OpRole.ROLE_ATTR_NAME: fw.OpRole.Optimize},
        )


class MomentumOptimizer(Optimizer):
    _velocity_acc_str = "velocity"

    def __init__(self, learning_rate, momentum, use_nesterov=False,
                 regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self.type = "momentum"
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._velocity_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        velocity = self._get_accumulator(self._velocity_acc_str, p)
        return block.append_op(
            "momentum",
            inputs={
                "Param": [p],
                "Grad": [g],
                "Velocity": [velocity],
                "LearningRate": [self._create_param_lr(param_and_grad)],
            },
            outputs={"ParamOut": [p.name], "VelocityOut": [velocity.name]},
            attrs={
                "mu": self._momentum,
                "use_nesterov": self._use_nesterov,
                fw.OpRole.ROLE_ATTR_NAME: fw.OpRole.Optimize,
            },
        )


class LarsMomentumOptimizer(Optimizer):
    def __init__(self, learning_rate, momentum, lars_coeff=0.001,
                 lars_weight_decay=0.0005, regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self.type = "lars_momentum"
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._lars_weight_decay = lars_weight_decay

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("velocity", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        velocity = self._get_accumulator("velocity", p)
        return block.append_op(
            "lars_momentum",
            inputs={
                "Param": [p],
                "Grad": [g],
                "Velocity": [velocity],
                "LearningRate": [self._create_param_lr(param_and_grad)],
            },
            outputs={"ParamOut": [p.name], "VelocityOut": [velocity.name]},
            attrs={
                "mu": self._momentum,
                "lars_coeff": self._lars_coeff,
                "lars_weight_decay": self._lars_weight_decay,
                fw.OpRole.ROLE_ATTR_NAME: fw.OpRole.Optimize,
            },
        )


class AdagradOptimizer(Optimizer):
    _moment_acc_str = "moment"

    def __init__(self, learning_rate, epsilon=1e-6, regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self.type = "adagrad"
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._moment_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        moment = self._get_accumulator(self._moment_acc_str, p)
        return block.append_op(
            "adagrad",
            inputs={
                "Param": [p],
                "Grad": [g],
                "Moment": [moment],
                "LearningRate": [self._create_param_lr(param_and_grad)],
            },
            outputs={"ParamOut": [p.name], "MomentOut": [moment.name]},
            attrs={"epsilon": self._epsilon,
                   fw.OpRole.ROLE_ATTR_NAME: fw.OpRole.Optimize},
        )


class AdamOptimizer(Optimizer):
    _moment1_acc_str = "moment1"
    _moment2_acc_str = "moment2"
    _beta1_pow_acc_str = "beta1_pow_acc"
    _beta2_pow_acc_str = "beta2_pow_acc"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, regularization=None, name=None,
                 lazy_mode=False, fuse=False):
        super().__init__(learning_rate, regularization, name)
        self.type = "adam"
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._lazy_mode = lazy_mode
        # fuse=True merges per-param adam ops sharing one LR var into a
        # single multi-tensor adam_multi op.  Default OFF: measured on
        # TPU (round 4), batching loses end-to-end — the concatenated
        # update breaks the scan carry's in-place buffer aliasing, and
        # the while-root copies that reappear cost more than the saved
        # kernel launches (-15% all params, -6% small-params-only).
        # Kept as an opt-in for host-bound/eager scenarios.
        self._fuse = fuse

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._moment1_acc_str, p)
            self._add_accumulator(self._moment2_acc_str, p)
            self._add_accumulator(
                self._beta1_pow_acc_str, p, fill_value=self._beta1, shape=[1]
            )
            self._add_accumulator(
                self._beta2_pow_acc_str, p, fill_value=self._beta2, shape=[1]
            )

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        m1 = self._get_accumulator(self._moment1_acc_str, p)
        m2 = self._get_accumulator(self._moment2_acc_str, p)
        b1p = self._get_accumulator(self._beta1_pow_acc_str, p)
        b2p = self._get_accumulator(self._beta2_pow_acc_str, p)
        return block.append_op(
            "adam",
            inputs={
                "Param": [p],
                "Grad": [g],
                "LearningRate": [self._create_param_lr(param_and_grad)],
                "Moment1": [m1],
                "Moment2": [m2],
                "Beta1Pow": [b1p],
                "Beta2Pow": [b2p],
            },
            outputs={
                "ParamOut": [p.name],
                "Moment1Out": [m1.name],
                "Moment2Out": [m2.name],
                "Beta1PowOut": [b1p.name],
                "Beta2PowOut": [b2p.name],
            },
            attrs={
                "beta1": self._beta1,
                "beta2": self._beta2,
                "epsilon": self._epsilon,
                "lazy_mode": self._lazy_mode,
                fw.OpRole.ROLE_ATTR_NAME: fw.OpRole.Optimize,
            },
        )

    def _finish_update(self, block, parameters_and_grads):
        """fuse=True: replace this instance's per-param `adam` ops that
        share one LearningRate var with a single multi-tensor `adam_multi`
        op (see ops/optimizer_ops.py lower_adam_multi)."""
        if not self._fuse:
            return
        import collections

        groups = collections.defaultdict(list)  # lr name -> [(idx, op)]
        my_params = {p.name for p, g in parameters_and_grads if g is not None}
        for i, op in enumerate(block.ops):
            if (op.type == "adam" and op.input("Param")[0] in my_params
                    and op.attr("beta1") == self._beta1
                    and op.attr("beta2") == self._beta2):
                groups[op.input("LearningRate")[0]].append((i, op))
        to_remove = []
        to_append = []
        for lr_name, entries in groups.items():
            if len(entries) < 2:
                continue
            merged = {s: [] for s in ("Param", "Grad", "Moment1", "Moment2",
                                      "Beta1Pow", "Beta2Pow")}
            outs = {s: [] for s in ("ParamOut", "Moment1Out", "Moment2Out",
                                    "Beta1PowOut", "Beta2PowOut")}
            for _, op in entries:
                for s in merged:
                    merged[s].append(op.input(s)[0])
                for s in outs:
                    outs[s].append(op.output(s)[0])
            merged["LearningRate"] = [lr_name]
            to_remove.extend(i for i, _ in entries)
            to_append.append((merged, outs))
        # remove across ALL groups in one descending pass: removing inside the
        # per-group loop would invalidate the indices recorded for later groups
        for i in sorted(to_remove, reverse=True):
            block.remove_op(i)
        for merged, outs in to_append:
            block.append_op(
                "adam_multi",
                inputs=merged,
                outputs=outs,
                attrs={
                    "beta1": self._beta1,
                    "beta2": self._beta2,
                    "epsilon": self._epsilon,
                    "lazy_mode": self._lazy_mode,
                    fw.OpRole.ROLE_ATTR_NAME: fw.OpRole.Optimize,
                },
            )


class AdamaxOptimizer(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self.type = "adamax"
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)
            self._add_accumulator("inf_norm", p)
            self._add_accumulator("beta1_pow_acc", p, fill_value=self._beta1, shape=[1])

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        moment = self._get_accumulator("moment", p)
        inf_norm = self._get_accumulator("inf_norm", p)
        b1p = self._get_accumulator("beta1_pow_acc", p)
        block.append_op(
            "adamax",
            inputs={
                "Param": [p],
                "Grad": [g],
                "LearningRate": [self._create_param_lr(param_and_grad)],
                "Moment": [moment],
                "InfNorm": [inf_norm],
                "Beta1Pow": [b1p],
            },
            outputs={
                "ParamOut": [p.name],
                "MomentOut": [moment.name],
                "InfNormOut": [inf_norm.name],
            },
            attrs={
                "beta1": self._beta1,
                "beta2": self._beta2,
                "epsilon": self._epsilon,
                fw.OpRole.ROLE_ATTR_NAME: fw.OpRole.Optimize,
            },
        )
        # beta1_pow update (reference appends a scale op per step)
        return block.append_op(
            "scale",
            inputs={"X": [b1p]},
            outputs={"Out": [b1p.name]},
            attrs={"scale": self._beta1,
                   fw.OpRole.ROLE_ATTR_NAME: fw.OpRole.Optimize},
        )


class DecayedAdagradOptimizer(Optimizer):
    def __init__(self, learning_rate, decay=0.95, epsilon=1e-6,
                 regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self.type = "decayed_adagrad"
        self._decay = decay
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        moment = self._get_accumulator("moment", p)
        return block.append_op(
            "decayed_adagrad",
            inputs={
                "Param": [p],
                "Grad": [g],
                "Moment": [moment],
                "LearningRate": [self._create_param_lr(param_and_grad)],
            },
            outputs={"ParamOut": [p.name], "MomentOut": [moment.name]},
            attrs={"decay": self._decay, "epsilon": self._epsilon,
                   fw.OpRole.ROLE_ATTR_NAME: fw.OpRole.Optimize},
        )


class AdadeltaOptimizer(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, rho=0.95,
                 regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self.type = "adadelta"
        self._epsilon = epsilon
        self._rho = rho

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("_avg_squared_grad", p)
            self._add_accumulator("_avg_squared_update", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        asg = self._get_accumulator("_avg_squared_grad", p)
        asu = self._get_accumulator("_avg_squared_update", p)
        return block.append_op(
            "adadelta",
            inputs={
                "Param": [p],
                "Grad": [g],
                "AvgSquaredGrad": [asg],
                "AvgSquaredUpdate": [asu],
            },
            outputs={
                "ParamOut": [p.name],
                "AvgSquaredGradOut": [asg.name],
                "AvgSquaredUpdateOut": [asu.name],
            },
            attrs={"epsilon": self._epsilon, "rho": self._rho,
                   fw.OpRole.ROLE_ATTR_NAME: fw.OpRole.Optimize},
        )


class RMSPropOptimizer(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self.type = "rmsprop"
        self._rho = rho
        self._epsilon = epsilon
        self._momentum = momentum
        self._centered = centered

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("momentum", p)
            self._add_accumulator("mean_square", p)
            self._add_accumulator("mean_grad", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        mom = self._get_accumulator("momentum", p)
        ms = self._get_accumulator("mean_square", p)
        mg = self._get_accumulator("mean_grad", p)
        return block.append_op(
            "rmsprop",
            inputs={
                "Param": [p],
                "Grad": [g],
                "Moment": [mom],
                "MeanSquare": [ms],
                "MeanGrad": [mg],
                "LearningRate": [self._create_param_lr(param_and_grad)],
            },
            outputs={
                "ParamOut": [p.name],
                "MomentOut": [mom.name],
                "MeanSquareOut": [ms.name],
                "MeanGradOut": [mg.name],
            },
            attrs={
                "epsilon": self._epsilon,
                "decay": self._rho,
                "momentum": self._momentum,
                "centered": self._centered,
                fw.OpRole.ROLE_ATTR_NAME: fw.OpRole.Optimize,
            },
        )


class ProximalGDOptimizer(Optimizer):
    """Proximal gradient descent w/ l1/l2 (reference optimizer.py
    ProximalGD / operators/optimizers/proximal_gd_op.cc)."""

    def __init__(self, learning_rate, l1=0.0, l2=0.0, regularization=None,
                 name=None):
        super().__init__(learning_rate, regularization, name)
        self.type = "proximal_gd"
        self._l1 = l1
        self._l2 = l2

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        return block.append_op(
            "proximal_gd",
            inputs={
                "Param": [p],
                "Grad": [g],
                "LearningRate": [self._create_param_lr(param_and_grad)],
            },
            outputs={"ParamOut": [p.name]},
            attrs={"l1": self._l1, "l2": self._l2,
                   fw.OpRole.ROLE_ATTR_NAME: fw.OpRole.Optimize},
        )


class ProximalAdagradOptimizer(Optimizer):
    """Adagrad with proximal l1/l2 regularization (reference optimizer.py
    ProximalAdagrad / operators/optimizers/proximal_adagrad_op.h)."""

    def __init__(self, learning_rate, initial_accumulator_value=0.0,
                 l1=0.0, l2=0.0, regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self.type = "proximal_adagrad"
        self._l1 = l1
        self._l2 = l2
        self._initial_accumulator_value = initial_accumulator_value

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p,
                                  fill_value=self._initial_accumulator_value)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        moment = self._get_accumulator("moment", p)
        return block.append_op(
            "proximal_adagrad",
            inputs={
                "Param": [p],
                "Grad": [g],
                "Moment": [moment],
                "LearningRate": [self._create_param_lr(param_and_grad)],
            },
            outputs={"ParamOut": [p.name], "MomentOut": [moment.name]},
            attrs={"l1": self._l1, "l2": self._l2,
                   fw.OpRole.ROLE_ATTR_NAME: fw.OpRole.Optimize},
        )


class FtrlOptimizer(Optimizer):
    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5,
                 regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self.type = "ftrl"
        self._l1 = l1
        self._l2 = l2
        self._lr_power = lr_power

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("squared", p)
            self._add_accumulator("linear", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        sq = self._get_accumulator("squared", p)
        lin = self._get_accumulator("linear", p)
        return block.append_op(
            "ftrl",
            inputs={
                "Param": [p],
                "Grad": [g],
                "SquaredAccumulator": [sq],
                "LinearAccumulator": [lin],
                "LearningRate": [self._create_param_lr(param_and_grad)],
            },
            outputs={
                "ParamOut": [p.name],
                "SquaredAccumOut": [sq.name],
                "LinearAccumOut": [lin.name],
            },
            attrs={"l1": self._l1, "l2": self._l2, "lr_power": self._lr_power,
                   fw.OpRole.ROLE_ATTR_NAME: fw.OpRole.Optimize},
        )


# short aliases matching the reference's public API
SGD = SGDOptimizer
Momentum = MomentumOptimizer
Adagrad = AdagradOptimizer
Adam = AdamOptimizer
Adamax = AdamaxOptimizer
DecayedAdagrad = DecayedAdagradOptimizer
Adadelta = AdadeltaOptimizer
RMSProp = RMSPropOptimizer
Ftrl = FtrlOptimizer
ProximalGD = ProximalGDOptimizer
ProximalAdagrad = ProximalAdagradOptimizer
LarsMomentum = LarsMomentumOptimizer


class ModelAverage:
    """Windowed running average of parameters for evaluation (reference:
    python/paddle/fluid/optimizer.py:1467 ModelAverage over
    operators/average_accumulates_op.h).

    Call AFTER minimize(): appends per-step accumulation ops to the main
    program, so averaging rides inside the compiled train step.  Window
    semantics follow the reference: per param keep sum_1 (current window),
    sum_3 (last completed window) and counters; once the window length
    num_accumulates reaches
    ``clamp(num_updates * average_window_rate, min_average_window,
    max_average_window)`` the running sum rotates into sum_3 and restarts,
    so the average always covers roughly the last 1-2 windows of steps
    rather than the whole history.  (The reference's extra sum_2 tier is a
    2018-era int-overflow guard for its 16384-step partial sums; a single
    fp32 sum per window is kept here — documented simplification.)

    `apply(executor)` swaps averaged weights in (a context manager —
    weights restore on exit), mirroring the reference's apply/restore
    programs."""

    def __init__(self, average_window_rate=0.15, min_average_window=10000,
                 max_average_window=10000, program=None,
                 startup_program=None):
        from . import layers
        from .core import framework as fw

        self.program = program or fw.default_main_program()
        startup = startup_program or fw.default_startup_program()
        self._pairs = []  # (param, sum_1, sum_3, n_acc, n_old, n_upd)
        with fw.program_guard(self.program, startup):
            # shared step counters (scalar, fp32 so `where` stays uniform)
            n_acc = layers.create_global_var(
                shape=[1], value=0.0, dtype="float32", persistable=True,
                name=fw.unique_name("model_avg.num_accumulates"))
            n_old = layers.create_global_var(
                shape=[1], value=0.0, dtype="float32", persistable=True,
                name=fw.unique_name("model_avg.old_num_accumulates"))
            n_upd = layers.create_global_var(
                shape=[1], value=0.0, dtype="float32", persistable=True,
                name=fw.unique_name("model_avg.num_updates"))
            new_acc = layers.elementwise_add(
                n_acc, layers.fill_constant([1], "float32", 1.0))
            new_upd = layers.elementwise_add(
                n_upd, layers.fill_constant([1], "float32", 1.0))
            # window = clamp(num_updates*rate, min_window, max_window)
            thr = layers.clip(
                layers.scale(new_upd, scale=float(average_window_rate)),
                min=float(min_average_window), max=float(max_average_window))
            rotate = layers.less_than(thr, new_acc + 1e-6)  # new_acc >= thr
            zero1 = layers.fill_constant([1], "float32", 0.0)

            params = [p for p in self.program.all_parameters()
                      if getattr(p, "trainable", True)]
            for p in params:
                sum_1 = layers.create_global_var(
                    shape=list(p.shape), value=0.0, dtype="float32",
                    persistable=True, name=f"{p.name}.avg_sum_1")
                sum_3 = layers.create_global_var(
                    shape=list(p.shape), value=0.0, dtype="float32",
                    persistable=True, name=f"{p.name}.avg_sum_3")
                new_sum = layers.elementwise_add(
                    sum_1, layers.cast(p, "float32"))
                # on rotation: sum_3 <- current window's sum, sum_1 <- 0
                # (zero1 broadcasts against any param shape)
                layers.assign(layers.where(rotate, new_sum, sum_3),
                              output=sum_3)
                layers.assign(layers.where(rotate, zero1, new_sum),
                              output=sum_1)
                self._pairs.append((p, sum_1, sum_3, n_acc, n_old, n_upd))
            # counter write-back (shared; after the per-param rotation)
            layers.assign(layers.where(rotate, new_acc, n_old), output=n_old)
            layers.assign(layers.where(rotate, zero1, new_acc), output=n_acc)
            layers.assign(new_upd, output=n_upd)

    import contextlib as _ctx

    @_ctx.contextmanager
    def apply(self, executor, need_restore=True, scope=None):
        import numpy as np

        from .core.executor import global_scope

        scope = scope or global_scope()
        saved = {}
        for p, s1, s3, n_acc, n_old, _ in self._pairs:
            pv = scope.find_var(p.name)
            s1v = np.asarray(scope.find_var(s1.name))
            s3v = np.asarray(scope.find_var(s3.name))
            nv = (float(np.asarray(scope.find_var(n_acc.name)).reshape(-1)[0])
                  + float(np.asarray(scope.find_var(n_old.name)).reshape(-1)[0]))
            if nv <= 0:
                continue
            saved[p.name] = pv
            avg = ((s1v + s3v) / nv).astype(str(
                np.asarray(pv).dtype) if pv is not None else "float32")
            scope.set_var(p.name, avg)
        try:
            yield
        finally:
            if need_restore:
                for name, val in saved.items():
                    scope.set_var(name, val)

    def restore(self, executor, scope=None):
        """No-op (apply() is a context manager that restores on exit);
        kept for reference-signature parity."""
