"""Fused-projection flash attention (PERF.md round 9,
FLAGS_fused_qkv_attention).

Covers the r09 acceptance contract:
  * numerical parity + gradcheck of flash_qkv_attention (interpret
    kernels) against the composed x@W + flash_attention(bthd) + @W_out
    path — fp32/bf16, causal/bias shapes, dropout on/off (hash masks are
    BIT-identical to the unfused kernels', so fused-vs-unfused train
    trajectories match exactly on CPU);
  * op/program level: one train step of the bundled models with the flag
    on vs off matches (loss, every updated parameter), dropout
    trajectories included; parameter names identical across the flag
    (checkpoint interop, transplant-tested); amp; is_test;
  * zero-cost-off: flag off => the model builders emit the exact op
    sequence of the pre-r09 fc+split+fused_attention+fc composition and
    its compiled HLO is bit-identical to the hand-written legacy copy;
  * the hlo_diag --copy-census report: the fused path holds zero
    projection-site copy bytes (and no more than the unfused path
    anywhere);
  * a TPU-only class that arms on the driver's chip (compiled Mosaic
    kernels vs the composed reference + hw-PRNG dropout determinism).
"""

import contextlib
import importlib.util
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.core import framework as fw
from paddle_tpu.flags import FLAGS
from paddle_tpu.kernels.attention import (
    _composed_qkv,
    flash_qkv_attention,
)
from paddle_tpu.models import bert as B
from paddle_tpu.models import transformer as T


@contextlib.contextmanager
def _fused_qkv(flag):
    """Set FLAGS.fused_qkv_attention, restoring the previous override on
    exit (nestable — same discipline as test_conv_bn's _fused_bn)."""
    values = object.__getattribute__(FLAGS, "_values")
    had = "fused_qkv_attention" in values
    prev = values.get("fused_qkv_attention")
    FLAGS.fused_qkv_attention = flag
    try:
        yield
    finally:
        if had:
            FLAGS.fused_qkv_attention = prev
        else:
            FLAGS.reset("fused_qkv_attention")


def _hlo_diag():
    path = os.path.join(os.path.dirname(__file__), "..", "tools",
                        "hlo_diag.py")
    spec = importlib.util.spec_from_file_location("_hlo_diag_mod", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _mk(rng, *shape, s=0.08):
    return jnp.asarray((rng.randn(*shape) * s).astype("float32"))


def _inputs(b=2, t=128, h=2, dh=64, dm=128, seed=0):
    rng = np.random.RandomState(seed)
    x = _mk(rng, b, t, dm, s=0.3)
    w_qkv = _mk(rng, dm, 3 * h * dh)
    w_out = _mk(rng, h * dh, dm)
    pad_bias = jnp.asarray(
        np.where(rng.rand(b, 1, 1, t) < 0.2, -1e9, 0.0).astype("float32"))
    return x, w_qkv, w_out, pad_bias


_ZSEED = jnp.zeros((1,), jnp.uint32)


class TestKernels:
    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("with_bias", [False, True])
    def test_fwd_parity_fp32(self, causal, with_bias):
        x, w_qkv, w_out, bias = _inputs()
        bias = bias if with_bias else None
        fused = flash_qkv_attention(
            x, w_qkv, w_out, bias, n_head=2, scale=0.125, causal=causal,
            block_q=64, block_k=64, interpret=True)
        ref = _composed_qkv(x, w_qkv, w_out, bias, 2, 0.125, causal,
                            64, 64, True, 0.0, _ZSEED, False)
        np.testing.assert_allclose(np.asarray(fused), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    @pytest.mark.parametrize("bias_shape", [
        (1, 1, 1, 128),    # broadcast padding mask
        (2, 1, 128, 128),  # per-batch causal+pad plane (the decoder's)
        (1, 2, 1, 128),    # per-head key bias
        (2, 2, 128, 128),  # fully-expanded
    ])
    def test_fwd_parity_bias_shapes(self, bias_shape):
        x, w_qkv, w_out, _ = _inputs()
        rng = np.random.RandomState(3)
        bias = jnp.asarray(
            np.where(rng.rand(*bias_shape) < 0.15, -1e9, 0.0)
            .astype("float32"))
        fused = flash_qkv_attention(
            x, w_qkv, w_out, bias, n_head=2, scale=0.125,
            block_q=64, block_k=64, interpret=True)
        ref = _composed_qkv(x, w_qkv, w_out, bias, 2, 0.125, False,
                            64, 64, True, 0.0, _ZSEED, False)
        np.testing.assert_allclose(np.asarray(fused), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_gradcheck_vs_composed(self):
        """dx, dW_qkv, dW_out AND dbias (trainable-bias recompute) against
        jax.grad of the composed path — the in-kernel projection backward
        + grid-accumulated weight cotangents are numerically the unfused
        autodiff."""
        x, w_qkv, w_out, bias = _inputs()

        def lf(x, wq, wo, bias):
            return jnp.sum(flash_qkv_attention(
                x, wq, wo, bias, n_head=2, scale=0.125, causal=True,
                block_q=64, block_k=64, interpret=True) ** 2)

        def lr(x, wq, wo, bias):
            return jnp.sum(_composed_qkv(
                x, wq, wo, bias, 2, 0.125, True, 64, 64, True, 0.0,
                _ZSEED, True) ** 2)

        gf = jax.grad(lf, (0, 1, 2, 3))(x, w_qkv, w_out, bias)
        gr = jax.grad(lr, (0, 1, 2, 3))(x, w_qkv, w_out, bias)
        for name, a, b in zip(("dx", "dw_qkv", "dw_out", "dbias"), gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=1e-6, err_msg=name)

    def test_dropout_parity_and_grads(self):
        """In-kernel weights-dropout: the per-head hash masks are
        bit-identical to the unfused bthd kernels' (same (seed, b*H+h,
        q*Tk+k) keying), so fused output AND gradients match the composed
        path exactly — the mechanism behind the CPU A/B trajectory
        identity."""
        x, w_qkv, w_out, bias = _inputs()
        seed = jnp.asarray([77], jnp.uint32)

        def lf(x, wq, wo):
            return jnp.sum(flash_qkv_attention(
                x, wq, wo, bias, n_head=2, scale=0.125, block_q=64,
                block_k=64, interpret=True, dropout_rate=0.1,
                dropout_seed=seed, trainable_bias=False) ** 2)

        def lr(x, wq, wo):
            return jnp.sum(_composed_qkv(
                x, wq, wo, bias, 2, 0.125, False, 64, 64, True, 0.1,
                seed, False) ** 2)

        np.testing.assert_allclose(float(lf(x, w_qkv, w_out)),
                                   float(lr(x, w_qkv, w_out)), rtol=1e-5)
        gf = jax.grad(lf, (0, 1, 2))(x, w_qkv, w_out)
        gr = jax.grad(lr, (0, 1, 2))(x, w_qkv, w_out)
        for name, a, b in zip(("dx", "dw_qkv", "dw_out"), gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=1e-6, err_msg=name)

    def test_bf16(self):
        x, w_qkv, w_out, bias = _inputs()
        xb, wqb, wob = (a.astype(jnp.bfloat16) for a in (x, w_qkv, w_out))
        fused = flash_qkv_attention(xb, wqb, wob, bias, n_head=2,
                                    scale=0.125, block_q=64, block_k=64,
                                    interpret=True)
        assert fused.dtype == jnp.bfloat16
        ref = _composed_qkv(xb, wqb, wob, bias, 2, 0.125, False, 64, 64,
                            True, 0.0, _ZSEED, False)
        f32 = np.asarray(fused.astype(jnp.float32))
        r32 = np.asarray(ref.astype(jnp.float32))
        scale = np.abs(r32).max() + 1e-6
        assert np.abs(f32 - r32).max() < 0.05 * scale

    def test_plan_reject_falls_back_composed(self):
        """d_head not a lane multiple: the plan rejects and the public
        entry returns the composed path's numbers (no crash, no drift)."""
        rng = np.random.RandomState(5)
        x = _mk(rng, 2, 16, 24, s=0.3)
        w_qkv = _mk(rng, 24, 3 * 2 * 8)   # d_head=8 -> reject
        w_out = _mk(rng, 16, 24)
        got = flash_qkv_attention(x, w_qkv, w_out, None, n_head=2,
                                  scale=0.35, interpret=True)
        want = _composed_qkv(x, w_qkv, w_out, None, 2, 0.35, False, 512,
                             512, None, 0.0, _ZSEED, False)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-6)

    def test_wout_none_returns_context(self):
        x, w_qkv, _, _ = _inputs(t=64)
        got = flash_qkv_attention(x, w_qkv, None, None, n_head=2,
                                  scale=0.125, interpret=True)
        assert got.shape == (2, 64, 128)


def _build_bert(flag, dropout=0.0, seq=32, opt=True):
    """Mini BERT MLM net (1 layer, d_head 64 so the fused kernel plan is
    feasible in interpret mode)."""
    with _fused_qkv(flag):
        fw._rng_id_counter[0] = 0
        prog, startup = pt.Program(), pt.Program()
        with fw.guard_unique_name():
            with pt.program_guard(prog, startup):
                loss, _ = B.build_pretrain_net(
                    vocab_size=64, seq_len=seq, n_layer=1, n_head=2,
                    d_model=128, d_ff=128, dropout_rate=dropout,
                    use_flash=True, with_optimizer=opt, lr=1e-3)
    return prog, startup, loss


def _bert_feed(seq=32, seed=0):
    return B.make_batch(2, seq, 64, rng=np.random.RandomState(seed))


def _init_params(prog, scope, seed=7):
    r = np.random.RandomState(seed)
    for p in prog.all_parameters():
        v = np.asarray(scope.find_var(p.name))
        scope.set_var(p.name, (r.randn(*v.shape) * 0.05).astype(v.dtype))


_TRAIN_CACHE = {}


def _trained(flag, dropout=0.0, steps=3):
    """Cached (losses, params) of `steps` Adam steps of the mini BERT —
    several tests compare the same trajectories, one train each."""
    key = (flag, dropout, steps)
    if key not in _TRAIN_CACHE:
        prog, startup, loss = _build_bert(flag, dropout=dropout)
        _TRAIN_CACHE[key] = _train(prog, startup, loss, flag,
                                   dropout_steps=steps)[:2]
    return _TRAIN_CACHE[key]


def _train(prog, startup, loss, flag, dropout_steps=3, feed_seed=0,
           amp=False):
    exe = pt.Executor(pt.CPUPlace())
    scope = pt.Scope()
    exe.run(startup, scope=scope)
    _init_params(prog, scope)
    if amp:
        pt.amp.enable(prog)
    losses = []
    with _fused_qkv(flag):
        for i in range(dropout_steps):
            (lv,) = exe.run(prog, feed=_bert_feed(seed=feed_seed),
                            fetch_list=[loss], scope=scope)
            losses.append(float(np.asarray(lv).reshape(-1)[-1]))
    params = {p.name: np.asarray(scope.find_var(p.name))
              for p in prog.all_parameters()}
    return losses, params, (exe, scope)


class TestOpProgram:
    def test_flag_on_vs_off_one_train_step(self):
        """Loss trajectory AND every updated parameter match across the
        flag (3 Adam steps of the mini BERT; dropout off => the only
        difference is the fused kernels vs the composed dots)."""
        for flag in (True, False):
            prog, _, _ = _build_bert(flag)
            ops = [op.type for op in prog.global_block().ops]
            if flag:
                assert "fused_qkv_attention" in ops
                assert "fused_attention" not in ops
            else:
                assert "fused_qkv_attention" not in ops
                assert "fused_attention" in ops
        lf, pf = _trained(True)
        lr_, pr = _trained(False)
        np.testing.assert_allclose(lf, lr_, rtol=1e-5, atol=1e-6)
        assert pf.keys() == pr.keys()
        for k in pf:
            np.testing.assert_allclose(pf[k], pr[k], rtol=5e-4, atol=1e-6,
                                       err_msg=k)

    @pytest.mark.slow
    def test_dropout_trajectory_identical(self):
        """Dropout ON: the in-kernel hash masks key on the same (seed,
        head, plane-index) tuples as the unfused kernels, so even the
        DROPPED trajectories are identical across the flag on CPU."""
        on = _trained(True, dropout=0.1)[0]
        off = _trained(False, dropout=0.1)[0]
        np.testing.assert_allclose(on, off, rtol=1e-5, atol=1e-6)
        # sanity: dropout actually differs from the no-dropout trajectory
        nodrop = _trained(True, dropout=0.0)[0]
        assert abs(nodrop[-1] - on[-1]) > 1e-7

    def test_param_names_identical_across_flag(self):
        """Checkpoint interop: the fused build creates the exact param
        names/shapes of the unfused fc+split+attention+fc composition."""
        shapes = {}
        for flag in (True, False):
            prog, _, _ = _build_bert(flag)
            shapes[flag] = sorted(
                (p.name, tuple(p.shape)) for p in prog.all_parameters())
        assert shapes[True] == shapes[False]

    @pytest.mark.slow
    def test_checkpoint_interop_across_flag(self):
        """Train 2 steps with the flag ON, transplant the checkpoint into
        a flag-OFF program (and back), evaluate: identical losses — the
        packed [dm, 3hd]/[hd, dm] parameters are the same tensors either
        way.  Slow lane: test_param_names_identical_across_flag is the
        fast tripwire for the same interop contract."""
        _, params = _trained(True)

        def eval_with(flag, params):
            prog, startup, loss = _build_bert(flag)
            exe = pt.Executor(pt.CPUPlace())
            scope = pt.Scope()
            exe.run(startup, scope=scope)
            for name, val in params.items():
                scope.set_var(name, val)
            prog._is_test = True
            with _fused_qkv(flag):
                (lv,) = exe.run(prog, feed=_bert_feed(),
                                fetch_list=[loss], scope=scope)
            return float(np.asarray(lv).reshape(-1)[-1])

        on = eval_with(True, params)
        off = eval_with(False, params)
        assert abs(on - off) < 1e-5, (on, off)

    @pytest.mark.slow
    def test_amp_step_finite_and_close(self):
        la = _train(*_build_bert(True, dropout=0.1)[:3], True, amp=True)[0]
        lb = _train(*_build_bert(False, dropout=0.1)[:3], False,
                    amp=True)[0]
        assert all(np.isfinite(la)) and all(np.isfinite(lb))
        np.testing.assert_allclose(la, lb, rtol=0.02, atol=0.02)

    def test_is_test_disables_dropout(self):
        prog, startup, loss = _build_bert(True, dropout=0.4, opt=False)
        exe = pt.Executor(pt.CPUPlace())
        scope = pt.Scope()
        exe.run(startup, scope=scope)
        _init_params(prog, scope)
        prog._is_test = True
        with _fused_qkv(True):
            a = float(np.asarray(exe.run(prog, feed=_bert_feed(),
                                         fetch_list=[loss],
                                         scope=scope)[0]).reshape(-1)[-1])
            b = float(np.asarray(exe.run(prog, feed=_bert_feed(),
                                         fetch_list=[loss],
                                         scope=scope)[0]).reshape(-1)[-1])
        assert abs(a - b) < 1e-7  # deterministic: no dropout draws


# -- zero-cost-off ----------------------------------------------------------


def _legacy_flash_mha(queries, attn_bias, d_key, d_value, d_model, n_head,
                      dropout_rate):
    """Verbatim pre-r09 self-attention flash path (the 'today' this PR
    must preserve with the flag off): one packed qkv fc + split + bthd
    fused_attention + output fc."""
    from paddle_tpu.core.framework import unique_name
    from paddle_tpu.layers.contrib import fused_attention
    from paddle_tpu.param_attr import ParamAttr

    qkv = layers.fc(input=queries, size=3 * d_key * n_head,
                    bias_attr=False, num_flatten_dims=2,
                    param_attr=ParamAttr(name=unique_name("attn_qkv_w")))
    q, k, v = layers.split(qkv, 3, dim=-1)

    def to_bthd(x, d):
        b, t, _ = x.shape
        return layers.reshape(x, [b, t, n_head, d])

    ctx = fused_attention(
        to_bthd(q, d_key), to_bthd(k, d_key), to_bthd(v, d_value),
        attn_bias, scale=d_key**-0.5, dropout_rate=dropout_rate,
        fmt="bthd",
    )
    b, t, h, d = ctx.shape
    ctx = layers.reshape(ctx, [b, t, h * d])
    return layers.fc(input=ctx, size=d_model, bias_attr=False,
                     num_flatten_dims=2,
                     param_attr=ParamAttr(name=unique_name("attn_out_w")))


def _build_mha_net(builder):
    """Tiny self-attention net around `builder(x, bias) -> out`."""
    fw._rng_id_counter[0] = 0
    prog, startup = pt.Program(), pt.Program()
    with fw.guard_unique_name():
        with pt.program_guard(prog, startup):
            x = layers.data(name="x", shape=[32, 128], dtype="float32")
            mask = layers.data(name="mask", shape=[32, 1],
                               dtype="float32")
            neg = layers.scale(layers.transpose(mask, [0, 2, 1]),
                               scale=1e9, bias=-1e9)
            bias = layers.reshape(neg, [-1, 1, 1, 32])
            bias.stop_gradient = True
            out = builder(x, bias)
            loss = layers.mean(out)
            pt.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)
    return prog, startup, loss


def _mha_feed(seed=0):
    rng = np.random.RandomState(seed)
    return {
        "x": (rng.randn(2, 32, 128) * 0.2).astype("float32"),
        "mask": (rng.rand(2, 32, 1) > 0.2).astype("float32"),
    }


def _lower_hlo(exe, prog, startup, loss, feed):
    scope = pt.Scope()
    exe.run(startup, scope=scope)
    exe.run_steps(prog, feed={k: v[None] for k, v in feed.items()},
                  fetch_list=[loss], scope=scope)
    from paddle_tpu.core.executor import latest_jitted_entry

    entry = latest_jitted_entry(exe)
    rw = [scope.find_var(n) for n in entry.rw_state]
    ro = [scope.find_var(n) for n in entry.ro_state]
    feed_names = sorted(feed)
    feed_vals = [exe._to_device_array(prog, n, feed[n][None])
                 for n in feed_names]
    key = jax.random.PRNGKey(0)
    return entry.jitted.lower(feed_vals, rw, ro, key).compile().as_text()


class TestZeroCostOff:
    def _model_mha(self, x, bias):
        return T.multi_head_attention(
            x, None, None, bias, 64, 64, 128, n_head=2,
            dropout_rate=0.1, use_flash=True)

    def _legacy_mha(self, x, bias):
        return _legacy_flash_mha(x, bias, 64, 64, 128, 2, 0.1)

    def test_flag_off_graph_identical_to_legacy(self):
        with _fused_qkv(False):
            prog_off, _, _ = _build_mha_net(self._model_mha)
        prog_leg, _, _ = _build_mha_net(self._legacy_mha)
        ops_off = [op.type for op in prog_off.global_block().ops]
        ops_leg = [op.type for op in prog_leg.global_block().ops]
        assert ops_off == ops_leg
        assert "fused_qkv_attention" not in ops_off

    def test_flag_on_graph_single_op(self):
        with _fused_qkv(True):
            prog_on, _, _ = _build_mha_net(self._model_mha)
        ops = [op.type for op in prog_on.global_block().ops]
        assert ops.count("fused_qkv_attention") == 1
        # the boundary dots are gone from the graph: the only remaining
        # mul is... none — qkv, split and the output fc all folded in
        assert "split" not in ops
        assert "fused_attention" not in ops

    @pytest.mark.slow
    def test_flag_off_hlo_identical_to_legacy(self):
        # slow lane: the op-sequence identity above is the fast
        # tripwire; this compiles both nets to cross-check the HLO text
        with _fused_qkv(False):
            exe = pt.Executor(pt.CPUPlace())
            prog_off, st_off, loss_off = _build_mha_net(self._model_mha)
            h_off = _lower_hlo(exe, prog_off, st_off, loss_off,
                               _mha_feed())
            exe2 = pt.Executor(pt.CPUPlace())
            prog_leg, st_leg, loss_leg = _build_mha_net(self._legacy_mha)
            h_leg = _lower_hlo(exe2, prog_leg, st_leg, loss_leg,
                               _mha_feed())
        assert h_off == h_leg


class TestCopyCensus:
    def test_fused_drives_projection_site_bytes_to_zero(self):
        """tools/hlo_diag.py --copy-census on the mini attention net: the
        fused path holds ZERO projection-site (math_ops.py mul) copy
        bytes and no more pallas-boundary bytes than the unfused path.
        (On CPU the XLA layouts are trivial so both sides are small; the
        1.2 GB claim is re-measured on the driver's chip by the same
        census — TestFusedQkvTPU.)"""
        hd = _hlo_diag()
        reps = {}
        for flag in (True, False):
            with _fused_qkv(flag):
                exe = pt.Executor(pt.CPUPlace())
                prog, st, loss = _build_mha_net(
                    TestZeroCostOff()._model_mha)
                reps[flag] = hd.analyze_copy_census(
                    _lower_hlo(exe, prog, st, loss, _mha_feed()))
        on, off = reps[True], reps[False]
        assert on["sites"]["projection"]["mb"] == 0.0, on
        assert (on["sites"]["projection"]["mb"]
                <= off["sites"]["projection"]["mb"])
        assert on["sites"]["pallas"]["mb"] <= off["sites"]["pallas"]["mb"]
        assert "copy census by site" in hd.format_copy_census(on)


class TestRingBthd:
    def test_ring_model_path_has_no_transposes(self):
        """The CP model path on fmt='bthd': no transpose op anywhere in
        the attention block (the satellite contract: context parallelism
        must not re-introduce split-head transposes)."""
        fw._rng_id_counter[0] = 0
        prog, startup = pt.Program(), pt.Program()
        with fw.guard_unique_name():
            with pt.program_guard(prog, startup):
                x = layers.data(name="x", shape=[32, 128],
                                dtype="float32")
                out = T.multi_head_attention(
                    x, None, None, None, 64, 64, 128, n_head=2,
                    use_ring=True)
        ops = [op.type for op in prog.global_block().ops]
        assert "ring_attention" in ops
        assert "transpose2" not in ops and "transpose" not in ops


@pytest.mark.skipif(jax.default_backend() != "tpu",
                    reason="compiled Mosaic kernel paths need a TPU")
class TestFusedQkvTPU:
    """Arms on the driver's chip: the COMPILED fused-projection kernels
    (not interpret mode) against the composed reference, hw-PRNG dropout
    determinism, and the on-chip census claim."""

    def test_kernel_parity_compiled(self):
        rng = np.random.RandomState(0)
        b, t, h, dh, dm = 2, 256, 8, 64, 512
        x = jnp.asarray((rng.randn(b, t, dm) * 0.2).astype("float32")
                        ).astype(jnp.bfloat16)
        w_qkv = jnp.asarray((rng.randn(dm, 3 * h * dh) * 0.04)
                            .astype("float32")).astype(jnp.bfloat16)
        w_out = jnp.asarray((rng.randn(h * dh, dm) * 0.04)
                            .astype("float32")).astype(jnp.bfloat16)
        scale = dh ** -0.5

        fused = jax.jit(lambda *a: flash_qkv_attention(
            *a, n_head=h, scale=scale, causal=True))(x, w_qkv, w_out)
        ref = jax.jit(lambda *a: _composed_qkv(
            a[0], a[1], a[2], None, h, scale, True, 512, 512, None, 0.0,
            _ZSEED, False))(x, w_qkv, w_out)
        f = np.asarray(fused.astype(jnp.float32))
        r = np.asarray(ref.astype(jnp.float32))
        assert np.abs(f - r).max() < 0.05 * (np.abs(r).max() + 1e-6)

        def lf(x, wq, wo):
            return jnp.sum(flash_qkv_attention(
                x, wq, wo, None, n_head=h, scale=scale,
                causal=True).astype(jnp.float32) * 1e-3)

        def lr(x, wq, wo):
            return jnp.sum(_composed_qkv(
                x, wq, wo, None, h, scale, True, 512, 512, None, 0.0,
                _ZSEED, False).astype(jnp.float32) * 1e-3)

        gf = jax.jit(jax.grad(lf, (0, 1, 2)))(x, w_qkv, w_out)
        gr = jax.jit(jax.grad(lr, (0, 1, 2)))(x, w_qkv, w_out)
        for i, (a, b_) in enumerate(zip(gf, gr)):
            a = np.asarray(a.astype(jnp.float32))
            b_ = np.asarray(b_.astype(jnp.float32))
            assert np.abs(a - b_).max() < 0.05 * (np.abs(b_).max() + 1e-6), i

    def test_hw_prng_dropout_deterministic(self):
        """Same seed => bit-identical output (fwd/bwd tile regeneration
        is the whole correctness story of the hw-PRNG path)."""
        rng = np.random.RandomState(1)
        b, t, h, dh, dm = 2, 256, 8, 64, 512
        x = jnp.asarray((rng.randn(b, t, dm) * 0.2).astype("float32"))
        w_qkv = _mk(rng, dm, 3 * h * dh, s=0.04)
        w_out = _mk(rng, h * dh, dm, s=0.04)
        seed = jnp.asarray([99], jnp.uint32)
        f = jax.jit(lambda *a: flash_qkv_attention(
            *a, n_head=h, scale=dh**-0.5, dropout_rate=0.1,
            dropout_seed=seed))
        a = np.asarray(f(x, w_qkv, w_out))
        b_ = np.asarray(f(x, w_qkv, w_out))
        np.testing.assert_array_equal(a, b_)

    def test_census_projection_copies_eliminated_on_chip(self):
        """The r09 acceptance attribution, compiled for the real chip:
        the fused path eliminates the projection-site relayout copy bytes
        the unfused composition pays (PERF.md post-r08 lead 1)."""
        hd = _hlo_diag()
        reps = {}
        for flag in (True, False):
            with _fused_qkv(flag):
                exe = pt.Executor()
                prog, st, loss = _build_mha_net(
                    TestZeroCostOff()._model_mha)
                reps[flag] = hd.analyze_copy_census(
                    _lower_hlo(exe, prog, st, loss, _mha_feed()))
        # the DIFF isolates the attention-projection subset (this mini
        # net has no FFN, so the dot tier should empty outright; the
        # full-model census keeps FFN mul relayouts on both sides)
        assert (reps[True]["sites"]["projection"]["mb"]
                <= reps[False]["sites"]["projection"]["mb"])
        assert reps[True]["sites"]["projection"]["mb"] == 0.0
