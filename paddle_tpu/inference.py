"""Inference serving: Predictor with an AOT executable cache + the
BN-fold inference optimization pass.

Reference parity:
  * PaddlePredictor / NativeConfig — inference/api/paddle_api.h:153,200,
    api/api_impl.h:34 (NativePaddlePredictor): load a saved model once,
    then serve many Run() calls with no per-call graph work.
  * AnalysisPredictor pass pipeline — api/analysis_predictor.h:45,
    analysis/analyzer.cc: IR optimization before serving; the first pass
    delivered here is conv/fc + batch_norm folding, the reference's
    inference_transpiler.py:1 / conv_bn_fuse_pass.cc.

TPU-first: the "executable cache" is the Executor's fingerprint-keyed XLA
compile cache — Run() re-traces nothing after the first call per feed
signature; parameters stay resident in the Predictor's private Scope (HBM)
across calls, mirroring ir_params_sync_among_devices_pass.cc's
params-frozen-to-device behavior.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from . import io
from .core import framework as fw
from .core.executor import CPUPlace, Executor, Scope


def _consumers(block: fw.Block, name: str) -> List[fw.Operator]:
    return [op for op in block.ops if name in op.input_arg_names()]


def _fold_bn_into(block, scope, idx, bn_op, prod_op) -> bool:
    """Fold `bn_op` (at op index `idx`) into its producer conv2d/mul.
    Returns True on success; mutates program + scope."""
    if prod_op.type == "conv2d":
        # the BN must normalize the conv's channel axis: its data_layout
        # has to agree with the conv's data_format
        if (bn_op.attr("data_layout", "NCHW")
                != prod_op.attr("data_format", "NCHW")):
            return False
        w_name = prod_op.input("Filter")[0]
        out_axis = 0  # filter is OIHW for either data_format
    elif prod_op.type == "mul":
        w_name = prod_op.input("Y")[0]
        out_axis = 1  # [in, out]
    else:
        return False

    w_var = scope.find_var(w_name)
    if w_var is None:
        return False
    gamma = np.asarray(scope.find_var(bn_op.input("Scale")[0]))
    beta = np.asarray(scope.find_var(bn_op.input("Bias")[0]))
    mean = np.asarray(scope.find_var(bn_op.input("Mean")[0]))
    var = np.asarray(scope.find_var(bn_op.input("Variance")[0]))
    eps = bn_op.attr("epsilon", 1e-5)

    w = np.asarray(w_var)
    orig_dtype = w.dtype
    factor = (gamma / np.sqrt(var.astype("float64") + eps)).astype("float64")
    bshape = [1] * w.ndim
    bshape[out_axis] = -1
    scope.set_var(
        w_name,
        (w.astype("float64") * factor.reshape(bshape)).astype(orig_dtype),
    )
    fold_bias = (
        beta.astype("float64") - mean.astype("float64") * factor
    ).astype(orig_dtype)

    bias_name = fw.unique_name(f"{w_name}.bn_fold_bias")
    block.create_var(
        name=bias_name, shape=list(fold_bias.shape),
        dtype=str(fold_bias.dtype), persistable=True,
    )
    scope.set_var(bias_name, fold_bias)

    y_name = bn_op.output("Y")[0]
    x_name = bn_op.input("X")[0]
    block.remove_op(idx)
    # channel axis of the producer's output: conv2d NCHW -> 1, NHWC -> -1;
    # mul output [.., C] -> -1
    if prod_op.type == "conv2d":
        axis = -1 if prod_op.attr("data_format", "NCHW") == "NHWC" else 1
    else:
        axis = -1
    block.insert_op(
        idx,
        "elementwise_add",
        inputs={"X": [x_name], "Y": [bias_name]},
        outputs={"Out": [y_name]},
        attrs={"axis": axis},
    )
    return True


def inference_transpile(program: fw.Program, scope: Scope) -> int:
    """Fold batch_norm (inference mode) into the preceding conv2d/mul
    weights: W' = W * gamma/sqrt(var+eps); +bias' = beta - mean*that
    (reference: transpiler/inference_transpiler.py:1, ir/conv_bn_fuse_pass.cc).

    Mutates `program` and the parameter values in `scope`; returns the
    number of batch_norm ops folded.  Only valid for inference programs
    (clone(for_test=True) / load_inference_model output)."""
    block = program.global_block()
    folded = 0
    changed = True
    while changed:
        changed = False
        producers: Dict[str, tuple] = {}
        for i, op in enumerate(block.ops):
            for n in op.output_arg_names():
                producers[n] = (i, op)
        for i, op in enumerate(block.ops):
            if op.type != "batch_norm":
                continue
            x_name = op.input("X")[0]
            prod = producers.get(x_name)
            if prod is None:
                continue
            _, prod_op = prod
            # the conv output must feed only this BN (otherwise other
            # consumers would see the refolded weights)
            if len(_consumers(block, x_name)) != 1:
                continue
            if _fold_bn_into(block, scope, i, op, prod_op):
                folded += 1
                changed = True
                break
    return folded


class Predictor:
    """Load-once, serve-many inference API (reference: PaddlePredictor
    api/paddle_api.h:153 + NativePaddlePredictor api_impl.h:34).

        pred = Predictor(dirname)            # load + optimize once
        outs = pred.run({"x": batch})        # AOT-cached; no retracing

    Each distinct feed signature (shapes/dtypes) compiles exactly once;
    `pred.compile_count` exposes the executable-cache size for tests.
    """

    def __init__(
        self,
        dirname: str,
        place=None,
        optimize: bool = True,
        model_filename: Optional[str] = None,
        params_filename: Optional[str] = None,
    ):
        self._scope = Scope()
        self._exe = Executor(place or CPUPlace())
        self._program, self._feed_names, self._fetch_vars = (
            io.load_inference_model(
                dirname, self._exe, scope=self._scope,
                model_filename=model_filename,
                params_filename=params_filename,
            )
        )
        self._fetch_names = [v.name for v in self._fetch_vars]
        self.folded_ops = 0
        if optimize:
            self.folded_ops = inference_transpile(self._program, self._scope)

    @property
    def feed_names(self) -> List[str]:
        return list(self._feed_names)

    @property
    def fetch_names(self) -> List[str]:
        return list(self._fetch_names)

    @property
    def program(self) -> fw.Program:
        return self._program

    @property
    def compile_count(self) -> int:
        return len(self._exe._cache)

    def run(self, feed: Dict[str, np.ndarray], return_numpy: bool = True):
        """Serve one batch; compiles on first call per feed signature."""
        missing = [n for n in self._feed_names if n not in feed]
        if missing:
            raise KeyError(f"Predictor.run: missing feeds {missing}")
        return self._exe.run(
            self._program,
            feed={n: feed[n] for n in self._feed_names},
            fetch_list=self._fetch_names,
            scope=self._scope,
            return_numpy=return_numpy,
        )
