#!/usr/bin/env python
"""Benchmark driver entry: trains the flagship models on the available chip
and prints ONE JSON line {"metric", "value", "unit", "vs_baseline"} (plus
informational fields: mfu, loss, config).

Method: bf16 mixed-precision (pt.amp) training steps fused into one XLA call
per K steps via Executor.run_steps (lax.scan over device-resident batches),
so host dispatch latency amortizes and parameters never leave HBM.

vs_baseline:
  * resnet50 — ratio to the reference's best committed ResNet-50 training
    throughput (84.08 img/s, 2-socket Xeon 6148 + MKL-DNN,
    benchmark/IntelOptimizedPaddle.md:40-46; the reference repo has no
    committed GPU ResNet-50 number — see BASELINE.md).
  * transformer — the reference has NO committed transformer number, so
    vs_baseline is the ratio to the north-star target of BASELINE.json:
    50% MFU on this chip (vs_baseline = measured_mfu / 0.50).

MFU uses analytic model FLOPs (documented below) over the chip's bf16 peak.
"""

import argparse
import json
import sys
import time
import traceback

import numpy as np

# Substrings identifying retryable transport failures (the tunnel's RPC
# stream occasionally drops a response mid-read; the work itself is fine
# and a retry succeeds — round 3 lost its bench record to exactly this).
_TRANSIENT_ERR_MARKERS = (
    "read body",
    "remote_compile",
    "DEADLINE_EXCEEDED",
    "UNAVAILABLE",
    "Connection reset",
    "Broken pipe",
    "EOF",
)


def _is_transient(exc):
    msg = f"{type(exc).__name__}: {exc}"
    return any(m in msg for m in _TRANSIENT_ERR_MARKERS)


def run_guarded(name, fn, *args, retries=2):
    """Run one workload; print its JSON line the moment it is measured.

    A failure in one workload must never zero the others: exceptions are
    caught, transient tunnel/RPC errors are retried (the whole workload is
    re-run — compile caches make the retry cheap), and the error is
    reported on stderr.  Returns True iff a metric line was printed.
    """
    for attempt in range(retries + 1):
        try:
            fn(*args)
            return True
        except Warning:
            # only reachable under an explicit -W error::UserWarning run
            # (the CI warnings gate): a warning-turned-exception must FAIL
            # the bench, not be swallowed as a workload hiccup
            raise
        except Exception as e:  # noqa: BLE001 — bench must survive anything
            transient = _is_transient(e)
            print(f"[bench] {name} attempt {attempt + 1} failed "
                  f"({'transient' if transient else 'fatal'}): "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
            if not transient or attempt == retries:
                traceback.print_exc(file=sys.stderr)
                return False
            time.sleep(5.0 * (attempt + 1))
    return False

def _step_monitor(name, examples_per_call=None, tokens_per_call=None,
                  flops_per_call=None):
    """A StepMonitor when FLAGS.monitor is on, else None.  One bench
    "step" is one run_steps call (scan_steps fused steps); JSONL goes to
    FLAGS.monitor_jsonl when set."""
    from paddle_tpu.flags import FLAGS

    if not FLAGS.monitor:
        return None
    from paddle_tpu.monitor import StepMonitor

    return StepMonitor(
        name=f"bench.{name}",
        examples_per_step=examples_per_call,
        tokens_per_step=tokens_per_call,
        flops_per_step=flops_per_call,
        jsonl_path=FLAGS.monitor_jsonl or None,
        watchdog=_bench_watchdog(),
    )


def _ckpt_manager(name, exe, prog, scope):
    """A CheckpointManager under FLAGS.checkpoint_dir/<name> (emergency
    save armed through the flight recorder), else None.  One bench "step"
    is one run_steps call."""
    from paddle_tpu.flags import FLAGS

    if not FLAGS.checkpoint_dir:
        return None
    import os

    import paddle_tpu as pt
    from paddle_tpu.monitor import flight

    mgr = pt.io.CheckpointManager(
        os.path.join(FLAGS.checkpoint_dir, name), exe,
        interval_steps=FLAGS.checkpoint_interval, main_program=prog,
        scope=scope)
    flight.install()
    mgr.install_emergency()
    return mgr


_WATCHDOG = None


def _bench_watchdog():
    """One process-wide watchdog shared by every workload's StepMonitor
    (armed by FLAGS_watchdog=1; hang monitor rides a daemon thread)."""
    global _WATCHDOG
    from paddle_tpu.flags import FLAGS

    if not (FLAGS.monitor and FLAGS.watchdog):
        return None
    if _WATCHDOG is None:
        from paddle_tpu.monitor import Watchdog

        _WATCHDOG = Watchdog()
        _WATCHDOG.arm()
    return _WATCHDOG


def timed_steps(exe, prog, feed, fetch, scope, warmup, calls, mon=None,
                ckpt=None, repeats=1):
    """Shared warmup + timing loop: returns (seconds, first_loss,
    last_loss).  first_loss is step 0 of the first (warmup) call, so
    last_loss < first_loss certifies the timed program actually LEARNS on
    its (fixed, memorizable) batches — the reference's book tests assert
    loss thresholds the same way (tests/book/test_recognize_digits.py).

    `repeats` repeats the `calls`-sized timed region that many times
    against the SAME compiled program (warmup runs once, before the
    first timed region).  The first return value is ALWAYS the list of
    per-repeat seconds (length `repeats`) — the repeated-run protocol
    PERF.md's tunnel-variance section demands before believing any
    single number.

    `mon`: optional StepMonitor (see _step_monitor) — records per-call
    loss/throughput/MFU telemetry for the timed calls.
    `ckpt`: optional CheckpointManager (see _ckpt_manager) — interval +
    emergency checkpoints; stepped IN the loop (use async_save /
    FLAGS_checkpoint_async to keep disk writes off the step path, and
    leave it off for measurement-grade numbers)."""
    from paddle_tpu.flags import FLAGS

    # Two stepping modes.  Measurement mode (default): inside the timed
    # region only a perf_counter stamp is taken per call; registry/JSONL
    # writes replay AFTER dt is measured so telemetry cost never lands in
    # the reported throughput.  Live mode (a watchdog is wired or a
    # flight dir is armed): mon.step() runs IN the loop — the watchdog
    # must see NaN/hang at the step it happens and a SIGTERM dump must
    # name the last completed step, which deferred replay cannot give.
    # Cost: ~tens of µs of writes per multi-ms call — the price of a
    # black box; leave watchdog/flight off for measurement-grade runs.
    live = mon is not None and (mon.watchdog is not None
                                or bool(FLAGS.flight_dir))
    first_loss = None
    for i in range(max(warmup, 1)):
        (losses,) = exe.run_steps(prog, feed=feed, fetch_list=fetch,
                                  scope=scope)
        if i == 0:
            first_loss = float(np.asarray(losses).reshape(-1)[0])
    try:
        dts = []
        stamps = []
        if mon is not None:
            mon.step(now=time.perf_counter())  # arm at region start
        for rep in range(max(repeats, 1)):
            t0 = time.perf_counter()
            for i in range(calls):
                step_no = rep * calls + i
                if ckpt is not None:
                    ckpt.step_started(step_no)
                (losses,) = exe.run_steps(prog, feed=feed, fetch_list=fetch,
                                          scope=scope)
                if live:
                    mon.step(loss=float(np.asarray(losses).reshape(-1)[-1]),
                             now=time.perf_counter())
                elif mon is not None:
                    stamps.append((time.perf_counter(), losses))
                if ckpt is not None:
                    ckpt.on_step(step_no)
            dts.append(time.perf_counter() - t0)
        if mon is not None:
            for now_i, lv in stamps:
                mon.step(loss=float(np.asarray(lv).reshape(-1)[-1]),
                         now=now_i)
    finally:
        # run_guarded retries whole workloads: a leaked handle per retry
        # would outlive the StepMonitor that opened it
        if mon is not None:
            mon.close()
        if ckpt is not None:
            ckpt.close()  # flush + detach the emergency callback
    return dts, first_loss, float(np.asarray(losses).reshape(-1)[-1])


def memory_probe(exe, prog, feed, fetch_list, scope, batch_size):
    """The ISSUE-15 memory fields for a dense-workload record:
    `activation_peak_bytes` (the static planner over the one-step
    program, paddle_tpu/memory) and `memory_analysis_peak_bytes` (XLA
    ground truth: the executed run_steps entry re-lowered AOT and its
    CompiledMemoryStats read — one extra compile per workload, after the
    timed region).  Telemetry must never fail a measured bench: each
    probe degrades to a stderr note."""
    fields = {}
    feed_names = sorted(feed)
    fetch_names = [getattr(v, "name", v) for v in fetch_list]
    try:
        from paddle_tpu import memory as M

        plan = M.plan_program(prog, feed_names, fetch_names,
                              batch_size=batch_size)
        fields["activation_peak_bytes"] = int(plan.activation_peak_bytes)
        fields["planner_peak_bytes"] = int(plan.peak_bytes)
        if plan.warnings:
            fields["planner_warnings"] = len(plan.warnings)
        M.publish_plan(plan, name="bench")
    except Exception as e:  # noqa: BLE001
        print(f"[bench] planner probe failed: {type(e).__name__}: {e}",
              file=sys.stderr)
    try:
        import jax

        from paddle_tpu.core.executor import latest_jitted_entry
        from paddle_tpu.memory import xla_memory_stats

        entry = latest_jitted_entry(exe)
        feed_vals = [exe._to_device_array(prog, n, feed[n])
                     for n in feed_names]
        rw = [scope.find_var(n) for n in entry.rw_state]
        ro = [scope.find_var(n) for n in entry.ro_state]
        args = [feed_vals, rw, ro]
        if entry.needs_key:
            args.append(jax.random.key(0, impl="rbg"))
        stats = xla_memory_stats(entry.jitted.lower(*args).compile())
        fields["memory_analysis_peak_bytes"] = int(stats["peak_bytes"])
    except Exception as e:  # noqa: BLE001
        print(f"[bench] memory_analysis probe failed: "
              f"{type(e).__name__}: {e}", file=sys.stderr)
    return fields


def cost_probe(prog, batch_size, name):
    """Static roofline attribution for a record's one-step program
    (paddle_tpu/analysis/costmodel): predicted step time, launch count,
    and launch-bound fraction land in the record's config so
    tools/perf_report.py can compute predicted-vs-measured without
    rebuilding the program.  Degrades to a stderr note like
    memory_probe — attribution must never fail a measured bench."""
    try:
        from paddle_tpu.analysis.costmodel import cost_program, publish_cost

        cost = cost_program(prog, name=name, batch_size=batch_size)
        publish_cost(cost)
        return {
            "cost_device": cost.device.name,
            "cost_launches": cost.n_launches,
            "cost_launches_fused": cost.n_launches_fused,
            "cost_predicted_step_us": round(
                cost.predicted_seconds * 1e6, 2),
            "cost_predicted_step_us_fused": round(
                cost.predicted_seconds_fused * 1e6, 2),
            "cost_launch_bound_fraction": round(
                cost.launch_bound_fraction, 4),
            "cost_launch_bound_fraction_fused": round(
                cost.launch_bound_fraction_fused, 4),
        }
    except Exception as e:  # noqa: BLE001
        print(f"[bench] cost probe failed: {type(e).__name__}: {e}",
              file=sys.stderr)
        return {}


_PROVENANCE = None


def _provenance():
    """Computed once per process: git commit + dirty flag, jax/jaxlib
    versions, and the non-default flags — rides every record so a
    bench_diff comparison is attributable to a code/flag delta, not a
    mystery."""
    global _PROVENANCE
    if _PROVENANCE is not None:
        return _PROVENANCE
    import os
    import subprocess

    prov = {"git_commit": "unknown", "git_dirty": None}
    repo = os.path.dirname(os.path.abspath(__file__))
    try:
        out = subprocess.run(
            ["git", "rev-parse", "HEAD"], cwd=repo, capture_output=True,
            text=True, timeout=10)
        if out.returncode == 0 and out.stdout.strip():
            prov["git_commit"] = out.stdout.strip()
            st = subprocess.run(
                ["git", "status", "--porcelain"], cwd=repo,
                capture_output=True, text=True, timeout=10)
            if st.returncode == 0:
                prov["git_dirty"] = bool(st.stdout.strip())
    except Exception:  # noqa: BLE001 — no git / not a checkout
        pass
    try:
        import jax
        import jaxlib

        prov["jax"] = jax.__version__
        prov["jaxlib"] = jaxlib.__version__
    except Exception:  # noqa: BLE001
        prov["jax"] = prov["jaxlib"] = "unknown"
    try:
        from paddle_tpu.flags import FLAGS

        defs = object.__getattribute__(FLAGS, "_defs")
        prov["flags"] = {
            n: getattr(FLAGS, n) for n in sorted(defs)
            if getattr(FLAGS, n) != defs[n].default}
    except Exception:  # noqa: BLE001
        prov["flags"] = {}
    _PROVENANCE = prov
    return prov


def emit_metric(metric, value, unit, vs_baseline, mfu, loss, config,
                loss_first=None):
    """One-json-line contract, extended with the self-validation fields:
    loss_first (pre-training) vs loss (final) and learned = decreased,
    plus the provenance block every bench_diff comparison requires."""
    rec = {
        "metric": metric,
        "value": round(value, 2),
        "unit": unit,
        "vs_baseline": round(vs_baseline, 3) if vs_baseline is not None else 0.0,
        "mfu": round(mfu, 4) if mfu is not None else None,
        "loss": round(loss, 4),
        "config": config,
        "provenance": _provenance(),
    }
    if loss_first is not None:
        rec["loss_first"] = round(loss_first, 4)
        rec["learned"] = bool(loss < loss_first)
    print(json.dumps(rec), flush=True)
    return rec


def _repeats(args):
    """--runs N, defaulting to the PERF.md protocol: 3 timed repeats in a
    full bench, 1 in smoke."""
    return args.runs or (1 if args.smoke else 3)


def _mean_spread(runs):
    """(mean, spread, runs_list) of per-run throughputs.  The spread rides
    into the JSON record so +-4-6% tunnel variance (PERF.md) can't
    masquerade as a code-change regression or win."""
    runs = [float(r) for r in (runs if isinstance(runs, list) else [runs])]
    mean = float(np.mean(runs))
    spread = float(np.max(runs) - np.min(runs)) if len(runs) > 1 else 0.0
    return mean, spread, runs


REFERENCE_RESNET50_IMGS_PER_SEC = 84.08

# Committed per-chip throughput targets for the workloads with no
# reference number and no meaningful MFU (VERDICT r4 weak #5/#6: every
# line needs a baseline).  Values = the round-4 measured results on this
# chip, rounded down — vs_baseline >= 1.0 means "no regression vs r04".
MNIST_TARGET_IMGS_PER_SEC = 16000.0
DEEPFM_TARGET_EXAMPLES_PER_SEC = 40000.0

# ResNet-50 @224: 4.089 GMACs forward (standard torchvision/paper count,
# incl. final fc) -> 8.18 GFLOPs fwd; training fwd+bwd ~= 3x fwd.
RESNET50_TRAIN_FLOPS_PER_IMG = 3 * 2 * 4.089e9

def _peak_flops():
    """bf16 peak FLOP/s of device 0.  The committed per-chip table lives
    with StepMonitor (library users get MFU without this script); the
    import is function-local so `--help`/bad-flag invocations exit in
    argparse without loading the framework — a real run pays the import
    here, moments before the workloads would anyway."""
    import jax

    from paddle_tpu.monitor.step import TPU_PEAK_FLOPS

    d = jax.devices()[0]
    return TPU_PEAK_FLOPS.get(getattr(d, "device_kind", ""), None)


def transformer_train_flops_per_token(n_layer, d_model, d_ff, n_head, d_key,
                                      seq_len, vocab):
    """Analytic matmul FLOPs per token, fwd, for the enc+dec transformer
    (matmuls only; 2 FLOPs per MAC).  Train = 3x fwd (bwd ~= 2x fwd).

    Per layer per token: qkv+out projections 4 * d_model * (n_head*d_key),
    attention scores+values 2 * seq_len * (n_head*d_key), ffn 2 * d_model *
    d_ff.  Decoder layers add cross-attention (same cost as self-attention).
    Final vocab projection d_model * vocab.
    """
    dh = n_head * d_key
    attn = 4 * d_model * dh + 2 * seq_len * dh
    ffn = 2 * d_model * d_ff
    enc = n_layer * (attn + ffn)
    dec = n_layer * (2 * attn + ffn)
    fwd_macs = enc + dec + d_model * vocab
    return 3 * 2 * fwd_macs


def bench_resnet50(batch_size=256, scan_steps=16, calls=2, warmup=1,
                   image_size=224, depth=50, amp=True, stream=False,
                   data_format="NHWC"):
    """stream=True feeds a fresh host batch per call through the
    double-buffer prefetcher (reader/decorator.py double_buffer), so the
    host->HBM copy overlaps the previous call's compute — the
    buffered_reader.cc capability; target is within ~5% of the
    cached-device-batch number."""
    import paddle_tpu as pt
    from paddle_tpu.models import resnet as R

    prog, startup = pt.Program(), pt.Program()
    with pt.program_guard(prog, startup):
        img, label, avg_cost, acc, _ = R.build_train_net(
            class_dim=1000, image_shape=(3, image_size, image_size),
            depth=depth, lr=0.1, input_u8=stream, data_format=data_format,
        )
    if amp:
        pt.amp.enable(prog)
    scope = pt.Scope()
    exe = pt.Executor()
    exe.run(startup, scope=scope)

    import jax.numpy as jnp

    rng = np.random.RandomState(0)
    x = rng.rand(scan_steps, batch_size, 3, image_size, image_size)
    y = rng.randint(0, 1000, (scan_steps, batch_size, 1))
    y64 = y.astype("int64")
    if stream:
        # uint8 wire format (what a decode pipeline hands over): 4x less
        # host->device traffic, normalized INSIDE the compiled program
        x_feed = (x * 255).astype("uint8")
    else:
        x_feed = x.astype("float32")
    feed = {"image": jnp.asarray(x_feed), "label": jnp.asarray(y64)}

    first_loss = None
    for i in range(max(warmup, 1)):
        (wl,) = exe.run_steps(prog, feed=feed, fetch_list=[avg_cost],
                              scope=scope)
        if i == 0:
            first_loss = float(np.asarray(wl).reshape(-1)[0])

    if stream:
        from paddle_tpu.reader.decorator import double_buffer

        # fresh host batch per call; the prefetch thread's only job is the
        # chunked host->HBM copy, overlapping the previous call's compute
        # (buffered_reader.cc pre-copies the raw batch the same way)
        def src(n):
            def reader():
                for i in range(n):
                    yield {"image": x_feed, "label": (y64 + i) % 1000}
            return reader

        # warm the streaming path (first transfer pipeline)
        for fd in double_buffer(src(1), capacity=2)():
            exe.run_steps(prog, feed=fd, fetch_list=[avg_cost], scope=scope)

        losses = None
        t0 = time.perf_counter()
        for fd in double_buffer(src(calls), capacity=2)():
            (losses,) = exe.run_steps(prog, feed=fd,
                                      fetch_list=[avg_cost], scope=scope)
        dt = time.perf_counter() - t0
    else:
        t0 = time.perf_counter()
        for _ in range(calls):
            (losses,) = exe.run_steps(prog, feed=feed,
                                      fetch_list=[avg_cost], scope=scope)
        dt = time.perf_counter() - t0
    mem = memory_probe(exe, prog, feed, [avg_cost], scope, batch_size)
    ips = batch_size * scan_steps * calls / dt
    return ips, first_loss, float(np.asarray(losses)[-1]), mem


def bench_transformer(batch_size=32, seq_len=256, scan_steps=8, calls=4,
                      warmup=1, amp=True, tiny=False, use_flash=True,
                      repeats=1, recompute=False):
    import paddle_tpu as pt
    from paddle_tpu.models import transformer as T

    cfg = dict(n_layer=2, n_head=4, d_key=16, d_value=16, d_model=64,
               d_inner_hid=128, vocab=256) if tiny else dict(
        n_layer=6, n_head=8, d_key=64, d_value=64, d_model=512,
        d_inner_hid=2048, vocab=32000)
    prog, startup = pt.Program(), pt.Program()
    with pt.program_guard(prog, startup):
        avg_cost, _, feeds = T.transformer(
            src_vocab_size=cfg["vocab"], trg_vocab_size=cfg["vocab"],
            max_length=seq_len, n_layer=cfg["n_layer"], n_head=cfg["n_head"],
            d_key=cfg["d_key"], d_value=cfg["d_value"], d_model=cfg["d_model"],
            d_inner_hid=cfg["d_inner_hid"], dropout_rate=0.1,
            src_seq_len=seq_len, trg_seq_len=seq_len,
            use_flash=use_flash,
        )
        pt.optimizer.Adam(learning_rate=1e-4).minimize(avg_cost)
    if amp:
        pt.amp.enable(prog)
    # numerics observability A/B knob: FLAGS_check_numerics=summary adds
    # the fused per-param-group stats reductions + one [N,4] fetch per
    # step (the PERF.md overhead leg); off is a no-op by contract
    from paddle_tpu.analysis import numerics as AN

    AN.maybe_instrument(prog)
    rc_fields = {}
    if recompute:
        # the r12 A/B leg: activation-recompute pass applied to the
        # trained program (auto sqrt(N)-segment policy); the record
        # carries the planner's before/after peaks + est FLOPs factor
        from paddle_tpu import memory as M

        rep = M.apply_recompute(prog, list(feeds),
                                fetch_names=[avg_cost.name],
                                batch_size=batch_size)
        rc_fields = {
            "recompute_segments": rep["n_segments"],
            "recompute_cloned_ops": rep["cloned_ops"],
            "recompute_activation_peak_before": rep[
                "activation_peak_before"],
            "recompute_activation_peak_after": rep[
                "activation_peak_after"],
            "recompute_flops_ratio": round(rep["flops_ratio"], 4),
        }
    scope = pt.Scope()
    exe = pt.Executor()
    exe.run(startup, scope=scope)

    batches = [
        T.make_batch(batch_size, seq_len, seq_len, cfg["n_head"],
                     cfg["vocab"], cfg["vocab"], rng=np.random.RandomState(s))
        for s in range(scan_steps)
    ]
    feed = {k: np.stack([b[k] for b in batches]) for k in batches[0]}

    flops_tok = transformer_train_flops_per_token(
        cfg["n_layer"], cfg["d_model"], cfg["d_inner_hid"], cfg["n_head"],
        cfg["d_key"], seq_len, cfg["vocab"])
    toks_per_call = batch_size * seq_len * scan_steps
    mon = _step_monitor("transformer", tokens_per_call=toks_per_call,
                        flops_per_call=flops_tok * toks_per_call)
    ckpt = _ckpt_manager("transformer", exe, prog, scope)
    dt, first_loss, last_loss = timed_steps(exe, prog, feed, [avg_cost],
                                            scope, warmup, calls, mon=mon,
                                            ckpt=ckpt, repeats=repeats)
    mem = memory_probe(exe, prog, feed, [avg_cost], scope, batch_size)
    mem.update(rc_fields)
    mem.update(cost_probe(prog, batch_size, "bench.transformer"))
    # tokens counted on the decoded (trg) stream, the convention for MT
    toks = batch_size * seq_len * scan_steps * calls
    return [toks / d for d in dt], flops_tok, first_loss, last_loss, mem


# fixed HBM budget the decode records' serving-capacity gauge is quoted
# against: concurrent_slots_at_budget = how many sequences of the
# benched shape fit this many KV bytes.  The ring layout charges every
# sequence its full ring rows; the paged layout charges only the blocks
# the sequence touches — tools/run_ci.sh gates the paged/ring ratio.
KV_CAPACITY_BUDGET_BYTES = 64 << 20


def _kv_capacity(progs, batch_size, src_len, max_tokens):
    """Serving-capacity fields for one decode record: bytes one
    sequence of this workload's shape holds resident, the slot count at
    the fixed budget, and the planner's kv_cache row (the same number
    hlo_diag --memory prints — keeps the bench and the planner honest
    against each other)."""
    from paddle_tpu import memory as M

    self_c, cross_c = progs.self_cache, progs.cross_cache
    if getattr(progs, "paged", False):
        per_seq = (self_c.blocks_for(max_tokens) * self_c.block_bytes
                   + cross_c.blocks_for(src_len) * cross_c.block_bytes)
    else:
        per_seq = (self_c.hbm_bytes + cross_c.hbm_bytes) // batch_size
    kv_row = M.plan_program(progs.decode, [], []).class_peaks.get(
        "kv_cache", 0)
    budget = KV_CAPACITY_BUDGET_BYTES
    return {
        "paged": bool(getattr(progs, "paged", False)),
        "kv_bytes_per_seq": int(per_seq),
        "kv_budget_bytes": int(budget),
        "concurrent_slots_at_budget": int(budget // max(per_seq, 1)),
        "planner_kv_cache_bytes": int(kv_row),
        "kv_resident_gb": (self_c.hbm_bytes + cross_c.hbm_bytes) / 1e9,
    }


def bench_decode(batch_size=1, max_tokens=64, tiny=False, repeats=1,
                 use_flash=True):
    """Autoregressive decode tokens/sec (ROADMAP item 2's named metric:
    decode at batch 1 and 64).  One compiled prefill + ONE compiled
    per-token decode program stepped by the host — the serving-shaped
    loop (token fetched to host every step).  Route follows
    FLAGS.kv_cache (the A/B knob: cached O(T) vs full-prefix-recompute
    O(T²)); FLAGS.flash_decode picks the Pallas decode kernel on TPU.

    Returns (tokens/sec per repeat, prefill_seconds, compile_flat,
    compile_count): compile_flat asserts the executor compile cache did
    NOT grow between the end of warmup and the last generated token —
    the length-independent-compile-key acceptance criterion."""
    import paddle_tpu as pt
    from paddle_tpu.generation import GenerationSession
    from paddle_tpu.models import transformer as T

    cfg = dict(n_layer=2, n_head=4, d_key=32, d_value=32, d_model=128,
               d_inner_hid=256, vocab=1000, src_len=32,
               max_out=max(max_tokens, 16)) if tiny else dict(
        n_layer=6, n_head=8, d_key=64, d_value=64, d_model=512,
        d_inner_hid=2048, vocab=32000, src_len=256,
        max_out=max(max_tokens, 64))
    max_tokens = min(max_tokens, cfg["max_out"])
    progs = T.build_generation_programs(
        src_vocab_size=cfg["vocab"], trg_vocab_size=cfg["vocab"],
        max_length=max(cfg["src_len"], cfg["max_out"]) + 2,
        n_layer=cfg["n_layer"], n_head=cfg["n_head"], d_key=cfg["d_key"],
        d_value=cfg["d_value"], d_model=cfg["d_model"],
        d_inner_hid=cfg["d_inner_hid"], batch_size=batch_size,
        src_seq_len=cfg["src_len"], max_out_len=cfg["max_out"],
        # eos outside the sampled range: every run generates exactly
        # max_tokens tokens (fixed work for the timed region)
        bos_id=0, eos_id=-1, use_flash=use_flash, strategy="greedy")
    sess = GenerationSession(progs)
    sess.init_params()
    rng = np.random.RandomState(0)
    src = rng.randint(2, cfg["vocab"],
                      (batch_size, cfg["src_len"], 1)).astype(np.int64)

    from paddle_tpu.testing import chaos

    def one_pass(n_tokens):
        t0 = time.perf_counter()
        sess.prefill(src)
        t_prefill = time.perf_counter() - t0
        tokens = np.full((batch_size,), progs.bos_id, np.int64)
        prefix = np.full((batch_size, progs.t_buf), progs.bos_id,
                         np.int64)
        t1 = time.perf_counter()
        for t in range(n_tokens):
            # per-decode-step chaos latency hook (one flag read when
            # off): FLAGS_chaos + FLAGS_chaos_serve_latency_s inject a
            # deterministic synthetic slowdown — the bench_diff red
            # gate's regression source (tools/run_ci.sh)
            chaos.maybe_serve_latency()
            if progs.kv_cache:
                tokens = sess.decode_step(tokens)
            else:
                tokens = sess.decode_step(None, prefix=prefix, t=t)
                if t + 1 < progs.t_buf:
                    prefix[:, t + 1] = tokens
        return t_prefill, time.perf_counter() - t1

    one_pass(2)  # warmup: compiles prefill + decode
    compiles = sess.compile_count
    runs, prefill_s = [], None
    for _ in range(max(repeats, 1)):
        prefill_s, dt = one_pass(max_tokens)
        runs.append(batch_size * max_tokens / dt)
    compile_flat = sess.compile_count == compiles
    # static roofline attribution of the per-token decode program — the
    # launch-bound-fraction input ROADMAP item 1 reads off this record
    cost = cost_probe(progs.decode, batch_size, "bench.decode")
    if progs.kv_cache:
        cost = dict(cost)
        cost.update(_kv_capacity(progs, batch_size, cfg["src_len"],
                                 max_tokens))
    return runs, prefill_s, compile_flat, sess.compile_count, cost


def run_decode(args, peak):
    """Emit decode_tokens_per_sec at the ROADMAP batch pair (1 and 64;
    tiny shapes under --smoke).  config records the kv_cache /
    flash_decode / fused_decode_step flags — tools/run_ci.sh pairs a
    FLAGS_kv_cache=0 recompute record next to the cached one for the
    A/B — and compile_flat, which run_ci asserts True.

    When FLAGS_fused_decode_step is on (the default) each batch emits a
    PAIR: the fused record under the baseline-continuous metric name,
    then a `_unfused` record rebuilt with the flag off — the megastep
    speedup ratio run_ci's decode smoke gate reads (fused b1 tokens/sec
    must not lose to unfused)."""
    from paddle_tpu.flags import FLAGS

    repeats = _repeats(args)
    max_tokens = 16 if args.smoke else 64
    batches = ([1, 8] if args.smoke else [1, 64])
    if args.batch_size:
        batches = [args.batch_size]
    # the pair only means something on the cached route (the recompute
    # oracle never runs cached_decoder_step)
    variants = ([(True, ""), (False, "_unfused")]
                if FLAGS.fused_decode_step and FLAGS.kv_cache
                else [(bool(FLAGS.fused_decode_step), "")])
    for bs in batches:
        for fused, suffix in variants:
            try:
                if not fused:
                    FLAGS.set("fused_decode_step", False)
                runs, prefill_s, flat, n_compiles, cost = bench_decode(
                    batch_size=bs, max_tokens=max_tokens, tiny=args.smoke,
                    repeats=repeats)
            finally:
                FLAGS.reset("fused_decode_step")
            tps, spread, run_list = _mean_spread(runs)
            config = {"batch": bs, "max_tokens": max_tokens,
                      "tiny": args.smoke,
                      "kv_cache": bool(FLAGS.kv_cache),
                      "flash_decode": bool(FLAGS.flash_decode),
                      "fused_decode_step": fused,
                      "prefill_ms": round(prefill_s * 1e3, 2),
                      "compile_flat": bool(flat),
                      "compiled_signatures": n_compiles,
                      "runs": [round(r, 1) for r in run_list],
                      "spread": round(spread, 1)}
            config.update(cost)
            if config.get("kv_resident_gb"):
                # ROADMAP item 2's capacity-efficiency metric, bench-side
                config["tokens_per_sec_per_hbm_gb"] = round(
                    tps / config["kv_resident_gb"], 1)
            emit_metric(
                f"decode_tokens_per_sec_b{bs}{suffix}", tps, "tokens/sec",
                None, None, 0.0, config)
        if FLAGS.kv_cache and not FLAGS.paged_kv_cache:
            # paired paged record next to the ring one: same shape, the
            # block-pool cache layout — run_ci's capacity gate reads the
            # concurrent_slots_at_budget ratio off this pair
            try:
                FLAGS.set("paged_kv_cache", True)
                runs, prefill_s, flat, n_compiles, cost = bench_decode(
                    batch_size=bs, max_tokens=max_tokens, tiny=args.smoke,
                    repeats=repeats)
            finally:
                FLAGS.reset("paged_kv_cache")
            tps, spread, run_list = _mean_spread(runs)
            config = {"batch": bs, "max_tokens": max_tokens,
                      "tiny": args.smoke,
                      "kv_cache": bool(FLAGS.kv_cache),
                      "flash_decode": bool(FLAGS.flash_decode),
                      "fused_decode_step": bool(FLAGS.fused_decode_step),
                      "prefill_ms": round(prefill_s * 1e3, 2),
                      "compile_flat": bool(flat),
                      "compiled_signatures": n_compiles,
                      "runs": [round(r, 1) for r in run_list],
                      "spread": round(spread, 1)}
            config.update(cost)
            if config.get("kv_resident_gb"):
                config["tokens_per_sec_per_hbm_gb"] = round(
                    tps / config["kv_resident_gb"], 1)
            emit_metric(
                f"decode_tokens_per_sec_b{bs}_paged", tps, "tokens/sec",
                None, None, 0.0, config)


def bench_dispatch(calls=300, warmup=30, repeats=3):
    """Per-launch dispatch overhead microbench: time N cache-hit
    Executor.run calls of a trivially small program (one mean over 32
    floats — nanoseconds of arithmetic), so the per-call wall time IS
    the host-side launch cost the cost model charges each op: Python
    bookkeeping, cache lookup, device enqueue, and the blocking fetch.
    CPU-measurable today; re-run on chip to re-arm DEVICE_MODELS /
    FLAGS_launch_overhead_us.  Returns per-repeat seconds/call."""
    import paddle_tpu as pt
    from paddle_tpu import layers

    prog, startup = pt.Program(), pt.Program()
    with pt.program_guard(prog, startup):
        x = layers.data(name="x", shape=[8], dtype="float32")
        out = layers.mean(x)
    scope = pt.Scope()
    exe = pt.Executor()
    exe.run(startup, scope=scope)
    feed = {"x": np.zeros((4, 8), np.float32)}
    for _ in range(max(warmup, 1)):
        exe.run(prog, feed=feed, fetch_list=[out], scope=scope)
    per_call = []
    for _ in range(max(repeats, 1)):
        t0 = time.perf_counter()
        for _ in range(calls):
            exe.run(prog, feed=feed, fetch_list=[out], scope=scope)
        per_call.append((time.perf_counter() - t0) / calls)
    return per_call


def run_dispatch(args, peak):
    """Explicit-only (--model dispatch): emits dispatch_overhead_us, the
    measured per-launch constant behind DEVICE_MODELS' launch term.  The
    config carries the device kind and the table constant currently in
    force so the report shows measured-vs-declared drift."""
    from paddle_tpu.analysis.costmodel import resolve_device_model

    repeats = _repeats(args)
    calls = args.calls or (50 if args.smoke else 300)
    per_call = bench_dispatch(calls=calls, repeats=repeats)
    mean_us, spread, run_list = _mean_spread([p * 1e6 for p in per_call])
    dm = resolve_device_model()
    emit_metric(
        "dispatch_overhead_us", mean_us, "us/launch", None, None, 0.0,
        {"calls": calls, "device_model": dm.name,
         "table_launch_overhead_us": round(dm.launch_overhead_s * 1e6, 1),
         "table_source": dm.source,
         "runs": [round(r, 2) for r in run_list],
         "spread": round(spread, 2)})


def bench_ringattn(seq_len=8192, n_head=8, d_head=64, iters=8, warmup=2):
    """Long-context attention kernel line (VERDICT r4 item 3): fwd+bwd
    tokens/sec of the Pallas flash path vs the unfused reference at 8k+
    sequence on one chip.  vs_baseline = flash/reference speedup — the
    single-device leg of the long-context capability (the multi-device leg,
    ring CP over a mesh, is exercised by tests/test_ring_attention.py and
    dryrun_multichip's sp axis; one tunneled chip can't run a real ring)."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.kernels.attention import (
        flash_attention,
        reference_attention,
    )

    rng = np.random.RandomState(0)
    shape = (1, n_head, seq_len, d_head)
    q = jnp.asarray(rng.randn(*shape).astype("float32")).astype(jnp.bfloat16)
    k = jnp.asarray(rng.randn(*shape).astype("float32")).astype(jnp.bfloat16)
    v = jnp.asarray(rng.randn(*shape).astype("float32")).astype(jnp.bfloat16)
    scale = 1.0 / np.sqrt(d_head)

    def make(fn):
        def loss(q, k, v):
            o = fn(q, k, v, None, scale=scale, causal=True)
            return jnp.sum(o.astype(jnp.float32) * 1e-3)
        return jax.jit(jax.grad(loss, (0, 1, 2)))

    def time_one(g):
        r = g(q, k, v)
        np.asarray(jax.tree_util.tree_leaves(r)[0][0, 0, 0])  # sync
        for _ in range(warmup):
            r = g(q, k, v)
        np.asarray(jax.tree_util.tree_leaves(r)[0][0, 0, 0])
        t0 = time.perf_counter()
        for _ in range(iters):
            r = g(q, k, v)
        np.asarray(jax.tree_util.tree_leaves(r)[0][0, 0, 0])
        return (time.perf_counter() - t0) / iters

    t_flash = time_one(make(flash_attention))
    t_ref = time_one(make(reference_attention))
    tps = seq_len / t_flash
    return tps, t_ref / t_flash, t_flash, t_ref


def run_ringattn(args, peak):
    seq = 1024 if args.smoke else 8192
    tps, speedup, t_flash, t_ref = bench_ringattn(seq_len=seq)
    emit_metric("flash_attention_longseq_fwd_bwd_tokens_per_sec", tps,
                "tokens/sec", speedup, None, 0.0,
                {"seq_len": seq, "n_head": 8, "d_head": 64, "causal": True,
                 "bf16": True, "flash_ms": round(t_flash * 1e3, 2),
                 "reference_ms": round(t_ref * 1e3, 2)})


# The five distinct ResNet-50 bottleneck conv+BN shapes (stage 1-4 members;
# one 3x3 so both fused routes — dot+stats epilogue and conv+stats-kernel —
# are measured).  (label, batch, hw, c_in, c_out, ksize, stride, residual);
# residual=True also folds the block's add+relu epilogue, the conv3 site.
CONVBN_SHAPES = [
    ("s1_1x1_256_64_hw56", 16, 56, 256, 64, 1, 1, False),
    ("s1_1x1_64_256_hw56", 16, 56, 64, 256, 1, 1, True),
    ("s2_3x3_128_128_hw28", 16, 28, 128, 128, 3, 1, False),
    ("s3_1x1_1024_256_hw14", 16, 14, 1024, 256, 1, 1, False),
    ("s4_1x1_512_2048_hw7", 16, 7, 512, 2048, 1, 1, True),
]
CONVBN_SHAPES_SMOKE = [
    ("smoke_1x1_128_128_hw8", 2, 8, 128, 128, 1, 1, True),
    ("smoke_3x3_64_64_hw8", 2, 8, 64, 64, 3, 1, False),
]


def bench_convbn_shape(n, hw, cin, cout, ksize, stride, residual,
                       iters=20, repeats=3, warmup=1):
    """One conv+BN(+residual+relu) fwd+bwd A/B at a fixed shape: the XLA
    reference composition vs the fused kernels (kernels/conv_bn.py).

    In-loop protocol (PERF.md tunnel rules: per-CALL RPC latency makes
    micro-benchmarks useless below ~1 s of device work): `iters` chained
    fwd+bwd steps run INSIDE one jit via lax.scan — each step feeds its
    gradients back into the carried operands, so nothing is DCE'd and one
    host sync covers the whole loop.  Returns (fused_ms, ref_ms) lists of
    per-repeat ms/iter."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.kernels import conv_bn as CB

    rng = np.random.RandomState(0)
    pad = ksize // 2
    ohw = (hw + 2 * pad - ksize) // stride + 1
    dt = jnp.bfloat16
    x = jnp.asarray(rng.randn(n, hw, hw, cin).astype("float32")).astype(dt)
    w = jnp.asarray(
        (rng.randn(cout, cin, ksize, ksize)
         * np.sqrt(2.0 / (cin * ksize * ksize))).astype("float32")).astype(dt)
    gamma = jnp.asarray(rng.rand(cout).astype("float32") + 0.5)
    beta = jnp.asarray(rng.randn(cout).astype("float32"))
    res = (jnp.asarray(rng.randn(n, ohw, ohw, cout).astype("float32"))
           .astype(dt) if residual else None)
    eps = 1e-5

    def fused_loss(x, w, gamma, beta):
        y, s1, s2 = CB.conv_bn_stats(x, w, (stride, stride), (pad, pad))
        m = y.size // y.shape[-1]
        mean = s1 / m
        var = s2 / m - jnp.square(mean)
        out = CB.bn_apply(y, gamma, beta, mean, var, residual=res,
                          eps=eps, act="relu")
        return jnp.sum(out.astype(jnp.float32)) * 1e-6

    def ref_loss(x, w, gamma, beta):
        y = jax.lax.conv_general_dilated(
            x, w, (stride, stride), [(pad, pad), (pad, pad)],
            dimension_numbers=("NHWC", "OIHW", "NHWC"))
        ys = y.astype(jnp.float32)
        mean = ys.mean((0, 1, 2))
        var = (ys * ys).mean((0, 1, 2)) - jnp.square(mean)
        wv = gamma * jax.lax.rsqrt(var + eps)
        bv = beta - mean * wv
        out = y * wv.astype(y.dtype) + bv.astype(y.dtype)
        if res is not None:
            out = out + res
        return jnp.sum(jax.nn.relu(out).astype(jnp.float32)) * 1e-6

    def make_timed(loss):
        g = jax.grad(loss, (0, 1, 2, 3))

        @jax.jit
        def run(x, w, gamma, beta):
            def body(carry, _):
                x, w, gamma, beta = carry
                dx, dw, dg, db = g(x, w, gamma, beta)
                # feed the grads back so the chain is sequential on device
                return (x + dx * jnp.asarray(1e-3, x.dtype),
                        w + dw * jnp.asarray(1e-3, w.dtype),
                        gamma + dg * 1e-3, beta + db * 1e-3), None
            (x, w, gamma, beta), _ = jax.lax.scan(
                body, (x, w, gamma, beta), None, length=iters)
            return x, gamma

        def timed():
            xs = []
            for _ in range(max(warmup, 1)):
                out = run(x, w, gamma, beta)
            np.asarray(out[1])  # host readback sync (PERF.md tunnel note)
            for _ in range(repeats):
                t0 = time.perf_counter()
                out = run(x, w, gamma, beta)
                np.asarray(out[1])
                xs.append((time.perf_counter() - t0) * 1e3 / iters)
            return xs

        return timed

    fused_ms = make_timed(fused_loss)()
    ref_ms = make_timed(ref_loss)()
    return fused_ms, ref_ms


def run_convbn(args, peak):
    """--model convbn: per-shape fused-vs-XLA A/B records (BENCH_r07.json
    `convbn_*` slots).  vs_baseline = XLA-composition time / fused time —
    > 1.0 means the fused kernels win that shape; the per-lever protocol
    in PERF.md round 7 reads these before trusting the end-to-end number."""
    shapes = CONVBN_SHAPES_SMOKE if args.smoke else CONVBN_SHAPES
    iters = 2 if args.smoke else 20
    repeats = args.runs or (1 if args.smoke else 3)
    for (label, n, hw, cin, cout, k, stride, residual) in shapes:
        fused_ms, ref_ms = bench_convbn_shape(
            n, hw, cin, cout, k, stride, residual, iters=iters,
            repeats=repeats)
        fmean, fspread, fruns = _mean_spread(fused_ms)
        rmean, rspread, rruns = _mean_spread(ref_ms)
        emit_metric(
            f"convbn_fused_step_ms_{label}", fmean, "ms/iter",
            rmean / fmean if fmean else None, None, 0.0,
            {"batch": n, "hw": hw, "c_in": cin, "c_out": cout,
             "ksize": k, "stride": stride, "residual": residual,
             "iters": iters, "bf16": True,
             "runs": [round(r, 3) for r in fruns],
             "spread": round(fspread, 3),
             "ref_ms": round(rmean, 3),
             "ref_runs": [round(r, 3) for r in rruns],
             "ref_spread": round(rspread, 3)})


def bert_train_flops_per_token(n_layer, d_model, d_ff, seq_len, vocab):
    """Analytic matmul FLOPs per token, encoder-only + MLM head (2 FLOPs
    per MAC, train = 3x fwd)."""
    attn = 4 * d_model * d_model + 2 * seq_len * d_model
    fwd_macs = n_layer * (attn + 2 * d_model * d_ff) + d_model * vocab
    return 3 * 2 * fwd_macs


def bench_bert(batch_size=32, seq_len=128, scan_steps=8, calls=4, warmup=1,
               amp=True, tiny=False, use_flash=True, repeats=1):
    """BERT-base MLM pretraining step (BASELINE.md workload 4: the
    layer_norm/gelu/fused-attention path)."""
    import paddle_tpu as pt
    from paddle_tpu.models import bert as B

    cfg = dict(n_layer=2, n_head=4, d_model=128, d_ff=512,
               vocab=1000) if tiny else dict(
        n_layer=12, n_head=12, d_model=768, d_ff=3072, vocab=30522)
    prog, startup = pt.Program(), pt.Program()
    with pt.program_guard(prog, startup):
        avg_loss, _ = B.build_pretrain_net(
            vocab_size=cfg["vocab"], seq_len=seq_len, n_layer=cfg["n_layer"],
            n_head=cfg["n_head"], d_model=cfg["d_model"], d_ff=cfg["d_ff"],
            dropout_rate=0.1, use_flash=use_flash)
    if amp:
        pt.amp.enable(prog)
    scope = pt.Scope()
    exe = pt.Executor()
    exe.run(startup, scope=scope)

    batches = [B.make_batch(batch_size, seq_len, cfg["vocab"],
                            rng=np.random.RandomState(s))
               for s in range(scan_steps)]
    feed = {k: np.stack([b[k] for b in batches]) for k in batches[0]}

    flops_tok = bert_train_flops_per_token(
        cfg["n_layer"], cfg["d_model"], cfg["d_ff"], seq_len, cfg["vocab"])
    toks_per_call = batch_size * seq_len * scan_steps
    mon = _step_monitor("bert", tokens_per_call=toks_per_call,
                        flops_per_call=flops_tok * toks_per_call)
    ckpt = _ckpt_manager("bert", exe, prog, scope)
    dt, first_loss, last_loss = timed_steps(exe, prog, feed, [avg_loss],
                                            scope, warmup, calls, mon=mon,
                                            ckpt=ckpt, repeats=repeats)
    mem = memory_probe(exe, prog, feed, [avg_loss], scope, batch_size)
    toks = batch_size * seq_len * scan_steps * calls
    return [toks / d for d in dt], flops_tok, first_loss, last_loss, mem


def bench_deepfm(batch_size=4096, scan_steps=8, calls=4, warmup=1,
                 hash_dim=1000001, amp=False):
    """DeepFM CTR step (BASELINE.md workload 5: sparse lookup_table).
    hash_dim defaults to the reference dist_ctr_reader.py scale (1e6+1).
    MFU is not meaningful for a sparse-dominated workload; reports
    examples/sec."""
    import paddle_tpu as pt
    from paddle_tpu.models import deepfm as D

    prog, startup = pt.Program(), pt.Program()
    with pt.program_guard(prog, startup):
        avg_cost, _, _, _ = D.build_train_net(hash_dim=hash_dim)
    if amp:
        pt.amp.enable(prog)
    scope = pt.Scope()
    exe = pt.Executor()
    exe.run(startup, scope=scope)

    batches = [D.make_batch(batch_size, hash_dim=hash_dim,
                            rng=np.random.RandomState(s))
               for s in range(scan_steps)]
    feed = {k: np.stack([b[k] for b in batches]) for k in batches[0]}

    mon = _step_monitor("deepfm",
                        examples_per_call=batch_size * scan_steps)
    ckpt = _ckpt_manager("deepfm", exe, prog, scope)
    dts, first_loss, last_loss = timed_steps(exe, prog, feed, [avg_cost],
                                             scope, warmup, calls, mon=mon,
                                             ckpt=ckpt)
    eps = batch_size * scan_steps * calls / dts[0]
    return eps, first_loss, last_loss


def bench_mnist(batch_size=512, scan_steps=16, calls=2, warmup=1, amp=True):
    """LeNet-5 MNIST train step (BASELINE.md workload 1) — smoke-scale."""
    import paddle_tpu as pt
    from paddle_tpu.models import mnist as M

    prog, startup = pt.Program(), pt.Program()
    with pt.program_guard(prog, startup):
        img, label, avg_cost, acc, _ = M.build_train_net()
        pt.optimizer.Adam(learning_rate=1e-3).minimize(avg_cost)
    if amp:
        pt.amp.enable(prog)
    scope = pt.Scope()
    exe = pt.Executor()
    exe.run(startup, scope=scope)

    # learnable synthetic digits (class k = bright k x k corner patch) so
    # the loss demonstrably decreases — mirrors tests/test_mnist.py
    rng = np.random.RandomState(0)
    x = rng.rand(scan_steps, batch_size, 1, 28, 28).astype("float32") * 0.1
    y = rng.randint(0, 10, (scan_steps, batch_size, 1)).astype("int64")
    for s in range(scan_steps):
        for b in range(batch_size):
            k = int(y[s, b, 0])
            x[s, b, 0, k:k + 3, k:k + 3] += 1.0
    feed = {"pixel": x, "label": y}
    mon = _step_monitor("mnist", examples_per_call=batch_size * scan_steps)
    ckpt = _ckpt_manager("mnist", exe, prog, scope)
    dts, first_loss, last_loss = timed_steps(exe, prog, feed, [avg_cost],
                                             scope, warmup, calls, mon=mon,
                                             ckpt=ckpt)
    mem = memory_probe(exe, prog, feed, [avg_cost], scope, batch_size)
    mem.update(cost_probe(prog, batch_size, "bench.mnist"))
    ips = batch_size * scan_steps * calls / dts[0]
    return ips, first_loss, last_loss, mem


def run_bert(args, peak):
    # bs 128 measured best on v5e (35.5% MFU vs 28.9% at bs 32; 256
    # regresses under scan memory pressure) — PERF.md r04
    bs = args.batch_size or (4 if args.smoke else 128)
    seq = 64 if args.smoke else 128
    repeats = _repeats(args)
    tps_runs, flops_tok, loss0, loss, mem = bench_bert(
        batch_size=bs, seq_len=seq,
        scan_steps=args.scan_steps or (2 if args.smoke else 16),
        calls=args.calls or (1 if args.smoke else 2),
        amp=args.amp, tiny=args.smoke, repeats=repeats)
    tps, spread, runs = _mean_spread(tps_runs)
    mfu = (tps * flops_tok / peak) if peak else None
    # no committed reference BERT number: vs_baseline is the ratio to the
    # BASELINE.json north star (50% MFU on this chip)
    from paddle_tpu.flags import FLAGS as _FLAGS

    config = {"bf16": args.amp, "batch": bs, "seq_len": seq,
              "tiny": args.smoke,
              "fused_qkv_attention": bool(_FLAGS.fused_qkv_attention),
              "runs": [round(r, 1) for r in runs],
              "spread": round(spread, 1)}
    config.update(mem)
    emit_metric("bert_base_train_tokens_per_sec_per_chip", tps, "tokens/sec",
                mfu / 0.50 if mfu is not None else None, mfu, loss,
                config, loss_first=loss0)


def run_deepfm(args, peak):
    bs = args.batch_size or (64 if args.smoke else 4096)
    hash_dim = 10001 if args.smoke else 1000001
    # r04 recorded 49.8k (BENCH_r04) vs 39.4k (PERF.md) from single runs —
    # repeat and report mean+-spread so the number is trustworthy
    repeats = _repeats(args)
    runs = []
    loss0 = loss = None
    for _ in range(repeats):
        eps_i, loss0, loss = bench_deepfm(
            batch_size=bs,
            scan_steps=args.scan_steps or (2 if args.smoke else 8),
            calls=args.calls or (1 if args.smoke else 2),
            hash_dim=hash_dim)
        runs.append(eps_i)
    eps, spread, runs = _mean_spread(runs)
    # gather-bound workload: MFU is meaningless; report the analytic HBM
    # traffic of the sparse path (embedding gathers fwd + row-sparse
    # scatter bwd + lazy-adam moment updates on touched rows) vs the v5e
    # roofline (~800 GB/s), plus throughput vs the committed target
    from paddle_tpu.models import deepfm as D

    emb_bytes = D.SPARSE_SLOTS * (10 + 1) * 4  # per-example rows (k=10 + w1)
    bytes_per_ex = emb_bytes * (1 + 2 + 4)  # fwd + grad r/w + m,v r/w
    hbm_gbps = eps * bytes_per_ex / 1e9
    from paddle_tpu.flags import FLAGS as _FLAGS

    emit_metric("deepfm_ctr_train_examples_per_sec_per_chip", eps,
                "examples/sec", eps / DEEPFM_TARGET_EXAMPLES_PER_SEC,
                None, loss,
                {"batch": bs, "hash_dim": hash_dim, "sparse": True,
                 # the r08 A/B knob: run once with FLAGS_fused_embedding=0
                 # for the per-slot baseline record (tools/run_ci.sh does)
                 "fused_embedding": bool(_FLAGS.fused_embedding),
                 "runs": [round(r, 1) for r in runs],
                 "spread": round(spread, 1),
                 "hbm_gbps_analytic": round(hbm_gbps, 2),
                 "hbm_roofline_frac": round(hbm_gbps / 800.0, 4),
                 "bound": "dispatch/gather-latency (not HBM, not MXU)"},
                loss_first=loss0)


def run_mnist(args, peak):
    bs = args.batch_size or (64 if args.smoke else 512)
    ips, loss0, loss, mem = bench_mnist(
        batch_size=bs,
        scan_steps=args.scan_steps or (2 if args.smoke else 16),
        calls=args.calls or (1 if args.smoke else 2),
        amp=args.amp)
    # no reference MNIST throughput number exists: vs_baseline is the
    # ratio to the committed round-4 target (no-regression contract)
    config = {"bf16": args.amp, "batch": bs}
    config.update(mem)
    emit_metric("mnist_lenet5_train_images_per_sec_per_chip", ips,
                "images/sec", ips / MNIST_TARGET_IMGS_PER_SEC, None, loss,
                config, loss_first=loss0)


def run_resnet50(args, peak):
        if args.smoke:
            bs = args.batch_size or 8
            ips, loss0, loss, mem = bench_resnet50(
                batch_size=bs, scan_steps=2, calls=1, warmup=1,
                image_size=64, depth=18, amp=args.amp, stream=args.stream,
                data_format=args.data_format)
            mfu = None  # smoke runs ResNet-18@64: the R50@224 FLOPs no longer apply
            config = {"bf16": args.amp, "batch": bs, "image": 64,
                      "depth": 18, "data_format": args.data_format}
        else:
            bs = args.batch_size or 256
            ips, loss0, loss, mem = bench_resnet50(
                batch_size=bs, scan_steps=args.scan_steps or 16,
                calls=args.calls or 2, amp=args.amp, stream=args.stream,
                data_format=args.data_format)
            mfu = (ips * RESNET50_TRAIN_FLOPS_PER_IMG / peak) if peak else None
            config = {"bf16": args.amp, "batch": bs, "image": 224,
                      "depth": 50, "stream": args.stream,
                      "data_format": args.data_format}
        config.update(mem)
        emit_metric("resnet50_train_images_per_sec_per_chip", ips,
                    "images/sec", ips / REFERENCE_RESNET50_IMGS_PER_SEC,
                    mfu, loss, config, loss_first=loss0)


def run_transformer(args, peak):
        bs = args.batch_size or (2 if args.smoke else 64)
        seq = 64 if args.smoke else 256
        repeats = _repeats(args)
        tps_runs, flops_tok, loss0, loss, mem = bench_transformer(
            batch_size=bs, seq_len=seq,
            scan_steps=args.scan_steps or (2 if args.smoke else 32),
            calls=args.calls or (1 if args.smoke else 2),
            amp=args.amp, tiny=args.smoke, repeats=repeats,
            recompute=args.recompute)
        tps, spread, runs = _mean_spread(tps_runs)
        # flops_tok matches the model actually run (tiny config in smoke)
        mfu = (tps * flops_tok / peak) if peak else None
        # no committed reference transformer number exists: vs_baseline is
        # the ratio to the BASELINE.json north star (50% MFU on this chip)
        from paddle_tpu.flags import FLAGS as _FLAGS

        config = {"bf16": args.amp, "batch": bs, "seq_len": seq,
                  "tiny": args.smoke,
                  # the r09 A/B knob: run once with
                  # FLAGS_fused_qkv_attention=0 for the unfused-
                  # composition baseline record (tools/run_ci.sh does)
                  "fused_qkv_attention": bool(
                      _FLAGS.fused_qkv_attention),
                  # the r12 A/B knob: --recompute pairs a rewritten
                  # record next to this one (tools/run_ci.sh does)
                  "recompute": bool(args.recompute),
                  "runs": [round(r, 1) for r in runs],
                  "spread": round(spread, 1)}
        config.update(mem)
        emit_metric("transformer_base_train_tokens_per_sec_per_chip", tps,
                    "tokens/sec", mfu / 0.50 if mfu is not None else None,
                    mfu, loss, config, loss_first=loss0)


def run_pipeline(args, peak):
    """`--model transformer --pp N`: the pipeline-parallel training leg
    (parallel/pipeline).  Runs pp-stage GPipe AND 1F1B micro-batch
    schedules against single-program run_accumulated from identical
    init, asserts the LOSS TRAJECTORIES ARE BIT-IDENTICAL (dropout on —
    the subsystem's core numeric contract), and reports tokens/sec for
    each variant; config carries pp/schedule/micro_batches/bit_parity +
    the schedule's analytic bubble fraction.  run_ci.sh archives the
    three paired records as ci_artifacts/bench_pipeline_smoke.json."""
    import paddle_tpu as pt
    from paddle_tpu.core import framework as fw
    from paddle_tpu.models import transformer as T
    from paddle_tpu.parallel.pipeline import (
        PipelineProgram, bubble_fraction, split_program)

    pp = args.pp
    tiny = args.smoke
    cfg = dict(n_layer=max(2, pp), n_head=4, d_key=16, d_value=16,
               d_model=64, d_inner_hid=128, vocab=256,
               seq=32) if tiny else dict(
        n_layer=6, n_head=8, d_key=64, d_value=64, d_model=512,
        d_inner_hid=2048, vocab=2048, seq=32)
    k = args.scan_steps or 4                       # micro-batches
    mbs = args.batch_size or 2                     # micro-batch size
    steps = args.calls or 2

    prog, startup = pt.Program(), pt.Program()
    with pt.program_guard(prog, startup), fw.guard_unique_name():
        avg_cost, _, feeds = T.transformer(
            src_vocab_size=cfg["vocab"], trg_vocab_size=cfg["vocab"],
            max_length=cfg["seq"], n_layer=cfg["n_layer"],
            n_head=cfg["n_head"], d_key=cfg["d_key"],
            d_value=cfg["d_value"], d_model=cfg["d_model"],
            d_inner_hid=cfg["d_inner_hid"], dropout_rate=0.1,
            src_seq_len=cfg["seq"], trg_seq_len=cfg["seq"],
            use_flash=False)
        pt.optimizer.Adam(learning_rate=1e-4).minimize(avg_cost)
    loss = avg_cost.name
    stages = split_program(prog, feeds, n_stages=pp)
    pnames = [p.name for p in prog.all_parameters()]

    batches = [T.make_batch(mbs, cfg["seq"], cfg["seq"], cfg["n_head"],
                            cfg["vocab"], cfg["vocab"],
                            rng=np.random.RandomState(s))
               for s in range(k)]
    feed = {n: np.stack([b[n] for b in batches]) for n in batches[0]}
    toks_per_step = k * mbs * cfg["seq"]

    def run_variant(runner_for):
        """Fresh scope from the shared init; returns (traj, tokens/sec,
        final param snapshot)."""
        scope = pt.Scope()
        exe = pt.Executor()
        with pt.scope_guard(scope):
            exe.run(startup, scope=scope)
            for n, v in run_variant.init.items():
                scope.set_var(n, v)
            step = runner_for(exe, scope)
            traj = [np.asarray(step())]          # warmup incl. compile
            t0 = time.perf_counter()
            for _ in range(steps):
                traj.append(np.asarray(step()))
            dt = time.perf_counter() - t0
            params = {n: np.asarray(scope.find_var(n)) for n in pnames}
        return traj, steps * toks_per_step / dt, params

    scope0 = pt.Scope()
    exe0 = pt.Executor()
    with pt.scope_guard(scope0):
        exe0.run(startup, scope=scope0)
        run_variant.init = {n: np.asarray(scope0.find_var(n)).copy()
                            for n in pnames}

    traj_single, tps_single, params_single = run_variant(
        lambda exe, scope: lambda: exe.run_accumulated(
            prog, feed=feed, fetch_list=[loss], scope=scope)[0])
    variants = {"single": (traj_single, tps_single, None, 0.0)}
    # ONE PipelineProgram: compiled stage entries are schedule-
    # independent, so GPipe and 1F1B share them
    pipe = PipelineProgram(prog, feeds, schedule="gpipe", stages=stages)
    for sched in ("gpipe", "1f1b"):
        pipe.schedule = sched
        traj, tps, params = run_variant(
            lambda exe, scope: lambda: exe.run(
                pipe, feed=feed, fetch_list=[loss], scope=scope)[0])
        # the pipeline parity CONTRACT (PERF.md r11): training STATE
        # bit-identical; fetched loss to the ulp (a reduce feeding only
        # a fetched scalar may round differently across separately
        # compiled modules — params never drift)
        state_parity = all(
            np.array_equal(params_single[n], params[n]) for n in pnames)
        with np.errstate(divide="ignore", invalid="ignore"):
            rel = max(
                float(np.nanmax(np.abs(a - b) / np.maximum(
                    np.abs(a), 1e-30)))
                for a, b in zip(traj_single, traj))
        variants[sched] = (traj, tps, state_parity, rel)

    for name, (traj, tps, parity, rel) in variants.items():
        emit_metric(
            f"transformer_pp{pp}_{name}_tokens_per_sec", tps,
            "tokens/sec", None, None, float(np.asarray(traj[-1]).mean()),
            {"pp": pp, "schedule": name, "micro_batches": k,
             "micro_batch_size": mbs, "seq_len": cfg["seq"],
             "tiny": tiny, "dropout": 0.1,
             "state_bit_parity": parity,
             "loss_max_rel_diff": rel,
             "bubble_fraction": (round(bubble_fraction(pp, k, name), 4)
                                 if name != "single" else 0.0)})
    bad = [n for n, (_, _, p, rel) in variants.items()
           if p is False or (rel is not None and rel > 3e-7)]
    if bad:
        raise AssertionError(
            f"pipeline schedules {bad} lost parity vs single-program "
            f"run_accumulated (state must be bit-identical, losses "
            f"within 1 ulp)")


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="all",
                   choices=["all", "resnet50", "transformer", "bert",
                            "deepfm", "mnist", "ringattn", "convbn",
                            "decode", "dispatch"])
    p.add_argument("--pp", type=int, default=0,
                   help="with --model transformer: run the pp-stage "
                        "pipeline-parallel leg (GPipe + 1F1B vs single-"
                        "program run_accumulated, loss bit-parity "
                        "asserted) instead of the dense bench")
    p.add_argument("--smoke", action="store_true",
                   help="tiny shapes for a fast correctness pass")
    p.add_argument("--recompute", action="store_true",
                   help="with --model transformer: apply the activation-"
                        "recompute pass (paddle_tpu/memory, auto sqrt(N) "
                        "segments) to the trained program before timing — "
                        "the r12 A/B leg; the record carries the planner's "
                        "before/after activation peaks + est FLOPs factor")
    p.add_argument("--no-amp", dest="amp", action="store_false")
    p.add_argument("--batch-size", type=int, default=None)
    p.add_argument("--scan-steps", type=int, default=None)
    p.add_argument("--calls", type=int, default=None)
    p.add_argument("--runs", type=int, default=None,
                   help="repeat the timed region N times and report "
                        "mean + runs[] + spread (transformer/bert/deepfm/"
                        "convbn; "
                        "default 3 full, 1 smoke) — PERF.md tunnel-"
                        "variance protocol")
    p.add_argument("--data-format", default="NHWC",
                   choices=["NHWC", "NCHW"],
                   help="resnet50 conv layout (NHWC is ~18%% faster on "
                        "v5e; NCHW for reference-parity comparison)")
    p.add_argument("--stream", action="store_true",
                   help="resnet50: stream fresh host batches through the "
                        "double-buffer prefetcher instead of a cached "
                        "device batch")
    p.add_argument("--monitor-snapshot", default=None, metavar="PATH",
                   help="with FLAGS_monitor=1: write a Prometheus-text "
                        "metrics snapshot to PATH after all workloads "
                        "(plus PATH.jsonl with the JSONL exposition)")
    args = p.parse_args()

    from paddle_tpu.flags import FLAGS

    if FLAGS.monitor:
        # black box + scrape endpoint for the whole bench run: a SIGTERM'd
        # or crashed bench leaves flight-*.jsonl under FLAGS_flight_dir,
        # and FLAGS_monitor_port serves /metrics /health /flight live
        from paddle_tpu.monitor import flight, serve

        flight.install()
        try:
            serve.start()
        except OSError as e:  # port taken: telemetry must not fail the run
            print(f"[bench] monitor endpoint disabled: {e}",
                  file=sys.stderr)

    peak = _peak_flops()
    # Default run prints one metric line per workload, each emitted the
    # moment it is measured (a crash in one workload cannot zero the rest).
    # The driver parses the LAST line, so resnet50 (the metric tracked
    # since round 1) stays last.
    ran = []
    if args.model in ("all", "mnist"):
        ran.append(run_guarded("mnist", run_mnist, args, peak))
    if args.model in ("all", "deepfm"):
        ran.append(run_guarded("deepfm", run_deepfm, args, peak))
    if args.model == "convbn":
        # per-lever A/B microbench (PERF.md r07); not part of "all" so the
        # full-bench content and the resnet50-last line stay unchanged —
        # the driver runs it explicitly: python bench.py --model convbn
        ran.append(run_guarded("convbn", run_convbn, args, peak))
    if args.model == "decode":
        # generation workload (PERF.md r10): tokens/sec decode at batch
        # 1 and 64 with the kv_cache/flash_decode flags in the record;
        # explicit-only for the same resnet50-last reason —
        # python bench.py --model decode (run_ci.sh pairs the
        # FLAGS_kv_cache=0 recompute baseline next to it)
        ran.append(run_guarded("decode", run_decode, args, peak))
    if args.model == "dispatch":
        # per-launch overhead microbench (the cost model's launch-term
        # constant); explicit-only like convbn/decode —
        # python bench.py --model dispatch
        ran.append(run_guarded("dispatch", run_dispatch, args, peak))
    if args.model in ("all", "ringattn"):
        ran.append(run_guarded("ringattn", run_ringattn, args, peak))
    if args.model in ("all", "bert"):
        ran.append(run_guarded("bert", run_bert, args, peak))
    if args.model == "transformer" and args.pp:
        # pipeline-parallel leg (PERF.md r11): explicit-only, like
        # convbn/decode — python bench.py --model transformer --pp 2
        ran.append(run_guarded("pipeline", run_pipeline, args, peak))
    elif args.model in ("all", "transformer"):
        ran.append(run_guarded("transformer", run_transformer, args, peak))
    if args.model in ("all", "resnet50"):
        ok = run_guarded("resnet50", run_resnet50, args, peak)
        if not ok:
            # the driver records the LAST line as the round-tracked
            # resnet50 metric: on failure emit an explicit null line so a
            # different workload's number is never mis-attributed to it
            print(json.dumps({
                "metric": "resnet50_train_images_per_sec_per_chip",
                "value": None, "unit": "images/sec", "vs_baseline": 0.0,
                "error": "workload failed after retries (see stderr)",
            }), flush=True)
        ran.append(ok)

    if args.monitor_snapshot:
        from paddle_tpu.flags import FLAGS
        from paddle_tpu.monitor import default_registry

        if FLAGS.monitor:
            # a bad path must not turn a measured bench run into a
            # failure — the metric lines already printed are the product
            try:
                import os

                d = os.path.dirname(args.monitor_snapshot)
                if d:
                    os.makedirs(d, exist_ok=True)
                reg = default_registry()
                reg.write_prometheus(args.monitor_snapshot)
                reg.write_jsonl(args.monitor_snapshot + ".jsonl")
                print(f"[bench] metrics snapshot: {args.monitor_snapshot} "
                      f"(+ .jsonl)", file=sys.stderr)
            except OSError as e:
                print(f"[bench] metrics snapshot failed: {e}",
                      file=sys.stderr)
        else:
            print("[bench] --monitor-snapshot ignored: FLAGS_monitor is "
                  "off", file=sys.stderr)
    # exit 0 if ANY workload produced a number
    return 0 if any(ran) else 1


if __name__ == "__main__":
    sys.exit(main())
