"""Context-parallel transformer training: sequence axis sharded 4-way via
ring attention behind a ShardingPlan (SURVEY.md §5.7 — a capability the
reference lacks; its max context is bounded by one device's memory)."""

import numpy as np

import paddle_tpu as pt
from paddle_tpu.core import framework as fw
from paddle_tpu.models import transformer as T
from paddle_tpu.parallel.sharding import ShardingPlan, ShardedProgram


def _build(use_ring):
    prog, startup = pt.Program(), pt.Program()
    with fw.guard_unique_name():
        with pt.program_guard(prog, startup):
            avg_cost, _, feeds = T.transformer(
                src_vocab_size=32, trg_vocab_size=32, max_length=20,
                n_layer=1, n_head=2, d_key=8, d_value=8, d_model=16,
                d_inner_hid=32, dropout_rate=0.0,
                batch_size=4, src_seq_len=16, trg_seq_len=16,
                use_ring=use_ring)
            pt.optimizer.SGDOptimizer(learning_rate=0.1).minimize(avg_cost)
    return prog, startup, avg_cost


def _copy_state(prog, src_scope, dst_scope):
    for v in prog.list_vars():
        if v.persistable and src_scope.find_var(v.name) is not None:
            dst_scope.set_var(v.name, np.asarray(src_scope.find_var(v.name)))


def test_transformer_context_parallel_loss_parity():
    from jax.sharding import PartitionSpec as P

    ring_prog, ring_startup, ring_cost = _build(use_ring=True)
    base_prog, base_startup, base_cost = _build(use_ring=False)

    exe = pt.Executor(pt.CPUPlace())
    scope_ring, scope_base = pt.Scope(), pt.Scope()
    exe.run(ring_startup, scope=scope_ring)
    _copy_state(ring_prog, scope_ring, scope_base)

    plan = ShardingPlan(
        mesh_axes={"data": 2, "sp": 4},
        feed_rules=[
            (r"(src|trg|lbl)_\w+", P("data", "sp")),
        ],
    )
    sharded = ShardedProgram(ring_prog, plan, loss_name=ring_cost.name)

    rng = np.random.RandomState(4)
    ring_losses, base_losses = [], []
    for step in range(3):
        batch = T.make_batch(4, 16, 16, 2, 32, 32,
                             rng=np.random.RandomState(100 + step))
        (rl,) = exe.run(sharded, feed=batch, fetch_list=[ring_cost],
                        scope=scope_ring)
        (bl,) = exe.run(base_prog, feed=batch, fetch_list=[base_cost],
                        scope=scope_base)
        ring_losses.append(float(np.asarray(rl)))
        base_losses.append(float(np.asarray(bl)))

    np.testing.assert_allclose(ring_losses, base_losses, rtol=2e-4,
                               atol=2e-5)


def test_ring_attention_op_falls_back_without_mesh():
    """Single-device trace (no sp axis): ring_attention lowers to the
    reference path and still matches unfused attention numerics."""
    from paddle_tpu import layers
    from paddle_tpu.layers.contrib import ring_attention

    q = layers.data(name="q", shape=[2, 8, 4], dtype="float32")
    k = layers.data(name="k", shape=[2, 8, 4], dtype="float32")
    v = layers.data(name="v", shape=[2, 8, 4], dtype="float32")
    out = ring_attention(q, k, v, scale=0.5, causal=True)
    exe = pt.Executor(pt.CPUPlace())
    rng = np.random.RandomState(0)
    qv = rng.randn(1, 2, 8, 4).astype("float32")
    kv = rng.randn(1, 2, 8, 4).astype("float32")
    vv = rng.randn(1, 2, 8, 4).astype("float32")
    (o,) = exe.run(feed={"q": qv, "k": kv, "v": vv}, fetch_list=[out])

    from paddle_tpu.kernels.attention import reference_attention

    import jax.numpy as jnp

    ref = reference_attention(jnp.asarray(qv), jnp.asarray(kv),
                              jnp.asarray(vv), scale=0.5, causal=True)
    np.testing.assert_allclose(np.asarray(o), np.asarray(ref), atol=1e-5)
