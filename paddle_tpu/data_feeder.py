"""DataFeeder: sample batches -> feed dict of dense arrays
(reference: python/paddle/fluid/data_feeder.py — DataToLoDTensorConverter/
DataFeeder).

TPU-first: instead of LoD tensors for ragged samples, variable-length
sequences are padded to the var's static sequence length (SURVEY.md §5.7:
dense padding + masks replaces LoD)."""

from __future__ import annotations

from typing import Dict, List, Sequence

import numpy as np

from .core import framework as fw


class DataFeeder:
    def __init__(self, feed_list: Sequence, place=None, program=None):
        self.program = program or fw.default_main_program()
        self.feed_vars: List[fw.Variable] = []
        for v in feed_list:
            if isinstance(v, str):
                v = self.program.global_block().var(v)
            self.feed_vars.append(v)
        self.place = place

    def feed(self, iterable) -> Dict[str, np.ndarray]:
        """iterable: list of samples; each sample is a tuple aligned with
        feed_list.  Returns {name: batched ndarray}."""
        columns: List[List] = [[] for _ in self.feed_vars]
        for sample in iterable:
            assert len(sample) == len(self.feed_vars), (
                f"sample arity {len(sample)} != feed arity {len(self.feed_vars)}"
            )
            for c, v in zip(columns, sample):
                c.append(v)
        out = {}
        for var, col in zip(self.feed_vars, columns):
            arr = self._to_batch(var, col)
            out[var.name] = arr
        return out

    def _to_batch(self, var: fw.Variable, col: List) -> np.ndarray:
        if not col:
            raise ValueError(f"DataFeeder.feed: empty batch for {var.name!r}")
        dtype = np.float32 if var.dtype == "bfloat16" else np.dtype(var.dtype)
        # dim 0 of the var is the batch dim by convention (layers.data
        # prepends -1); per-sample target shape is the rest
        sample_shape = tuple(var.shape[1:]) if var.shape else None
        arrs = [np.asarray(c, dtype=dtype) for c in col]
        shapes = {a.shape for a in arrs}
        if len(shapes) == 1:
            batch = np.stack(arrs)
            if sample_shape and batch.shape[1:] != sample_shape and all(
                s not in (-1, None) for s in sample_shape
            ):
                try:
                    batch = batch.reshape(
                        (len(arrs),) + tuple(int(s) for s in sample_shape)
                    )
                except ValueError:
                    pass  # shape-inference mismatch: let the lowering report
            return batch
        # ragged: pad each sample's first axis to the var's static sequence
        # length (dense padding replaces the reference's LoD, SURVEY.md §5.7)
        if sample_shape and sample_shape[0] not in (-1, None):
            max_len = int(sample_shape[0])
            too_long = max(a.shape[0] for a in arrs)
            if too_long > max_len:
                raise ValueError(
                    f"sample length {too_long} exceeds {var.name!r} static "
                    f"sequence length {max_len}"
                )
        else:
            max_len = max(a.shape[0] for a in arrs)
        padded = []
        for a in arrs:
            pad = [(0, max_len - a.shape[0])] + [(0, 0)] * (a.ndim - 1)
            padded.append(np.pad(a, pad))
        return np.stack(padded)
