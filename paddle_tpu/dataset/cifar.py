"""CIFAR-10/100 (reference: python/paddle/dataset/cifar.py — pickled batch
archives; yields (flattened float image / 255, label)).

Offline fallback: synthetic class-separable images."""

from __future__ import annotations

import os
import pickle
import tarfile

import numpy as np

from . import common

URL10 = "https://www.cs.toronto.edu/~kriz/cifar-10-python.tar.gz"
URL100 = "https://www.cs.toronto.edu/~kriz/cifar-100-python.tar.gz"


def _synthetic(n, num_classes, seed):
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, num_classes, n).astype("int64")
    imgs = rng.rand(n, 3, 32, 32).astype("float32") * 0.1
    for i in range(n):
        c = int(labels[i]) % 16
        imgs[i, c % 3, (c * 2) % 28:(c * 2) % 28 + 4, :] += 0.9
    return imgs.reshape(n, 3072), labels


def _read_archive(url, sub_names, label_key, synthetic, num_classes, seed):
    def reader():
        if common.use_synthetic(synthetic):
            imgs, labels = _synthetic(512, num_classes, seed)
            for im, lb in zip(imgs, labels):
                yield im, int(lb)
            return
        path = common.download(url, "cifar", None)
        with tarfile.open(path, mode="r") as f:
            names = [n for n in f.getnames()
                     if any(s in n for s in sub_names)]
            for name in sorted(names):
                batch = pickle.load(f.extractfile(name), encoding="bytes")
                data = batch[b"data"].astype("float32") / 255.0
                labels = batch[label_key]
                for im, lb in zip(data, labels):
                    yield im, int(lb)
    return reader


def train10(synthetic=False):
    return _read_archive(URL10, ["data_batch"], b"labels", synthetic, 10, 1)


def test10(synthetic=False):
    return _read_archive(URL10, ["test_batch"], b"labels", synthetic, 10, 2)


def train100(synthetic=False):
    return _read_archive(URL100, ["train"], b"fine_labels", synthetic, 100, 3)


def test100(synthetic=False):
    return _read_archive(URL100, ["test"], b"fine_labels", synthetic, 100, 4)
