"""Numerics-observability ops: the fused per-tensor health reduction and
its packing op (paddle_tpu/analysis/numerics.py instrumentation pass —
the reference's FLAGS_check_nan_inf per-op output walk, operator.cc:943,
rebuilt for whole-block XLA where ops never individually return to the
host).

  * `numerics_stat` — ONE fused reduction over a tensor producing the
    [4] f32 health row `[nonfinite_count, abs_max, abs_mean, l2]`.
    Non-finite elements are masked out of the magnitude stats so a
    single Inf doesn't saturate abs_max into uselessness; everything
    accumulates in f32 regardless of the input dtype (bf16/f16 grads
    included).  Optional `Ref` input switches to delta stats over
    `X - Ref` (update magnitude: `ParamOut - Param` gives the
    update-to-weight numerator without a separate subtract op in the
    user graph).  Optional `Acc` input combines with a previous row
    (`[add, max, max, max]`) — the while-sub-block accumulator idiom:
    the loop carries one [4] row per instrumented inner op, so inner
    tensors are observed without any per-iteration host traffic.
  * `numerics_pack` — stacks N such rows into the single [N, 4] stats
    tensor the executor fetches alongside the user's fetches: one
    device->host transfer per step, not N.
  * `numerics_zeros` — the [4] zero row that seeds a while accumulator
    in the outer block (so the verifier's def-before-use pass sees the
    carry defined before the loop).

All three are no_grad, derive no RNG, and infer static shapes even when
input shapes are unknown — the instrumented program must stay green
through the full verifier (analysis/verifier.py) and graph_lint.
"""

from __future__ import annotations

from ..core.registry import register

# the stat-row layout; monitor/numerics.py indexes columns by this
STAT_WIDTH = 4
STAT_COLUMNS = ("nonfinite", "abs_max", "abs_mean", "l2")


def _stat_infer(ctx):
    ctx.set_output("Out", (STAT_WIDTH,), "float32")


def _pack_infer(ctx):
    ctx.set_output("Out", (int(ctx.attr("n")), STAT_WIDTH), "float32")


def _zeros_infer(ctx):
    ctx.set_output("Out", (STAT_WIDTH,), "float32")


def _stat_row(x, ref=None):
    import jax.numpy as jnp

    x = jnp.asarray(x).astype(jnp.float32)
    if ref is not None:
        x = x - jnp.asarray(ref).astype(jnp.float32)
    finite = jnp.isfinite(x)
    nonfinite = jnp.sum(~finite).astype(jnp.float32)
    ax = jnp.abs(jnp.where(finite, x, jnp.float32(0)))
    n = max(int(x.size), 1)
    if x.size:
        abs_max = jnp.max(ax)
    else:
        abs_max = jnp.float32(0)
    abs_sum = jnp.sum(ax)
    abs_mean = abs_sum / jnp.float32(n)
    l2 = jnp.sqrt(jnp.sum(ax * ax))
    return jnp.stack([nonfinite, abs_max, abs_mean, l2])


@register("numerics_stat", infer_shape=_stat_infer, no_grad=True,
          doc="fused [nonfinite_count, abs_max, abs_mean, l2] health row "
              "over one tensor (finite-masked, f32 accumulation); Ref "
              "switches to delta stats over X-Ref, Acc combines with a "
              "loop-carried previous row via [add, max, max, max] "
              "(analysis/numerics.py)")
def lower_numerics_stat(ctx, ins):
    import jax.numpy as jnp

    x = ins["X"][0]
    ref = (ins.get("Ref") or [None])[0]
    acc = (ins.get("Acc") or [None])[0]
    if x is None:
        # declared-but-unwritten producer output (optional slot): an
        # all-zero row rather than a trace crash — telemetry must not
        # be able to fail the run
        row = jnp.zeros((4,), jnp.float32)
    else:
        row = _stat_row(x, ref)
    if acc is not None:
        acc = jnp.asarray(acc).astype(jnp.float32)
        row = jnp.stack([
            acc[0] + row[0],
            jnp.maximum(acc[1], row[1]),
            jnp.maximum(acc[2], row[2]),
            jnp.maximum(acc[3], row[3]),
        ])
    return {"Out": [row]}


@register("numerics_pack", infer_shape=_pack_infer, no_grad=True,
          doc="stack N [4] health rows into the single [N, 4] stats "
              "tensor fetched once per step (attr n = row count)")
def lower_numerics_pack(ctx, ins):
    import jax.numpy as jnp

    rows = [jnp.asarray(v).astype(jnp.float32) for v in ins["X"]]
    return {"Out": [jnp.stack(rows, axis=0)]}


@register("numerics_zeros", infer_shape=_zeros_infer, no_grad=True,
          doc="the [4] f32 zero row seeding a while-loop stats "
              "accumulator in the outer block")
def lower_numerics_zeros(ctx, ins):
    import jax.numpy as jnp

    return {"Out": [jnp.zeros((STAT_WIDTH,), jnp.float32)]}


__all__ = ["STAT_WIDTH", "STAT_COLUMNS", "lower_numerics_stat",
           "lower_numerics_pack", "lower_numerics_zeros"]
