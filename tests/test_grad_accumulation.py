"""Gradient accumulation (VERDICT r3 item 8; reference
ir/multi_batch_merge_pass.h:25): Executor.run_accumulated runs the fwd/bwd
prefix over K micro-batches, averages the grads, applies the optimizer
once.  Loss-trajectory parity: bs=64 direct vs 4x accumulated bs=16."""

import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers


def _build(lr=0.1, opt="sgd"):
    x = layers.data(name="x", shape=[8], dtype="float32")
    y = layers.data(name="y", shape=[1], dtype="float32")
    h = layers.fc(x, size=16, act="tanh",
                  param_attr=pt.ParamAttr(name="w1"),
                  bias_attr=pt.ParamAttr(name="b1"))
    pred = layers.fc(h, size=1, param_attr=pt.ParamAttr(name="w2"),
                     bias_attr=pt.ParamAttr(name="b2"))
    loss = layers.mean(layers.square(pred - y))
    if opt == "sgd":
        pt.optimizer.SGD(learning_rate=lr).minimize(loss)
    else:
        pt.optimizer.AdamOptimizer(learning_rate=lr).minimize(loss)
    return loss


def _data(rs, n):
    w = rs.randn(8, 1).astype("float32")
    x = rs.randn(n, 8).astype("float32")
    return x, (x @ w + 0.1).astype("float32")


def _run_pair(opt):
    rs = np.random.RandomState(0)
    xs, ys = _data(rs, 64 * 20)

    # direct: bs=64
    prog_a, start_a = pt.Program(), pt.Program()
    with pt.program_guard(prog_a, start_a):
        loss_a = _build(opt=opt)
    scope_a = pt.Scope()
    exe = pt.Executor(pt.CPUPlace())
    with pt.scope_guard(scope_a):
        exe.run(start_a, scope=scope_a)
        init_w = {n: np.asarray(scope_a.find_var(n)).copy()
                  for n in ("w1", "b1", "w2", "b2")}
        traj_a = []
        for i in range(20):
            xb = xs[i * 64:(i + 1) * 64]
            yb = ys[i * 64:(i + 1) * 64]
            (lv,) = exe.run(prog_a, feed={"x": xb, "y": yb},
                            fetch_list=[loss_a], scope=scope_a)
            traj_a.append(float(np.asarray(lv)))

    # accumulated: 4 x bs=16 per update — identical math for both SGD
    # (mean-of-micro-losses gradient == big-batch gradient for mean loss)
    prog_b, start_b = pt.Program(), pt.Program()
    with pt.program_guard(prog_b, start_b):
        loss_b = _build(opt=opt)
    scope_b = pt.Scope()
    with pt.scope_guard(scope_b):
        exe.run(start_b, scope=scope_b)
        # identical starting weights (each startup draws its own rng)
        for name, val in init_w.items():
            scope_b.set_var(name, val)
        traj_b = []
        for i in range(20):
            xb = xs[i * 64:(i + 1) * 64].reshape(4, 16, 8)
            yb = ys[i * 64:(i + 1) * 64].reshape(4, 16, 1)
            lv = exe.run_accumulated(
                prog_b, feed={"x": xb, "y": yb}, fetch_list=[loss_b],
                scope=scope_b)[0]
            traj_b.append(float(np.asarray(lv).mean()))
    return traj_a, traj_b


def test_sgd_trajectory_parity():
    traj_a, traj_b = _run_pair("sgd")
    assert traj_a[-1] < traj_a[0] * 0.2
    np.testing.assert_allclose(traj_a, traj_b, rtol=2e-3, atol=1e-5)


def test_adam_trajectory_parity():
    traj_a, traj_b = _run_pair("adam")
    assert traj_a[-1] < traj_a[0] * 0.9
    np.testing.assert_allclose(traj_a, traj_b, rtol=5e-3, atol=1e-4)


def test_running_stats_update_per_microbatch():
    """BatchNorm running stats must advance once per micro-batch (the
    fwd/bwd prefix carries rw state through the scan)."""
    prog, start = pt.Program(), pt.Program()
    with pt.program_guard(prog, start):
        x = layers.data(name="x", shape=[4], dtype="float32")
        y = layers.data(name="y", shape=[1], dtype="float32")
        h = layers.batch_norm(layers.fc(x, size=4), momentum=0.5)
        loss = layers.mean(layers.square(layers.fc(h, size=1) - y))
        pt.optimizer.SGD(learning_rate=0.01).minimize(loss)
        bn_mean = [v for v in prog.global_block().vars.values()
                   if "batch_norm" in v.name and "mean" in v.name]
    assert bn_mean, "no bn mean var found"
    scope = pt.Scope()
    exe = pt.Executor(pt.CPUPlace())
    rs = np.random.RandomState(1)
    with pt.scope_guard(scope):
        exe.run(start, scope=scope)
        m0 = np.asarray(scope.find_var(bn_mean[0].name)).copy()
        xb = (rs.randn(4, 16, 4) + 3).astype("float32")
        yb = rs.randn(4, 16, 1).astype("float32")
        exe.run_accumulated(prog, feed={"x": xb, "y": yb},
                            fetch_list=[loss], scope=scope)
        m1 = np.asarray(scope.find_var(bn_mean[0].name))
    # momentum 0.5 over 4 micro-batches moves mean most of the way to ~3
    assert not np.allclose(m0, m1)
    assert (np.abs(m1) > 1.0).any(), m1


def test_fetching_optimize_output_raises():
    """Fetch targets must come from the fwd/bwd prefix; asking for an
    Optimize-role product fails loudly instead of misaligning results."""
    import pytest

    prog, start = pt.Program(), pt.Program()
    with pt.program_guard(prog, start):
        x = layers.data(name="x", shape=[2], dtype="float32")
        y = layers.data(name="y", shape=[1], dtype="float32")
        loss = layers.mean(layers.square(layers.fc(x, size=1) - y))
        pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
    scope = pt.Scope()
    exe = pt.Executor(pt.CPUPlace())
    with pt.scope_guard(scope):
        exe.run(start, scope=scope)
        xb = np.zeros((2, 8, 2), "float32")
        yb = np.zeros((2, 8, 1), "float32")
        with pytest.raises((KeyError, RuntimeError)):
            exe.run_accumulated(prog, feed={"x": xb, "y": yb},
                                fetch_list=["not_a_prefix_var"],
                                scope=scope)


def test_check_nan_inf_fires_in_accumulated_mode():
    import pytest

    prog, start = pt.Program(), pt.Program()
    with pt.program_guard(prog, start):
        x = layers.data(name="x", shape=[2], dtype="float32")
        y = layers.data(name="y", shape=[1], dtype="float32")
        h = layers.log(x)  # NaN for negative feeds
        loss = layers.mean(layers.square(layers.fc(h, size=1) - y))
        pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
    scope = pt.Scope()
    exe = pt.Executor(pt.CPUPlace(), check_nan_inf=True)
    with pt.scope_guard(scope):
        exe.run(start, scope=scope)
        xb = -np.ones((2, 8, 2), "float32")
        yb = np.zeros((2, 8, 1), "float32")
        with pytest.raises(FloatingPointError, match="log"):
            exe.run_accumulated(prog, feed={"x": xb, "y": yb},
                                fetch_list=[loss], scope=scope)
