"""QAT program rewrite (reference:
python/paddle/fluid/contrib/quantize/quantize_transpiler.py:81
QuantizeTranspiler).

Inserts fake_quantize/fake_dequantize pairs around the quantizable ops'
inputs: weights use per-step abs_max, activations a moving-average abs-max
with persistable scale state initialized in the startup program.

Contract difference from the reference: call `training_transpile` BEFORE
optimizer.minimize() — the straight-through estimator lives inside the
fake-quant lowerings (ops/quant_ops.py), so append_backward differentiates
the rewritten program directly instead of the reference's separate grad-op
rewiring pass.
"""

from __future__ import annotations

from typing import Dict, Optional

from ..core import framework as fw

QUANTIZABLE_OPS = {
    "conv2d": ("Input", "Filter"),
    "depthwise_conv2d": ("Input", "Filter"),
    "mul": ("X", "Y"),
}


class QuantizeTranspiler:
    def __init__(
        self,
        weight_bits: int = 8,
        activation_bits: int = 8,
        activation_quantize_type: str = "moving_average_abs_max",
        weight_quantize_type: str = "abs_max",
        moving_rate: float = 0.9,
    ):
        if activation_quantize_type not in (
            "moving_average_abs_max", "abs_max"
        ):
            raise ValueError(
                f"unsupported activation_quantize_type "
                f"{activation_quantize_type!r}")
        if weight_quantize_type != "abs_max":
            raise ValueError("weight_quantize_type must be 'abs_max'")
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.activation_quantize_type = activation_quantize_type
        self.moving_rate = moving_rate

    # -- helpers ---------------------------------------------------------

    def _quant_abs_max(self, block, idx, name, bits):
        q = block.create_var(
            name=fw.unique_name(f"{name}.quantized"), dtype="float32")
        scale = block.create_var(
            name=fw.unique_name(f"{name}.scale"), dtype="float32")
        block.insert_op(
            idx,
            "fake_quantize_abs_max",
            inputs={"X": [name]},
            outputs={"Out": [q], "OutScale": [scale]},
            attrs={"bit_length": bits},
        )
        return q.name, scale.name

    def _quant_moving_average(self, block, startup, idx, name, bits):
        def state(suffix, init):
            v = block.create_var(
                name=fw.unique_name(f"{name}.{suffix}"),
                shape=[1], dtype="float32", persistable=True)
            v.stop_gradient = True  # scale state gets no cotangent
            sv = startup.global_block().create_var(
                name=v.name, shape=[1], dtype="float32", persistable=True)
            startup.global_block().append_op(
                "fill_constant",
                outputs={"Out": [sv]},
                attrs={"shape": [1], "value": init, "dtype": "float32"},
            )
            return v

        scale_in = state("quant_scale", 0.001)
        accum = state("quant_accum", 0.0)
        st = state("quant_state", 0.0)
        q = block.create_var(
            name=fw.unique_name(f"{name}.quantized"), dtype="float32")
        block.insert_op(
            idx,
            "fake_quantize_moving_average_abs_max",
            inputs={"X": [name], "InScale": [scale_in],
                    "InAccum": [accum], "InState": [st]},
            outputs={"Out": [q], "OutScale": [scale_in],
                     "OutAccum": [accum], "OutState": [st]},
            attrs={"bit_length": bits, "moving_rate": self.moving_rate},
        )
        return q.name, scale_in.name

    def _dequant(self, block, idx, name, scale_name, bits):
        out = block.create_var(
            name=fw.unique_name(f"{name}.dequantized"), dtype="float32")
        block.insert_op(
            idx,
            "fake_dequantize_max_abs",
            inputs={"X": [name], "Scale": [scale_name]},
            outputs={"Out": [out]},
            attrs={"max_range": float((1 << (bits - 1)) - 1),
                   "bit_length": bits},
        )
        return out.name

    # -- public ----------------------------------------------------------

    def training_transpile(
        self,
        program: Optional[fw.Program] = None,
        startup_program: Optional[fw.Program] = None,
    ) -> int:
        """Rewrite `program` in place; returns the number of quantized
        input slots.  Call before minimize()."""
        program = program or fw.default_main_program()
        startup = startup_program or fw.default_startup_program()
        block = program.global_block()
        params = {p.name for p in block.all_parameters()}

        dequantized: Dict[str, str] = {}
        count = 0
        i = 0
        while i < len(block.ops):
            op = block.ops[i]
            slots = QUANTIZABLE_OPS.get(op.type)
            if slots is None:
                i += 1
                continue
            for slot in slots:
                names = op.input(slot)
                if not names:
                    continue
                name = names[0]
                if name not in dequantized:
                    is_weight = name in params
                    bits = (self.weight_bits if is_weight
                            else self.activation_bits)
                    if is_weight or (
                        self.activation_quantize_type == "abs_max"
                    ):
                        qname, sname = self._quant_abs_max(
                            block, i, name, bits)
                    else:
                        qname, sname = self._quant_moving_average(
                            block, startup, i, name, bits)
                    i += 1
                    dq = self._dequant(block, i, qname, sname, bits)
                    i += 1
                    dequantized[name] = dq
                op.inputs[slot] = [dequantized[name]]
                count += 1
            block._bump()
            i += 1
        return count


def freeze_int8(program: fw.Program, scope, startup_program=None) -> int:
    """Convert a QAT-trained program (QuantizeTranspiler.training_transpile
    structure) to an int8 INFERENCE program (the execution path the
    reference reaches via quantize_op.cc/dequantize_op.cc + the slim
    freeze pass):

      * each quantized weight VALUE in `scope` is replaced by an int8
        tensor plus a [1] f32 scale var — 4x smaller storage;
      * fake_quantize on activations becomes a real `quantize` op reading
        the trained moving-average scale (or a runtime abs-max);
      * mul / conv2d consumers become int8_mul / int8_conv2d: int8 x int8
        with int32 accumulation on the MXU, scales folded back in f32;
      * all fake_dequantize ops disappear.

    Returns the number of converted consumer ops.  Run on a clone(for_test
    =True) program; the original float program stays usable.
    """
    import numpy as np

    block = program.global_block()

    # producer map is cached and rebuilt only after mutations (building it
    # per trace_back would make the pass O(ops^2))
    _prod_cache = [None]

    def producers():
        if _prod_cache[0] is None:
            _prod_cache[0] = {n: (i, op)
                              for i, op in enumerate(block.ops)
                              for n in op.output_arg_names()}
        return _prod_cache[0]

    def invalidate_producers():
        _prod_cache[0] = None

    def trace_back(name):
        """name '.dequantized' -> (orig_name, scale_source, quant_op_info,
        dequant_op_info)"""
        prod = producers()
        if name not in prod:
            return None
        di, dop = prod[name]
        if dop.type != "fake_dequantize_max_abs":
            return None
        qname = dop.input("X")[0]
        qi, qop = prod[qname]
        orig = qop.input("X")[0]
        if qop.type == "fake_quantize_abs_max":
            scale_src = qop.output("OutScale")[0]
            kind = "abs_max"
        elif qop.type == "fake_quantize_moving_average_abs_max":
            scale_src = qop.input("InScale")[0]
            kind = "moving_average"
        else:
            return None
        return orig, scale_src, kind, (qi, qop), (di, dop)

    params = {p.name for p in block.all_parameters()}
    # one source of truth with training_transpile's table
    slot_map = QUANTIZABLE_OPS
    int8_type = {"conv2d": "int8_conv2d", "depthwise_conv2d": "int8_conv2d",
                 "mul": "int8_mul"}
    scale_slots = {"int8_conv2d": ("ScaleX", "ScaleW"),
                   "int8_mul": ("ScaleX", "ScaleY")}
    in_slots = {"int8_conv2d": ("Input", "Filter"),
                "int8_mul": ("X", "Y")}

    count = 0
    i = 0
    to_remove = set()
    frozen_weights = {}  # orig name -> scale var name (shared weights)
    while i < len(block.ops):
        op = block.ops[i]
        slots = slot_map.get(op.type)
        if slots is None:
            i += 1
            continue
        traced = [trace_back(op.input(s)[0]) for s in slots]
        if any(t is None for t in traced):
            i += 1
            continue
        nt = int8_type[op.type]
        new_inputs = {}
        for (orig, scale_src, kind, qinfo, dinfo), islot, sslot in zip(
                traced, in_slots[nt], scale_slots[nt]):
            to_remove.add(qinfo[0])
            to_remove.add(dinfo[0])
            # a load_inference_model program loses Parameter-ness
            # (parse_from_string rebuilds plain Variables), but weights
            # are exactly the scope-resident quantized inputs — the
            # serving tier freezes loaded artifacts through here
            if orig in params or scope.find_var(orig) is not None:
                if orig in frozen_weights:
                    # shared weight already int8: REUSE its scale var
                    # (re-quantizing the int8 tensor would compute
                    # scale ~= 127 and corrupt the model)
                    new_inputs[islot] = [orig]
                    new_inputs[sslot] = [frozen_weights[orig]]
                    continue
                # offline weight quantization: int8 value + scale in scope
                w = np.asarray(scope.find_var(orig))
                scale = float(np.max(np.abs(w))) or 1e-8
                wq = np.clip(np.round(w / scale * 127.0), -127,
                             127).astype(np.int8)
                scope.set_var(orig, wq)
                sname = orig + "@int8_scale"
                sv = block.create_var(name=sname, shape=[1],
                                      dtype="float32", persistable=True)
                sv.stop_gradient = True
                scope.set_var(sname, np.asarray([scale], "float32"))
                wvar = block._find_var_recursive(orig)
                if wvar is not None:
                    wvar.dtype = "int8"
                frozen_weights[orig] = sname
                new_inputs[islot] = [orig]
                new_inputs[sslot] = [sname]
            else:
                if kind != "moving_average":
                    raise NotImplementedError(
                        "freeze_int8: activation quantized with abs_max "
                        "has no stored scale to freeze — train with "
                        "activation_quantize_type="
                        "'moving_average_abs_max'")
                # runtime activation quantization against the trained scale
                aq = fw.unique_name(orig + "@int8")
                block.create_var(name=aq, dtype="int8")
                block.insert_op(
                    i, "quantize",
                    inputs={"X": [orig], "Scale": [scale_src]},
                    outputs={"Out": [aq]},
                )
                invalidate_producers()
                # inserting shifts every recorded index at/after i
                to_remove = {j + 1 if j >= i else j for j in to_remove}
                i += 1
                new_inputs[islot] = [aq]
                new_inputs[sslot] = [scale_src]
        # rewrite the consumer in place
        op.type = nt
        op.inputs = new_inputs
        if nt == "int8_conv2d":
            op.outputs = {"Out": op.outputs.get("Output", op.outputs.get("Out"))}
        count += 1
        i += 1
    for j in sorted(to_remove, reverse=True):
        block.remove_op(j)
    block._bump()
    return count


def count_fake_quant_ops(program: fw.Program) -> int:
    """How many fake_quantize/fake_dequantize ops the program carries —
    i.e. whether freeze_int8 has anything to freeze.  The serving tier
    uses this to validate an int8-replica request BEFORE loading: a model
    exported without QAT (QuantizeTranspiler.training_transpile) has no
    trained scales, and freezing it would silently serve the float path."""
    return sum(
        1 for op in program.global_block().ops
        if op.type.startswith("fake_quantize")
        or op.type.startswith("fake_dequantize")
    )


def quantize_var(x, scale, name=None):
    """Append a real `quantize` op (f32 -> int8 with scale); building
    block for custom int8 graphs outside freeze_int8."""
    from ..layer_helper import LayerHelper

    helper = LayerHelper("quantize", name=name)
    out = helper.create_variable_for_type_inference("int8")
    helper.append_op("quantize", inputs={"X": [x], "Scale": [scale]},
                     outputs={"Out": [out]})
    return out


def dequantize_var(x, scale, name=None):
    """Append a real `dequantize` op (int8 -> f32 with scale)."""
    from ..layer_helper import LayerHelper

    helper = LayerHelper("dequantize", name=name)
    out = helper.create_variable_for_type_inference("float32")
    helper.append_op("dequantize", inputs={"X": [x], "Scale": [scale]},
                     outputs={"Out": [out]})
    return out
