"""PTB/imikolov language-model dataset (reference:
python/paddle/dataset/imikolov.py — build_dict + train/test readers
yielding n-gram tuples or sequences; the word2vec book model's data).

Offline fallback: synthetic text from a Zipfian unigram model with
order-2 Markov structure, so n-gram models actually learn."""

from __future__ import annotations

import numpy as np

from . import common

URL = "https://raw.githubusercontent.com/wojzaremba/lstm/master/data/ptb.train.txt"
_VOCAB = 2000


class DataType:
    NGRAM = 1
    SEQ = 2


def _synthetic_tokens(seed, n_sentences=400):
    rng = np.random.RandomState(seed)
    # order-2 structure: next word depends on previous via a shift pattern
    for _ in range(n_sentences):
        ln = int(rng.randint(5, 25))
        w = int(rng.zipf(1.3)) % _VOCAB
        sent = []
        for _ in range(ln):
            sent.append(f"w{w}")
            w = (w * 31 + int(rng.zipf(1.3))) % _VOCAB
        yield sent


def _real_sentences(path):
    with open(path) as f:
        for line in f:
            toks = line.strip().split()
            if toks:
                yield toks


def build_dict(min_word_freq=50, synthetic=False):
    """word -> id, frequency-sorted, '<unk>' last (reference
    imikolov.build_dict)."""
    freq = {}
    if common.use_synthetic(synthetic):
        sents = _synthetic_tokens(3)
    else:
        sents = _real_sentences(common.download(URL, "imikolov", None))
    for sent in sents:
        # sentence boundaries get real ids (reference imikolov counts
        # <s>/<e> per sentence), so LM n-grams see true boundaries
        for w in sent + ["<s>", "<e>"]:
            freq[w] = freq.get(w, 0) + 1
    if common.use_synthetic(synthetic):
        min_word_freq = 1
    words = sorted(
        (w for w, c in freq.items() if c >= min_word_freq),
        key=lambda w: (-freq[w], w))
    d = {w: i for i, w in enumerate(words)}
    d["<unk>"] = len(d)
    return d


def _reader(word_idx, n, data_type, seed, synthetic):
    def reader():
        unk = word_idx["<unk>"]
        if common.use_synthetic(synthetic):
            sents = _synthetic_tokens(seed)
        else:
            sents = _real_sentences(common.download(URL, "imikolov", None))
        for sent in sents:
            ids = [word_idx.get(w, unk) for w in ["<s>"] + sent + ["<e>"]]
            if data_type == DataType.NGRAM:
                if len(ids) >= n:
                    for i in range(n, len(ids) + 1):
                        yield tuple(ids[i - n:i])
            else:
                yield ids
    return reader


def train(word_idx, n, data_type=DataType.NGRAM, synthetic=False):
    return _reader(word_idx, n, data_type, 11, synthetic)


def test(word_idx, n, data_type=DataType.NGRAM, synthetic=False):
    return _reader(word_idx, n, data_type, 12, synthetic)
