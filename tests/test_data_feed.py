"""MultiSlot DataFeed + AsyncExecutor file trainer (reference:
framework/data_feed.cc MultiSlotDataFeed, async_executor.cc RunFromFile,
dist_ctr.py pattern)."""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers

rng = np.random.RandomState(31)


def _write_files(tmp_path, n_files=3, lines_per=40, vocab=50):
    """CTR-ish data: sparse id slot + dense feature slot + float label;
    label = 1 if any id < vocab/5."""
    files = []
    for fi in range(n_files):
        path = tmp_path / f"part-{fi}.txt"
        with open(path, "w") as f:
            for _ in range(lines_per):
                n_ids = rng.randint(1, 6)
                ids = rng.randint(0, vocab, n_ids)
                label = 1.0 if (ids < vocab // 5).any() else 0.0
                dense = rng.rand(4)
                f.write(
                    f"{n_ids} " + " ".join(map(str, ids)) + " "
                    + "4 " + " ".join(f"{v:.4f}" for v in dense) + " "
                    + f"1 {label}\n")
        files.append(str(path))
    return files


def _desc(batch_size=16):
    desc = pt.DataFeedDesc(batch_size=batch_size, name="ctr")
    desc.add_slot("ids", type="uint64", max_len=8)
    desc.add_slot("dense", type="float", is_dense=True, dim=4)
    desc.add_slot("label", type="float", is_dense=True, dim=1)
    return desc


def test_multislot_parse_roundtrip(tmp_path):
    files = _write_files(tmp_path, n_files=1, lines_per=7)
    feed = list(pt.MultiSlotDataFeed(_desc(batch_size=4)).read_file(files[0]))
    assert len(feed) == 2  # 4 + 3
    b0 = feed[0]
    assert b0["ids"].shape == (4, 8) and b0["ids__len"].shape == (4,)
    assert b0["dense"].shape == (4, 4)
    assert b0["label"].shape == (4, 1)
    assert set(np.unique(b0["label"])) <= {0.0, 1.0}
    # padded ids beyond length are zeros
    for i in range(4):
        ln = int(b0["ids__len"][i])
        assert (b0["ids"][i, ln:] == 0).all()


def test_multislot_rejects_malformed(tmp_path):
    import pytest

    path = tmp_path / "bad.txt"
    path.write_text("2 5\n")  # claims 2 values, has 1
    with pytest.raises(ValueError, match="malformed"):
        list(pt.MultiSlotDataFeed(_desc()).read_file(str(path)))


def test_desc_str_prototxt():
    s = _desc().desc_str()
    assert 'name: "ids"' in s and 'type: "uint64"' in s
    assert "batch_size: 16" in s and "is_dense: true" in s


def test_async_executor_trains_ctr_model(tmp_path):
    files = _write_files(tmp_path, n_files=4, lines_per=64)
    vocab, max_len = 50, 8

    ids = layers.data(name="ids", shape=[max_len], dtype="int64")
    ids_len = layers.data(name="ids__len", shape=[1], dtype="int64")
    dense = layers.data(name="dense", shape=[4], dtype="float32")
    label = layers.data(name="label", shape=[1], dtype="float32")
    emb = layers.embedding(
        layers.reshape(ids, [-1, max_len, 1]), size=[vocab, 8])
    pooled = layers.sequence_pool(emb, "sum", length=ids_len)
    feat = layers.concat([pooled, dense], axis=1)
    logit = layers.fc(feat, size=1)
    loss = layers.mean(
        layers.sigmoid_cross_entropy_with_logits(logit, label))
    pt.optimizer.AdamOptimizer(learning_rate=0.05).minimize(loss)

    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())

    aexe = pt.AsyncExecutor(pt.CPUPlace())
    aexe.executor = exe  # share the compiled cache/scope path
    all_losses = []
    for epoch in range(6):
        res = aexe.run_from_files(
            pt.default_main_program(), _desc(), files, thread_num=2,
            fetch_list=[loss])
        all_losses.append(float(np.mean([r[0] for r in res])))
    assert all_losses[-1] < all_losses[0] * 0.7, all_losses


def test_multislot_uint64_ids(tmp_path):
    """Hashed CTR ids live in the full uint64 range (reference MultiSlot
    uses uint64 slots); the parser must not overflow, and the batch must
    reduce ids into the table's id space ON THE HOST — with jax x64 off a
    uint64 feed would be silently truncated to uint32 at device transfer
    (round-3 advisor finding)."""
    path = tmp_path / "u64.txt"
    big = 2**64 - 1
    path.write_text(f"2 {big} 7 1 0.5 1 1.0\n")
    feed = list(pt.MultiSlotDataFeed(_desc(batch_size=1)).read_file(
        str(path)))[0]
    assert feed["ids"].dtype == np.int64
    assert feed["ids"][0, 0] == big % 0x7FFFFFFF  # int32-safe default space
    assert feed["ids"][0, 1] == 7
    assert feed["ids__len"][0] == 2

    # explicit table size: ids arrive ready to index the embedding
    desc = pt.DataFeedDesc(batch_size=1)
    desc.add_slot("ids", type="uint64", max_len=8, id_space=1000)
    desc.add_slot("dense", type="float", is_dense=True, dim=4)
    desc.add_slot("label", type="float", is_dense=True, dim=1)
    feed = list(pt.MultiSlotDataFeed(desc).read_file(str(path)))[0]
    assert feed["ids"][0, 0] == big % 1000
    assert (feed["ids"] < 1000).all()


class TestNativeMultiSlotParser:
    """native/multislot.cc vs the Python parser: identical rows, identical
    malformed-line behavior (reference parses in C++ the same way,
    data_feed.cc ParseOneInstance)."""

    def _desc(self):
        from paddle_tpu.data_feed import DataFeedDesc

        desc = DataFeedDesc(batch_size=4)
        desc.add_slot("dense_f", type="float", is_dense=True, dim=3)
        desc.add_slot("ids", type="uint64", max_len=5, id_space=1000)
        return desc

    def test_native_matches_python(self):
        from paddle_tpu import data_feed as dfm
        from paddle_tpu.data_feed import MultiSlotDataFeed

        lib = dfm._native_multislot()
        assert lib is not None, "g++ toolchain expected in this image"
        feed = MultiSlotDataFeed(self._desc())
        lines = []
        rng = np.random.RandomState(0)
        for _ in range(64):
            f = rng.randn(3)
            ids = rng.randint(0, 2**63, size=rng.randint(1, 5),
                              dtype=np.uint64)
            lines.append("3 " + " ".join(f"{v:.6f}" for v in f) + f" {len(ids)} "
                         + " ".join(str(int(i)) for i in ids))
        buf = ("\n".join(lines) + "\n").encode()
        native_rows = feed.parse_buffer(buf)
        py_rows = [feed.parse_line(ln) for ln in lines]
        assert len(native_rows) == len(py_rows) == 64
        for nr, pr in zip(native_rows, py_rows):
            np.testing.assert_allclose(nr[0], pr[0], rtol=1e-6)
            assert (nr[1] == pr[1]).all()
            assert nr[1].dtype == np.uint64  # >= 2^63 ids survive

    def test_malformed_lines_raise(self):
        from paddle_tpu.data_feed import MultiSlotDataFeed

        feed = MultiSlotDataFeed(self._desc())
        with pytest.raises(ValueError):
            feed.parse_buffer(b"3 1.0 2.0\n")   # truncated dense group
        with pytest.raises(ValueError):
            feed.parse_buffer(b"3 1.0 2.0 3.0 2 5 6 extra\n")  # trailing

    def test_read_file_batches_via_native(self, tmp_path):
        from paddle_tpu.data_feed import MultiSlotDataFeed

        feed = MultiSlotDataFeed(self._desc())
        p = tmp_path / "data.txt"
        p.write_text("\n".join(
            "3 0.5 1.5 2.5 2 7 8" for _ in range(10)) + "\n")
        batches = list(feed.read_file(str(p)))
        assert [b["dense_f"].shape[0] for b in batches] == [4, 4, 2]
        np.testing.assert_allclose(batches[0]["dense_f"][0],
                                   [0.5, 1.5, 2.5])
        assert batches[0]["ids"][0, :2].tolist() == [7, 8]
        assert batches[0]["ids__len"][0] == 2
