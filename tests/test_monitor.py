"""Telemetry subsystem tests (tier-1, no TPU): metrics-registry semantics,
executor instrumentation + the recompile detector, StepMonitor JSONL,
data-feed / inference metrics, and the hash_rng uint32 wrap guard."""

import json
import logging
import threading

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.flags import FLAGS
from paddle_tpu.monitor import (
    Counter,
    Histogram,
    MetricsRegistry,
    StepMonitor,
    default_registry,
)


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    """Each test starts with default flags and an empty default registry."""
    FLAGS.reset()
    default_registry().reset()
    yield
    FLAGS.reset()
    default_registry().reset()


# ---------------------------------------------------------------------------
# registry semantics
# ---------------------------------------------------------------------------


class TestRegistry:
    def test_counter_gauge_basics(self):
        reg = MetricsRegistry()
        c = reg.counter("a.calls")
        c.inc()
        c.inc(2.5)
        assert c.value == 3.5
        with pytest.raises(ValueError):
            c.inc(-1)
        g = reg.gauge("a.depth")
        g.set(7)
        g.inc()
        g.dec(3)
        assert g.value == 5.0
        # get-or-create returns the same object; kind mismatch raises
        assert reg.counter("a.calls") is c
        with pytest.raises(TypeError):
            reg.gauge("a.calls")

    def test_histogram_buckets(self):
        reg = MetricsRegistry()
        h = reg.histogram("lat", buckets=(0.01, 0.1, 1.0))
        for v in (0.005, 0.005, 0.05, 0.5, 5.0):
            h.observe(v)
        snap = h.snapshot()
        assert snap["count"] == 5
        assert snap["sum"] == pytest.approx(5.56)
        # cumulative le counts: 0.01->2, 0.1->3, 1.0->4, +Inf->5
        assert snap["buckets"] == [[0.01, 2], [0.1, 3], [1.0, 4],
                                   [float("inf"), 5]]
        # boundary lands in its own bucket (le semantics)
        h2 = reg.histogram("lat2", buckets=(1.0, 2.0))
        h2.observe(1.0)
        assert h2.snapshot()["buckets"][0] == [1.0, 1]
        with pytest.raises(ValueError):
            reg.histogram("bad", buckets=(2.0, 1.0))

    def test_prometheus_exposition(self):
        reg = MetricsRegistry()
        reg.counter("executor.cache_miss").inc(3)
        reg.histogram("req.seconds", buckets=(0.1, 1.0)).observe(0.05)
        text = reg.prometheus_text()
        assert "# TYPE executor_cache_miss counter" in text
        assert "executor_cache_miss 3" in text
        assert '# TYPE req_seconds histogram' in text
        assert 'req_seconds_bucket{le="0.1"} 1' in text
        assert 'req_seconds_bucket{le="+Inf"} 1' in text
        assert "req_seconds_count 1" in text

    def test_jsonl_exposition(self):
        reg = MetricsRegistry()
        reg.counter("n.calls").inc()
        reg.gauge("n.depth").set(2)
        lines = [json.loads(l) for l in reg.jsonl().splitlines()]
        by_name = {r["metric"]: r for r in lines}
        assert by_name["n.calls"]["type"] == "counter"
        assert by_name["n.calls"]["value"] == 1
        assert by_name["n.depth"]["value"] == 2
        assert all("ts" in r for r in lines)

    def test_quantile_inf_bucket_clamps_to_max_observed(self):
        """Regression (ISSUE 14 satellite): one outlier past the top
        bucket bound used to make quantile() return +Inf — /v1/models
        then reported "p99": Infinity.  The +Inf tail now clamps to the
        largest OBSERVED value."""
        reg = MetricsRegistry()
        h = reg.histogram("lat", buckets=(0.01, 0.1, 1.0))
        for _ in range(99):
            h.observe(0.005)
        h.observe(50.0)  # single outlier beyond the last bound
        assert h.quantile(0.5) == 0.01
        p99 = h.quantile(0.999)
        assert p99 == 50.0 and p99 != float("inf")
        assert h.max == 50.0
        # every observation past the top bound: still finite
        h2 = reg.histogram("lat2", buckets=(0.01,))
        h2.observe(3.0)
        h2.observe(7.0)
        assert h2.quantile(0.5) == 7.0
        assert h2.quantile(0.99) == 7.0
        # in-range behavior unchanged: bucket upper bound
        h3 = reg.histogram("lat3", buckets=(0.01, 0.1, 1.0))
        for v in (0.005, 0.05, 0.5):
            h3.observe(v)
        assert h3.quantile(0.99) == 1.0
        # max rides the snapshot for artifact consumers
        assert h3.snapshot()["max"] == 0.5

    def test_collect_hooks_run_at_snapshot(self):
        reg = MetricsRegistry()
        calls = []

        def hook():
            calls.append(1)
            reg.gauge("derived.g").set(42)

        reg.add_collect_hook(hook)
        reg.add_collect_hook(hook)  # idempotent
        text = reg.prometheus_text()
        assert calls == [1]
        assert "derived_g 42" in text

        def broken():
            raise RuntimeError("must not fail the scrape")

        reg.add_collect_hook(broken)
        assert "derived_g" in reg.prometheus_text()
        reg.remove_collect_hook(hook)
        reg.remove_collect_hook(broken)
        calls.clear()
        reg.snapshot()
        assert calls == []

    def test_slo_tracker_windows_and_burn_rate(self):
        from paddle_tpu.monitor import SloTracker

        tr = SloTracker("m", objective_ms=100.0, target=0.9)
        t0 = 1_000_000.0
        for _ in range(8):
            tr.observe(True, now=t0)
        for _ in range(2):
            tr.observe(False, now=t0)
        # 20% bad against a 10% budget -> burn rate 2.0
        assert tr.burn_rate(300, now=t0 + 5) == pytest.approx(2.0)
        assert tr.good_total == 8 and tr.bad_total == 2
        # the bad events age out of the 5m window but stay in the 1h one
        for _ in range(10):
            tr.observe(True, now=t0 + 1000)
        assert tr.burn_rate(300, now=t0 + 1000) == pytest.approx(0.0)
        assert tr.burn_rate(3600, now=t0 + 1000) == pytest.approx(1.0)
        # empty window burns nothing
        assert tr.burn_rate(300, now=t0 + 10_000) == 0.0

    def test_thread_safety_smoke(self):
        reg = MetricsRegistry()
        c = reg.counter("smoke.calls")
        h = reg.histogram("smoke.lat", buckets=(0.5,))
        n_threads, per = 8, 2000

        def work():
            for i in range(per):
                c.inc()
                h.observe((i % 10) / 10.0)

        ts = [threading.Thread(target=work) for _ in range(n_threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert c.value == n_threads * per
        assert h.count == n_threads * per
        assert h.snapshot()["buckets"][-1][1] == n_threads * per


# ---------------------------------------------------------------------------
# executor instrumentation + recompile detector
# ---------------------------------------------------------------------------


def _build_train_net():
    x = layers.data(name="x", shape=[8], dtype="float32")
    y = layers.data(name="y", shape=[1], dtype="float32")
    pred = layers.fc(x, size=1)
    loss = layers.mean(layers.square(pred - y))
    pt.optimizer.SGD(learning_rate=0.01).minimize(loss)
    return loss


def _feed(bs=4, seed=0):
    rng = np.random.RandomState(seed)
    return {"x": rng.randn(bs, 8).astype("float32"),
            "y": rng.randn(bs, 1).astype("float32")}


class TestExecutorTelemetry:
    def test_training_loop_counters_and_jsonl(self, tmp_path):
        """The acceptance-criteria loop: nonzero compile/run counters, a
        cache miss->hit transition, and a populated step-telemetry JSONL."""
        FLAGS.monitor = True
        loss = _build_train_net()
        exe = pt.Executor(pt.CPUPlace())
        exe.run(pt.default_startup_program())

        jsonl = tmp_path / "steps.jsonl"
        mon = StepMonitor(name="loop", examples_per_step=4,
                          jsonl_path=str(jsonl))
        mon.step()  # arm the timer
        feed = _feed()
        for _ in range(3):
            (lv,) = exe.run(feed=feed, fetch_list=[loss])
            mon.step(loss=float(np.asarray(lv).reshape(-1)[0]))
        mon.close()

        reg = default_registry()
        # compile/run counters nonzero (startup + train program compiles)
        assert reg.get("executor.compiles").value >= 2
        assert reg.get("executor.run.calls").value == 4
        # run_seconds holds cache-HIT calls only (startup + first train
        # call were compiles and land in compile_seconds instead)
        assert reg.get("executor.run_seconds").count == 2
        assert reg.get("executor.compile_seconds").count >= 2
        # miss -> hit transition: both sides populated
        assert reg.get("executor.cache_miss").value >= 2
        assert reg.get("executor.cache_hit").value >= 2
        # transfer byte counters moved
        assert reg.get("executor.feed_bytes").value > 0
        assert reg.get("executor.fetch_bytes").value > 0
        # no recompile storm: same key all loop -> no recompiles metric
        assert reg.get("executor.recompiles") is None

        recs = [json.loads(l) for l in jsonl.read_text().splitlines()]
        assert len(recs) == 3
        assert recs[0]["metric"] == "loop.step"
        assert recs[0]["unit"] == "examples/sec"
        assert recs[0]["value"] > 0
        assert "loss" in recs[-1] and "step_seconds" in recs[-1]
        assert reg.get("loop.steps").value == 3

    def test_recompile_detector_names_feed_signature(self, caplog):
        FLAGS.monitor = True
        FLAGS.vlog = 1
        loss = _build_train_net()
        exe = pt.Executor(pt.CPUPlace())
        exe.run(pt.default_startup_program())
        exe.run(feed=_feed(bs=4), fetch_list=[loss])  # miss (compile)
        exe.run(feed=_feed(bs=4), fetch_list=[loss])  # hit
        with caplog.at_level(logging.INFO, logger="paddle_tpu"):
            # forced feed-signature change: new batch size -> cache miss
            exe.run(feed=_feed(bs=2), fetch_list=[loss])
        msgs = [r.getMessage() for r in caplog.records
                if "recompile" in r.getMessage()]
        assert msgs, "recompile detector logged nothing"
        assert "feed-signature" in msgs[-1]
        # the unchanged components are NOT blamed
        assert "program-stamp" not in msgs[-1]
        assert "fetch-list" not in msgs[-1]
        assert default_registry().get("executor.recompiles").value == 1

    def test_recompile_storm_counts_every_miss(self):
        """A ragged-shape loop must count EVERY recompile of the storm,
        not just the first miss-after-hit; a first-compile burst (misses
        before anything ever hit) must count none."""
        FLAGS.monitor = True
        loss = _build_train_net()
        exe = pt.Executor(pt.CPUPlace())
        exe.run(pt.default_startup_program())     # miss (burst)
        exe.run(feed=_feed(bs=4), fetch_list=[loss])   # miss (burst)
        assert default_registry().get("executor.recompiles") is None
        exe.run(feed=_feed(bs=4), fetch_list=[loss])   # hit
        for bs in (2, 3, 5, 6):                        # 4-miss storm
            exe.run(feed=_feed(bs=bs), fetch_list=[loss])
        assert default_registry().get("executor.recompiles").value == 4
        # a hit ends the storm; the next first-compile is not a recompile
        exe.run(feed=_feed(bs=6), fetch_list=[loss])   # hit
        exe.run(feed=_feed(bs=7), fetch_list=[loss])   # miss-after-hit
        assert default_registry().get("executor.recompiles").value == 5

    def test_fetch_list_change_named(self, caplog):
        FLAGS.monitor = True
        FLAGS.vlog = 1
        loss = _build_train_net()
        exe = pt.Executor(pt.CPUPlace())
        exe.run(pt.default_startup_program())
        exe.run(feed=_feed(), fetch_list=[loss])
        exe.run(feed=_feed(), fetch_list=[loss])
        with caplog.at_level(logging.INFO, logger="paddle_tpu"):
            exe.run(feed=_feed(), fetch_list=[])
        msgs = [r.getMessage() for r in caplog.records
                if "recompile" in r.getMessage()]
        assert msgs and "fetch-list" in msgs[-1]

    def test_monitor_off_no_registry_writes(self):
        """Flag off (default): the executor hot path must not touch the
        registry at all."""
        assert FLAGS.monitor is False
        loss = _build_train_net()
        exe = pt.Executor(pt.CPUPlace())
        exe.run(pt.default_startup_program())
        for _ in range(2):
            exe.run(feed=_feed(), fetch_list=[loss])
        assert default_registry().names() == []

    def test_delegated_program_coarse_telemetry(self):
        """CompiledProgram delegates via _run: the delegation records
        coarse call/wall-time metrics; the non-parallel path falls back
        into run() and gets the full instrumentation too."""
        FLAGS.monitor = True
        loss = _build_train_net()
        exe = pt.Executor(pt.CPUPlace())
        exe.run(pt.default_startup_program())
        cp = pt.CompiledProgram(pt.default_main_program())
        exe.run(cp, feed=_feed(), fetch_list=[loss])
        reg = default_registry()
        assert reg.get("executor.delegated.calls").value == 1
        assert reg.get("executor.delegated_seconds").count == 1
        assert reg.get("executor.run.calls").value >= 1

    def test_error_counter_on_failed_run(self):
        FLAGS.monitor = True
        loss = _build_train_net()
        exe = pt.Executor(pt.CPUPlace())
        exe.run(pt.default_startup_program())
        with pytest.raises(Exception):
            exe.run(feed=_feed(), fetch_list=["no_such_var"])
        assert default_registry().get("executor.errors").value == 1
        # a healthy run afterwards still records normally
        exe.run(feed=_feed(), fetch_list=[loss])
        assert default_registry().get("executor.run.calls").value >= 1

    def test_run_steps_counters(self):
        FLAGS.monitor = True
        loss = _build_train_net()
        exe = pt.Executor(pt.CPUPlace())
        exe.run(pt.default_startup_program())
        feed = {k: np.stack([v, v]) for k, v in _feed().items()}
        exe.run_steps(feed=feed, fetch_list=[loss])
        exe.run_steps(feed=feed, fetch_list=[loss])
        reg = default_registry()
        assert reg.get("executor.run_steps.calls").value == 2
        assert reg.get("executor.cache_hit").value >= 1


# ---------------------------------------------------------------------------
# StepMonitor
# ---------------------------------------------------------------------------


class TestStepMonitor:
    def test_rates_and_mfu(self):
        import time

        mon = StepMonitor(name="t", examples_per_step=32,
                          tokens_per_step=64, flops_per_step=1e6,
                          peak_flops=1e12, window=4)
        assert mon.step(loss=2.0) is None  # arming call
        recs = []
        for i in range(5):
            time.sleep(0.002)  # bound dt away from 0 so mfu stays < 1
            recs.append(mon.step(loss=2.0 - 0.1 * i))
        assert all(r is not None for r in recs)
        r = recs[-1]
        assert r["unit"] == "examples/sec" and r["value"] > 0
        assert r["tokens_per_sec"] > 0
        assert 0 <= r["mfu"] <= 1.0
        assert "rolling_mfu" in r
        s = mon.summary()
        assert s["steps"] == 5 and s["examples_per_sec"] > 0
        reg = default_registry()
        assert reg.get("t.steps").value == 5
        assert reg.get("t.loss").value == pytest.approx(1.6)

    def test_cost_from_uses_xla_cost_model(self):
        """MFU FLOPs can come lazily from profiler.cost_analysis."""
        x = layers.data(name="x", shape=[64], dtype="float32")
        h = layers.fc(x, size=128, bias_attr=False)
        loss = layers.mean(h)
        exe = pt.Executor(pt.CPUPlace())
        exe.run(pt.default_startup_program())
        feed = {"x": np.zeros((32, 64), "float32")}
        mon = StepMonitor(
            name="c", peak_flops=1e12,
            cost_from=(pt.default_main_program(), feed, [loss]))
        assert mon.flops_per_step >= 2 * 32 * 64 * 128
        mon.step()
        rec = mon.step(loss=1.0)
        assert "mfu" in rec


# ---------------------------------------------------------------------------
# data feed + inference metrics
# ---------------------------------------------------------------------------


class TestDataFeedTelemetry:
    def _desc(self):
        from paddle_tpu.data_feed import DataFeedDesc

        desc = DataFeedDesc(batch_size=2)
        desc.add_slot("f", type="float", is_dense=True, dim=2)
        return desc

    def test_malformed_line_located_and_counted(self, tmp_path):
        from paddle_tpu.data_feed import MultiSlotDataFeed

        FLAGS.monitor = True
        path = tmp_path / "shard.txt"
        path.write_text("2 1.0 2.0\n2 3.0\n2 5.0 6.0\n")  # line 2 is short
        feed = MultiSlotDataFeed(self._desc())
        with pytest.raises(ValueError) as ei:
            list(feed.read_file(str(path)))
        msg = str(ei.value)
        assert "malformed" in msg
        # the exception names the offending content, not just a count
        assert "2 3.0" in msg or "line 2" in msg
        assert default_registry().get(
            "data_feed.malformed_lines").value >= 1

    def test_queue_gauges_populate(self, tmp_path):
        from paddle_tpu.data_feed import AsyncExecutor

        FLAGS.monitor = True
        path = tmp_path / "data.txt"
        path.write_text("".join(f"2 {i}.0 {i}.5\n" for i in range(6)))
        x = layers.data(name="f", shape=[2], dtype="float32")
        loss = layers.mean(x)
        exe = AsyncExecutor(pt.CPUPlace())
        scope = pt.Scope()
        results = exe.run_from_files(
            pt.default_main_program(), self._desc(), [str(path)],
            thread_num=1, fetch_list=[loss], scope=scope)
        assert len(results) == 3
        reg = default_registry()
        assert reg.get("data_feed.batches").value == 3
        assert reg.get("data_feed.stall_seconds").value >= 0
        assert reg.get("data_feed.queue_depth") is not None


class TestInferenceTelemetry:
    def test_request_histogram_and_qps_counter(self, tmp_path):
        from paddle_tpu.inference import Predictor

        prog, startup = pt.Program(), pt.Program()
        with pt.program_guard(prog, startup):
            x = layers.data(name="x", shape=[8], dtype="float32")
            pred = layers.fc(x, size=3, act="softmax")
        exe = pt.Executor(pt.CPUPlace())
        scope = pt.Scope()
        with pt.scope_guard(scope):
            exe.run(startup, scope=scope)
            pt.io.save_inference_model(
                str(tmp_path / "m"), ["x"], [pred], exe,
                main_program=prog, scope=scope)

        FLAGS.monitor = True
        p = Predictor(str(tmp_path / "m"))
        feed = {"x": np.random.RandomState(0).randn(4, 8).astype("float32")}
        for _ in range(5):
            (out,) = p.run(feed)
        assert out.shape == (4, 3)
        reg = default_registry()
        assert reg.get("inference.requests").value == 5
        h = reg.get("inference.request_seconds")
        assert isinstance(h, Histogram) and h.count == 5
        assert h.sum > 0
        assert reg.get("inference.examples").value == 20

    def test_use_aot_defaults_off(self):
        """ADVICE high: bundle loading runs jax's pickle-based executable
        deserializer — it must be explicit opt-in."""
        import inspect

        from paddle_tpu.inference import Predictor

        sig = inspect.signature(Predictor.__init__)
        assert sig.parameters["use_aot"].default is False


class TestCollectiveCounters:
    def test_trace_time_byte_accounting(self):
        from paddle_tpu.parallel import distributed as dist

        FLAGS.monitor = True
        x = np.zeros((4, 8), np.float32)
        dist._count_collective("all_reduce", x)
        dist._count_collective("all_reduce", x)
        dist._count_collective("all_gather", np.zeros((2,), np.int64))
        reg = default_registry()
        assert reg.get("collective.all_reduce.ops").value == 2
        assert reg.get("collective.all_reduce.bytes").value == 2 * 4 * 8 * 4
        assert reg.get("collective.all_gather.bytes").value == 16

    def test_gated_off(self):
        from paddle_tpu.parallel import distributed as dist

        dist._count_collective("all_reduce", np.zeros((4,), np.float32))
        assert default_registry().names() == []


# ---------------------------------------------------------------------------
# hash_rng uint32 wrap guard
# ---------------------------------------------------------------------------


class TestHashRngWrapGuard:
    def test_keep_mask_attn_raises_past_2_32(self):
        import jax.numpy as jnp

        from paddle_tpu.kernels import hash_rng

        seed = jnp.uint32(7)
        # fine: below the wrap line (tiny tensors; just probe the check)
        m = hash_rng.keep_mask_attn(seed, (1, 1, 4, 4), 0.5)
        assert m.shape == (1, 1, 4, 4)
        # tq*tk == 2^32 exactly still fits (max index 2^32 - 1): the
        # guard must be strictly greater-than
        with pytest.raises(ValueError, match="2\\^32"):
            hash_rng.keep_mask_attn(seed, (1, 1, 1 << 16, 1 << 17), 0.5)

    def test_flash_attention_guard(self):
        import jax.numpy as jnp

        from paddle_tpu.kernels.attention import flash_attention

        # shapes are validated BEFORE any compute: a >=2^32 mask plane
        # with dropout must raise, not silently wrap
        tq, tk = 1 << 16, 1 << 17
        q = jnp.zeros((1, 1, tq, 8), jnp.float32)
        kv = jnp.zeros((1, 1, tk, 8), jnp.float32)
        with pytest.raises(ValueError, match="2\\^32"):
            flash_attention(q, kv, kv, dropout_rate=0.1,
                            dropout_seed=jnp.uint32(1))

    def test_small_shapes_still_work(self):
        import jax.numpy as jnp

        from paddle_tpu.kernels.attention import flash_attention

        q = jnp.ones((1, 2, 8, 4), jnp.float32)
        out = flash_attention(q, q, q, dropout_rate=0.5,
                              dropout_seed=jnp.uint32(3))
        assert out.shape == q.shape
