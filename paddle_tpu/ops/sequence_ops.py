"""Sequence ops over padded batches + lengths/masks.

The reference represents ragged batches as LoD offset tables consumed by ~30
sequence_* ops (SURVEY.md §5.7, operators/sequence_ops/).  TPU-first these
become dense [batch, max_len, ...] tensors + a Length vector (static shapes,
MXU-friendly); each op takes an optional "Length" input where the reference
read LoD level 0.

Citations: sequence_pool_op.cc, sequence_softmax_op.cc, sequence_conv_op.cc,
sequence_expand_op.cc, sequence_reverse_op.h, sequence_mask_op.cc,
sequence_pad_op.cc, edit_distance_op.cc, row_conv_op.cc.
"""

from __future__ import annotations

from ..core.registry import register


def _jnp():
    import jax.numpy as jnp

    return jnp


def _mask_from_length(length, max_len, dtype="float32"):
    jnp = _jnp()
    ar = jnp.arange(max_len)[None, :]
    return (ar < length.reshape(-1, 1)).astype(dtype)


def _length_or_full(ins, x):
    jnp = _jnp()
    lens = ins.get("Length", [None])
    if lens and lens[0] is not None:
        # clamp to T so masks and count-denominators stay consistent
        return jnp.clip(lens[0].reshape(-1).astype("int32"), 0, x.shape[1])
    return jnp.full((x.shape[0],), x.shape[1], "int32")


@register("sequence_mask", no_grad=True)
def lower_sequence_mask(ctx, ins):
    jnp = _jnp()
    x = ins["X"][0].reshape(-1)
    maxlen = ctx.attr("maxlen", -1)
    if maxlen is None or maxlen < 0:
        raise ValueError("sequence_mask needs a static maxlen attr on TPU")
    dtype = ctx.attr("out_dtype", "int64")
    return {"Y": [_mask_from_length(x, maxlen, dtype)]}


@register("sequence_pool")
def lower_sequence_pool(ctx, ins):
    """X: [B, T, D] (+ Length [B]); pooltype sum/average/sqrt/max/last/first
    (reference sequence_pool_op.cc + math/sequence_pooling.cc)."""
    import jax.numpy as jnp

    x = ins["X"][0]
    length = _length_or_full(ins, x)
    ptype = ctx.attr("pooltype", "AVERAGE").upper()
    t = x.shape[1]
    mask = _mask_from_length(length, t, x.dtype)
    mask = mask.reshape(mask.shape + (1,) * (x.ndim - 2))
    if ptype == "SUM":
        out = (x * mask).sum(axis=1)
    elif ptype == "AVERAGE":
        out = (x * mask).sum(axis=1) / jnp.maximum(
            length.reshape((-1,) + (1,) * (x.ndim - 2)).astype(x.dtype), 1
        )
    elif ptype == "SQRT":
        out = (x * mask).sum(axis=1) / jnp.sqrt(
            jnp.maximum(
                length.reshape((-1,) + (1,) * (x.ndim - 2)).astype(x.dtype), 1
            )
        )
    elif ptype == "MAX":
        neg = jnp.full_like(x, -1e30)
        out = jnp.where(mask > 0, x, neg).max(axis=1)
    elif ptype == "LAST":
        idx = jnp.maximum(length - 1, 0)
        out = jnp.take_along_axis(
            x, idx.reshape((-1, 1) + (1,) * (x.ndim - 2)).astype("int32"), axis=1
        ).squeeze(1)
    elif ptype == "FIRST":
        out = x[:, 0]
    else:
        raise ValueError(f"sequence_pool: unknown pooltype {ptype}")
    return {"Out": [out]}


@register("sequence_softmax")
def lower_sequence_softmax(ctx, ins):
    import jax

    jnp = _jnp()
    x = ins["X"][0]  # [B, T]
    length = _length_or_full(ins, x)
    mask = _mask_from_length(length, x.shape[1], "bool")
    logits = jnp.where(mask, x.astype(jnp.float32), -1e30)
    out = jax.nn.softmax(logits, axis=-1) * mask.astype(jnp.float32)
    return {"Out": [out.astype(x.dtype)]}


@register("sequence_reverse")
def lower_sequence_reverse(ctx, ins):
    """Reverse each sequence within its valid length (padding stays)."""
    jnp = _jnp()
    x = ins["X"][0]
    length = _length_or_full(ins, x)
    t = x.shape[1]
    ar = jnp.arange(t)[None, :]
    idx = jnp.where(ar < length[:, None], length[:, None] - 1 - ar, ar)
    idx = idx.reshape((x.shape[0], t) + (1,) * (x.ndim - 2)).astype("int32")
    return {"Y": [jnp.take_along_axis(x, idx, axis=1)]}


@register("sequence_expand")
def lower_sequence_expand(ctx, ins):
    """Tile X rows per Y's time dim (simplified padded-world semantics:
    X [B, D] -> [B, T, D] matching Y's T)."""
    jnp = _jnp()
    x, y = ins["X"][0], ins["Y"][0]
    t = y.shape[1]
    return {"Out": [jnp.repeat(x[:, None], t, axis=1)]}


@register("sequence_conv")
def lower_sequence_conv(ctx, ins):
    """Context-window conv over time (reference sequence_conv_op.cc +
    math/context_project.h): for each t, concat rows [t+start, t+start+len)
    then project with Filter [len*D, M]."""
    jnp = _jnp()
    x = ins["X"][0]  # [B, T, D]
    w = ins["Filter"][0]
    ctx_len = ctx.attr("contextLength", 3)
    ctx_start = ctx.attr("contextStart", -1)
    b, t, d = x.shape
    cols = []
    for i in range(ctx_len):
        off = ctx_start + i
        shifted = jnp.roll(x, -off, axis=1)
        ar = jnp.arange(t)
        valid = ((ar + off) >= 0) & ((ar + off) < t)
        shifted = shifted * valid[None, :, None].astype(x.dtype)
        cols.append(shifted)
    ctx_mat = jnp.concatenate(cols, axis=-1)  # [B, T, len*D]
    out = jnp.einsum("btd,dm->btm", ctx_mat, w)
    return {"Out": [out]}


@register("row_conv")
def lower_row_conv(ctx, ins):
    """Lookahead row convolution (reference row_conv_op.cc): X [B,T,D],
    Filter [future_ctx, D]."""
    jnp = _jnp()
    x = ins["X"][0]
    w = ins["Filter"][0]
    k = w.shape[0]
    b, t, d = x.shape
    out = jnp.zeros_like(x)
    for i in range(k):
        shifted = jnp.roll(x, -i, axis=1)
        ar = jnp.arange(t)
        valid = (ar + i) < t
        shifted = shifted * valid[None, :, None].astype(x.dtype)
        out = out + shifted * w[i][None, None, :]
    return {"Out": [out]}


@register("sequence_pad")
def lower_sequence_pad(ctx, ins):
    """In the padded world X is already dense; emits X + Length passthrough
    (reference sequence_pad_op.cc converts LoD->padded)."""
    x = ins["X"][0]
    length = _length_or_full(ins, x)
    return {"Out": [x], "Length": [length.astype("int64")]}


@register("sequence_unpad")
def lower_sequence_unpad(ctx, ins):
    x = ins["X"][0]
    return {"Out": [x]}


@register("sequence_erase", no_grad=True)
def lower_sequence_erase(ctx, ins):
    """Mask out tokens in the erase list (dense variant: zeros them;
    reference removes them via LoD shrink, sequence_erase_op.cc)."""
    jnp = _jnp()
    x = ins["X"][0]
    tokens = ctx.attr("tokens", [])
    keep = jnp.ones_like(x, dtype=bool)
    for tok in tokens:
        keep &= x != tok
    return {"Out": [jnp.where(keep, x, jnp.zeros_like(x))]}


@register("edit_distance", no_grad=True)
def lower_edit_distance(ctx, ins):
    """Levenshtein distance via DP over lax.scan (reference
    edit_distance_op.cc).  Hyps/Refs: [B, T] int + lengths."""
    import jax
    import jax.numpy as jnp

    from .tensor_ops import _canon_i64

    hyp = ins["Hyps"][0].astype("int32")
    ref = ins["Refs"][0].astype("int32")
    if hyp.ndim == 3:
        hyp = hyp.reshape(hyp.shape[0], -1)
    if ref.ndim == 3:
        ref = ref.reshape(ref.shape[0], -1)
    hyp_len = _length_or_full({"Length": ins.get("HypsLength", [None])}, hyp)
    ref_len = _length_or_full({"Length": ins.get("RefsLength", [None])}, ref)
    b, th = hyp.shape
    tr = ref.shape[1]

    def one(h, r, hl, rl):
        row0 = jnp.arange(tr + 1, dtype=jnp.float32)
        row0 = jnp.minimum(row0, rl.astype(jnp.float32))

        def step(row, i):
            # row = distances for hyp prefix i; compute prefix i+1
            cost_del = row + 1.0
            sub = jnp.where(r == h[i], 0.0, 1.0)
            new = jnp.zeros_like(row).at[0].set(
                jnp.minimum((i + 1).astype(jnp.float32), hl.astype(jnp.float32))
            )

            def inner(carry, j):
                val = jnp.minimum(
                    jnp.minimum(row[j + 1] + 1.0, carry + 1.0),
                    row[j] + sub[j],
                )
                return val, val

            _, vals = jax.lax.scan(inner, new[0], jnp.arange(tr))
            new = new.at[1:].set(vals)
            # freeze rows beyond hyp length
            return jnp.where(i < hl, new, row), None

        final, _ = jax.lax.scan(step, row0, jnp.arange(th))
        return final[rl]

    dist = jax.vmap(one)(hyp, ref, hyp_len, ref_len)
    if ctx.attr("normalized", False):
        dist = dist / jnp.maximum(ref_len.astype(dist.dtype), 1.0)
    return {
        "Out": [dist.reshape(-1, 1)],
        # canonical int (int32 when x64 is off): an explicit jnp.int64
        # would truncate-and-warn on every trace
        "SequenceNum": [jnp.asarray([b], _canon_i64())],
    }


def _crf_unpack(transition):
    """Transition param [(n+2), n]: row 0 start weights, row 1 stop weights,
    rows 2.. the [n, n] transition matrix (reference linear_chain_crf_op.h
    layout)."""
    return transition[0], transition[1], transition[2:]


@register("linear_chain_crf", no_grad=False)
def lower_linear_chain_crf(ctx, ins):
    """Linear-chain CRF negative log-likelihood (reference:
    operators/linear_chain_crf_op.cc:1).

    Dense TPU form: Emission [b, T, n] + Label [b, T(,1)] + optional Length
    [b] replace the reference's LoD ragged batch; the forward algorithm is a
    lax.scan of masked log-sum-exp steps, so the whole loss jit-compiles
    (the reference walks sequences one by one on the host).  Gradients come
    from the generic vjp (the reference hand-derives alpha/beta recursions).

    Output LogLikelihood [b, 1] is the NEGATIVE log-likelihood (what the
    book label_semantic_roles model minimizes directly).
    """
    import jax
    jnp = _jnp()

    emission = ins["Emission"][0].astype(jnp.float32)
    transition = ins["Transition"][0].astype(jnp.float32)
    label = ins["Label"][0]
    b, t_max, n = emission.shape
    label = label.reshape(b, t_max).astype(jnp.int32)
    if ins.get("Length"):
        length = ins["Length"][0].reshape(-1).astype(jnp.int32)
    else:
        length = jnp.full((b,), t_max, jnp.int32)
    mask = (jnp.arange(t_max)[None, :] < length[:, None])  # [b, T] bool

    start, stop, trans = _crf_unpack(transition)

    # ---- score of the gold path ----------------------------------------
    emit_scores = jnp.take_along_axis(
        emission, label[:, :, None], axis=2)[:, :, 0]  # [b, T]
    gold_emit = jnp.where(mask, emit_scores, 0.0).sum(axis=1)
    gold_start = jnp.take(start, label[:, 0])
    last_idx = jnp.maximum(length - 1, 0)
    last_label = jnp.take_along_axis(label, last_idx[:, None], axis=1)[:, 0]
    gold_stop = jnp.take(stop, last_label)
    pair_scores = trans[label[:, :-1], label[:, 1:]]  # [b, T-1]
    pair_mask = mask[:, 1:]
    gold_trans = jnp.where(pair_mask, pair_scores, 0.0).sum(axis=1)
    gold = gold_start + gold_emit + gold_trans + gold_stop

    # ---- partition function (forward algorithm) -------------------------
    alpha0 = start[None, :] + emission[:, 0, :]  # [b, n]

    def step(alpha, xs):
        emit_t, mask_t = xs  # [b, n], [b]
        nxt = jax.nn.logsumexp(
            alpha[:, :, None] + trans[None, :, :], axis=1) + emit_t
        alpha = jnp.where(mask_t[:, None], nxt, alpha)
        return alpha, None

    alpha, _ = jax.lax.scan(
        step, alpha0,
        (emission[:, 1:].transpose(1, 0, 2), mask[:, 1:].T),
    )
    log_z = jax.nn.logsumexp(alpha + stop[None, :], axis=1)

    nll = log_z - gold
    return {"LogLikelihood": [nll[:, None]]}


@register("crf_decoding", no_grad=True)
def lower_crf_decoding(ctx, ins):
    """Viterbi decoding for the linear-chain CRF (reference:
    operators/crf_decoding_op.cc:1).

    Same dense layout as linear_chain_crf; the max-product recursion and
    the backtrack are both lax.scans, fully on device.  Without Label the
    output is the decoded tag path [b, T] (zeros past Length); with Label
    it is the per-position correctness indicator the reference emits.
    """
    import jax
    jnp = _jnp()

    emission = ins["Emission"][0].astype(jnp.float32)
    transition = ins["Transition"][0].astype(jnp.float32)
    b, t_max, n = emission.shape
    if ins.get("Length"):
        length = ins["Length"][0].reshape(-1).astype(jnp.int32)
    else:
        length = jnp.full((b,), t_max, jnp.int32)
    mask = (jnp.arange(t_max)[None, :] < length[:, None])

    start, stop, trans = _crf_unpack(transition)

    delta0 = start[None, :] + emission[:, 0, :]

    def fwd(delta, xs):
        emit_t, mask_t = xs
        cand = delta[:, :, None] + trans[None, :, :]  # [b, prev, cur]
        best_prev = jnp.argmax(cand, axis=1)          # [b, cur]
        nxt = jnp.max(cand, axis=1) + emit_t
        new_delta = jnp.where(mask_t[:, None], nxt, delta)
        return new_delta, best_prev

    delta, back = jax.lax.scan(
        fwd, delta0,
        (emission[:, 1:].transpose(1, 0, 2), mask[:, 1:].T),
    )  # back: [T-1, b, n]

    # stop weights apply to each sequence's final alive delta
    final = delta + stop[None, :]
    last_tag = jnp.argmax(final, axis=1).astype(jnp.int32)  # [b]

    def backtrack(tag, xs):
        back_t, t = xs  # [b, n], scalar time (row back_t maps t -> t+1)
        prev = jnp.take_along_axis(back_t, tag[:, None], axis=1)[:, 0]
        # only backtrack while t+1 < length (inside the sequence)
        keep = (t + 1) < length
        new_tag = jnp.where(keep, prev.astype(jnp.int32), tag)
        return new_tag, new_tag

    rev_ts = jnp.arange(t_max - 2, -1, -1)
    _, tags_rev = jax.lax.scan(backtrack, last_tag, (back[::-1], rev_ts))
    path = jnp.concatenate(
        [jnp.flip(tags_rev, axis=0), last_tag[None, :]], axis=0
    ).T  # [b, T]
    path = jnp.where(mask, path, 0).astype(jnp.int64)

    if ins.get("Label"):
        label = ins["Label"][0].reshape(b, t_max).astype(jnp.int64)
        correct = (path == label) & mask
        return {"ViterbiPath": [correct.astype(jnp.int64)]}
    return {"ViterbiPath": [path]}


@register("sequence_concat", no_grad=False)
def lower_sequence_concat(ctx, ins):
    """Per-sequence concatenation of two padded batches (reference:
    sequence_ops/sequence_concat_op.cc — LoD concat; dense form: out[i] =
    [x[i, :lx_i], y[i, :ly_i]] packed left, padded with 0).

    Inputs: X [b, Tx, ...], Y [b, Ty, ...], XLength/YLength [b] (optional;
    default full).  Output: Out [b, Tx+Ty, ...], OutLength [b]."""
    jnp = _jnp()
    x, y = ins["X"][0], ins["Y"][0]
    b, tx = x.shape[0], x.shape[1]
    ty = y.shape[1]
    if ins.get("XLength"):
        lx = ins["XLength"][0].reshape(-1).astype(jnp.int32)
    else:
        lx = jnp.full((b,), tx, jnp.int32)
    if ins.get("YLength"):
        ly = ins["YLength"][0].reshape(-1).astype(jnp.int32)
    else:
        ly = jnp.full((b,), ty, jnp.int32)
    t_out = tx + ty
    pos = jnp.arange(t_out)
    # gather map: position p takes x[p] if p < lx, else y[p - lx]
    from_x = pos[None, :] < lx[:, None]
    x_idx = jnp.clip(pos[None, :], 0, tx - 1)
    y_idx = jnp.clip(pos[None, :] - lx[:, None], 0, ty - 1)
    extra = (1,) * (x.ndim - 2)
    fx = from_x.reshape(from_x.shape + extra)
    xg = jnp.take_along_axis(
        x, x_idx.reshape(x_idx.shape + extra), axis=1)
    yg = jnp.take_along_axis(
        y, y_idx.reshape(y_idx.shape + extra), axis=1)
    out = jnp.where(fx, xg, yg)
    valid = pos[None, :] < (lx + ly)[:, None]
    out = jnp.where(valid.reshape(valid.shape + extra), out,
                    jnp.zeros_like(out))
    return {"Out": [out], "OutLength": [(lx + ly).astype(jnp.int64)]}


@register("sequence_slice", no_grad=False)
def lower_sequence_slice(ctx, ins):
    """Per-sequence [offset, offset+length) slice (reference:
    sequence_ops/sequence_slice_op.cc).  Inputs: X [b, T, ...], Offset [b],
    Length [b].  Output packed left into [b, T, ...], zeros past each new
    length, plus OutLength.

    Divergence: the reference host-validates offset+length <= seq_len with
    PADDLE_ENFORCE; data-dependent validation can't raise inside a jitted
    TPU program, so out-of-range requests are truncated to the sequence
    bounds (OutLength reflects the truncation) instead of fabricating
    duplicated rows."""
    jnp = _jnp()
    x = ins["X"][0]
    b, t = x.shape[0], x.shape[1]
    off = jnp.clip(
        ins["Offset"][0].reshape(-1).astype(jnp.int32), 0, t)
    ln = ins["Length"][0].reshape(-1).astype(jnp.int32)
    ln = jnp.clip(ln, 0, t - off)  # truncate to the sequence bounds
    pos = jnp.arange(t)
    src = jnp.clip(pos[None, :] + off[:, None], 0, t - 1)
    extra = (1,) * (x.ndim - 2)
    g = jnp.take_along_axis(x, src.reshape(src.shape + extra), axis=1)
    valid = pos[None, :] < ln[:, None]
    out = jnp.where(valid.reshape(valid.shape + extra), g,
                    jnp.zeros_like(g))
    return {"Out": [out], "OutLength": [ln.astype(jnp.int64)]}


@register("im2sequence", no_grad=False)
def lower_im2sequence(ctx, ins):
    """Image -> patch sequence (reference: im2sequence_op.cc): NCHW input
    with kernel/stride/padding becomes [b, oh*ow, c*kh*kw] rows, the OCR-
    pipeline front end.  XLA's patch extraction is one strided gather."""
    import jax

    jnp = _jnp()
    x = ins["X"][0]
    kh, kw = ctx.attr("kernels", [1, 1])
    sh, sw = ctx.attr("strides", [1, 1])
    pads = ctx.attr("paddings", [0, 0, 0, 0])  # up, left, down, right
    n, c, h, w = x.shape
    x = jnp.pad(x, ((0, 0), (0, 0), (pads[0], pads[2]),
                    (pads[1], pads[3])))
    hp, wp = x.shape[2], x.shape[3]
    oh = (hp - kh) // sh + 1
    ow = (wp - kw) // sw + 1
    patches = jax.lax.conv_general_dilated_patches(
        x, (kh, kw), (sh, sw), padding="VALID",
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
    )  # [n, c*kh*kw, oh, ow]
    out = patches.reshape(n, c * kh * kw, oh * ow).transpose(0, 2, 1)
    return {"Out": [out]}


@register("sequence_expand_as")
def lower_sequence_expand_as(ctx, ins):
    """Tile each X row to match Y's time dim (reference
    sequence_expand_as_op.cc; padded idiom: X [B, D] or [B, 1, D] ->
    [B, T, D] with T from Y)."""
    jnp = _jnp()
    x, y = ins["X"][0], ins["Y"][0]
    if x.ndim == 3 and x.shape[1] == 1:
        x = x[:, 0]
    t = y.shape[1]
    return {"Out": [jnp.repeat(x[:, None], t, axis=1)]}


@register("sequence_reshape")
def lower_sequence_reshape(ctx, ins):
    """Re-chunk the feature dim (reference sequence_reshape_op.cc: each
    timestep of width D becomes D/new_dim steps of width new_dim; dense
    idiom reshapes [B, T, D] -> [B, T*D/new_dim, new_dim])."""
    x = ins["X"][0]
    new_dim = ctx.attr("new_dim")
    b, t, d = x.shape
    return {"Out": [x.reshape(b, t * d // new_dim, new_dim)]}


@register("sequence_scatter", no_grad=False)
def lower_sequence_scatter(ctx, ins):
    """Scatter per-sequence updates into X (reference
    sequence_scatter_op.cc: X [B, D], Ids [B, S] column indices per row,
    Updates [B, S]; out[b, ids[b,s]] += updates[b,s])."""
    jnp = _jnp()
    x = ins["X"][0]
    ids = ins["Ids"][0].astype("int32")
    upd = ins["Updates"][0]
    if ids.ndim == 3:
        ids = ids[..., 0]
    b = x.shape[0]
    bi = jnp.broadcast_to(jnp.arange(b)[:, None], ids.shape)
    out = x.at[bi.reshape(-1), ids.reshape(-1)].add(
        upd.reshape(-1).astype(x.dtype))
    return {"Out": [out]}


@register("sequence_enumerate", no_grad=True)
def lower_sequence_enumerate(ctx, ins):
    """All win_size-length sub-sequences per step (reference
    sequence_enumerate_op.cc): X [B, T] int -> Out [B, T, win_size],
    steps beyond each row's Length (or T) padded with pad_value."""
    jnp = _jnp()
    x = ins["X"][0]
    if x.ndim == 3:
        x = x[..., 0]
    win = ctx.attr("win_size")
    pad = ctx.attr("pad_value", 0)
    b, t = x.shape
    length = _length_or_full(ins, x[:, :, None])
    idx = jnp.arange(t)[:, None] + jnp.arange(win)[None, :]   # [T, W]
    valid = idx[None] < length[:, None, None]                 # [B, T, W]
    gathered = x[:, jnp.minimum(idx, t - 1)]                  # [B, T, W]
    return {"Out": [jnp.where(valid, gathered,
                              jnp.asarray(pad, x.dtype))]}


@register("lod_reset", no_grad=False)
def lower_lod_reset(ctx, ins):
    """Re-segment a batch (reference lod_reset_op.cc: replace X's LoD with
    a target, keeping the data).  TPU-first mapping of LoD: data is padded
    dense + a Length vector, so the op passes the data through and emits
    the NEW per-sequence lengths — from input Y (a lengths tensor or a
    [n+1] offsets tensor, dtype int) or the static `target_lod` attr
    (reference convention: offsets)."""
    jnp = _jnp()
    x = ins["X"][0]
    if ins.get("Y"):
        y = ins["Y"][0].reshape(-1)
        # a [batch+1] vector is an offsets table (the reference feeds
        # offsets); a [batch] vector is already per-sequence lengths
        if y.shape[0] == x.shape[0] + 1:
            length = y[1:] - y[:-1]
        else:
            length = y
        return {"Out": [x], "Length": [length.astype(jnp.int64)]}
    lod = ctx.attr("target_lod", None)
    if not lod:
        return {"Out": [x], "Length": [jnp.full((x.shape[0],), x.shape[1]
                                                if x.ndim > 1 else 1,
                                                jnp.int64)]}
    import numpy as _np

    off = _np.asarray(lod, _np.int64)
    return {"Out": [x], "Length": [jnp.asarray(off[1:] - off[:-1])]}
