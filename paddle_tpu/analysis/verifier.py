"""Program verifier: pre-compile contract checks over the Python IR.

Capability parity with the reference's build/run-time op validation
(reference: operator.cc RuntimeInferShape + ENFORCE macros,
framework/op_desc.cc CheckAttrs, executor.cc:312 CheckTensorNANOrInf
being the *runtime* tail of it), redesigned TPU-first: since execution
lowers a whole block to ONE XLA computation, a contract violation that
the reference would catch per-op at dispatch time here surfaces as an
opaque trace error (or worse, silently wrong numerics — the PR-4
unthreaded step key).  This verifier runs the same class of checks
statically over the Program, BEFORE the trace, and names the op/var.

Checks (Finding.check ids):
  error severity — gate the executor compile (ProgramVerifyError):
    unregistered-op    op type has no lowering and is not grad-resolvable
    use-before-def     an op reads a name no prior op/feed/scope defines
    shape-contract     a registered infer_shape raises with fully known
                       input shapes (the reference ENFORCE class)
    shape-mismatch     declared output shape/dtype differs from what the
                       op's contract re-infers (stale/corrupt IR)
    fetch-unreachable  a fetch target no op produces and no feed/scope
                       var covers
    rng-unthreaded     an op whose registered lowering derives PRNG bits
                       (OpDef.derives_rng) is invisible to the executor's
                       step-key threading (executor.op_threads_rng) — it
                       would reuse the trace-constant base key every run
  warning severity — reported (CI gate fails) but do not block compile:
    dead-op            op contributes to no fetch target and writes no
                       persistable/scope state
    dead-var           declared var no op reads or writes, not data/fetch
    donated-fetch      a var is both donated rw state (read+written
                       persistable) and a fetch target — the aliasing
                       class behind the PR-6 stateful-AOT corruption
    double-write       a persistable/scope var written by 2+ stateful ops
                       in one block (write-back order becomes load-bearing)

Multi-program families (verify_program_set — the pipeline tier's
per-stage sub-programs) add cross-stage checks:
    stage-undefined-input    (error)  a stage input no earlier stage
                             (activations) / later stage (grads)
                             declares as an output
    stage-io-mismatch        (error)  producer/consumer disagree on a
                             boundary var's shape or dtype
    stage-foreign-optimizer  (error)  an Optimize-role op on a stage
                             that does not own its Param
    stage-unconsumed-output  (warning) declared boundary output nobody
                             consumes (dead wire traffic)
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..core import framework as fw
from ..core import registry

# side-effectful op types that must survive dead-code analysis even when
# nothing consumes their outputs
_SIDE_EFFECT_OPS = frozenset({"print", "while", "conditional_block"})

# per-check cap: a single corrupt var cascades through its consumers; the
# first few findings name the root cause, the rest are noise
_MAX_FINDINGS_PER_CHECK = 20


class Finding:
    """One named verifier/linter finding."""

    __slots__ = ("check", "severity", "message", "block_idx", "op_index",
                 "op_type", "var")

    def __init__(self, check: str, severity: str, message: str,
                 block_idx: Optional[int] = None,
                 op_index: Optional[int] = None,
                 op_type: Optional[str] = None,
                 var: Optional[str] = None):
        self.check = check
        self.severity = severity
        self.message = message
        self.block_idx = block_idx
        self.op_index = op_index
        self.op_type = op_type
        self.var = var

    def to_dict(self) -> dict:
        return {
            "check": self.check,
            "severity": self.severity,
            "message": self.message,
            "block": self.block_idx,
            "op_index": self.op_index,
            "op_type": self.op_type,
            "var": self.var,
        }

    def __repr__(self):
        where = ""
        if self.op_type is not None:
            where = f" [op {self.op_type}"
            if self.block_idx is not None:
                where += f" @ block {self.block_idx}:{self.op_index}"
            where += "]"
        return f"{self.severity}:{self.check}{where} {self.message}"

    __str__ = __repr__


class ProgramVerifyError(RuntimeError):
    """Raised by verify_or_raise when error-severity findings exist.
    Carries ALL findings (warnings included) on .findings."""

    def __init__(self, findings: List[Finding]):
        self.findings = findings
        errors = [f for f in findings if f.severity == "error"]
        lines = [f"program verification failed ({len(errors)} error(s)):"]
        lines += [f"  {f}" for f in findings]
        super().__init__("\n".join(lines))


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


def _initial_defined(program: fw.Program, feed_names, scope) -> set:
    """Names defined before the first op runs: feeds, scope-resident vars,
    and declared vars the startup program materializes (persistable /
    data / initializer-carrying)."""
    defined = set(feed_names)
    for blk in program.blocks:
        for name, v in blk.vars.items():
            if (v.persistable or v.is_data
                    or getattr(v, "initializer", None) is not None):
                defined.add(name)
            elif scope is not None and scope.has_var(name):
                defined.add(name)
    return defined


def _sub_blocks(op: fw.Operator):
    for a in op.attrs.values():
        if isinstance(a, fw.Block):
            yield a


def _iter_ops_recursive(block: fw.Block):
    for op in block.ops:
        yield block, op
        for sub in _sub_blocks(op):
            yield from _iter_ops_recursive(sub)


def _writes_recursive(op: fw.Operator) -> set:
    """All names written by the op, including inside its sub-blocks."""
    out = set(n for n in op.output_arg_names() if n)
    for sub in _sub_blocks(op):
        for sop in sub.ops:
            out |= _writes_recursive(sop)
    return out


def _reads_recursive(op: fw.Operator) -> set:
    out = set(n for n in op.input_arg_names() if n)
    for sub in _sub_blocks(op):
        for sop in sub.ops:
            out |= _reads_recursive(sop)
    return out


class _Capped:
    """Append findings with a per-check cap (cascades name their root in
    the first few findings; the tail is noise)."""

    def __init__(self, findings: List[Finding]):
        self.findings = findings
        self._counts: Dict[str, int] = {}

    def add(self, f: Finding):
        n = self._counts.get(f.check, 0)
        if n < _MAX_FINDINGS_PER_CHECK:
            self.findings.append(f)
        self._counts[f.check] = n + 1


# ---------------------------------------------------------------------------
# individual checks
# ---------------------------------------------------------------------------


def _check_def_before_use(program, defined0: set, cap: _Capped):
    """Strict in-order def-before-use on the global block; sub-blocks get
    the weaker defined-ANYWHERE rule (loop bodies legitimately read
    loop-carried names written later in the body)."""
    gb = program.global_block()
    defined = set(defined0)
    for i, op in enumerate(gb.ops):
        for n in op.input_arg_names():
            if n and n not in defined:
                cap.add(Finding(
                    "use-before-def", "error",
                    f"op {op.type!r} (block 0, index {i}) reads {n!r} "
                    f"before any feed, scope var, or prior op defines it",
                    block_idx=0, op_index=i, op_type=op.type, var=n))
        for sub in _sub_blocks(op):
            _check_sub_block_uses(sub, defined | _writes_recursive(op), cap)
        for n in op.output_arg_names():
            if n:
                defined.add(n)


def _check_sub_block_uses(block: fw.Block, outer_defined: set, cap: _Capped):
    available = set(outer_defined)
    available.update(block.vars)
    for op in block.ops:
        available |= _writes_recursive(op)
    for i, op in enumerate(block.ops):
        for n in op.input_arg_names():
            if n and n not in available:
                cap.add(Finding(
                    "use-before-def", "error",
                    f"op {op.type!r} (block {block.idx}, index {i}) reads "
                    f"{n!r}, which nothing in the block, its parents, or "
                    f"the feed/scope defines",
                    block_idx=block.idx, op_index=i, op_type=op.type,
                    var=n))
        for sub in _sub_blocks(op):
            _check_sub_block_uses(sub, available, cap)


def _check_shape_contracts(program, cap: _Capped):
    """Re-run every registered infer_shape in program order and compare
    against the declared output shapes/dtypes.  The program is restored
    bit-exact afterwards (set_output mutates Variable.shape, which feeds
    the fingerprint)."""
    snapshot: List[Tuple[Any, Any, Any]] = []
    for blk in program.blocks:
        for v in blk.vars.values():
            snapshot.append((v, v.shape, v.dtype))
    try:
        for blk, op in _iter_ops_recursive(program.global_block()):
            opdef = registry.lookup(op.type)
            if opdef is None or opdef.infer_shape is None:
                continue
            declared = {}
            for n in op.output_arg_names():
                if not n:
                    continue
                v = op.block._find_var_recursive(n)
                if v is not None:
                    declared[n] = (v.shape, v.dtype, v)
            try:
                opdef.infer_shape(fw.InferShapeContext(op))
            except Exception as e:
                # mirror Operator.__init__: a failure with fully known
                # input shapes is a real contract violation
                shapes = {}
                all_known = True
                for names in op.inputs.values():
                    for n in names:
                        if not n:
                            continue
                        v = op.block._find_var_recursive(n)
                        s = v.shape if v is not None else None
                        shapes[n] = s
                        if s is None:
                            all_known = False
                if all_known and shapes:
                    cap.add(Finding(
                        "shape-contract", "error",
                        f"infer_shape of op {op.type!r} failed with fully "
                        f"known input shapes {shapes}: {e}",
                        block_idx=blk.idx, op_type=op.type))
                continue
            for n, (shape0, dtype0, v) in declared.items():
                if shape0 is not None and v.shape is not None \
                        and tuple(shape0) != tuple(v.shape):
                    cap.add(Finding(
                        "shape-mismatch", "error",
                        f"op {op.type!r} declares output {n!r} shape "
                        f"{tuple(shape0)} but its contract infers "
                        f"{tuple(v.shape)}",
                        block_idx=blk.idx, op_type=op.type, var=n))
                elif dtype0 != v.dtype:
                    cap.add(Finding(
                        "shape-mismatch", "error",
                        f"op {op.type!r} declares output {n!r} dtype "
                        f"{dtype0} but its contract infers {v.dtype}",
                        block_idx=blk.idx, op_type=op.type, var=n))
    finally:
        for v, shape, dtype in snapshot:
            v.shape = shape
            v.dtype = dtype


def _check_rng_threading(program, cap: _Capped):
    """BIDIRECTIONAL cross-check of the two independent RNG declarations:
    registry derives_rng metadata vs the executor's step-key threading
    sets.  declared-but-unthreaded = the PR-4 frozen-mask class;
    threaded-but-undeclared = the metadata contract is stale, so the NEXT
    consumer of derives_rng (this verifier included) mis-models the op."""
    from ..core import executor as ex

    for blk, op in _iter_ops_recursive(program.global_block()):
        opdef = registry.lookup(op.type)
        if opdef is None:
            continue
        if not opdef.op_derives_rng(op):
            if (not op.type.endswith("_grad")
                    and ex.op_threads_rng(op)):
                cap.add(Finding(
                    "rng-undeclared", "error",
                    f"op {op.type!r} is in the executor's step-key "
                    f"threading sets (_RANDOM_OPS/_EXTRA_RANDOM_OPS) but "
                    f"its registration carries no derives_rng metadata — "
                    f"declare it via registry.register(..., derives_rng=...)"
                    f" so the contract stays two-sided",
                    block_idx=blk.idx, op_type=op.type))
            continue
        if not ex.op_threads_rng(op):
            cap.add(Finding(
                "rng-unthreaded", "error",
                f"op {op.type!r} declares derives_rng (its lowering draws "
                f"PRNG bits) but executor.op_threads_rng does not cover "
                f"it: plain Executor.run would reuse the trace-constant "
                f"base key on every step (the PR-4 dropout_add bug class)."
                f" In-tree ops belong in executor._RANDOM_OPS / "
                f"_COND_RANDOM_OPS; downstream ops call "
                f"executor.register_random_op({op.type!r}).",
                block_idx=blk.idx, op_type=op.type))


def _check_fetch_reachable(program, defined0, fetch_names, cap: _Capped):
    produced = set(defined0)
    for op in program.global_block().ops:
        produced |= set(n for n in op.output_arg_names() if n)
    for n in fetch_names:
        if n and n not in produced:
            cap.add(Finding(
                "fetch-unreachable", "error",
                f"fetch target {n!r} is produced by no op and covered by "
                f"no feed/scope/persistable var",
                var=n))


def _check_dead_code(program, feed_names, fetch_names, scope, cap: _Capped):
    gb = program.global_block()

    def _stateful_write(op) -> bool:
        for n in op.output_arg_names():
            if not n:
                continue
            v = op.block._find_var_recursive(n)
            if v is not None and v.persistable:
                return True
            if scope is not None and scope.has_var(n):
                return True
        return False

    # ---- dead ops: backward slice from fetches + stateful writes -------
    if fetch_names:
        needed = set(fetch_names)
        keep_flags = [False] * len(gb.ops)
        for i in range(len(gb.ops) - 1, -1, -1):
            op = gb.ops[i]
            keep = (
                op.type in _SIDE_EFFECT_OPS
                or any(o in needed for o in op.output_arg_names())
                or _stateful_write(op)
            )
            if keep:
                keep_flags[i] = True
                needed |= _reads_recursive(op)
        for i, op in enumerate(gb.ops):
            if not keep_flags[i]:
                cap.add(Finding(
                    "dead-op", "warning",
                    f"op {op.type!r} (block 0, index {i}, outputs "
                    f"{[n for n in op.output_arg_names() if n][:4]}) "
                    f"contributes to no fetch target and writes no "
                    f"persistable/scope state",
                    block_idx=0, op_index=i, op_type=op.type))

    # ---- dead vars: declared but referenced by no op -------------------
    referenced: set = set()
    for _, op in _iter_ops_recursive(gb):
        referenced |= set(op.input_arg_names())
        referenced |= set(op.output_arg_names())
    keep_names = set(feed_names) | set(fetch_names)
    for blk in program.blocks:
        for name, v in blk.vars.items():
            if name in referenced or name in keep_names:
                continue
            if v.persistable or v.is_data:
                continue
            if v.type != fw.VarType.DENSE_TENSOR:
                continue
            cap.add(Finding(
                "dead-var", "warning",
                f"var {name!r} (block {blk.idx}) is declared but no op "
                f"reads or writes it",
                block_idx=blk.idx, var=name))


def _check_alias_conflicts(program, feed_names, fetch_names, scope,
                           cap: _Capped):
    """Donation hazards, mirroring the executor's rw-state split
    (analyze_block_io): a var read before written AND written among the
    persistable/scope set gets its buffer DONATED to the executable."""
    gb = program.global_block()

    def _is_state(n: str) -> bool:
        v = gb._find_var_recursive(n)
        if v is not None and v.persistable:
            return True
        return scope is not None and scope.has_var(n)

    defined = set(feed_names)
    reads_before_write: set = set()
    writers: Dict[str, List[str]] = {}
    for op in gb.ops:
        in_names = set(n for n in op.input_arg_names() if n)
        for sub in _sub_blocks(op):
            for _, sop in _iter_ops_recursive(sub):
                in_names |= set(n for n in sop.input_arg_names() if n)
        for n in in_names:
            if n not in defined and _is_state(n):
                reads_before_write.add(n)
                defined.add(n)
        own_reads = set(n for n in op.input_arg_names() if n)
        for n in op.output_arg_names():
            if not n:
                continue
            defined.add(n)
            if _is_state(n):
                # rmw: the op also READS the var it writes (optimizer
                # in-place updates, the generation tier's per-layer
                # kv_cache_update chain) — ordered by data flow, so it
                # counts for donation (rw state) but is NOT the
                # independent-writer hazard double-write warns about
                writers.setdefault(n, []).append(
                    (op.type, n in own_reads))

    rw = reads_before_write & set(writers)
    for n in sorted(rw & set(fetch_names)):
        cap.add(Finding(
            "donated-fetch", "warning",
            f"var {n!r} is donated rw state (read+written persistable, "
            f"updated in place in HBM) AND a fetch target — the aliasing "
            f"class behind the v1 stateful-AOT corruption (PR 6); fetch a "
            f"copy or drop the fetch",
            var=n))
    for n, ops in sorted(writers.items()):
        indep = [t for t, rmw in ops if not rmw]
        if len(ops) > 1 and indep:
            cap.add(Finding(
                "double-write", "warning",
                f"persistable/scope var {n!r} is written by {len(ops)} "
                f"ops in one block ({[t for t, _ in ops][:4]}): the "
                f"scope write-back order becomes load-bearing",
                var=n))


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------


def verify_program(
    program: fw.Program,
    feed_names: Sequence[str] = (),
    fetch_names: Sequence[str] = (),
    scope=None,
    check_dead: bool = True,
) -> List[Finding]:
    """Run every static check over `program`; returns ALL findings
    (errors first).  Never mutates the program (shape re-inference is
    snapshot/restored)."""
    findings: List[Finding] = []
    cap = _Capped(findings)
    fetch_names = [
        v.name if isinstance(v, fw.Variable) else v for v in fetch_names
    ]
    defined0 = _initial_defined(program, feed_names, scope)

    gb = program.global_block()
    for blk, op in _iter_ops_recursive(gb):
        if registry.lookup(op.type) is None \
                and registry.get_grad_lowering(op.type) is None:
            cap.add(Finding(
                "unregistered-op", "error",
                f"op type {op.type!r} has no registered lowering and no "
                f"grad-resolvable forward op",
                block_idx=blk.idx, op_type=op.type))
    _check_def_before_use(program, defined0, cap)
    _check_shape_contracts(program, cap)
    _check_rng_threading(program, cap)
    _check_fetch_reachable(program, defined0, fetch_names, cap)
    if check_dead:
        _check_dead_code(program, feed_names, fetch_names, scope, cap)
    _check_alias_conflicts(program, feed_names, fetch_names, scope, cap)

    findings.sort(key=lambda f: (f.severity != "error", f.check))
    return findings


def verify_program_set(stages: Sequence[dict]) -> List[Finding]:
    """Cross-stage checks over a multi-program family (the pipeline
    tier's per-stage sub-programs; PR-6 multi-model serving is the other
    consumer of multi-program scheduling).  Each entry is a
    PipelineStage.io_summary()-shaped dict:

        {index, fwd_inputs/fwd_outputs/bwd_inputs/bwd_outputs:
         [(name, shape, dtype)], owned_params: [names], program}

    Checks (error severity — the pipeline trainer's pre-compile gate):
      stage-undefined-input   a declared stage input no earlier stage
                              (fwd) / later stage (bwd grads) declares as
                              an output — the cross-program
                              def-before-use class
      stage-io-mismatch       producer and consumer declare different
                              shapes/dtypes for the same boundary var
      stage-foreign-optimizer an Optimize-role op landed on a stage that
                              does not own its Param — its grads/moments
                              would never meet
    Warning severity:
      stage-unconsumed-output a declared boundary output no other stage
                              consumes (dead wire traffic)
    """
    findings: List[Finding] = []
    cap = _Capped(findings)
    n = len(stages)
    by_idx = sorted(stages, key=lambda s: s["index"])

    def _sigs(stage, key):
        return {name: (tuple(shape), dtype)
                for name, shape, dtype in stage.get(key, ())}

    fwd_outs = [_sigs(s, "fwd_outputs") for s in by_idx]
    bwd_outs = [_sigs(s, "bwd_outputs") for s in by_idx]
    consumed: set = set()
    for i, stage in enumerate(by_idx):
        for name, shape, dtype in stage.get("fwd_inputs", ()):
            consumed.add(("fwd", name))
            hits = [(j, fwd_outs[j][name]) for j in range(i)
                    if name in fwd_outs[j]]
            if not hits:
                cap.add(Finding(
                    "stage-undefined-input", "error",
                    f"stage {stage['index']} consumes activation "
                    f"{name!r} that no earlier stage declares as a "
                    f"forward output", var=name))
                continue
            _check_sig_match(cap, stage["index"], name,
                             (tuple(shape), dtype), hits)
        for name, shape, dtype in stage.get("bwd_inputs", ()):
            consumed.add(("bwd", name))
            hits = [(j, bwd_outs[j][name]) for j in range(i + 1, n)
                    if name in bwd_outs[j]]
            if not hits:
                cap.add(Finding(
                    "stage-undefined-input", "error",
                    f"stage {stage['index']} consumes boundary grad "
                    f"{name!r} that no later stage declares as a "
                    f"backward output", var=name))
                continue
            _check_sig_match(cap, stage["index"], name,
                             (tuple(shape), dtype), hits)
        owned = set(stage.get("owned_params", ()))
        prog = stage.get("program")
        if prog is not None:
            for op in prog.global_block().ops:
                role = int(op.attrs.get(fw.OpRole.ROLE_ATTR_NAME, 0))
                if not role & fw.OpRole.Optimize:
                    continue
                for p in op.inputs.get("Param", []):
                    if p and p not in owned:
                        cap.add(Finding(
                            "stage-foreign-optimizer", "error",
                            f"Optimize-role op {op.type!r} on stage "
                            f"{stage['index']} updates param {p!r} owned "
                            f"by another stage — its grads/moments would "
                            f"never meet", op_type=op.type, var=p))
    for i, stage in enumerate(by_idx):
        for kind, outs in (("fwd", stage.get("fwd_outputs", ())),
                           ("bwd", stage.get("bwd_outputs", ()))):
            for name, _, _ in outs:
                if (kind, name) not in consumed:
                    cap.add(Finding(
                        "stage-unconsumed-output", "warning",
                        f"stage {stage['index']} declares {kind} boundary "
                        f"output {name!r} that no other stage consumes",
                        var=name))
    findings.sort(key=lambda f: (f.severity != "error", f.check))
    return findings


def _check_sig_match(cap, idx, name, want, hits):
    for j, got in hits:
        if want[0] and got[0] and tuple(want[0]) != tuple(got[0]):
            cap.add(Finding(
                "stage-io-mismatch", "error",
                f"boundary var {name!r}: stage {idx} expects shape "
                f"{tuple(want[0])} but stage {j} produces "
                f"{tuple(got[0])}", var=name))
        elif want[1] != got[1]:
            cap.add(Finding(
                "stage-io-mismatch", "error",
                f"boundary var {name!r}: stage {idx} expects dtype "
                f"{want[1]} but stage {j} produces {got[1]}", var=name))


def verify_or_raise(program, feed_names=(), fetch_names=(), scope=None,
                    check_dead: bool = False):
    """The executor's pre-compile gate: raise ProgramVerifyError when any
    ERROR-severity finding exists.  Dead-code analysis is off by default
    here — partially-fetched programs are legitimate at run time (the
    executor prunes nothing); the CLI/CI path (tools/graph_lint.py) runs
    it with check_dead=True and gates on warnings too."""
    findings = verify_program(program, feed_names=feed_names,
                              fetch_names=fetch_names, scope=scope,
                              check_dead=check_dead)
    if any(f.severity == "error" for f in findings):
        raise ProgramVerifyError(findings)
    return findings
