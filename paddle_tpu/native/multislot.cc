// Native MultiSlot text parser — the C++ data plane of the AsyncExecutor
// path (reference: framework/data_feed.cc MultiSlotDataFeed::ParseOneInstance
// — the reference parses training text in C++ so no Python sits in the
// ingest loop; this is the TPU-native equivalent, ctypes-bound).
//
// Wire format per line (data_feed.proto MultiSlot):
//   <n0> v0_1 ... v0_n0  <n1> v1_1 ... v1_n1  ...     (one group per slot)
// float slots parse with strtof; id slots with strtoull (ids are uint64 on
// the wire — hashed ids >= 2^63 must not overflow, data_feed.h:224).
//
// ms_parse tokenizes a whole buffer into two flat value streams (floats /
// ids) plus a per-(row, slot) count matrix; the Python side reassembles
// batches with numpy slicing.  Malformed lines are skipped, matching the
// Python parser's parse_line -> None contract.

#include <cstdint>
#include <cstdlib>
#include <cstring>

extern "C" {

// Returns rows parsed (>= 0), or -1 if an output capacity was exceeded.
// used[0] <- floats written, used[1] <- ids written, used[2] <- lines
// skipped as malformed.
long long ms_parse(const char* buf, long long len, int n_slots,
                   const unsigned char* is_float, long long max_rows,
                   float* fvals, long long fcap,
                   unsigned long long* ivals, long long icap,
                   long long* counts, long long* used) {
  long long rows = 0, fused = 0, iused = 0, skipped = 0;
  const char* p = buf;
  const char* end = buf + len;

  while (p < end && rows < max_rows) {
    // isolate one line
    const char* line_end = static_cast<const char*>(
        memchr(p, '\n', static_cast<size_t>(end - p)));
    if (line_end == nullptr) line_end = end;
    const char* q = p;

    // skip blank lines
    while (q < line_end && (*q == ' ' || *q == '\t' || *q == '\r')) q++;
    if (q == line_end) {
      p = line_end + 1;
      continue;
    }

    long long row_f = fused, row_i = iused;  // rollback points
    long long* row_counts = counts + rows * n_slots;
    bool ok = true;

    for (int s = 0; s < n_slots && ok; s++) {
      // group count
      char* next = nullptr;
      long long n = strtoll(q, &next, 10);
      // strtoll/strtof skip leading whitespace INCLUDING '\n' — a short
      // line must not silently consume tokens from the next one
      if (next == q || n < 0 || next > line_end) { ok = false; break; }
      q = next;
      row_counts[s] = n;
      if (is_float[s]) {
        if (fused + n > fcap) return -1;
        for (long long j = 0; j < n; j++) {
          float v = strtof(q, &next);
          if (next == q || next > line_end) { ok = false; break; }
          q = next;
          fvals[fused++] = v;
        }
      } else {
        if (iused + n > icap) return -1;
        for (long long j = 0; j < n; j++) {
          unsigned long long v = strtoull(q, &next, 10);
          if (next == q || next > line_end) { ok = false; break; }
          q = next;
          ivals[iused++] = v;
        }
      }
    }
    // trailing garbage on the line also marks it malformed
    if (ok) {
      while (q < line_end && (*q == ' ' || *q == '\t' || *q == '\r')) q++;
      if (q != line_end) ok = false;
    }

    if (ok) {
      rows++;
    } else {
      fused = row_f;
      iused = row_i;
      skipped++;
    }
    p = line_end + 1;
  }

  used[0] = fused;
  used[1] = iused;
  used[2] = skipped;
  return rows;
}

}  // extern "C"
