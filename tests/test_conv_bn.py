"""Fused conv+BN path (PERF.md round 7, FLAGS_fused_bn).

Covers the r07 acceptance contract:
  * numerical parity of the fused kernels and the conv2d_bn op against the
    reference batch_norm composition, train AND is_test modes, including
    the stateful running-mean/variance updates and fp32/bf16 mixed
    precision;
  * custom-VJP gradcheck against jax reference gradients (the fused
    backward folds the dgamma/dbeta channel reductions into the dx pass
    and regenerates the ReLU mask / x-hat instead of storing them);
  * interpret-kernel <-> XLA-fallback parity;
  * zero-cost-off: FLAGS_fused_bn off => the model builders emit a graph
    op-for-op identical to the pre-fusion one, and its compiled HLO is
    bit-identical to the hand-written legacy composition;
  * the hlo_diag --bn-fusion report: the fused path removes the BN-stat
    channel-reduction passes from the optimized HLO;
  * a TPU-only class that arms on the driver's chip (compiled Mosaic
    kernels vs the XLA fallback).
"""

import contextlib
import importlib.util
import os
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.core import framework as fw
from paddle_tpu.flags import FLAGS
from paddle_tpu.kernels import conv_bn as CB
from paddle_tpu.models import resnet as R

EPS = 1e-5


@contextlib.contextmanager
def _fused_bn(flag):
    """Set FLAGS.fused_bn, restoring the PREVIOUS override on exit (a
    plain FLAGS.reset would clobber an enclosing _fused_bn context —
    these nest: the builders use one internally)."""
    values = object.__getattribute__(FLAGS, "_values")
    had = "fused_bn" in values
    prev = values.get("fused_bn")
    FLAGS.fused_bn = flag
    try:
        yield
    finally:
        if had:
            FLAGS.fused_bn = prev
        else:
            FLAGS.reset("fused_bn")


def _hlo_diag():
    path = os.path.join(os.path.dirname(__file__), "..", "tools",
                        "hlo_diag.py")
    spec = importlib.util.spec_from_file_location("_hlo_diag_mod", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _ref_bn(x, gamma, beta, eps=EPS, residual=None, relu=False):
    """Pure-jax reference of the training BN (the batch_norm lowering's
    math): fp32 stats, per-channel scale/shift applied in x's dtype."""
    xs = x.astype(jnp.float32)
    mean = xs.mean(tuple(range(x.ndim - 1)))
    var = (xs * xs).mean(tuple(range(x.ndim - 1))) - jnp.square(mean)
    wv = gamma.astype(jnp.float32) * jax.lax.rsqrt(var + eps)
    bv = beta.astype(jnp.float32) - mean * wv
    out = x * wv.astype(x.dtype) + bv.astype(x.dtype)
    if residual is not None:
        out = out + residual.astype(x.dtype)
    if relu:
        out = jax.nn.relu(out)
    return out


def _fused_bn_fn(x, gamma, beta, eps=EPS, residual=None, relu=False,
                 interpret=None):
    s1, s2 = CB.channel_stats(x, interpret=interpret)
    m = x.size // x.shape[-1]
    mean = s1 / m
    var = s2 / m - jnp.square(mean)
    return CB.bn_apply(x, gamma, beta, mean, var, residual=residual,
                       eps=eps, act="relu" if relu else "",
                       interpret=interpret)


class TestKernels:
    @pytest.mark.parametrize("c", [256, 64])  # direct lanes / lane-fold
    def test_channel_stats_parity_and_vjp(self, c):
        rng = np.random.RandomState(0)
        y = jnp.asarray(rng.randn(4, 8, 8, c).astype("float32"))
        s1, s2 = jax.jit(CB.channel_stats)(y)
        ys = np.asarray(y, np.float64).reshape(-1, c)
        np.testing.assert_allclose(np.asarray(s1), ys.sum(0),
                                   rtol=1e-5, atol=1e-3)
        np.testing.assert_allclose(np.asarray(s2), (ys * ys).sum(0),
                                   rtol=1e-5, atol=1e-3)

        def loss_fused(y):
            s1, s2 = CB.channel_stats(y)
            return jnp.sum(jnp.cos(s1)) + 1e-3 * jnp.sum(s2)

        def loss_ref(y):
            ys = y.astype(jnp.float32).reshape(-1, c)
            return (jnp.sum(jnp.cos(ys.sum(0)))
                    + 1e-3 * jnp.sum((ys * ys).sum(0)))

        gf = jax.grad(loss_fused)(y)
        gr = jax.grad(loss_ref)(y)
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   rtol=1e-4, atol=1e-4)

    def test_dot_col_stats_parity_and_grads(self):
        rng = np.random.RandomState(1)
        x = jnp.asarray(rng.randn(512, 64).astype("float32"))
        w = jnp.asarray(rng.randn(256, 64).astype("float32"))
        y, s1, s2 = jax.jit(CB.dot_col_stats)(x, w)
        y0 = np.asarray(x, np.float64) @ np.asarray(w, np.float64).T
        np.testing.assert_allclose(np.asarray(y), y0, rtol=1e-4, atol=1e-3)
        np.testing.assert_allclose(np.asarray(s1), y0.sum(0),
                                   rtol=1e-4, atol=1e-2)
        np.testing.assert_allclose(np.asarray(s2), (y0 * y0).sum(0),
                                   rtol=1e-4, atol=1e-1)

        def loss_fused(x, w):
            y, s1, s2 = CB.dot_col_stats(x, w)
            return (jnp.sum(y * 0.3) + jnp.sum(jnp.cos(s1))
                    + 1e-4 * jnp.sum(s2))

        def loss_ref(x, w):
            y = jax.lax.dot_general(x, w, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32)
            ys = y.astype(jnp.float32)
            return (jnp.sum(y * 0.3) + jnp.sum(jnp.cos(ys.sum(0)))
                    + 1e-4 * jnp.sum((ys * ys).sum(0)))

        gf = jax.grad(loss_fused, (0, 1))(x, w)
        gr = jax.grad(loss_ref, (0, 1))(x, w)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-3)

    @pytest.mark.parametrize("residual,relu", [(False, False), (True, True)])
    def test_bn_apply_fwd_parity_fp32(self, residual, relu):
        rng = np.random.RandomState(2)
        x = jnp.asarray(rng.randn(4, 8, 8, 128).astype("float32"))
        res = (jnp.asarray(rng.randn(4, 8, 8, 128).astype("float32"))
               if residual else None)
        gamma = jnp.asarray(rng.rand(128).astype("float32") + 0.5)
        beta = jnp.asarray(rng.randn(128).astype("float32"))
        out = jax.jit(lambda *a: _fused_bn_fn(
            *a, residual=res, relu=relu))(x, gamma, beta)
        ref = _ref_bn(x, gamma, beta, residual=res, relu=relu)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    @pytest.mark.parametrize("dt", [jnp.float32, jnp.bfloat16])
    def test_bn_apply_gradcheck_vs_jax_reference(self, dt):
        """Custom-VJP gradcheck: the fused backward (mask/x-hat recompute
        + in-pass channel reductions) against jax.grad of the reference
        composition.  bf16 compares both against the all-f32 truth — the
        fused path's f32 channel accumulations are strictly CLOSER to
        truth than the reference's bf16 reductions (measured in-session),
        so fused-vs-ref comparisons would test the reference's noise."""
        rng = np.random.RandomState(3)
        x = jnp.asarray(rng.randn(4, 8, 8, 128).astype("float32")).astype(dt)
        res = jnp.asarray(
            rng.randn(4, 8, 8, 128).astype("float32")).astype(dt)
        gamma = jnp.asarray(rng.rand(128).astype("float32") + 0.5)
        beta = jnp.asarray(rng.randn(128).astype("float32"))

        def loss(fn):
            return lambda *a: jnp.sum(
                fn(*a).astype(jnp.float32) * 0.1)

        gf = jax.grad(loss(lambda x, g, b, r: _fused_bn_fn(
            x, g, b, residual=r, relu=True)), (0, 1, 2, 3))(
            x, gamma, beta, res)
        gr = jax.grad(loss(lambda x, g, b, r: _ref_bn(
            x, g, b, residual=r, relu=True)), (0, 1, 2, 3))(
            x, gamma, beta, res)
        if dt == jnp.float32:
            for i, (a, b) in enumerate(zip(gf, gr)):
                np.testing.assert_allclose(
                    np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4,
                    err_msg=f"grad {i}")
            return
        # bf16: the elementwise grads (dx, dres) share the reference's
        # quantized forward, so they compare against the bf16 reference;
        # the CHANNEL grads (dgamma, dbeta) compare against the all-f32
        # truth, because the fused kernel accumulates them in f32 while
        # the reference's autodiff reduces bf16 products — the fused path
        # is measurably the closer of the two (PERF.md r07 notes).
        def truth(x, g, b, r):
            return _ref_bn(x.astype(jnp.float32), g, b,
                           residual=r.astype(jnp.float32), relu=True)
        gt = jax.grad(loss(truth), (0, 1, 2, 3))(
            x.astype(jnp.float32), gamma, beta, res.astype(jnp.float32))
        for i in (0, 3):  # dx, dres vs bf16 reference
            np.testing.assert_allclose(
                np.asarray(gf[i].astype(jnp.float32)),
                np.asarray(gr[i].astype(jnp.float32)),
                rtol=3e-2, atol=3e-2, err_msg=f"grad {i}")
        for i in (1, 2):  # dgamma, dbeta vs f32 truth
            np.testing.assert_allclose(
                np.asarray(gf[i]), np.asarray(gt[i]),
                rtol=5e-2, atol=0.3, err_msg=f"grad {i}")
            # and strictly no worse than the reference's own error
            assert (np.abs(np.asarray(gf[i]) - np.asarray(gt[i])).max()
                    <= np.abs(np.asarray(gr[i])
                              - np.asarray(gt[i])).max() + 1e-3)

    def test_interpret_kernel_matches_xla_fallback(self):
        """The interpret-mode kernels and the pure-XLA fallback implement
        the same arithmetic: bn_apply compares bitwise in fp32 (identical
        op order per element) and channel_stats to summation-order
        tolerance."""
        rng = np.random.RandomState(4)
        x = jnp.asarray(rng.randn(4, 4, 8, 128).astype("float32"))
        gamma = jnp.asarray(rng.rand(128).astype("float32") + 0.5)
        beta = jnp.asarray(rng.randn(128).astype("float32"))
        wv = gamma * 1.3
        bv = beta - 0.2
        kern = CB.scale_shift_act(x, wv, bv, relu=True, interpret=True)
        # C=100 fails the lane plan -> the same entry point's XLA fallback
        x100 = x[..., :100]
        fall = CB.scale_shift_act(x100, wv[:100], bv[:100], relu=True)
        ref = jnp.maximum(x * wv.astype(x.dtype) + bv.astype(x.dtype), 0)
        assert np.array_equal(np.asarray(fall), np.asarray(ref[..., :100]))
        np.testing.assert_allclose(np.asarray(kern), np.asarray(ref),
                                   rtol=1e-6, atol=1e-6)
        s1k, _ = CB.channel_stats(x, interpret=True)
        s1f, _ = CB.channel_stats(x100)
        np.testing.assert_allclose(
            np.asarray(s1k[:100]) - np.asarray(s1f),
            np.zeros(100), atol=2e-3)

    def test_conv_bn_stats_general_path_and_strided_1x1(self):
        rng = np.random.RandomState(5)
        x = jnp.asarray(rng.randn(2, 8, 8, 64).astype("float32"))
        w3 = jnp.asarray((rng.randn(64, 64, 3, 3) * 0.1).astype("float32"))
        y, s1, s2 = jax.jit(
            lambda x, w: CB.conv_bn_stats(x, w, (1, 1), (1, 1)))(x, w3)
        y0 = jax.lax.conv_general_dilated(
            x, w3, (1, 1), [(1, 1), (1, 1)],
            dimension_numbers=("NHWC", "OIHW", "NHWC"))
        np.testing.assert_allclose(np.asarray(y), np.asarray(y0),
                                   rtol=1e-5, atol=1e-4)
        np.testing.assert_allclose(
            np.asarray(s1),
            np.asarray(y0.astype(jnp.float32).sum((0, 1, 2))),
            rtol=1e-4, atol=1e-2)
        # strided 1x1 rides the dot path on pre-sliced rows
        w1 = jnp.asarray((rng.randn(128, 64, 1, 1) * 0.1).astype("float32"))
        y, s1, _ = jax.jit(
            lambda x, w: CB.conv_bn_stats(x, w, (2, 2), (0, 0)))(x, w1)
        y0 = jax.lax.conv_general_dilated(
            x, w1, (2, 2), [(0, 0), (0, 0)],
            dimension_numbers=("NHWC", "OIHW", "NHWC"))
        np.testing.assert_allclose(np.asarray(y), np.asarray(y0),
                                   rtol=1e-4, atol=1e-3)


def _bn_program(flag, is_test=False, use_global=False):
    """A single batch_norm op over NHWC input, built under FLAGS_fused_bn
    = flag."""
    with _fused_bn(flag):
        prog, startup = pt.Program(), pt.Program()
        with fw.guard_unique_name():
            with pt.program_guard(prog, startup):
                x = layers.data(name="x", shape=[6, 6, 32], dtype="float32")
                y = layers.batch_norm(x, is_test=is_test,
                                      data_layout="NHWC",
                                      use_global_stats=use_global)
                loss = layers.mean(y * y)
    return prog, startup, y, loss


class TestBatchNormFusedRoute:
    """lower_batch_norm's FLAGS_fused_bn route (standalone NHWC BN)."""

    def _run(self, flag, is_test=False, steps=1, seed=0):
        prog, startup, y, loss = _bn_program(flag, is_test=is_test)
        exe = pt.Executor(pt.CPUPlace())
        scope = pt.Scope()
        exe.run(startup, scope=scope)
        rng = np.random.RandomState(7)
        scope.set_var("batch_norm_0.w_0",
                      rng.rand(32).astype("float32") + 0.5)
        scope.set_var("batch_norm_0.b_0", rng.randn(32).astype("float32"))
        scope.set_var("batch_norm_0.mean_0",
                      rng.randn(32).astype("float32") * 0.1)
        scope.set_var("batch_norm_0.var_0",
                      rng.rand(32).astype("float32") + 0.5)
        feed_rng = np.random.RandomState(seed)
        with _fused_bn(flag):
            for _ in range(steps):
                (yv,) = exe.run(
                    prog, feed={"x": feed_rng.rand(4, 6, 6, 32)
                                .astype("float32")},
                    fetch_list=[y], scope=scope)
        stats = {n: np.asarray(scope.find_var(n))
                 for n in ("batch_norm_0.mean_0", "batch_norm_0.var_0")}
        return np.asarray(yv), stats

    def test_train_mode_parity_and_running_stats(self):
        yf, sf = self._run(True, steps=3)
        yr, sr = self._run(False, steps=3)
        np.testing.assert_allclose(yf, yr, rtol=1e-5, atol=1e-5)
        for k in sf:
            np.testing.assert_allclose(sf[k], sr[k], rtol=1e-5, atol=1e-6,
                                       err_msg=k)

    def test_is_test_mode_parity(self):
        yf, sf = self._run(True, is_test=True)
        yr, sr = self._run(False, is_test=True)
        # inference lowers through the same reference path either way
        np.testing.assert_allclose(yf, yr, rtol=0, atol=0)
        for k in sf:  # global stats untouched
            np.testing.assert_allclose(sf[k], sr[k], rtol=0, atol=0)

    def test_train_grads_parity(self):
        """Backward through the executor: d(loss)/d(scale, bias) and the
        updated params after one SGD step match the reference route."""
        def run(flag):
            with _fused_bn(flag):
                prog, startup = pt.Program(), pt.Program()
                with fw.guard_unique_name():
                    with pt.program_guard(prog, startup):
                        x = layers.data(name="x", shape=[6, 6, 32],
                                        dtype="float32")
                        y = layers.batch_norm(x, data_layout="NHWC")
                        loss = layers.mean(y * y * 0.1)
                        pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
                exe = pt.Executor(pt.CPUPlace())
                scope = pt.Scope()
                exe.run(startup, scope=scope)
                rng = np.random.RandomState(7)
                scope.set_var("batch_norm_0.w_0",
                              rng.rand(32).astype("float32") + 0.5)
                scope.set_var("batch_norm_0.b_0",
                              rng.randn(32).astype("float32"))
                feed = {"x": np.random.RandomState(1).rand(4, 6, 6, 32)
                        .astype("float32")}
                (lv,) = exe.run(prog, feed=feed, fetch_list=[loss],
                                scope=scope)
                return (float(np.asarray(lv)),
                        np.asarray(scope.find_var("batch_norm_0.w_0")),
                        np.asarray(scope.find_var("batch_norm_0.b_0")))

        lf, wf, bf = run(True)
        lr_, wr, br = run(False)
        assert abs(lf - lr_) < 1e-6
        np.testing.assert_allclose(wf, wr, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(bf, br, rtol=1e-5, atol=1e-6)


def _mini_feed(scan=1, batch=2, seed=0):
    rng = np.random.RandomState(seed)
    return {
        "image": rng.rand(scan, batch, 3, 8, 8).astype("float32"),
        "label": rng.randint(0, 4, (scan, batch, 1)).astype("int64"),
    }


def _build_mini(fmt, flag, is_train=True, lr=0.1):
    """Tiny NHWC-capable tower exercising every fused site kind: general
    conv (from 3 channels), basicblock 3x3s, the fused residual+relu
    site, and a strided 1x1 shortcut."""
    with _fused_bn(flag):
        prog, startup = pt.Program(), pt.Program()
        with fw.guard_unique_name():
            with pt.program_guard(prog, startup):
                img = layers.data(name="image", shape=[3, 8, 8],
                                  dtype="float32")
                label = layers.data(name="label", shape=[1], dtype="int64")
                x = (layers.transpose(img, [0, 2, 3, 1])
                     if fmt == "NHWC" else img)
                c1 = R.conv_bn_layer(x, 16, 3, 1, 1, is_train=is_train,
                                     data_format=fmt)
                b1 = R.basicblock(c1, 16, 1, is_train=is_train,
                                  data_format=fmt)
                b2 = R.basicblock(b1, 32, 2, is_train=is_train,
                                  data_format=fmt)
                pool = layers.pool2d(b2, pool_type="avg",
                                     global_pooling=True, data_format=fmt)
                out = layers.fc(pool, size=4, act="softmax")
                loss = layers.mean(layers.cross_entropy(out, label))
                if lr:
                    pt.optimizer.Momentum(learning_rate=lr,
                                          momentum=0.9).minimize(loss)
    return prog, startup, loss


def _init_and_sync(exe, progs_scopes):
    """Run startups; copy the FIRST scope's params into the rest by name
    (works because fused/unfused builders create identical param names)."""
    saved = None
    for prog, startup, scope in progs_scopes:
        exe.run(startup, scope=scope)
        params = sorted(p.name for p in prog.all_parameters())
        if saved is None:
            saved = {n: np.asarray(scope.find_var(n)) for n in params}
        else:
            assert sorted(saved) == params, (sorted(saved), params)
            for n, v in saved.items():
                scope.set_var(n, v)


class TestConvBnOpProgram:
    def test_fused_vs_reference_one_train_step(self):
        """One optimizer step of the mini tower: loss, every running-stat
        var, and every updated parameter match the reference composition
        (this is the op-level parity + gradcheck + running-stats contract
        in one shot — same graph-building code, flag flipped)."""
        exe = pt.Executor(pt.CPUPlace())
        results = {}
        for flag in (True, False):
            prog, startup, loss = _build_mini("NHWC", flag)
            scope = pt.Scope()
            _init_and_sync(exe, [(prog, startup, scope)])
            r2 = np.random.RandomState(7)
            for p in prog.all_parameters():
                v = np.asarray(scope.find_var(p.name))
                scope.set_var(p.name,
                              (r2.randn(*v.shape) * 0.1).astype(v.dtype))
            with _fused_bn(flag):
                (lv,) = exe.run_steps(prog, feed=_mini_feed(),
                                      fetch_list=[loss], scope=scope)
            state = {}
            for name in (v.name for v in
                         prog.global_block().vars.values()):
                if ".mean" in name or ".var" in name:
                    state[name] = np.asarray(scope.find_var(name))
            for p in prog.all_parameters():
                state[p.name] = np.asarray(scope.find_var(p.name))
            ops = [op.type for op in prog.global_block().ops]
            results[flag] = (float(np.asarray(lv).reshape(-1)[-1]), state,
                             ops)
        lf, sf, ops_on = results[True]
        lr_, sr, ops_off = results[False]
        assert "conv2d_bn" in ops_on and "conv2d_bn" not in ops_off
        assert abs(lf - lr_) < 1e-5, (lf, lr_)
        assert sf.keys() == sr.keys()
        for k in sf:
            np.testing.assert_allclose(sf[k], sr[k], rtol=5e-4, atol=1e-5,
                                       err_msg=k)

    def test_is_test_mode_uses_global_stats(self):
        """The fused op in is_test mode: the TRAINING-built graphs (fused
        conv2d_bn ops vs the reference composition) run under
        program._is_test — global running stats drive the normalization,
        are NOT updated, and the two routes agree exactly."""
        exe = pt.Executor(pt.CPUPlace())
        outs = {}
        for flag in (True, False):
            prog, startup, loss = _build_mini("NHWC", flag, lr=None)
            if flag:
                assert "conv2d_bn" in [op.type for op
                                       in prog.global_block().ops]
            prog._is_test = True
            scope = pt.Scope()
            _init_and_sync(exe, [(prog, startup, scope)])
            r2 = np.random.RandomState(7)
            for p in prog.all_parameters():
                v = np.asarray(scope.find_var(p.name))
                scope.set_var(p.name,
                              (r2.randn(*v.shape) * 0.1).astype(v.dtype))
            # non-trivial running stats so the global-stat path is visible
            rng = np.random.RandomState(3)
            for name in (v.name for v in
                         prog.global_block().vars.values()):
                if ".mean" in name:
                    v = np.asarray(scope.find_var(name))
                    scope.set_var(name,
                                  rng.randn(*v.shape).astype("float32")
                                  * 0.1)
                elif ".var" in name:
                    v = np.asarray(scope.find_var(name))
                    scope.set_var(name,
                                  rng.rand(*v.shape).astype("float32")
                                  + 0.5)
            with _fused_bn(flag):
                (lv,) = exe.run_steps(prog, feed=_mini_feed(),
                                      fetch_list=[loss], scope=scope)
            outs[flag] = float(np.asarray(lv).reshape(-1)[-1])
        assert abs(outs[True] - outs[False]) < 1e-6, outs

    def test_param_names_identical_across_flag(self):
        """Checkpoint interop: the fused build creates the exact param and
        moving-stat names of the unfused conv2d+batch_norm pair."""
        names = {}
        for flag in (True, False):
            prog, _, _ = _build_mini("NHWC", flag)
            names[flag] = sorted(p.name for p in prog.all_parameters())
        assert names[True] == names[False]
        assert any(".w_" in n and n.startswith("conv2d")
                   for n in names[True])

    def test_bf16_amp_step_finite_and_stats_fp32(self):
        """Under pt.amp the conv operands run bf16 (slot-wise WHITE) while
        the running stats stay fp32 and finite."""
        exe = pt.Executor(pt.CPUPlace())
        prog, startup, loss = _build_mini("NHWC", True)
        pt.amp.enable(prog)
        scope = pt.Scope()
        _init_and_sync(exe, [(prog, startup, scope)])
        with _fused_bn(True):
            (lv,) = exe.run_steps(prog, feed=_mini_feed(),
                                  fetch_list=[loss], scope=scope)
        assert np.isfinite(np.asarray(lv)).all()
        for name in (v.name for v in prog.global_block().vars.values()):
            if ".mean" in name or ".var" in name:
                v = np.asarray(scope.find_var(name))
                assert v.dtype == np.float32, (name, v.dtype)
                assert np.isfinite(v).all(), name


# -- zero-cost-off ----------------------------------------------------------


def _legacy_conv_bn_layer(input, ch_out, filter_size, stride, padding,
                          act="relu", is_train=True, data_format="NCHW"):
    """Verbatim pre-r07 conv_bn_layer (the 'today' this PR must preserve
    with the flag off)."""
    conv1 = layers.conv2d(
        input=input, filter_size=filter_size, num_filters=ch_out,
        stride=stride, padding=padding, act=None, bias_attr=False,
        data_format=data_format)
    return layers.batch_norm(input=conv1, act=act, is_test=not is_train,
                             data_layout=data_format)


def _legacy_basicblock(input, ch_out, stride, is_train, fmt):
    ch_in = input.shape[-1 if fmt == "NHWC" else 1]
    short = (input if ch_in == ch_out else _legacy_conv_bn_layer(
        input, ch_out, 1, stride, 0, None, is_train, fmt))
    conv1 = _legacy_conv_bn_layer(input, ch_out, 3, stride, 1,
                                  is_train=is_train, data_format=fmt)
    conv2 = _legacy_conv_bn_layer(conv1, ch_out, 3, 1, 1, act=None,
                                  is_train=is_train, data_format=fmt)
    return layers.elementwise_add(short, conv2, act="relu")


def _build_mini_legacy(fmt, lr=0.1):
    prog, startup = pt.Program(), pt.Program()
    with fw.guard_unique_name():
        with pt.program_guard(prog, startup):
            img = layers.data(name="image", shape=[3, 8, 8],
                              dtype="float32")
            label = layers.data(name="label", shape=[1], dtype="int64")
            x = (layers.transpose(img, [0, 2, 3, 1])
                 if fmt == "NHWC" else img)
            c1 = _legacy_conv_bn_layer(x, 16, 3, 1, 1, is_train=True,
                                       data_format=fmt)
            b1 = _legacy_basicblock(c1, 16, 1, True, fmt)
            b2 = _legacy_basicblock(b1, 32, 2, True, fmt)
            pool = layers.pool2d(b2, pool_type="avg", global_pooling=True,
                                 data_format=fmt)
            out = layers.fc(pool, size=4, act="softmax")
            loss = layers.mean(layers.cross_entropy(out, label))
            if lr:
                pt.optimizer.Momentum(learning_rate=lr,
                                      momentum=0.9).minimize(loss)
    return prog, startup, loss


def _lower_hlo(exe, prog, startup, loss, scope=None):
    """Compile one run_steps entry and return its optimized-HLO text
    (tools/hlo_diag.py lower_entry, test-sized)."""
    scope = scope or pt.Scope()
    exe.run(startup, scope=scope)
    feed = _mini_feed()
    exe.run_steps(prog, feed=feed, fetch_list=[loss], scope=scope)
    from paddle_tpu.core.executor import latest_jitted_entry

    entry = latest_jitted_entry(exe)
    rw = [scope.find_var(n) for n in entry.rw_state]
    ro = [scope.find_var(n) for n in entry.ro_state]
    feed_names = sorted(feed)
    feed_vals = [exe._to_device_array(prog, n, feed[n])
                 for n in feed_names]
    key = jax.random.PRNGKey(0)
    return entry.jitted.lower(feed_vals, rw, ro, key).compile().as_text()


class TestZeroCostOff:
    def test_flag_off_graph_identical_to_legacy(self):
        """FLAGS_fused_bn off => the model builder emits the exact op
        sequence of the pre-r07 code (no conv2d_bn anywhere)."""
        prog_off, _, _ = _build_mini("NHWC", False)
        prog_leg, _, _ = _build_mini_legacy("NHWC")
        ops_off = [op.type for op in prog_off.global_block().ops]
        ops_leg = [op.type for op in prog_leg.global_block().ops]
        assert ops_off == ops_leg
        assert "conv2d_bn" not in ops_off

    @pytest.mark.slow
    def test_flag_off_hlo_identical_to_legacy(self):
        """...and its compiled train step is HLO-identical (trace-time
        flag off too: the batch_norm lowering takes the reference path).
        Slow lane: the op-sequence identity above is the fast tripwire;
        this compiles both towers to cross-check the HLO text."""
        with _fused_bn(False):
            exe = pt.Executor(pt.CPUPlace())
            prog_off, startup_off, loss_off = _build_mini("NHWC", False)
            h_off = _lower_hlo(exe, prog_off, startup_off, loss_off)
            exe2 = pt.Executor(pt.CPUPlace())
            prog_leg, startup_leg, loss_leg = _build_mini_legacy("NHWC")
            h_leg = _lower_hlo(exe2, prog_leg, startup_leg, loss_leg)
        assert h_off == h_leg

    def test_nchw_unaffected_by_flag(self):
        """NCHW towers never take the fused route: identical graph with
        the flag on and off."""
        on, _, _ = _build_mini("NCHW", True)
        off, _, _ = _build_mini("NCHW", False)
        assert ([op.type for op in on.global_block().ops]
                == [op.type for op in off.global_block().ops])


class TestBnFusionReport:
    @pytest.mark.slow
    def test_fused_path_removes_channel_reduction_passes(self):
        """tools/hlo_diag.py --bn-fusion on the mini tower: the reference
        HLO is full of BN-stat channel reductions over 4-D activations
        (fwd mean/sqmean + bwd dgamma/dbeta per BN); the fused HLO has
        (nearly) none — the statistics ride the kernels."""
        hd = _hlo_diag()
        texts = {}
        for flag in (True, False):
            with _fused_bn(flag):
                exe = pt.Executor(pt.CPUPlace())
                prog, startup, loss = _build_mini("NHWC", flag)
                texts[flag] = _lower_hlo(exe, prog, startup, loss)
        rep_on = hd.analyze_bn_fusion(texts[True])
        rep_off = hd.analyze_bn_fusion(texts[False])
        # 7 BN sites x (>=2 fwd + >=2 bwd) channel reductions in reference
        assert rep_off["bn_stat_reduces"] >= 14, rep_off
        # the fused path's statistics ride the kernels: the batch_norm
        # lowering emits ZERO reduction passes (on a real chip even the
        # kernel-internal ones vanish into Mosaic custom calls — asserted
        # in TestConvBnTPU on the driver's chip)
        assert rep_on["bn_stat_reduces"] == 0, rep_on
        # the report renders (the mechanical-attribution artifact)
        assert "channel-stat reduction passes" in hd.format_bn_fusion(
            rep_off)


@pytest.mark.skipif(jax.default_backend() != "tpu",
                    reason="compiled Mosaic kernel paths need a TPU")
class TestConvBnTPU:
    """Arms on the driver's chip: the COMPILED kernels (not interpret
    mode) against the XLA fallback, plus the r07 acceptance asserts."""

    def test_kernel_parity_compiled(self):
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(8, 14, 14, 256).astype("float32")
                        ).astype(jnp.bfloat16)
        w = jnp.asarray((rng.randn(512, 256, 1, 1) * 0.06)
                        .astype("float32")).astype(jnp.bfloat16)
        gamma = jnp.asarray(rng.rand(512).astype("float32") + 0.5)
        beta = jnp.asarray(rng.randn(512).astype("float32"))

        def fused(x, w, gamma, beta):
            y, s1, s2 = CB.conv_bn_stats(x, w)
            m = y.size // y.shape[-1]
            mean = s1 / m
            var = s2 / m - jnp.square(mean)
            return CB.bn_apply(y, gamma, beta, mean, var, act="relu")

        def ref(x, w, gamma, beta):
            y = jax.lax.conv_general_dilated(
                x, w, (1, 1), [(0, 0), (0, 0)],
                dimension_numbers=("NHWC", "OIHW", "NHWC"))
            return _ref_bn(y, gamma, beta, relu=True)

        of = jax.jit(fused)(x, w, gamma, beta)
        orf = jax.jit(ref)(x, w, gamma, beta)
        np.testing.assert_allclose(
            np.asarray(of.astype(jnp.float32)),
            np.asarray(orf.astype(jnp.float32)), rtol=2e-2, atol=2e-2)

        gf = jax.jit(jax.grad(
            lambda *a: jnp.sum(fused(*a).astype(jnp.float32)) * 1e-3,
            (0, 1, 2, 3)))(x, w, gamma, beta)
        gr = jax.jit(jax.grad(
            lambda *a: jnp.sum(ref(*a).astype(jnp.float32)) * 1e-3,
            (0, 1, 2, 3)))(x, w, gamma, beta)
        for i, (a, b) in enumerate(zip(gf, gr)):
            np.testing.assert_allclose(
                np.asarray(a.astype(jnp.float32)),
                np.asarray(b.astype(jnp.float32)),
                rtol=5e-2, atol=5e-2, err_msg=f"grad {i}")

    def test_resnet_fused_step_runs_and_learns(self):
        exe = pt.Executor()
        prog, startup, loss = _build_mini("NHWC", True)
        pt.amp.enable(prog)
        scope = pt.Scope()
        exe.run(startup, scope=scope)
        with _fused_bn(True):
            losses = []
            for i in range(4):
                (lv,) = exe.run_steps(prog, feed=_mini_feed(seed=0),
                                      fetch_list=[loss], scope=scope)
                losses.append(float(np.asarray(lv).reshape(-1)[-1]))
        assert all(np.isfinite(losses)), losses
        assert losses[-1] < losses[0], losses

    def test_fused_hlo_removes_channel_reductions_on_chip(self):
        """The r07 acceptance attribution, compiled for the real chip:
        the fused path removes the BN channel-reduction passes from the
        optimized HLO outright (the kernel statistics live inside the
        Mosaic custom calls, which emit no HLO reduce)."""
        hd = _hlo_diag()
        reps = {}
        for flag in (True, False):
            with _fused_bn(flag):
                exe = pt.Executor()
                prog, startup, loss = _build_mini("NHWC", flag)
                reps[flag] = hd.analyze_bn_fusion(
                    _lower_hlo(exe, prog, startup, loss))
        assert reps[False]["bn_stat_reduces"] >= 14, reps[False]
        assert reps[True]["bn_stat_reduces"] == 0, reps[True]
        assert (reps[True]["channel_reduces"]
                < reps[False]["channel_reduces"]), reps
