"""yolov3_loss, generate_proposals, rpn_target_assign,
polygon_box_transform, roi_perspective_transform, psroi_pool
(reference yolov3_loss_op.h, detection/generate_proposals_op.cc,
rpn_target_assign_op.cc, polygon_box_transform_op.cc,
roi_perspective_transform_op.cc, psroi_pool_op.h)."""

import numpy as np

import paddle_tpu as pt
from paddle_tpu import layers

rng = np.random.RandomState(4)


def test_polygon_box_transform():
    x = rng.randn(2, 4, 3, 5).astype("float32")
    xv = layers.data(name="x", shape=[4, 3, 5], dtype="float32")
    out = layers.polygon_box_transform(xv)
    exe = pt.Executor(pt.CPUPlace())
    (o,) = exe.run(feed={"x": x}, fetch_list=[out])
    o = np.asarray(o)
    expect = np.empty_like(x)
    for c in range(4):
        for h in range(3):
            for w in range(5):
                base = w * 4 if c % 2 == 0 else h * 4
                expect[:, c, h, w] = base - x[:, c, h, w]
    np.testing.assert_allclose(o, expect, rtol=1e-6)


def test_yolov3_loss_decreases_and_grad_flows():
    N, A, C, H = 4, 2, 3, 8
    anchors = [8, 8, 16, 16]
    rs = np.random.RandomState(0)

    def make_batch():
        gtb = np.zeros((N, 2, 4), "float32")
        gtl = np.zeros((N, 2), "int32")
        for i in range(N):
            gtb[i, 0] = [rs.uniform(0.2, 0.8), rs.uniform(0.2, 0.8),
                         rs.uniform(0.2, 0.4), rs.uniform(0.2, 0.4)]
            gtl[i, 0] = rs.randint(0, C)
        return gtb, gtl

    img = layers.data(name="img", shape=[4, H, H], dtype="float32")
    gtb = layers.data(name="gtb", shape=[2, 4], dtype="float32")
    gtl = layers.data(name="gtl", shape=[2], dtype="int32")
    feat = layers.conv2d(img, num_filters=A * (5 + C), filter_size=3,
                         padding=1)
    loss = layers.yolov3_loss(feat, gtb, gtl, anchors=anchors, class_num=C,
                              ignore_thresh=0.5)
    pt.optimizer.AdamOptimizer(learning_rate=5e-3).minimize(loss)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    losses = []
    fixed_img = rs.randn(N, 4, H, H).astype("float32")
    gtb_v, gtl_v = make_batch()
    for _ in range(80):
        (lv,) = exe.run(feed={"img": fixed_img, "gtb": gtb_v, "gtl": gtl_v},
                        fetch_list=[loss])
        losses.append(float(np.asarray(lv)))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_rpn_target_assign_dense():
    # anchors laid out so exactly one overlaps each gt strongly
    anchors = np.array([[0, 0, 10, 10], [20, 20, 30, 30],
                        [50, 50, 60, 60], [5, 40, 12, 47]], "float32")
    gt = np.zeros((1, 2, 4), "float32")
    gt[0, 0] = [0, 0, 10, 10]       # matches anchor 0
    gt[0, 1] = [21, 21, 29, 29]     # matches anchor 1
    av = layers.data(name="a", shape=[4], dtype="float32",
                     append_batch_size=False)
    av.shape = (4, 4)
    gv = layers.data(name="g", shape=[2, 4], dtype="float32")
    lbl, tbox, inw = layers.rpn_target_assign(
        av, gv, rpn_positive_overlap=0.5, rpn_negative_overlap=0.3)
    exe = pt.Executor(pt.CPUPlace())
    l, t, w = exe.run(feed={"a": anchors, "g": gt},
                      fetch_list=[lbl, tbox, inw])
    l, t, w = np.asarray(l), np.asarray(t), np.asarray(w)
    assert l[0, 0] == 1 and l[0, 1] == 1      # fg
    assert l[0, 2] == 0 and l[0, 3] == 0      # bg (no overlap)
    np.testing.assert_allclose(t[0, 0], 0.0, atol=1e-5)  # exact match
    assert w[0, 0, 0] == 1.0 and w[0, 2, 0] == 0.0


def test_generate_proposals_shapes_and_sanity():
    N, A, H, W = 2, 3, 4, 4
    scores = rng.rand(N, A, H, W).astype("float32")
    deltas = (rng.randn(N, A * 4, H, W) * 0.1).astype("float32")
    im_info = np.array([[32, 32, 1.0], [32, 32, 1.0]], "float32")
    sv = layers.data(name="s", shape=[A, H, W], dtype="float32")
    dv = layers.data(name="d", shape=[A * 4, H, W], dtype="float32")
    iv = layers.data(name="i", shape=[3], dtype="float32")
    anc, var = layers.anchor_generator(sv, anchor_sizes=[8.0],
                                       aspect_ratios=[1.0, 2.0, 0.5],
                                       stride=[8.0, 8.0])
    rois, probs, num = layers.generate_proposals(
        sv, dv, iv, anc, var, pre_nms_top_n=20, post_nms_top_n=10,
        nms_thresh=0.7, min_size=1.0)
    exe = pt.Executor(pt.CPUPlace())
    r, p, c = exe.run(feed={"s": scores, "d": deltas, "i": im_info},
                      fetch_list=[rois, probs, num])
    r, p, c = np.asarray(r), np.asarray(p), np.asarray(c)
    assert r.shape == (N, 10, 4) and p.shape == (N, 10, 1)
    assert (c >= 1).all() and (c <= 10).all()
    for n in range(N):
        k = int(c[n])
        assert (r[n, :k, 0] >= 0).all() and (r[n, :k, 2] <= 31).all()
        assert (r[n, :k, 2] >= r[n, :k, 0]).all()
        # probs sorted descending over the valid prefix
        assert (np.diff(p[n, :k, 0]) <= 1e-6).all()


def test_roi_perspective_transform_identity_quad():
    # a rect quad aligned with the axes behaves like a crop+resize
    N, C, H, W = 1, 1, 8, 8
    x = np.arange(H * W, dtype="float32").reshape(N, C, H, W)
    # quad corners (tl, tr, br, bl) of the rect [2,2]-[5,5]
    rois = np.array([[2, 2, 5, 2, 5, 5, 2, 5]], "float32")
    xv = layers.data(name="x", shape=[C, H, W], dtype="float32")
    rv = layers.data(name="r", shape=[8], dtype="float32",
                     append_batch_size=False)
    rv.shape = (1, 8)
    out = layers.roi_perspective_transform(xv, rv, transformed_height=4,
                                           transformed_width=4)
    exe = pt.Executor(pt.CPUPlace())
    (o,) = exe.run(feed={"x": x, "r": rois}, fetch_list=[out])
    o = np.asarray(o)
    assert o.shape == (1, C, 4, 4)
    # corners must hit the quad corners exactly
    np.testing.assert_allclose(o[0, 0, 0, 0], x[0, 0, 2, 2])
    np.testing.assert_allclose(o[0, 0, 3, 3], x[0, 0, 5, 5])


def test_psroi_pool_position_sensitive():
    N, O, ph, pw, H, W = 1, 2, 2, 2, 8, 8
    C = O * ph * pw
    # each channel holds its own constant -> output bin (i,j) of out-chan d
    # must equal the constant of channel (d*ph+i)*pw+j... i.e. chan d, bin
    # index i*pw+j within group d
    x = np.zeros((N, C, H, W), "float32")
    for c in range(C):
        x[0, c] = c
    rois = np.array([[0, 0, 7, 7]], "float32")
    xv = layers.data(name="x", shape=[C, H, W], dtype="float32")
    rv = layers.data(name="r", shape=[4], dtype="float32",
                     append_batch_size=False)
    rv.shape = (1, 4)
    out = layers.psroi_pool(xv, rv, output_channels=O, spatial_scale=1.0,
                            pooled_height=ph, pooled_width=pw)
    exe = pt.Executor(pt.CPUPlace())
    (o,) = exe.run(feed={"x": x, "r": rois}, fetch_list=[out])
    o = np.asarray(o)
    assert o.shape == (1, O, ph, pw)
    for d in range(O):
        for i in range(ph):
            for j in range(pw):
                expect = d * ph * pw + (i * pw + j)
                np.testing.assert_allclose(o[0, d, i, j], expect, atol=1e-5)


def test_rpn_target_assign_straddle_exclusion():
    """Anchors outside the image get label -1 when im_info is given."""
    anchors = np.array([[0, 0, 10, 10], [28, 28, 40, 40]], "float32")
    gt = np.zeros((1, 1, 4), "float32")
    gt[0, 0] = [0, 0, 10, 10]
    av = layers.data(name="a2", shape=[4], dtype="float32",
                     append_batch_size=False)
    av.shape = (2, 4)
    gv = layers.data(name="g2", shape=[1, 4], dtype="float32")
    iv = layers.data(name="i2", shape=[3], dtype="float32")
    lbl, _, _ = layers.rpn_target_assign(
        av, gv, im_info=iv, rpn_positive_overlap=0.5,
        rpn_negative_overlap=0.3, rpn_straddle_thresh=0.0)
    exe = pt.Executor(pt.CPUPlace())
    im_info = np.array([[32, 32, 1.0]], "float32")
    (l,) = exe.run(feed={"a2": anchors, "g2": gt, "i2": im_info},
                   fetch_list=[lbl])
    l = np.asarray(l)
    assert l[0, 0] == 1          # in-image matching anchor
    assert l[0, 1] == -1         # straddles the boundary -> excluded


def test_generate_proposal_labels_numerics():
    """Hand-checkable case (reference generate_proposal_labels_op.cc
    SampleRoisForOneImage): 2 gts + 3 proposals, fg_thresh 0.5."""
    gt_boxes = np.array([[[0.1, 0.1, 0.4, 0.4],
                          [0.6, 0.6, 0.9, 0.9]]], "float32")
    gt_classes = np.array([[1, 2]], "int32")
    is_crowd = np.array([[0, 0]], "int32")
    # proposal 0 ~ gt0 (high IoU), proposal 1 ~ gt1, proposal 2 ~ nothing
    rois_np = np.array([[[0.1, 0.1, 0.42, 0.42],
                         [0.58, 0.6, 0.9, 0.88],
                         [0.05, 0.7, 0.25, 0.95]]], "float32")
    im_info = np.array([[1.0, 1.0, 1.0]], "float32")
    C, B = 3, 6

    rpn = layers.data(name="rpn", shape=[3, 4], dtype="float32")
    gtc = layers.data(name="gtc", shape=[2], dtype="int32")
    crw = layers.data(name="crw", shape=[2], dtype="int32")
    gtb = layers.data(name="gtb", shape=[2, 4], dtype="float32")
    info = layers.data(name="info", shape=[3], dtype="float32")
    rois, labels, tgts, inw, outw, valid = layers.generate_proposal_labels(
        rpn, gtc, crw, gtb, info, batch_size_per_im=B, fg_fraction=0.5,
        fg_thresh=0.5, bg_thresh_hi=0.5, bg_thresh_lo=0.0, class_nums=C)
    exe = pt.Executor(pt.CPUPlace())
    r, l, t, iw, ow, v = [np.asarray(x) for x in exe.run(
        feed={"rpn": rois_np, "gtc": gt_classes, "crw": is_crowd,
              "gtb": gt_boxes, "info": im_info},
        fetch_list=[rois, labels, tgts, inw, outw, valid])]
    assert r.shape == (1, B, 4) and l.shape == (1, B, 1)
    assert t.shape == (1, B, 4 * C)
    lbl = l[0, :, 0]
    # fg rows first: the 2 gt self-matches (IoU 1.0) rank above the two
    # high-IoU proposals; quota = 3 fg — labels 1/2 appear, bg rows 0
    fg_labels = lbl[lbl > 0]
    assert set(fg_labels.tolist()) <= {1, 2} and len(fg_labels) >= 2
    assert (lbl[(lbl == 0)].size + fg_labels.size
            == int(v.sum())), "valid rows = fg + bg"
    # fg rows have exactly one 4-col group of inside weights, at the label
    for i in range(B):
        row_w = iw[0, i].reshape(C, 4)
        if lbl[i] > 0:
            assert row_w[lbl[i]].sum() == 4.0 and row_w.sum() == 4.0
            # the matched gt's encoded target is finite and nonzero cols
            assert np.isfinite(t[0, i]).all()
        else:
            assert row_w.sum() == 0.0
    # invalid rows labeled -1 with zero weight
    assert ((lbl == -1) == (v[0, :, 0] == 0.0)).all()


def test_faster_rcnn_two_stage_trains():
    """Toy end-to-end Faster-RCNN: RPN (rpn_target_assign losses) +
    generate_proposals -> generate_proposal_labels -> roi_align -> cls/reg
    heads; joint loss decreases (VERDICT r4 item 4; mirrors
    tests/test_ssd.py's trainable-SSD contract)."""
    N, H, W, A, C = 2, 8, 8, 3, 3
    rs = np.random.RandomState(0)

    # fixed synthetic scene: one gt per image, well inside
    gt_boxes_np = np.zeros((N, 2, 4), "float32")
    gt_classes_np = np.zeros((N, 2), "int32")
    for i in range(N):
        x1, y1 = rs.uniform(4, 16, 2)
        gt_boxes_np[i, 0] = [x1, y1, x1 + rs.uniform(8, 12),
                             y1 + rs.uniform(8, 12)]
        gt_classes_np[i, 0] = rs.randint(1, C)
    is_crowd_np = np.zeros((N, 2), "int32")
    im_info_np = np.tile(np.array([[32.0, 32.0, 1.0]], "float32"), (N, 1))
    imgs_np = rs.randn(N, 3, 32, 32).astype("float32") * 0.1

    img = layers.data(name="img", shape=[3, 32, 32], dtype="float32")
    gtb = layers.data(name="gtb", shape=[2, 4], dtype="float32")
    gtc = layers.data(name="gtc", shape=[2], dtype="int32")
    crw = layers.data(name="crw", shape=[2], dtype="int32")
    info = layers.data(name="info", shape=[3], dtype="float32")
    bidx = layers.data(name="bidx", shape=[1], dtype="int32")

    feat = layers.conv2d(img, num_filters=16, filter_size=3, stride=4,
                         padding=1, act="relu")              # [N,16,8,8]
    # RPN head: A = len(anchor_sizes) * len(aspect_ratios) = 2 per cell
    A2 = 2
    rpn_cls = layers.conv2d(feat, num_filters=A2, filter_size=1)
    rpn_reg = layers.conv2d(feat, num_filters=4 * A2, filter_size=1)
    anchors, avar = layers.anchor_generator(
        feat, anchor_sizes=[8.0, 16.0], aspect_ratios=[1.0],
        stride=[4.0, 4.0])
    anchors = layers.reshape(anchors, [-1, 4])
    navn = H * W * A2

    # RPN losses against assigned anchors
    tl, tb, iw_rpn = layers.rpn_target_assign(
        anchors, gtb, im_info=info, is_crowd=crw,
        rpn_batch_size_per_im=64)
    scores2 = layers.reshape(layers.transpose(rpn_cls, [0, 2, 3, 1]),
                             [N, navn])
    probs = layers.sigmoid(scores2)
    lbl_f = layers.cast(tl, "float32")
    mask = layers.cast(
        layers.greater_equal(lbl_f, layers.fill_constant([1], "float32",
                                                         0.0)), "float32")
    bce = layers.elementwise_sub(
        layers.elementwise_mul(probs, probs),  # placeholder smooth term
        layers.elementwise_mul(lbl_f, probs))
    rpn_loss = layers.reduce_sum(layers.elementwise_mul(bce, mask))

    # proposals (no grad) -> second stage
    rois, _, _ = layers.generate_proposals(
        rpn_cls, rpn_reg, info, anchors,
        post_nms_top_n=8, nms_thresh=0.7, min_size=0.0)
    s_rois, s_lbl, s_tgt, s_inw, _, s_valid = (
        layers.generate_proposal_labels(
            rois, gtc, crw, gtb, info, batch_size_per_im=16,
            fg_fraction=0.5, fg_thresh=0.3, bg_thresh_hi=0.3,
            bg_thresh_lo=0.0, class_nums=C))
    roi_feats = layers.roi_align(
        feat, layers.reshape(s_rois, [-1, 4]), pooled_height=2,
        pooled_width=2, spatial_scale=0.25,
        batch_idx=layers.reshape(bidx, [-1]))
    flat = layers.reshape(roi_feats, [N * 16, 16 * 2 * 2])
    cls_logits = layers.fc(input=flat, size=C)
    reg_out = layers.fc(input=flat, size=4 * C)

    lbl_flat = layers.reshape(s_lbl, [N * 16, 1])
    lbl_safe = layers.cast(
        layers.elementwise_max(
            layers.cast(lbl_flat, "float32"),
            layers.fill_constant([1], "float32", 0.0)), "int64")
    ce = layers.softmax_with_cross_entropy(logits=cls_logits,
                                           label=lbl_safe)
    vmask = layers.reshape(s_valid, [N * 16, 1])
    cls_loss = layers.reduce_sum(layers.elementwise_mul(ce, vmask))
    reg_diff = layers.elementwise_sub(
        reg_out, layers.reshape(s_tgt, [N * 16, 4 * C]))
    reg_loss = layers.reduce_sum(
        layers.elementwise_mul(
            layers.elementwise_mul(reg_diff, reg_diff),
            layers.reshape(s_inw, [N * 16, 4 * C])))
    loss = rpn_loss + 0.5 * cls_loss + 0.1 * reg_loss
    pt.optimizer.Adam(learning_rate=2e-3).minimize(loss)

    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())
    bidx_np = np.repeat(np.arange(N), 16).astype("int32").reshape(N, 16, 1)
    feed = {"img": imgs_np, "gtb": gt_boxes_np, "gtc": gt_classes_np,
            "crw": is_crowd_np, "info": im_info_np, "bidx": bidx_np}
    losses = []
    for _ in range(60):
        (l,) = exe.run(feed=feed, fetch_list=[loss])
        losses.append(float(np.asarray(l)))
    assert np.isfinite(losses).all(), losses
    assert losses[-1] < losses[0] * 0.6, (losses[0], losses[-1])
