"""ServingModel: one exported model directory made servable.

Wraps a Predictor (inference.py) with everything the dynamic batcher
needs to keep every executed batch on a WARM entry of the executor's
per-feed-signature compile cache:

  * a pad-to-bucket batch-size ladder (requests coalesce and pad up to
    the smallest bucket >= total rows, so an unbounded stream of request
    shapes maps onto a BOUNDED set of compiled signatures);
  * warmup: pre-compile (or AOT-load) every bucket signature at startup,
    so no production request ever pays a compile;
  * optional int8 replica via the existing contrib.quantize.freeze_int8
    path (QAT-exported models only), selectable per request;
  * a serving-tier recompile-cause tag: any compile that happens while
    serving a batch is flight-recorded with the REQUESTED vs BUCKETED
    feed signature, so an undersized bucket ladder is diagnosable from
    /flight instead of showing up as silent retrace stalls.

Reference role: the multi-model half of the reference's C++ serving
story (api/paddle_api.h:153 — one PaddlePredictor per model, load once /
serve many); the bucket ladder is the adaptive-batching idea of
Clipper (NSDI'17) mapped onto XLA's compile-per-signature reality.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..inference import Predictor


def parse_buckets(spec) -> Tuple[int, ...]:
    """"1,2,4,8" / [1, 2, 4, 8] -> sorted, deduped, validated tuple."""
    if isinstance(spec, str):
        parts = [p.strip() for p in spec.split(",") if p.strip()]
        vals = [int(p) for p in parts]
    else:
        vals = [int(v) for v in spec]
    if not vals or any(v <= 0 for v in vals):
        raise ValueError(f"bucket ladder must be positive ints, got {spec!r}")
    return tuple(sorted(set(vals)))


class ModelConfig:
    """Per-model serving policy (CLI flags / server API both build this)."""

    __slots__ = ("name", "dirname", "use_aot", "optimize", "int8",
                 "buckets", "max_batch", "max_wait_ms", "warmup_shapes")

    def __init__(self, name: str, dirname: str, use_aot: bool = False,
                 optimize: bool = True, int8: bool = False,
                 buckets=None, max_batch: Optional[int] = None,
                 max_wait_ms: Optional[float] = None,
                 warmup_shapes: Optional[Dict[str, tuple]] = None):
        from ..flags import FLAGS

        if not name or "/" in name or ":" in name:
            raise ValueError(f"model name {name!r} must be URL-path safe")
        self.name = name
        self.dirname = dirname
        # AOT bundles deserialize via jax's pickle-based executable
        # loader: opt-in per model, trusted artifacts only (the PR-1
        # posture — same default as Predictor)
        self.use_aot = use_aot
        self.optimize = optimize
        self.int8 = int8
        self.buckets = parse_buckets(
            buckets if buckets is not None else FLAGS.serving_buckets)
        self.max_batch = (int(max_batch) if max_batch is not None
                          else FLAGS.serving_max_batch)
        self.max_wait_ms = (float(max_wait_ms) if max_wait_ms is not None
                            else FLAGS.serving_max_wait_ms)
        # override for feed dims the saved program declares as -1 beyond
        # the leading batch dim (warmup can't guess those)
        self.warmup_shapes = dict(warmup_shapes or {})


def item_signature(feed: Dict[str, np.ndarray]) -> tuple:
    """Per-request shape identity MINUS the batch dim: requests with the
    same item signature coalesce into one padded batch."""
    return tuple(
        (n, tuple(np.asarray(feed[n]).shape[1:]),
         str(np.asarray(feed[n]).dtype))
        for n in sorted(feed)
    )


class ServingModel:
    """One model directory, loaded once, servable at one or more
    precisions ("fp32" always; "int8" when the artifact was QAT-exported
    and the config asks for a replica)."""

    def __init__(self, config: ModelConfig):
        self.config = config
        self.name = config.name
        self.buckets = config.buckets
        self.ready = False
        self._warm_sigs: set = set()
        # one predictor per precision replica, each with a private scope
        self._predictors: Dict[str, Predictor] = {
            "fp32": Predictor(config.dirname, optimize=config.optimize,
                              use_aot=config.use_aot)
        }
        if config.int8:
            self._predictors["int8"] = self._build_int8_replica()
        # the loaded program never changes: compute the feed/fetch specs
        # once instead of re-walking the program block per request
        self.feed_specs = self._predictors["fp32"].feed_var_specs()
        # per-fetch batch-dim flags (declared leading -1 = batch-sized,
        # slice per request; fixed leading dim = whole value per request;
        # None = unknown shape, the batcher falls back to its heuristic)
        self.fetch_batched = [
            None if shape is None
            else bool(shape) and int(shape[0]) < 0
            for (_n, shape, _d) in
            self._predictors["fp32"].fetch_var_specs()
        ]

    # -- replicas --------------------------------------------------------
    def _build_int8_replica(self) -> Predictor:
        """Freeze a second Predictor of the same artifact to int8 via the
        existing contrib.quantize.freeze_int8 path (int8 weights in its
        private scope, int8_mul/int8_conv2d consumers, runtime activation
        quantize against the trained moving-average scales)."""
        from ..contrib.quantize import count_fake_quant_ops, freeze_int8

        pred = Predictor(self.config.dirname, optimize=False,
                         use_aot=False)
        if count_fake_quant_ops(pred._program) == 0:
            raise ValueError(
                f"model {self.name!r}: int8 replica requested but the "
                "artifact carries no fake_quantize ops — export it from a "
                "QAT program (contrib.quantize.QuantizeTranspiler."
                "training_transpile before save_inference_model)")
        n = freeze_int8(pred._program, pred._scope)
        from ..log import vlog

        vlog(1, "serving: model %s int8 replica frozen (%d consumers)",
             self.name, n)
        return pred

    @property
    def precisions(self) -> List[str]:
        return sorted(self._predictors)

    def predictor(self, precision: str = "fp32") -> Predictor:
        p = self._predictors.get(precision)
        if p is None:
            raise KeyError(
                f"model {self.name!r} has no {precision!r} replica "
                f"(available: {self.precisions})")
        return p

    @property
    def feed_names(self) -> List[str]:
        return self._predictors["fp32"].feed_names

    @property
    def fetch_names(self) -> List[str]:
        return self._predictors["fp32"].fetch_names

    # -- bucket ladder ---------------------------------------------------
    def bucket_for(self, rows: int) -> Optional[int]:
        """Smallest bucket >= rows; None when rows exceed the ladder
        (the batch then runs at its exact size — counted, flight-tagged,
        and visible as an unplanned compile)."""
        for b in self.buckets:
            if b >= rows:
                return b
        return None

    @staticmethod
    def pad_feed(feed: Dict[str, np.ndarray], rows: int,
                 target: int) -> Dict[str, np.ndarray]:
        """Pad every feed array's leading dim from `rows` to `target` by
        repeating the last row (real-data values keep every op's numerics
        in-distribution; the pad rows are sliced off the outputs)."""
        if target == rows:
            return feed
        out = {}
        for n, a in feed.items():
            a = np.asarray(a)
            pad = np.repeat(a[-1:], target - rows, axis=0)
            out[n] = np.concatenate([a, pad], axis=0)
        return out

    # -- warmup ----------------------------------------------------------
    def _warmup_feed(self, precision: str, batch: int):
        """Synthesize one feed dict of `batch` rows from the program's
        declared feed shapes (leading -1 := batch); returns None when a
        non-leading dim is unknown and no warmup_shapes override names it
        (that feed signature then compiles on first live request)."""
        specs = self.feed_specs
        feed = {}
        for n, (shape, dtype) in specs.items():
            item = self.config.warmup_shapes.get(n)
            if item is None:
                if shape is None:
                    return None
                item = shape[1:]
            if any(d is None or int(d) < 0 for d in item):
                return None
            feed[n] = np.zeros((batch,) + tuple(int(d) for d in item),
                               dtype=np.dtype(dtype) if dtype != "bfloat16"
                               else np.float32)
        return feed

    def warmup(self) -> int:
        """Pre-compile (or AOT-serve) every (precision, bucket) signature
        so production traffic never pays a trace.  Returns how many
        signatures were warmed; flips `ready` (the /health readiness
        signal) even on partial warmup — remaining signatures compile on
        first request and are counted as unplanned."""
        from .. import monitor

        warmed = 0
        for precision in self.precisions:
            pred = self.predictor(precision)
            for b in self.buckets:
                feed = self._warmup_feed(precision, b)
                if feed is None:
                    if monitor.enabled():
                        monitor.counter(
                            f"serving.{self.name}.warmup_skipped").inc()
                    continue
                pred.run(feed)
                self._warm_sigs.add((precision, item_signature(feed), b))
                warmed += 1
        if monitor.enabled():
            monitor.counter(f"serving.{self.name}.warmup_signatures").inc(
                warmed)
        self.ready = True
        return warmed

    def readiness_detail(self) -> dict:
        """Structured per-model readiness for the /health body: how much
        of the (precision x bucket) warmup ladder is actually compiled,
        so a fleet router can tell a replica that is WARMING (poll again
        soon) from one that is dead or will never be ready — without
        string-matching status prose."""
        warm = {(p, b) for (p, _sig, b) in self._warm_sigs}
        ladder = len(self.buckets) * max(1, len(self.precisions))
        return {
            "ready": self.ready,
            "state": "ready" if self.ready else "warming",
            "precisions": self.precisions,
            "warm_buckets": len(warm),
            "ladder_size": ladder,
        }

    # -- execution -------------------------------------------------------
    def run_batch(self, precision: str, feed: Dict[str, np.ndarray],
                  rows: int, bucket: int, requested_sig: tuple):
        """Run one coalesced/padded batch; any compile-cache miss taken
        HERE is a serving-tier recompile and is flight-tagged with the
        requested vs bucketed signature (satellite: undersized ladders
        must be diagnosable from /flight, not silent retrace stalls)."""
        from .. import monitor
        from ..monitor import flight
        from ..testing import chaos

        # chaos fault points (no-ops unless FLAGS_chaos): deterministic
        # per-batch latency pins capacity for the overload gate; the
        # transient-error budget is the circuit breaker's fodder
        chaos.maybe_serve_latency()
        chaos.maybe_serve_error(f"serving/{self.name}")
        pred = self.predictor(precision)
        before = pred.compile_count
        with flight.context(f"serving/{self.name}"):
            outs = pred.run(feed)
            if pred.compile_count > before:
                bucketed_sig = item_signature(feed)
                after_warmup = self.ready
                flight.record(
                    "serving.compile", model=self.name, precision=precision,
                    requested_rows=rows, bucketed_rows=bucket,
                    requested_signature=[[n, list(s), d]
                                         for n, s, d in requested_sig],
                    bucketed_signature=[[n, list(s), d]
                                        for n, s, d in bucketed_sig],
                    after_warmup=after_warmup)
                if after_warmup and monitor.enabled():
                    monitor.counter("serving.unplanned_compiles").inc()
                    monitor.counter(
                        f"serving.{self.name}.unplanned_compiles").inc()
        return outs

    # -- introspection ---------------------------------------------------
    def info(self) -> dict:
        """/v1/models payload for this model."""
        from .. import monitor

        fp32 = self._predictors["fp32"]
        reg = monitor.default_registry()
        lat = reg.get(f"serving.{self.name}.request_seconds")
        req = reg.get(f"serving.{self.name}.requests")
        info = {
            "name": self.name,
            "ready": self.ready,
            "precisions": self.precisions,
            "buckets": list(self.buckets),
            "max_batch": self.config.max_batch,
            "max_wait_ms": self.config.max_wait_ms,
            "feeds": {
                n: {"shape": list(s) if s else None, "dtype": d}
                for n, (s, d) in self.feed_specs.items()
            },
            "fetches": fp32.fetch_names,
            "use_aot": self.config.use_aot,
            "aot_signatures": len(fp32.aot_signatures),
            "warm_signatures": len(self._warm_sigs),
            "compiled_signatures": {
                p: self._predictors[p].compile_count
                for p in self.precisions
            },
            "requests": req.value if req is not None else 0,
        }
        if lat is not None and lat.count:
            info["latency_s"] = {"p50": lat.quantile(0.5),
                                 "p99": lat.quantile(0.99),
                                 "count": lat.count}
        # SLO state (FLAGS_serving_slo_ms): objective + good/bad totals +
        # the multi-window burn rates the /metrics gauges expose
        from ..monitor import tracing

        slo = tracing.slo_info(self.name)
        if slo is not None:
            info["slo"] = slo
        return info
