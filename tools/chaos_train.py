#!/usr/bin/env python
"""Deterministic chaos-testable training loop — the subprocess target for
tests/test_fault_tolerance.py and the run_ci.sh chaos smoke gate.

A tiny fc+dropout regression trains over a FIXED dataset through a
reader.StatefulReader (epoch/offset cursor checkpointed), with checkpoint
v2 interval saves, emergency saves armed through the flight recorder, and
every chaos hook live.  Every source of randomness is pinned (data from a
fixed seed, dropout from the checkpointed executor RNG counter), so:

    run A: uninterrupted N steps           -> params_A
    run B: SIGKILLed at step K (chaos), then resumed to N -> params_B
    assert params_A == params_B (bit-exact)

Prints one JSON line {"start": resume_step, "steps_run": n, "final_loss":
..., "ckpt_dir": ...} on success; --out saves the final params as .npz.
"""

import argparse
import json
import os
import sys
import time

# runnable from anywhere (tests invoke it by absolute path)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_dataset(n_batches, batch_size, dim, seed):
    """The whole (tiny) dataset up front, deterministically: batch k is a
    pure function of (seed, k), never of which process generates it."""
    import numpy as np

    rng = np.random.RandomState(seed)
    w = rng.randn(dim, 1).astype("float32")
    batches = []
    for _ in range(n_batches):
        x = rng.randn(batch_size, dim).astype("float32")
        y = (x @ w + 0.1 * rng.randn(batch_size, 1)).astype("float32")
        batches.append({"x": x, "y": y})
    return batches


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--ckpt-dir", required=True)
    p.add_argument("--steps", type=int, default=12)
    p.add_argument("--interval", type=int, default=4)
    p.add_argument("--batches-per-epoch", type=int, default=5)
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--dim", type=int, default=4)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--dropout", type=float, default=0.2)
    p.add_argument("--async-save", action="store_true")
    p.add_argument("--out", default=None,
                   help="write final params to this .npz path")
    p.add_argument("--sleep-at-step", type=int, default=-1,
                   help="pause --sleep-s before this step (lets a parent "
                        "deliver SIGTERM mid-run)")
    p.add_argument("--sleep-s", type=float, default=10.0)
    args = p.parse_args()

    import numpy as np

    import paddle_tpu as pt
    from paddle_tpu import layers
    from paddle_tpu.monitor import flight
    from paddle_tpu.reader import StatefulReader
    from paddle_tpu.testing import chaos

    x = layers.data(name="x", shape=[args.dim], dtype="float32")
    y = layers.data(name="y", shape=[1], dtype="float32")
    h = layers.fc(x, size=16, act="relu",
                  param_attr=pt.param_attr.ParamAttr(name="ct_w1"))
    if args.dropout > 0:
        # exercises the executor RNG counter: masks must REPLAY across a
        # resume for bit-exact recovery (the counter rides the manifest)
        h = layers.dropout(h, dropout_prob=args.dropout)
    pred = layers.fc(h, size=1,
                     param_attr=pt.param_attr.ParamAttr(name="ct_w2"))
    loss = layers.mean(layers.square(pred - y))
    pt.optimizer.MomentumOptimizer(
        learning_rate=args.lr, momentum=0.9).minimize(loss)

    exe = pt.Executor(pt.CPUPlace())
    exe.run(pt.default_startup_program())

    batches = build_dataset(args.batches_per_epoch, args.batch_size,
                            args.dim, args.seed)
    sreader = StatefulReader(lambda: iter(batches))

    mgr = pt.io.CheckpointManager(
        args.ckpt_dir, exe, interval_steps=args.interval,
        async_save=args.async_save, keep_last=3)
    mgr.register_state("reader", sreader)
    flight.install()          # SIGTERM/crash hooks
    mgr.install_emergency()   # ... trigger a final checkpoint

    start = mgr.resume()

    def batch_stream():
        while True:
            for feed in sreader():
                yield feed

    stream = batch_stream()
    final_loss = None
    n_run = 0
    for step in range(start, args.steps):
        if step == args.sleep_at_step:
            print(json.dumps({"sleeping_at": step}), flush=True)
            time.sleep(args.sleep_s)
        feed = next(stream)
        mgr.step_started(step)  # emergency saves mid-run label THIS step
        (lv,) = exe.run(feed=feed, fetch_list=[loss])
        final_loss = chaos.nan_loss(step, float(np.asarray(lv)))
        flight.note_step(step, final_loss)
        mgr.on_step(step)  # interval save + chaos kill-at-step hook
        n_run += 1
    mgr.wait()
    mgr.close()

    if args.out:
        scope = pt.global_scope()
        params = {n: np.asarray(scope.find_var(n))
                  for n in ("ct_w1", "ct_w2")}
        np.savez(args.out, **params)
    print(json.dumps({
        "start": start,
        "steps_run": n_run,
        "final_loss": final_loss,
        "ckpt_dir": args.ckpt_dir,
        "skipped": mgr.skipped,
    }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
