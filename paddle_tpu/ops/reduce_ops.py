"""Reductions (reference: operators/reduce_ops/reduce_{sum,mean,max,min,prod}_op.cc)."""

from __future__ import annotations

from ..core.registry import register


def _reduce_infer(ctx):
    xs = ctx.input_shape("X")
    if xs is None:
        return
    dims = ctx.attr("dim", [0])
    keep = ctx.attr("keep_dim", False)
    if ctx.attr("reduce_all", False):
        out = [1] if keep else []
    else:
        dims = [d % len(xs) for d in dims]
        if keep:
            out = [1 if i in dims else s for i, s in enumerate(xs)]
        else:
            out = [s for i, s in enumerate(xs) if i not in dims]
    ctx.set_output("Out", out or [], ctx.input_dtype("X"))


def _make(name, jfn_name):
    def lower(ctx, ins):
        import jax.numpy as jnp

        fn = getattr(jnp, jfn_name)
        x = ins["X"][0]
        if ctx.attr("reduce_all", False):
            out = fn(x, keepdims=ctx.attr("keep_dim", False))
        else:
            dims = tuple(d % x.ndim for d in ctx.attr("dim", [0]))
            out = fn(x, axis=dims, keepdims=ctx.attr("keep_dim", False))
        return {"Out": [out]}

    lower.__name__ = f"lower_{name}"
    register(name, infer_shape=_reduce_infer)(lower)


_make("reduce_sum", "sum")
_make("reduce_mean", "mean")
_make("reduce_max", "max")
_make("reduce_min", "min")
_make("reduce_prod", "prod")
