"""BERT-style encoder (BASELINE.md: "BERT-class (layer_norm/gelu/fused
attention)"; built from the same primitives as the reference would be —
layers/nn.py layer_norm:3030 + gelu + attention composed from matmul/softmax
— but with the Pallas fused-attention path available via use_flash).

Under use_flash the self-attention sites ride transformer.py's
multi_head_attention selection: with FLAGS_fused_qkv_attention (default
on) each site lowers to ONE fused_qkv_attention op whose kernels compute
the qkv/output projection dots in-VMEM (PERF.md round 9 — q/k/v never
exist in HBM); flag off emits the fc+split+fused_attention+fc
composition, with parameter names unchanged either way (the unnamed
ffn/head fc parameters keep their fc_N draws — checkpoints interop,
asserted in tests/test_fused_qkv_attention.py)."""

from __future__ import annotations

import numpy as np

from .. import layers
from ..initializer import NormalInitializer, ConstantInitializer
from ..param_attr import ParamAttr


def _dropout_residual(sub, x, dropout_rate):
    """dropout(sub) + x: ONE fused dropout-add op (the epilogue kernel of
    kernels/dropout_epilogue.py — mask regenerated in-kernel, fwd and bwd)
    under FLAGS.fused_dropout_add; the reference's separate dropout +
    elementwise_add ops otherwise.  rate 0 is a plain add either way."""
    from ..flags import FLAGS

    if dropout_rate and FLAGS.fused_dropout_add:
        return layers.dropout_add(sub, x, dropout_rate)
    if dropout_rate:
        sub = layers.dropout(sub, dropout_prob=dropout_rate,
                             dropout_implementation="upscale_in_train")
    return layers.elementwise_add(x, sub)


def bert_encoder_layer(x, attn_bias, n_head, d_model, d_ff, dropout_rate,
                       use_flash=False, name="layer"):
    from .transformer import multi_head_attention

    attn = multi_head_attention(
        x, None, None, attn_bias, d_model // n_head, d_model // n_head,
        d_model, n_head, dropout_rate, use_flash=use_flash,
    )
    x = layers.layer_norm(_dropout_residual(attn, x, dropout_rate),
                          begin_norm_axis=len(x.shape) - 1)
    ff = layers.fc(input=x, size=d_ff, act="gelu", num_flatten_dims=2)
    ff = layers.fc(input=ff, size=d_model, num_flatten_dims=2)
    return layers.layer_norm(_dropout_residual(ff, x, dropout_rate),
                             begin_norm_axis=len(x.shape) - 1)


def bert_encoder(
    src_ids,
    position_ids,
    sentence_ids,
    input_mask,
    vocab_size=30522,
    max_position=512,
    type_vocab_size=2,
    n_layer=12,
    n_head=12,
    d_model=768,
    d_ff=3072,
    dropout_rate=0.1,
    use_flash=False,
):
    """input_mask: [B, T, 1] float 1/0.  Returns [B, T, d_model]."""
    emb = layers.embedding(
        src_ids, size=[vocab_size, d_model],
        param_attr=ParamAttr(name="word_embedding",
                             initializer=NormalInitializer(0.0, 0.02)),
    )
    pos = layers.embedding(
        position_ids, size=[max_position, d_model],
        param_attr=ParamAttr(name="pos_embedding",
                             initializer=NormalInitializer(0.0, 0.02)),
    )
    sent = layers.embedding(
        sentence_ids, size=[type_vocab_size, d_model],
        param_attr=ParamAttr(name="sent_embedding",
                             initializer=NormalInitializer(0.0, 0.02)),
    )
    x = layers.elementwise_add(layers.elementwise_add(emb, pos), sent)
    x = layers.layer_norm(x, begin_norm_axis=len(x.shape) - 1)
    if dropout_rate:
        x = layers.dropout(x, dropout_prob=dropout_rate,
                           dropout_implementation="upscale_in_train")

    # attn bias from mask: (1-m)(-1e9), broadcast over heads
    # input_mask [B,T,1] -> [B,1,1,T]
    m = layers.transpose(input_mask, [0, 2, 1])  # [B,1,T]
    neg = layers.scale(m, scale=1e9, bias=-1e9)  # 0 where valid, -1e9 pad

    b, t, _ = src_ids.shape if src_ids.shape else (None, None, None)
    bias4 = layers.reshape(neg, [-1, 1, 1, neg.shape[-1]])
    # padding mask, not a parameter: marks the fused-attention bias as
    # stop-gradient so the TPU hardware-PRNG dropout fast path stays on
    # (a trainable bias forces hash masks — see ops/fused_ops.py)
    bias4.stop_gradient = True

    for i in range(n_layer):
        x = bert_encoder_layer(x, bias4, n_head, d_model, d_ff, dropout_rate,
                               use_flash=use_flash, name=f"layer_{i}")
    return x


def build_pretrain_net(vocab_size=1000, seq_len=128, n_layer=2, n_head=4,
                       d_model=128, d_ff=512, dropout_rate=0.0,
                       use_flash=False, with_optimizer=True, lr=1e-4):
    """Masked-LM pretraining objective (simplified: predict all positions,
    weighted by mask_weight)."""
    from .. import optimizer as opt_mod

    src = layers.data(name="src_ids", shape=[seq_len, 1], dtype="int64")
    pos = layers.data(name="pos_ids", shape=[seq_len, 1], dtype="int64")
    sent = layers.data(name="sent_ids", shape=[seq_len, 1], dtype="int64")
    mask = layers.data(name="input_mask", shape=[seq_len, 1], dtype="float32")
    labels = layers.data(name="mask_labels", shape=[seq_len, 1], dtype="int64")
    weights = layers.data(name="mask_weights", shape=[seq_len, 1],
                          dtype="float32")

    enc = bert_encoder(
        src, pos, sent, mask, vocab_size=vocab_size, max_position=seq_len,
        n_layer=n_layer, n_head=n_head, d_model=d_model, d_ff=d_ff,
        dropout_rate=dropout_rate, use_flash=use_flash,
    )
    logits = layers.fc(input=enc, size=vocab_size, num_flatten_dims=2)
    logits2 = layers.reshape(logits, [-1, vocab_size])
    labels2 = layers.reshape(labels, [-1, 1])
    loss = layers.softmax_with_cross_entropy(logits=logits2, label=labels2)
    w2 = layers.reshape(weights, [-1, 1])
    weighted = layers.elementwise_mul(loss, w2)
    total = layers.reduce_sum(weighted)
    denom = layers.reduce_sum(w2)
    avg_loss = layers.elementwise_div(total, denom)
    if with_optimizer:
        opt_mod.Adam(learning_rate=lr).minimize(avg_loss)
    return avg_loss, enc


def make_batch(batch_size, seq_len, vocab_size, rng=None):
    rng = rng or np.random.RandomState(0)
    pos = np.tile(np.arange(seq_len, dtype=np.int64)[None, :, None],
                  (batch_size, 1, 1))
    return {
        "src_ids": rng.randint(0, vocab_size, (batch_size, seq_len, 1)).astype("int64"),
        "pos_ids": pos,
        "sent_ids": np.zeros((batch_size, seq_len, 1), np.int64),
        "input_mask": np.ones((batch_size, seq_len, 1), np.float32),
        "mask_labels": rng.randint(0, vocab_size, (batch_size, seq_len, 1)).astype("int64"),
        "mask_weights": (rng.rand(batch_size, seq_len, 1) < 0.15).astype("float32"),
    }
