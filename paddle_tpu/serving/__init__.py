"""Production serving tier: multi-model inference server with dynamic
batching on the AOT-bundle path (ROADMAP item 1 — the reference's
out-of-Python serving property, api/paddle_api.h:153, grown into the
"heavy traffic" story).

Three layers:

  * `model.py`   — ServingModel: a Predictor (+ optional int8 replica via
    contrib.quantize.freeze_int8) with a pad-to-bucket batch ladder,
    startup warmup, and serving-tier recompile tagging.
  * `batcher.py` — DynamicBatcher: per-model request queue drained by a
    scheduler thread that coalesces concurrent requests into bucket
    shapes (max-wait deadline, max-batch cap), so every executed batch
    hits a warm entry in the executor's compile cache.
  * `server.py`  — InferenceServer: stdlib-HTTP multi-model endpoint
    (JSON + npz), /v1/models introspection, /metrics //health //flight
    inherited from the monitor stack, persistent XLA compilation cache.

CLI: `python -m paddle_tpu.serving --model name=/path/to/export ...`
Load test: `python tools/loadgen.py --url http://host:port --model name`.
"""

from .batcher import DynamicBatcher, FILL_BUCKETS  # noqa: F401
from .model import ModelConfig, ServingModel, parse_buckets  # noqa: F401
from .server import (  # noqa: F401
    InferenceServer,
    RequestError,
    ServingHandler,
    enable_compilation_cache,
)
