"""Graph pass framework: registry + pattern matching + fusion passes
(reference: framework/ir/ — Pass::Apply + PassRegistry + REGISTER_PASS
ir/pass.h:32,144,207; GraphPatternDetector ir/graph_pattern_detector.cc;
the ~20 fuse passes like fc_fuse_pass.cc, conv_bn_fuse_pass.cc).

TPU-first scope: XLA already performs producer-consumer fusion, so passes
here exist for (a) rewrites XLA cannot do because they need parameter
VALUES (conv+bn folding mutates weights), (b) mapping op subgraphs onto
hand-written Pallas kernels (layer_norm+gelu, attention_fuse), (c)
program hygiene.  Two matchers: find_chains for linear single-consumer
chains, and Pattern — a backtracking DAG matcher (GraphPatternDetector
parity) for multi-input/multi-consumer shapes like the attention
subgraph.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Sequence

from .core import framework as fw

_PASS_REGISTRY: Dict[str, Callable] = {}


def register_pass(name: str):
    """REGISTER_PASS parity (ir/pass.h:207): decorator for
    fn(program, scope) -> int (number of rewrites applied)."""

    def deco(fn):
        if name in _PASS_REGISTRY:
            raise ValueError(f"pass {name!r} already registered")
        _PASS_REGISTRY[name] = fn
        return fn

    return deco


def list_passes() -> List[str]:
    return sorted(_PASS_REGISTRY)


def apply_pass(name: str, program: fw.Program, scope=None) -> int:
    """Pass::Apply parity: run one registered pass; returns its rewrite
    count."""
    if name not in _PASS_REGISTRY:
        raise KeyError(f"unknown pass {name!r} (have {list_passes()})")
    return _PASS_REGISTRY[name](program, scope)


def apply_passes(names: Sequence[str], program: fw.Program,
                 scope=None) -> Dict[str, int]:
    """BuildStrategy-style pass pipeline."""
    return {n: apply_pass(n, program, scope) for n in names}


# ---------------------------------------------------------------------------
# pattern matching (GraphPatternDetector's role for linear chains)
# ---------------------------------------------------------------------------


def consumers(block: fw.Block, name: str) -> List[fw.Operator]:
    return [op for op in block.ops if name in op.input_arg_names()]


def consumer_counts(block: fw.Block) -> Dict[str, int]:
    """One-pass name -> number of consuming ops map."""
    counts: Dict[str, int] = {}
    for op in block.ops:
        for n in set(op.input_arg_names()):
            counts[n] = counts.get(n, 0) + 1
    return counts


def find_chains(block: fw.Block, types: Sequence[str]):
    """Find op chains op0 -> op1 -> ... where opK's type is types[K] and
    each link variable feeds ONLY op{K+1}.  Returns a list of lists of
    (index, op) pairs, in program order of the chain head.  Builds its
    producer/consumer indexes in one pass each (O(ops))."""
    producers = {}
    for i, op in enumerate(block.ops):
        for n in op.output_arg_names():
            producers[n] = (i, op)
    counts = consumer_counts(block)

    chains = []
    for i, op in enumerate(block.ops):
        if op.type != types[-1]:
            continue
        chain = [(i, op)]
        ok = True
        cur = op
        for k in range(len(types) - 2, -1, -1):
            prev = None
            for n in cur.input_arg_names():
                p = producers.get(n)
                if p is not None and p[1].type == types[k]:
                    # the link var must feed only `cur`
                    if counts.get(n, 0) == 1:
                        prev = p
                        break
            if prev is None:
                ok = False
                break
            chain.append(prev)
            cur = prev[1]
        if ok:
            chains.append(list(reversed(chain)))
    return chains


# ---------------------------------------------------------------------------
# built-in passes
# ---------------------------------------------------------------------------


@register_pass("conv_bn_fuse")
def _conv_bn_fuse(program: fw.Program, scope) -> int:
    """Folds inference-mode batch_norm into conv2d/mul weights — needs the
    parameter VALUES, so it lives at the program level (reference
    conv_bn_fuse_pass.cc / inference_transpiler.py)."""
    from .inference import inference_transpile

    if scope is None:
        raise ValueError("conv_bn_fuse needs a scope (it folds weights)")
    return inference_transpile(program, scope)


@register_pass("layer_norm_gelu_fuse")
def _layer_norm_gelu_fuse(program: fw.Program, scope=None) -> int:
    """Rewrites layer_norm -> gelu chains into the Pallas-backed
    fused_layer_norm_gelu op (the reference's fuse-pass tier, e.g.
    fuse_elewise_add_act; here the fused op is the hand-written kernel
    target)."""
    block = program.global_block()
    fetch_names = set(getattr(program, "fetch_var_names", []) or [])
    n = 0
    changed = True
    while changed:
        changed = False
        counts = consumer_counts(block)
        for chain in find_chains(block, ["layer_norm", "gelu"]):
            (i_ln, ln), (i_act, act) = chain
            # the rewrite deletes layer_norm's Y/Mean/Variance vars: bail
            # if any is a fetch target or has consumers beyond the gelu
            aux_used = any(
                counts.get(o, 0) > 0
                for slot in ("Mean", "Variance")
                for o in ln.output(slot)
            )
            removed_outs = set(ln.output_arg_names())
            if aux_used or (removed_outs & fetch_names):
                continue
            inputs = {"X": ln.input("X")}
            if ln.input("Scale"):
                inputs["Scale"] = ln.input("Scale")
            if ln.input("Bias"):
                inputs["Bias"] = ln.input("Bias")
            out_name = act.output("Out")[0]
            attrs = {
                "begin_norm_axis": ln.attr("begin_norm_axis", 1),
                "epsilon": ln.attr("epsilon", 1e-5),
                "approximate": act.attr("approximate", False),
            }
            # remove the higher index first so the lower stays valid
            for idx in sorted((i_ln, i_act), reverse=True):
                block.remove_op(idx)
            block.insert_op(
                min(i_ln, i_act),
                "fused_layer_norm_gelu",
                inputs=inputs,
                outputs={"Out": [out_name]},
                attrs=attrs,
            )
            n += 1
            changed = True
            break  # indices shifted: rescan (one O(ops) pass per rewrite)
    return n


@register_pass("fused_embedding")
def _fused_embedding_pass(program: fw.Program, scope=None) -> int:
    """Coalesce per-slot `lookup_table` op groups into ONE
    `fused_lookup_table` per same-shape table group, plus their
    `lookup_table_grad` ops and per-table row-sparse optimizer chains
    (sgd / lazy-mode adam) into `fused_lookup_table_grad` /
    `fused_sparse_{sgd,adam}` — the graph tier of the round-8 DeepFM
    dispatch-wall attack (ops/nn_ops.py, kernels/embedding.py; gate:
    FLAGS_fused_embedding, applied by models/deepfm.py).

    Every rewrite preserves variable names (parameters, outputs, grads),
    so checkpoints interop across the flag and downstream consumers
    never change.  Groups are conservative: >= 2 lookups over DISTINCT
    single-use tables of identical [V, D] shape/dtype with identical
    ids shapes and attrs; anything else (shared tables, distributed
    lookups, producers interleaved past the fusion point) keeps the
    per-slot composition, which remains correct alongside fused groups.
    Returns the number of ops fused away."""
    block = program.global_block()
    removed_total = 0

    def producers_and_first_consumers():
        prod: Dict[str, int] = {}
        first_use: Dict[str, int] = {}
        for i, op in enumerate(block.ops):
            for n in op.input_arg_names():
                if n and n not in first_use:
                    first_use[n] = i
            for n in op.output_arg_names():
                if n:
                    prod.setdefault(n, i)
        return prod, first_use

    # ---- tier 1: forward lookups (one rewrite per O(ops) rescan: every
    # rewrite shifts op indices, so group indices are refetched fresh) ---
    fused_groups = []  # (ws, ids_names, out_names, attrs) per rewrite
    changed = True
    while changed:
        changed = False
        table_uses: Dict[str, int] = {}
        for op in block.ops:
            if op.type == "lookup_table":
                w = op.input("W")[0]
                table_uses[w] = table_uses.get(w, 0) + 1
        groups: Dict[tuple, list] = {}
        order: list = []
        for i, op in enumerate(block.ops):
            if op.type != "lookup_table" or op.attr("is_distributed", False):
                continue
            w, ids = op.input("W")[0], op.input("Ids")[0]
            wv = block._find_var_recursive(w)
            iv = block._find_var_recursive(ids)
            if wv is None or iv is None or not wv.shape or table_uses[w] != 1:
                continue
            key = (tuple(wv.shape), wv.dtype, tuple(iv.shape or ()),
                   bool(op.attr("is_sparse", False)),
                   op.attr("padding_idx", -1))
            if key not in groups:
                order.append(key)
            groups.setdefault(key, []).append((i, op))
        prod, _ = producers_and_first_consumers()
        for key in order:
            items = groups[key]
            if len(items) < 2:
                continue
            ws = [op.input("W")[0] for _, op in items]
            if len(set(ws)) != len(ws):
                continue
            insert_at = min(i for i, _ in items)
            max_idx = max(i for i, _ in items)
            in_names = [op.input("Ids")[0] for _, op in items] + ws
            # an input produced between the fusion point and its original
            # op (e.g. hashed ids) blocks hoisting; producers PAST the
            # group are next-iteration state writes (the optimizer's
            # in-place ParamOut) and don't
            if any(insert_at <= prod.get(n, -1) <= max_idx
                   for n in in_names):
                continue
            idxs = sorted((i for i, _ in items), reverse=True)
            inputs = {"Ids": [op.input("Ids")[0] for _, op in items],
                      "W": ws}
            outputs = {"Out": [op.output("Out")[0] for _, op in items]}
            attrs = dict(items[0][1].attrs)
            for i in idxs:
                block.remove_op(i)
            block.insert_op(insert_at, "fused_lookup_table", inputs=inputs,
                            outputs=outputs, attrs=attrs)
            removed_total += len(items) - 1
            fused_groups.append((ws, inputs["Ids"], outputs["Out"], attrs))
            changed = True
            break  # indices shifted: rescan

    # ---- tier 2: backward lookups --------------------------------------
    for ws, ids_names, out_names, attrs in fused_groups:
        wset = set(ws)
        found = {}
        for i, op in enumerate(block.ops):
            if op.type == "lookup_table_grad" and op.input("W")[0] in wset:
                found[op.input("W")[0]] = (i, op)
        if len(found) != len(ws):
            continue  # partial/no backward: per-slot grads stay correct
        idxs = sorted((i for i, _ in found.values()), reverse=True)
        insert_at = max(idxs) - (len(idxs) - 1)
        _, first_use = producers_and_first_consumers()
        g_outs = [found[w][1].output("W@GRAD")[0] for w in ws]
        if any(first_use.get(n, len(block.ops)) <= max(idxs)
               for n in g_outs):
            continue  # a grad consumer sits between the per-slot grads
        g_inputs = {
            "Ids": [found[w][1].input("Ids")[0] for w in ws],
            "W": list(ws),
            "Out@GRAD": [found[w][1].input("Out@GRAD")[0] for w in ws],
        }
        g_attrs = dict(found[ws[0]][1].attrs)
        for i in idxs:
            block.remove_op(i)
        block.insert_op(insert_at, "fused_lookup_table_grad",
                        inputs=g_inputs, outputs={"W@GRAD": g_outs},
                        attrs=g_attrs)
        removed_total += len(ws) - 1

        # ---- tier 3: the per-table row-sparse optimizer chain ----------
        if not attrs.get("is_sparse", False):
            continue  # dense grads keep the per-param dense updates
        opt_found = {}
        opt_type = None
        for i, op in enumerate(block.ops):
            if op.type not in ("sgd", "adam"):
                continue
            p = op.input("Param")[0]
            if p not in wset:
                continue
            opt_found[p] = (i, op)
            opt_type = op.type if opt_type in (None, op.type) else "mixed"
        if len(opt_found) != len(ws) or opt_type not in ("sgd", "adam"):
            continue
        ops_g = [opt_found[w][1] for w in ws]
        lrs = {op.input("LearningRate")[0] for op in ops_g}
        if len(lrs) != 1:
            continue  # per-table LR schedules: keep per-table ops
        if opt_type == "adam":
            hp = [(op.attr("beta1", 0.9), op.attr("beta2", 0.999),
                   op.attr("epsilon", 1e-8), op.attr("lazy_mode", False))
                  for op in ops_g]
            if len(set(hp)) != 1 or not hp[0][3]:
                continue  # non-lazy adam densifies per table — no group win
        idxs = sorted((i for i, _ in opt_found.values()), reverse=True)
        insert_at = max(idxs) - (len(idxs) - 1)
        o_attrs = dict(ops_g[0].attrs)
        if opt_type == "sgd":
            inputs = {
                "Param": list(ws),
                "Grad": [op.input("Grad")[0] for op in ops_g],
                "LearningRate": [lrs.pop()],
            }
            outputs = {"ParamOut": list(ws)}
            fused_type = "fused_sparse_sgd"
        else:
            inputs = {
                "Param": list(ws),
                "Grad": [op.input("Grad")[0] for op in ops_g],
                "LearningRate": [lrs.pop()],
                "Moment1": [op.input("Moment1")[0] for op in ops_g],
                "Moment2": [op.input("Moment2")[0] for op in ops_g],
                "Beta1Pow": [op.input("Beta1Pow")[0] for op in ops_g],
                "Beta2Pow": [op.input("Beta2Pow")[0] for op in ops_g],
            }
            outputs = {
                "ParamOut": list(ws),
                "Moment1Out": [op.output("Moment1Out")[0] for op in ops_g],
                "Moment2Out": [op.output("Moment2Out")[0] for op in ops_g],
                "Beta1PowOut": [op.output("Beta1PowOut")[0] for op in ops_g],
                "Beta2PowOut": [op.output("Beta2PowOut")[0] for op in ops_g],
            }
            fused_type = "fused_sparse_adam"
        for i in idxs:
            block.remove_op(i)
        block.insert_op(insert_at, fused_type, inputs=inputs,
                        outputs=outputs, attrs=o_attrs)
        removed_total += len(ws) - 1

    return removed_total


# ---------------------------------------------------------------------------
# DAG pattern matching (GraphPatternDetector parity,
# ir/graph_pattern_detector.cc: multi-input/multi-consumer patterns, not
# just linear chains)
# ---------------------------------------------------------------------------


class Pattern:
    """A small op-DAG pattern.

    nodes: name -> op type.  edges: (src, dst, src_slot, dst_slot,
    single_consumer) — some output of `src` (restricted to src_slot if
    given) must feed some input of `dst` (restricted to dst_slot);
    single_consumer=True additionally requires the link variable to feed
    ONLY `dst` (safe-to-delete intermediate).

    match() returns assignments {node_name: (op_index, op)} with all ops
    distinct, found by backtracking over per-node candidates.
    """

    def __init__(self):
        self._nodes = {}
        self._edges = []

    def node(self, name, op_type):
        self._nodes[name] = op_type
        return self

    def edge(self, src, dst, src_slot=None, dst_slot=None,
             single_consumer=True):
        self._edges.append((src, dst, src_slot, dst_slot, single_consumer))
        return self

    def _link_ok(self, block, counts, sop, dop, src_slot, dst_slot, single):
        src_outs = (sop.output(src_slot) if src_slot
                    else sop.output_arg_names())
        dst_ins = (dop.input(dst_slot) if dst_slot
                   else dop.input_arg_names())
        links = set(src_outs) & set(dst_ins)
        if not links:
            return False
        if single and all(counts.get(n, 0) != 1 for n in links):
            return False
        return True

    def match(self, block: fw.Block):
        counts = consumer_counts(block)
        names = list(self._nodes)
        cands = {
            n: [(i, op) for i, op in enumerate(block.ops)
                if op.type == self._nodes[n]]
            for n in names
        }
        matches = []

        def backtrack(k, assign):
            if k == len(names):
                matches.append(dict(assign))
                return
            name = names[k]
            for i, op in cands[name]:
                if any(i == a[0] for a in assign.values()):
                    continue
                assign[name] = (i, op)
                ok = True
                for src, dst, ss, ds, single in self._edges:
                    if src in assign and dst in assign:
                        if not self._link_ok(block, counts,
                                             assign[src][1], assign[dst][1],
                                             ss, ds, single):
                            ok = False
                            break
                if ok:
                    backtrack(k + 1, assign)
                del assign[name]

        backtrack(0, {})
        return matches


@register_pass("attention_fuse")
def _attention_fuse(program: fw.Program, scope=None) -> int:
    """Rewrites user-built scaled-dot-product attention subgraphs —
    matmul(Q,K^T) [-> elementwise_add bias] -> softmax [-> dropout]
    -> matmul(.,V) — onto the Pallas flash-attention op, so the kernel
    perf reaches programs that spell attention by hand, not just the
    bundled model (VERDICT r3 weak #5; reference analogue:
    attention_lstm_fuse / GraphPatternDetector-driven fusions).

    Dropout on the attention WEIGHTS with upscale_in_train semantics is
    folded INTO the fused op (the kernels apply the mask in-register via
    the deterministic hash PRNG — exact weights-dropout semantics, see
    kernels/attention.py).  downgrade_in_infer dropout (train-time output
    is NOT upscaled) is not expressible in-kernel and is re-sited onto the
    fused output, the documented approximation.
    """
    block = program.global_block()
    fetch_names = set(getattr(program, "fetch_var_names", []) or [])
    total = 0
    changed = True
    while changed:
        changed = False
        # enumerate variants longest-first so the bias/dropout forms win
        for with_bias in (True, False):
            for with_dropout in (True, False):
                pat = Pattern()
                pat.node("qk", "matmul")
                if with_bias:
                    pat.node("add", "elementwise_add")
                    pat.edge("qk", "add", "Out", "X")
                pat.node("sm", "softmax")
                if with_bias:
                    pat.edge("add", "sm", "Out", "X")
                else:
                    pat.edge("qk", "sm", "Out", "X")
                if with_dropout:
                    pat.node("drop", "dropout")
                    pat.edge("sm", "drop", "Out", "X")
                    pat.node("av", "matmul")
                    pat.edge("drop", "av", "Out", "X")
                else:
                    pat.node("av", "matmul")
                    pat.edge("sm", "av", "Out", "X")

                for m in pat.match(block):
                    qk = m["qk"][1]
                    av = m["av"][1]
                    # shape/attr guards: canonical attention only
                    if not qk.attr("transpose_Y", False):
                        continue
                    if qk.attr("transpose_X", False):
                        continue
                    if av.attr("transpose_X", False) or av.attr(
                            "transpose_Y", False):
                        continue
                    qvar = block._find_var_recursive(qk.input("X")[0])
                    if qvar is None or not qvar.shape or len(qvar.shape) != 4:
                        continue
                    removed_outs = set()
                    for key in ("qk", "add", "sm"):
                        if key in m:
                            removed_outs |= set(m[key][1].output_arg_names())
                    if with_dropout:
                        # the dropout's original output (the attention
                        # weights) loses its producer in the rewrite
                        removed_outs |= set(m["drop"][1].output_arg_names())
                    if removed_outs & fetch_names:
                        continue

                    inputs = {"Q": qk.input("X"), "K": qk.input("Y"),
                              "V": av.input("Y")}
                    if with_bias:
                        inputs["Bias"] = m["add"][1].input("Y")
                    attrs = {"scale": qk.attr("alpha", 1.0), "fmt": "bhtd"}
                    av_out = av.output("Out")[0]

                    drop_spec = None
                    if with_dropout:
                        drop = m["drop"][1]
                        d_impl = drop.attrs.get("dropout_implementation",
                                                "downgrade_in_infer")
                        # fold only plain train-mode dropout: an is_test or
                        # fixed-seed dropout op carries semantics the fused
                        # attrs can't express, and a consumed Mask output
                        # needs its producer — re-site those instead
                        mask_names = set(drop.outputs.get("Mask", []))
                        mask_used = mask_names and any(
                            mask_names & set(op2.input_arg_names())
                            for op2 in block.ops if op2 is not drop)
                        if (d_impl == "upscale_in_train"
                                and not drop.attrs.get("is_test", False)
                                and not drop.attrs.get("seed", 0)
                                and not mask_used):
                            # exact weights-dropout inside the kernel
                            attrs["dropout_rate"] = drop.attrs.get(
                                "dropout_prob", 0.5)
                            attrs["rng_id"] = fw.unique_rng_id()
                            out_name = av_out
                        else:
                            fused_out = fw.unique_name("attn_fuse_out")
                            block.create_var(name=fused_out,
                                             dtype=qvar.dtype)
                            # dropout re-sited onto the fused output; the
                            # op is REBUILT after the fused op (V's
                            # producer may sit between the old dropout and
                            # AV matmul positions, so the old dropout slot
                            # can precede V)
                            drop_spec = (dict(drop.attrs),
                                         {"X": [fused_out]},
                                         {"Out": [av_out],
                                          "Mask": drop.outputs.get(
                                              "Mask", [])})
                            out_name = fused_out
                        remove_keys = ("qk", "add", "sm", "drop", "av")
                    else:
                        out_name = av_out
                        remove_keys = ("qk", "add", "sm", "av")

                    idxs = sorted((m[k][0] for k in remove_keys if k in m),
                                  reverse=True)
                    for i in idxs:
                        block.remove_op(i)
                    # insert where the AV matmul stood (highest removed
                    # index, shifted): every input's producer — including
                    # V's — is above that point by construction
                    pos = max(idxs) - (len(idxs) - 1)
                    block.insert_op(
                        pos,
                        "fused_attention",
                        inputs=inputs,
                        outputs={"Out": [out_name]},
                        attrs=attrs,
                    )
                    if drop_spec is not None:
                        d_attrs, d_in, d_out = drop_spec
                        block.insert_op(pos + 1, "dropout", inputs=d_in,
                                        outputs=d_out, attrs=d_attrs)
                    total += 1
                    changed = True
                    break  # indices shifted: rescan
                if changed:
                    break
            if changed:
                break
    return total
