"""InferenceServer: multi-model HTTP inference on the stdlib HTTP stack.

The production serving tier the ROADMAP north star asks for (the
reference's out-of-Python serving property, api/paddle_api.h:153, scaled
to many models + concurrent clients): load one or more exported model
dirs (AOT bundles opt-in for trusted artifacts), accept concurrent
JSON / npz requests, and drain them through per-model dynamic batchers
so every executed batch lands on a warm compiled signature.

Endpoints (handler subclasses monitor/serve.py's MonitorHandler, so the
observability routes come for free):

  * POST /v1/models/<name>:predict   (also .../predict) — run inference;
      JSON body  {"inputs": {feed: nested-list | {"b64","dtype","shape"}},
                  "precision": "fp32"|"int8"}  ->
                 {"outputs": {fetch: nested-list}, "batch": {...}}
      npz body   (Content-Type: application/x-npz, arrays keyed by feed
                 name; add ?format=npz for an npz response) — the binary
                 path for large tensors, np.load(allow_pickle=False).
  * GET  /v1/models            — model list w/ readiness, buckets, stats
  * GET  /v1/models/<name>     — one model's info
  * GET  /metrics /health /flight — inherited; /health reports serving
      READINESS (distinct from trainer liveness) via the registered
      readiness provider.

Startup: `InferenceServer([...ModelConfig...]).start()` enables
telemetry, arms the persistent XLA compilation cache
(FLAGS.serving_cache_dir — warmup compiles survive restarts), starts the
batcher threads + HTTP listener, then warms every model's bucket ladder.
"""

from __future__ import annotations

import base64
import io as _io
import json
import threading
import time
from http.server import ThreadingHTTPServer
from typing import Dict, List, Optional
from urllib.parse import parse_qs, urlparse

import numpy as np

from ..monitor import serve as mserve
from ..monitor import tracing
from ..monitor.registry import _json_safe
from .batcher import (DynamicBatcher, Overloaded, Unavailable,
                      _record_shed, _slo_bad)
from .model import ModelConfig, ServingModel


class RequestError(Exception):
    """Client-side error -> HTTP 4xx with a JSON body."""

    def __init__(self, code: int, message: str):
        super().__init__(message)
        self.code = code


def _decode_inputs(body: bytes, ctype: str, specs) -> tuple:
    """Request body -> (feed dict, options dict).  JSON (nested lists or
    b64 raw buffers) and npz (allow_pickle=False) are supported; values
    are cast to the program's declared feed dtypes."""
    if "json" in ctype or ctype.startswith("text/plain"):
        try:
            payload = json.loads(body.decode())
        except (ValueError, UnicodeDecodeError) as e:
            raise RequestError(400, f"malformed JSON body: {e}")
        if not isinstance(payload, dict) or "inputs" not in payload:
            raise RequestError(400, 'JSON body must carry an "inputs" map')
        raw = payload["inputs"]
        if not isinstance(raw, dict):
            raise RequestError(400, '"inputs" must map feed name -> value')
        feed = {}
        for n, v in raw.items():
            dtype = np.dtype(specs[n][1]) if (
                n in specs and specs[n][1] != "bfloat16") else np.float32
            try:
                if isinstance(v, dict) and "b64" in v:
                    buf = base64.b64decode(v["b64"])
                    a = np.frombuffer(buf, dtype=np.dtype(v.get(
                        "dtype", str(dtype))))
                    if "shape" in v:
                        a = a.reshape([int(d) for d in v["shape"]])
                    feed[n] = a.astype(dtype, copy=False)
                else:
                    feed[n] = np.asarray(v, dtype=dtype)
            except (ValueError, TypeError) as e:
                raise RequestError(400, f"input {n!r}: {e}")
        opts = {k: v for k, v in payload.items() if k != "inputs"}
        return feed, opts
    if "npz" in ctype or "octet-stream" in ctype:
        try:
            with np.load(_io.BytesIO(body), allow_pickle=False) as z:
                feed = {n: z[n] for n in z.files}
        except (ValueError, OSError) as e:
            raise RequestError(400, f"malformed npz body: {e}")
        return feed, {}
    raise RequestError(
        415, f"unsupported Content-Type {ctype!r} "
             "(use application/json or application/x-npz)")


def _encode_outputs(fetch_names, outs, meta, want_npz: bool):
    """-> (body bytes, content type)."""
    if want_npz:
        buf = _io.BytesIO()
        np.savez(buf, **{n: np.asarray(o)
                         for n, o in zip(fetch_names, outs)})
        return buf.getvalue(), "application/x-npz"
    body = {
        "outputs": {n: np.asarray(o).tolist()
                    for n, o in zip(fetch_names, outs)},
        "batch": meta,
    }
    return (json.dumps(_json_safe(body)) + "\n").encode(), \
        "application/json"


class _ServingHTTPServer(ThreadingHTTPServer):
    daemon_threads = True
    inference_server: "InferenceServer" = None


class ServingHandler(mserve.MonitorHandler):
    server_version = "paddle-tpu-serving/1.0"

    # -- GET: model listing + inherited monitor routes -------------------
    def _route_get(self, url) -> bool:
        srv = self.server.inference_server
        if url.path == "/v1/models":
            self._send_json(200, {"models": srv.models_info()})
        elif url.path.startswith("/v1/models/"):
            name = url.path[len("/v1/models/"):]
            model = srv.model(name)
            if model is None:
                self._send_json(404, {"error": f"no model {name!r}"})
            else:
                self._send_json(200, model.info())
        else:
            return super()._route_get(url)
        return True

    # -- POST: prediction ------------------------------------------------
    def do_POST(self):  # noqa: N802 — BaseHTTPRequestHandler contract
        from ..testing import chaos

        # whole-request-path chaos hooks (one flag read each when off):
        # straggler latency BEFORE admission, replica death AFTER the
        # response is written — the router sees a slow replica / a dead
        # socket on its next request, never a half-written response
        chaos.maybe_replica_latency()
        try:
            self._do_post_inner()
        finally:
            chaos.on_request_done()

    def _do_post_inner(self):
        trace = None
        try:
            t_req0 = time.perf_counter()
            url = urlparse(self.path)
            gen_name = self._generate_target(url.path)
            if gen_name is not None:
                self._do_generate(gen_name, t_req0)
                return
            name = self._predict_target(url.path)
            if name is None:
                self._send_json(404, {
                    "error": "POST /v1/models/<name>:predict "
                             "(or :generate for generation models)"})
                return
            srv = self.server.inference_server
            model = srv.model(name)
            if model is None:
                self._send_json(404, {"error": f"no model {name!r}"})
                return
            # request trace: accept the client's W3C traceparent (the
            # id correlates client and server records), generate one
            # otherwise; the root span opens at request arrival
            trace = tracing.start(
                "predict", name,
                traceparent=self.headers.get("traceparent"),
                t0=tracing.pc_to_epoch(t_req0))
            length = int(self.headers.get("Content-Length", 0))
            if length <= 0:
                raise RequestError(411, "request body required")
            body = self.rfile.read(length)
            ctype = (self.headers.get("Content-Type")
                     or "application/json").lower()
            specs = model.feed_specs
            feed, opts = _decode_inputs(body, ctype, specs)
            if trace is not None:
                trace.add_span("parse", tracing.pc_to_epoch(t_req0),
                               tracing.pc_to_epoch(time.perf_counter()),
                               bytes=length)
            q = parse_qs(url.query)
            precision = str(opts.get(
                "precision", q.get("precision", ["fp32"])[0]))
            if precision not in model.precisions:
                raise RequestError(
                    400, f"model {name!r} has no {precision!r} replica "
                         f"(available: {model.precisions})")
            try:
                timeout = float(opts.get("timeout_s", 30.0))
            except (TypeError, ValueError):
                raise RequestError(
                    400, f'"timeout_s" must be a number, got '
                         f'{opts.get("timeout_s")!r}')
            try:
                outs, meta = srv.submit(name, feed, precision=precision,
                                        timeout=timeout, trace=trace)
            except (KeyError, ValueError) as e:
                raise RequestError(400, str(e))
            except TimeoutError as e:
                raise RequestError(504, str(e))
            if trace is not None:
                # the in-response decomposition block (partial: the
                # respond span lands in the stored trace, which the
                # traceparent header points the client at)
                meta = dict(meta, trace=trace.meta_block())
            t_resp0 = time.perf_counter()
            want_npz = ("npz" in q.get("format", [""])[0]
                        or "npz" in (self.headers.get("Accept") or ""))
            data, out_ctype = _encode_outputs(
                model.fetch_names, outs, meta, want_npz)
            self.send_response(200)
            self.send_header("Content-Type", out_ctype)
            self.send_header("Content-Length", str(len(data)))
            if trace is not None:
                self.send_header("traceparent", trace.traceparent())
            self.end_headers()
            self.wfile.write(data)
            if trace is not None:
                t_done = time.perf_counter()
                trace.add_span("respond", tracing.pc_to_epoch(t_resp0),
                               tracing.pc_to_epoch(t_done),
                               bytes=len(data))
                trace.finish(status="ok",
                             t_end=tracing.pc_to_epoch(t_done))
        except RequestError as e:
            if trace is not None:
                trace.finish(status=f"error:client:{e.code}")
            self._send_json(e.code, {"error": str(e)})
        except Overloaded as e:
            # admission control shed: fail fast, tell the client when a
            # retry would realistically be served (queue-latency EWMA).
            # The batcher already closed the trace with the shed reason.
            if trace is not None:
                trace.finish(status=f"rejected:{e.reason}")
            self._send_json(
                429, {"error": str(e), "reason": e.reason,
                      "retry_after_s": round(e.retry_after_s, 4)},
                headers={"Retry-After": e.retry_after_header})
        except Unavailable as e:
            if trace is not None:
                trace.finish(status=f"rejected:{e.reason}")
            hdr = e.retry_after_header
            self._send_json(503, {"error": str(e), "reason": e.reason},
                            headers={"Retry-After": hdr} if hdr else None)
        except Exception as e:  # noqa: BLE001 — a request must not kill serving
            if trace is not None:
                trace.finish(status="error:server")
            try:
                self._send_json(500, {
                    "error": f"{type(e).__name__}: {e}"})
            except OSError:
                pass

    @staticmethod
    def _predict_target(path: str) -> Optional[str]:
        if not path.startswith("/v1/models/"):
            return None
        rest = path[len("/v1/models/"):]
        if rest.endswith(":predict"):
            return rest[:-len(":predict")]
        if rest.endswith("/predict"):
            return rest[:-len("/predict")]
        return None

    @staticmethod
    def _generate_target(path: str) -> Optional[str]:
        if not path.startswith("/v1/models/"):
            return None
        rest = path[len("/v1/models/"):]
        for suffix in (":generate", "/generate"):
            if rest.endswith(suffix):
                return rest[:-len(suffix)]
        return None

    def _do_generate(self, name: str,
                     t_req0: Optional[float] = None) -> None:
        """POST /v1/models/<name>:generate — continuous-batched
        autoregressive generation.  JSON body:
            {"prompt": [token ids...], "max_tokens": N,
             "timeout_s": S}  ->
            {"tokens": [...], "meta": {"ttft_ms", "total_ms", ...}}
        The request joins the model's in-flight decode stream at prefill
        (no retrace, no stall of other sequences) and returns when its
        sequence emits eos or exhausts its token budget."""
        srv = self.server.inference_server
        trace = None
        if t_req0 is None:
            t_req0 = time.perf_counter()
        try:
            gen = srv.generation_model(name)
            if gen is None:
                raise RequestError(
                    404, f"no generation model {name!r} "
                         f"(served: {sorted(srv._gen_models)})")
            trace = tracing.start(
                "generate", name,
                traceparent=self.headers.get("traceparent"),
                t0=tracing.pc_to_epoch(t_req0))
            length = int(self.headers.get("Content-Length", 0))
            if length <= 0:
                raise RequestError(411, "request body required")
            try:
                payload = json.loads(self.rfile.read(length).decode())
            except (ValueError, UnicodeDecodeError) as e:
                raise RequestError(400, f"malformed JSON body: {e}")
            if not isinstance(payload, dict) or "prompt" not in payload:
                raise RequestError(
                    400, 'JSON body must carry a "prompt" id list')
            if trace is not None:
                trace.add_span("parse", tracing.pc_to_epoch(t_req0),
                               tracing.pc_to_epoch(time.perf_counter()),
                               bytes=length)
            try:
                timeout = float(payload.get("timeout_s", 60.0))
            except (TypeError, ValueError):
                raise RequestError(400, '"timeout_s" must be a number')
            try:
                tokens, meta = srv.submit_generate(
                    name, payload["prompt"],
                    max_tokens=payload.get("max_tokens"),
                    timeout=timeout, trace=trace)
            except (TypeError, ValueError) as e:
                raise RequestError(400, str(e))
            except TimeoutError as e:
                raise RequestError(504, str(e))
            if trace is not None:
                meta = dict(meta or {}, trace=trace.meta_block())
            t_resp0 = time.perf_counter()
            body = json.dumps(_json_safe(
                {"tokens": [int(t) for t in tokens],
                 "meta": meta})) + "\n"
            self._send(200, body, "application/json",
                       extra_headers=({"traceparent": trace.traceparent()}
                                      if trace is not None else None))
            if trace is not None:
                t_done = time.perf_counter()
                trace.add_span("respond", tracing.pc_to_epoch(t_resp0),
                               tracing.pc_to_epoch(t_done),
                               bytes=len(body))
                trace.finish(status="ok",
                             t_end=tracing.pc_to_epoch(t_done))
        except RequestError as e:
            if trace is not None:
                trace.finish(status=f"error:client:{e.code}")
            self._send_json(e.code, {"error": str(e)})
        except (Overloaded, Unavailable) as e:
            if trace is not None:
                trace.finish(status=f"rejected:{e.reason}")
            raise
        except Exception:
            # anything else (e.g. BrokenPipeError writing the response)
            # escapes to do_POST's generic 500 path, whose own `trace`
            # local is None — close THIS trace here or it leaks open
            # (never stored, never flight-recorded) until evicted
            if trace is not None:
                trace.finish(status="error:server")
            raise

    def _send_json(self, code: int, body: dict,
                   headers: Optional[dict] = None) -> None:
        self._send(code, json.dumps(_json_safe(body)) + "\n",
                   "application/json", extra_headers=headers)


def enable_compilation_cache() -> bool:
    """Point jax's persistent compilation cache at
    FLAGS.serving_cache_dir so the warmup ladder's XLA compiles are
    reused across server restarts (cold start pays trace+compile once
    per artifact change, not once per process).  Best-effort: an old jax
    or an unsupported backend downgrades to in-process caching only."""
    import os

    from ..flags import FLAGS
    from ..log import vlog, warning

    d = FLAGS.serving_cache_dir
    if not d:
        return False
    try:
        import jax

        os.makedirs(d, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", d)
        # serving compiles are worth persisting even when fast (CPU CI):
        # drop the min-compile-time / min-entry-size skip heuristics
        for opt, val in (("jax_persistent_cache_min_compile_time_secs", 0.0),
                         ("jax_persistent_cache_min_entry_size_bytes", -1)):
            try:
                jax.config.update(opt, val)
            except Exception:  # noqa: BLE001 — older jax: option absent
                pass
        # jax memoizes "cache disabled" at the first compile; a process
        # that compiled anything before this call (warm startup code, an
        # in-process test) must reset the cache singleton to pick the new
        # dir up
        from ..inference import reset_compilation_cache_singleton

        reset_compilation_cache_singleton()
        vlog(1, "serving: persistent compilation cache at %s", d)
        return True
    except Exception as e:  # noqa: BLE001 — never fail startup over caching
        warning("serving: compilation cache disabled (%s: %s)",
                type(e).__name__, e)
        return False


# Hot-serving policy for the static verifier (FLAGS_verify_program):
# planned warmup compiles ALWAYS verify; once any warmup in this process
# completes, the gate drops so cold-signature stragglers (already
# flight-tagged unplanned compiles) reach the trace as fast as possible.
# The flag is process-global, so the did-WE-drop-it bookkeeping is too —
# per-server (or per-model) state would let a second server's warmup, or
# a late add_model, compile unverified while believing the gate was never
# touched.  [0] = a warmup in this process dropped the gate.  The lock
# serializes whole restore->warm->drop sequences: a concurrent add_model
# finishing mid-way through another warmup's ladder would otherwise drop
# the gate under the first warmup's remaining planned compiles.
_VERIFY_DROPPED = [False]
_WARMUP_LOCK = threading.Lock()


def _warmup_verified(warm_fn) -> int:
    """Run warmup compiles with the verify gate restored (if a prior
    warmup dropped it), then drop the gate again once warm.  A warmup
    that warms zero signatures leaves an untouched gate alone — those
    signatures compile (and verify) on first request instead.  The drop
    runs in a finally: a warmup that RAISES after the gate was restored
    must not leave the whole process re-verifying (the hot-serving
    contract) — a first-warmup failure leaves the untouched gate on, as
    the process never got warm."""
    from ..flags import FLAGS

    with _WARMUP_LOCK:
        if _VERIFY_DROPPED[0] and not FLAGS.verify_program:
            FLAGS.verify_program = True
        warmed = 0
        try:
            warmed = warm_fn()
        finally:
            if (warmed or _VERIFY_DROPPED[0]) and FLAGS.verify_program:
                FLAGS.verify_program = False
                if not _VERIFY_DROPPED[0]:
                    _VERIFY_DROPPED[0] = True
                    from ..log import vlog

                    vlog(1, "serving: FLAGS_verify_program off after "
                            "warmup (%d signatures verified)", warmed)
        return warmed


class InferenceServer:
    """Load-many, serve-many: the multi-model production server."""

    def __init__(self, configs=None, host: str = "127.0.0.1",
                 port: int = 0, monitor: bool = True):
        # telemetry goes on BEFORE any model loads: load-time events (a
        # corrupted AOT bundle's inference.aot_bundle_errors counter +
        # flight event) must be counted, not lost to a late flag flip
        if monitor:
            from ..flags import FLAGS

            FLAGS.monitor = True
        self._monitor = monitor
        self.host = host
        self._requested_port = port
        self._models: Dict[str, ServingModel] = {}
        self._batchers: Dict[str, DynamicBatcher] = {}
        # decode-aware generation tier (continuous token-level batching)
        self._gen_models: Dict[str, "GenerationServingModel"] = {}
        self._gen_batchers: Dict[str, "ContinuousBatcher"] = {}
        self._httpd: Optional[_ServingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._started = False
        self._draining = False
        self._drain_reason = ""
        # server-level in-flight accounting: the FLAGS_serving_max_inflight
        # admission cap, and the drain path's "every admitted request has
        # written its response" condition
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        # scheduler-death is flight-recorded once per batcher, not once
        # per health poll
        self._reported_dead: set = set()
        for c in configs or []:
            self.add_model(c)

    # -- model management ------------------------------------------------
    def add_model(self, config: ModelConfig) -> ServingModel:
        if (config.name in self._models
                or config.name in self._gen_models):
            raise ValueError(f"model {config.name!r} already served")
        model = ServingModel(config)
        batcher = DynamicBatcher(model)
        self._models[config.name] = model
        self._batchers[config.name] = batcher
        if self._started:
            batcher.start()
            # a late-added model's planned compiles verify like any other
            _warmup_verified(model.warmup)
        return model

    def add_generation_model(self, model) -> "GenerationServingModel":
        """Serve a GenerationServingModel (serving/generation.py) at
        POST /v1/models/<name>:generate with continuous token-level
        batching.  Accepts a built model or a GenerationConfig."""
        from .generation import (ContinuousBatcher, GenerationConfig,
                                 GenerationServingModel)

        if isinstance(model, GenerationConfig):
            model = GenerationServingModel(model)
            model.init_params()
        if model.name in self._models or model.name in self._gen_models:
            raise ValueError(f"model {model.name!r} already served")
        batcher = ContinuousBatcher(model)
        self._gen_models[model.name] = model
        self._gen_batchers[model.name] = batcher
        if self._started:
            _warmup_verified(model.warmup)
            batcher.start()
        return model

    def model(self, name: str) -> Optional[ServingModel]:
        return self._models.get(name)

    def generation_model(self, name: str):
        return self._gen_models.get(name)

    @property
    def model_names(self) -> List[str]:
        return sorted(self._models) + sorted(self._gen_models)

    def models_info(self) -> List[dict]:
        return ([self._models[n].info() for n in sorted(self._models)]
                + [self._gen_models[n].info()
                   for n in sorted(self._gen_models)])

    # -- lifecycle -------------------------------------------------------
    def start(self, warmup: bool = True) -> int:
        """Boot the serving tier; returns the bound port.  Construction
        already turned FLAGS.monitor on (unless monitor=False) — a serving
        process without its latency histograms and compile counters is
        undebuggable, and the hot-path cost is the PR-1 contract (cheap
        registry writes)."""
        if self._started:
            return self.port
        from ..flags import FLAGS

        self._draining = False
        if self._monitor:
            FLAGS.monitor = True
        enable_compilation_cache()
        for b in self._batchers.values():
            b.start()
        for b in self._gen_batchers.values():
            b.start()
        self._httpd = _ServingHTTPServer(
            (self.host, int(self._requested_port)), ServingHandler)
        self._httpd.inference_server = self
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="paddle-tpu-serving-http", daemon=True)
        self._thread.start()
        self._started = True
        # /health (here AND on a separately-started monitor endpoint)
        # now reports serving readiness distinct from trainer liveness
        mserve.set_readiness_provider(self.readiness)
        if warmup:
            self.warmup()
        from ..log import vlog

        vlog(1, "serving: listening on %s:%d (models: %s)",
             self.host, self.port, ", ".join(self.model_names) or "-")
        return self.port

    def warmup(self) -> int:
        """Pre-compile every model's (precision x bucket) ladder and
        every generation model's prefill+decode pair; with
        FLAGS.serving_cache_dir set the compiles persist across
        restarts.  Returns total signatures warmed."""
        return _warmup_verified(
            lambda: sum(m.warmup() for m in self._models.values())
            + sum(m.warmup() for m in self._gen_models.values()))

    def stop(self, timeout: float = 5.0) -> None:
        for b in self._batchers.values():
            b.stop(timeout=timeout)
        for b in self._gen_batchers.values():
            b.stop(timeout=timeout)
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        if mserve._readiness_provider == self.readiness:
            mserve.set_readiness_provider(None)
        self._started = False

    @property
    def port(self) -> int:
        if self._httpd is None:
            return 0
        return self._httpd.server_address[1]

    # -- serving ---------------------------------------------------------
    def submit(self, name: str, feed, precision: str = "fp32",
               timeout: float = 30.0, trace=None):
        """Programmatic entry (the HTTP handler and in-process callers
        share the same batcher path).  `trace` is the HTTP handler's
        RequestTrace; an in-process caller with tracing on gets a root
        trace of its own (finished here — there is no respond phase)."""
        batcher = self._batchers.get(name)
        if batcher is None:
            raise KeyError(f"no model {name!r} "
                           f"(served: {self.model_names})")
        own_trace = None
        if trace is None:
            trace = own_trace = tracing.start("predict", name)
        if self._draining:
            # server-level rejects are SLO bad events like batcher-level
            # ones — burn rates must not read healthy mid-outage
            _slo_bad(name)
            tracing.reject(trace, "draining")
            raise Unavailable("server draining", reason="draining")
        self._chaos_flood(name, feed, precision)
        self._admit_inflight(batcher.retry_after, trace=trace, model=name)
        try:
            outs, meta = batcher.submit(feed, precision=precision,
                                        timeout=timeout, trace=trace)
        except Exception:
            # in-process root: close it even on paths the batcher never
            # saw (validation 4xx) — idempotent past a batcher finish
            if own_trace is not None:
                own_trace.finish(status="error")
            raise
        finally:
            self._release_inflight()
        if own_trace is not None:
            # no respond phase in-process: finish first so the meta block
            # carries the FULL decomposition (total + unattributed)
            own_trace.finish(status="ok")
            meta = dict(meta, trace=own_trace.meta_block())
        return outs, meta

    def submit_generate(self, name: str, prompt, max_tokens=None,
                        timeout: float = 60.0, trace=None):
        """Programmatic generation entry (the HTTP :generate handler and
        in-process callers share the same continuous batcher)."""
        batcher = self._gen_batchers.get(name)
        if batcher is None:
            raise KeyError(f"no generation model {name!r} "
                           f"(served: {sorted(self._gen_models)})")
        own_trace = None
        if trace is None:
            trace = own_trace = tracing.start("generate", name)
        if self._draining:
            _slo_bad(name)
            tracing.reject(trace, "draining")
            raise Unavailable("server draining", reason="draining")
        self._admit_inflight(batcher.retry_after, trace=trace, model=name)
        try:
            tokens, meta = batcher.submit(prompt, max_tokens=max_tokens,
                                          timeout=timeout, trace=trace)
        except Exception:
            if own_trace is not None:
                own_trace.finish(status="error")
            raise
        finally:
            self._release_inflight()
        if own_trace is not None:
            own_trace.finish(status="ok")
            meta = dict(meta or {}, trace=own_trace.meta_block())
        return tokens, meta

    # -- admission (server-level) ----------------------------------------
    def _admit_inflight(self, retry_after, trace=None,
                        model: Optional[str] = None) -> None:
        """Count one admitted request; at the FLAGS_serving_max_inflight
        cap, shed with 429 instead (Retry-After from the target
        batcher's queue-latency EWMA).  The count always runs (it is the
        drain path's completion condition); only the cap is flag-gated."""
        from ..flags import FLAGS

        cap = FLAGS.serving_max_inflight
        with self._inflight_lock:
            if cap > 0 and self._inflight >= cap:
                shed = True
            else:
                self._inflight += 1
                shed = False
        if shed:
            ra = retry_after()
            _record_shed("serving.inflight_shed_total", "inflight_cap",
                         ra, cap=cap)
            if model is not None:
                _slo_bad(model)
            tracing.reject(trace, "inflight_cap")
            raise Overloaded(
                f"server in-flight cap reached ({cap} admitted)",
                retry_after_s=ra, reason="inflight_cap")

    def _release_inflight(self) -> None:
        with self._inflight_lock:
            self._inflight -= 1

    def _chaos_flood(self, name: str, feed, precision: str) -> None:
        """FLAGS_chaos request-flood: one deterministic burst of
        synthetic duplicate requests piles queue pressure on `name`
        (admission control must shed, not stall).  One flag read when
        chaos is off."""
        from ..testing import chaos

        burst = chaos.serve_flood()
        if not burst:
            return
        batcher = self._batchers[name]

        def _one():
            try:
                batcher.submit(feed, precision=precision, timeout=0.5)
            except Exception:  # noqa: BLE001 — synthetic load, outcome moot
                pass

        for _ in range(burst):
            threading.Thread(target=_one, daemon=True).start()

    # -- graceful drain ---------------------------------------------------
    def drain(self, timeout_s: Optional[float] = None,
              reason: str = "shutdown") -> bool:
        """Graceful drain (the SIGTERM path): flip /health readiness to
        'draining' (load balancers stop sending), reject new requests
        with 503, let in-flight and queued-admitted work complete up to
        FLAGS_serving_drain_timeout_s, then stop the serving tier.
        `reason` lands in the /health body (draining_reason) so a fleet
        router can tell a PLANNED drain (rolling restart: keep the slot,
        re-admit soon) from an unexplained one.  Returns True when every
        admitted request completed inside the budget."""
        from ..flags import FLAGS
        from ..monitor import flight

        if timeout_s is None:
            timeout_s = FLAGS.serving_drain_timeout_s
        self._drain_reason = reason
        self._draining = True
        batchers = (list(self._batchers.values())
                    + list(self._gen_batchers.values()))
        for b in batchers:
            b.begin_drain()
        flight.record("serving.drain", timeout_s=float(timeout_s),
                      models=self.model_names, reason=reason)
        deadline = time.monotonic() + max(0.0, float(timeout_s))
        ok = True
        for b in batchers:
            ok = b.drain(max(0.0, deadline - time.monotonic())) and ok
        # admitted work has left the batchers; wait for handler threads
        # to finish writing responses (the in-flight count spans the
        # whole submit), then a short grace for the final socket writes
        while True:
            with self._inflight_lock:
                n = self._inflight
            if n == 0:
                break
            if time.monotonic() >= deadline:
                ok = False
                break
            time.sleep(0.02)
        time.sleep(0.1)
        # a stuck batch past the budget is ABANDONED (daemon scheduler),
        # not waited out: the drain deadline is the whole point
        self.stop(timeout=max(0.5, deadline - time.monotonic()))
        return ok

    @property
    def draining(self) -> bool:
        return self._draining

    def readiness(self) -> dict:
        models = {
            n: m.readiness_detail()
            for n, m in self._models.items()
        }
        models.update({
            n: m.readiness_detail()
            for n, m in self._gen_models.items()
        })
        all_models = list(self._models.values()) \
            + list(self._gen_models.values())
        ready = bool(all_models) and all(m.ready for m in all_models)
        # chaos probe-flap rides the readiness verdict itself (one flag
        # read when chaos is off): the flapped probe reports not_ready
        # while every model detail still says ready/warming — exactly the
        # flicker a router's eviction hysteresis must ride out
        from ..testing import chaos

        ready = chaos.probe_flap(ready)
        out = {
            "ready": ready,
            "models": models,
        }
        if self._draining:
            out["ready"] = False
            out["draining"] = True
            out["draining_reason"] = self._drain_reason
        # liveness satellite: a dead scheduler thread leaves a healthy-
        # LOOKING server that times out every request — name it so the
        # probe can evict the process
        dead = sorted(
            n for n, b in {**self._batchers, **self._gen_batchers}.items()
            if not b.scheduler_alive)
        if dead:
            out["ready"] = False
            out["scheduler_dead"] = dead
            from ..monitor import flight

            for n in dead:
                if n not in self._reported_dead:
                    self._reported_dead.add(n)
                    flight.record("serving.scheduler_dead", model=n,
                                  fatal=True)
        return out
