"""Numerics telemetry + NaN/Inf origin localization (monitor side of the
check_numerics tier; the graph rewrite lives in analysis/numerics.py).

Three jobs:

  * `publish_step_stats` — the executor hands over each step's packed
    [N, 4] stats tensor(s); summary-level rows become per-param-group
    gauges (`numerics.grad_norm.<group>`, `numerics.weight_norm.<group>`,
    `numerics.update_ratio.<group>` + process-wide aggregates) and amp
    overflow accounting (`amp.overflow.<group>` counters + flight
    events, loss-scale update when dynamic scaling is armed).  The last
    step's rows are kept for postmortems whatever the level.
  * failing-step capture + replay — with FLAGS_check_numerics=locate the
    executor snapshots each run's inputs (feed, pre-donation rw-state
    copies, the folded-in run id) via `note_step_context`; on a watchdog
    nan_loss trip `locate_replay` re-runs THAT step bit-identically
    (same run id -> same step key -> same dropout masks) on a clone
    instrumented with full per-op stats, and names the first op in
    topological order with a non-finite output — the reference
    FLAGS_check_nan_inf verdict, reconstructed after the fact for XLA.
  * postmortem wiring — the locate result rides a flight header provider
    (every dump and unified-trace export carries a "numerics" block),
    `last_locate_result()` feeds the emergency-checkpoint manifest
    (io.py), and tools/trace_report.py renders the "Numerics" section.

Cost: nothing here runs unless the executor saw an instrumented program
or FLAGS_check_numerics=locate armed the capture; every publish is
exception-proof (telemetry must not fail the run).
"""

from __future__ import annotations

import math
import threading
from typing import Any, Dict, List, Optional

import numpy as np

from . import flight as _flight
from . import registry as _registry

# column indices of a stat row (ops/numerics_ops.py STAT_COLUMNS)
_NONFINITE, _ABS_MAX, _ABS_MEAN, _L2 = 0, 1, 2, 3

_lock = threading.Lock()
_last_stats: Optional[dict] = None    # {"level", "rows": [merged row dicts]}
_capture: Optional[dict] = None       # last locate-armed step context
_last_locate: Optional[dict] = None   # last localization verdict
_replaying = False                    # re-entrancy guard for the replay run


def reset() -> None:
    """Test isolation: forget captures, stats, and verdicts."""
    global _last_stats, _capture, _last_locate, _replaying
    with _lock:
        _last_stats = None
        _capture = None
        _last_locate = None
        _replaying = False


# ---------------------------------------------------------------------------
# Row plumbing
# ---------------------------------------------------------------------------


def _combine_axis0(arr: np.ndarray) -> np.ndarray:
    """Collapse a stacked [K, N, 4] stats tensor (run_steps scan slices,
    run_accumulated micro-batches) to [N, 4]: counts add, magnitudes take
    the per-row max over the stacked axis."""
    out = np.empty(arr.shape[1:], dtype=np.float64)
    out[..., _NONFINITE] = arr[..., _NONFINITE].sum(axis=0)
    for c in (_ABS_MAX, _ABS_MEAN, _L2):
        out[..., c] = arr[..., c].max(axis=0)
    return out


def merged_rows(program, stats: Dict[str, Any]) -> List[dict]:
    """Join fetched stats tensors with the program's row metadata into one
    topologically-ordered list of row dicts (meta fields + 'stat')."""
    meta = getattr(program, "_numerics_meta", None)
    if meta is None:
        return []
    rows: List[dict] = []
    for tensor_name, tensor_meta in meta["tensors"].items():
        arr = stats.get(tensor_name)
        if arr is None or not tensor_meta:
            continue
        arr = np.asarray(arr, dtype=np.float64)
        while arr.ndim > 2:
            arr = _combine_axis0(arr)
        if arr.ndim != 2 or arr.shape[0] != len(tensor_meta):
            continue  # shape drifted from meta: refuse to mislabel rows
        for m, row in zip(tensor_meta, arr):
            r = dict(m)
            r["stat"] = {
                "nonfinite": float(row[_NONFINITE]),
                "abs_max": float(row[_ABS_MAX]),
                "abs_mean": float(row[_ABS_MEAN]),
                "l2": float(row[_L2]),
            }
            rows.append(r)
    rows.sort(key=lambda r: r.get("pos", 0))
    return rows


def first_bad_row(rows: List[dict]) -> Optional[dict]:
    """First row (topological order) whose tensor had non-finite elements,
    or a NaN/Inf statistic (an Inf abs_max with a zero non-finite count
    means the value overflowed inside the stat reduction itself)."""
    for r in rows:
        st = r["stat"]
        if st["nonfinite"] > 0 or not all(
                math.isfinite(v) for v in st.values()):
            return r
    return None


def _verdict_from_row(row: dict, step=None, replayed=False) -> dict:
    return {
        "step": step,
        "first_bad_op": f"{row.get('op_type', '?')}"
                        f"@block{row.get('block', 0)}"
                        f":op{row.get('op_index', '?')}",
        "op_type": row.get("op_type"),
        "op_index": row.get("op_index"),
        "block": row.get("block", 0),
        "in_loop": bool(row.get("in_loop")),
        "var": row.get("var"),
        "stat": dict(row["stat"]),
        "replayed": bool(replayed),
    }


# ---------------------------------------------------------------------------
# Summary publication (gauges / overflow accounting)
# ---------------------------------------------------------------------------


def summarize(rows: List[dict]) -> dict:
    """Aggregate summary-level rows into per-param-group training-dynamics
    numbers (pure; hand-checked against numpy in tests)."""
    groups: Dict[str, dict] = {}
    glob = {"grad_norm_sq": 0.0, "nonfinite_rows": 0, "grad_nonfinite": 0.0}
    for r in rows:
        st = r["stat"]
        if st["nonfinite"] > 0:
            glob["nonfinite_rows"] += 1
        kind = r.get("kind", "op")
        if kind not in ("grad", "weight", "update"):
            continue
        g = groups.setdefault(r.get("group", "?"), {
            "grad_norm_sq": 0.0, "weight_norm_sq": 0.0,
            "update_norm_sq": 0.0, "grad_nonfinite": 0.0, "params": 0})
        if kind == "grad":
            g["grad_norm_sq"] += st["l2"] ** 2
            g["grad_nonfinite"] += st["nonfinite"]
            glob["grad_norm_sq"] += st["l2"] ** 2
            glob["grad_nonfinite"] += st["nonfinite"]
            g["params"] += 1
        elif kind == "weight":
            g["weight_norm_sq"] += st["l2"] ** 2
        elif kind == "update":
            g["update_norm_sq"] += st["l2"] ** 2
    out = {"groups": {}, "grad_norm": math.sqrt(glob["grad_norm_sq"]),
           "grad_nonfinite": glob["grad_nonfinite"],
           "nonfinite_rows": glob["nonfinite_rows"]}
    for name, g in groups.items():
        wn = math.sqrt(g["weight_norm_sq"])
        un = math.sqrt(g["update_norm_sq"])
        out["groups"][name] = {
            "grad_norm": math.sqrt(g["grad_norm_sq"]),
            "weight_norm": wn,
            "update_norm": un,
            "update_ratio": (un / wn) if wn > 0 else 0.0,
            "grad_nonfinite": g["grad_nonfinite"],
            "params": g["params"],
        }
    return out


def publish_step_stats(program, stats: Dict[str, Any]) -> None:
    """Executor hand-off: one call per run with the fetched stats tensors
    ({tensor_name: array}).  Never raises."""
    global _last_stats
    try:
        rows = merged_rows(program, stats)
        if not rows:
            return
        meta = getattr(program, "_numerics_meta", None) or {}
        level = meta.get("level", "summary")
        with _lock:
            _last_stats = {"level": level, "rows": rows}
        if level != "summary" or not _registry.enabled():
            return
        summ = summarize(rows)
        gauge = _registry.default_registry().gauge
        gauge("numerics.grad_norm").set(summ["grad_norm"])
        gauge("numerics.nonfinite_rows").set(summ["nonfinite_rows"])
        for gname, g in summ["groups"].items():
            gauge(f"numerics.grad_norm.{gname}").set(g["grad_norm"])
            gauge(f"numerics.weight_norm.{gname}").set(g["weight_norm"])
            gauge(f"numerics.update_ratio.{gname}").set(g["update_ratio"])
        _flight.record("numerics.summary",
                       grad_norm=round(summ["grad_norm"], 6),
                       grad_nonfinite=summ["grad_nonfinite"],
                       nonfinite_rows=summ["nonfinite_rows"],
                       groups=len(summ["groups"]))
        _publish_overflow(program, summ, rows)
    except Exception:  # pragma: no cover - telemetry must not fail the run
        pass


def _publish_overflow(program, summ: dict, rows: List[dict]) -> None:
    """amp satellite: named overflow counters + flight events per param
    group (inf/nan in low-precision grads was previously silently
    absorbed), and the dynamic loss-scale update/gauge when armed."""
    from .. import amp as _amp

    scaler = _amp.active_loss_scaler()
    if not (_amp.is_enabled(program) or scaler is not None):
        return
    found = False
    for gname, g in summ["groups"].items():
        if g["grad_nonfinite"] > 0:
            found = True
            _registry.default_registry().counter(
                f"amp.overflow.{gname}").inc()
            worst = max(
                (r for r in rows
                 if r.get("kind") == "grad" and r.get("group") == gname),
                key=lambda r: r["stat"]["nonfinite"])
            _flight.record("amp.overflow", group=gname,
                           param=worst.get("param"),
                           nonfinite=worst["stat"]["nonfinite"])
    if scaler is not None:
        scaler.update(found)


# ---------------------------------------------------------------------------
# Locate: failing-step capture + deterministic replay
# ---------------------------------------------------------------------------


def capture_armed() -> bool:
    """Whether executors should snapshot step contexts (one flag read)."""
    if _replaying:
        return False
    from ..flags import FLAGS

    return FLAGS.check_numerics == "locate"


def note_step_context(ctx: dict) -> None:
    """Executor hand-off (locate mode): the just-dispatched step's replay
    context — program/feed/fetch refs, PRE-donation copies of the rw
    state, and the run id folded into the step key.  Only the latest
    step is kept (the failing step is by definition the last one)."""
    global _capture
    if _replaying:
        return
    with _lock:
        _capture = ctx


def last_capture() -> Optional[dict]:
    return _capture


def locate_replay(step: Optional[int] = None) -> Optional[dict]:
    """Replay the captured step on a fully-instrumented clone and name
    the first op (topological order) with a non-finite output.  Returns
    the verdict dict (also stored for header/manifest consumers), or
    None without a capture."""
    global _replaying, _last_locate
    ctx = _capture
    if ctx is None:
        return None
    from ..analysis import numerics as _anum
    from ..core import executor as _ex

    prog = ctx["program"].clone()
    report = _anum.instrument_program(prog, "locate")
    scope = _ex.Scope()
    for n, v in ctx["state"].items():
        scope.set_var(n, v)
    exe = ctx["executor"]
    _replaying = True
    try:
        exe._forced_run_id = ctx["run_id"]
        try:
            outs = exe.run(prog, feed=dict(ctx["feed"]),
                           fetch_list=list(prog._numerics_stats_vars),
                           scope=scope)
        finally:
            exe._forced_run_id = None
    finally:
        _replaying = False
    stats = dict(zip(prog._numerics_stats_vars, outs))
    rows = merged_rows(prog, stats)
    bad = first_bad_row(rows)
    if bad is None:
        verdict = {"step": step, "first_bad_op": None, "replayed": True,
                   "rows_checked": len(rows),
                   "note": "replay found no non-finite op output"}
    else:
        verdict = _verdict_from_row(bad, step=step, replayed=True)
        verdict["rows_checked"] = len(rows)
    verdict["run_id"] = ctx.get("run_id")
    verdict["instrumented_rows"] = report.get("rows")
    with _lock:
        _last_locate = verdict
    if _registry.enabled():
        _registry.default_registry().counter("numerics.locate_replays").inc()
        _flight.record("numerics.locate", **verdict)
    return verdict


def handle_nan_trip(step: Optional[int] = None) -> Optional[dict]:
    """Watchdog hook (monitor/watchdog.py _fire, kind nan_loss): produce
    the best localization available — a bit-identical replay in locate
    mode, or the failing step's already-fetched summary rows otherwise.
    Exception-proof: a broken replay must not mask the trip handling."""
    global _last_locate
    try:
        from ..flags import FLAGS

        level = FLAGS.check_numerics
        if level == "locate" and _capture is not None:
            return locate_replay(step=step)
        if _last_stats is not None:
            bad = first_bad_row(_last_stats["rows"])
            if bad is not None:
                verdict = _verdict_from_row(bad, step=step, replayed=False)
                verdict["rows_checked"] = len(_last_stats["rows"])
                with _lock:
                    _last_locate = verdict
                if _registry.enabled():
                    _flight.record("numerics.locate", **verdict)
                return verdict
    except Exception:  # pragma: no cover - trip handling must not raise
        pass
    return None


def last_locate_result() -> Optional[dict]:
    """The most recent localization verdict (emergency-checkpoint
    manifests and the flight header provider read this)."""
    return _last_locate


def last_summary() -> Optional[dict]:
    """Aggregates of the most recent published stats (None when nothing
    was published)."""
    snap = _last_stats
    if snap is None:
        return None
    return summarize(snap["rows"])


def _header_provider() -> dict:
    """Flight header provider: every dump / unified-trace export carries
    the localization verdict once one exists."""
    if _last_locate is not None:
        return {"numerics": dict(_last_locate)}
    return {}


_flight.add_header_provider(_header_provider)


__all__ = [
    "publish_step_stats",
    "merged_rows",
    "first_bad_row",
    "summarize",
    "last_summary",
    "capture_armed",
    "note_step_context",
    "last_capture",
    "locate_replay",
    "handle_nan_trip",
    "last_locate_result",
    "reset",
]
