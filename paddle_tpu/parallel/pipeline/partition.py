"""Stage partitioner: cut ONE trained Program into per-stage sub-programs.

Generalizes Executor.run_accumulated's prefix/suffix split (fwd+bwd
prefix, Optimize suffix) into an N-segment pipeline form:

  * Forward-role ops are split into N contiguous segments at
    user-annotated cut vars or auto-balanced boundaries.
  * Backward-role ops follow the forward op whose gradient they compute
    (the stage where every forward value they read already lives).
  * Optimize-role ops stay LOCAL to the stage owning their Param — no
    optimizer state ever crosses a stage boundary.
  * Cheap feed-derived subgraphs (attention masks/biases, position ids —
    ops whose transitive inputs are only feeds and constants) are
    REPLICATED into every consuming stage instead of wired across cuts,
    so boundary transfers carry real activations only.

Each stage is emitted as a REAL fw.Program (verifiable by
paddle_tpu.analysis, lintable by tools/graph_lint.py) whose declared
data vars include the activation/grad boundary inputs, plus the
explicit IO contract the scheduler and the verifier's
verify_program_set consume:

  fwd_inputs   activations received from earlier stages
  fwd_outputs  activations later stages (fwd OR bwd) consume
  bwd_inputs   boundary grads received from later stages
  bwd_outputs  boundary grads earlier stages consume
  stash        fwd env names this stage's OWN backward re-reads
               (activation stashing: held per in-flight micro-batch)

Cut-crossing sets (crossing(c) = vars produced at stage <= c consumed at
stage > c) drive the mesh runner's hop-by-hop neighbor wires; the
direct-delivery scheduler uses the per-stage sets above.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from ...core import framework as fw

_GRAD_TOKEN = "@GRAD"


def _is_grad_name(name: str) -> bool:
    return _GRAD_TOKEN in name


def _role(op) -> int:
    return int(op.attrs.get(fw.OpRole.ROLE_ATTR_NAME, fw.OpRole.Forward))


def _is_opt(op) -> bool:
    return bool(_role(op) & fw.OpRole.Optimize)


def _is_bwd(op) -> bool:
    return bool(_role(op) & fw.OpRole.Backward) and not _is_opt(op)


class PipelineStage:
    """One stage's sub-program + boundary contract."""

    def __init__(self, index: int, program: fw.Program):
        self.index = index
        self.program = program
        # op index lists INTO program.global_block().ops, per phase
        self.fwd_idx: List[int] = []
        self.bwd_idx: List[int] = []
        self.opt_idx: List[int] = []
        # boundary IO: [(name, shape, dtype)], deterministic order
        self.fwd_inputs: List[Tuple[str, tuple, str]] = []
        self.fwd_outputs: List[Tuple[str, tuple, str]] = []
        self.bwd_inputs: List[Tuple[str, tuple, str]] = []
        self.bwd_outputs: List[Tuple[str, tuple, str]] = []
        self.feeds: List[str] = []          # data vars this stage reads
        self.bwd_feeds: List[str] = []      # feeds the bwd phase re-reads
        self.stash: List[str] = []          # fwd env names bwd re-reads
        self.owned_params: List[str] = []   # params whose optimizer is local
        self.grad_names: List[str] = []     # grads the local optimizer reads
        self.fetch_candidates: Set[str] = set()

    def fwd_ops(self):
        ops = self.program.global_block().ops
        return [ops[i] for i in self.fwd_idx]

    def bwd_ops(self):
        ops = self.program.global_block().ops
        return [ops[i] for i in self.bwd_idx]

    def opt_ops(self):
        ops = self.program.global_block().ops
        return [ops[i] for i in self.opt_idx]

    def io_summary(self) -> dict:
        """The contract verify_program_set checks (analysis/verifier.py)."""
        return {
            "index": self.index,
            "fwd_inputs": list(self.fwd_inputs),
            "fwd_outputs": list(self.fwd_outputs),
            "bwd_inputs": list(self.bwd_inputs),
            "bwd_outputs": list(self.bwd_outputs),
            "owned_params": list(self.owned_params),
            "program": self.program,
        }


class PipelineStages:
    """The partition result: stages + cut-crossing wire layouts."""

    def __init__(self, source: fw.Program, stages: List[PipelineStage],
                 crossing: List[List[Tuple[str, tuple, str]]],
                 feed_names: List[str]):
        self.source = source
        self.stages = stages
        # crossing[c]: vars flowing over cut c (stage c -> c+1); the bwd
        # wire at cut c carries exactly these vars' cotangents
        self.crossing = crossing
        self.feed_names = feed_names
        self.fetch_owner: Dict[str, Tuple[int, str]] = {}

    @property
    def n_stages(self) -> int:
        return len(self.stages)

    def __iter__(self):
        return iter(self.stages)


def _feed_only_ops(block: fw.Block, opt_start_set: Set[int]) -> Set[int]:
    """Indices of Forward-role ops whose TRANSITIVE inputs are only data
    vars and constants (no param/persistable reads, no randomness, no
    sub-blocks): the replicable mask/bias prologue."""
    from ...core import executor as ex

    cheap_names: Set[str] = set()
    for v in block.vars.values():
        if v.is_data:
            cheap_names.add(v.name)
    cheap_ops: Set[int] = set()
    for i, op in enumerate(block.ops):
        if i in opt_start_set or _is_bwd(op) or _is_opt(op):
            continue
        if op.attrs.get("sub_block") is not None:
            continue
        if ex.op_threads_rng(op):
            continue
        reads = [n for n in op.input_arg_names() if n]
        writes = [n for n in op.output_arg_names() if n]
        if any(n not in cheap_names for n in reads):
            continue
        if any(block._find_var_recursive(n) is not None
               and block._find_var_recursive(n).persistable
               for n in writes):
            continue
        cheap_ops.add(i)
        cheap_names.update(writes)
    return cheap_ops


def _op_cost(block: fw.Block, op, cost: str = "params") -> float:
    """Balance proxy: bytes of Parameter inputs (flop-dominant dots read
    their weights) + 1 so param-free ops still carry weight.

    cost="activations" additionally charges each op its non-persistable
    OUTPUT elements (the per-micro-batch stash the memory planner's
    plan_stages totals per stage) — activation-aware auto-balancing, so
    a stage's share reflects what it must HOLD across the fwd->bwd gap,
    not just the weights it reads.  A -1 batch dim counts as 1 (uniform
    across ops, so the balance is unaffected)."""
    total = 1.0
    for n in op.input_arg_names():
        v = block._find_var_recursive(n) if n else None
        if isinstance(v, fw.Parameter) and v.shape:
            total += float(np.prod([d for d in v.shape if d]))
    if cost == "activations":
        for n in op.output_arg_names():
            v = block._find_var_recursive(n) if n else None
            if v is not None and not v.persistable and v.shape:
                total += float(np.prod([abs(d) if d else 1
                                        for d in v.shape]))
    return total


def _auto_boundaries(block: fw.Block, fwd_ids: List[int],
                     prologue: Set[int], n_stages: int,
                     cost: str = "params") -> List[int]:
    """Greedy prefix-sum balance of fwd op costs into n contiguous
    segments; returns the fwd-op indices (into block.ops) where each new
    stage begins (n_stages - 1 entries)."""
    weighted = [(i, _op_cost(block, block.ops[i], cost)) for i in fwd_ids
                if i not in prologue]
    total = sum(c for _, c in weighted)
    bounds, acc, next_share, s = [], 0.0, total / n_stages, 1
    for i, c in weighted:
        if s < n_stages and acc >= next_share * s and acc + c > next_share * s:
            bounds.append(i)
            s += 1
        acc += c
    while len(bounds) < n_stages - 1:  # degenerate tiny programs
        bounds.append(weighted[-1][0])
    return bounds[:n_stages - 1]


def split_program(
    program: fw.Program,
    feed_names: Sequence[str],
    n_stages: int = 2,
    cut_vars: Optional[Sequence[str]] = None,
    mark_boundaries: bool = True,
    cost: str = "params",
) -> PipelineStages:
    """Partition `program` (a trained global-block program: forward +
    append_backward grads + optimizer.minimize suffix) into `n_stages`
    pipeline stages.

    cut_vars: optional user annotation — n_stages-1 var names; stage s
    ends with the op producing cut_vars[s].  Omitted: auto-balanced on
    `cost` — "params" (parameter-byte, the original proxy) or
    "activations" (params + per-op activation output elements, so
    stages balance what they STASH across the fwd->bwd gap too; cost
    the result precisely with memory.plan_stages).

    mark_boundaries (default on): annotate the SOURCE program's
    boundary-crossing producers with `pipeline_boundary_vars` attrs — the
    executor trace puts an optimization barrier on those values, so XLA
    associates the reductions consuming them identically whether the
    value is in-program (single-program run_accumulated) or a stage
    boundary input.  Without it, XLA CPU fuses producer chains into
    downstream reduces and the two compilations drift by ~1 ulp per step
    (measured: a boundary layer-norm's bias-grad reduce) — the
    association normalization is what makes the pipeline-vs-single-
    program BIT-parity contract assertable.  The mark changes the source
    program's fingerprint (it recompiles once) but not its math.
    """
    block = program.global_block()
    if len(program.blocks) > 1:
        raise ValueError(
            "split_program: control-flow sub-blocks (While/conditional) "
            "cannot be stage-split; pipeline the global block only")
    n_ops = len(block.ops)
    opt_ids = [i for i in range(n_ops) if _is_opt(block.ops[i])]
    if not opt_ids:
        raise ValueError(
            "split_program: program has no Optimize-role ops (call "
            "optimizer.minimize first) — pipeline stages keep each "
            "param's optimizer local, so the suffix must exist")
    bwd_ids = [i for i in range(n_ops) if _is_bwd(block.ops[i])]
    fwd_ids = [i for i in range(n_ops)
               if not _is_bwd(block.ops[i]) and not _is_opt(block.ops[i])]

    feed_set = set(feed_names)
    prologue = _feed_only_ops(block, set(opt_ids))

    # ---- forward stage assignment --------------------------------------
    if cut_vars is not None:
        if len(cut_vars) != n_stages - 1:
            raise ValueError(
                f"split_program: {n_stages} stages need {n_stages - 1} "
                f"cut vars, got {len(cut_vars)}")
        stage_of_fwd: Dict[int, int] = {}
        cur, cut_list = 0, list(cut_vars)
        for i in fwd_ids:
            stage_of_fwd[i] = cur
            if cur < len(cut_list) and cut_list[cur] in set(
                    block.ops[i].output_arg_names()):
                cur += 1
        if cur != n_stages - 1:
            missing = cut_list[cur:]
            raise ValueError(
                f"split_program: cut var(s) {missing} produced by no "
                f"forward op — annotate real activation names")
    else:
        bounds = _auto_boundaries(block, fwd_ids, prologue, n_stages, cost)
        stage_of_fwd = {}
        for i in fwd_ids:
            stage_of_fwd[i] = sum(1 for b in bounds if i >= b)

    # producer map over fwd ops (last writer wins, program order)
    producer: Dict[str, int] = {}
    for i in fwd_ids:
        if i in prologue:
            continue
        for n in block.ops[i].output_arg_names():
            if n:
                producer[n] = stage_of_fwd[i]
    prologue_outputs: Set[str] = set()
    for i in prologue:
        prologue_outputs.update(
            n for n in block.ops[i].output_arg_names() if n)

    # param/persistable ownership: min consuming fwd stage (the
    # optimizer op for that param lands there)
    param_owner: Dict[str, int] = {}
    for i in fwd_ids:
        if i in prologue:
            continue
        for n in block.ops[i].input_arg_names():
            if not n or n in feed_set or n in producer \
                    or n in prologue_outputs:
                continue
            v = block._find_var_recursive(n)
            if v is not None and v.persistable:
                s = stage_of_fwd[i]
                param_owner[n] = min(param_owner.get(n, s), s)

    def _value_stage(name: str) -> Optional[int]:
        """Stage where a FORWARD value is produced/available (None for
        feeds and prologue values — available to every stage)."""
        if name in producer:
            return producer[name]
        if name in param_owner:
            return param_owner[name]
        return None

    # ---- backward stage assignment -------------------------------------
    # rule 1: max producer stage over the op's forward-value inputs (a
    # grad op reads its fwd op's inputs AND outputs, so this lands it on
    # the fwd op's own stage); rule 2 (pure grad plumbing — the sum/
    # assign combines, the loss-grad fill): the stage producing the base
    # var of its @GRAD output.
    stage_of_bwd: Dict[int, int] = {}
    for i in bwd_ids:
        op = block.ops[i]
        cands = []
        for n in op.input_arg_names():
            if n and not _is_grad_name(n):
                s = _value_stage(n)
                if s is not None:
                    cands.append(s)
        if not cands:
            for n in op.output_arg_names():
                if n and _is_grad_name(n):
                    base = n.split(_GRAD_TOKEN)[0]
                    s = _value_stage(base)
                    if s is not None:
                        cands.append(s)
                    elif base == "":  # loss-grad fill names <loss>@GRAD
                        continue
        stage_of_bwd[i] = max(cands) if cands else n_stages - 1

    # Grad routing must be POSITION-aware: the IR accumulates
    # multi-consumer grads in place (the first consumer writes the
    # canonical <v>@GRAD, later consumers write @RENAME partials, and
    # the materialize `sum` re-writes the canonical name at the
    # producer's stage) — so "who produced the grad this op reads" is
    # the last writer BEFORE the op, not the last writer overall.
    grad_writer_stage: Dict[str, int] = {}
    bwd_read_src: Dict[int, Dict[str, int]] = {}
    for i in bwd_ids:
        srcs = {}
        for n in block.ops[i].input_arg_names():
            if n and _is_grad_name(n) and n in grad_writer_stage:
                srcs[n] = grad_writer_stage[n]
        bwd_read_src[i] = srcs
        for n in block.ops[i].output_arg_names():
            if n:
                grad_writer_stage[n] = stage_of_bwd[i]
    # final-writer map (the canonical materialized grads the optimizer
    # reads): used for opt placement fallbacks only
    grad_producer: Dict[str, int] = dict(grad_writer_stage)

    # ---- optimizer stage assignment ------------------------------------
    stage_of_opt: Dict[int, int] = {}
    for i in opt_ids:
        op = block.ops[i]
        pnames = op.inputs.get("Param", [])
        if pnames and pnames[0]:
            p = pnames[0]
            if p not in param_owner:
                # param read by no fwd op (frozen head etc.): keep its
                # update with its grad producer, else the last stage
                gname = op.inputs.get("Grad", [""])[0]
                param_owner[p] = grad_producer.get(gname, n_stages - 1)
            stage_of_opt[i] = param_owner[p]
        else:
            # param-less suffix op (global counters, shared lr chains):
            # stage-local duplication would double-apply persistable
            # writes — refuse loudly rather than corrupt state
            writes_state = any(
                block._find_var_recursive(n) is not None
                and block._find_var_recursive(n).persistable
                for n in op.output_arg_names() if n)
            if writes_state:
                raise NotImplementedError(
                    f"split_program: Optimize-role op {op.type!r} has no "
                    f"Param input but writes persistable state — a "
                    f"global optimizer accumulator cannot be made "
                    f"stage-local (cut the program differently or fold "
                    f"the update into a per-param op)")
            stage_of_opt[i] = n_stages - 1

    # ---- per-stage op sets (prologue replicated on demand) -------------
    fwd_by_stage: List[List[int]] = [[] for _ in range(n_stages)]
    for i in fwd_ids:
        if i not in prologue:
            fwd_by_stage[stage_of_fwd[i]].append(i)
    bwd_by_stage: List[List[int]] = [[] for _ in range(n_stages)]
    for i in bwd_ids:
        bwd_by_stage[stage_of_bwd[i]].append(i)
    opt_by_stage: List[List[int]] = [[] for _ in range(n_stages)]
    for i in opt_ids:
        opt_by_stage[stage_of_opt[i]].append(i)

    # prologue replication: closure of prologue ops whose outputs a
    # stage's (fwd or bwd or opt) ops read
    prologue_list = sorted(prologue)
    prologue_producer = {}
    for i in prologue_list:
        for n in block.ops[i].output_arg_names():
            if n:
                prologue_producer[n] = i

    def _prologue_for(op_ids: List[int]) -> List[int]:
        needed: Set[int] = set()
        frontier = [n for i in op_ids
                    for n in block.ops[i].input_arg_names()
                    if n in prologue_producer]
        while frontier:
            n = frontier.pop()
            i = prologue_producer[n]
            if i in needed:
                continue
            needed.add(i)
            frontier.extend(m for m in block.ops[i].input_arg_names()
                            if m in prologue_producer)
        return sorted(needed)

    # ---- boundary IO ----------------------------------------------------
    def _var_sig(name: str) -> Tuple[str, tuple, str]:
        v = block._find_var_recursive(name)
        shape = tuple(v.shape) if v is not None and v.shape else ()
        dtype = v.dtype if v is not None else "float32"
        return (name, shape, dtype)

    fwd_in: List[Set[str]] = [set() for _ in range(n_stages)]
    fwd_out: List[Set[str]] = [set() for _ in range(n_stages)]
    bwd_in: List[Set[str]] = [set() for _ in range(n_stages)]
    bwd_out: List[Set[str]] = [set() for _ in range(n_stages)]
    feeds_per_stage: List[Set[str]] = [set() for _ in range(n_stages)]
    bwd_feeds: List[Set[str]] = [set() for _ in range(n_stages)]
    stash_per_stage: List[Set[str]] = [set() for _ in range(n_stages)]

    for s in range(n_stages):
        own_fwd = set(fwd_by_stage[s])
        own_prologue = set(_prologue_for(
            fwd_by_stage[s] + bwd_by_stage[s] + opt_by_stage[s]))
        produced_here: Set[str] = set()
        for i in sorted(own_fwd | own_prologue):
            produced_here.update(
                n for n in block.ops[i].output_arg_names() if n)
        # fwd reads
        for i in fwd_by_stage[s]:
            for n in block.ops[i].input_arg_names():
                if not n or n in produced_here or n in feed_set:
                    if n in feed_set:
                        feeds_per_stage[s].add(n)
                    continue
                ps = _value_stage(n)
                if ps is not None and ps < s:
                    fwd_in[s].add(n)
                    fwd_out[ps].add(n)
                # ps == s or persistable state: scope-resident, local
        # bwd reads: fwd values -> stash or boundary; grads -> boundary
        for i in bwd_by_stage[s]:
            for n in block.ops[i].input_arg_names():
                if not n:
                    continue
                if _is_grad_name(n):
                    gp = bwd_read_src[i].get(n)
                    if gp is not None and gp > s:
                        bwd_in[s].add(n)
                        bwd_out[gp].add(n)
                    continue
                if n in feed_set:
                    bwd_feeds[s].add(n)
                    continue
                if n in prologue_outputs or n in produced_here \
                        or _value_stage(n) == s:
                    if n in produced_here or n in prologue_outputs:
                        stash_per_stage[s].add(n)
                    continue
                ps = _value_stage(n)
                if ps is not None and ps < s:
                    # fwd value from an earlier stage, read only by THIS
                    # stage's bwd: it still crosses the fwd wire and is
                    # stashed here with the rest of the fwd env
                    fwd_in[s].add(n)
                    fwd_out[ps].add(n)
                    stash_per_stage[s].add(n)
        # every fwd boundary input the bwd re-reads is stash too
        for i in bwd_by_stage[s]:
            for n in block.ops[i].input_arg_names():
                if n in fwd_in[s]:
                    stash_per_stage[s].add(n)
        # opt reads (grads produced by own bwd by construction; anything
        # else is a contract violation verify_program_set names)
        for i in opt_by_stage[s]:
            for n in block.ops[i].inputs.get("Grad", []):
                if n:
                    gp = grad_producer.get(n)
                    if gp is not None and gp != s:
                        bwd_in[s].add(n)
                        bwd_out[gp].add(n)

    # cut-crossing wires for the mesh runner: crossing(c) = fwd values
    # produced at stage <= c consumed (fwd or bwd) at stage > c
    crossing: List[List[Tuple[str, tuple, str]]] = []
    for c in range(n_stages - 1):
        names = sorted({
            n
            for s2 in range(c + 1, n_stages)
            for n in fwd_in[s2]
            if _value_stage(n) is not None and _value_stage(n) <= c
        })
        crossing.append([_var_sig(n) for n in names])

    # ---- boundary association normalization ----------------------------
    # (must precede the stage-program build so copied ops carry the mark)
    if mark_boundaries:
        crossing_names: Set[str] = set()
        for s in range(n_stages):
            crossing_names |= fwd_in[s] | bwd_in[s]
        marked = False
        for op in block.ops:
            here = [n for n in op.output_arg_names()
                    if n in crossing_names]
            if here:
                prev = set(op.attrs.get("pipeline_boundary_vars", ()))
                merged = prev | set(here)
                if merged != prev:
                    op.attrs["pipeline_boundary_vars"] = sorted(merged)
                    marked = True
        if marked:
            block._bump()

    # ---- build per-stage programs --------------------------------------
    stages: List[PipelineStage] = []
    for s in range(n_stages):
        sp = fw.Program()
        sp.random_seed = program.random_seed
        sp._is_test = getattr(program, "_is_test", False)
        sp._amp_bf16 = bool(getattr(program, "_amp_bf16", False))
        blk = sp.global_block()
        st = PipelineStage(s, sp)

        op_ids = (_prologue_for(fwd_by_stage[s] + bwd_by_stage[s]
                                + opt_by_stage[s])
                  + fwd_by_stage[s] + bwd_by_stage[s] + opt_by_stage[s])
        # declare every referenced var first (copies — the stage program
        # must not alias the source IR's mutable Variable objects)
        boundary_ins = fwd_in[s] | bwd_in[s]
        referenced: List[str] = []
        seen: Set[str] = set()
        for i in op_ids:
            for n in (block.ops[i].input_arg_names()
                      + block.ops[i].output_arg_names()):
                if n and n not in seen:
                    seen.add(n)
                    referenced.append(n)
        for n in referenced:
            v = block._find_var_recursive(n)
            is_param = isinstance(v, fw.Parameter)
            kw = dict(
                shape=(list(v.shape) if v is not None and v.shape is not None
                       else None),
                dtype=v.dtype if v is not None else "float32",
                persistable=bool(v is not None and v.persistable),
                stop_gradient=bool(v is None or v.stop_gradient),
                is_data=bool(n in boundary_ins
                             or (v is not None and v.is_data)),
            )
            if is_param:
                nv = fw.Parameter(blk, n, kw["shape"], kw["dtype"],
                                  trainable=getattr(v, "trainable", True))
                blk.vars[n] = nv
            else:
                blk.create_var(name=n, **kw)
        n_pro = len(_prologue_for(fwd_by_stage[s] + bwd_by_stage[s]
                                  + opt_by_stage[s]))
        for j, i in enumerate(op_ids):
            op = block.ops[i]
            blk.append_op(op.type, {k: list(v) for k, v in op.inputs.items()},
                          {k: list(v) for k, v in op.outputs.items()},
                          dict(op.attrs))
            if j < n_pro or i in fwd_by_stage[s]:
                # replicated prologue executes with the fwd phase
                st.fwd_idx.append(j)
            elif i in stage_of_bwd and stage_of_bwd.get(i) == s \
                    and _is_bwd(op):
                st.bwd_idx.append(j)
            else:
                st.opt_idx.append(j)

        st.fwd_inputs = [_var_sig(n) for n in sorted(fwd_in[s])]
        st.fwd_outputs = [_var_sig(n) for n in sorted(fwd_out[s])]
        st.bwd_inputs = [_var_sig(n) for n in sorted(bwd_in[s])]
        st.bwd_outputs = [_var_sig(n) for n in sorted(bwd_out[s])]
        st.feeds = sorted(feeds_per_stage[s]
                          | {n for i in _prologue_for(
                              fwd_by_stage[s] + bwd_by_stage[s]
                              + opt_by_stage[s])
                             for n in block.ops[i].input_arg_names()
                             if n in feed_set})
        st.bwd_feeds = sorted(bwd_feeds[s])
        st.stash = sorted(stash_per_stage[s])
        st.owned_params = sorted(
            p for p, o in param_owner.items() if o == s
            and isinstance(block._find_var_recursive(p), fw.Parameter))
        st.grad_names = sorted({
            n for i in opt_by_stage[s]
            for n in block.ops[i].inputs.get("Grad", []) if n
        })
        st.fetch_candidates = {
            n for i in fwd_by_stage[s]
            for n in block.ops[i].output_arg_names() if n
        }
        stages.append(st)

    result = PipelineStages(program, stages, crossing,
                            list(feed_names))
    for s, st in enumerate(stages):
        for n in st.fetch_candidates:
            result.fetch_owner[n] = (s, "fwd")
    return result
