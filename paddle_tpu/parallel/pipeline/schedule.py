"""Micro-batch schedules: per-tick GPipe / 1F1B event tables.

One generator feeds BOTH execution paths: the host scheduler
(trainer.py) walks the table tick by tick, and the mesh runner (mesh.py)
lowers it to constant per-tick [n_stages] micro-batch index arrays the
SPMD program indexes by pipe rank.  Tables are dependency-validated at
build time (validate_schedule) — an invalid schedule is a named error,
not silent numeric drift.

Both schedules run every phase the same number of times in the same
per-stage micro-batch ORDER (fwd 0..K-1, bwd 0..K-1), so loss/grad
accumulation is bit-identical between them and to run_accumulated; they
differ only in interleaving — GPipe stashes up to K micro-batches at the
first stage, 1F1B caps the stash at the stage's warmup depth
(min(K, n_stages - stage)).
"""

from __future__ import annotations

import functools
from typing import Dict, List, Tuple

SCHEDULES = ("gpipe", "1f1b")

# one tick's work for one stage: ("fwd"|"bwd", micro_batch) — at most one
# fwd and one bwd per (tick, stage)
Tick = List[Tuple[int, str, int]]  # [(stage, phase, mb), ...]


def _action_sequences(n_stages: int, n_micro: int, kind: str
                      ) -> List[List[Tuple[str, int]]]:
    """Per-stage action list [(phase, mb), ...] in issue order."""
    seqs = []
    for s in range(n_stages):
        if kind == "gpipe":
            seq = ([("fwd", m) for m in range(n_micro)]
                   + [("bwd", m) for m in range(n_micro)])
        elif kind == "1f1b":
            # PipeDream-Flush / Megatron non-interleaved 1F1B: warmup
            # fwds, steady-state one-forward-one-backward, cooldown bwds
            warmup = min(n_micro, n_stages - s)
            seq = [("fwd", m) for m in range(warmup)]
            f, b = warmup, 0
            while b < n_micro:
                seq.append(("bwd", b))
                b += 1
                if f < n_micro:
                    seq.append(("fwd", f))
                    f += 1
        else:
            raise ValueError(f"unknown schedule {kind!r}; one of {SCHEDULES}")
        seqs.append(seq)
    return seqs


@functools.lru_cache(maxsize=128)
def schedule_table(n_stages: int, n_micro: int, kind: str = "gpipe"
                   ) -> List[Tick]:
    """Greedy dependency-respecting tick simulation: each tick, every
    stage issues its next pending action if its dependencies completed
    at a STRICTLY earlier tick (so within-tick order is free):

      fwd(s, m):  needs fwd(s-1, m)
      bwd(s, m):  needs fwd(s, m); and bwd(s+1, m) unless s is last

    Memoized per (S, K, kind) — the trainer walks it every step; treat
    the returned table as read-only.
    """
    seqs = _action_sequences(n_stages, n_micro, kind)
    pos = [0] * n_stages
    done: Dict[Tuple[str, int, int], int] = {}  # (phase, s, m) -> tick
    ticks: List[Tick] = []
    t = 0
    guard = 8 * n_stages * n_micro + 16
    while any(pos[s] < len(seqs[s]) for s in range(n_stages)):
        tick: Tick = []
        for s in range(n_stages):
            if pos[s] >= len(seqs[s]):
                continue
            phase, m = seqs[s][pos[s]]
            if phase == "fwd":
                ready = s == 0 or done.get(("fwd", s - 1, m), t) < t
            else:
                ready = done.get(("fwd", s, m), t) < t and (
                    s == n_stages - 1
                    or done.get(("bwd", s + 1, m), t) < t)
            if ready:
                tick.append((s, phase, m))
        for s, phase, m in tick:
            done[(phase, s, m)] = t
            pos[s] += 1
        ticks.append(tick)
        t += 1
        if t > guard:  # a schedule bug must fail loudly, never spin
            raise RuntimeError(
                f"schedule_table({n_stages}, {n_micro}, {kind!r}): no "
                f"progress after {t} ticks — dependency deadlock")
    return ticks


def validate_schedule(n_stages: int, n_micro: int, kind: str) -> List[str]:
    """Named violations in the generated table (empty = valid): every
    (phase, stage, mb) exactly once, fwd per stage in mb order, all
    dependencies strictly earlier.  graph_lint's pipeline entry runs
    this for every (pp, schedule) it covers."""
    problems: List[str] = []
    ticks = schedule_table(n_stages, n_micro, kind)
    at: Dict[Tuple[str, int, int], int] = {}
    for t, tick in enumerate(ticks):
        for s, phase, m in tick:
            key = (phase, s, m)
            if key in at:
                problems.append(f"{key} issued twice (ticks {at[key]},{t})")
            at[key] = t
    for s in range(n_stages):
        for phase in ("fwd", "bwd"):
            mbs = sorted(
                (t, m) for (p, st, m), t in at.items()
                if p == phase and st == s)
            order = [m for _, m in mbs]
            if order != list(range(n_micro)):
                problems.append(
                    f"stage {s} {phase} order {order} != 0..{n_micro - 1} "
                    f"(grad accumulation order would drift)")
    for (phase, s, m), t in at.items():
        if phase == "fwd" and s > 0:
            dep = at.get(("fwd", s - 1, m))
            if dep is None or dep >= t:
                problems.append(f"fwd({s},{m})@{t} before fwd({s - 1},{m})")
        if phase == "bwd":
            dep = at.get(("fwd", s, m))
            if dep is None or dep >= t:
                problems.append(f"bwd({s},{m})@{t} before fwd({s},{m})")
            if s < n_stages - 1:
                dep = at.get(("bwd", s + 1, m))
                if dep is None or dep >= t:
                    problems.append(
                        f"bwd({s},{m})@{t} before bwd({s + 1},{m})")
    return problems


def bubble_fraction(n_stages: int, n_micro: int, kind: str = "gpipe"
                    ) -> float:
    """Measured idle fraction of the generated table: 1 - busy slots /
    (ticks * stages).  For both schedules this lands on the analytic
    GPipe bubble (S-1)/(K+S-1) when fwd and bwd cost one tick each —
    1F1B buys MEMORY (bounded stash), not bubble, in its non-interleaved
    form."""
    ticks = schedule_table(n_stages, n_micro, kind)
    busy = sum(len(t) for t in ticks)
    return 1.0 - busy / float(len(ticks) * n_stages)


def max_in_flight(n_stages: int, n_micro: int, kind: str = "gpipe") -> int:
    """Peak stashed micro-batches on any stage (fwd done, bwd pending) —
    the activation-memory high-water mark the schedules trade on."""
    ticks = schedule_table(n_stages, n_micro, kind)
    stash = [0] * n_stages
    peak = 0
    for tick in ticks:
        for s, phase, _ in tick:
            stash[s] += 1 if phase == "fwd" else -1
        peak = max(peak, max(stash))
    return peak
