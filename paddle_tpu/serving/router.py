"""Fleet front-end: health-driven request routing across N InferenceServer
replicas (the robustness half of ROADMAP item 3 — the Fluid distributed
runtime's client/server split, rebuilt as a modern scale-out serving tier).

One process on one chip is a total outage waiting to happen; the per-replica
contracts already exist (ISSUE 13: admission control, graceful SIGTERM
drain, breakers, scheduler-death health) and the cross-process signals too
(ISSUE 14: traceparent propagation, SLO burn-rate gauges).  This module is
the part that turns N independently-mortal replicas into one durable
endpoint:

  * Health-driven rotation — a probe thread polls each replica's /health
    every FLAGS_router_probe_interval_s and drives a per-replica state
    machine: in_rotation / warming (alive, ladder still compiling — poll
    again, do NOT evict) / draining (planned exit: stop sending, keep the
    slot) / evicted (scheduler_dead, stalled, or
    FLAGS_router_evict_failures consecutive probe failures).  A single
    passing probe re-admits.  Evictions and re-admissions are flight
    events (`router.evict` / `router.readmit`).
  * Least-inflight balancing with SLO awareness — effective load is
    inflight + FLAGS_router_slo_weight x the replica's worst
    slo_burn_rate_5m gauge (scraped alongside the probe), steering
    traffic away from replicas burning error budget before they fail.
  * Deadline-budgeted retry-with-failover — connect errors, 5xx, and 429
    fail over to a different replica with jittered backoff
    (utils/retry.backoff_delays with deadline_s = the request's own
    timeout_s), so the router NEVER sleeps a request past its deadline.
    Predict is idempotent and retries freely; generation fails over only
    when no response was received (connect error) or the replica rejected
    it before admission (429/503) — never after tokens may have flowed.
  * Tail-latency hedging (FLAGS_router_hedge_ms) — a predict that has no
    response after the hedge delay fires a second attempt at a different
    replica; first response wins, the loser's connection is torn down.
  * Traceparent propagation — the client's W3C traceparent header rides
    through to the replica and the replica's response header rides back,
    so ISSUE-14 traces span client -> router -> replica.

The router holds no model state and imports no jax: it is pure stdlib
HTTP (same MonitorHandler base as the monitor endpoint, so /metrics,
/flight, and /v1/replicas come for free).  Zero-cost contract: nothing
here is imported by the single-replica serving path.
"""

from __future__ import annotations

import http.client
import json
import queue
import threading
import time
from typing import Dict, List, Optional, Tuple
from urllib.parse import urlparse

from ..flags import FLAGS
from ..monitor import serve as mserve
from ..utils.retry import backoff_delays

# replica states (gauge encoding: router.replica.<rid>.state)
IN_ROTATION = "in_rotation"
WARMING = "warming"
DRAINING = "draining"
EVICTED = "evicted"
_STATE_CODE = {IN_ROTATION: 0, WARMING: 1, DRAINING: 2, EVICTED: 3}

# response statuses that justify sending a predict elsewhere; generation
# retries only the pre-admission rejections (429/503) — a 5xx may have
# consumed tokens
_RETRY_PREDICT = frozenset({429}) | frozenset(range(500, 600))
_RETRY_GENERATE = frozenset({429, 503})

# request headers forwarded replica-ward; response headers forwarded back
_FWD_REQ_HEADERS = ("Content-Type", "Accept", "traceparent")
_FWD_RESP_HEADERS = ("Content-Type", "Retry-After", "traceparent")


class _ConnectError(Exception):
    """The attempt never produced an HTTP response (dead socket, refused
    connection, timeout before status line) — always safe to fail over."""


class Replica:
    """One backend InferenceServer as the router sees it."""

    def __init__(self, rid: str, host: str, port: int):
        self.rid = rid
        self.host = host
        self.port = int(port)
        self.state = WARMING  # nothing enters rotation unprobed
        self.inflight = 0
        self.consec_fail = 0
        self.probe_latency_ms = 0.0
        self.slo_burn = 0.0
        self.last_status: Optional[str] = None
        self.detail: dict = {}

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def snapshot(self) -> dict:
        return {
            "rid": self.rid,
            "url": self.url,
            "state": self.state,
            "inflight": self.inflight,
            "consec_fail": self.consec_fail,
            "probe_latency_ms": round(self.probe_latency_ms, 3),
            "slo_burn": self.slo_burn,
            "health_status": self.last_status,
            "detail": self.detail,
        }


class _RouterHTTPServer(mserve.ThreadingHTTPServer):
    daemon_threads = True
    router: "Router" = None


class RouterHandler(mserve.MonitorHandler):
    """/v1/models/<name>:predict|:generate proxy + /v1/replicas fleet
    introspection; /metrics //health //flight inherited (they report the
    ROUTER process — replica health lives under /v1/replicas)."""

    server_version = "paddle-tpu-router/1.0"

    def _route_get(self, url) -> bool:
        router = self.server.router
        if url.path == "/v1/replicas":
            self._send_json(200, {"replicas": router.replicas_info()})
            return True
        if url.path.startswith("/v1/models"):
            # introspection GETs proxy to any in-rotation replica
            status, headers, body = router.proxy_get(self.path)
            self._respond(status, headers, body)
            return True
        return super()._route_get(url)

    def _send_json(self, code: int, obj, headers=None) -> None:
        self._send(code, json.dumps(obj) + "\n", "application/json",
                   extra_headers=headers)

    def _respond(self, status: int, headers: dict, body: bytes) -> None:
        self.send_response(status)
        ctype = headers.get("Content-Type") or "application/json"
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        for k, v in headers.items():
            if k != "Content-Type" and v:
                self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def do_POST(self):  # noqa: N802 — BaseHTTPRequestHandler contract
        router = self.server.router
        try:
            path = urlparse(self.path).path
            kind = ("generate" if path.endswith((":generate", "/generate"))
                    else "predict" if path.endswith((":predict", "/predict"))
                    else None)
            if kind is None:
                self._send_json(404, {
                    "error": "POST /v1/models/<name>:predict "
                             "(or :generate)"})
                return
            length = int(self.headers.get("Content-Length", 0))
            body = self.rfile.read(length) if length > 0 else b""
            headers = {h: self.headers.get(h) for h in _FWD_REQ_HEADERS
                       if self.headers.get(h)}
            status, resp_headers, resp_body = router.proxy(
                kind, self.path, body, headers)
            self._respond(status, resp_headers, resp_body)
        except Exception as e:  # noqa: BLE001 — a request must not kill routing
            try:
                self._send_json(500, {
                    "error": f"router: {type(e).__name__}: {e}"})
            except OSError:
                pass


class Router:
    """The fleet front-end.  Replicas are registered by the supervisor
    (serving/fleet.py) or by hand (`add_replica`); `start()` boots the
    proxy endpoint and the probe thread."""

    def __init__(self, host: str = "127.0.0.1",
                 port: Optional[int] = None):
        self.host = host
        self._requested_port = (FLAGS.router_port if port is None
                                else port)
        self._replicas: Dict[str, Replica] = {}
        self._lock = threading.Lock()
        self._httpd: Optional[_RouterHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._probe_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._local = threading.local()  # per-thread keep-alive conns

    # -- fleet membership (supervisor API) -------------------------------
    def add_replica(self, host: str, port: int,
                    rid: Optional[str] = None) -> Replica:
        with self._lock:
            if rid is None:
                rid = f"r{len(self._replicas)}"
            rep = Replica(rid, host, port)
            self._replicas[rid] = rep
        # probe immediately so a ready replica does not wait out a full
        # probe interval before taking traffic
        self.probe_now(rid)
        return rep

    def update_replica(self, rid: str, host: str, port: int) -> None:
        """A restarted replica came back on a new ephemeral port: repoint
        the slot and let the next probe re-admit it."""
        with self._lock:
            rep = self._replicas[rid]
            rep.host, rep.port = host, int(port)
            rep.state = WARMING
            rep.consec_fail = 0
        self.probe_now(rid)

    def remove_replica(self, rid: str) -> None:
        with self._lock:
            self._replicas.pop(rid, None)

    def set_draining(self, rid: str) -> None:
        """Planned drain (rolling restart): stop sending BEFORE the
        replica's own /health flips, so zero requests race the SIGTERM."""
        with self._lock:
            rep = self._replicas.get(rid)
            if rep is not None and rep.state != DRAINING:
                self._transition(rep, DRAINING, reason="planned_drain")

    def replicas_info(self) -> List[dict]:
        with self._lock:
            return [self._replicas[rid].snapshot()
                    for rid in sorted(self._replicas)]

    def replica_state(self, rid: str) -> Optional[str]:
        with self._lock:
            rep = self._replicas.get(rid)
            return rep.state if rep is not None else None

    # -- lifecycle -------------------------------------------------------
    def start(self) -> int:
        if self._httpd is not None:
            return self.port
        self._stop.clear()
        self._httpd = _RouterHTTPServer(
            (self.host, int(self._requested_port)), RouterHandler)
        self._httpd.router = self
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="paddle-tpu-router-http", daemon=True)
        self._thread.start()
        self._probe_thread = threading.Thread(
            target=self._probe_loop, name="paddle-tpu-router-probe",
            daemon=True)
        self._probe_thread.start()
        from ..log import vlog

        vlog(1, "router: listening on %s:%d (%d replicas)", self.host,
             self.port, len(self._replicas))
        return self.port

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def stop(self) -> None:
        self._stop.set()
        if self._probe_thread is not None:
            self._probe_thread.join(timeout=5.0)
            self._probe_thread = None
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    # -- health probes ---------------------------------------------------
    def _probe_loop(self) -> None:
        while not self._stop.wait(FLAGS.router_probe_interval_s):
            with self._lock:
                rids = list(self._replicas)
            for rid in rids:
                if self._stop.is_set():
                    return
                self.probe_now(rid)

    def probe_now(self, rid: str) -> None:
        """Probe one replica's /health and apply the state machine."""
        with self._lock:
            rep = self._replicas.get(rid)
        if rep is None:
            return
        t0 = time.perf_counter()
        try:
            status, body = self._http_get(rep, "/health",
                                          FLAGS.router_probe_timeout_s)
            health = json.loads(body)
        except Exception:  # noqa: BLE001 — dead socket, bad JSON: a failure
            health = None
        latency_ms = (time.perf_counter() - t0) * 1e3
        with self._lock:
            rep = self._replicas.get(rid)
            if rep is None:
                return
            rep.probe_latency_ms = latency_ms
            self._apply_probe(rep, health)
        self._publish(rep)

    def _apply_probe(self, rep: Replica, health: Optional[dict]) -> None:
        """State machine (caller holds the lock).  `health` is the parsed
        /health body, or None for an unanswered probe."""
        if health is None:
            rep.last_status = None
            rep.consec_fail += 1
            if (rep.state not in (EVICTED, DRAINING)
                    and rep.consec_fail >= FLAGS.router_evict_failures):
                self._transition(rep, EVICTED, reason="probe_failures")
            return
        hstatus = health.get("status")
        serving = health.get("serving") or {}
        rep.last_status = hstatus
        rep.detail = serving.get("models") or {}
        if FLAGS.router_slo_weight > 0:
            rep.slo_burn = self._scrape_burn(rep)
        if hstatus == "ok":
            rep.consec_fail = 0
            if rep.state != IN_ROTATION:
                self._transition(rep, IN_ROTATION, reason=rep.state)
            return
        if hstatus in ("scheduler_dead", "stalled"):
            # a dead scheduler never finishes a drain and never recovers
            # on its own: evict NOW, no hysteresis
            rep.consec_fail += 1
            if rep.state != EVICTED:
                self._transition(rep, EVICTED, reason=hstatus)
            return
        if hstatus == "draining":
            # planned exit: out of rotation but NOT a failure
            rep.consec_fail = 0
            if rep.state != DRAINING:
                self._transition(
                    rep, DRAINING,
                    reason=serving.get("draining_reason") or "draining")
            return
        # not_ready: the structured per-model detail distinguishes a
        # replica still compiling its ladder (warming — poll again) from
        # one that will never be ready (count toward eviction)
        warming = any(
            (m or {}).get("state") == "warming"
            for m in rep.detail.values()) if rep.detail else False
        if warming:
            rep.consec_fail = 0
            if rep.state not in (WARMING, DRAINING):
                self._transition(rep, WARMING, reason="warming")
        else:
            rep.consec_fail += 1
            if (rep.state not in (EVICTED, DRAINING)
                    and rep.consec_fail >= FLAGS.router_evict_failures):
                self._transition(rep, EVICTED, reason="not_ready")

    def _transition(self, rep: Replica, state: str, reason: str) -> None:
        """Caller holds the lock.  Eviction and re-admission are the two
        transitions an operator pages on — both flight-record."""
        prev, rep.state = rep.state, state
        from ..monitor import counter, enabled, flight

        if state == EVICTED:
            if enabled():
                counter("router.evictions_total").inc()
            flight.record("router.evict", replica=rep.rid, url=rep.url,
                          reason=reason, prev=prev)
        elif state == IN_ROTATION and prev != IN_ROTATION:
            if enabled():
                counter("router.readmissions_total").inc()
            flight.record("router.readmit", replica=rep.rid, url=rep.url,
                          prev=prev)

    def _scrape_burn(self, rep: Replica) -> float:
        """Worst slo_burn_rate_5m across the replica's models (the
        /metrics scrape also refreshes the replica's burn windows)."""
        try:
            _status, body = self._http_get(rep, "/metrics",
                                           FLAGS.router_probe_timeout_s)
            worst = 0.0
            for line in body.decode().splitlines():
                if "_slo_burn_rate_5m " in line and line[0] != "#":
                    try:
                        worst = max(worst, float(line.rsplit(" ", 1)[1]))
                    except ValueError:
                        pass
            return worst
        except Exception:  # noqa: BLE001 — burn is advisory, never fatal
            return 0.0

    def _publish(self, rep: Replica) -> None:
        from ..monitor import enabled, gauge

        if not enabled():
            return
        pfx = f"router.replica.{rep.rid}"
        gauge(f"{pfx}.state").set(_STATE_CODE[rep.state])
        gauge(f"{pfx}.inflight").set(rep.inflight)
        gauge(f"{pfx}.probe_latency_ms").set(rep.probe_latency_ms)
        if FLAGS.router_slo_weight > 0:
            gauge(f"{pfx}.slo_burn").set(rep.slo_burn)

    # -- balancing -------------------------------------------------------
    def pick(self, exclude=()) -> Optional[Replica]:
        """Least loaded in-rotation replica; effective load is
        inflight + FLAGS_router_slo_weight x burn.  Falls back to an
        already-tried replica rather than failing when the exclusion
        empties the candidate set (retrying somewhere beats 503)."""
        w = FLAGS.router_slo_weight
        with self._lock:
            pool = [r for r in self._replicas.values()
                    if r.state == IN_ROTATION]
            if not pool:
                return None
            fresh = [r for r in pool if r.rid not in exclude]
            return min(fresh or pool,
                       key=lambda r: (r.inflight + w * r.slo_burn, r.rid))

    # -- proxying --------------------------------------------------------
    def proxy(self, kind: str, path: str, body: bytes,
              headers: dict) -> Tuple[int, dict, bytes]:
        """Forward one request, failing over inside its own deadline.
        Returns (status, response headers, response body)."""
        from ..monitor import counter, enabled

        timeout_s = _body_timeout_s(body, headers.get("Content-Type"))
        deadline = time.monotonic() + timeout_s
        if enabled():
            counter("router.requests_total").inc()
        retryable = (_RETRY_PREDICT if kind == "predict"
                     else _RETRY_GENERATE)
        delays = backoff_delays(FLAGS.router_retries, base_delay=0.02,
                                max_delay=0.5, deadline_s=timeout_s)
        tried: set = set()
        last: Optional[Tuple[int, dict, bytes]] = None
        while True:
            rep = self.pick(exclude=tried)
            if rep is None:
                if last is not None:
                    return last
                return _json_error(
                    503, "no replicas in rotation",
                    reason="no_replicas")
            tried.add(rep.rid)
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return last if last is not None else _json_error(
                    504, f"deadline exhausted after {timeout_s}s",
                    reason="deadline")
            try:
                if kind == "predict" and FLAGS.router_hedge_ms > 0:
                    result = self._attempt_hedged(
                        rep, path, body, headers, remaining, tried)
                else:
                    result = self._attempt(
                        rep, path, body, headers, remaining)
            except _ConnectError as e:
                last = _json_error(
                    502, f"replica {rep.rid} unreachable: {e}",
                    reason="connect_error")
                result = None
            if result is not None:
                status = result[0]
                if status not in retryable:
                    return result
                last = result
            # failover: a different replica may well serve this
            try:
                delay = next(delays)
            except StopIteration:
                return last
            delay = min(delay, max(0.0, deadline - time.monotonic()))
            if enabled():
                counter("router.failover_total").inc()
                counter(f"router.replica.{rep.rid}.failovers").inc()
            from ..monitor import flight

            flight.record("router.failover", replica=rep.rid,
                          request=kind,
                          status=(last[0] if last else None))
            if delay > 0:
                time.sleep(delay)

    def proxy_get(self, path: str) -> Tuple[int, dict, bytes]:
        """Introspection GET (one failover, no body)."""
        tried: set = set()
        for _ in range(2):
            rep = self.pick(exclude=tried)
            if rep is None:
                break
            tried.add(rep.rid)
            try:
                status, body = self._http_get(
                    rep, path, FLAGS.router_probe_timeout_s)
                return status, {"Content-Type": "application/json"}, body
            except Exception:  # noqa: BLE001 — try the next replica
                continue
        return _json_error(503, "no replicas in rotation",
                           reason="no_replicas")

    # -- attempts --------------------------------------------------------
    def _attempt(self, rep: Replica, path: str, body: bytes,
                 headers: dict,
                 timeout_s: float) -> Tuple[int, dict, bytes]:
        """One forwarded request on this handler thread's keep-alive
        connection to `rep`; raises _ConnectError when no HTTP response
        came back (always retryable)."""
        conn = self._conn(rep, timeout_s)
        with self._lock:
            rep.inflight += 1
        try:
            try:
                conn.request("POST", path, body=body, headers=headers)
                resp = conn.getresponse()
                data = resp.read()
            except Exception as e:
                self._drop_conn(rep)
                raise _ConnectError(f"{type(e).__name__}: {e}") from e
            out_headers = {h: resp.getheader(h)
                           for h in _FWD_RESP_HEADERS if resp.getheader(h)}
            return resp.status, out_headers, data
        finally:
            with self._lock:
                rep.inflight -= 1

    def _attempt_hedged(self, rep: Replica, path: str, body: bytes,
                        headers: dict, timeout_s: float,
                        tried: set) -> Optional[Tuple[int, dict, bytes]]:
        """Primary attempt + a hedge at a different replica once
        FLAGS_router_hedge_ms passes without a response; first response
        wins, the loser's socket is closed.  Hedged attempts run on
        worker threads with their own connections (the keep-alive pool
        is thread-local)."""
        from ..monitor import counter, enabled

        results: "queue.Queue" = queue.Queue()
        conns: Dict[str, http.client.HTTPConnection] = {}
        conns_lock = threading.Lock()
        deadline = time.monotonic() + timeout_s

        def run(r: Replica) -> None:
            conn = http.client.HTTPConnection(
                r.host, r.port, timeout=max(0.05, deadline
                                            - time.monotonic()))
            with conns_lock:
                conns[r.rid] = conn
            with self._lock:
                r.inflight += 1
            try:
                try:
                    conn.request("POST", path, body=body, headers=headers)
                    resp = conn.getresponse()
                    data = resp.read()
                except Exception as e:
                    results.put((r, _ConnectError(str(e))))
                    return
                out = {h: resp.getheader(h) for h in _FWD_RESP_HEADERS
                       if resp.getheader(h)}
                results.put((r, (resp.status, out, data)))
            finally:
                with self._lock:
                    r.inflight -= 1
                try:
                    conn.close()
                except Exception:  # noqa: BLE001
                    pass

        threading.Thread(target=run, args=(rep,), daemon=True).start()
        hedge_rep = None
        try:
            got = results.get(timeout=FLAGS.router_hedge_ms / 1e3)
        except queue.Empty:
            hedge_rep = self.pick(exclude=tried | {rep.rid})
            if hedge_rep is not None and hedge_rep.rid != rep.rid:
                tried.add(hedge_rep.rid)
                if enabled():
                    counter("router.hedges_total").inc()
                threading.Thread(target=run, args=(hedge_rep,),
                                 daemon=True).start()
            else:
                hedge_rep = None
            got = self._wait_result(results, deadline)
        if got is None:
            raise _ConnectError("hedged attempt timed out")
        winner, result = got
        if isinstance(result, _ConnectError) and hedge_rep is not None:
            # the first finisher failed; its twin may still deliver
            got = self._wait_result(results, deadline)
            if got is not None:
                winner, result = got
        # cancel the loser: closing its socket aborts the in-flight read
        with conns_lock:
            for rid, conn in conns.items():
                if rid != winner.rid:
                    try:
                        conn.close()
                    except Exception:  # noqa: BLE001
                        pass
        if hedge_rep is not None and winner.rid == hedge_rep.rid:
            if enabled():
                counter("router.hedges_won_total").inc()
                counter(
                    f"router.replica.{winner.rid}.hedges_won").inc()
        if isinstance(result, _ConnectError):
            raise result
        return result

    @staticmethod
    def _wait_result(results: "queue.Queue", deadline: float):
        try:
            return results.get(
                timeout=max(0.01, deadline - time.monotonic()))
        except queue.Empty:
            return None

    # -- connections -----------------------------------------------------
    def _conn(self, rep: Replica,
              timeout_s: float) -> http.client.HTTPConnection:
        """Keep-alive connection to `rep` for THIS thread (handler
        threads are per-client-connection, so the pool amortizes the
        TCP handshake across a client's whole keep-alive session)."""
        pool = getattr(self._local, "conns", None)
        if pool is None:
            pool = self._local.conns = {}
        key = (rep.rid, rep.host, rep.port)
        conn = pool.get(key)
        if conn is None:
            conn = http.client.HTTPConnection(rep.host, rep.port,
                                              timeout=timeout_s)
            pool[key] = conn
        else:
            conn.timeout = timeout_s
            if conn.sock is not None:
                conn.sock.settimeout(timeout_s)
        return conn

    def _drop_conn(self, rep: Replica) -> None:
        pool = getattr(self._local, "conns", None)
        if not pool:
            return
        conn = pool.pop((rep.rid, rep.host, rep.port), None)
        if conn is not None:
            try:
                conn.close()
            except Exception:  # noqa: BLE001
                pass

    def _http_get(self, rep: Replica, path: str,
                  timeout_s: float) -> Tuple[int, bytes]:
        """Probe-side GET on a fresh connection (the probe thread must
        never contend with request traffic for a socket)."""
        conn = http.client.HTTPConnection(rep.host, rep.port,
                                          timeout=timeout_s)
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            return resp.status, resp.read()
        finally:
            try:
                conn.close()
            except Exception:  # noqa: BLE001
                pass


def _body_timeout_s(body: bytes, ctype: Optional[str]) -> float:
    """The request's own deadline (JSON `timeout_s`, default 30 — the
    same default the replica's handler applies); npz bodies keep the
    default rather than paying a parse."""
    if body and (ctype or "application/json").lower().startswith(
            "application/json"):
        try:
            t = float(json.loads(body).get("timeout_s", 30.0))
            if t > 0:
                return t
        except Exception:  # noqa: BLE001 — replica returns the real 400
            pass
    return 30.0


def _json_error(status: int, msg: str,
                reason: str) -> Tuple[int, dict, bytes]:
    body = (json.dumps({"error": msg, "reason": reason}) + "\n").encode()
    return status, {"Content-Type": "application/json"}, body
