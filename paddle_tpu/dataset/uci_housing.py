"""UCI housing dataset (reference: python/paddle/dataset/uci_housing.py —
13 normalized features, median price target; fit_a_line book model).

Offline fallback: synthetic linear data with the same shape/scale."""

from __future__ import annotations

import os

import numpy as np

from . import common

URL = ("https://archive.ics.uci.edu/ml/machine-learning-databases/housing/"
       "housing.data")
FEATURE_NUM = 13


def _load_real():
    path = common.download(URL, "uci_housing", None)
    data = np.loadtxt(path)
    return data[:, :-1].astype("float32"), data[:, -1:].astype("float32")


def _synthetic(n=506, seed=13):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, FEATURE_NUM).astype("float32")
    w = rng.randn(FEATURE_NUM, 1).astype("float32")
    y = x @ w + 0.1 * rng.randn(n, 1).astype("float32") + 22.5
    return x, y.astype("float32")


def _data(synthetic):
    if common.use_synthetic(synthetic):
        x, y = _synthetic()
    else:
        x, y = _load_real()
    # feature-wise normalization (reference feature_range maximums/minimums)
    x = (x - x.mean(axis=0)) / (x.std(axis=0) + 1e-8)
    return x, y


def train(synthetic=False):
    def reader():
        x, y = _data(synthetic)
        n = int(len(x) * 0.8)
        for i in range(n):
            yield x[i], y[i]
    return reader


def test(synthetic=False):
    def reader():
        x, y = _data(synthetic)
        n = int(len(x) * 0.8)
        for i in range(n, len(x)):
            yield x[i], y[i]
    return reader
