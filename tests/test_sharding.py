"""Tensor-parallel / ZeRO sharding tests (GSPMD over the virtual mesh)."""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import layers
from paddle_tpu.parallel.sharding import ShardingPlan, ShardedProgram


def _build(seed):
    prog, startup = pt.Program(), pt.Program()
    prog.random_seed = startup.random_seed = seed
    with pt.program_guard(prog, startup):
        with pt.core.framework.guard_unique_name():
            x = layers.data(name="x", shape=[64], dtype="float32")
            label = layers.data(name="label", shape=[1], dtype="int64")
            h = layers.fc(input=x, size=128, act="relu",
                          param_attr=pt.ParamAttr(name="fc1_w"),
                          bias_attr=pt.ParamAttr(name="fc1_b"))
            pred = layers.fc(input=h, size=10, act="softmax",
                             param_attr=pt.ParamAttr(name="fc2_w"),
                             bias_attr=pt.ParamAttr(name="fc2_b"))
            loss = layers.mean(layers.cross_entropy(input=pred, label=label))
            pt.optimizer.Adam(learning_rate=0.01).minimize(loss)
    return prog, startup, loss


def _data(rng, n=32):
    return {
        "x": rng.rand(n, 64).astype("float32"),
        "label": rng.randint(0, 10, (n, 1)).astype("int64"),
    }


def _run(mode, steps=4):
    from jax.sharding import PartitionSpec as P

    prog, startup, loss = _build(seed=11)
    scope = pt.Scope()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup, scope=scope)
    if mode == "single":
        target = prog
    elif mode == "tp":
        plan = ShardingPlan(
            mesh_axes={"data": 2, "model": 4},
            param_rules=[
                ("fc1_w", P(None, "model")),  # split hidden dim (col-parallel)
                ("fc1_b", P("model")),
                ("fc2_w", P("model", None)),  # split input dim (row-parallel)
            ],
        )
        target = ShardedProgram(prog, plan, loss_name=loss.name)
    elif mode == "zero":
        plan = ShardingPlan(mesh_axes={"data": 8}, zero_stage=1)
        target = ShardedProgram(prog, plan, loss_name=loss.name)
    elif mode == "zero3":
        # stage 3 (param-sharded; alias of stage 2 under GSPMD — grads
        # reduce-scatter and params all-gather at use sites automatically)
        plan = ShardingPlan(mesh_axes={"data": 8}, zero_stage=3)
        target = ShardedProgram(prog, plan, loss_name=loss.name)
    rng = np.random.RandomState(3)
    out = []
    for _ in range(steps):
        (l,) = exe.run(target, feed=_data(rng), fetch_list=[loss], scope=scope)
        out.append(float(np.asarray(l)))
    return out


def test_tensor_parallel_loss_parity():
    single = _run("single")
    tp = _run("tp")
    np.testing.assert_allclose(single, tp, rtol=1e-4, atol=1e-5)


def test_zero_sharded_optimizer_parity():
    single = _run("single")
    zero = _run("zero")
    np.testing.assert_allclose(single, zero, rtol=1e-4, atol=1e-5)


def test_zero3_param_sharded_parity():
    """ZeRO stage-3 (params sharded over the data axis): training
    trajectory must match the unsharded run exactly — and the params must
    actually BE sharded on device (VERDICT r4 item 7)."""
    from jax.sharding import PartitionSpec as P

    single = _run("single")
    z3 = _run("zero3")
    np.testing.assert_allclose(single, z3, rtol=1e-4, atol=1e-5)

    # verify the placement: a stage-3 plan shards param dim0 on "data"
    plan = ShardingPlan(mesh_axes={"data": 8}, zero_stage=3)
    assert plan.spec_for_param("fc1_w", (64, 128)) == P("data")
    assert plan.spec_for_param("fc1_w", (64, 128), is_moment=True) == P("data")


def _run_transformer(mode, steps=3):
    from paddle_tpu.models import transformer as T
    from paddle_tpu.parallel.sharding import transformer_tp_rules

    prog, startup = pt.Program(), pt.Program()
    prog.random_seed = startup.random_seed = 5
    bs, seq, vocab, n_head = 4, 8, 32, 2
    with pt.program_guard(prog, startup):
        with pt.core.framework.guard_unique_name():
            avg_cost, _, _ = T.transformer(
                src_vocab_size=vocab, trg_vocab_size=vocab,
                max_length=seq, n_layer=1, n_head=n_head, d_key=8,
                d_value=8, d_model=16, d_inner_hid=32, dropout_rate=0.0,
                src_seq_len=seq, trg_seq_len=seq)
            pt.optimizer.Adam(learning_rate=1e-3).minimize(avg_cost)
    scope = pt.Scope()
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup, scope=scope)
    if mode == "single":
        target = prog
    else:
        plan = ShardingPlan(
            mesh_axes={"data": 2, "model": 4},
            param_rules=transformer_tp_rules("model"))
        target = ShardedProgram(prog, plan, loss_name=avg_cost.name)
    out = []
    for s in range(steps):
        batch = T.make_batch(bs, seq, seq, n_head, vocab, vocab,
                             rng=np.random.RandomState(s))
        (l,) = exe.run(target, feed=batch, fetch_list=[avg_cost],
                       scope=scope)
        out.append(float(np.asarray(l)))
    return out


@pytest.mark.slow
def test_transformer_tp_rules_loss_parity():
    """The full Megatron spec (transformer_tp_rules) must reproduce the
    single-device loss trajectory exactly (VERDICT r3 weak #6)."""
    single = _run_transformer("single")
    tp = _run_transformer("tp")
    np.testing.assert_allclose(single, tp, rtol=2e-4, atol=1e-5)


def test_transformer_tp_rules_actually_match():
    """Every rule family matches at least one parameter (no vestigial
    regexes) and sharded dims divide by the axis size."""
    import re

    from paddle_tpu.models import transformer as T
    from paddle_tpu.parallel.sharding import transformer_tp_rules

    prog, startup = pt.Program(), pt.Program()
    with pt.program_guard(prog, startup):
        with pt.core.framework.guard_unique_name():
            avg_cost, _, _ = T.transformer(
                src_vocab_size=32, trg_vocab_size=32, max_length=8,
                n_layer=1, n_head=2, d_key=8, d_value=8, d_model=16,
                d_inner_hid=32, dropout_rate=0.0, src_seq_len=8,
                trg_seq_len=8)
    names = [p.name for p in prog.all_parameters()]
    for pat, _ in transformer_tp_rules():
        assert any(re.fullmatch(pat, n) for n in names), (
            f"tp rule {pat!r} matches no parameter; have {names}")
