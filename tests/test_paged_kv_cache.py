"""Paged KV cache with shared-prefix block reuse (PR 20).

Acceptance criteria covered here:
  * BlockAllocator's ledger invariants: exclusive alloc at ref 1,
    share/free refcount lifecycle, exhaustion raises MemoryError with
    the ledger intact, double-free and share-of-unallocated are errors,
    the reserve withholds the trap block;
  * the paged ops are exact: reference_decode_paged over the static
    identity table is BIT-identical to the ring reference;
    flash_decode_paged passes interpret-mode parity against it on a
    scattered (non-identity) table with ragged lengths; the plan gate
    rejects misaligned block_t and oversized tables with a bit-identical
    XLA fallback;
  * greedy decode through the paged program pair is TOKEN-IDENTICAL to
    the flag-off ring pair across >= 64 tokens with a FLAT executor
    compile cache, at batch 1 and 64 (the PR-11 protocol);
  * flag-off builds are byte-stable (op-for-op free of the paged ops)
    and parameter names interop across the flag;
  * cow_if_shared isolates divergent appends: after fork_slot maps a
    prefix into a second slot, the writer's append copies first and the
    sharer's rows survive (tokens match a no-fork baseline exactly);
  * the serving exploit: N same-prompt requests prefill ONCE
    (prefix_hits_total == N-1), admission is by block budget — a
    request without blocks stays pending despite a free slot — and
    every block returns to the free list on retirement;
  * telemetry is zero-cost with FLAGS_monitor off (no metrics created);
  * the memory planner charges the pools to the kv_cache class.
"""

import numpy as np
import pytest

from paddle_tpu.core import executor as ex
from paddle_tpu.flags import FLAGS
from paddle_tpu.generation import GenerationSession
from paddle_tpu.generation.kv_cache import BlockAllocator, PagedKVCache
from paddle_tpu.models import transformer as T

TINY = dict(src_vocab_size=16, trg_vocab_size=16, max_length=12,
            n_layer=2, n_head=2, d_key=8, d_value=8, d_model=16,
            d_inner_hid=32)


def _src(rng, b, seq, vocab=16):
    return rng.randint(2, vocab, (b, seq, 1)).astype(np.int64)


# ---------------------------------------------------------------------------
# allocator ledger
# ---------------------------------------------------------------------------


class TestBlockAllocator:
    def test_alloc_free_roundtrip(self):
        a = BlockAllocator(8)
        got = a.alloc(3)
        assert got == [0, 1, 2]          # lowest-first, stable
        assert a.used_count == 3 and a.free_count == 5
        assert all(a.refcount(b) == 1 for b in got)
        a.free(got)
        assert a.used_count == 0 and a.free_count == 8
        assert a.refcount(0) == 0

    def test_share_refcount_lifecycle(self):
        a = BlockAllocator(4)
        (b,) = a.alloc(1)
        a.share([b])
        a.share([b])
        assert a.refcount(b) == 3
        a.free([b])
        a.free([b])
        assert a.refcount(b) == 1 and a.used_count == 1
        a.free([b])
        assert a.free_count == 4

    def test_exhaustion_raises_and_keeps_ledger(self):
        a = BlockAllocator(4)
        a.alloc(3)
        with pytest.raises(MemoryError):
            a.alloc(2)
        # the failed alloc must not have consumed the last block
        assert a.free_count == 1
        assert a.alloc(1) == [3]

    def test_double_free_and_share_unallocated_raise(self):
        a = BlockAllocator(4)
        (b,) = a.alloc(1)
        a.free([b])
        with pytest.raises(ValueError):
            a.free([b])
        with pytest.raises(ValueError):
            a.share([2])

    def test_reserve_withholds_trap_block(self):
        a = BlockAllocator(8, reserve=1)
        assert a.free_count == 7
        assert 0 not in a.alloc(7)       # block 0 never handed out
        with pytest.raises(MemoryError):
            a.alloc(1)


# ---------------------------------------------------------------------------
# paged ops: exactness, kernel parity, plan gate
# ---------------------------------------------------------------------------


class TestPagedOps:
    def _ring_and_pool(self, rng, b, h, dh, max_t, block_t, dtype="float32"):
        """A ring-layout cache and its identity-table paged pool holding
        the SAME rows."""
        import jax.numpy as jnp

        mb = max_t // block_t
        ring = rng.randn(b, max_t, h, dh).astype(dtype)
        pool = ring.reshape(b * mb, block_t, h, dh)
        table = np.arange(b * mb, dtype=np.int32).reshape(b, mb)
        return jnp.asarray(ring), jnp.asarray(pool), jnp.asarray(table)

    def test_reference_paged_identity_table_bit_equal_to_ring(self):
        import jax.numpy as jnp

        from paddle_tpu.kernels import decode_attention as kda

        rng = np.random.RandomState(0)
        b, h, dh, max_t, bt = 4, 2, 16, 64, 16
        k, kp, tab = self._ring_and_pool(rng, b, h, dh, max_t, bt)
        v, vp, _ = self._ring_and_pool(rng, b, h, dh, max_t, bt)
        q = jnp.asarray(rng.randn(b, h, dh).astype("float32"))
        lens = jnp.asarray([1, 17, 40, 64], jnp.int32)
        ring = kda.reference_decode(q, k, v, lens, scale=0.25)
        paged = kda.reference_decode_paged(q, kp, vp, tab, lens, scale=0.25)
        np.testing.assert_array_equal(np.asarray(ring), np.asarray(paged))

    def test_flash_paged_interpret_parity_scattered_table(self):
        """The Pallas block walk vs the reference gather on a SHUFFLED
        table (the serving allocator never hands out identity) with
        ragged mid-block lengths."""
        import jax.numpy as jnp

        from paddle_tpu.kernels import decode_attention as kda

        rng = np.random.RandomState(1)
        b, h, dh, bt, mb = 4, 8, 64, 16, 4
        pool_n = 32                       # bigger than b*mb: holes
        kp = jnp.asarray(rng.randn(pool_n, bt, h, dh).astype("float32"))
        vp = jnp.asarray(rng.randn(pool_n, bt, h, dh).astype("float32"))
        table = jnp.asarray(
            rng.permutation(pool_n)[:b * mb].reshape(b, mb).astype("int32"))
        q = jnp.asarray(rng.randn(b, h, dh).astype("float32"))
        lens = jnp.asarray([3, 16, 33, 64], jnp.int32)

        ok, _, _ = kda._paged_plan(q, kp, table, True)
        assert ok
        ref = kda.reference_decode_paged(q, kp, vp, table, lens, scale=0.125)
        out = kda.flash_decode_paged(q, kp, vp, table, lens, scale=0.125,
                                     interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-6, rtol=2e-6)

    def test_paged_scatter_rows_targets_table_blocks(self):
        """Rows land at table-directed pool blocks; inactive lanes leave
        the pool untouched."""
        import jax.numpy as jnp

        from paddle_tpu.kernels import decode_attention as kda

        rng = np.random.RandomState(2)
        L, pool_n, bt, h, dh = 1, 8, 8, 2, 16
        cache = jnp.zeros((L, pool_n, bt, h, dh), jnp.float32)
        new = jnp.asarray(rng.randn(2, 1, h, dh).astype("float32"))
        table = jnp.asarray([[5, 1], [2, 7]], jnp.int32)
        pos = jnp.asarray([9, 3], jnp.int32)    # lane0 row 9 -> blk idx 1
        act = jnp.asarray([1, 0], jnp.int32)
        out = np.asarray(kda.paged_scatter_rows(cache, new, table, pos,
                                                act, 0))
        np.testing.assert_array_equal(out[0, 1, 1], np.asarray(new)[0, 0])
        assert out[0, 2].sum() == 0 and out[0, 7].sum() == 0  # lane1 inactive
        mask = np.ones(pool_n, bool)
        mask[1] = False
        assert np.all(out[0, mask] == 0)

    def test_paged_plan_gate_contract(self):
        import jax

        from paddle_tpu.analysis.kernel_lint import _pretend_tpu
        from paddle_tpu.kernels import decode_attention as kda
        from paddle_tpu.kernels import decode_step as kds

        def spec(shape, dtype="float32"):
            return jax.ShapeDtypeStruct(shape, dtype)

        def plan(b=4, h=8, dh=64, bt=16, mb=8):
            with _pretend_tpu():
                return kda._paged_plan(
                    spec((b, h, dh)), spec((b * mb, bt, h, dh)),
                    spec((b, mb), "int32"), None)

        assert plan()[0]
        assert not plan(bt=12)[0]          # block_t % 8
        assert not plan(dh=48)[0]          # lane alignment
        assert not plan(b=64, mb=128)[0]   # b*mb > _PAGED_TABLE_CAP
        # off-TPU without explicit interpret: fallback (interpret=True)
        ok, _, interp = kda._paged_plan(
            spec((4, 8, 64)), spec((32, 16, 8, 64)),
            spec((4, 8), "int32"), None)
        assert ok and interp
        with _pretend_tpu():
            mega = kds._paged_megastep_plan(
                128, 8, 64, 256, 16, 16, 4, 8, 8, "float32")
            assert mega.ok and mega.fuse_ffn
            assert not kds._paged_megastep_plan(
                128, 8, 64, 256, 12, 16, 4, 8, 8, "float32").ok
            assert not kds._paged_megastep_plan(
                128, 8, 64, 256, 16, 16, 64, 128, 8, "float32").ok

    def test_fused_paged_megastep_falls_back_bit_identical(self):
        """Off-contract (block_t=12 pools) the fused paged entry IS the
        composed reference — bit-equal outputs and caches."""
        import jax.numpy as jnp

        from paddle_tpu.kernels import decode_step as kds

        rng = np.random.RandomState(3)
        dm, h, dh, di, bt, b, mb = 128, 8, 8, 256, 12, 2, 2
        hd = h * dh

        def f(*s):
            return jnp.asarray(rng.randn(*s).astype("float32") * 0.1)

        weights = [f(b, 1, dm), f(dm, 3 * hd), f(hd, dm), f(dm) + 1,
                   f(dm), f(dm, hd), f(hd, dm), f(dm) + 1, f(dm),
                   f(dm, di), f(di), f(di, dm), f(dm), f(dm) + 1, f(dm)]
        pools = [f(1, b * mb, bt, h, dh) for _ in range(4)]
        tab = jnp.arange(b * mb, dtype=jnp.int32).reshape(b, mb)
        ints = [jnp.asarray(a, jnp.int32) for a in
                ([1, 5], [2, 6], [bt, 3], [1, 1])]
        kw = dict(layer=0, n_head=h, scale=dh ** -0.5)
        ref = kds.reference_decode_step_paged(
            *weights, *pools, ints[0], ints[1], ints[2], tab, tab,
            ints[3], **kw)
        fused = kds.fused_decode_step_paged(
            *weights, *pools, ints[0], ints[1], ints[2], tab, tab,
            ints[3], **kw)
        for a, b_ in zip(ref, fused):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b_))


# ---------------------------------------------------------------------------
# host choreography: COW + fork on a bare scope
# ---------------------------------------------------------------------------


class TestCowAndFork:
    def _cache(self):
        c = PagedKVCache("t", num_layers=1, batch=2, max_t=32,
                         n_head=2, d_head=8, block_t=8, num_blocks=8)
        scope = ex.Scope()
        c.reset_dynamic(scope)
        return c, scope

    def test_fork_shares_and_cow_preserves_sharer(self):
        import jax.numpy as jnp

        c, scope = self._cache()
        blocks = c.allocator.alloc(2)
        c.set_table_row(scope, 0, blocks)
        scope.set_var(c.len_name, jnp.asarray([12, 0], jnp.int32))
        # stamp recognizable rows into slot 0's pool blocks
        pool = np.asarray(scope.find_var(c.k_name)).copy()
        pool[0, blocks[0]] = 1.0
        pool[0, blocks[1]] = 2.0
        scope.set_var(c.k_name, jnp.asarray(pool))

        c.fork_slot(scope, 1, 0, 12)
        assert c.allocator.refcount(blocks[0]) == 2
        assert c.slot_blocks(scope, 1, 12) == blocks

        # slot 0 appends at row 12 (block idx 1, shared) -> COW copies
        assert c.cow_if_shared(scope, 0, 12)
        new = c.slot_blocks(scope, 0, 16)[1]
        assert new not in blocks
        assert c.allocator.refcount(blocks[1]) == 1   # sharer keeps it
        assert c.allocator.refcount(new) == 1
        # sharer's table and rows are untouched; the copy carried them
        assert c.slot_blocks(scope, 1, 12) == blocks
        pool = np.asarray(scope.find_var(c.k_name))
        np.testing.assert_array_equal(pool[0, new], pool[0, blocks[1]])
        # unshared append: no copy
        assert not c.cow_if_shared(scope, 0, 13)

    def test_fork_releases_previous_mapping(self):
        import jax.numpy as jnp

        c, scope = self._cache()
        a = c.allocator.alloc(1)
        b = c.allocator.alloc(1)
        c.set_table_row(scope, 0, a)
        c.set_table_row(scope, 1, b)
        scope.set_var(c.len_name, jnp.asarray([6, 6], jnp.int32))
        c.fork_slot(scope, 1, 0, 6)
        assert c.allocator.refcount(b[0]) == 0        # old mapping freed
        assert c.allocator.refcount(a[0]) == 2

    def test_static_allocate_is_identity(self):
        c = PagedKVCache("t", num_layers=1, batch=2, max_t=32,
                         n_head=2, d_head=8, block_t=8)
        scope = ex.Scope()
        c.allocate(scope)
        np.testing.assert_array_equal(
            c.host_table(scope),
            np.arange(2 * 4, dtype=np.int32).reshape(2, 4))
        assert c.allocator is None
        small = PagedKVCache("u", num_layers=1, batch=2, max_t=32,
                             n_head=2, d_head=8, block_t=8, num_blocks=4)
        with pytest.raises(ValueError):
            small.allocate(ex.Scope())

    def test_block_t_alignment_enforced(self):
        with pytest.raises(ValueError):
            PagedKVCache("t", 1, 2, 32, 2, 8, block_t=12)


# ---------------------------------------------------------------------------
# program pair: paged vs ring token identity + flag-off stability
# ---------------------------------------------------------------------------


class TestPagedGeneration:
    @pytest.mark.parametrize("batch", [1, 64])
    def test_token_identity_paged_vs_ring_compile_flat(self, batch):
        """THE acceptance criterion: >= 64 greedy tokens, paged vs
        flag-off ring path token-identical, compile cache flat for BOTH
        program pairs — at batch 1 and 64."""
        dims = dict(TINY, max_length=66, batch_size=batch, src_seq_len=6,
                    max_out_len=64, bos_id=0, eos_id=-1)  # no early eos
        rng = np.random.RandomState(7 + batch)
        src = _src(rng, batch, 6)
        scope = ex.Scope()

        ring = GenerationSession(
            T.build_generation_programs(kv_cache=True, **dims),
            scope=scope)
        ring.init_params()
        toks_r, steps = ring.generate(src)
        assert steps == 64 and toks_r.shape == (batch, 64)
        n_compiled = ring.compile_count
        ring.generate(src)
        assert ring.compile_count == n_compiled

        try:
            FLAGS.set("paged_kv_cache", True)
            paged = GenerationSession(
                T.build_generation_programs(kv_cache=True, **dims),
                scope=scope)
            assert paged.p.paged
            toks_p, steps_p = paged.generate(src)
            assert steps_p == 64
            n_compiled = paged.compile_count
            paged.generate(src)
            assert paged.compile_count == n_compiled
        finally:
            FLAGS.reset("paged_kv_cache")
        np.testing.assert_array_equal(toks_p, toks_r)

    def test_flag_off_graph_identity_and_param_interop(self):
        """Flag-off builds are byte-stable op-for-op (no paged ops, ring
        cache vars); parameter names are IDENTICAL across the flag
        (checkpoints interop)."""
        dims = dict(TINY, batch_size=2, src_seq_len=6, max_out_len=5)

        p_off = T.build_generation_programs(kv_cache=True, **dims)
        p_off2 = T.build_generation_programs(kv_cache=True, **dims)
        try:
            FLAGS.set("paged_kv_cache", True)
            p_on = T.build_generation_programs(kv_cache=True, **dims)
        finally:
            FLAGS.reset("paged_kv_cache")

        def ops(p):
            return [op.type for op in p.decode.global_block().ops]

        assert ops(p_off) == ops(p_off2)      # flag-off build is stable
        assert not any(o.startswith("paged_") for o in ops(p_off))
        assert any(o.startswith("paged_") or o == "fused_decode_step_paged"
                   for o in ops(p_on))
        off_vars = set(p_off.decode.global_block().vars)
        assert p_on.self_cache.table_name not in off_vars

        def param_names(p):
            return {v.name for v in
                    p.decode.global_block().all_parameters()}

        assert param_names(p_on) == param_names(p_off)

    def test_unfused_paged_route_token_identity(self):
        """FLAGS_fused_decode_step off decomposes the decode step into
        the discrete paged ops (paged_kv_cache_update +
        paged_decode_attention) — that walk must stay token-identical
        to the flag-off ring build."""
        dims = dict(TINY, max_length=66, batch_size=2, src_seq_len=6,
                    max_out_len=8, bos_id=0, eos_id=-1)
        rng = np.random.RandomState(11)
        src = _src(rng, 2, 6)
        scope = ex.Scope()
        try:
            FLAGS.set("fused_decode_step", False)
            ring = GenerationSession(
                T.build_generation_programs(kv_cache=True, **dims),
                scope=scope)
            ring.init_params()
            toks_r, _ = ring.generate(src)

            FLAGS.set("paged_kv_cache", True)
            paged = GenerationSession(
                T.build_generation_programs(kv_cache=True, **dims),
                scope=scope)
            ops = [op.type for op in paged.p.decode.global_block().ops]
            assert "paged_decode_attention" in ops
            assert "paged_kv_cache_update" in ops
            toks_p, _ = paged.generate(src)
        finally:
            FLAGS.reset("fused_decode_step")
            FLAGS.reset("paged_kv_cache")
        np.testing.assert_array_equal(toks_p, toks_r)

    def test_paged_beam_reorder_matches_ring_beam(self):
        """Beam programs under the flag swap kv_cache_reorder for
        paged_kv_cache_reorder (the parent gather permutes block-table
        ROWS, not pool bytes); hypotheses and scores must match the
        ring beam build exactly."""
        dims = dict(TINY, batch_size=2, src_seq_len=6, max_out_len=5,
                    beam_size=2, bos_id=0, eos_id=1)
        rng = np.random.RandomState(13)
        src = _src(rng, 2, 6)
        scope = ex.Scope()
        ring = GenerationSession(
            T.build_generation_programs(kv_cache=True, **dims),
            scope=scope)
        ring.init_params()
        sent_r, scores_r = ring.generate_beam(src)
        try:
            FLAGS.set("paged_kv_cache", True)
            paged = GenerationSession(
                T.build_generation_programs(kv_cache=True, **dims),
                scope=scope)
            ops = [op.type for op in paged.p.decode.global_block().ops]
            assert "paged_kv_cache_reorder" in ops
            sent_p, scores_p = paged.generate_beam(src)
        finally:
            FLAGS.reset("paged_kv_cache")
        np.testing.assert_array_equal(sent_p, sent_r)
        np.testing.assert_allclose(scores_p, scores_r, rtol=1e-6)


# ---------------------------------------------------------------------------
# serving: shared-prefix admission, block budget, release, telemetry
# ---------------------------------------------------------------------------


def _drive(batcher, reqs, max_iters=300):
    """Synchronous admit/step loop (no scheduler thread): returns when
    every request's event is set."""
    for r in reqs:
        batcher._pending_join.append(r)
    it = 0
    while not all(r.event.is_set() for r in reqs):
        batcher._admit()
        batcher._step()
        it += 1
        assert it < max_iters, "batcher made no progress"


class TestPagedServing:
    def _model(self, slots=4):
        from paddle_tpu.serving.generation import (
            ContinuousBatcher, build_demo_generation_model)

        model = build_demo_generation_model(slots=slots)
        model.warmup()
        return model, ContinuousBatcher(model)

    def test_shared_prefix_prefills_once_and_tokens_match_ring(self):
        from paddle_tpu import monitor
        from paddle_tpu.serving.generation import _GenRequest

        prompts = [[5, 9, 3], [5, 9, 3], [5, 9, 3], [7, 2]]

        def run(paged):
            try:
                if paged:
                    FLAGS.set("paged_kv_cache", True)
                model, b = self._model()
                pre0 = monitor.counter(
                    "serving.gen.gendemo.prefills").value
                hit0 = monitor.counter(
                    "generation.gendemo.prefix_hits_total").value
                reqs = [_GenRequest(list(p), 12) for p in prompts]
                _drive(b, reqs)
                pre = monitor.counter(
                    "serving.gen.gendemo.prefills").value - pre0
                hit = monitor.counter(
                    "generation.gendemo.prefix_hits_total").value - hit0
                return model, b, [list(r.tokens) for r in reqs], pre, hit
            finally:
                if paged:
                    FLAGS.reset("paged_kv_cache")

        try:
            FLAGS.set("monitor", True)
            _, _, toks_ring, pre_ring, _ = run(False)
            model, b, toks_paged, pre_paged, hits = run(True)
        finally:
            FLAGS.reset("monitor")

        assert toks_paged == toks_ring
        assert pre_ring == 4               # ring prefills every lane
        assert pre_paged == 2              # 3 sharers prefill ONCE + 1
        assert hits == 2                   # N-1 for the shared triple
        # retirement returned every block; the prefix registry drained
        p = model.session.p
        assert p.self_cache.allocator.used_count == 0
        assert p.cross_cache.allocator.used_count == 0
        assert not b._prefix_map

    def test_admission_is_by_block_budget_not_slots(self):
        """A request that cannot get blocks stays PENDING even with free
        slots, and admits as soon as a retirement frees them."""
        from paddle_tpu.serving.generation import _GenRequest

        try:
            FLAGS.set("paged_kv_cache", True)
            # 1 non-trap block per pool: ONE request (1 self + 1 cross
            # needed at max_tokens=12, prompt len 3) exhausts both
            # pools; a second DISTINCT prompt must wait for retirement
            FLAGS.set("kv_cache_blocks", 2)
            model, b = self._model()
            p = model.session.p
            assert p.self_cache.allocator.free_count == 1
            r1 = _GenRequest([5, 9, 3], 12)
            r2 = _GenRequest([7, 2, 4], 12)
            b._pending_join.append(r1)
            b._pending_join.append(r2)
            b._admit()
            assert b._slot_req.count(None) == model.slots - 1
            assert len(b._pending_join) == 1      # r2 held back
            assert p.self_cache.allocator.free_count == 0
            it = 0
            while not r2.event.is_set():
                b._admit()
                b._step()
                it += 1
                assert it < 200
            assert r1.event.is_set() and len(r1.tokens) == 12
            assert len(r2.tokens) == 12
            assert p.self_cache.allocator.used_count == 0
        finally:
            FLAGS.reset("kv_cache_blocks")
            FLAGS.reset("paged_kv_cache")

    def test_fork_then_diverge_cow_keeps_sharer_tokens(self):
        """The speculative-decode skeleton: fork a live sequence into a
        spare slot mid-decode; the writer's next appends must COW and
        the original's tokens must match a no-fork baseline exactly."""
        from paddle_tpu import monitor
        from paddle_tpu.serving.generation import _GenRequest

        def run(fork):
            try:
                FLAGS.set("paged_kv_cache", True)
                if fork:
                    FLAGS.set("monitor", True)
                model, b = self._model()
                req = _GenRequest([5, 9, 3], 16)
                b._pending_join.append(req)
                b._admit()
                slot = next(i for i, r in enumerate(b._slot_req)
                            if r is req)
                spare = next(i for i, r in enumerate(b._slot_req)
                             if r is None)
                cow0 = monitor.counter(
                    "generation.gendemo.cow_copies_total").value
                for _ in range(4):
                    b._step()
                if fork:
                    model.fork_slot(spare, slot)
                    p = model.session.p
                    scope = model.session.scope
                    shared = p.self_cache.slot_blocks(
                        scope, spare,
                        int(p.self_cache.lengths(scope)[spare]))
                    frozen = np.asarray(scope.find_var(
                        p.self_cache.k_name))[:, shared].copy()
                it = 0
                while not req.event.is_set():
                    b._admit()
                    b._step()
                    it += 1
                    assert it < 200
                cow = monitor.counter(
                    "generation.gendemo.cow_copies_total").value - cow0
                if fork:
                    # the sharer's pool rows survived the divergence
                    after = np.asarray(scope.find_var(
                        p.self_cache.k_name))[:, shared]
                    np.testing.assert_array_equal(after, frozen)
                    assert cow >= 1
                return list(req.tokens)
            finally:
                if fork:
                    FLAGS.reset("monitor")
                FLAGS.reset("paged_kv_cache")

        base = run(fork=False)
        forked = run(fork=True)
        assert forked == base

    def test_telemetry_zero_cost_with_monitor_off(self):
        from paddle_tpu import monitor
        from paddle_tpu.serving.generation import _GenRequest

        assert not FLAGS.monitor
        try:
            FLAGS.set("paged_kv_cache", True)
            _, b = self._model()
            before = set(monitor.default_registry().names())
            reqs = [_GenRequest([5, 9, 3], 8), _GenRequest([5, 9, 3], 8)]
            _drive(b, reqs)
        finally:
            FLAGS.reset("paged_kv_cache")
        created = set(monitor.default_registry().names()) - before
        assert not {n for n in created
                    if "blocks_" in n or "prefix_hits" in n
                    or "cow_copies" in n or "prefills" in n}, created


# ---------------------------------------------------------------------------
# memory planner: the pools are a named kv_cache row
# ---------------------------------------------------------------------------


def test_planner_charges_pools_to_kv_cache_class():
    from paddle_tpu.memory import planner as M

    dims = dict(TINY, batch_size=2, src_seq_len=6, max_out_len=5)
    try:
        FLAGS.set("paged_kv_cache", True)
        p = T.build_generation_programs(kv_cache=True, **dims)
    finally:
        FLAGS.reset("paged_kv_cache")
    plan = M.plan_program(p.decode, [], [])
    kv = plan.class_peaks.get("kv_cache", 0)
    assert kv > 0
    # the row covers both pools' K+V (+ tables/counters via hbm_bytes)
    expect = p.self_cache.hbm_bytes + p.cross_cache.hbm_bytes
    assert abs(kv - expect) <= 0.05 * expect, (kv, expect)
    assert "kv_cache" in plan.table()
