#!/usr/bin/env python
"""CI serving gate: export a model, boot the server, prove the batcher.

Driven by tools/run_ci.sh (the serving smoke step).  Three phases, all
against `python -m paddle_tpu.serving` subprocesses driven by
tools/loadgen.py:

  1. smoke    — a few hundred shape-varying requests (batch sizes cycle
     1,2,3,4) against a batched server; asserts the request-latency p99
     and batch-fill histograms appear in the scraped /metrics, and that
     the executor compile counter stayed FLAT during the load (warm
     bucket ladder: zero recompiles across the shape-varying stream).
  2. A/B      — the acceptance demonstration: the SAME single-row
     request stream against a batched server vs a --max-batch 1 server
     (both warm, same compiled-signature ladder).  Dynamic batching must
     deliver >= --ab-target x the QPS of batch-size-1 serving.  BOTH
     servers are chaos-latency-armed (FLAGS_chaos_serve_latency_s pins
     the per-batch cost at AB_CHAOS_LAT_S), so capacity is determined by
     the injected latency, not the CI box: batch1 serves ~1/L rows/s
     while the batched server coalesces ~concurrency rows per L —
     the expected ratio is ~min(concurrency, max_batch), and the 2x
     gate is box-independent (the earlier uninjected gate measured
     1.2x-3.3x for the SAME build depending on the box).  Trials are
     interleaved pairs and the gate takes the best pair, stopping early
     once the target is met.
  3. artifact — every loadgen JSON + an ab_summary.json with the
     per-trial QPS table lands in --out-dir for CI archiving.
  4. overload — the robustness gate (overload_gate): an open-loop flood
     at ~4x MEASURED capacity against a chaos-latency-armed server with
     bounded queues must shed (429 + Retry-After), drop expired
     requests before dispatch (expired_dropped_total delta > 0), serve
     zero crash-5xx with a FLAT compile counter, keep accepted-request
     p99 under a stated bound — and a SIGTERM mid-load must drain
     in-flight work (200s), 503 new requests, dump a drain-trigger
     flight record and exit 0; artifact overload_smoke.json.
  5. generation — the continuous token-level batching gate against a
     `--demo-generation` server (generation_gate): staggered
     prompt-in/tokens-out stream with the compile counter FLAT and TTFT
     histograms served, a late-joining request that must neither retrace
     nor stall the in-flight long generation, and the throughput A/B
     (concurrent streams >= 2x one sequential stream's tokens/sec);
     artifacts loadgen_gen*.json + gen_ab_summary.json.
  6. tracing — the request-scoped distributed-tracing gate
     (tracing_gate): a FLAGS_trace_requests server must echo the
     client's traceparent, serve /v1/traces with full span trees for a
     predict AND a multi-token generation whose latency decompositions
     sum to the measured wall clock within 5%, expose SLO burn-rate
     gauges on /metrics, and close the loadgen --trace correlation loop;
     artifact trace_sample.json (one trace per kind, all span kinds).

Both servers stay resident across trials (warmup is paid once) and
requests ride keep-alive connections, so the measurement sees the
serving tier, not process startup or TCP churn.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import threading
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)


def export_demo_model(dirname: str, in_dim: int = 32, hidden: int = 256,
                      nlayers: int = 32, out_dim: int = 4) -> str:
    """A deep-but-narrow fc stack: per-dispatch cost is dominated by the
    layer count (weight reads + dispatch overhead), nearly flat in batch
    size on CPU — the regime where coalescing visibly pays."""
    import paddle_tpu as pt
    from paddle_tpu import layers

    prog, startup = pt.Program(), pt.Program()
    prog.random_seed = startup.random_seed = 3
    with pt.program_guard(prog, startup):
        x = layers.data(name="x", shape=[in_dim], dtype="float32")
        h = x
        for _ in range(nlayers):
            h = layers.fc(h, size=hidden, act="relu")
        out = layers.fc(h, size=out_dim)
    scope, exe = pt.Scope(), pt.Executor(pt.CPUPlace())
    with pt.scope_guard(scope):
        exe.run(startup, scope=scope)
        pt.io.save_inference_model(dirname, ["x"], [out], exe,
                                   main_program=prog, scope=scope)
    return dirname


class Server:
    """One `python -m paddle_tpu.serving` subprocess on an ephemeral
    port; parses the ready line, kills the process on close()."""

    def __init__(self, model_dir, extra_args, extra_env=None):
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   PYTHONPATH=REPO_ROOT + os.pathsep
                   + os.environ.get("PYTHONPATH", ""))
        env.update(extra_env or {})
        model_args = ([] if model_dir is None
                      else ["--model", f"demo={model_dir}"])
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.serving",
             "--port", "0"] + model_args + list(extra_args),
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env)
        line = self.proc.stdout.readline().decode()
        try:
            ready = json.loads(line)
        except ValueError:
            err = self.proc.stderr.read().decode()[-2000:]
            raise RuntimeError(
                f"server did not print a ready line: {line!r}\n{err}")
        self.url = f"http://127.0.0.1:{ready['port']}"
        # Drain both pipes for the life of the server: an undrained PIPE
        # fills at ~64KB and blocks the server's writer (e.g. verbose
        # jax warnings), stalling requests until the loadgen timeout.
        for stream in (self.proc.stdout, self.proc.stderr):
            threading.Thread(target=self._drain, args=(stream,),
                             daemon=True).start()

    @staticmethod
    def _drain(stream):
        for _ in iter(stream.readline, b""):
            pass

    def close(self):
        self.proc.terminate()
        try:
            self.proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            self.proc.kill()


def run_loadgen(url: str, out: str, requests: int, concurrency: int,
                batch_sizes: str, model: str = "demo",
                extra=()) -> dict:
    cmd = [sys.executable, os.path.join(REPO_ROOT, "tools", "loadgen.py"),
           "--url", url, "--model", model,
           "--requests", str(requests), "--concurrency", str(concurrency),
           "--batch-sizes", batch_sizes, "--out", out] + list(extra)
    r = subprocess.run(cmd, capture_output=True, text=True, timeout=600)
    if r.returncode != 0:
        raise RuntimeError(f"loadgen failed:\n{r.stderr[-3000:]}")
    with open(out) as f:
        return json.load(f)


def http_generate(url: str, prompt, max_tokens: int,
                  timeout: float = 60.0, headers=None) -> dict:
    import urllib.request

    body = json.dumps({"prompt": prompt,
                       "max_tokens": max_tokens}).encode()
    hdrs = {"Content-Type": "application/json"}
    hdrs.update(headers or {})
    req = urllib.request.Request(
        f"{url}/v1/models/gendemo:generate", data=body, headers=hdrs)
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def generation_gate(args) -> None:
    """Continuous token-level batching gate (PR-11 acceptance):

      1. loadgen --generate smoke: staggered prompt-in/tokens-out stream
         with the executor compile counter FLAT and TTFT p50/p99 in the
         artifact;
      2. late-join: a short request submitted while a long generation is
         mid-flight must finish FIRST (no head-of-line stall) and add
         ZERO compiles (no retrace);
      3. throughput A/B: >= --gen-ab-target x tokens/sec from
         concurrent streams (continuous batching fills the decode batch)
         vs one sequential stream (batch-1 decode), interleaved trials.
    """
    import urllib.request

    server = Server(None, ["--demo-generation", "gendemo",
                           "--gen-slots", "4"])
    try:
        # -- phase 1: staggered stream, compile counter flat ------------
        smoke = run_loadgen(
            server.url, os.path.join(args.out_dir, "loadgen_gen.json"),
            40, 6, "1", model="gendemo",
            extra=["--generate", "--max-tokens", "8"])
        assert smoke["errors"] == 0, smoke
        gen = smoke["generation"]
        assert gen["tokens_received"] > 0, smoke
        assert gen["ttft_ms"] and gen["ttft_ms"]["p99"] > 0, smoke
        assert smoke["server_metrics"][
            "executor_compiles_during_load"] == 0, \
            f"retrace during generation load: {smoke['server_metrics']}"
        prom = scrape(server.url)
        assert "serving_gen_gendemo_ttft_seconds_bucket" in prom, \
            "ttft histogram missing from /metrics"
        print(f"generation smoke OK: {gen['tokens_received']} tokens, "
              f"{gen['tokens_per_sec']} tok/s, "
              f"ttft p50={gen['ttft_ms']['p50']}ms "
              f"p99={gen['ttft_ms']['p99']}ms, recompiles=0", flush=True)

        # -- phase 2: late join must neither retrace nor stall ----------
        c0 = _prom_scalar(scrape(server.url), "executor_compiles")
        done = {}

        def long_req():
            done["long"] = (http_generate(server.url, [3, 5, 7], 64),
                            time.perf_counter())

        t_long = threading.Thread(target=long_req)
        t_long.start()
        time.sleep(0.01)  # let the long request start decoding
        short, t_short_done = (http_generate(server.url, [9, 2], 2),
                               time.perf_counter())
        t_long.join(timeout=60)
        long_rec, t_long_done = done["long"]
        assert len(short["tokens"]) == 2, short
        assert len(long_rec["tokens"]) == 64, long_rec
        assert t_short_done < t_long_done, \
            "late-joining short request stalled behind the long one"
        assert _prom_scalar(scrape(server.url),
                            "executor_compiles") == c0, \
            "late join retraced"
        print(f"late-join OK: short ttft "
              f"{short['meta']['ttft_ms']}ms while long in flight, "
              f"0 compiles", flush=True)

        # -- phase 3: continuous batching >= target x batch-1 decode ----
        trials, best = [], None
        for t in range(args.ab_trials):
            multi = run_loadgen(
                server.url,
                os.path.join(args.out_dir, "loadgen_gen_multi.json"),
                16, 4, "1", model="gendemo",
                extra=["--generate", "--max-tokens", "16"])
            single = run_loadgen(
                server.url,
                os.path.join(args.out_dir, "loadgen_gen_single.json"),
                8, 1, "1", model="gendemo",
                extra=["--generate", "--max-tokens", "16"])
            for rec in (multi, single):
                assert rec["errors"] == 0, rec
                assert rec["server_metrics"][
                    "executor_compiles_during_load"] == 0, rec
            tps_m = multi["generation"]["tokens_per_sec"]
            tps_s = single["generation"]["tokens_per_sec"]
            ratio = tps_m / max(tps_s, 1e-9)
            trials.append({"trial": t, "multi_tok_s": tps_m,
                           "single_tok_s": tps_s,
                           "ratio": round(ratio, 3)})
            print(f"gen A/B trial {t}: {tps_m} vs {tps_s} tok/s -> "
                  f"{ratio:.2f}x", flush=True)
            if best is None or ratio > best["ratio"]:
                best = trials[-1]
            if ratio >= args.gen_ab_target:
                break
            time.sleep(1.0)
        summary = {
            "tool": "serving_smoke.generation",
            "slots": 4,
            "target_ratio": args.gen_ab_target,
            "trials": trials,
            "best": best,
            "passed": best["ratio"] >= args.gen_ab_target,
        }
        with open(os.path.join(args.out_dir,
                               "gen_ab_summary.json"), "w") as f:
            json.dump(summary, f, indent=2)
        if not summary["passed"]:
            raise AssertionError(
                f"generation A/B gate FAILED: best "
                f"{best['ratio']}x < {args.gen_ab_target}x")
        print(f"generation A/B gate OK: continuous batching "
              f"{best['ratio']}x over batch-1 decode", flush=True)
    finally:
        server.close()


def overload_gate(args) -> None:
    """[robustness] The overload gate (ISSUE 13 acceptance criteria).

    A chaos-armed server (deterministic per-batch latency pins capacity
    so the gate is CI-box-independent; --max-batch 1 disables coalescing
    so queue wait is load-proportional; bounded queue) faces an
    open-loop flood at ~4x its MEASURED capacity with a short propagated
    client deadline.  Asserted:

      * shedding engaged: 429s with Retry-After at the client, server
        shed counter delta > 0;
      * deadline propagation: expired_dropped_total delta > 0 — admitted
        requests whose deadline passed while queued were dropped BEFORE
        dispatch, never executed;
      * zero crash-5xx (no 500s) and a FLAT executor compile counter;
      * accepted-request p99 under the stated bound: whatever the server
        ACCEPTS stays fast (deadline + one batch + scheduling slack);
      * SIGTERM mid-load: admitted in-flight work completes 200, a
        request during the drain gets 503, the flight dump names trigger
        "drain", and the process exits 0 inside the drain budget.

    Artifact: overload_smoke.json.
    """
    import glob
    import signal
    import urllib.error
    import urllib.request

    CHAOS_LAT_S = 0.15      # injected per-batch latency -> capacity ~6.7qps
    QUEUE_DEPTH = 12        # bounded queue: max wait ~ 12 x 0.15 = 1.8s
    DEADLINE_S = 1.2        # propagated client deadline < max queue wait
    DRAIN_TIMEOUT_S = 10.0
    # stated accepted-p99 bound: a request the server ACCEPTS waited at
    # most its deadline, plus one chaos-slowed batch, plus slack
    P99_BOUND_MS = (DEADLINE_S + CHAOS_LAT_S) * 1e3 + 1500

    model_dir = os.path.join(args.out_dir, "demo_model")
    flight_dir = os.path.join(args.out_dir, "flight")
    os.makedirs(flight_dir, exist_ok=True)
    chaos_env = {
        "FLAGS_chaos": "1",
        "FLAGS_chaos_serve_latency_s": str(CHAOS_LAT_S),
        "FLAGS_serving_max_queue_depth": str(QUEUE_DEPTH),
        "FLAGS_serving_drain_timeout_s": str(DRAIN_TIMEOUT_S),
        "FLAGS_flight_dir": flight_dir,
    }
    policy = ["--buckets", "1", "--max-batch", "1", "--max-wait-ms", "1"]
    artifact = {"tool": "serving_smoke.overload",
                "chaos_latency_s": CHAOS_LAT_S,
                "queue_depth": QUEUE_DEPTH,
                "deadline_s": DEADLINE_S,
                "p99_bound_ms": P99_BOUND_MS}

    server = Server(model_dir, policy, extra_env=chaos_env)
    try:
        # -- phase 1: measure capacity (closed loop, no pressure) -------
        cap = run_loadgen(
            server.url, os.path.join(args.out_dir, "loadgen_capacity.json"),
            16, 4, "1", extra=["--timeout-s", "30"])
        assert cap["errors"] == 0, cap
        cap_qps = max(cap["qps"], 1e-3)
        artifact["capacity_qps"] = cap_qps

        # -- phase 2: open-loop flood at ~4x capacity -------------------
        offered = round(4.0 * cap_qps, 2)
        n = max(80, min(300, int(offered * 6)))
        flood = run_loadgen(
            server.url, os.path.join(args.out_dir, "loadgen_flood.json"),
            n, 16, "1",
            extra=["--mode", "open", "--qps", str(offered),
                   "--timeout-s", str(DEADLINE_S),
                   "--max-retries", "0", "--max-error-rate", "1.0"])
        sm = flood["server_metrics"]
        sc = flood["status_counts"]
        assert flood["sheds"] > 0 and sc.get("429", 0) > 0, \
            f"no shedding at {offered} qps offered: {sc}"
        assert flood["retry_after_seen"] > 0, \
            "429s did not carry a Retry-After"
        assert sm["shed_total"] > 0, sm
        assert sm["expired_dropped_total"] > 0, \
            f"no deadline drops (expired requests were executed?): {sm}"
        assert sc.get("500", 0) == 0, f"crash-5xx under overload: {sc}"
        assert sm["executor_compiles_during_load"] == 0, sm
        assert flood["latency_ms"]["p99"] < P99_BOUND_MS, \
            (f"accepted-request p99 {flood['latency_ms']['p99']}ms over "
             f"the {P99_BOUND_MS}ms bound")
        artifact["flood"] = {
            "offered_qps": offered, "requests": n,
            "accepted": flood["completed"],
            "accepted_p99_ms": flood["latency_ms"]["p99"],
            "sheds_429": sc.get("429", 0),
            "retry_after_seen": flood["retry_after_seen"],
            "server_shed_total": sm["shed_total"],
            "expired_dropped_total": sm["expired_dropped_total"],
            "status_counts": sc,
            "compile_delta": sm["executor_compiles_during_load"],
        }
        print(f"overload flood OK: {offered} qps offered vs "
              f"{cap_qps} capacity -> {flood['completed']} accepted "
              f"(p99 {flood['latency_ms']['p99']}ms), "
              f"{sc.get('429', 0)} shed, "
              f"{sm['expired_dropped_total']:.0f} expired-dropped, "
              f"0 crash-5xx, compiles flat", flush=True)
    finally:
        server.close()

    # -- phase 3: SIGTERM mid-load drains and exits 0 -------------------
    server = Server(model_dir, policy, extra_env=chaos_env)
    results = []

    def one_request():
        body = json.dumps({"inputs": {"x": [[0.5] * 32]},
                           "timeout_s": 30}).encode()
        req = urllib.request.Request(
            f"{server.url}/v1/models/demo:predict", data=body,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=30) as r:
                results.append(r.status)
        except urllib.error.HTTPError as e:
            results.append(e.code)
        except Exception as e:  # noqa: BLE001 — recorded for the assert
            results.append(f"{type(e).__name__}: {e}")

    try:
        # ~10 x 0.15s of admitted work = the drain window
        threads = [threading.Thread(target=one_request)
                   for _ in range(10)]
        for t in threads:
            t.start()
        # SIGTERM only once every burst request is ADMITTED (the
        # in-flight gauge counts them) — requests that arrive after the
        # drain begins are 503s by design, not members of this assert
        t_wait = time.monotonic() + 10
        while time.monotonic() < t_wait:
            done_200 = sum(1 for r in results if r == 200)
            inflight = _prom_scalar(scrape(server.url),
                                    "serving_demo_inflight")
            if inflight + done_200 >= len(threads):
                break
            time.sleep(0.05)
        t0 = time.monotonic()
        server.proc.send_signal(signal.SIGTERM)
        time.sleep(0.3)
        # a request DURING the drain: 503, not a hang/5xx-crash
        during = None
        body = json.dumps({"inputs": {"x": [[0.5] * 32]}}).encode()
        req = urllib.request.Request(
            f"{server.url}/v1/models/demo:predict", data=body,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=10) as r:
                during = r.status
        except urllib.error.HTTPError as e:
            during = e.code
        except Exception as e:  # noqa: BLE001
            during = f"{type(e).__name__}"
        for t in threads:
            t.join(timeout=30)
        rc = server.proc.wait(timeout=DRAIN_TIMEOUT_S + 10)
        drain_s = round(time.monotonic() - t0, 3)
    finally:
        server.close()
    assert rc == 0, f"drain exit code {rc} (want 0)"
    assert during == 503, f"request during drain got {during!r} (want 503)"
    assert all(r == 200 for r in results), \
        f"admitted in-flight work did not complete 200: {results}"
    assert drain_s < DRAIN_TIMEOUT_S + 5, drain_s
    dumps = glob.glob(os.path.join(flight_dir, "flight-*-drain.jsonl"))
    assert dumps, f"no drain-trigger flight dump in {flight_dir}"
    with open(dumps[-1]) as f:
        header = json.loads(f.readline())
    assert header.get("trigger") == "drain", header
    artifact["drain"] = {"exit_code": rc, "drain_s": drain_s,
                        "inflight_results": results,
                        "during_drain_status": during,
                        "flight_dump": os.path.basename(dumps[-1])}
    artifact["passed"] = True
    with open(os.path.join(args.out_dir, "overload_smoke.json"), "w") as f:
        json.dump(artifact, f, indent=2)
    print(f"overload gate OK: shed+expired+flat compiles under 4x load; "
          f"SIGTERM drained {len(results)} in-flight in {drain_s}s, "
          f"exit 0, drain flight dump archived", flush=True)


def tracing_gate(args) -> None:
    """[observability] Request-scoped tracing gate (ISSUE 14 acceptance).

    One FLAGS_trace_requests + FLAGS_serving_slo_ms server (predict
    model + demo generation model).  Asserted:

      * loadgen --trace closes the correlation loop: client-generated
        traceparent ids resolve at /v1/traces/<id> with a server-side
        decomposition for the slowest requests in the artifact;
      * a direct predict with a KNOWN traceparent echoes it in the
        response header + meta.trace, and the stored trace carries every
        predict span kind (parse/admission/queue.wait/batch.form/
        batch.pad/batch.exec/debatch/respond + executor.*) with the
        decomposition summing to the request wall clock within 5%;
      * a multi-token :generate trace carries prefill + per-token
        decode.step spans (iteration accounting) under the same 5% sum
        contract;
      * SLO burn-rate gauges + good/bad counters appear on /metrics.

    Artifact: trace_sample.json (the full predict + generate traces).
    """
    import urllib.request

    model_dir = os.path.join(args.out_dir, "demo_model")
    env = {"FLAGS_trace_requests": "1",
           "FLAGS_serving_slo_ms": "demo=2000,gendemo=10000"}
    server = Server(model_dir,
                    ["--buckets", "1,2,4,8", "--max-wait-ms", "4",
                     "--demo-generation", "gendemo", "--gen-slots", "4"],
                    extra_env=env)
    try:
        # -- correlation loop via loadgen --trace -----------------------
        rec = run_loadgen(
            server.url, os.path.join(args.out_dir, "loadgen_trace.json"),
            60, 6, "1,2,3", extra=["--trace"])
        assert rec["errors"] == 0, rec
        st = rec.get("slow_traces")
        assert st, "loadgen --trace produced no slow_traces"
        resolved = [t for t in st
                    if (t.get("server") or {}).get("decomposition")]
        assert resolved, f"no slow trace resolved server-side: {st}"
        print(f"tracing correlation OK: {len(resolved)}/{len(st)} "
              f"slowest-request decompositions resolved via /v1/traces",
              flush=True)

        def fetch_trace(tid):
            with urllib.request.urlopen(
                    f"{server.url}/v1/traces/{tid}", timeout=10) as r:
                return json.loads(r.read())

        def assert_sum(tr, client_ms, label):
            dec = tr["decomposition"]
            total = dec["total_ms"]
            s = sum(dec["components_ms"].values())
            tol = 0.05 * total + 0.5  # 5% + scheduling-jitter floor
            assert abs(s + dec["unattributed_ms"] - total) <= tol, \
                (label, dec)
            assert dec["unattributed_ms"] <= tol, \
                (f"{label}: {dec['unattributed_ms']}ms unattributed of "
                 f"{total}ms", dec)
            assert total <= client_ms + 1.0, \
                (f"{label}: server total exceeds client wall", total,
                 client_ms)

        # -- direct predict with a KNOWN traceparent --------------------
        ptid = "ab" * 16
        body = json.dumps({"inputs": {"x": [[0.5] * 32] * 3}}).encode()
        req = urllib.request.Request(
            f"{server.url}/v1/models/demo:predict", data=body,
            headers={"Content-Type": "application/json",
                     "traceparent": f"00-{ptid}-{'12' * 8}-01"})
        t0 = time.perf_counter()
        with urllib.request.urlopen(req, timeout=30) as r:
            hdr = dict(r.getheaders())
            payload = json.loads(r.read())
        predict_client_ms = (time.perf_counter() - t0) * 1e3
        assert ptid in (hdr.get("traceparent") or ""), hdr
        assert payload["batch"]["trace"]["trace_id"] == ptid, payload
        ptrace = fetch_trace(ptid)
        kinds = {s["name"] for s in ptrace["spans"]}
        need = {"parse", "admission", "queue.wait", "batch.form",
                "batch.pad", "batch.exec", "debatch", "respond"}
        assert need <= kinds, f"predict spans missing: {need - kinds}"
        assert kinds & {"executor.run", "executor.compile"}, kinds
        assert_sum(ptrace, predict_client_ms, "predict")
        pad = ptrace["decomposition"]["padding"]
        assert pad["rows_real"] == 3 and pad["bucket"] == 4 \
            and pad["rows_padded"] == 1, pad
        print(f"predict trace OK: {len(ptrace['spans'])} spans, "
              f"total {ptrace['decomposition']['total_ms']}ms, "
              f"unattributed "
              f"{ptrace['decomposition']['unattributed_ms']}ms, "
              f"padding {pad['rows_padded']}/{pad['bucket']}", flush=True)

        # -- multi-token generation trace -------------------------------
        gtid = "cd" * 16
        t0 = time.perf_counter()
        gen = http_generate(server.url, [3, 5, 7], 16,
                            headers={"traceparent":
                                     f"00-{gtid}-{'34' * 8}-01"})
        gen_client_ms = (time.perf_counter() - t0) * 1e3
        gtrace = fetch_trace(gtid)
        gkinds = {s["name"] for s in gtrace["spans"]}
        gneed = {"parse", "admission", "queue.wait", "prefill",
                 "decode.step", "deliver", "respond"}
        assert gneed <= gkinds, f"generate spans missing: {gneed - gkinds}"
        steps = gtrace["decomposition"].get("decode_steps", 0)
        assert steps >= len(gen["tokens"]) >= 1, (steps, gen)
        assert_sum(gtrace, gen_client_ms, "generate")
        print(f"generation trace OK: {steps} decode iterations, "
              f"total {gtrace['decomposition']['total_ms']}ms, "
              f"ttft linked "
              f"{gtrace['spans'][0]['attrs'].get('ttft_ms')}ms",
              flush=True)

        # -- SLO burn-rate gauges on /metrics ---------------------------
        prom = scrape(server.url)
        for needed in ("serving_demo_slo_burn_rate_5m",
                       "serving_demo_slo_burn_rate_30m",
                       "serving_demo_slo_burn_rate_1h",
                       "serving_demo_slo_good_total",
                       "serving_gendemo_slo_burn_rate_5m"):
            assert needed in prom, f"{needed} missing from /metrics"
        print("SLO burn-rate gauges OK on /metrics", flush=True)

        sample = {
            "tool": "serving_smoke.tracing",
            "predict": ptrace,
            "generate": gtrace,
            "predict_client_ms": round(predict_client_ms, 3),
            "generate_client_ms": round(gen_client_ms, 3),
        }
        with open(os.path.join(args.out_dir, "trace_sample.json"),
                  "w") as f:
            json.dump(sample, f, indent=2)
        print("tracing gate OK: trace_sample.json archived", flush=True)
    finally:
        server.close()


def scrape(url: str) -> str:
    import urllib.request

    with urllib.request.urlopen(f"{url}/metrics", timeout=10) as r:
        return r.read().decode()


def _prom_scalar(text: str, name: str) -> float:
    for line in text.splitlines():
        parts = line.split()
        if len(parts) == 2 and parts[0] == name:
            return float(parts[1])
    return 0.0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--out-dir", default="ci_artifacts/serving")
    p.add_argument("--requests", type=int, default=300,
                   help="smoke-phase request count")
    p.add_argument("--ab-requests", type=int, default=200,
                   help="requests per A/B trial leg")
    p.add_argument("--concurrency", type=int, default=12)
    p.add_argument("--ab-target", type=float, default=2.0,
                   help="required batched/batch1 QPS ratio (best pair)")
    p.add_argument("--ab-trials", type=int, default=8,
                   help="max interleaved trial pairs (early exit on "
                        "target; the budget is sized for noisy shared "
                        "CI boxes where absolute QPS swings ~2x between "
                        "trials — a clean pair usually lands by trial 2)")
    p.add_argument("--gen-ab-target", type=float, default=2.0,
                   help="required concurrent/sequential tokens-per-sec "
                        "ratio for the continuous-batching generation "
                        "gate")
    p.add_argument("--skip-generation", action="store_true",
                   help="skip the generation continuous-batching gate")
    p.add_argument("--skip-overload", action="store_true",
                   help="skip the overload/graceful-drain robustness gate")
    p.add_argument("--skip-tracing", action="store_true",
                   help="skip the request-scoped tracing gate")
    args = p.parse_args(argv)

    os.makedirs(args.out_dir, exist_ok=True)
    model_dir = os.path.join(args.out_dir, "demo_model")
    if not os.path.exists(os.path.join(model_dir, "__model__")):
        export_demo_model(model_dir)

    # The A/B capacity is PINNED by injected per-batch latency
    # (chaos.maybe_serve_latency) on BOTH servers, so the gate ratio is a
    # property of the batching policy, not the CI box: batch1 executes
    # one row per AB_CHAOS_LAT_S (~1/L rows/s) while the batched server
    # coalesces ~concurrency rows into one L-cost batch — the expected
    # ratio is ~min(concurrency, max_batch) >> the 2x target.  (The
    # uninjected gate measured 1.2x-3.3x for the same build across
    # boxes — CHANGES.md PR 13's known box-dependence, resolved here.)
    AB_CHAOS_LAT_S = 0.04
    ab_env = {"FLAGS_chaos": "1",
              "FLAGS_chaos_serve_latency_s": str(AB_CHAOS_LAT_S)}
    policy = ["--buckets", "1,2,4,8,16", "--max-wait-ms", "4"]

    # -- phase 1: shape-varying smoke against an UNARMED server ---------
    # (its own instance: the chaos pin below must not pollute the
    # archived smoke latencies — loadgen_smoke.json measures the real
    # serving path, so a real-latency regression stays visible)
    smoke_srv = Server(model_dir, policy)
    try:
        smoke = run_loadgen(
            smoke_srv.url, os.path.join(args.out_dir, "loadgen_smoke.json"),
            args.requests, args.concurrency, "1,2,3,4")
        assert smoke["errors"] == 0, smoke
        assert smoke["latency_ms"]["p99"] > 0, smoke
        sm = smoke["server_metrics"]
        assert sm["executor_compiles_during_load"] == 0, \
            f"recompile during shape-varying load: {sm}"
        assert sm["unplanned_compiles"] == 0, sm
        assert sm["batch_fill_mean"] is not None, sm
        prom = scrape(smoke_srv.url)
        for needed in ("serving_demo_request_seconds_bucket",
                       "serving_demo_batch_fill_bucket",
                       "serving_demo_queue_seconds_bucket"):
            assert needed in prom, f"{needed} missing from /metrics"
        print(f"serving smoke OK: {smoke['completed']} requests, "
              f"qps={smoke['qps']} p99={smoke['latency_ms']['p99']}ms "
              f"fill={sm['batch_fill_mean']} recompiles=0", flush=True)
    finally:
        smoke_srv.close()

    batched = Server(model_dir, policy, extra_env=ab_env)
    batch1 = Server(model_dir, policy + ["--max-batch", "1"],
                    extra_env=ab_env)
    try:
        # -- phase 2: batched vs batch-size-1 A/B (single-row stream) ---
        trials = []
        best = None
        for t in range(args.ab_trials):
            b = run_loadgen(
                batched.url,
                os.path.join(args.out_dir, "loadgen_batched.json"),
                args.ab_requests, args.concurrency, "1")
            s = run_loadgen(
                batch1.url,
                os.path.join(args.out_dir, "loadgen_batch1.json"),
                args.ab_requests, args.concurrency, "1")
            for rec in (b, s):
                assert rec["errors"] == 0, rec
                assert rec["server_metrics"][
                    "executor_compiles_during_load"] == 0, rec
            ratio = b["qps"] / max(s["qps"], 1e-9)
            trials.append({
                "trial": t, "batched_qps": b["qps"],
                "batch1_qps": s["qps"], "ratio": round(ratio, 3),
                "batched_fill": b["server_metrics"]["batch_fill_mean"],
                "batched_batches": b["server_metrics"]["batches"],
            })
            print(f"A/B trial {t}: batched {b['qps']} qps vs batch1 "
                  f"{s['qps']} qps -> {ratio:.2f}x", flush=True)
            if best is None or ratio > best["ratio"]:
                best = trials[-1]
            if ratio >= args.ab_target:
                break
            time.sleep(1.0)  # let a noisy-neighbour burst pass

        summary = {
            "tool": "serving_smoke",
            "policy": {"buckets": [1, 2, 4, 8, 16], "max_wait_ms": 4.0,
                       "batched_max_batch": 16, "batch1_max_batch": 1},
            "pinned_batch_latency_s": AB_CHAOS_LAT_S,
            "pinned_batch1_capacity_qps": round(1.0 / AB_CHAOS_LAT_S, 1),
            "ab_requests": args.ab_requests,
            "concurrency": args.concurrency,
            "target_ratio": args.ab_target,
            "trials": trials,
            "best": best,
            "passed": best["ratio"] >= args.ab_target,
        }
        with open(os.path.join(args.out_dir, "ab_summary.json"), "w") as f:
            json.dump(summary, f, indent=2)
        print(json.dumps(summary["best"], indent=2))
        if not summary["passed"]:
            print(f"serving A/B gate FAILED: best ratio "
                  f"{best['ratio']}x < {args.ab_target}x "
                  f"across {len(trials)} trials", file=sys.stderr)
            return 1
        print(f"serving A/B gate OK: dynamic batching {best['ratio']}x "
              f"over batch-size-1 at zero recompiles", flush=True)
    finally:
        batched.close()
        batch1.close()

    # -- phase 4: overload shedding + deadline drops + graceful drain ----
    if not args.skip_overload:
        overload_gate(args)

    # -- phase 5: continuous token-level batching (generation tier) ------
    if not args.skip_generation:
        generation_gate(args)

    # -- phase 6: request-scoped tracing + SLO burn rates ----------------
    if not args.skip_tracing:
        tracing_gate(args)
    return 0


if __name__ == "__main__":
    sys.exit(main())
