"""Layer functions (reference: python/paddle/fluid/layers/nn.py — fc:192,
embedding:301, conv2d:1754, batch_norm:2714, layer_norm:3030, matmul:4520,
softmax_with_cross_entropy:5591, dropout, pool2d:2292, ...)."""

from __future__ import annotations

from ..core import framework as fw
from ..initializer import ConstantInitializer
from ..layer_helper import LayerHelper


def fc(
    input,
    size,
    num_flatten_dims=1,
    param_attr=None,
    bias_attr=None,
    act=None,
    is_test=False,
    name=None,
):
    """Fully-connected (reference nn.py:192): mul per input + sum + bias + act."""
    helper = LayerHelper("fc", input=input, param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = helper.input_dtype()
    inputs = input if isinstance(input, (list, tuple)) else [input]
    attrs = (
        param_attr
        if isinstance(param_attr, (list, tuple))
        else [param_attr] * len(inputs)
    )
    mul_results = []
    for x, pa in zip(inputs, attrs):
        in_features = 1
        for d in x.shape[num_flatten_dims:]:
            in_features *= d
        w = helper.create_parameter(pa, shape=[in_features, size], dtype=dtype)
        tmp = helper.create_variable_for_type_inference(dtype)
        helper.append_op(
            "mul",
            inputs={"X": [x], "Y": [w]},
            outputs={"Out": [tmp]},
            attrs={"x_num_col_dims": num_flatten_dims, "y_num_col_dims": 1},
        )
        mul_results.append(tmp)
    if len(mul_results) == 1:
        pre_bias = mul_results[0]
    else:
        pre_bias = helper.create_variable_for_type_inference(dtype)
        helper.append_op("sum", inputs={"X": mul_results}, outputs={"Out": [pre_bias]})
    pre_act = helper.append_bias_op(pre_bias, dim_start=num_flatten_dims)
    return helper.append_activation(pre_act)


def embedding(
    input,
    size,
    is_sparse=False,
    is_distributed=False,
    padding_idx=None,
    param_attr=None,
    dtype="float32",
):
    """reference nn.py:301; `is_sparse` keeps the row-sparse grad path."""
    helper = LayerHelper("embedding", param_attr=param_attr)
    w = helper.create_parameter(helper.param_attr(), shape=list(size), dtype=dtype)
    tmp = helper.create_variable_for_type_inference(dtype)
    padding_idx = (
        -1
        if padding_idx is None
        else padding_idx
        if padding_idx >= 0
        else size[0] + padding_idx
    )
    helper.append_op(
        "lookup_table",
        inputs={"Ids": [input], "W": [w]},
        outputs={"Out": [tmp]},
        attrs={
            "is_sparse": is_sparse,
            "is_distributed": is_distributed,
            "padding_idx": padding_idx,
        },
    )
    return tmp


def fused_embedding(
    inputs,
    size,
    is_sparse=False,
    padding_idx=None,
    param_attrs=None,
    dtype="float32",
):
    """One fused multi-table lookup over a GROUP of slots sharing the
    same [vocab, dim] table shape: each slot keeps its own parameter
    (checkpoint layout identical to per-slot `embedding` calls with the
    same names) but every gather rides one launch
    (kernels/embedding.py multi_table_gather; PERF.md round 8).  Returns
    one output per slot.  `param_attrs` is an optional per-slot list —
    names default to the helper sequence, same as S separate embedding
    calls.  Programs built with per-slot `embedding` get the same fusion
    from the `fused_embedding` graph pass instead (passes.py)."""
    if param_attrs is None:
        param_attrs = [None] * len(inputs)
    if len(param_attrs) != len(inputs):
        raise ValueError(
            f"fused_embedding: {len(inputs)} slots but "
            f"{len(param_attrs)} param_attrs")
    padding_idx = (
        -1
        if padding_idx is None
        else padding_idx
        if padding_idx >= 0
        else size[0] + padding_idx
    )
    ws, outs = [], []
    for attr in param_attrs:
        helper = LayerHelper("embedding", param_attr=attr)
        ws.append(helper.create_parameter(helper.param_attr(),
                                          shape=list(size), dtype=dtype))
        outs.append(helper.create_variable_for_type_inference(dtype))
    helper.append_op(
        "fused_lookup_table",
        inputs={"Ids": list(inputs), "W": ws},
        outputs={"Out": outs},
        attrs={"is_sparse": is_sparse, "padding_idx": padding_idx},
    )
    return outs


def conv2d(
    input,
    num_filters,
    filter_size,
    stride=1,
    padding=0,
    dilation=1,
    groups=None,
    param_attr=None,
    bias_attr=None,
    use_cudnn=True,
    act=None,
    name=None,
    data_format="NCHW",
):
    """reference nn.py:1754 (use_cudnn accepted for API parity; XLA owns
    kernel choice on TPU).  data_format NHWC runs channel-last (the
    MXU-preferred layout; filter param stays OIHW)."""
    helper = LayerHelper("conv2d", param_attr=param_attr, bias_attr=bias_attr,
                         act=act, name=name)
    dtype = input.dtype
    num_channels = input.shape[-1 if data_format == "NHWC" else 1]
    groups = groups or 1

    def _pair(x):
        return list(x) if isinstance(x, (list, tuple)) else [x, x]

    filter_size = _pair(filter_size)
    stride = _pair(stride)
    padding = _pair(padding)
    dilation = _pair(dilation)
    filter_shape = [num_filters, num_channels // groups] + filter_size

    import numpy as np

    from ..initializer import NormalInitializer

    fan_in = (num_channels // groups) * filter_size[0] * filter_size[1]
    std = float(np.sqrt(2.0 / fan_in))
    w = helper.create_parameter(
        helper.param_attr(),
        shape=filter_shape,
        dtype=dtype,
        default_initializer=NormalInitializer(0.0, std),
    )
    pre_bias = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "conv2d",
        inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [pre_bias]},
        attrs={
            "strides": stride,
            "paddings": padding,
            "dilations": dilation,
            "groups": groups,
            "data_format": data_format,
        },
    )
    if bias_attr is False:
        pre_act = pre_bias
    else:
        b = helper.create_parameter(
            helper.bias_attr(), shape=[num_filters], dtype=dtype, is_bias=True
        )
        pre_act = helper.create_variable_for_type_inference(dtype)
        helper.append_op(
            "elementwise_add",
            inputs={"X": [pre_bias], "Y": [b]},
            outputs={"Out": [pre_act]},
            attrs={"axis": -1 if data_format == "NHWC" else 1},
        )
    return helper.append_activation(pre_act)


def conv2d_transpose(
    input,
    num_filters,
    output_size=None,
    filter_size=None,
    padding=0,
    stride=1,
    dilation=1,
    groups=None,
    param_attr=None,
    bias_attr=None,
    act=None,
    name=None,
):
    helper = LayerHelper("conv2d_transpose", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = input.dtype
    num_channels = input.shape[1]
    groups = groups or 1

    def _pair(x):
        return list(x) if isinstance(x, (list, tuple)) else [x, x]

    stride = _pair(stride)
    padding = _pair(padding)
    dilation = _pair(dilation)
    if filter_size is None:
        h = output_size[0] - (input.shape[2] - 1) * stride[0] + 2 * padding[0]
        w_ = output_size[1] - (input.shape[3] - 1) * stride[1] + 2 * padding[1]
        filter_size = [h, w_]
    else:
        filter_size = _pair(filter_size)
    filter_shape = [num_channels, num_filters // groups] + filter_size
    w = helper.create_parameter(helper.param_attr(), shape=filter_shape, dtype=dtype)
    pre_bias = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "conv2d_transpose",
        inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [pre_bias]},
        attrs={
            "strides": stride,
            "paddings": padding,
            "dilations": dilation,
            "groups": groups,
        },
    )
    pre_act = helper.append_bias_op(pre_bias)
    return helper.append_activation(pre_act)


def pool2d(
    input,
    pool_size=-1,
    pool_type="max",
    pool_stride=1,
    pool_padding=0,
    global_pooling=False,
    use_cudnn=True,
    ceil_mode=False,
    exclusive=True,
    name=None,
    data_format="NCHW",
):
    """reference nn.py:2292."""
    helper = LayerHelper("pool2d", name=name)

    def _pair(x):
        return list(x) if isinstance(x, (list, tuple)) else [x, x]

    tmp = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "pool2d",
        inputs={"X": [input]},
        outputs={"Out": [tmp]},
        attrs={
            "pooling_type": pool_type,
            "ksize": _pair(pool_size),
            "strides": _pair(pool_stride),
            "paddings": _pair(pool_padding),
            "global_pooling": global_pooling,
            "ceil_mode": ceil_mode,
            "exclusive": exclusive,
            "data_format": data_format,
        },
    )
    return tmp


def batch_norm(
    input,
    act=None,
    is_test=False,
    momentum=0.9,
    epsilon=1e-5,
    param_attr=None,
    bias_attr=None,
    data_layout="NCHW",
    name=None,
    moving_mean_name=None,
    moving_variance_name=None,
    use_global_stats=False,
):
    """reference nn.py:2714; moving stats are persistable Scope state."""
    helper = LayerHelper("batch_norm", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = input.dtype
    c = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    scale = helper.create_parameter(
        helper.param_attr(), shape=[c], dtype=dtype,
        default_initializer=ConstantInitializer(1.0),
    )
    bias = helper.create_parameter(
        helper.bias_attr(), shape=[c], dtype=dtype, is_bias=True
    )
    mean = helper.create_global_variable(
        name=moving_mean_name or fw.unique_name(".".join([helper.name, "mean"])),
        shape=[c],
        dtype=dtype,
        persistable=True,
    )
    helper.set_variable_initializer(mean, ConstantInitializer(0.0))
    variance = helper.create_global_variable(
        name=moving_variance_name or fw.unique_name(".".join([helper.name, "var"])),
        shape=[c],
        dtype=dtype,
        persistable=True,
    )
    helper.set_variable_initializer(variance, ConstantInitializer(1.0))

    saved_mean = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    saved_var = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "batch_norm",
        inputs={
            "X": [input],
            "Scale": [scale],
            "Bias": [bias],
            "Mean": [mean],
            "Variance": [variance],
        },
        outputs={
            "Y": [out],
            "MeanOut": [mean],
            "VarianceOut": [variance],
            "SavedMean": [saved_mean],
            "SavedVariance": [saved_var],
        },
        attrs={
            "momentum": momentum,
            "epsilon": epsilon,
            "is_test": is_test,
            "data_layout": data_layout,
            "use_global_stats": use_global_stats,
        },
    )
    return helper.append_activation(out)


def conv2d_bn(
    input,
    num_filters,
    filter_size,
    stride=1,
    padding=0,
    dilation=1,
    groups=None,
    param_attr=None,
    bias_attr=None,
    act=None,
    residual=None,
    is_test=False,
    momentum=0.9,
    epsilon=1e-5,
    data_format="NCHW",
    name=None,
    moving_mean_name=None,
    moving_variance_name=None,
    use_global_stats=False,
):
    """Fused conv2d (bias-free) + batch_norm [+ residual add] [+ act] as
    ONE `conv2d_bn` op (ops/nn_ops.py lower_conv2d_bn, kernels/conv_bn.py)
    — the FLAGS_fused_bn route models select for conv->bn[->add->relu]
    chains (models/resnet.py conv_bn_layer).

    Parameters and moving-stat variables are created through the SAME
    LayerHelper name sequence as the unfused `conv2d(bias_attr=False)` +
    `batch_norm` pair, so parameter names — and therefore checkpoints —
    are identical whichever route FLAGS_fused_bn picks (asserted in
    tests/test_conv_bn.py).  `param_attr` names the conv filter attr
    (conv2d parity); scale/bias take batch_norm's defaults.  `act` must
    be None or "relu" (the fusable epilogues); `residual` is added after
    the BN scale/shift and before the activation, replacing the separate
    `elementwise_add(residual, bn, act=act)` op."""
    if act not in (None, "relu"):
        raise ValueError(f"conv2d_bn fuses act None|'relu', got {act!r}")
    conv_helper = LayerHelper("conv2d", param_attr=param_attr, name=name)
    dtype = input.dtype
    num_channels = input.shape[-1 if data_format == "NHWC" else 1]
    groups = groups or 1

    def _pair(x):
        return list(x) if isinstance(x, (list, tuple)) else [x, x]

    filter_size = _pair(filter_size)
    stride = _pair(stride)
    padding = _pair(padding)
    dilation = _pair(dilation)
    filter_shape = [num_filters, num_channels // groups] + filter_size

    import numpy as np

    from ..initializer import NormalInitializer

    fan_in = (num_channels // groups) * filter_size[0] * filter_size[1]
    std = float(np.sqrt(2.0 / fan_in))
    w = conv_helper.create_parameter(
        conv_helper.param_attr(),
        shape=filter_shape,
        dtype=dtype,
        default_initializer=NormalInitializer(0.0, std),
    )

    bn_helper = LayerHelper("batch_norm", bias_attr=bias_attr)
    c = num_filters
    scale = bn_helper.create_parameter(
        bn_helper.param_attr(), shape=[c], dtype=dtype,
        default_initializer=ConstantInitializer(1.0),
    )
    bias = bn_helper.create_parameter(
        bn_helper.bias_attr(), shape=[c], dtype=dtype, is_bias=True
    )
    mean = bn_helper.create_global_variable(
        name=moving_mean_name or fw.unique_name(
            ".".join([bn_helper.name, "mean"])),
        shape=[c],
        dtype=dtype,
        persistable=True,
    )
    bn_helper.set_variable_initializer(mean, ConstantInitializer(0.0))
    variance = bn_helper.create_global_variable(
        name=moving_variance_name or fw.unique_name(
            ".".join([bn_helper.name, "var"])),
        shape=[c],
        dtype=dtype,
        persistable=True,
    )
    bn_helper.set_variable_initializer(variance, ConstantInitializer(1.0))

    saved_mean = bn_helper.create_variable_for_type_inference(
        dtype, stop_gradient=True)
    saved_var = bn_helper.create_variable_for_type_inference(
        dtype, stop_gradient=True)
    out = bn_helper.create_variable_for_type_inference(dtype)
    inputs = {
        "Input": [input],
        "Filter": [w],
        "Scale": [scale],
        "Bias": [bias],
        "Mean": [mean],
        "Variance": [variance],
    }
    if residual is not None:
        inputs["Residual"] = [residual]
    bn_helper.append_op(
        "conv2d_bn",
        inputs=inputs,
        outputs={
            "Y": [out],
            "MeanOut": [mean],
            "VarianceOut": [variance],
            "SavedMean": [saved_mean],
            "SavedVariance": [saved_var],
        },
        attrs={
            "strides": stride,
            "paddings": padding,
            "dilations": dilation,
            "groups": groups,
            "data_format": data_format,
            "momentum": momentum,
            "epsilon": epsilon,
            "is_test": is_test,
            "use_global_stats": use_global_stats,
            "act": act or "",
        },
    )
    return out


def layer_norm(
    input,
    scale=True,
    shift=True,
    begin_norm_axis=1,
    epsilon=1e-5,
    param_attr=None,
    bias_attr=None,
    act=None,
    name=None,
):
    """reference nn.py:3030."""
    helper = LayerHelper("layer_norm", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = input.dtype
    import numpy as np

    norm_shape = [int(np.prod(input.shape[begin_norm_axis:]))]
    inputs = {"X": [input]}
    if scale:
        s = helper.create_parameter(
            helper.param_attr(), shape=norm_shape, dtype=dtype,
            default_initializer=ConstantInitializer(1.0),
        )
        inputs["Scale"] = [s]
    if shift:
        b = helper.create_parameter(
            helper.bias_attr(), shape=norm_shape, dtype=dtype, is_bias=True
        )
        inputs["Bias"] = [b]
    mean = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    var = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "layer_norm",
        inputs=inputs,
        outputs={"Y": [out], "Mean": [mean], "Variance": [var]},
        attrs={"begin_norm_axis": begin_norm_axis, "epsilon": epsilon},
    )
    return helper.append_activation(out)


def dropout(
    x,
    dropout_prob,
    is_test=False,
    seed=None,
    name=None,
    dropout_implementation="downgrade_in_infer",
):
    helper = LayerHelper("dropout", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    mask = helper.create_variable_for_type_inference(x.dtype, stop_gradient=True)
    helper.append_op(
        "dropout",
        inputs={"X": [x]},
        outputs={"Out": [out], "Mask": [mask]},
        attrs={
            "dropout_prob": dropout_prob,
            "is_test": is_test,
            "seed": seed or 0,
            "dropout_implementation": dropout_implementation,
            # static per-op id: forward AND backward regenerate the same
            # mask from fold_in(step_key, rng_id) — no mask residual has
            # to cross fwd->bwd in HBM (ops/nn_ops.py lower_dropout)
            "rng_id": fw.unique_rng_id(),
        },
    )
    return out


def dropout_add(x, residual, dropout_prob, is_test=False, name=None):
    """Fused `dropout(x) + residual` (upscale_in_train semantics) — the
    dropout-add epilogue of every transformer/BERT residual connection,
    lowered as ONE op so the Pallas kernel (kernels/dropout_epilogue.py)
    can regenerate the keep-mask from scalar seeds in fwd AND bwd: no
    mask tensor in HBM, no fwd->bwd residual beyond the seed.  With
    dropout_prob == 0 or in test mode it lowers to a plain add."""
    helper = LayerHelper("dropout_add", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "dropout_add",
        inputs={"X": [x], "Residual": [residual]},
        outputs={"Out": [out]},
        attrs={
            "dropout_prob": dropout_prob,
            "is_test": is_test,
            # static per-op stream id (same scheme as dropout): forward
            # and backward re-derive the same seed from fold_in(step_key,
            # rng_id), so the mask is regenerated, never stored
            "rng_id": fw.unique_rng_id(),
        },
    )
    return out


def softmax(input, use_cudnn=False, name=None, axis=-1):
    helper = LayerHelper("softmax", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "softmax", inputs={"X": [input]}, outputs={"Out": [out]}, attrs={"axis": axis}
    )
    return out


def softmax_with_cross_entropy(
    logits,
    label,
    soft_label=False,
    ignore_index=-100,
    numeric_stable_mode=True,
    return_softmax=False,
):
    """reference nn.py:5591."""
    helper = LayerHelper("softmax_with_cross_entropy")
    softmax_out = helper.create_variable_for_type_inference(logits.dtype)
    loss = helper.create_variable_for_type_inference(logits.dtype)
    helper.append_op(
        "softmax_with_cross_entropy",
        inputs={"Logits": [logits], "Label": [label]},
        outputs={"Softmax": [softmax_out], "Loss": [loss]},
        attrs={"soft_label": soft_label, "ignore_index": ignore_index},
    )
    if return_softmax:
        return loss, softmax_out
    return loss


def cross_entropy(input, label, soft_label=False, ignore_index=-100):
    helper = LayerHelper("cross_entropy")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "cross_entropy",
        inputs={"X": [input], "Label": [label]},
        outputs={"Y": [out]},
        attrs={"soft_label": soft_label, "ignore_index": ignore_index},
    )
    return out


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0, name=None):
    helper = LayerHelper("matmul", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "matmul",
        inputs={"X": [x], "Y": [y]},
        outputs={"Out": [out]},
        attrs={
            "transpose_X": transpose_x,
            "transpose_Y": transpose_y,
            "alpha": float(alpha),
        },
    )
    return out


def mean(x, name=None):
    helper = LayerHelper("mean", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    if out.shape is None or True:
        out.shape = ()
    helper.append_op("mean", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def accuracy(input, label, k=1, correct=None, total=None):
    """reference: layers/metric_op.py accuracy — topk + accuracy op."""
    helper = LayerHelper("accuracy")
    topk_out = helper.create_variable_for_type_inference(input.dtype)
    topk_indices = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        "top_k",
        inputs={"X": [input]},
        outputs={"Out": [topk_out], "Indices": [topk_indices]},
        attrs={"k": k},
    )
    acc_out = helper.create_variable_for_type_inference("float32")
    correct = correct or helper.create_variable_for_type_inference("int32")
    total = total or helper.create_variable_for_type_inference("int32")
    helper.append_op(
        "accuracy",
        inputs={"Out": [topk_out], "Indices": [topk_indices], "Label": [label]},
        outputs={"Accuracy": [acc_out], "Correct": [correct], "Total": [total]},
    )
    return acc_out


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1, slide_steps=1):
    helper = LayerHelper("auc")
    stat_pos = helper.create_global_variable(
        persistable=True,
        name=fw.unique_name("auc_stat_pos"),
        shape=[num_thresholds + 1],
        dtype="float32",
    )
    helper.set_variable_initializer(stat_pos, ConstantInitializer(0.0))
    stat_neg = helper.create_global_variable(
        persistable=True,
        name=fw.unique_name("auc_stat_neg"),
        shape=[num_thresholds + 1],
        dtype="float32",
    )
    helper.set_variable_initializer(stat_neg, ConstantInitializer(0.0))
    auc_out = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        "auc",
        inputs={"Predict": [input], "Label": [label], "StatPos": [stat_pos], "StatNeg": [stat_neg]},
        outputs={"AUC": [auc_out], "StatPosOut": [stat_pos], "StatNegOut": [stat_neg]},
        attrs={"curve": curve, "num_thresholds": num_thresholds},
    )
    return auc_out, [stat_pos, stat_neg]


def topk(input, k=1, name=None):
    helper = LayerHelper("top_k", name=name)
    values = helper.create_variable_for_type_inference(input.dtype)
    indices = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        "top_k",
        inputs={"X": [input]},
        outputs={"Out": [values], "Indices": [indices]},
        attrs={"k": k},
    )
    return values, indices


def l2_normalize(x, axis, epsilon=1e-12, name=None):
    helper = LayerHelper("l2_normalize", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    norm = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "norm",
        inputs={"X": [x]},
        outputs={"Out": [out], "Norm": [norm]},
        attrs={"axis": 1 if axis is None else axis, "epsilon": epsilon},
    )
    return out


def group_norm(input, groups, epsilon=1e-5, param_attr=None, bias_attr=None,
               act=None, name=None):
    helper = LayerHelper("group_norm", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = input.dtype
    c = input.shape[1]
    inputs = {"X": [input]}
    if param_attr is not False:
        scale = helper.create_parameter(
            helper.param_attr(), shape=[c], dtype=dtype,
            default_initializer=ConstantInitializer(1.0),
        )
        inputs["Scale"] = [scale]
    if bias_attr is not False:
        bias = helper.create_parameter(
            helper.bias_attr(), shape=[c], dtype=dtype, is_bias=True
        )
        inputs["Bias"] = [bias]
    mean = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    var = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "group_norm",
        inputs=inputs,
        outputs={"Y": [out], "Mean": [mean], "Variance": [var]},
        attrs={"groups": groups, "epsilon": epsilon},
    )
    return helper.append_activation(out)


# ---------------------------------------------------------------------------
# Misc losses / similarity / utility layers (reference layers/nn.py:
# cos_sim:1190, multiplex:5559, smooth_l1:5700, label_smooth:6334,
# selu:7047, mean_iou:7087, crop:7141, rank_loss:7358, affine_channel:9040,
# similarity_focus:9081, add_position_encoding:9438,
# bilinear_tensor_product:9488, fsp_matrix:9900)
# ---------------------------------------------------------------------------


def cos_sim(X, Y, name=None):
    helper = LayerHelper("cos_sim", name=name)
    out = helper.create_variable_for_type_inference(X.dtype)
    xnorm = helper.create_variable_for_type_inference(X.dtype)
    ynorm = helper.create_variable_for_type_inference(X.dtype)
    helper.append_op(
        "cos_sim",
        inputs={"X": [X], "Y": [Y]},
        outputs={"Out": [out], "XNorm": [xnorm], "YNorm": [ynorm]},
    )
    return out


def where(condition, x, y, name=None):
    """Ternary select: out = condition ? x : y, with broadcasting on
    condition (TPU-native addition — modern paddle.where semantics; used
    internally by IfElse's merge).  Differentiable in x/y."""
    helper = LayerHelper("where", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "where",
        inputs={"Condition": [condition], "X": [x], "Y": [y]},
        outputs={"Out": [out]},
    )
    return out


def multiplex(inputs, index, name=None):
    helper = LayerHelper("multiplex", name=name)
    out = helper.create_variable_for_type_inference(inputs[0].dtype)
    helper.append_op(
        "multiplex",
        inputs={"X": list(inputs), "Ids": [index]},
        outputs={"Out": [out]},
    )
    return out


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=None,
              name=None):
    helper = LayerHelper("smooth_l1_loss", name=name)
    diff = helper.create_variable_for_type_inference(x.dtype)
    out = helper.create_variable_for_type_inference(x.dtype)
    ins = {"X": [x], "Y": [y]}
    if inside_weight is not None:
        ins["InsideWeight"] = [inside_weight]
    if outside_weight is not None:
        ins["OutsideWeight"] = [outside_weight]
    helper.append_op(
        "smooth_l1_loss",
        inputs=ins,
        outputs={"Diff": [diff], "Out": [out]},
        attrs={"sigma": 1.0 if sigma is None else sigma},
    )
    return out


def label_smooth(label, prior_dist=None, epsilon=0.1, dtype="float32",
                 name=None):
    helper = LayerHelper("label_smooth", name=name)
    out = helper.create_variable_for_type_inference(dtype)
    ins = {"X": [label]}
    if prior_dist is not None:
        ins["PriorDist"] = [prior_dist]
    helper.append_op(
        "label_smooth",
        inputs=ins,
        outputs={"Out": [out]},
        attrs={"epsilon": float(epsilon)},
    )
    return out


def selu(x, scale=None, alpha=None, name=None):
    helper = LayerHelper("selu", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    attrs = {}
    if scale is not None:
        attrs["scale"] = float(scale)
    if alpha is not None:
        attrs["alpha"] = float(alpha)
    helper.append_op("selu", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs=attrs)
    return out


def mean_iou(input, label, num_classes):
    helper = LayerHelper("mean_iou")
    iou = helper.create_variable_for_type_inference("float32")
    wrong = helper.create_variable_for_type_inference("int32")
    correct = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        "mean_iou",
        inputs={"Predictions": [input], "Labels": [label]},
        outputs={
            "OutMeanIou": [iou], "OutWrong": [wrong], "OutCorrect": [correct]
        },
        attrs={"num_classes": int(num_classes)},
    )
    return iou, wrong, correct


def crop(x, shape=None, offsets=None, name=None):
    helper = LayerHelper("crop", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    ins = {"X": [x]}
    attrs = {}
    if isinstance(shape, (list, tuple)):
        attrs["shape"] = list(shape)
    elif shape is not None:
        ins["Y"] = [shape]
    if isinstance(offsets, (list, tuple)):
        attrs["offsets"] = list(offsets)
    elif offsets is not None:
        ins["Offsets"] = [offsets]
    helper.append_op("crop", inputs=ins, outputs={"Out": [out]}, attrs=attrs)
    return out


def rank_loss(label, left, right, name=None):
    helper = LayerHelper("rank_loss", name=name)
    out = helper.create_variable_for_type_inference(left.dtype)
    helper.append_op(
        "rank_loss",
        inputs={"Label": [label], "Left": [left], "Right": [right]},
        outputs={"Out": [out]},
    )
    return out


def affine_channel(x, scale=None, bias=None, data_layout="NCHW", name=None):
    helper = LayerHelper("affine_channel", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "affine_channel",
        inputs={"X": [x], "Scale": [scale], "Bias": [bias]},
        outputs={"Out": [out]},
        attrs={"data_layout": data_layout},
    )
    return out


def similarity_focus(input, axis, indexes, name=None):
    helper = LayerHelper("similarity_focus", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "similarity_focus",
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={"axis": int(axis), "indexes": list(indexes)},
    )
    return out


def add_position_encoding(input, alpha, beta, name=None):
    helper = LayerHelper("add_position_encoding", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "add_position_encoding",
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={"alpha": float(alpha), "beta": float(beta)},
    )
    return out


def bilinear_tensor_product(x, y, size, act=None, name=None, param_attr=None,
                            bias_attr=None):
    helper = LayerHelper("bilinear_tensor_product", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = x.dtype
    w = helper.create_parameter(
        helper.param_attr(), shape=[size, x.shape[1], y.shape[1]], dtype=dtype
    )
    ins = {"X": [x], "Y": [y], "Weight": [w]}
    if bias_attr is not False:
        bias = helper.create_parameter(
            helper.bias_attr(), shape=[1, size], dtype=dtype, is_bias=True
        )
        ins["Bias"] = [bias]
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "bilinear_tensor_product", inputs=ins, outputs={"Out": [out]}
    )
    return helper.append_activation(out)


def fsp_matrix(x, y):
    helper = LayerHelper("fsp")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("fsp", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]})
    return out


def beam_search(pre_ids, pre_scores, ids, scores, beam_size, end_id,
                level=0, name=None, return_parent_idx=True):
    """One beam-search step (reference: python/paddle/fluid/layers/nn.py:3833,
    operators/beam_search_op.cc:1).

    Dense TPU form: `scores` is the full [batch, beam, vocab] next-token
    log-prob tensor (the reference takes pre-top-k'd ragged (ids, scores)
    LoD pairs; on TPU the single fused top-k over beam*vocab is cheaper than
    host-side pruning).  `ids` is accepted for signature parity and ignored;
    `level` is meaningless without LoD.

    For the first step feed pre_scores as [0, -inf, -inf, ...] per sentence
    so identical beams don't fill the whole top-k.

    Returns (selected_ids, selected_scores[, parent_idx]) — each
    [batch, beam_size].
    """
    helper = LayerHelper("beam_search", name=name)
    sel_ids = helper.create_variable_for_type_inference("int64")
    sel_scores = helper.create_variable_for_type_inference("float32")
    parent_idx = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        "beam_search",
        inputs={
            "PreIds": [pre_ids],
            "PreScores": [pre_scores],
            "Scores": [scores],
        },
        outputs={
            "SelectedIds": [sel_ids],
            "SelectedScores": [sel_scores],
            "ParentIdx": [parent_idx],
        },
        attrs={"beam_size": beam_size, "end_id": end_id, "level": level},
    )
    if return_parent_idx:
        return sel_ids, sel_scores, parent_idx
    return sel_ids, sel_scores


def beam_search_decode(ids, scores, beam_size, end_id, parents=None,
                       num_steps=None, name=None):
    """Backtrack beam-search steps into full hypotheses (reference:
    python/paddle/fluid/layers/nn.py:3946, beam_search_decode_op.cc:1).

    `ids` is the stacked tensor-array of selected ids [T, batch, beam] and
    `parents` the matching stacked ParentIdx steps (the reference encodes
    parents implicitly in LoD; dense beams need them explicit).  `scores`
    is the FINAL [batch, beam] cumulative score tensor.  `num_steps`
    (optional [1] int) masks unused array slack.

    Returns (sentence_ids [batch, beam, T] int64 end_id-padded,
    sentence_scores [batch, beam]).
    """
    if parents is None:
        raise ValueError(
            "beam_search_decode: dense beams need `parents` (the stacked "
            "ParentIdx array from beam_search)")
    helper = LayerHelper("beam_search_decode", name=name)
    sent_ids = helper.create_variable_for_type_inference("int64")
    sent_scores = helper.create_variable_for_type_inference("float32")
    inputs = {"Ids": [ids], "Parents": [parents], "Scores": [scores]}
    if num_steps is not None:
        inputs["NumSteps"] = [num_steps]
    helper.append_op(
        "beam_search_decode",
        inputs=inputs,
        outputs={"SentenceIds": [sent_ids], "SentenceScores": [sent_scores]},
        attrs={"beam_size": beam_size, "end_id": end_id},
    )
    return sent_ids, sent_scores


def sample_token(logits, strategy="greedy", temperature=1.0, top_k=0,
                 name=None):
    """Next-token selection from [batch, vocab] logits (the generation
    tier's sampling op, ops/generation_ops.py): "greedy" argmax (no PRNG
    — the decode program compiles key-free), or "sample" for a
    temperature-scaled categorical draw optionally truncated to the
    top_k logits.  Returns [batch, 1] int64."""
    if strategy not in ("greedy", "sample"):
        raise ValueError(
            f"sample_token: strategy must be 'greedy' or 'sample', "
            f"got {strategy!r}")
    helper = LayerHelper("sample_token", name=name)
    out = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        "sample_token",
        inputs={"Logits": [logits]},
        outputs={"Out": [out]},
        attrs={"strategy": strategy, "temperature": float(temperature),
               "top_k": int(top_k)},
    )
    return out


def nce(input, label, num_total_classes, sample_weight=None, param_attr=None,
        bias_attr=None, num_neg_samples=None, name=None, sampler="uniform",
        custom_dist=None, seed=0, is_sparse=False):
    """Noise-contrastive estimation (reference: python/paddle/fluid/layers/
    nn.py nce, operators/nce_op.cc:1).  Only the uniform sampler is
    implemented (custom_dist/log_uniform fall back to it); is_sparse is
    accepted for parity but grads are dense."""
    helper = LayerHelper("nce", name=name, param_attr=param_attr,
                         bias_attr=bias_attr)
    dim = input.shape[-1]
    w = helper.create_parameter(
        attr=helper.param_attr(), shape=[num_total_classes, dim],
        dtype=input.dtype)
    inputs = {"Input": [input], "Label": [label], "Weight": [w]}
    if helper.bias_attr() is not False:
        b = helper.create_parameter(
            attr=helper.bias_attr(), shape=[num_total_classes],
            dtype=input.dtype, is_bias=True)
        inputs["Bias"] = [b]
    if sample_weight is not None:
        inputs["SampleWeight"] = [sample_weight]
    cost = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        "nce",
        inputs=inputs,
        outputs={"Cost": [cost]},
        attrs={
            "num_total_classes": num_total_classes,
            "num_neg_samples": num_neg_samples or 10,
            "seed": seed,
        },
    )
    return cost


def hsigmoid(input, label, num_classes, param_attr=None, bias_attr=None,
             name=None, path_table=None, path_code=None, is_custom=False,
             is_sparse=False):
    """Hierarchical sigmoid (reference: layers/nn.py hsigmoid,
    hierarchical_sigmoid_op.cc:1).  Default complete binary tree, or a
    CUSTOM tree via path_table/path_code Variables ([b, L] row-ids into W
    with negative padding / 0-1 branch codes — matrix_bit_code.h
    CustomCode semantics).  With a custom tree, num_classes is the number
    of non-leaf nodes + 1 (W has num_classes - 1 rows), per the
    reference API."""
    if is_custom and (path_table is None or path_code is None):
        raise ValueError("hsigmoid: is_custom needs path_table + path_code")
    helper = LayerHelper("hsigmoid", name=name, param_attr=param_attr,
                         bias_attr=bias_attr)
    dim = input.shape[-1]
    w = helper.create_parameter(
        attr=helper.param_attr(), shape=[num_classes - 1, dim],
        dtype=input.dtype)
    inputs = {"X": [input], "Label": [label], "W": [w]}
    if path_table is not None:
        inputs["PathTable"] = [path_table]
        inputs["PathCode"] = [path_code]
    if helper.bias_attr() is not False:
        b = helper.create_parameter(
            attr=helper.bias_attr(), shape=[num_classes - 1],
            dtype=input.dtype, is_bias=True)
        inputs["Bias"] = [b]
    out = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        "hierarchical_sigmoid",
        inputs=inputs,
        outputs={"Out": [out]},
        attrs={"num_classes": num_classes},
    )
    return out


def linear_chain_crf(input, label, param_attr=None, length=None):
    """Linear-chain CRF NLL (reference: layers/nn.py linear_chain_crf,
    linear_chain_crf_op.cc:1).  Dense form: input [b, T, n] emissions +
    label [b, T] + optional length [b] (the reference reads LoD).  Returns
    the per-sequence negative log-likelihood [b, 1]."""
    helper = LayerHelper("linear_chain_crf", param_attr=param_attr)
    n_tags = input.shape[-1]
    transition = helper.create_parameter(
        attr=helper.param_attr(), shape=[n_tags + 2, n_tags],
        dtype=input.dtype)
    inputs = {"Emission": [input], "Transition": [transition],
              "Label": [label]}
    if length is not None:
        inputs["Length"] = [length]
    ll = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        "linear_chain_crf",
        inputs=inputs,
        outputs={"LogLikelihood": [ll]},
    )
    return ll


def crf_decoding(input, param_attr, label=None, length=None):
    """Viterbi decode with the CRF transition param (reference: layers/nn.py
    crf_decoding, crf_decoding_op.cc:1).  param_attr must name the SAME
    transition parameter linear_chain_crf created."""
    helper = LayerHelper("crf_decoding", param_attr=param_attr)
    n_tags = input.shape[-1]
    transition = helper.create_parameter(
        attr=helper.param_attr(), shape=[n_tags + 2, n_tags],
        dtype=input.dtype)
    inputs = {"Emission": [input], "Transition": [transition]}
    if label is not None:
        inputs["Label"] = [label]
    if length is not None:
        inputs["Length"] = [length]
    path = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        "crf_decoding",
        inputs=inputs,
        outputs={"ViterbiPath": [path]},
    )
    return path


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None, name=None):
    """NCDHW 3-D convolution (reference: layers/nn.py conv3d,
    conv_op.cc Conv3D)."""
    helper = LayerHelper("conv3d", name=name, param_attr=param_attr,
                         bias_attr=bias_attr)

    def triple(v):
        return [v] * 3 if isinstance(v, int) else list(v)

    fs = triple(filter_size)
    c_in = input.shape[1]
    w = helper.create_parameter(
        attr=helper.param_attr(),
        shape=[num_filters, c_in // groups] + fs,
        dtype=input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "conv3d",
        inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [out]},
        attrs={
            "strides": triple(stride),
            "paddings": triple(padding),
            "dilations": triple(dilation),
            "groups": groups,
        },
    )
    if helper.bias_attr() is not False:
        b = helper.create_parameter(
            attr=helper.bias_attr(), shape=[num_filters],
            dtype=input.dtype, is_bias=True)
        biased = helper.create_variable_for_type_inference(input.dtype)
        helper.append_op(
            "elementwise_add",
            inputs={"X": [out], "Y": [b]},
            outputs={"Out": [biased]},
            attrs={"axis": 1},
        )
        out = biased
    return helper.append_activation(out)


def pool3d(input, pool_size=2, pool_type="max", pool_stride=None,
           pool_padding=0, global_pooling=False, name=None):
    """NCDHW 3-D pooling (reference: layers/nn.py pool3d)."""
    helper = LayerHelper("pool3d", name=name)

    def triple(v):
        return [v] * 3 if isinstance(v, int) else list(v)

    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "pool3d",
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={
            "ksize": triple(pool_size),
            "strides": triple(pool_stride or pool_size),
            "paddings": triple(pool_padding),
            "pooling_type": pool_type,
            "global_pooling": global_pooling,
        },
    )
    return out


def chunk_eval(input, label, chunk_scheme, num_chunk_types,
               excluded_chunk_types=None, length=None):
    """Chunking evaluation (reference: layers/nn.py chunk_eval,
    chunk_eval_op.h).  Returns (precision, recall, f1, num_infer,
    num_label, num_correct)."""
    helper = LayerHelper("chunk_eval")
    outs = [helper.create_variable_for_type_inference(dt)
            for dt in ("float32", "float32", "float32",
                       "int64", "int64", "int64")]
    inputs = {"Inference": [input], "Label": [label]}
    if length is not None:
        inputs["Length"] = [length]
    helper.append_op(
        "chunk_eval",
        inputs=inputs,
        outputs={
            "Precision": [outs[0]],
            "Recall": [outs[1]],
            "F1-Score": [outs[2]],
            "NumInferChunks": [outs[3]],
            "NumLabelChunks": [outs[4]],
            "NumCorrectChunks": [outs[5]],
        },
        attrs={
            "chunk_scheme": chunk_scheme,
            "num_chunk_types": num_chunk_types,
            "excluded_chunk_types": list(excluded_chunk_types or []),
        },
    )
    return tuple(outs)


def sigmoid_cross_entropy_with_logits(x, label, ignore_index=-100,
                                      name=None, normalize=False):
    """reference: layers/nn.py sigmoid_cross_entropy_with_logits,
    sigmoid_cross_entropy_with_logits_op.cc."""
    helper = LayerHelper("sigmoid_cross_entropy_with_logits", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "sigmoid_cross_entropy_with_logits",
        inputs={"X": [x], "Label": [label]},
        outputs={"Out": [out]},
        attrs={"ignore_index": ignore_index, "normalize": normalize},
    )
    return out


# ---------------------------------------------------------------------------
# registry-parity wrappers (round 4): every registered op reachable from the
# DSL (tests/test_registry_coverage.py enforces this)
# ---------------------------------------------------------------------------

from .tensor import (  # noqa: E402
    concat,
    one_hot,
    reduce_sum,
    reduce_mean,
    scale,
    ones,
    fill_constant,
    elementwise_add,
    elementwise_sub,
    elementwise_mul,
    elementwise_div,
)


def maxout(x, groups, name=None):
    """Max across `groups` channel slices (reference maxout_op.cc,
    layers/nn.py maxout)."""
    helper = LayerHelper("maxout", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("maxout", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"groups": groups})
    if x.shape:
        out.shape = (x.shape[0], x.shape[1] // groups) + tuple(x.shape[2:])
    return out


def prelu(x, mode="all", param_attr=None, name=None):
    """Parametric ReLU; mode in {all, channel, element} sizes the learned
    Alpha (reference prelu_op.cc, layers/nn.py prelu)."""
    helper = LayerHelper("prelu", param_attr=param_attr, name=name)
    if mode == "all":
        alpha_shape = [1]
    elif mode == "channel":
        alpha_shape = [1, x.shape[1], 1, 1]
    elif mode == "element":
        alpha_shape = [1] + list(x.shape[1:])
    else:
        raise ValueError("prelu mode must be all/channel/element")
    alpha = helper.create_parameter(
        helper.param_attr(), shape=alpha_shape, dtype=x.dtype,
        default_initializer=ConstantInitializer(0.25),
    )
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("prelu", inputs={"X": [x], "Alpha": [alpha]},
                     outputs={"Out": [out]}, attrs={"mode": mode})
    out.shape = x.shape
    return out


def row_conv(input, future_context_size, param_attr=None, act=None):
    """Lookahead (row) convolution over time (reference row_conv_op.cc,
    layers/nn.py row_conv)."""
    helper = LayerHelper("row_conv", param_attr=param_attr, act=act)
    d = input.shape[-1]
    w = helper.create_parameter(
        helper.param_attr(), shape=[future_context_size + 1, d],
        dtype=input.dtype,
    )
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("row_conv", inputs={"X": [input], "Filter": [w]},
                     outputs={"Out": [out]})
    out.shape = input.shape
    return helper.append_activation(out)


def conv_shift(x, y, name=None):
    """Circular correlation (reference conv_shift_op.cc)."""
    helper = LayerHelper("conv_shift", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("conv_shift", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]})
    out.shape = x.shape
    return out


def adaptive_pool2d(input, pool_size, pool_type="avg", name=None):
    """Adaptive avg/max pool to a fixed output grid (reference
    adaptive pooling path of pool_op.cc)."""
    helper = LayerHelper("adaptive_pool2d", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "adaptive_pool2d",
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={"pooling_size": list(pool_size), "pooling_type": pool_type},
    )
    if input.shape:
        out.shape = tuple(input.shape[:2]) + tuple(pool_size)
    return out


def lstm_unit(x_t, hidden_t_prev, cell_t_prev, forget_bias=0.0,
              param_attr=None, bias_attr=None, name=None):
    """One LSTM step: fc([x_t, h_prev]) -> lstm_unit op (reference
    layers/nn.py lstm_unit / lstm_unit_op.cc). Returns (hidden, cell)."""
    helper = LayerHelper("lstm_unit", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    d = cell_t_prev.shape[-1]
    concat_in = concat([x_t, hidden_t_prev], axis=1)
    # bias_attr=None means the reference default: a trainable zero bias
    gates = fc(concat_in, size=4 * d, param_attr=param_attr,
               bias_attr=bias_attr)
    c = helper.create_variable_for_type_inference(x_t.dtype)
    h = helper.create_variable_for_type_inference(x_t.dtype)
    helper.append_op(
        "lstm_unit",
        inputs={"X": [gates], "C_prev": [cell_t_prev]},
        outputs={"C": [c], "H": [h]},
        attrs={"forget_bias": float(forget_bias)},
    )
    c.shape = cell_t_prev.shape
    h.shape = cell_t_prev.shape
    return h, c


def unpool(x, indices, ksize, strides=None, output_size=None, name=None):
    """Max-unpooling with saved flat indices (reference unpool_op.cc)."""
    if strides is not None and list(strides) != list(ksize):
        raise NotImplementedError(
            "unpool: the lowering assumes strides == ksize "
            f"(got strides={strides}, ksize={ksize})")
    helper = LayerHelper("unpool", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    attrs = {"ksize": list(ksize)}
    if output_size:
        attrs["output_size"] = list(output_size)
    helper.append_op("unpool", inputs={"X": [x], "Indices": [indices]},
                     outputs={"Out": [out]}, attrs=attrs)
    return out


def space_to_depth(x, blocksize, name=None):
    """Rearrange spatial blocks into channels (reference
    space_to_depth_op.cc)."""
    helper = LayerHelper("space_to_depth", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("space_to_depth", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"blocksize": blocksize})
    if x.shape:
        n, c, h, w = x.shape
        out.shape = (n, c * blocksize * blocksize,
                     h // blocksize, w // blocksize)
    return out


def pad2d(input, paddings=(0, 0, 0, 0), mode="constant", pad_value=0.0,
          data_format="NCHW", name=None):
    """Pad H/W dims with constant/reflect/edge modes (reference
    pad2d_op.cc)."""
    helper = LayerHelper("pad2d", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "pad2d",
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={"paddings": list(paddings), "mode": mode,
               "pad_value": float(pad_value), "data_format": data_format},
    )
    return out


def image_resize(input, out_shape=None, scale=None, resample="BILINEAR",
                 name=None):
    """Resize [N,C,H,W] images (reference layers/nn.py:6526 image_resize,
    bilinear_interp_op.cc / nearest_interp_op.cc)."""
    if resample.upper() not in ("BILINEAR", "NEAREST"):
        raise ValueError("image_resize resample must be BILINEAR or NEAREST")
    if out_shape is None:
        if scale is None:
            raise ValueError("one of out_shape/scale is required")
        out_shape = [int(input.shape[2] * scale), int(input.shape[3] * scale)]
    op_type = ("bilinear_interp" if resample.upper() == "BILINEAR"
               else "nearest_interp")
    helper = LayerHelper(op_type, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        op_type,
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={"out_h": int(out_shape[0]), "out_w": int(out_shape[1])},
    )
    if input.shape:
        out.shape = tuple(input.shape[:2]) + (int(out_shape[0]),
                                              int(out_shape[1]))
    return out


def resize_bilinear(input, out_shape=None, scale=None, name=None):
    return image_resize(input, out_shape, scale, "BILINEAR", name)


def resize_nearest(input, out_shape=None, scale=None, name=None):
    return image_resize(input, out_shape, scale, "NEAREST", name)


def grid_sampler(x, grid, name=None):
    """Bilinear spatial sampling of x at grid coords (reference
    layers/nn.py:9266 grid_sampler, grid_sampler_op.cc)."""
    helper = LayerHelper("grid_sampler", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("grid_sampler", inputs={"X": [x], "Grid": [grid]},
                     outputs={"Output": [out]})
    if x.shape and grid.shape:
        out.shape = tuple(x.shape[:2]) + tuple(grid.shape[1:3])
    return out


def affine_grid(theta, out_shape, name=None):
    """Generate a sampling grid from batched 2x3 affine matrices (reference
    layers/nn.py:7239 affine_grid, affine_grid_op.cc). out_shape must be a
    static [N,C,H,W] list on TPU."""
    helper = LayerHelper("affine_grid", name=name)
    out = helper.create_variable_for_type_inference(theta.dtype)
    helper.append_op(
        "affine_grid",
        inputs={"Theta": [theta]},
        outputs={"Output": [out]},
        attrs={"output_shape": [int(s) for s in out_shape]},
    )
    if theta.shape:
        out.shape = (theta.shape[0], int(out_shape[-2]), int(out_shape[-1]), 2)
    return out


def random_crop(x, shape, seed=None):
    """Per-instance random crop of the trailing dims (reference
    layers/nn.py:6944 random_crop, random_crop_op.cc; the seed rides the
    executor's threefry key)."""
    helper = LayerHelper("random_crop")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "random_crop",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"shape": [int(s) for s in shape]},
    )
    if x.shape:
        out.shape = tuple(x.shape[: len(x.shape) - len(shape)]) + tuple(shape)
    return out


def hash(input, hash_size, num_hash=1, name=None):
    """Hash integer id rows num_hash times into [0, hash_size) (reference
    layers/nn.py:9196 hash, hash_op.cc)."""
    helper = LayerHelper("hash", name=name)
    out = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        "hash",
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={"num_hash": num_hash, "mod_by": hash_size},
    )
    if input.shape:
        out.shape = (input.shape[0], num_hash, 1)
    return out


def dice_loss(input, label, epsilon=1e-5):
    """Dice coefficient loss for segmentation (reference layers/nn.py:6485
    dice_loss — a composition, as in the reference)."""
    label = one_hot(label, depth=input.shape[-1])
    reduce_dims = list(range(1, len(input.shape)))
    inse = reduce_sum(elementwise_mul(input, label), dim=reduce_dims)
    dice_denominator = elementwise_add(
        reduce_sum(input, dim=reduce_dims),
        reduce_sum(label, dim=reduce_dims),
    )
    dice_score = elementwise_sub(
        ones([1], input.dtype),
        elementwise_div(
            scale(inse, scale=2.0),
            elementwise_add(dice_denominator,
                            fill_constant([1], input.dtype, epsilon)),
        ),
    )
    return reduce_mean(dice_score)


def square_error_cost(input, label):
    """(input - label)^2 (reference squared_l2 square_error_cost layer,
    operators/squared_l2_... / square_error_cost in layers/nn.py)."""
    helper = LayerHelper("square_error_cost")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "square_error_cost",
        inputs={"X": [input], "Y": [label]},
        outputs={"Out": [out]},
    )
    out.shape = input.shape
    return out


def squared_l2_distance(x, y, name=None):
    """Row-wise squared euclidean distance (reference
    squared_l2_distance_op.h)."""
    helper = LayerHelper("squared_l2_distance", name=name)
    sub = helper.create_variable_for_type_inference(x.dtype)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "squared_l2_distance",
        inputs={"X": [x], "Y": [y]},
        outputs={"sub_result": [sub], "Out": [out]},
    )
    if x.shape:
        out.shape = (x.shape[0], 1)
    return out


def modified_huber_loss(input, label, name=None):
    """Classification huber variant (reference modified_huber_loss_op.h)."""
    helper = LayerHelper("modified_huber_loss", name=name)
    inter = helper.create_variable_for_type_inference(input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "modified_huber_loss",
        inputs={"X": [input], "Y": [label]},
        outputs={"IntermediateVal": [inter], "Out": [out]},
    )
    out.shape = input.shape
    return out


def teacher_student_sigmoid_loss(input, label,
                                 soft_max_up_bound=15.0,
                                 soft_max_lower_bound=-15.0):
    """Distillation CTR loss (reference
    teacher_student_sigmoid_loss_op.cc)."""
    helper = LayerHelper("teacher_student_sigmoid_loss")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "teacher_student_sigmoid_loss",
        inputs={"X": [input], "Label": [label]},
        outputs={"Y": [out]},
        attrs={"soft_max_up_bound": soft_max_up_bound,
               "soft_max_lower_bound": soft_max_lower_bound},
    )
    out.shape = input.shape
    return out


def l1_norm(x, name=None):
    """sum(|x|) as a [1] tensor (reference l1_norm_op.cc)."""
    helper = LayerHelper("l1_norm", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("l1_norm", inputs={"X": [x]}, outputs={"Out": [out]})
    out.shape = (1,)
    return out


def sampling_id(x, min=0.0, max=1.0, seed=0, dtype="int64"):
    """Sample a class id per row from probabilities (reference
    sampling_id_op.cc; randomness from the executor key)."""
    helper = LayerHelper("sampling_id")
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op("sampling_id", inputs={"X": [x]},
                     outputs={"Out": [out]})
    if x.shape:
        out.shape = (x.shape[0],)
    return out


def shuffle_batch(x, name=None):
    """Shuffle rows of a batch on-device (reference shuffle_batch_op.cc).
    Returns (shuffled, shuffle_idx)."""
    helper = LayerHelper("shuffle_batch", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    idx = helper.create_variable_for_type_inference("int64")
    helper.append_op("shuffle_batch", inputs={"X": [x]},
                     outputs={"Out": [out], "ShuffleIdx": [idx]})
    out.shape = x.shape
    return out, idx


def precision_recall(input, label, class_number, weights=None,
                     states_info=None, name=None):
    """Multi-class precision/recall/F1 metric op (reference
    metrics/precision_recall_op.cc). Returns (batch_metrics [6],
    accum_metrics [6], accum_states [C,4])."""
    helper = LayerHelper("precision_recall", name=name)
    batch_m = helper.create_variable_for_type_inference("float32")
    accum_m = helper.create_variable_for_type_inference("float32")
    accum_s = helper.create_variable_for_type_inference("float32")
    inputs = {"Indices": [input], "Labels": [label]}
    if weights is not None:
        inputs["Weights"] = [weights]
    if states_info is not None:
        inputs["StatesInfo"] = [states_info]
    helper.append_op(
        "precision_recall",
        inputs=inputs,
        outputs={"BatchMetrics": [batch_m], "AccumMetrics": [accum_m],
                 "AccumStatesInfo": [accum_s]},
        attrs={"class_number": class_number},
    )
    return batch_m, accum_m, accum_s


def conv3d_transpose(input, num_filters, filter_size, stride=1, padding=0,
                     dilation=1, groups=None, param_attr=None,
                     bias_attr=None, act=None, name=None):
    """3D transpose convolution (reference conv_transpose_op.cc
    conv3d_transpose; NCDHW, filter [C_in, C_out/g, kd, kh, kw])."""
    helper = LayerHelper("conv3d_transpose", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = input.dtype
    c_in = input.shape[1]
    groups = groups or 1

    def _triple(v):
        return list(v) if isinstance(v, (list, tuple)) else [v] * 3

    fs = _triple(filter_size)
    w = helper.create_parameter(
        helper.param_attr(),
        shape=[c_in, num_filters // groups] + fs, dtype=dtype)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "conv3d_transpose",
        inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [out]},
        attrs={"strides": _triple(stride), "paddings": _triple(padding),
               "dilations": _triple(dilation), "groups": groups},
    )
    if bias_attr is not False:
        b = helper.create_parameter(helper.bias_attr(),
                                    shape=[num_filters], dtype=dtype,
                                    is_bias=True)
        pre = helper.create_variable_for_type_inference(dtype)
        helper.append_op("elementwise_add", inputs={"X": [out], "Y": [b]},
                         outputs={"Out": [pre]}, attrs={"axis": 1})
        out = pre
    return helper.append_activation(out)


def max_pool2d_with_index(input, pool_size, pool_stride=None, pool_padding=0,
                          name=None):
    """Max pool returning (out, flat argmax indices) — the Indices feed
    layers.unpool (reference pool_with_index_op.cc)."""
    helper = LayerHelper("max_pool2d_with_index", name=name)

    def _pair(v):
        return list(v) if isinstance(v, (list, tuple)) else [v, v]

    out = helper.create_variable_for_type_inference(input.dtype)
    mask = helper.create_variable_for_type_inference("int32")
    ks = _pair(pool_size)
    helper.append_op(
        "max_pool2d_with_index",
        inputs={"X": [input]},
        outputs={"Out": [out], "Mask": [mask]},
        attrs={"ksize": ks,
               "strides": _pair(pool_stride) if pool_stride else ks,
               "paddings": _pair(pool_padding)},
    )
    return out, mask


def max_pool3d_with_index(input, pool_size, pool_stride=None, pool_padding=0,
                          global_pooling=False, name=None):
    """3-D max pool returning (out, flat argmax indices into each [D,H,W]
    map) — reference pool_with_index_op.cc MaxPool3dWithIndex."""
    helper = LayerHelper("max_pool3d_with_index", name=name)

    def _triple(v):
        return list(v) if isinstance(v, (list, tuple)) else [v, v, v]

    out = helper.create_variable_for_type_inference(input.dtype)
    mask = helper.create_variable_for_type_inference("int32")
    ks = _triple(pool_size)
    helper.append_op(
        "max_pool3d_with_index",
        inputs={"X": [input]},
        outputs={"Out": [out], "Mask": [mask]},
        attrs={"ksize": ks,
               "strides": _triple(pool_stride) if pool_stride else ks,
               "paddings": _triple(pool_padding),
               "global_pooling": global_pooling},
    )
    return out, mask


def spp(input, pyramid_height=1, pool_type="max", name=None):
    """Spatial pyramid pooling over NCHW input (reference spp_op.cc;
    layer parity with nets-style SPPLayer): concat of 2^l x 2^l adaptive
    poolings for l < pyramid_height -> [N, C * sum(4^l)]."""
    helper = LayerHelper("spp", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "spp",
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={"pyramid_height": pyramid_height, "pooling_type": pool_type},
    )
    n, c = input.shape[0], input.shape[1]
    out.shape = (n, c * sum(4 ** l for l in range(pyramid_height)))
    return out


def positive_negative_pair(score, label, qid, name=None):
    """Ranking-pair metric (reference positive_negative_pair_op.cc +
    metric_op.py): returns (positive, negative, neutral) pair counts over
    intra-query item pairs."""
    helper = LayerHelper("positive_negative_pair", name=name)
    pos = helper.create_variable_for_type_inference("float32")
    neg = helper.create_variable_for_type_inference("float32")
    neu = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        "positive_negative_pair",
        inputs={"Score": [score], "Label": [label], "QueryID": [qid]},
        outputs={"PositivePair": [pos], "NegativePair": [neg],
                 "NeutralPair": [neu]},
    )
    return pos, neg, neu


def py_func(func, x, out_shapes, out_dtypes, name=None):
    """Host-Python escape hatch (reference layers/nn.py:9655 py_func,
    py_func_op.cc), realized with jax.pure_callback: `func` must be a
    PURE function of its numpy inputs; it runs on the host every step.
    out_shapes/out_dtypes declare the outputs (static shapes — TPU).
    Returns one Variable per declared output."""
    from ..ops.misc_ops import register_py_func

    helper = LayerHelper("py_func", name=name)
    xs = x if isinstance(x, (list, tuple)) else [x]
    fid = register_py_func(func)
    outs = [helper.create_variable_for_type_inference(d)
            for d in out_dtypes]
    helper.append_op(
        "py_func",
        inputs={"X": list(xs)},
        outputs={"Out": outs},
        attrs={"func_id": fid,
               "out_shapes": [list(s) for s in out_shapes],
               "out_dtypes": list(out_dtypes)},
    )
    for o, s in zip(outs, out_shapes):
        o.shape = tuple(s)
    return outs if len(outs) > 1 else outs[0]
